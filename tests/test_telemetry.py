"""Telemetry registry tests (r12 satellites): uniform reservoir
sampling and well-formed Prometheus exposition."""
import math
import random

from nomad_tpu.telemetry import MetricsRegistry, _Sample


def test_reservoir_is_uniform_over_the_whole_series():
    """Algorithm R keeps every observation with equal probability.  Feed
    a series whose value IS its index: a uniform reservoir's mean sits
    near the series midpoint; the old `count % 1024` ring kept only the
    most recent window, whose mean sits near the end."""
    n = 50_000
    s = _Sample()
    for i in range(n):
        s.add(float(i))
    assert s.count == n
    assert len(s.values) == 1024
    mean = sum(s.values) / len(s.values)
    # midpoint is (n-1)/2 = 24999.5; a last-window ring would sit at
    # ~49487.  1024 uniform draws from U(0, n) have stddev of the mean
    # ~ n/sqrt(12)/32 ~ 451, so +/-6 sigma is a comfortable, non-flaky
    # band that still rules the ring out by ~40 sigma.
    mid = (n - 1) / 2.0
    band = 6.0 * n / math.sqrt(12.0) / math.sqrt(1024.0)
    assert abs(mean - mid) < band, mean

    # percentiles follow: p50 of a uniform 0..n series is ~n/2, where
    # the ring's p50 was pinned inside the last 1024 values
    p50 = s.summary()["p50"]
    assert abs(p50 - mid) < 4_000, p50


def test_reservoir_every_index_can_survive():
    """Spot-check the survival mechanics: early values are not always
    evicted (the ring overwrote slot `count % 1024` deterministically,
    so value i never outlived step i + 1024)."""
    survived_early = 0
    for seed in range(20):
        s = _Sample()
        s._rng = random.Random(seed)
        for i in range(10_000):
            s.add(float(i))
        if any(v < 1024 for v in s.values):
            survived_early += 1
    assert survived_early > 0


def test_prometheus_exposition_shape():
    reg = MetricsRegistry()
    reg.incr("nomad.rpc.request", 3)
    reg.set_gauge("nomad.broker.total_ready", 7)
    reg.add_sample("nomad.plan.submit", 12.5)
    text = reg.prometheus()
    lines = text.splitlines()

    # counters carry the conventional _total suffix
    assert "nomad_rpc_request_total 3.0" in lines
    assert not any(line.startswith("nomad_rpc_request ")
                   for line in lines)
    # every family has exactly one HELP immediately before its TYPE
    for name, kind in (("nomad_rpc_request_total", "counter"),
                       ("nomad_broker_total_ready", "gauge"),
                       ("nomad_plan_submit", "summary")):
        helps = [i for i, ln in enumerate(lines)
                 if ln.startswith(f"# HELP {name} ")]
        assert len(helps) == 1, (name, helps)
        ti = lines.index(f"# TYPE {name} {kind}")
        assert helps[0] == ti - 1, (name, helps, ti)
    assert 'nomad_plan_submit{quantile="0.5"} 12.5' in lines
    assert "nomad_plan_submit_count 1" in lines


def test_prometheus_sanitization_collision_detected():
    """`a.b` and `a-b` both sanitize to `a_b`: exactly one family may be
    exported — duplicate TYPE blocks make scrapers reject the whole
    page — and the skipped name must be called out."""
    reg = MetricsRegistry()
    reg.set_gauge("a.b", 1)
    reg.set_gauge("a-b", 2)
    text = reg.prometheus()
    assert text.count("# TYPE a_b gauge") == 1
    assert "collision" in text
    # the surviving family still has a value line
    assert sum(1 for line in text.splitlines()
               if line.startswith("a_b ")) == 1
