"""Invariant linter suite tests: fixture corpus per checker (seeded
violations caught, allow-comment suppresses, clean tree passes), the CLI
contract, the runtime lock-order recorder, and FSM replay determinism
(the property the fsm-determinism checker exists to protect)."""
import copy
import json
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from nomad_tpu import mock
from nomad_tpu.analysis import CHECKERS, run_all
from nomad_tpu.analysis.lock_order import LockOrderRecorder
from nomad_tpu.raft import MessageType, NomadFSM
from nomad_tpu.state import StateStore

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"
REPO = Path(__file__).resolve().parent.parent

# (fixture dir, checker name, findings seeded in bad/)
CASES = [
    ("fsm_determinism", "fsm-determinism", 4),
    ("lock_discipline", "lock-discipline", 1),
    ("native_abi", "native-abi", 5),
    ("jax_purity", "jax-purity", 4),
    ("chaos_coverage", "chaos-coverage", 5),
    ("transfer_purity", "transfer-purity", 6),
    ("recompile", "recompile-budget", 2),
    ("race", "happens-before", 5),
    ("snapshot_completeness", "snapshot-completeness", 10),
    ("canonical_form", "canonical-form", 6),
    ("wait_graph", "wait-graph", 4),
    ("context_propagation", "context-propagation", 8),
    ("deadline_coverage", "deadline-coverage", 7),
    ("donation_safety", "donation-safety", 6),
    ("knob_registry", "knob-registry", 7),
    ("allow_audit", "allow-audit", 3),
]


# ------------------------------------------------------------ fixture corpus


@pytest.mark.parametrize("fixture,checker,n_bad", CASES,
                         ids=[c[1] for c in CASES])
def test_seeded_violations_caught(fixture, checker, n_bad):
    findings = run_all(FIXTURES / fixture / "bad", checkers=[checker])
    assert len(findings) == n_bad
    assert all(f.checker == checker for f in findings)
    assert all(f.line > 0 and f.message for f in findings)


@pytest.mark.parametrize("fixture,checker,n_bad", CASES,
                         ids=[c[1] for c in CASES])
def test_allow_comment_suppresses(fixture, checker, n_bad):
    assert run_all(FIXTURES / fixture / "allowed", checkers=[checker]) == []


@pytest.mark.parametrize("fixture,checker,n_bad", CASES,
                         ids=[c[1] for c in CASES])
def test_clean_tree_passes(fixture, checker, n_bad):
    assert run_all(FIXTURES / fixture / "clean", checkers=[checker]) == []


@pytest.mark.parametrize("fixture,checker,n_bad", CASES,
                         ids=[c[1] for c in CASES])
def test_allowed_corpus_is_audit_clean(fixture, checker, n_bad):
    """Every allowed-corpus suppression carries a reason and is consulted
    by the checker it names: run_all runs the whole suite before the
    audit, so a dead or reasonless allow would surface here."""
    assert run_all(FIXTURES / fixture / "allowed",
                   checkers=[checker, "allow-audit"]) == []


def test_transitive_findings_carry_call_chain():
    findings = run_all(FIXTURES / "fsm_determinism" / "bad",
                       checkers=["fsm-determinism"])
    transitive = [f for f in findings if len(f.chain) > 1]
    assert transitive, "expected the helper's entropy via a call chain"
    assert transitive[0].chain == ("MiniFSM._apply_job", "MiniFSM._stamp")


def test_repo_tree_is_clean():
    """The acceptance bar: the linters find nothing in the repo itself."""
    assert [f.render() for f in run_all(REPO)] == []


def test_unknown_checker_rejected():
    with pytest.raises(ValueError, match="unknown checker"):
        run_all(FIXTURES / "fsm_determinism" / "clean", checkers=["nope"])


def test_wait_graph_merges_runtime_corpus_into_cycle():
    """A runtime-observed edge opposite to a static one must close a
    cycle — the merged graph is the whole point of the shared corpus."""
    from nomad_tpu.analysis import wait_graph
    from nomad_tpu.analysis.common import load_corpus, lock_alloc_sites

    root = FIXTURES / "wait_graph" / "clean"
    corpus = load_corpus(root)
    sites = lock_alloc_sites(corpus.py)
    la, lb = sites[("Pair", "_la")], sites[("Pair", "_lb")]
    corpus.lock_corpus = {
        "format": "nomad-tpu-lock-order/1",
        "edges": [{"a": lb, "b": la, "thread": "t9", "held": [lb]}],
    }
    findings = wait_graph.run(corpus)
    assert len(findings) == 1
    msg = findings[0].message
    assert "lock-order cycle" in msg
    assert "[runtime: thread t9]" in msg and "[static:" in msg


def test_wait_graph_rejects_foreign_corpus_format():
    from nomad_tpu.analysis import wait_graph
    from nomad_tpu.analysis.common import load_corpus

    corpus = load_corpus(FIXTURES / "wait_graph" / "clean")
    corpus.lock_corpus = {"format": "bogus/9", "edges": []}
    findings = wait_graph.run(corpus)
    assert len(findings) == 1
    assert "format" in findings[0].message


# ------------------------------------------------------------------ the CLI


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "nomad_tpu.analysis", *args],
        capture_output=True, text=True, cwd=str(REPO))


def test_cli_exits_nonzero_on_findings():
    res = _cli("--root", str(FIXTURES / "lock_discipline" / "bad"),
               "--checker", "lock-discipline")
    assert res.returncode == 1
    assert "[lock-discipline]" in res.stdout


def test_cli_exits_zero_on_clean_tree():
    res = _cli("--root", str(FIXTURES / "lock_discipline" / "clean"),
               "--checker", "lock-discipline")
    assert res.returncode == 0


def test_cli_json_output():
    res = _cli("--root", str(FIXTURES / "native_abi" / "bad"),
               "--checker", "native-abi", "--json")
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert len(doc["findings"]) == 5
    assert {f["checker"] for f in doc["findings"]} == {"native-abi"}
    assert all({"path", "line", "message"} <= set(f) for f in doc["findings"])


def test_cli_list_checkers():
    res = _cli("--list-checkers")
    assert res.returncode == 0
    assert res.stdout.split() == list(CHECKERS)
    assert len(CHECKERS) == 16


def test_cli_checkers_csv_and_json_counts():
    res = _cli("--root", str(FIXTURES / "wait_graph" / "bad"),
               "--checkers", "wait-graph,allow-audit", "--json")
    assert res.returncode == 1
    doc = json.loads(res.stdout)
    assert doc["checkers"] == ["wait-graph", "allow-audit"]
    assert doc["counts"]["wait-graph"] == 4
    assert doc["counts"]["allow-audit"] == 0
    assert len(doc["findings"]) == 4


def test_cli_baseline_ratchets_known_findings(tmp_path):
    """--baseline turns known debt into exit 0: a report generated from
    the same tree baselines every finding away."""
    root = str(FIXTURES / "knob_registry" / "bad")
    res = _cli("--root", root, "--checker", "knob-registry", "--json")
    assert res.returncode == 1
    baseline = tmp_path / "report.json"
    baseline.write_text(res.stdout)
    res2 = _cli("--root", root, "--checker", "knob-registry",
                "--baseline", str(baseline))
    assert res2.returncode == 0
    assert "0 new findings" in res2.stdout
    assert "(7 baselined)" in res2.stdout


def test_cli_baseline_fails_on_new_findings(tmp_path):
    """Findings not in the baseline still fail, and only they print."""
    root = str(FIXTURES / "knob_registry" / "bad")
    res = _cli("--root", root, "--checker", "knob-registry", "--json")
    doc = json.loads(res.stdout)
    doc["findings"] = [f for f in doc["findings"]
                       if "NOMAD_TPU_RAW_GET`" not in f["message"]]
    baseline = tmp_path / "report.json"
    baseline.write_text(json.dumps(doc))
    res2 = _cli("--root", root, "--checker", "knob-registry",
                "--baseline", str(baseline), "--json")
    assert res2.returncode == 1
    out = json.loads(res2.stdout)
    assert len(out["findings"]) == 1
    assert "NOMAD_TPU_RAW_GET" in out["findings"][0]["message"]
    assert out["baselined"] == 6


def test_cli_baseline_unreadable_is_usage_error(tmp_path):
    p = tmp_path / "nope.json"
    res = _cli("--root", str(FIXTURES / "lock_discipline" / "clean"),
               "--baseline", str(p))
    assert res.returncode == 2
    assert "--baseline" in res.stderr


def test_cli_lock_corpus_flag(tmp_path):
    from nomad_tpu.analysis.common import load_corpus, lock_alloc_sites

    root = FIXTURES / "wait_graph" / "clean"
    sites = lock_alloc_sites(load_corpus(root).py)
    corpus = {"format": "nomad-tpu-lock-order/1",
              "edges": [{"a": sites[("Pair", "_lb")],
                         "b": sites[("Pair", "_la")],
                         "thread": "t1", "held": []}]}
    p = tmp_path / "corpus.json"
    p.write_text(json.dumps(corpus))
    res = _cli("--root", str(root), "--checker", "wait-graph",
               "--lock-corpus", str(p))
    assert res.returncode == 1
    assert "lock-order cycle" in res.stdout


def test_cli_rejects_foreign_lock_corpus(tmp_path):
    p = tmp_path / "bogus.json"
    p.write_text('{"format": "other/1"}')
    res = _cli("--root", str(FIXTURES / "wait_graph" / "clean"),
               "--lock-corpus", str(p))
    assert res.returncode == 2
    assert "lock-order corpus" in res.stderr


def test_cli_runs_without_jax():
    """The analyzers are stdlib-only: a bare interpreter that cannot
    import jax must still run them (the CI analysis leg relies on it)."""
    code = ("import sys; sys.modules['jax'] = None; "
            "from nomad_tpu.analysis.__main__ import main; "
            "sys.exit(main(['--root', sys.argv[1]]))")
    res = subprocess.run(
        [sys.executable, "-c", code,
         str(FIXTURES / "lock_discipline" / "clean")],
        capture_output=True, text=True, cwd=str(REPO))
    assert res.returncode == 0, res.stderr


# ------------------------------------------------- runtime lock-order cycles


def _nest(outer, inner):
    with outer:
        with inner:
            pass


def _wrapped(rec, name):
    """A recorded lock over a raw _thread lock: invisible to any outer
    (session-level) recorder, so deliberately seeded cycles stay local."""
    import _thread

    from nomad_tpu.analysis.lock_order import _RecordingLock
    return _RecordingLock(_thread.allocate_lock(), name, rec)


def test_lock_order_recorder_flags_cycle():
    rec = LockOrderRecorder()
    a = _wrapped(rec, "lock-a")
    b = _wrapped(rec, "lock-b")
    _nest(a, b)
    t = threading.Thread(target=_nest, args=(b, a))
    t.start()
    t.join()
    cycles = rec.cycles()
    assert len(cycles) == 1
    rendered = rec.render_cycles()
    assert "lock-order cycle" in rendered and "lock-a" in rendered


def test_lock_order_recorder_consistent_order_is_clean():
    rec = LockOrderRecorder()
    a = _wrapped(rec, "lock-a")
    b = _wrapped(rec, "lock-b")
    c = _wrapped(rec, "lock-c")
    _nest(a, b)
    _nest(b, c)
    t = threading.Thread(target=_nest, args=(a, c))
    t.start()
    t.join()
    assert rec.cycles() == []


def test_lock_order_recorder_install_wraps_new_locks():
    from nomad_tpu.analysis.lock_order import _RecordingLock
    rec = LockOrderRecorder()
    with rec:
        assert isinstance(threading.Lock(), _RecordingLock)
        assert isinstance(threading.RLock(), _RecordingLock)


def test_lock_order_recorder_wraps_condition():
    """Condition() over a recorded RLock keeps the wait/notify protocol
    (the wrapper must delegate _release_save/_acquire_restore)."""
    rec = LockOrderRecorder()
    with rec:
        cv = threading.Condition(threading.RLock())
        hits = []

        def waiter():
            with cv:
                while not hits:
                    cv.wait(timeout=2.0)

        t = threading.Thread(target=waiter)
        t.start()
        with cv:
            hits.append(1)
            cv.notify_all()
        t.join()
    assert rec.cycles() == []


def test_lock_order_dump_load_roundtrip(tmp_path):
    """dump() writes the shared corpus format wait-graph consumes."""
    from nomad_tpu.analysis import load_lock_corpus
    from nomad_tpu.analysis.lock_order import LOCK_ORDER_FORMAT

    rec = LockOrderRecorder()
    a = _wrapped(rec, "store.py:10")
    b = _wrapped(rec, "wal.py:20")
    _nest(a, b)
    path = tmp_path / "corpus.json"
    rec.dump(path)
    data = load_lock_corpus(path)
    assert data["format"] == LOCK_ORDER_FORMAT
    assert len(data["edges"]) == 1
    edge = data["edges"][0]
    assert edge["a"] == "store.py:10" and edge["b"] == "wal.py:20"
    assert edge["thread"] and edge["held"] == ["store.py:10"]


def test_load_lock_corpus_rejects_foreign_json(tmp_path):
    from nomad_tpu.analysis import load_lock_corpus

    p = tmp_path / "x.json"
    p.write_text('{"what": 1}')
    with pytest.raises(ValueError, match="lock-order corpus"):
        load_lock_corpus(p)


def test_lock_order_recorder_uninstall_restores_factories():
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    rec = LockOrderRecorder().install()
    rec.uninstall()
    assert threading.Lock is orig_lock and threading.RLock is orig_rlock


# ------------------------------------------- runtime happens-before detection


@pytest.fixture
def race_detector():
    """An installed RaceDetector wired to the module hooks, torn down
    even on assertion failure (a leaked detector corrupts every later
    test that allocates a lock)."""
    from nomad_tpu.analysis import race as race_mod
    from nomad_tpu.analysis.race import RaceDetector
    det = RaceDetector().install()
    prev, race_mod.active = race_mod.active, det
    try:
        yield race_mod, det
    finally:
        race_mod.active = prev
        det.uninstall()


def test_race_detector_flags_unlocked_writes(race_detector):
    race_mod, det = race_detector
    gate = threading.Barrier(2)

    def unlocked():
        gate.wait()
        for _ in range(100):
            race_mod.write("Demo._tbl", None)

    ts = [threading.Thread(target=unlocked) for _ in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert det.races
    rendered = det.races[0].render()
    assert "Demo._tbl" in rendered and "unordered" in rendered


def test_race_detector_locked_writes_are_clean(race_detector):
    race_mod, det = race_detector
    lk = threading.Lock()       # allocated under install() -> wrapped
    gate = threading.Barrier(2)

    def locked():
        gate.wait()
        for _ in range(100):
            with lk:
                race_mod.write("Demo._tbl", None)

    ts = [threading.Thread(target=locked) for _ in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert det.races == [], det.render_races()
    assert det.cycles() == []


def test_race_detector_fork_join_orders_accesses(race_detector):
    race_mod, det = race_detector
    race_mod.write("Demo._tbl", None)
    t = threading.Thread(target=lambda: race_mod.write("Demo._tbl", None))
    t.start()
    t.join()
    race_mod.write("Demo._tbl", None)
    assert det.races == [], det.render_races()


def test_race_detector_condition_handoff_is_clean(race_detector):
    """Producer writes under the condition, consumer reads after wait:
    the wrapped RLock's _release_save/_acquire_restore pair must carry
    the clocks through the wait."""
    race_mod, det = race_detector
    cv = threading.Condition(threading.RLock())
    ready = []

    def producer():
        with cv:
            race_mod.write("Demo._q", None)
            ready.append(1)
            cv.notify()

    def consumer():
        with cv:
            while not ready:
                cv.wait(timeout=5.0)
            race_mod.read("Demo._q", None)

    tc = threading.Thread(target=consumer)
    tp = threading.Thread(target=producer)
    tc.start()
    tp.start()
    tc.join()
    tp.join()
    assert det.races == [], det.render_races()


def test_race_detector_uninstall_restores_patches():
    from nomad_tpu.analysis.race import RaceDetector
    orig = (threading.Lock, threading.RLock,
            threading.Thread.start, threading.Thread.join)
    det = RaceDetector().install()
    det.uninstall()
    assert (threading.Lock, threading.RLock,
            threading.Thread.start, threading.Thread.join) == orig


def test_race_hooks_tolerate_missing_detector():
    """Production hooks must be safe (and near-free) with no detector
    installed — they run unconditionally on the hot path."""
    from nomad_tpu.analysis import race as race_mod
    race_mod.read("Demo._tbl", None)
    race_mod.write("Demo._tbl", None)


# ------------------------------------------------------ FSM replay determinism


def _fsm_log():
    """A log exercising the once-nondeterministic paths: job register
    (submit_time), eval update (create/modify times), deployment upsert,
    plan results, and a deregister.  Timestamps are pre-stamped the way
    the propose path does it now."""
    node = mock.node()
    job = mock.job(submit_time=1234.5)
    ev = mock.eval(job_id=job.id, create_time=10.0, modify_time=10.0)
    alloc = mock.alloc_for(job, node.id)
    return [
        (1, MessageType.NODE_REGISTER, {"node": node}),
        (2, MessageType.JOB_REGISTER, {"job": job}),
        (3, MessageType.EVAL_UPDATE, {"evals": [ev]}),
        (4, MessageType.ALLOC_UPDATE, {"allocs": [alloc]}),
        (5, MessageType.JOB_DEREGISTER,
         {"namespace": "default", "job_id": job.id, "purge": False}),
    ]


def _replay(log):
    fsm = NomadFSM(StateStore())
    for index, msg_type, payload in copy.deepcopy(log):
        fsm.apply(index, msg_type, payload)
    return fsm.snapshot()


def test_fsm_replay_is_byte_identical():
    log = _fsm_log()
    assert _replay(log) == _replay(log)


def test_snapshot_derived_builders_are_real_methods():
    """The _SNAPSHOT_DERIVED contract the snapshot-completeness checker
    enforces statically, asserted live: every declared builder exists
    and every derived table is in the replicated universe."""
    for table, builder in StateStore._SNAPSHOT_DERIVED.items():
        assert callable(getattr(StateStore, builder)), (table, builder)
        assert table in StateStore._LOCK_PROTECTED, table


def test_restore_rebuilds_derived_indexes_like_a_live_store():
    """A restored follower's derived indexes must equal a live
    survivor's — including the liveness index, which must NOT contain
    terminal allocs.  Apply and restore share the _index_*_locked
    builders, so the two paths cannot drift."""
    from nomad_tpu.structs import AllocClientStatus

    node = mock.node()
    job = mock.job(submit_time=1.0)
    live_a = mock.alloc_for(job, node.id)
    dead_a = mock.alloc_for(job, node.id, index=1,
                            client_status=AllocClientStatus.COMPLETE)
    log = [
        (1, MessageType.NODE_REGISTER, {"node": node}),
        (2, MessageType.JOB_REGISTER, {"job": job}),
        (3, MessageType.ALLOC_UPDATE, {"allocs": [live_a, dead_a]}),
    ]
    live = NomadFSM(StateStore())
    for index, msg_type, payload in copy.deepcopy(log):
        live.apply(index, msg_type, payload)
    restored = NomadFSM(StateStore())
    restored.restore(live.snapshot())
    ls, rs = live.store, restored.store
    for table in ("_allocs_by_job", "_allocs_by_node", "_allocs_by_eval",
                  "_evals_by_job", "_services_by_alloc"):
        assert dict(getattr(ls, table)) == dict(getattr(rs, table)), table
    assert ls._live_names == rs._live_names
    assert all(dead_a.id not in ids for ids in rs._live_names.values())
    assert set(ls._acl_by_secret) == set(rs._acl_by_secret)
    assert ls._applied_plan_ids_set == rs._applied_plan_ids_set


def test_fsm_replay_matches_snapshot_restore_roundtrip():
    """Replay onto a restored snapshot must agree with direct replay —
    the plan_id dedup ring and follower catch-up both rely on it.
    Compared after a loads/dumps normalization pass: raw snapshot bytes
    differ across a restore only in pickle's string-memoization layout
    (object identity of interned keys), not in state."""
    import pickle

    def canon(blob):
        return pickle.dumps(pickle.loads(blob))

    log = _fsm_log()
    blob = _replay(log)
    fsm = NomadFSM(StateStore())
    fsm.restore(blob)
    assert canon(fsm.snapshot()) == canon(blob)
