"""Vault-shaped secrets: provider leases/policies, per-task token
derivation, template rendering of secrets, re-render on change
(reference nomad/vault.go, taskrunner/vault_hook.go,
taskrunner/template/template.go)."""
import os
import time

import pytest

from nomad_tpu.core.secrets import SecretsError, SecretsProvider


def test_provider_put_read_versions():
    p = SecretsProvider()
    assert p.put("db/creds", {"user": "u", "password": "p1"}) == 1
    tok = p.derive_token("a1", "t", ["db"])["token"]
    data, ver = p.read("db/creds", tok)
    assert data == {"user": "u", "password": "p1"} and ver == 1
    assert p.put("db/creds", {"user": "u", "password": "p2"}) == 2
    assert p.version("db/creds", tok) == 2


def test_provider_policy_prefix_enforced():
    p = SecretsProvider()
    p.put("db/creds", {"x": "1"})
    p.put("other/creds", {"x": "2"})
    tok = p.derive_token("a1", "t", ["db"])["token"]
    assert p.read("db/creds", tok)[0] == {"x": "1"}
    with pytest.raises(SecretsError, match="do not cover"):
        p.read("other/creds", tok)


def test_provider_renew_and_revoke():
    p = SecretsProvider()
    p.put("db/x", {"k": "v"})
    grant = p.derive_token("a1", "t", ["db"], ttl_s=0.2)
    tok = grant["token"]
    assert p.renew(tok)["renewals"] == 1
    time.sleep(0.25)
    with pytest.raises(SecretsError, match="expired"):
        p.renew(tok)
    tok2 = p.derive_token("a1", "t", ["db"])["token"]
    assert p.revoke_for_alloc("a1") >= 1
    with pytest.raises(SecretsError):
        p.read("db/x", tok2)


def _world(tmp_path):
    from nomad_tpu.client.client import Client, ClientConfig
    from nomad_tpu.core.server import Server, ServerConfig
    s = Server(ServerConfig(num_schedulers=1, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    c = Client(ClientConfig(node_name="secrets-client",
                            data_dir=str(tmp_path / "client"),
                            drivers=["mock", "mock_driver", "raw_exec"]),
               rpc=s.rpc_leader)
    c.start()
    return s, c


def _wait(cond, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_task_gets_token_and_rendered_secret(tmp_path, monkeypatch):
    """End-to-end: vault stanza -> token in secrets/vault_token, a
    template reads the secret, and a secret update re-renders it
    (change_mode=noop so the file can be checked without a restart)."""
    monkeypatch.setenv("NOMAD_TPU_TEMPLATE_POLL_S", "0.1")
    from nomad_tpu.structs.job import Job, Task, TaskGroup
    s, c = _world(tmp_path)
    try:
        s.endpoints.handle("Secrets.Put", {
            "path": "db/creds", "data": {"password": "hunter2"}})
        t = Task(name="t", driver="mock_driver",
                 config={"run_for": 60.0})
        t.vault = {"policies": ["db"]}
        t.templates = [{
            "data": 'PW={{ secret "db/creds" "password" }}',
            "destination": "local/db.env",
            "change_mode": "noop"}]
        job = Job(id=f"vault-{time.time_ns()}", name="v", type="service",
                  task_groups=[TaskGroup(name="g", count=1, tasks=[t])])
        job.canonicalize()
        s.register_job(job)
        assert _wait(lambda: any(
            a.client_status == "running"
            for a in s.store.allocs_by_job("default", job.id)))

        ar = next(iter(c.alloc_runners.values()))
        task_dir = ar.alloc_dir.task_dir("t")
        token_file = os.path.join(task_dir, "secrets", "vault_token")
        assert _wait(lambda: os.path.exists(token_file))
        token = open(token_file).read()
        assert len(token) == 36
        rendered = os.path.join(task_dir, "local", "db.env")
        assert open(rendered).read() == "PW=hunter2"

        # rotation: put a new version; the watcher re-renders
        s.endpoints.handle("Secrets.Put", {
            "path": "db/creds", "data": {"password": "correct-horse"}})
        assert _wait(lambda: open(rendered).read() == "PW=correct-horse",
                     10.0)
    finally:
        s.stop()


def test_template_change_mode_restart(tmp_path, monkeypatch):
    """A secret rotation restarts the task when change_mode=restart,
    without counting against the restart policy."""
    monkeypatch.setenv("NOMAD_TPU_TEMPLATE_POLL_S", "0.1")
    from nomad_tpu.structs.job import Job, Task, TaskGroup
    s, c = _world(tmp_path)
    try:
        s.endpoints.handle("Secrets.Put", {
            "path": "app/cfg", "data": {"rev": "1"}})
        t = Task(name="t", driver="mock_driver",
                 config={"run_for": 60.0})
        t.vault = {"policies": ["app"]}
        t.templates = [{
            "data": 'REV={{ secret "app/cfg" "rev" }}',
            "destination": "local/app.cfg"}]     # default: restart
        job = Job(id=f"vault-r-{time.time_ns()}", name="vr",
                  type="service",
                  task_groups=[TaskGroup(name="g", count=1, tasks=[t])])
        job.canonicalize()
        s.register_job(job)
        assert _wait(lambda: any(
            a.client_status == "running"
            for a in s.store.allocs_by_job("default", job.id)))
        ar = next(iter(c.alloc_runners.values()))
        tr = ar.task_runners["t"]
        assert tr.state.restarts == 0

        s.endpoints.handle("Secrets.Put", {
            "path": "app/cfg", "data": {"rev": "2"}})
        assert _wait(lambda: tr.state.restarts >= 1, 15.0)
        assert _wait(lambda: tr.state.state == "running", 15.0)
        task_dir = ar.alloc_dir.task_dir("t")
        assert open(os.path.join(task_dir, "local",
                                 "app.cfg")).read() == "REV=2"
        # the alloc stayed healthy: restart was not a policy failure
        assert not tr.state.failed
    finally:
        s.stop()


def test_derive_requires_vault_stanza(tmp_path):
    from nomad_tpu.rpc.endpoints import RpcError
    from nomad_tpu.structs.job import Job, Task, TaskGroup
    s, c = _world(tmp_path)
    try:
        t = Task(name="t", driver="mock_driver", config={"run_for": 30.0})
        job = Job(id=f"nv-{time.time_ns()}", name="nv", type="service",
                  task_groups=[TaskGroup(name="g", count=1, tasks=[t])])
        job.canonicalize()
        s.register_job(job)
        assert _wait(lambda: any(
            a.client_status == "running"
            for a in s.store.allocs_by_job("default", job.id)))
        alloc = s.store.allocs_by_job("default", job.id)[0]
        # the wrong node secret is rejected before any policy checks
        with pytest.raises(RpcError, match="node secret"):
            s.endpoints.handle("Secrets.Derive",
                               {"alloc_id": alloc.id, "task": "t",
                                "node_id": c.node.id,
                                "node_secret_id": "not-the-secret"})
        with pytest.raises(RpcError, match="no vault stanza"):
            s.endpoints.handle("Secrets.Derive",
                               {"alloc_id": alloc.id, "task": "t",
                                "node_id": c.node.id,
                                "node_secret_id": c.node.secret_id})
    finally:
        s.stop()


def test_node_secret_redacted_and_put_acl_gated(tmp_path):
    """Node.SecretID never leaves the servers via Node.List/GetNode, and
    with ACLs on Secrets.Put demands a management token."""
    from nomad_tpu.rpc.endpoints import RpcError
    s, c = _world(tmp_path)
    try:
        assert _wait(lambda: s.store.node_by_id(c.node.id) is not None)
        assert s.store.node_by_id(c.node.id).secret_id  # store keeps it
        listed = s.endpoints.handle("Node.List", {})
        assert listed and all(n.secret_id == "" for n in listed)
        got = s.endpoints.handle("Node.GetNode", {"node_id": c.node.id})
        assert got.secret_id == ""
        # the redaction copies; the authoritative record is untouched
        assert s.store.node_by_id(c.node.id).secret_id

        s.enable_acl()
        tok = s.bootstrap_acl()
        with pytest.raises(RpcError, match="management"):
            s.endpoints.handle("Secrets.Put",
                               {"path": "x/y", "data": {"k": "v"}})
        out = s.endpoints.handle("Secrets.Put",
                                 {"path": "x/y", "data": {"k": "v"},
                                  "token": tok.secret_id})
        assert out["version"] == 1
    finally:
        s.stop()
