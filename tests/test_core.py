"""Control-plane tests: broker, blocked evals, plan applier, full server
spine (reference analogs: nomad/eval_broker_test.go, blocked_evals_test.go,
plan_apply_test.go, worker_test.go)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.broker import EvalBroker
from nomad_tpu.core.blocked import BlockedEvals
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.state import StateStore
from nomad_tpu.structs import AllocClientStatus, AllocDesiredStatus, EvalStatus
from nomad_tpu.structs.plan import Plan


# ------------------------------------------------------------------ broker

def make_broker():
    b = EvalBroker(nack_timeout=5.0, initial_nack_delay=0.0,
                   subsequent_nack_delay=0.0)
    b.set_enabled(True)
    return b


def test_broker_priority_and_fifo():
    b = make_broker()
    lo = mock.eval(priority=10)
    hi = mock.eval(priority=90)
    mid1 = mock.eval(priority=50)
    mid2 = mock.eval(priority=50)
    for e in (lo, mid1, hi, mid2):
        b.enqueue(e)
    got = [b.dequeue(["service"])[0].id for _ in range(4)]
    assert got == [hi.id, mid1.id, mid2.id, lo.id]


def test_broker_ack_nack_cycle():
    b = make_broker()
    ev = mock.eval()
    b.enqueue(ev)
    got, token = b.dequeue(["service"])
    assert got.id == ev.id
    assert b.dequeue(["service"])[0] is None     # leased, not available
    assert b.nack(ev.id, token)
    got2, token2 = b.dequeue(["service"])        # requeued
    assert got2.id == ev.id
    assert b.ack(ev.id, token2)
    assert b.ready_count() == 0


def test_broker_job_dedup_pending():
    b = make_broker()
    e1 = mock.eval(job_id="same-job")
    e2 = mock.eval(job_id="same-job")
    b.enqueue(e1)
    got, token = b.dequeue(["service"])
    b.enqueue(e2)                                 # waits behind e1
    assert b.dequeue(["service"])[0] is None
    b.ack(got.id, token)
    got2, _ = b.dequeue(["service"])
    assert got2.id == e2.id


def test_broker_delivery_limit_dead_letters():
    b = make_broker()
    b.delivery_limit = 2
    ev = mock.eval()
    b.enqueue(ev)
    for _ in range(2):
        got, token = b.dequeue(["service"])
        if got is None:
            break
        b.nack(ev.id, token)
    from nomad_tpu.core.broker import FAILED_QUEUE
    got, _ = b.dequeue([FAILED_QUEUE])
    assert got is not None and got.id == ev.id


def test_broker_delayed_eval():
    b = make_broker()
    ev = mock.eval()
    ev.wait_until = time.time() + 0.15
    b.enqueue(ev)
    assert b.dequeue(["service"])[0] is None
    got, _ = b.dequeue(["service"], timeout=1.0)
    assert got is not None and got.id == ev.id
    assert time.time() >= ev.wait_until


def test_broker_scheduler_type_routing():
    b = make_broker()
    svc = mock.eval(type="service")
    sys_ = mock.eval(type="system")
    b.enqueue(svc)
    b.enqueue(sys_)
    got, _ = b.dequeue(["system"])
    assert got.id == sys_.id


# ------------------------------------------------------------------ blocked

def test_blocked_unblock_on_class():
    b = make_broker()
    blocked = BlockedEvals(b)
    blocked.set_enabled(True)
    ev = mock.eval()
    ev.status = EvalStatus.BLOCKED
    ev.class_eligibility = {"v1:abc": False}
    blocked.block(ev)
    assert blocked.blocked_count() == 1
    # same class with no new capacity signal for an ineligible class: the
    # eval only unblocks for unseen or eligible classes
    released = blocked.unblock("v1:abc", 100)
    assert released == []
    released = blocked.unblock("v1:new-class", 101)
    assert len(released) == 1
    assert b.ready_count() == 1


def test_blocked_dedup_per_job():
    b = make_broker()
    blocked = BlockedEvals(b)
    blocked.set_enabled(True)
    e1 = mock.eval(job_id="j1")
    e1.create_index = 1
    e2 = mock.eval(job_id="j1")
    e2.create_index = 2
    blocked.block(e1)
    blocked.block(e2)
    assert blocked.blocked_count() == 1
    dups = blocked.get_duplicates()
    assert [d.id for d in dups] == [e1.id]


# ------------------------------------------------------------------ applier

def test_plan_applier_rejects_overcommitted_node():
    store = StateStore()
    n1, n2 = mock.node(), mock.node()
    store.upsert_node(1, n1)
    store.upsert_node(2, n2)
    j = mock.job()
    store.upsert_job(3, j)
    applier = PlanApplier(store)

    # a plan whose placements on n1 exceed capacity but fit on n2
    big = mock.alloc_for(j, n1.id)
    big.allocated_resources.tasks["web"].cpu_shares = 5000
    ok = mock.alloc_for(j, n2.id, index=1)
    plan = Plan(eval_id="e1", job=j)
    plan.append_alloc(big, j)
    plan.append_alloc(ok, j)
    result = applier.apply(plan)
    assert n1.id in result.rejected_nodes
    assert [a.id for a in result.node_allocation[n2.id]] == [ok.id]
    full, expected, actual = result.full_commit(plan)
    assert not full and expected == 2 and actual == 1
    assert result.refresh_index > 0


def test_plan_applier_all_at_once_rejects_everything():
    store = StateStore()
    n1, n2 = mock.node(), mock.node()
    store.upsert_node(1, n1)
    store.upsert_node(2, n2)
    j = mock.job()
    store.upsert_job(3, j)
    applier = PlanApplier(store)
    big = mock.alloc_for(j, n1.id)
    big.allocated_resources.tasks["web"].cpu_shares = 5000
    ok = mock.alloc_for(j, n2.id, index=1)
    plan = Plan(eval_id="e1", job=j, all_at_once=True)
    plan.append_alloc(big, j)
    plan.append_alloc(ok, j)
    result = applier.apply(plan)
    assert result.node_allocation == {}


def test_plan_applier_stop_frees_capacity_for_placement():
    store = StateStore()
    n = mock.node()
    store.upsert_node(1, n)
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = 3000
    store.upsert_job(2, j)
    old = mock.alloc_for(j, n.id)
    old.allocated_resources.tasks["web"].cpu_shares = 3000
    store.upsert_allocs(3, [old])
    applier = PlanApplier(store)

    new = mock.alloc_for(j, n.id, index=1)
    new.allocated_resources.tasks["web"].cpu_shares = 3000
    plan = Plan(eval_id="e2", job=j)
    plan.append_stopped_alloc(old, "replaced")
    plan.append_alloc(new, j)
    result = applier.apply(plan)
    assert result.rejected_nodes == []
    assert store.alloc_by_id(old.id).desired_status == AllocDesiredStatus.STOP
    assert store.alloc_by_id(new.id) is not None


# ------------------------------------------------------------------ server

def test_server_end_to_end_spine():
    """job register -> broker -> worker -> scheduler -> plan queue ->
    applier -> committed allocs (the section 3.1 call stack)."""
    s = Server(ServerConfig(num_schedulers=2))
    s.start()
    try:
        for _ in range(5):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 5
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        allocs = s.store.allocs_by_job("default", job.id)
        assert len(allocs) == 5
        ev_list = s.store.evals_by_job("default", job.id)
        assert any(e.status == EvalStatus.COMPLETE for e in ev_list)
    finally:
        s.stop()


def test_server_blocked_eval_unblocks_on_new_node():
    s = Server(ServerConfig(num_schedulers=1))
    s.start()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        job.task_groups[0].tasks[0].resources.cpu = 3000
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        assert len(s.store.allocs_by_job("default", job.id)) == 1
        assert s.blocked_evals.blocked_count() == 1
        # capacity arrives: two more nodes -> unblock -> placements
        s.register_node(mock.node())
        s.register_node(mock.node())
        deadline = time.time() + 10
        while time.time() < deadline:
            allocs = [a for a in s.store.allocs_by_job("default", job.id)
                      if a.desired_status == AllocDesiredStatus.RUN]
            if len(allocs) == 3:
                break
            time.sleep(0.05)
        assert len([a for a in s.store.allocs_by_job("default", job.id)
                    if a.desired_status == AllocDesiredStatus.RUN]) == 3
    finally:
        s.stop()


def test_server_node_down_triggers_replacement():
    s = Server(ServerConfig(num_schedulers=2))
    s.start()
    try:
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 2
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        victim_alloc = s.store.allocs_by_job("default", job.id)[0]
        s.update_node_status(victim_alloc.node_id, "down")
        assert s.wait_for_idle(30.0)
        run = [a for a in s.store.allocs_by_job("default", job.id)
               if a.desired_status == AllocDesiredStatus.RUN
               and a.client_status != AllocClientStatus.LOST]
        assert len(run) == 2
        assert all(a.node_id != victim_alloc.node_id for a in run)
    finally:
        s.stop()
