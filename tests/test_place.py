"""Placement engine behavior tests (dense analog of scheduler/rank_test.go,
feasible_test.go, spread_test.go cases)."""
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.scheduler.stack import DenseStack
from nomad_tpu.structs.config import SchedulerConfiguration
from nomad_tpu.structs.job import Affinity, Constraint, Operand, Spread, SpreadTarget


def build_world(n_nodes=4, **node_overrides):
    cm = ClusterMatrix()
    nodes = [mock.node(**node_overrides) for _ in range(n_nodes)]
    for nd in nodes:
        cm.upsert_node(nd)
    return cm, nodes


def run_place(cm, job, count=None, allocs_by_tg=None, config=None, penalty=None):
    stack = DenseStack(cm, config)
    groups = [stack.compile_group(job, tg) for tg in job.task_groups]
    slots = []
    for gi, g in enumerate(groups):
        slots += [gi] * (count if count is not None else g.tg.count)
    inp = stack.build_inputs(job, groups, slots, allocs_by_tg or {}, penalty_nodes=penalty)
    return stack.place(inp), inp, slots


def test_basic_placement_fills_all_slots():
    cm, nodes = build_world(4)
    j = mock.job()
    j.task_groups[0].count = 4
    res, inp, slots = run_place(cm, j)
    sel = res.node[:len(slots)]
    assert (sel >= 0).all()
    # anti-affinity should spread the 4 placements over the 4 nodes
    assert len(set(sel.tolist())) == 4


def test_constraint_filters_nodes():
    cm, nodes = build_world(4)
    special = mock.node()
    special.attributes["rack"] = "r1"
    cm.upsert_node(special)
    j = mock.job()
    j.task_groups[0].count = 1
    j.constraints.append(Constraint("${attr.rack}", "r1", Operand.EQ))
    res, _, slots = run_place(cm, j)
    assert res.node[0] == cm.row_of[special.id]


def test_infeasible_yields_minus_one():
    cm, nodes = build_world(2)
    j = mock.job()
    j.constraints.append(Constraint("${attr.rack}", "nope", Operand.EQ))
    res, _, _ = run_place(cm, j, count=1)
    assert res.node[0] == -1
    assert res.nodes_evaluated[0] == 0


def test_resource_exhaustion_sequential_coupling():
    """Placements within one eval consume proposed capacity."""
    cm, nodes = build_world(1)
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = 3000   # node has 4000
    res, _, _ = run_place(cm, j, count=2)
    assert res.node[0] >= 0
    assert res.node[1] == -1                          # second no longer fits
    assert res.nodes_exhausted[1] == 1


def test_binpack_prefers_loaded_node():
    cm, nodes = build_world(2)
    j0 = mock.job()
    a = mock.alloc_for(j0, nodes[0].id)               # 500 MHz on node 0
    cm.upsert_alloc(a)
    j = mock.job()
    res, _, _ = run_place(cm, j, count=1)
    assert res.node[0] == cm.row_of[nodes[0].id]      # binpack packs onto loaded


def test_spread_algorithm_prefers_empty_node():
    cm, nodes = build_world(2)
    j0 = mock.job()
    cm.upsert_alloc(mock.alloc_for(j0, nodes[0].id))
    j = mock.job()
    cfg = SchedulerConfiguration(scheduler_algorithm="spread")
    res, _, _ = run_place(cm, j, count=1, config=cfg)
    assert res.node[0] == cm.row_of[nodes[1].id]


def test_rescheduling_penalty_avoids_previous_node():
    cm, nodes = build_world(2)
    j = mock.job()
    res, _, _ = run_place(cm, j, count=1,
                          penalty={"web": {nodes[0].id}})
    assert res.node[0] == cm.row_of[nodes[1].id]


def test_affinity_attracts():
    cm, nodes = build_world(3)
    target = mock.node()
    target.attributes["rack"] = "fast"
    cm.upsert_node(target)
    j = mock.job()
    j.affinities.append(Affinity("${attr.rack}", "fast", Operand.EQ, weight=100))
    res, _, _ = run_place(cm, j, count=1)
    assert res.node[0] == cm.row_of[target.id]


def test_negative_affinity_repels():
    cm, nodes = build_world(1)
    bad = mock.node()
    bad.attributes["rack"] = "slow"
    cm.upsert_node(bad)
    j = mock.job()
    j.affinities.append(Affinity("${attr.rack}", "slow", Operand.EQ, weight=-100))
    res, _, _ = run_place(cm, j, count=1)
    assert res.node[0] == cm.row_of[nodes[0].id]


def test_targeted_spread_follows_percentages():
    cm = ClusterMatrix()
    r1 = [mock.node() for _ in range(2)]
    r2 = [mock.node() for _ in range(2)]
    for n in r1:
        n.attributes["rack"] = "r1"
        cm.upsert_node(n)
    for n in r2:
        n.attributes["rack"] = "r2"
        cm.upsert_node(n)
    j = mock.job()
    j.task_groups[0].count = 4
    j.task_groups[0].spreads = [Spread("${attr.rack}", 100,
                                       (SpreadTarget("r1", 75), SpreadTarget("r2", 25)))]
    res, _, slots = run_place(cm, j)
    rows_r1 = {cm.row_of[n.id] for n in r1}
    placed_r1 = sum(1 for s in res.node[:4].tolist() if s in rows_r1)
    assert placed_r1 == 3                      # 75% of 4


def test_even_spread_balances():
    cm = ClusterMatrix()
    nodes = []
    for dc in ("dc1", "dc1", "dc2", "dc2"):
        n = mock.node(datacenter=dc)
        nodes.append(n)
        cm.upsert_node(n)
    j = mock.job()
    j.datacenters = ["dc1", "dc2"]
    j.task_groups[0].count = 4
    j.task_groups[0].spreads = [Spread("${node.datacenter}", 100, ())]
    res, _, _ = run_place(cm, j)
    dcs = [nodes_dc for nodes_dc in res.node[:4].tolist()]
    dc_of_row = {cm.row_of[n.id]: n.datacenter for n in nodes}
    counts = {}
    for r in dcs:
        counts[dc_of_row[r]] = counts.get(dc_of_row[r], 0) + 1
    assert counts == {"dc1": 2, "dc2": 2}


def test_distinct_hosts():
    cm, nodes = build_world(3)
    j = mock.job()
    j.constraints.append(Constraint(operand=Operand.DISTINCT_HOSTS))
    existing = mock.alloc_for(j, nodes[0].id)
    res, _, _ = run_place(cm, j, count=1, allocs_by_tg={"web": [existing]})
    assert res.node[0] != cm.row_of[nodes[0].id]


def test_score_meta_topk():
    cm, nodes = build_world(4)
    j = mock.job()
    res, _, _ = run_place(cm, j, count=1)
    assert (res.top_scores[0, 1:] <= res.top_scores[0, 0]).all()


def test_version_constraint():
    cm = ClusterMatrix()
    old = mock.node()
    old.attributes["nomad.version"] = "0.4.0"
    new = mock.node()
    new.attributes["nomad.version"] = "1.2.3"
    cm.upsert_node(old)
    cm.upsert_node(new)
    j = mock.job()
    j.constraints.append(Constraint("${attr.nomad.version}", ">= 1.0.0", Operand.VERSION))
    res, _, _ = run_place(cm, j, count=1)
    assert res.node[0] == cm.row_of[new.id]


def test_regex_constraint():
    cm = ClusterMatrix()
    a = mock.node(name="web-01")
    b = mock.node(name="db-01")
    cm.upsert_node(a)
    cm.upsert_node(b)
    j = mock.job()
    j.constraints.append(Constraint("${node.unique.name}", "^web-", Operand.REGEX))
    res, _, _ = run_place(cm, j, count=1)
    assert res.node[0] == cm.row_of[a.id]
