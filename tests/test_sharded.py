"""Multi-chip sharded placement: parity with the single-chip engine on the
8-device virtual CPU mesh."""
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.parallel import make_mesh, place_eval_batch_sharded, stack_inputs
from nomad_tpu.scheduler.stack import DenseStack


def build_inputs(n_nodes=16, count=6, seed=0):
    cm = ClusterMatrix()
    rng = np.random.default_rng(seed)
    for i in range(n_nodes):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 4}"
        cm.upsert_node(n)
    j = mock.job()
    j.task_groups[0].count = count
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    inp = st.build_inputs(j, groups, [0] * count, {})
    return st, inp, count


def test_sharded_matches_single_chip():
    from nomad_tpu.ops.place import place_eval
    st, inp, count = build_inputs()
    single = place_eval(inp, st.spread_algorithm)

    mesh = make_mesh(n_eval_shards=2, n_node_shards=4)
    batch = stack_inputs([inp, inp])
    node, score, n_eval, n_exh, top_i, top_s, used = \
        place_eval_batch_sharded(mesh, batch)

    for b in range(2):
        assert np.array_equal(np.asarray(node[b]), single.node), \
            (np.asarray(node[b]), single.node)
        np.testing.assert_allclose(np.asarray(score[b])[:count],
                                   single.score[:count], rtol=1e-5)
        assert np.array_equal(np.asarray(n_eval[b]), single.nodes_evaluated)
    # final usage matrices agree
    np.testing.assert_allclose(np.asarray(used[0]), single.used, rtol=1e-5)


def test_sharded_with_spread_and_affinity():
    from nomad_tpu.structs.job import Affinity, Operand, Spread
    cm = ClusterMatrix()
    for i in range(8):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 2}"
        cm.upsert_node(n)
    j = mock.job()
    j.task_groups[0].count = 4
    j.task_groups[0].spreads = [Spread("${attr.rack}", 100, ())]
    j.affinities.append(Affinity("${attr.rack}", "r0", Operand.EQ, weight=20))
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    inp = st.build_inputs(j, groups, [0] * 4, {})
    single = st.place(inp)

    mesh = make_mesh(n_eval_shards=1, n_node_shards=8)
    batch = stack_inputs([inp])
    node, score, *_ = place_eval_batch_sharded(mesh, batch)
    assert np.array_equal(np.asarray(node[0]), single.node)
    np.testing.assert_allclose(np.asarray(score[0])[:4], single.score[:4],
                               rtol=1e-5)
