"""Multi-chip sharded placement: parity with the single-chip engine on the
8-device virtual CPU mesh."""
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.parallel import make_mesh, place_eval_batch_sharded, stack_inputs
from nomad_tpu.scheduler.stack import DenseStack


def build_inputs(n_nodes=16, count=6, seed=0):
    cm = ClusterMatrix()
    rng = np.random.default_rng(seed)
    for i in range(n_nodes):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 4}"
        cm.upsert_node(n)
    j = mock.job()
    j.task_groups[0].count = count
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    inp = st.build_inputs(j, groups, [0] * count, {})
    return st, inp, count


def test_sharded_matches_single_chip():
    from nomad_tpu.ops.place import place_eval
    st, inp, count = build_inputs()
    single = place_eval(inp, st.spread_algorithm)

    mesh = make_mesh(n_wave_shards=2, n_node_shards=4)
    batch = stack_inputs([inp, inp])
    node, score, fit_s, n_eval, n_exh, top_i, top_s, used = \
        place_eval_batch_sharded(mesh, batch)

    for b in range(2):
        assert np.array_equal(np.asarray(node[b]), single.node), \
            (np.asarray(node[b]), single.node)
        np.testing.assert_allclose(np.asarray(score[b])[:count],
                                   single.score[:count], rtol=1e-5)
        assert np.array_equal(np.asarray(n_eval[b]), single.nodes_evaluated)
    # final usage matrices agree
    np.testing.assert_allclose(np.asarray(used[0]), single.used, rtol=1e-5)


def test_sharded_with_spread_and_affinity():
    from nomad_tpu.structs.job import Affinity, Operand, Spread
    cm = ClusterMatrix()
    for i in range(8):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 2}"
        cm.upsert_node(n)
    j = mock.job()
    j.task_groups[0].count = 4
    j.task_groups[0].spreads = [Spread("${attr.rack}", 100, ())]
    j.affinities.append(Affinity("${attr.rack}", "r0", Operand.EQ, weight=20))
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    inp = st.build_inputs(j, groups, [0] * 4, {})
    single = st.place(inp)

    mesh = make_mesh(n_wave_shards=1, n_node_shards=8)
    batch = stack_inputs([inp])
    node, score, *_ = place_eval_batch_sharded(mesh, batch)
    # the engine pads the slot axis to a canonical bucket; compare the
    # real slots
    assert np.array_equal(np.asarray(node[0]), single.node[:4])
    np.testing.assert_allclose(np.asarray(score[0])[:4], single.score[:4],
                               rtol=1e-5)


def _mixed_world(n_nodes, racks=8, seed=3):
    rng = np.random.default_rng(seed)
    cm = ClusterMatrix(initial_rows=n_nodes)
    for i in range(n_nodes):
        n = mock.node()
        n.attributes["rack"] = f"r{i % racks}"
        n.node_resources.cpu.cpu_shares = int(rng.integers(3000, 8000))
        cm.upsert_node(n)
    return cm


def _mixed_job(count):
    from nomad_tpu.structs.job import Affinity, Operand, Spread
    j = mock.job()
    tg = j.task_groups[0]
    tg.count = count
    tg.spreads = [Spread("${attr.rack}", 60, ())]
    j.affinities.append(Affinity("${attr.rack}", "r2", Operand.EQ,
                                 weight=40))
    return j


def test_sharded_scale_10k_nodes_mixed():
    """VERDICT r3 item 5: a 10K-node world with spreads + affinities
    active, a few hundred slots, through both the single-chip kernel and
    the 8-device sharded kernel — identical selections, scores, and
    spread-count carries."""
    from nomad_tpu.ops.place import place_eval

    cm = _mixed_world(10_000)
    assert cm.n_rows == 16384            # divides the 8-device mesh
    count = 200
    j = _mixed_job(count)
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    inp = st.build_inputs(j, groups, [0] * count, {})

    single = place_eval(inp, st.spread_algorithm)

    mesh = make_mesh(n_wave_shards=1, n_node_shards=8)
    batch = stack_inputs([inp])
    node, score, fit_s, n_eval, n_exh, top_i, top_s, used = \
        place_eval_batch_sharded(mesh, batch, st.spread_algorithm)

    np.testing.assert_array_equal(np.asarray(node[0]), single.node)
    np.testing.assert_allclose(np.asarray(score[0])[:count],
                               single.score[:count], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(fit_s[0])[:count],
                               single.fit_score[:count], rtol=1e-5)
    assert np.array_equal(np.asarray(n_eval[0]), single.nodes_evaluated)
    # spread-carry consistency: identical placements imply identical
    # per-rack distribution; verify against the selections directly
    racks = np.array([int(cm.attrs.columns["attr.rack"].values[r][1:])
                      for r in np.asarray(node[0])[:count]])
    single_racks = np.array(
        [int(cm.attrs.columns["attr.rack"].values[r][1:])
         for r in single.node[:count]])
    np.testing.assert_array_equal(racks, single_racks)
    # usage matrices agree (sharded returns the node-sharded final used)
    np.testing.assert_allclose(np.asarray(used[0]), np.asarray(single.used),
                               rtol=1e-5)


def test_engine_sharded_serving_parity():
    """The engine's multi-chip serving route (chained scan + bulk over
    the ('nodes',) mesh) must produce placements identical to the
    single-device engine paths."""
    from concurrent.futures import Future

    from nomad_tpu.ops.place import place_eval
    from nomad_tpu.parallel.engine import PlacementEngine, _Request

    cm = _mixed_world(1024)
    count = 12
    j = _mixed_job(count)
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    inp = st.build_inputs(j, groups, [0] * count, {})
    single = place_eval(inp, st.spread_algorithm)

    eng = PlacementEngine(shard_min_nodes=8)
    try:
        assert eng._mesh_for(cm.n_rows) is not None
        reqs = [_Request(cm=cm, inputs=inp, deltas=[],
                         spread_algorithm=False, future=Future())
                for _ in range(2)]
        eng._dispatch(reqs)
        res, ticket = reqs[0].future.result(timeout=120)
        np.testing.assert_array_equal(np.asarray(res.node[:count]),
                                      single.node[:count])
        np.testing.assert_allclose(np.asarray(res.score[:count]),
                                   single.score[:count], rtol=1e-5)
        eng.complete(ticket)
        _, ticket1 = reqs[1].future.result(timeout=120)
        eng.complete(ticket1)   # drain the overlay before the bulk check
        assert eng.stats.get("sharded_evals", 0) >= 2

        # bulk wavefront through the mesh vs the single-device kernel
        import jax

        from nomad_tpu.ops.place import place_bulk_jit, unpack_bulk
        N = cm.n_rows
        bj = mock.batch_job()
        btg = bj.task_groups[0]
        btg.count = 30
        btg.ephemeral_disk.size_mb = 0
        bst = DenseStack(cm)
        bg = bst.compile_group(bj, btg)
        zero = np.zeros(N, np.int32)
        packed = place_bulk_jit(
            np.ascontiguousarray(cm.capacity),
            np.ascontiguousarray(cm.used.astype(np.float32)),
            bg.feasible, bg.affinity.astype(np.float32),
            bool(bg.has_affinity), np.int32(30), np.zeros(N, bool),
            zero, bg.demand.astype(np.float32), np.int32(30))
        ref_assign, ref_placed, *_ = unpack_bulk(jax.device_get(packed))

        assign, placed, n_eval, n_exh, scores, tkt = \
            eng.place_bulk(cm, feasible=bg.feasible,
                           affinity=bg.affinity, has_affinity=bg.has_affinity,
                           desired=30, penalty=np.zeros(N, bool),
                           coll0=zero, demand=bg.demand, count=30)
        np.testing.assert_array_equal(assign, ref_assign)
        assert placed == ref_placed == 30
        eng.complete(tkt)
    finally:
        eng.stop()


def test_e2e_spine_sharded_matches_single_device():
    """VERDICT r3 item 1 'done' criterion: a 1K-node / 5K-alloc world
    placed through the FULL Server spine on the 8-virtual-device mesh,
    with placements identical (same node rows) to the single-device
    engine.  One scheduler worker keeps eval processing order
    deterministic so the runs are comparable."""
    import os

    from nomad_tpu.core.server import Server, ServerConfig

    def run_spine(shard: bool):
        os.environ["NOMAD_TPU_SHARD"] = "1" if shard else "0"
        try:
            s = Server(ServerConfig(num_schedulers=1,
                                    heartbeat_ttl=3600.0,
                                    gc_interval=3600.0))
            s.start()
            try:
                for i in range(1000):
                    n = mock.node()
                    n.attributes["rack"] = f"r{i % 8}"
                    s.register_node(n)
                assert s.store.matrix.n_rows == 1024
                jobs = []
                for k in range(50):
                    j = mock.batch_job(id=f"spine-{k}")
                    j.task_groups[0].count = 100
                    jobs.append(j)
                    s.register_job(j)
                import time
                deadline = time.time() + 240
                want = 5000
                while time.time() < deadline:
                    placed = sum(len(s.store.allocs_by_job("default", j.id))
                                 for j in jobs)
                    if placed >= want:
                        break
                    time.sleep(0.05)
                rows = {}
                cm = s.store.matrix
                for j in jobs:
                    counts = {}
                    for a in s.store.allocs_by_job("default", j.id):
                        row = cm.row_of[a.node_id]
                        counts[row] = counts.get(row, 0) + 1
                    rows[j.id] = counts
                assert placed == want, placed
                return rows
            finally:
                s.stop()
        finally:
            os.environ.pop("NOMAD_TPU_SHARD", None)

    sharded = run_spine(shard=True)
    single = run_spine(shard=False)
    assert sharded == single


def test_engine_sharded_c2m_scale_mixed_batch():
    """VERDICT r4 item 5: the engine's sharded serving paths at C2M
    node scale — N=10,240 (16,384 padded rows) sharded 8 ways — with a
    MIXED eval batch (small-count bulk, large-count bulk, spread scan),
    asserting placement parity with the single-device engine."""
    from concurrent.futures import Future

    from nomad_tpu.parallel.engine import PlacementEngine, _Request

    cm = _mixed_world(10_240)
    N = cm.n_rows
    assert N % 8 == 0

    # bulk groups: one small-count (sparse-output class), one large
    bj = mock.batch_job()
    btg = bj.task_groups[0]
    btg.count = 10
    btg.ephemeral_disk.size_mb = 0
    bst = DenseStack(cm)
    bg_small = bst.compile_group(bj, btg)
    bj2 = mock.batch_job()
    btg2 = bj2.task_groups[0]
    btg2.count = 200
    btg2.ephemeral_disk.size_mb = 0
    bg_large = DenseStack(cm).compile_group(bj2, btg2)

    # scan eval: spreads active
    count = 40
    sj = _mixed_job(count)
    st = DenseStack(cm)
    groups = [st.compile_group(sj, tg) for tg in sj.task_groups]
    scan_inp = st.build_inputs(sj, groups, [0] * count, {})

    zero = np.zeros(N, np.int32)

    def run(shard_min):
        eng = PlacementEngine(shard_min_nodes=shard_min)
        out = {}
        try:
            a1, p1, *_rest1, t1 = eng.place_bulk(
                cm, feasible=bg_small.feasible,
                affinity=bg_small.affinity,
                has_affinity=bg_small.has_affinity, desired=10,
                penalty=np.zeros(N, bool), coll0=zero,
                demand=bg_small.demand, count=10)
            eng.complete(t1)
            a2, p2, *_rest2, t2 = eng.place_bulk(
                cm, feasible=bg_large.feasible,
                affinity=bg_large.affinity,
                has_affinity=bg_large.has_affinity, desired=200,
                penalty=np.zeros(N, bool), coll0=zero,
                demand=bg_large.demand, count=200)
            eng.complete(t2)
            req = _Request(cm=cm, inputs=scan_inp, deltas=[],
                           spread_algorithm=False, future=Future())
            eng._dispatch([req])
            res, t3 = req.future.result(timeout=300)
            eng.complete(t3)
            out = {"a1": a1, "p1": p1, "a2": a2, "p2": p2,
                   "scan_nodes": np.asarray(res.node[:count]).copy(),
                   "scan_scores": np.asarray(res.score[:count]).copy(),
                   "sharded": eng.stats.get("sharded_evals", 0)}
        finally:
            eng.stop()
        return out

    sharded = run(shard_min=8)         # mesh active at this N
    single = run(shard_min=1 << 30)    # mesh disabled

    assert sharded["sharded"] >= 1
    assert single["sharded"] == 0
    assert sharded["p1"] == single["p1"] == 10
    assert sharded["p2"] == single["p2"] == 200
    np.testing.assert_array_equal(sharded["a1"], single["a1"])
    np.testing.assert_array_equal(sharded["a2"], single["a2"])
    np.testing.assert_array_equal(sharded["scan_nodes"],
                                  single["scan_nodes"])
    np.testing.assert_allclose(sharded["scan_scores"],
                               single["scan_scores"], rtol=1e-5)


@pytest.mark.parametrize("use_mesh", [False, True])
def test_device_world_parity_randomized(use_mesh):
    """Device-resident incremental state == from-scratch rebuild, bitwise,
    after a randomized interleaving of plan commits (rank-1 scatters),
    node joins/drains (row mutations), preemptions (negative counts), and
    a cluster epoch change (row-count growth -> full re-upload)."""
    import jax

    from nomad_tpu.parallel.sharded import make_serving_mesh
    from nomad_tpu.parallel.world import DeviceWorld

    rng = np.random.default_rng(7)
    N, R = 64, 4
    mesh = make_serving_mesh() if use_mesh else None
    world = DeviceWorld(mesh=mesh)

    capacity = rng.uniform(100, 1000, (N, R)).astype(np.float32)
    truth = np.zeros((N, R), np.float32)        # from-scratch reference
    world.update(capacity, truth.copy())

    def check():
        cap_dev, basis_dev = world.device_arrays()
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(basis_dev)), truth)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(cap_dev)), capacity)
        np.testing.assert_array_equal(world.host_basis(), truth)

    for step in range(60):
        op = rng.integers(0, 4)
        if op == 0:                              # plan commit
            k = int(rng.integers(1, 9))
            rows = rng.choice(N, k, replace=False).astype(np.int32)
            counts = rng.integers(1, 4, k).astype(np.int32)
            demand = rng.uniform(0, 50, R).astype(np.float32)
            world.apply_rank1(rows, counts, demand)
            truth[rows] += counts[:, None].astype(np.float32) * demand
        elif op == 1:                            # preemption: reverse
            k = int(rng.integers(1, 5))
            rows = rng.choice(N, k, replace=False).astype(np.int32)
            demand = rng.uniform(0, 20, R).astype(np.float32)
            world.apply_rank1(rows, np.full(k, -1, np.int32), demand)
            truth[rows] -= demand
        elif op == 2:                            # node join/drain churn
            k = int(rng.integers(1, 6))
            rows = rng.choice(N, k, replace=False)
            capacity[rows] = rng.uniform(100, 1000, (k, R))
            truth[rows] = 0.0                    # drained node resets
            world.update(capacity, truth.copy())
        else:                                    # clean dispatch
            world.update(capacity, truth.copy())
        check()

    # epoch change: the padded row axis grows -> one full re-upload
    N2 = N * 2
    cap2 = rng.uniform(100, 1000, (N2, R)).astype(np.float32)
    cap2[:N] = capacity
    truth2 = np.zeros((N2, R), np.float32)
    truth2[:N] = truth
    if use_mesh:
        capacity, truth = cap2, truth2
        N = N2
    else:                                        # odd N fine unsharded
        capacity = cap2[: N2 - 3].copy()
        truth = truth2[: N2 - 3].copy()
        N = N2 - 3
    world.update(capacity, truth.copy())
    rows = rng.choice(N, 5, replace=False).astype(np.int32)
    demand = rng.uniform(0, 50, R).astype(np.float32)
    world.apply_rank1(rows, np.ones(5, np.int32), demand)
    truth[rows] += demand
    check()
    assert world.stats["full_uploads"] >= 2
    assert world.stats["rank1_applies"] >= 1


def test_mesh_key_survives_mesh_recreation():
    """`mesh_key` identifies re-created meshes as the same serving mesh
    (the `id(mesh)` keying bug: a new Mesh object could reuse a dead
    mesh's id and resurrect stale shardings)."""
    from nomad_tpu.parallel.engine import PlacementEngine
    from nomad_tpu.parallel.sharded import make_serving_mesh
    from nomad_tpu.parallel.world import mesh_key

    import jax

    m1 = make_serving_mesh()
    m2 = make_serving_mesh()
    assert mesh_key(m1) == mesh_key(m2)
    assert mesh_key(None) is None
    # the key DISCRIMINATES meshes over different device sets
    half = make_serving_mesh(jax.devices()[: len(jax.devices()) // 2])
    assert mesh_key(half) != mesh_key(m1)

    eng = PlacementEngine()
    try:
        arr = np.arange(16, dtype=np.float32).reshape(8, 2)
        from jax.sharding import NamedSharding, PartitionSpec as P
        a1 = eng._cache.sharded("t", m1, arr,
                                NamedSharding(m1, P("node_shard", None)))
        a2 = eng._cache.sharded("t", m2, arr,
                                NamedSharding(m2, P("node_shard", None)))
        assert a1 is a2                          # same content-address
    finally:
        eng.stop()
