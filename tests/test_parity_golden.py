"""Golden parity suite: concrete inputs AND expected outputs ported from
the Go reference's own tests, so a failure here distinguishes "kernel
diverges from reference semantics" from "host twin and kernel share a bug"
(both would pass the self-referential twin tests in test_ops.py).

Sources (expected values copied from the reference assertions):
- /root/reference/nomad/structs/funcs_test.go:692-760  (TestScoreFitBinPack)
- /root/reference/scheduler/rank_test.go:34-139   (BinPackIterator_NoExistingAlloc)
- /root/reference/scheduler/rank_test.go:1843-1921 (JobAntiAffinity_PlannedAlloc)
- /root/reference/scheduler/rank_test.go:1923-1957 (NodeAntiAffinity_PenaltyNodes)
- /root/reference/scheduler/rank_test.go:1959-2022 (ScoreNormalizationIterator)
- /root/reference/scheduler/rank_test.go:2024-2101 (NodeAffinityIterator)
- /root/reference/scheduler/spread_test.go:19-177  (SpreadIterator_SingleAttribute)
- /root/reference/scheduler/spread_test.go:561-584 (evenSpreadScoreBoost)
- /root/reference/scheduler/preemption_test.go:16-146 (TestResourceDistance)
"""
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.ops.fit import score_fit
from nomad_tpu.ops.place import place_eval
from nomad_tpu.ops.preempt import _distance
from nomad_tpu.scheduler.stack import DenseStack
from nomad_tpu.structs.job import Affinity, Operand, Spread, SpreadTarget
from nomad_tpu.structs.node import (
    NodeCpuResources,
    NodeReservedResources,
    NodeResources,
)


def _node(cpu, mem, res_cpu=0, res_mem=0, disk=100_000, **over):
    n = mock.node(**over)
    n.node_resources = NodeResources(
        cpu=NodeCpuResources(cpu_shares=cpu, total_core_count=4,
                             reservable_cores=[0, 1, 2, 3]),
        memory_mb=mem, disk_mb=disk)
    n.reserved_resources = NodeReservedResources(
        cpu_shares=res_cpu, memory_mb=res_mem)
    return n


def _world(nodes):
    cm = ClusterMatrix(initial_rows=len(nodes))
    rows = [cm.upsert_node(n) for n in nodes]
    return cm, rows


def _place_one(cm, job, allocs_by_tg=None, penalty_nodes=None):
    """One placement slot through the real stack + kernel; returns
    (selected row, selected score, {row: score} from the top-K meta)."""
    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg) for tg in job.task_groups]
    inp = stack.build_inputs(job, groups, [0], allocs_by_tg or {},
                             penalty_nodes=penalty_nodes)
    res = place_eval(inp)
    scores = {int(r): float(s)
              for r, s in zip(res.top_nodes[0], res.top_scores[0])
              if s > -np.inf}
    return int(res.node[0]), float(res.score[0]), scores


# --------------------------------------------------------------- score_fit
# funcs_test.go:692-760: node 4096/8192 with 2048/4096 reserved
# => comparable capacity 2048 cpu / 4096 mem.

FIT_CASES = [
    # (util_cpu, util_mem, binpack, spread)  -- exact reference values
    (2048, 4096, 18.0, 0.0),     # "almost filled node, just enough hole"
    (0, 0, 0.0, 18.0),           # "unutilized node"
    (1024, 2048, 13.675, 4.325), # "mid-case scenario"
]


@pytest.mark.parametrize("cpu,mem,binpack,spread", FIT_CASES)
def test_score_fit_binpack_golden(cpu, mem, binpack, spread):
    capacity = np.array([[2048.0, 4096.0, 0.0]], np.float32)
    util = np.array([[cpu, mem, 0.0]], np.float32)
    got_bp = float(np.asarray(score_fit(capacity, util, False))[0])
    got_sp = float(np.asarray(score_fit(capacity, util, True))[0])
    assert got_bp == pytest.approx(binpack, abs=1e-3)
    assert got_sp == pytest.approx(spread, abs=1e-3)
    assert got_bp + got_sp == pytest.approx(18.0, abs=1e-3)


def test_binpack_iterator_no_existing_alloc():
    """rank_test.go:34-139.  Three nodes (after reserved subtraction:
    1024/1024, 512/512, 3072/3072), task demand 1024 cpu / 1024 mem:
    node0 is a perfect fit (score 1.0), node1 is overloaded (filtered),
    node2 scores in (0.50, 0.60)."""
    n0 = _node(2048, 2048, 1024, 1024)
    n1 = _node(1024, 1024, 512, 512)
    n2 = _node(4096, 4096, 1024, 1024)
    cm, rows = _world([n0, n1, n2])

    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].resources.cpu = 1024
    job.task_groups[0].tasks[0].resources.memory_mb = 1024
    job.task_groups[0].ephemeral_disk.size_mb = 0

    sel, score, scores = _place_one(cm, job)
    assert sel == rows[0]
    assert score == pytest.approx(1.0, abs=1e-3)
    assert rows[1] not in scores          # overloaded node filtered out
    assert 0.50 < scores[rows[2]] < 0.60


def test_binpack_mixed_reserve_equivalence():
    """rank_test.go:139-254 (MixedReserve): a node with reserved resources
    scores exactly as if it simply had less capacity."""
    n_reserved = _node(2048, 2048, 1024, 1024)
    n_smaller = _node(1024, 1024, 0, 0)
    cm, rows = _world([n_reserved, n_smaller])

    job = mock.job()
    job.task_groups[0].count = 1
    job.task_groups[0].tasks[0].resources.cpu = 512
    job.task_groups[0].tasks[0].resources.memory_mb = 512
    job.task_groups[0].ephemeral_disk.size_mb = 0

    _, _, scores = _place_one(cm, job)
    assert scores[rows[0]] == pytest.approx(scores[rows[1]], abs=1e-6)


# ------------------------------------------------------- scoring iterators
# The reference tests isolate one scoring iterator behind
# ScoreNormalization; the dense kernel always composes fit + active
# scorers and divides by the number that ran (rank.go:781-795), so the
# expected composites below are  (fit + iterator_golden) / n_scorers  with
# fit hand-derived from the funcs.go formula.

def _fit_for(cap_cpu, cap_mem, util_cpu, util_mem):
    """ScoreFitBinPack(funcs.go:259-279)/18, hand-computed."""
    free_cpu = 1.0 - util_cpu / cap_cpu
    free_mem = 1.0 - util_mem / cap_mem
    return (20.0 - 10.0 ** free_cpu - 10.0 ** free_mem) / 18.0


def test_job_anti_affinity_golden():
    """rank_test.go:1843-1921: two planned/existing allocs of the same
    (job, tg) on node0, desired count 4 => anti-affinity score -(2+1)/4 =
    -0.75 on node0 (reference asserts exactly -0.75), 0 on node1.
    Composite: node0 = (fit - 0.75)/2, node1 = fit (single scorer)."""
    n0 = _node(4000, 8192)
    n1 = _node(4000, 8192)
    cm, rows = _world([n0, n1])

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 4
    tg.tasks[0].resources.cpu = 1000
    tg.tasks[0].resources.memory_mb = 2048
    tg.ephemeral_disk.size_mb = 0

    a1 = mock.alloc_for(job, node_id=n0.id)
    a2 = mock.alloc_for(job, node_id=n0.id, index=1)
    cm.upsert_alloc(a1)
    cm.upsert_alloc(a2)
    _, _, scores = _place_one(cm, job, {tg.name: [a1, a2]})

    # node0 carries two existing allocs of this tg -> its usage includes
    # them (2000 cpu / 4096 mem) before the new demand
    fit0 = _fit_for(4000, 8192, 3000, 6144)
    fit1 = _fit_for(4000, 8192, 1000, 2048)
    assert scores[rows[0]] == pytest.approx((fit0 - 0.75) / 2.0, abs=1e-3)
    assert scores[rows[1]] == pytest.approx(fit1, abs=1e-3)


def test_penalty_nodes_golden():
    """rank_test.go:1923-1957: rescheduling-penalty node scores -1.0 on
    that iterator; composite = (fit - 1.0)/2 vs plain fit."""
    n0 = _node(4000, 8192)
    n1 = _node(4000, 8192)
    cm, rows = _world([n0, n1])

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.cpu = 1000
    tg.tasks[0].resources.memory_mb = 2048
    tg.ephemeral_disk.size_mb = 0

    _, _, scores = _place_one(cm, job,
                              penalty_nodes={tg.name: {n0.id}})
    fit = _fit_for(4000, 8192, 1000, 2048)
    assert scores[rows[0]] == pytest.approx((fit - 1.0) / 2.0, abs=1e-3)
    assert scores[rows[1]] == pytest.approx(fit, abs=1e-3)


def test_score_normalization_golden():
    """rank_test.go:1959-2022: anti-affinity (-0.75) AND penalty (-1.0)
    on node0 average to -0.875 over those two scorers; with the fit
    scorer the dense composite is (fit - 0.75 - 1.0)/3."""
    n0 = _node(4000, 8192)
    n1 = _node(4000, 8192)
    cm, rows = _world([n0, n1])

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 4
    tg.tasks[0].resources.cpu = 1000
    tg.tasks[0].resources.memory_mb = 2048
    tg.ephemeral_disk.size_mb = 0

    a1 = mock.alloc_for(job, node_id=n0.id)
    a2 = mock.alloc_for(job, node_id=n0.id, index=1)
    cm.upsert_alloc(a1)
    cm.upsert_alloc(a2)
    _, _, scores = _place_one(cm, job, {tg.name: [a1, a2]},
                              penalty_nodes={tg.name: {n0.id}})
    fit0 = _fit_for(4000, 8192, 3000, 6144)
    assert scores[rows[0]] == pytest.approx((fit0 - 0.75 - 1.0) / 3.0,
                                            abs=1e-3)


def test_node_affinity_golden():
    """rank_test.go:2024-2101: four affinities with weights 100/-100/50/50
    (total 300).  Expected affinity scores: node0 (dc1 + kernel 4.9) 0.5,
    node1 (dc2) -1/3, node2 (dc2 + class large) -1/6, node3 (dc1) 1/3."""
    n0 = mock.node()
    n0.attributes["kernel.version"] = "4.9"
    n1 = mock.node(datacenter="dc2")
    n2 = mock.node(datacenter="dc2", node_class="large")
    n3 = mock.node()
    cm, rows = _world([n0, n1, n2, n3])

    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    tg = job.task_groups[0]
    tg.affinities = [
        Affinity("${node.datacenter}", "dc1", "=", 100),
        Affinity("${node.datacenter}", "dc2", "=", -100),
        Affinity("${attr.kernel.version}", ">4.0", "version", 50),
        Affinity("${node.class}", "large", "is", 50),
    ]

    stack = DenseStack(cm)
    g = stack.compile_group(job, tg)
    expected = [0.5, -1.0 / 3.0, -1.0 / 6.0, 1.0 / 3.0]
    for row, want in zip(rows, expected):
        assert g.affinity[row] == pytest.approx(want, abs=1e-6), row


# ------------------------------------------------------------------ spread

def test_spread_single_attribute_golden():
    """spread_test.go:19-96: dcs [dc1,dc2,dc1,dc1], count 10, existing
    allocs on nodes 0 and 2 (both dc1), target 80% dc1 (implicit 20%
    dc2).  Reference spread boosts: dc1 nodes 0.625 = (8-(2+1))/8, dc2
    node 0.5 = (2-(0+1))/2."""
    nodes = [mock.node(datacenter=dc) for dc in ("dc1", "dc2", "dc1", "dc1")]
    cm, rows = _world(nodes)

    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    tg = job.task_groups[0]
    tg.count = 10
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 100
    tg.ephemeral_disk.size_mb = 0
    tg.spreads = [Spread("${node.datacenter}", 100,
                         (SpreadTarget("dc1", 80),))]

    a0 = mock.alloc(job=job, node_id=nodes[0].id)
    a2 = mock.alloc(job=job, node_id=nodes[2].id)
    allocs = {tg.name: [a0, a2]}

    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg)]
    inp = stack.build_inputs(job, groups, [0], allocs)

    # evaluate the spread boost tensor directly (the reference test
    # isolates SpreadIterator the same way)
    import jax
    from nomad_tpu.ops.place import _spread_boost
    boost = np.asarray(jax.jit(_spread_boost)(
        jax.device_put(inp), 0, inp.spread_counts[0]))
    assert boost[rows[0]] == pytest.approx(0.625, abs=1e-6)
    assert boost[rows[2]] == pytest.approx(0.625, abs=1e-6)
    assert boost[rows[3]] == pytest.approx(0.625, abs=1e-6)
    assert boost[rows[1]] == pytest.approx(0.5, abs=1e-6)


def test_even_spread_boost_golden():
    """spread_test.go:561-584 (evenSpreadScoreBoost): with combined
    counts {dc1: 1, dc2: 0}, a dc2 node gets boost exactly 1.0 =
    (minCount - ownCount)/minCount... reference asserts 1.0 and finite."""
    nodes = [mock.node(datacenter="dc1"), mock.node(datacenter="dc2")]
    cm, rows = _world(nodes)

    job = mock.job()
    job.datacenters = ["dc1", "dc2"]
    tg = job.task_groups[0]
    tg.count = 10
    tg.ephemeral_disk.size_mb = 0
    tg.spreads = [Spread("${node.datacenter}", 100, ())]   # even spread

    a0 = mock.alloc(job=job, node_id=nodes[0].id)
    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg)]
    inp = stack.build_inputs(job, groups, [0], {tg.name: [a0]})

    import jax
    from nomad_tpu.ops.place import _spread_boost
    boost = np.asarray(jax.jit(_spread_boost)(
        jax.device_put(inp), 0, inp.spread_counts[0]))
    assert np.isfinite(boost[rows[1]])
    assert boost[rows[1]] == pytest.approx(1.0, abs=1e-6)


# ------------------------------------------------------------- preemption

def test_resource_distance_golden():
    """preemption_test.go:16-146 (basicResourceDistance): ask
    cpu=2048/mem=512/disk=4096; expected distances (reference asserts the
    3-decimal strings) over the cpu/mem/disk dimensions."""
    ask = np.array([2048.0, 512.0, 4096.0], np.float32)
    cands = np.array([
        [2048.0, 512.0, 4096.0],
        [1024.0, 400.0, 1024.0],
        [8192.0, 200.0, 1024.0],
        [2048.0, 500.0, 4096.0],
    ], np.float32)
    import jax
    d = np.asarray(jax.jit(_distance)(ask, cands))
    for got, want in zip(d, (0.000, 0.928, 3.152, 0.023)):
        assert f"{got:.3f}" == f"{want:.3f}"
