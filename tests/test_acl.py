"""ACL tests (reference analog: acl/acl_test.go, acl/policy_test.go,
nomad/acl_endpoint_test.go)."""
import pytest

from nomad_tpu import mock
from nomad_tpu.acl import ACL, parse_policy
from nomad_tpu.acl.policy import (
    CAP_LIST_JOBS,
    CAP_READ_JOB,
    CAP_SUBMIT_JOB,
)


def test_parse_policy_namespace_short_form():
    p = parse_policy("readonly", 'namespace "default" { policy = "read" }')
    assert p.namespaces[0].name == "default"
    assert p.namespaces[0].policy == "read"
    caps = p.namespaces[0].expanded()
    assert CAP_LIST_JOBS in caps
    assert CAP_READ_JOB in caps
    assert CAP_SUBMIT_JOB not in caps


def test_parse_policy_capabilities():
    p = parse_policy("submitter", '''
namespace "ops" {
  capabilities = ["submit-job", "read-job"]
}
node { policy = "read" }
operator { policy = "write" }
''')
    assert p.namespaces[0].capabilities == ["submit-job", "read-job"]
    assert p.node == "read"
    assert p.operator == "write"


def test_parse_policy_rejects_empty():
    with pytest.raises(ValueError):
        parse_policy("empty", "# nothing")


def test_acl_allows():
    pol = parse_policy("p", '''
namespace "default" { policy = "write" }
namespace "prod-*"  { policy = "read" }
node { policy = "read" }
''')
    acl = ACL(policies=[pol])
    assert acl.allows("default", CAP_SUBMIT_JOB)
    assert acl.allows("prod-web", CAP_READ_JOB)
    assert not acl.allows("prod-web", CAP_SUBMIT_JOB)
    assert not acl.allows("other", CAP_READ_JOB)
    assert acl.allows(None, "node:read")
    assert not acl.allows(None, "node:write")
    assert not acl.allows(None, "operator:read")


def test_acl_deny_overrides():
    a = parse_policy("allow", 'namespace "secret" { policy = "write" }')
    d = parse_policy("deny", 'namespace "secret" { policy = "deny" }')
    acl = ACL(policies=[a, d])
    assert not acl.allows("secret", CAP_READ_JOB)


def test_management_allows_all():
    acl = ACL(management=True)
    assert acl.allows("anything", CAP_SUBMIT_JOB)
    assert acl.allows(None, "operator:write")


def test_server_token_resolution():
    from nomad_tpu.core.server import Server, ServerConfig
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        s.enable_acl()
        boot = s.bootstrap_acl()
        assert boot.type == "management"
        with pytest.raises(RuntimeError):
            s.bootstrap_acl()          # only once
        acl = s.resolve_token(boot.secret_id)
        assert acl.management

        s.upsert_acl_policy("readonly", "",
                            'namespace "default" { policy = "read" }')
        tok = s.create_acl_token(name="reader", policies=["readonly"])
        racl = s.resolve_token(tok.secret_id)
        assert racl.allows("default", CAP_READ_JOB)
        assert not racl.allows("default", CAP_SUBMIT_JOB)

        assert s.resolve_token("bogus-secret") is None
        assert s.resolve_token("") is None      # no anonymous policy

        s.delete_acl_token(tok.accessor_id)
        assert s.resolve_token(tok.secret_id) is None
    finally:
        s.stop()


def test_http_acl_enforcement():
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api import ApiClient, ApiError

    a = Agent(AgentConfig(http_port=0, num_schedulers=1,
                          heartbeat_ttl=60.0))
    a.start()
    try:
        a.server.register_node(mock.node())
        boot = a.server.bootstrap_acl()
        a.server.enable_acl()

        anon = ApiClient(a.http_addr)
        with pytest.raises(ApiError) as e:
            anon.jobs.list()
        assert e.value.status == 403
        # status endpoints stay anonymous
        assert anon.system.leader() is not None

        mgmt = ApiClient(a.http_addr, token=boot.secret_id)
        assert mgmt.jobs.list() == []
        mgmt.acl.upsert_policy(
            "readonly", 'namespace "default" { policy = "read" }')
        resp = mgmt.acl.create_token(name="ro", policies=["readonly"])

        ro = ApiClient(a.http_addr, token=resp["SecretID"])
        assert ro.jobs.list() == []
        with pytest.raises(ApiError) as e:
            ro.jobs.register(mock.job())
        assert e.value.status == 403

        assert mgmt.acl.self_token()["AccessorID"] == boot.accessor_id
        assert len(mgmt.acl.tokens()) == 2
    finally:
        a.stop()
