"""ACL tests (reference analog: acl/acl_test.go, acl/policy_test.go,
nomad/acl_endpoint_test.go)."""
import pytest

from nomad_tpu import mock
from nomad_tpu.acl import ACL, parse_policy
from nomad_tpu.acl.policy import (
    CAP_LIST_JOBS,
    CAP_READ_JOB,
    CAP_SUBMIT_JOB,
)


def test_parse_policy_namespace_short_form():
    p = parse_policy("readonly", 'namespace "default" { policy = "read" }')
    assert p.namespaces[0].name == "default"
    assert p.namespaces[0].policy == "read"
    caps = p.namespaces[0].expanded()
    assert CAP_LIST_JOBS in caps
    assert CAP_READ_JOB in caps
    assert CAP_SUBMIT_JOB not in caps


def test_parse_policy_capabilities():
    p = parse_policy("submitter", '''
namespace "ops" {
  capabilities = ["submit-job", "read-job"]
}
node { policy = "read" }
operator { policy = "write" }
''')
    assert p.namespaces[0].capabilities == ["submit-job", "read-job"]
    assert p.node == "read"
    assert p.operator == "write"


def test_parse_policy_rejects_empty():
    with pytest.raises(ValueError):
        parse_policy("empty", "# nothing")


def test_acl_allows():
    pol = parse_policy("p", '''
namespace "default" { policy = "write" }
namespace "prod-*"  { policy = "read" }
node { policy = "read" }
''')
    acl = ACL(policies=[pol])
    assert acl.allows("default", CAP_SUBMIT_JOB)
    assert acl.allows("prod-web", CAP_READ_JOB)
    assert not acl.allows("prod-web", CAP_SUBMIT_JOB)
    assert not acl.allows("other", CAP_READ_JOB)
    assert acl.allows(None, "node:read")
    assert not acl.allows(None, "node:write")
    assert not acl.allows(None, "operator:read")


def test_acl_deny_overrides():
    a = parse_policy("allow", 'namespace "secret" { policy = "write" }')
    d = parse_policy("deny", 'namespace "secret" { policy = "deny" }')
    acl = ACL(policies=[a, d])
    assert not acl.allows("secret", CAP_READ_JOB)


def test_management_allows_all():
    acl = ACL(management=True)
    assert acl.allows("anything", CAP_SUBMIT_JOB)
    assert acl.allows(None, "operator:write")


def test_server_token_resolution():
    from nomad_tpu.core.server import Server, ServerConfig
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        s.enable_acl()
        boot = s.bootstrap_acl()
        assert boot.type == "management"
        with pytest.raises(RuntimeError):
            s.bootstrap_acl()          # only once
        acl = s.resolve_token(boot.secret_id)
        assert acl.management

        s.upsert_acl_policy("readonly", "",
                            'namespace "default" { policy = "read" }')
        tok = s.create_acl_token(name="reader", policies=["readonly"])
        racl = s.resolve_token(tok.secret_id)
        assert racl.allows("default", CAP_READ_JOB)
        assert not racl.allows("default", CAP_SUBMIT_JOB)

        assert s.resolve_token("bogus-secret") is None
        assert s.resolve_token("") is None      # no anonymous policy

        s.delete_acl_token(tok.accessor_id)
        assert s.resolve_token(tok.secret_id) is None
    finally:
        s.stop()


def test_http_acl_enforcement():
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api import ApiClient, ApiError

    a = Agent(AgentConfig(http_port=0, num_schedulers=1,
                          heartbeat_ttl=60.0))
    a.start()
    try:
        a.server.register_node(mock.node())
        boot = a.server.bootstrap_acl()
        a.server.enable_acl()

        anon = ApiClient(a.http_addr)
        with pytest.raises(ApiError) as e:
            anon.jobs.list()
        assert e.value.status == 403
        # status endpoints stay anonymous
        assert anon.system.leader() is not None

        mgmt = ApiClient(a.http_addr, token=boot.secret_id)
        assert mgmt.jobs.list() == []
        mgmt.acl.upsert_policy(
            "readonly", 'namespace "default" { policy = "read" }')
        resp = mgmt.acl.create_token(name="ro", policies=["readonly"])

        ro = ApiClient(a.http_addr, token=resp["SecretID"])
        assert ro.jobs.list() == []
        with pytest.raises(ApiError) as e:
            ro.jobs.register(mock.job())
        assert e.value.status == 403

        assert mgmt.acl.self_token()["AccessorID"] == boot.accessor_id
        assert len(mgmt.acl.tokens()) == 2
    finally:
        a.stop()


def test_env_flag_enables_acl(monkeypatch):
    """NOMAD_TPU_ACL=1 turns on deny-by-default enforcement at server
    construction, without an explicit enable_acl() call."""
    from nomad_tpu.core.server import Server, ServerConfig
    monkeypatch.setenv("NOMAD_TPU_ACL", "1")
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        assert s.acl_enabled
        assert s.resolve_token("") is None      # anonymous denied
    finally:
        s.stop()


def test_http_acl_every_mutating_route(monkeypatch):
    """Deny-by-default sweep under NOMAD_TPU_ACL=1: every mutating HTTP
    route 403s for an anonymous caller and passes the ACL layer for a
    management token; a capability-scoped token is confined to its
    namespace grants."""
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api import ApiClient, ApiError
    from nomad_tpu.api.codec import to_wire

    monkeypatch.setenv("NOMAD_TPU_ACL", "1")
    a = Agent(AgentConfig(http_port=0, num_schedulers=1,
                          heartbeat_ttl=60.0))
    a.start()
    try:
        node = mock.node()
        a.server.register_node(node)
        boot = a.server.bootstrap_acl()
        anon = ApiClient(a.http_addr)
        mgmt = ApiClient(a.http_addr, token=boot.secret_id)

        job = mock.job(id="acl-sweep-job")
        job.task_groups[0].count = 1
        wire_job = {"Job": to_wire(job)}
        sched_cfg = {"fair_dequeue_enabled": True}
        # (method, path, body) for every mutating route in agent/http.py;
        # bogus IDs are fine — the ACL check runs before the handler, so
        # anonymous must see 403 where management sees the handler's own
        # answer (2xx, or 404/400 for the bogus objects)
        routes = [
            ("PUT", "/v1/jobs", wire_job),
            ("PUT", f"/v1/job/{job.id}", wire_job),
            ("POST", "/v1/search", {"Prefix": "acl", "Context": "jobs"}),
            ("PUT", f"/v1/node/{node.id}/eligibility",
             {"Eligibility": "ineligible"}),
            ("PUT", f"/v1/node/{node.id}/drain",
             {"DrainSpec": {"Deadline": 1.0}}),
            ("POST", "/v1/allocation/bogus-id/stop", {}),
            ("PUT", "/v1/deployment/fail/bogus-id", {}),
            ("PUT", "/v1/operator/scheduler/configuration", sched_cfg),
            ("PUT", "/v1/acl/policy/sweep-policy",
             {"Rules": 'namespace "default" { policy = "read" }'}),
            ("PUT", "/v1/acl/token",
             {"Name": "sweep", "Policies": ["sweep-policy"]}),
            ("PUT", "/v1/namespaces", {"Name": "acl-sweep-ns"}),
            ("PUT", "/v1/quotas", {"name": "acl-sweep-quota",
                                   "allocs": 1}),
            ("PUT", "/v1/volume/csi/sweep-vol",
             {"Volume": {"ID": "sweep-vol", "PluginID": "bogus"}}),
            ("DELETE", "/v1/volume/csi/sweep-vol", None),
            ("DELETE", "/v1/service/web/bogus-reg-id", None),
            ("DELETE", "/v1/quota/acl-sweep-quota", None),
            ("DELETE", "/v1/namespace/acl-sweep-ns", None),
            ("DELETE", "/v1/acl/policy/sweep-policy", None),
            ("DELETE", f"/v1/job/{job.id}", None),
        ]
        for method, path, body in routes:
            with pytest.raises(ApiError) as e:
                anon._request(method, path, body=body)
            assert e.value.status == 403, (method, path, e.value.status)
        for method, path, body in routes:
            try:
                mgmt._request(method, path, body=body)
            except ApiError as e:
                assert e.status != 403, (method, path)
                assert e.status < 500, (method, path, str(e))

        # capability-scoped token: submit in "default" only
        mgmt.acl.upsert_policy("submitter", '''
namespace "default" { capabilities = ["submit-job", "read-job",
                                      "list-jobs"] }
''')
        tok = mgmt.acl.create_token(name="sub", policies=["submitter"])
        sub = ApiClient(a.http_addr, token=tok["SecretID"])
        j2 = mock.job(id="sub-job")
        j2.task_groups[0].count = 1
        assert sub.jobs.register(j2)["EvalID"]
        for method, path, body in [
                ("PUT", "/v1/namespaces", {"Name": "nope"}),
                ("PUT", "/v1/quotas", {"name": "nope", "allocs": 1}),
                ("PUT", "/v1/operator/scheduler/configuration", sched_cfg),
                ("PUT", f"/v1/node/{node.id}/drain",
                 {"DrainSpec": {"Deadline": 1.0}})]:
            with pytest.raises(ApiError) as e:
                sub._request(method, path, body=body)
            assert e.value.status == 403, (method, path)
        # and its namespace grant does not leak into other namespaces
        mgmt.namespaces.register("other-ns")
        j3 = mock.job(id="other-job")
        j3.namespace = "other-ns"
        with pytest.raises(ApiError) as e:
            sub.jobs.register(j3)
        assert e.value.status == 403
    finally:
        a.stop()
