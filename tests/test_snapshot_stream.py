"""Streamed resumable InstallSnapshot (dissertation §7) + fleet-scale
heartbeat batching.

Covers the chunk frame protocol end to end — in-order streaming,
resync on drop/duplicate/corruption, resume across leader changes and
follower restarts, whole-stream CRC gating persist-before-accept — plus
the install-ordering races against the apply loop and AppendEntries,
the snapshot-send backoff satellite, and the HeartbeatTracker wheel /
HeartbeatBatcher coalescing the 10K-agent soak rides on.
"""
import os
import threading
import time
import zlib

import pytest

from nomad_tpu import chaos, mock
from nomad_tpu.chaos import ChaosRegistry
from nomad_tpu.core.heartbeat import HeartbeatBatcher, HeartbeatTracker
from nomad_tpu.raft import (
    FileSnapshotStore,
    InMemTransport,
    LogStore,
    MessageType,
    NomadFSM,
    RaftConfig,
    RaftNode,
)
from nomad_tpu.raft.node import LEADER
from nomad_tpu.raft.snapshot import ChunkSink
from nomad_tpu.state import StateStore
from nomad_tpu.telemetry import global_metrics

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout=0.1)


def _poll(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _counter(name):
    for c in global_metrics.snapshot()["Counters"]:
        if c["Name"] == name:
            return c["Count"]
    return 0.0


class CountingFSM(NomadFSM):
    """Records every applied index so double-apply / gap assertions are
    direct instead of inferred from store contents."""

    def __init__(self, store):
        super().__init__(store)
        self.applied_indexes = []

    def apply(self, index, msg_type, payload):
        self.applied_indexes.append(index)
        super().apply(index, msg_type, payload)


def _source_state(n=6):
    """An FSM with `n` registered nodes, its snapshot blob, and the log
    payloads (1-based) that produce it entry by entry."""
    fsm = NomadFSM(StateStore())
    payloads = []
    for i in range(1, n + 1):
        p = {"node": mock.node()}
        fsm.apply(i, MessageType.NODE_REGISTER, p)
        payloads.append(p)
    return fsm, fsm.snapshot(), payloads


def _prefix_blob(payloads, k):
    """Snapshot blob of the SAME history truncated at entry `k`."""
    fsm = NomadFSM(StateStore())
    for i, p in enumerate(payloads[:k]):
        fsm.apply(i + 1, MessageType.NODE_REGISTER, p)
    return fsm.snapshot()


def _frames(blob, last_index, last_term, chunk, term=1, leader="ld",
            config=None):
    """The leader's frame sequence for `blob` (mirrors _send_snapshot)."""
    total = len(blob)
    out = []
    offset = 0
    while True:
        data = blob[offset:offset + chunk]
        done = offset + len(data) >= total
        f = {"term": term, "leader": leader, "last_index": last_index,
             "last_term": last_term, "offset": offset, "total": total,
             "crc32": zlib.crc32(data), "data": data, "done": done,
             "config": config}
        if done:
            f["stream_crc32"] = zlib.crc32(blob)
        out.append(f)
        offset += len(data)
        if done:
            return out


def _follower(tmp_path, name="b", fsm=None):
    """An unstarted follower: handlers are fully wired in __init__, so
    tests drive frame interleavings deterministically — no threads."""
    return RaftNode(name, ["a", name], InMemTransport(),
                    fsm or NomadFSM(StateStore()), config=FAST,
                    snapshots=FileSnapshotStore(str(tmp_path / name)))


# ------------------------------------------------------------ ChunkSink


def test_chunk_sink_append_tracks_offset_and_stream_crc(tmp_path):
    sink = ChunkSink(str(tmp_path), key=(5, 1, 9))
    blob = b"abc" + b"defg" + b"hi"
    for piece in (b"abc", b"defg", b"hi"):
        sink.append(piece)
    assert sink.offset == len(blob)
    assert sink.crc == zlib.crc32(blob)
    path = sink.path
    assert os.path.exists(path)
    assert sink.finish() == blob
    assert not os.path.exists(path)     # scratch file reclaimed


def test_chunk_sink_abort_unlinks_temp_file(tmp_path):
    sink = ChunkSink(str(tmp_path), key=(5, 1, 4))
    sink.append(b"part")
    sink.abort()
    assert not os.path.exists(sink.path)


def test_snapshot_store_reaps_orphaned_rx_files(tmp_path):
    d = tmp_path / "snaps"
    d.mkdir()
    orphan = d / ".snap-rx-dead"
    orphan.write_bytes(b"half a stream")
    store = FileSnapshotStore(str(d))
    assert not orphan.exists()
    # real snapshots survive the reap
    store.save(3, 1, b"blob")
    FileSnapshotStore(str(d))
    assert store.latest() == (3, 1, b"blob")


def test_snapshot_stream_window_bounds_buffered_bytes(tmp_path):
    """The outbound stream reads frames off the sidecar file in a
    sliding window: peak buffered bytes stay <= window regardless of
    blob size (the whole point of the flow-control satellite)."""
    store = FileSnapshotStore(str(tmp_path))
    blob = os.urandom(64 * 1024)
    store.save(9, 2, blob, config={"voters": ["a"]})
    window = 4096
    stream = store.open_stream(window)
    assert stream is not None
    assert (stream.index, stream.term, stream.total) == (9, 2, len(blob))
    assert stream.stream_crc == zlib.crc32(blob)
    assert stream.config == {"voters": ["a"]}
    got = bytearray()
    chunk = 1024
    off = 0
    while off < stream.total:
        data = stream.read_at(off, chunk)
        assert data, "short read before EOF"
        got += data
        off += len(data)
    assert bytes(got) == blob
    assert stream.peak_buffered <= window
    assert stream.total > window        # the bound actually bit
    # retransmit: an ack can regress the offset; the window re-seeks
    assert stream.read_at(0, chunk) == blob[:chunk]
    assert stream.peak_buffered <= window
    stream.close()


def test_snapshot_stream_materializes_sidecar_for_legacy_snapshot(tmp_path):
    """Pre-sidecar snapshots (seed-era data dirs) stream too: the first
    open_stream materializes the .blob sidecar from the record."""
    store = FileSnapshotStore(str(tmp_path))
    blob = b"legacy " * 500
    path = store.save(4, 1, blob)
    os.unlink(path + ".blob")           # simulate a pre-sidecar data dir
    stream = store.open_stream(256)
    assert stream is not None
    assert os.path.exists(path + ".blob")
    # windowed reads still reassemble the exact blob
    got = b"".join(stream.read_at(o, 256)
                   for o in range(0, stream.total, 256))
    assert got == blob
    stream.close()


def test_snapshot_reap_removes_sidecar_blobs(tmp_path):
    store = FileSnapshotStore(str(tmp_path), retain=1)
    p1 = store.save(1, 1, b"one")
    p2 = store.save(2, 1, b"two")
    assert not os.path.exists(p1) and not os.path.exists(p1 + ".blob")
    assert os.path.exists(p2 + ".blob")
    # an orphaned sidecar (crash between sidecar write and record
    # rename) is reaped at startup
    orphan = tmp_path / "snapshot-0000000001-000000000099.snap.blob"
    orphan.write_bytes(b"orphan")
    FileSnapshotStore(str(tmp_path))
    assert not orphan.exists()
    assert os.path.exists(p2 + ".blob")  # live sidecar survives


# ------------------------------------------------- chunk frame protocol


def test_chunk_stream_in_order_installs_and_persists(tmp_path):
    src, blob, _ = _source_state()
    b = _follower(tmp_path)
    frames = _frames(blob, 6, 1, chunk=max(1, len(blob) // 5))
    for f in frames:
        resp = b._on_install_snapshot(f)
        assert resp["success"]
        assert resp["offset"] == min(f["offset"] + len(f["data"]),
                                     len(blob))
    assert b._last_snapshot_index == 6
    assert b.last_applied == 6
    assert b._snap_rx is None
    # persist-before-accept: the durable record is already on disk
    assert b.snapshots.latest() == (6, 1, blob)
    assert {n.id for n in b.fsm.store.nodes()} == \
        {n.id for n in src.store.nodes()}


def test_chunk_stream_duplicate_and_future_frames_resync(tmp_path):
    _, blob, _ = _source_state()
    b = _follower(tmp_path)
    chunk = max(1, len(blob) // 4)
    frames = _frames(blob, 6, 1, chunk=chunk)
    assert b._on_install_snapshot(frames[0])["offset"] == chunk
    # duplicate: acked back to the real position, bytes not re-appended
    resp = b._on_install_snapshot(frames[0])
    assert resp["success"] and resp["offset"] == chunk
    assert b._snap_rx.offset == chunk
    # reordered/future frame: same resync ack, nothing appended
    resp = b._on_install_snapshot(frames[2])
    assert resp["success"] and resp["offset"] == chunk
    for f in frames[1:]:
        b._on_install_snapshot(f)
    assert b._last_snapshot_index == 6


def test_chunk_frame_crc_reject_asks_for_same_offset(tmp_path):
    _, blob, _ = _source_state()
    b = _follower(tmp_path)
    chunk = max(1, len(blob) // 4)
    frames = _frames(blob, 6, 1, chunk=chunk)
    b._on_install_snapshot(frames[0])
    corrupt = dict(frames[1])
    corrupt["crc32"] = frames[1]["crc32"] ^ 0xDEAD
    resp = b._on_install_snapshot(corrupt)
    # ack the unchanged offset: the leader re-sends this frame
    assert resp["success"] and resp["offset"] == chunk
    assert b._snap_rx.offset == chunk
    for f in frames[1:]:
        assert b._on_install_snapshot(f)["success"]
    assert b.snapshots.latest() == (6, 1, blob)


def test_superseding_stream_discards_partial_sink(tmp_path):
    _, blob_a, _ = _source_state(4)
    src_b, blob_b, _ = _source_state(8)
    b = _follower(tmp_path)
    frames_a = _frames(blob_a, 4, 1, chunk=max(1, len(blob_a) // 3))
    b._on_install_snapshot(frames_a[0])
    old_path = b._snap_rx.path
    # a NEWER snapshot stream starts: the stale partial is discarded
    for f in _frames(blob_b, 8, 2, chunk=max(1, len(blob_b) // 3),
                     term=2):
        assert b._on_install_snapshot(f)["success"]
    assert not os.path.exists(old_path)
    assert b._last_snapshot_index == 8
    assert {n.id for n in b.fsm.store.nodes()} == \
        {n.id for n in src_b.store.nodes()}


def test_restarted_follower_acks_zero_for_mid_stream_frame(tmp_path):
    _, blob, _ = _source_state()
    b = _follower(tmp_path)
    chunk = max(1, len(blob) // 4)
    frames = _frames(blob, 6, 1, chunk=chunk)
    b._on_install_snapshot(frames[0])
    b._on_install_snapshot(frames[1])
    # crash + restart: same data dir, fresh node, sink gone (and the
    # orphaned temp file reaped by the store constructor)
    b2 = RaftNode("b2", ["a", "b2"], InMemTransport(),
                  NomadFSM(StateStore()), config=FAST,
                  snapshots=FileSnapshotStore(str(tmp_path / "b")))
    resp = b2._on_install_snapshot(frames[2])
    # stale-offset ack: tell the leader to restart from byte zero
    assert resp["success"] and resp["offset"] == 0
    assert not any(f.startswith(".snap-rx-")
                   for f in os.listdir(str(tmp_path / "b")))
    for f in frames:
        assert b2._on_install_snapshot(f)["success"]
    assert b2._last_snapshot_index == 6


def test_whole_stream_crc_mismatch_restarts_from_zero(tmp_path):
    _, blob, _ = _source_state()
    b = _follower(tmp_path)
    frames = _frames(blob, 6, 1, chunk=max(1, len(blob) // 3))
    bad_done = dict(frames[-1])
    bad_done["stream_crc32"] = frames[-1]["stream_crc32"] ^ 1
    for f in frames[:-1]:
        b._on_install_snapshot(f)
    resp = b._on_install_snapshot(bad_done)
    # assembled bytes are not the leader's blob: discard, ack zero
    assert resp["success"] and resp["offset"] == 0
    assert b._last_snapshot_index == 0
    assert b._snap_rx is None
    assert b.snapshots.latest() is None
    # the re-stream from zero succeeds
    for f in frames:
        assert b._on_install_snapshot(f)["success"]
    assert b._last_snapshot_index == 6


def test_new_leader_resumes_same_snapshot_from_acked_offset(tmp_path):
    """The sink survives a leader change: a new leader streaming the
    SAME snapshot identity starts its probe at zero and is bounced
    straight to the dead leader's high-water mark."""
    _, blob, _ = _source_state()
    b = _follower(tmp_path)
    chunk = max(1, len(blob) // 8)
    frames = _frames(blob, 6, 1, chunk=chunk)
    for f in frames[:3]:
        b._on_install_snapshot(f)
    resume_at = b._snap_rx.offset
    assert 0 < resume_at < len(blob)
    # new leader, higher term, same (last_index, last_term, total)
    frames2 = _frames(blob, 6, 1, chunk=chunk, term=2, leader="ld2")
    resp = b._on_install_snapshot(frames2[0])
    assert resp["success"] and resp["offset"] == resume_at
    sent = 0
    for f in frames2:
        if f["offset"] < resume_at:
            continue              # leader jumps to the acked offset
        sent += len(f["data"])
        assert b._on_install_snapshot(f)["success"]
    assert sent < len(blob)       # resumed, not restarted
    assert b._last_snapshot_index == 6
    assert b.snapshots.latest() == (6, 1, blob)


# -------------------------------------------- install-ordering races


def test_install_then_apply_loop_continues_past_snapshot(tmp_path):
    """Snapshot at 6 lands while entries 1..10 sit committed-unapplied:
    the apply loop must resume at 7 — no entry below the snapshot
    re-applies onto the restored state, no entry above it is lost."""
    src, _, payloads = _source_state(10)
    blob6 = _prefix_blob(payloads, 6)
    fsm = CountingFSM(StateStore())
    b = _follower(tmp_path, fsm=fsm)
    b._on_append_entries({
        "term": 1, "leader": "a", "prev_log_index": 0, "prev_log_term": 0,
        "entries": [(i + 1, 1, MessageType.NODE_REGISTER, p)
                    for i, p in enumerate(payloads)],
        "leader_commit": 10})
    assert b.commit_index == 10 and b.last_applied == 0
    resp = b._on_install_snapshot({
        "term": 1, "leader": "a", "last_index": 6, "last_term": 1,
        "data": blob6, "config": None})
    assert resp["success"]
    assert b.last_applied == 6
    assert b.log.first_index == 7          # prefix compacted
    b.start()
    try:
        assert _poll(lambda: b.last_applied == 10)
    finally:
        b.stop()
    # exactly 7..10 went through fsm.apply; 1..6 came from the blob
    assert fsm.applied_indexes == [7, 8, 9, 10]
    assert {n.id for n in b.fsm.store.nodes()} == \
        {n.id for n in src.store.nodes()}


def test_apply_loop_skips_compacted_gap(tmp_path):
    """The _run_apply compacted-skip guard: entries below the snapshot
    index that are no longer in the log advance last_applied without
    touching the FSM."""
    blob6 = _source_state(6)[1]
    fsm = CountingFSM(StateStore())
    b = _follower(tmp_path, fsm=fsm)   # empty log: 1..6 exist only in blob
    with b._lock:
        b.fsm.restore(blob6)
        fsm.applied_indexes.clear()
        b._last_snapshot_index = 6
        b._last_snap_term = 1
        b.commit_index = 6
        # last_applied deliberately behind the snapshot: the loop must
        # walk 1..6 as compacted skips, never as FSM applies
        b.last_applied = 0
    b.start()
    try:
        assert _poll(lambda: b.last_applied == 6)
    finally:
        b.stop()
    assert fsm.applied_indexes == []


def test_done_frame_after_append_entries_does_not_rewind_fsm(tmp_path):
    """AppendEntries covered the stream's whole range while the chunk
    stream was in flight: the late `done` frame must not restore the
    older blob over state that already includes it (entries 7..10 would
    never re-apply — a silent divergence)."""
    src, _, payloads = _source_state(10)
    blob6 = _prefix_blob(payloads, 6)
    fsm = CountingFSM(StateStore())
    b = _follower(tmp_path, fsm=fsm)
    chunk = max(1, len(blob6) // 4)
    frames = _frames(blob6, 6, 1, chunk=chunk)
    for f in frames[:-1]:
        assert b._on_install_snapshot(f)["success"]
    # the leader catches the follower up over AppendEntries meanwhile
    b._on_append_entries({
        "term": 1, "leader": "a", "prev_log_index": 0, "prev_log_term": 0,
        "entries": [(i + 1, 1, MessageType.NODE_REGISTER, p)
                    for i, p in enumerate(payloads)],
        "leader_commit": 10})
    # drive the apply loop to completion deterministically
    b.start()
    try:
        assert _poll(lambda: b.last_applied == 10)
    finally:
        b.stop()
    assert len(b.fsm.store.nodes()) == 10
    # ... and only now does the stream's done frame land
    resp = b._on_install_snapshot(frames[-1])
    assert resp["success"] and resp["offset"] == len(blob6)
    # state retained (10 nodes), log prefix still compacted
    assert {n.id for n in b.fsm.store.nodes()} == \
        {n.id for n in src.store.nodes()}
    assert b.last_applied == 10
    assert b._last_snapshot_index == 6
    assert b.log.first_index == 7
    assert fsm.applied_indexes == list(range(1, 11))   # each exactly once


# ------------------------------------- send-side backoff (satellite 2)


def test_snapshot_send_failure_counter_and_bounded_backoff(tmp_path):
    a = _follower(tmp_path, name="a")
    before = _counter("raft.snapshot.send_fail")
    for _ in range(10):
        a._note_snap_failure("p")
    assert _counter("raft.snapshot.send_fail") == before + 10
    fails, until = a._snap_backoff["p"]
    assert fails == 6                           # capped
    assert 0 < until - time.monotonic() <= 2.0  # bounded delay
    # the replication tick honors the backoff window: no stream spawned
    with a._lock:
        a._spawn_snapshot_stream("p")
    assert "p" not in a._snap_streams


def test_persist_failure_rejects_install_and_arms_backoff(tmp_path):
    """A follower that cannot persist must reject (persist-before-
    accept), and the leader must back off instead of re-streaming the
    full blob every tick."""
    _, blob, _ = _source_state()
    tr = InMemTransport()
    a = RaftNode("a", ["a", "b"], tr, NomadFSM(StateStore()), config=FAST,
                 snapshots=FileSnapshotStore(str(tmp_path / "a")))
    b = RaftNode("b", ["a", "b"], tr, NomadFSM(StateStore()), config=FAST,
                 snapshots=FileSnapshotStore(str(tmp_path / "b")))
    a.snapshots.save(6, 1, blob)
    with a._lock:
        a.state = LEADER
        a.term = 1
        a._last_snapshot_index = 6
        a._last_snap_term = 1

    def broken_save(*args, **kw):
        raise OSError("disk full")

    b.snapshots.save = broken_save
    before = _counter("raft.snapshot.send_fail")
    a._send_snapshot("b")       # synchronous: the whole chunk loop
    assert b._last_snapshot_index == 0          # install rejected
    assert _counter("raft.snapshot.send_fail") == before + 1
    assert a._snap_backoff["b"][0] >= 1
    # healthy retry after the window: restore save, clear backoff
    b.snapshots.save = FileSnapshotStore(str(tmp_path / "b")).save
    with a._lock:
        a._snap_backoff.pop("b")
    a._send_snapshot("b")
    assert b._last_snapshot_index == 6
    assert "b" not in a._snap_backoff           # cleared on success
    assert a._next_index["b"] == 7 and a._match_index["b"] == 6


def test_chunk_drop_chaos_stream_resyncs_to_completion(tmp_path):
    _, blob, _ = _source_state()
    tr = InMemTransport()
    a = RaftNode("a", ["a", "b"], tr, NomadFSM(StateStore()), config=FAST,
                 snapshots=FileSnapshotStore(str(tmp_path / "a")))
    b = RaftNode("b", ["a", "b"], tr, NomadFSM(StateStore()), config=FAST,
                 snapshots=FileSnapshotStore(str(tmp_path / "b")))
    a.snapshots.save(6, 1, blob)
    with a._lock:
        a.state = LEADER
        a.term = 1
    os.environ["NOMAD_TPU_SNAP_CHUNK"] = str(max(1, len(blob) // 16))
    reg = ChaosRegistry.from_spec("seed=7;snapshot.chunk_drop=0.3")
    reg.arm(now=0.0)
    chaos.install(reg)
    try:
        a._send_snapshot("b")
    finally:
        chaos.uninstall()
        del os.environ["NOMAD_TPU_SNAP_CHUNK"]
    assert b._last_snapshot_index == 6
    assert b.snapshots.latest() == (6, 1, blob)


def test_last_snap_term_is_instance_state_not_class_default():
    # the dead class attribute is gone; the live field is per-instance
    assert "_last_snap_term" not in vars(RaftNode)
    n = RaftNode("solo", ["solo"], InMemTransport(),
                 NomadFSM(StateStore()), config=FAST)
    assert n._last_snap_term == 0


# --------------------------------------------- blank join, end to end


def test_blank_join_catches_up_via_chunked_stream(tmp_path):
    """A joiner with no log or snapshot must catch up through the
    chunked stream alone: leader compacted its log, so AppendEntries
    cannot reach index 1."""
    os.environ["NOMAD_TPU_SNAP_CHUNK"] = "512"
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = [RaftNode(nm, names, tr, NomadFSM(StateStore()), config=FAST,
                      log_store=LogStore(str(tmp_path / f"{nm}.log")),
                      snapshots=FileSnapshotStore(str(tmp_path / nm)))
             for nm in names]
    joiner = RaftNode("d", [], tr, NomadFSM(StateStore()), config=FAST,
                      log_store=LogStore(str(tmp_path / "d.log")),
                      snapshots=FileSnapshotStore(str(tmp_path / "d")),
                      join=True)
    for n in nodes:
        n.start()
    joiner.start()
    try:
        assert _poll(lambda: any(n.is_leader for n in nodes), timeout=5)
        leader = next(n for n in nodes if n.is_leader)
        for _ in range(30):
            leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        leader.force_snapshot()
        assert leader.log.first_index > 30      # prefix gone
        leader.add_server("d")
        assert _poll(lambda: joiner._last_snapshot_index >= 30,
                     timeout=10), "joiner never installed the stream"
        assert _poll(lambda: len(joiner.fsm.store.nodes()) == 30,
                     timeout=5)
        assert {n.id for n in joiner.fsm.store.nodes()} == \
            {n.id for n in leader.fsm.store.nodes()}
        # membership arrived with the snapshot's config
        assert set(joiner._voters) == {"a", "b", "c"}
    finally:
        del os.environ["NOMAD_TPU_SNAP_CHUNK"]
        for n in nodes + [joiner]:
            n.stop()


# ------------------------------------ heartbeat fleet path (tentpole c)


class _StubServer:
    """Just enough Server for the tracker/batcher: a real StateStore
    plus recorders for the write paths."""

    class _Cfg:
        heartbeat_ttl = 10.0

    def __init__(self):
        self.store = StateStore()
        self.config = self._Cfg()
        self.status_writes = []
        self.applies = []
        self.evals_for = []
        self.heartbeat_batch = None

    def update_node_status(self, node_id, status):
        self.status_writes.append((node_id, status))

    def apply(self, msg_type, payload):
        self.applies.append((msg_type, payload))

    def create_node_evals(self, node_id):
        self.evals_for.append(node_id)


def _register(server, node_id="n1"):
    n = mock.node()
    n.id = node_id
    NomadFSM(server.store).apply(1, MessageType.NODE_REGISTER, {"node": n})
    return n


def test_heartbeat_tracker_restart_clears_stale_deadlines():
    """Satellite 1: deadlines armed under a previous tenure must not
    survive start() — a leftover TTL would expire a live node out of a
    tenure that never tracked it."""
    srv = _StubServer()
    _register(srv, "n1")
    tracker = HeartbeatTracker(srv, ttl=0.15, tick=0.02)
    tracker.heartbeat("n1")                 # armed pre-tenure
    assert tracker.tracked() == 1
    tracker.start()
    try:
        assert tracker.tracked() == 0       # wiped on start
        time.sleep(0.4)                     # well past the stale TTL
        assert srv.status_writes == []      # stale deadline never fired
    finally:
        tracker.stop()


def test_heartbeat_wheel_expiry_rearm_untrack():
    srv = _StubServer()
    for nid in ("n1", "n2"):
        _register(srv, nid)
    tracker = HeartbeatTracker(srv, ttl=0.15, tick=0.02)
    tracker.start()
    try:
        tracker.heartbeat("n1")
        tracker.heartbeat("n2")
        tracker.untrack("n2")               # deregistered: never expires
        # keep n1 alive across several TTL windows: re-arm wins
        for _ in range(6):
            time.sleep(0.05)
            tracker.heartbeat("n1")
        assert srv.status_writes == []
        assert _poll(lambda: ("n1", "down") in srv.status_writes,
                     timeout=2.0), "n1 TTL never expired"
        assert all(nid != "n2" for nid, _ in srv.status_writes)
        assert tracker.tracked() == 0
    finally:
        tracker.stop()


def test_heartbeat_batcher_coalesces_one_entry_per_flush():
    srv = _StubServer()
    b = HeartbeatBatcher(srv, interval=3600.0)   # manual flush only
    b.note("n1", "down")
    b.stamp("n2", "ready")
    b.stamp("n2", "ready")      # rate-limited to one per half-TTL
    b.stamp("n1", "ready")      # transition already pending: kept as-is
    before = _counter("heartbeat.batch_flush")
    b.flush()
    assert len(srv.applies) == 1             # ONE raft entry for the batch
    msg_type, payload = srv.applies[0]
    assert msg_type == MessageType.NODE_HEARTBEAT_BATCH
    assert {u["node_id"]: u["status"] for u in payload["updates"]} == \
        {"n1": "down", "n2": "ready"}
    assert all(u["updated_at"] > 0 for u in payload["updates"])
    assert srv.evals_for == ["n1"]           # evals only for transitions
    assert _counter("heartbeat.batch_flush") == before + 1
    b.flush()                                # nothing pending: no entry
    assert len(srv.applies) == 1


def test_heartbeat_batch_stall_chaos_defers_the_flush():
    srv = _StubServer()
    b = HeartbeatBatcher(srv, interval=3600.0)
    b.note("n1", "down")
    reg = ChaosRegistry.from_spec("seed=1;heartbeat.batch_stall=1.0")
    reg.arm(now=0.0)
    chaos.install(reg)
    try:
        b.flush()
        assert srv.applies == []             # stalled: batch keeps pending
    finally:
        chaos.uninstall()
    b.flush()
    assert len(srv.applies) == 1             # next tick carries the batch
    assert srv.applies[0][1]["updates"][0]["node_id"] == "n1"


def test_heartbeat_batcher_cap_forces_flush_through_stall_chaos():
    """Satellite: the pending table is bounded.  With heartbeat.batch_stall
    chaos skipping every regular flush, a churn storm must hit the cap,
    force a flush (which BYPASSES the stall-skip) and drain — memory
    stays O(cap), never O(storm)."""
    srv = _StubServer()
    b = HeartbeatBatcher(srv, interval=0.01)
    b.pending_max = 16
    reg = ChaosRegistry.from_spec("seed=1;heartbeat.batch_stall=1.0")
    reg.arm(now=0.0)
    chaos.install(reg)
    try:
        b.start()
        try:
            peak = 0
            for i in range(200):
                b.note(f"n{i}", "down")
                with b._lock:
                    peak = max(peak, len(b._pending))
                if i % 16 == 0:
                    time.sleep(0.02)        # let forced flushes run
            # the sub-cap tail stays pending under stall chaos (by
            # design — only cap-hit forces a drain); flush it by hand
            b.flush(force=True)
            assert _poll(lambda: sum(
                len(p["updates"]) for _, p in srv.applies) == 200,
                timeout=5.0), "forced flushes never drained the storm"
            # the cap held: the table never grew meaningfully past it
            # (writers may land between cap-hit and the forced drain)
            assert peak <= 2 * b.pending_max
            assert _counter("heartbeat.batch_forced") > 0
        finally:
            b.stop()
    finally:
        chaos.uninstall()


def test_fsm_applies_heartbeat_batch_in_one_store_write():
    store = StateStore()
    fsm = NomadFSM(store)
    nodes = [mock.node() for _ in range(3)]
    for i, n in enumerate(nodes):
        fsm.apply(i + 1, MessageType.NODE_REGISTER, {"node": n})
    ts = time.time()
    fsm.apply(10, MessageType.NODE_HEARTBEAT_BATCH, {"updates": [
        {"node_id": nodes[0].id, "status": "down", "updated_at": ts},
        {"node_id": nodes[1].id, "status": "disconnected",
         "updated_at": ts},
        {"node_id": "ghost", "status": "down", "updated_at": ts},
    ]})
    assert store.node_by_id(nodes[0].id).status == "down"
    assert store.node_by_id(nodes[1].id).status == "disconnected"
    assert store.node_by_id(nodes[2].id).status != "down"
    assert store.latest_index == 10          # unknown ids are ignored


def test_tracker_expiry_rides_batcher_when_running():
    """At fleet scale a churn wave must coalesce: expiries go through
    HeartbeatBatcher.note, not one update_node_status entry each."""
    srv = _StubServer()
    _register(srv, "n1")
    srv.heartbeat_batch = HeartbeatBatcher(srv, interval=3600.0)
    srv.heartbeat_batch.start()
    tracker = HeartbeatTracker(srv, ttl=0.1, tick=0.02)
    tracker.start()
    try:
        tracker.heartbeat("n1")
        assert _poll(
            lambda: "n1" in srv.heartbeat_batch._pending
            or any(u["node_id"] == "n1"
                   for _, p in srv.applies for u in p["updates"]),
            timeout=2.0)
        assert srv.status_writes == []       # never the per-node path
    finally:
        tracker.stop()
        srv.heartbeat_batch.stop()
