"""The 2-D ('node_shard','wave') serving mesh (PR 16): device-count
factorization, mesh-identity cache keys, donated usage-basis carries,
upload/compute overlap chaining, and laned-kernel placement parity with
the single-device engine."""
from concurrent.futures import Future

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.parallel.engine import PlacementEngine, _BulkRequest
from nomad_tpu.scheduler.stack import DenseStack


# ------------------------------------------------------------ mesh shapes

def test_wave_mesh_shape_factorizations(monkeypatch):
    from nomad_tpu.parallel import wave_mesh_shape
    monkeypatch.delenv("NOMAD_TPU_WAVE_SHARDS", raising=False)
    assert wave_mesh_shape(1) == (1, 1)
    assert wave_mesh_shape(2) == (2, 1)
    assert wave_mesh_shape(4) == (2, 2)
    assert wave_mesh_shape(8) == (4, 2)
    assert wave_mesh_shape(16) == (4, 4)
    with pytest.raises(ValueError):
        wave_mesh_shape(0)


def test_wave_mesh_shape_env_override(monkeypatch):
    from nomad_tpu.parallel import wave_mesh_shape
    monkeypatch.setenv("NOMAD_TPU_WAVE_SHARDS", "4")
    assert wave_mesh_shape(8) == (2, 4)
    # a wave extent that does not divide the device count falls back to
    # 1 rather than dropping devices from the mesh
    monkeypatch.setenv("NOMAD_TPU_WAVE_SHARDS", "3")
    assert wave_mesh_shape(8) == (8, 1)
    monkeypatch.setenv("NOMAD_TPU_WAVE_SHARDS", "1")
    assert wave_mesh_shape(8) == (8, 1)
    # explicit argument beats the env knob
    monkeypatch.setenv("NOMAD_TPU_WAVE_SHARDS", "4")
    assert wave_mesh_shape(8, wave_shards=2) == (4, 2)


def test_make_mesh_axis_names(monkeypatch):
    from nomad_tpu.parallel import make_mesh
    from nomad_tpu.parallel.sharded import make_serving_mesh, mesh_key
    monkeypatch.delenv("NOMAD_TPU_WAVE_SHARDS", raising=False)
    m = make_mesh()
    assert tuple(m.axis_names) == ("node_shard", "wave")
    assert dict(m.shape) == {"node_shard": 4, "wave": 2}
    # the serving mesh uses the same factorization -> same identity
    sm = make_serving_mesh()
    assert mesh_key(sm) == mesh_key(m)
    sm1 = make_serving_mesh(wave_shards=1)
    assert dict(sm1.shape) == {"node_shard": 8, "wave": 1}
    assert mesh_key(sm1) != mesh_key(sm)
    # explicit factor pair
    m2 = make_mesh(n_wave_shards=2, n_node_shards=4)
    assert dict(m2.shape) == {"node_shard": 4, "wave": 2}


# ------------------------------------------------------------- fixtures

def _world_cm(n_nodes, seed=3):
    rng = np.random.default_rng(seed)
    cm = ClusterMatrix(initial_rows=n_nodes)
    for i in range(n_nodes):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 4}"
        n.node_resources.cpu.cpu_shares = int(rng.integers(3000, 9000))
        cm.upsert_node(n)
    return cm


def _group_fields(cm, count):
    bj = mock.batch_job()
    btg = bj.task_groups[0]
    btg.count = count
    btg.ephemeral_disk.size_mb = 0
    bg = DenseStack(cm).compile_group(bj, btg)
    return bg


def _bulk_req(cm, bg, count, wave_key, deltas=None, seed=None):
    N = cm.n_rows
    rng = np.random.default_rng(seed)
    feasible = bg.feasible.copy()
    if seed is not None:                  # random infeasible holes
        feasible &= rng.random(N) > 0.1
    return _BulkRequest(
        cm=cm, feasible=feasible,
        affinity=bg.affinity.astype(np.float32),
        has_affinity=bool(bg.has_affinity), desired=int(count),
        penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
        demand=bg.demand.astype(np.float32), count=int(count),
        deltas=list(deltas or []), spread_algorithm=False,
        future=Future(), wave_key=wave_key)


def _results(reqs):
    out = []
    for r in reqs:
        assign, placed, n_eval, n_exh, scores, ticket = \
            r.future.result(timeout=120)
        out.append((np.asarray(assign).copy(), int(placed),
                    np.asarray(scores).copy(), ticket))
    return out


# ------------------------------------------------- sharded cache identity

def test_bulk_kernel_cache_survives_mesh_recreation(monkeypatch):
    """The sharded kernel cache keys on mesh IDENTITY (axis layout +
    device ids), not the Mesh object: a re-created serving mesh must hit
    the compiled entries of its predecessor (zero recompiles), while a
    RESHAPED mesh (different wave extent) must miss."""
    from nomad_tpu.parallel import sharded as sh

    cm = _world_cm(256)
    N = cm.n_rows
    bg = _group_fields(cm, 6)

    def run_once():
        eng = PlacementEngine(shard_min_nodes=8)
        try:
            assert eng._mesh_for(N) is not None
            _a, p, *_rest, t = eng.place_bulk(
                cm, feasible=bg.feasible, affinity=bg.affinity,
                has_affinity=bg.has_affinity, desired=6,
                penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
                demand=bg.demand, count=6, wave_key="ns")
            assert p == 6
            eng.complete(t)
        finally:
            eng.stop()

    def n_bulk_entries():
        return sum(1 for k in sh._SERVING_FN_CACHE
                   if isinstance(k, tuple) and k and k[0] == "bulk")

    run_once()
    before = n_bulk_entries()
    assert before >= 1
    # fresh engine -> fresh Mesh object, same devices/axes -> cache HIT
    run_once()
    assert n_bulk_entries() == before
    # reshaped mesh (wave extent pinned to 1) -> different mesh_key -> MISS
    monkeypatch.setenv("NOMAD_TPU_WAVE_SHARDS", "1")
    run_once()
    assert n_bulk_entries() > before


# ------------------------------------------------------- donated carries

def test_donated_carry_invalidates_loaned_buffer():
    """donate_argnums actually donates: the loaned device basis buffer
    is dead after the kernel runs, the adopted carry is bitwise equal to
    the host snapshot (exact_out reconstruction), and steady state ships
    ZERO basis bytes (no scatters, no re-uploads)."""
    import jax

    cm = _world_cm(64)
    N = cm.n_rows
    bg = _group_fields(cm, 6)
    eng = PlacementEngine()            # N=64 < shard_min -> mesh off
    try:
        assert eng._mesh_for(N) is None
        assert eng.donate                     # NOMAD_TPU_DONATE default
        world = eng._world(cm, N, None)
        loaned = []
        orig = world.loan_basis

        def spy():
            b = orig()
            loaned.append(b)
            return b

        world.loan_basis = spy
        tickets = []
        for i in range(3):
            _a, p, *_rest, t = eng.place_bulk(
                cm, feasible=bg.feasible, affinity=bg.affinity,
                has_affinity=bg.has_affinity, desired=6,
                penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
                demand=bg.demand, count=6, wave_key=f"ns-{i}")
            assert p == 6
            tickets.append(t)
        assert len(loaned) == 3
        assert all(b is not None and b.is_deleted() for b in loaned)
        assert eng.stats["donated_carries"] == 3
        ws = world.stats
        assert ws["basis_loans"] == 3 and ws["basis_adopts"] == 3
        # zero steady-state basis traffic: one epoch upload, then the
        # donated carry IS the next dispatch's basis
        assert ws["full_uploads"] == 1
        assert ws["rows_scattered"] == 0
        assert ws["steady_reuploads"] == 0
        # the adopted device carry is bitwise the host-side basis
        cap_dev, basis_dev = world.device_arrays()
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(basis_dev)),
            eng._basis_for(cm)[:N])
        for t in tickets:
            eng.complete(t)
    finally:
        eng.stop()


def test_donation_disabled_fallback():
    """NOMAD_TPU_DONATE=0 path: the plain (non-donating) kernel places
    identically and never loans the basis."""
    cm = _world_cm(64, seed=5)
    N = cm.n_rows
    bg = _group_fields(cm, 5)

    def run(donate):
        eng = PlacementEngine()
        eng.donate = donate
        eng.overlap = eng.overlap and donate
        try:
            _a, p, *_rest, t = eng.place_bulk(
                cm, feasible=bg.feasible, affinity=bg.affinity,
                has_affinity=bg.has_affinity, desired=5,
                penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
                demand=bg.demand, count=5)
            stats = dict(eng.stats)
            wstats = eng.world_stats()
            eng.complete(t)
            return np.asarray(_a).copy(), p, stats, wstats
        finally:
            eng.stop()

    a1, p1, s1, w1 = run(donate=True)
    a2, p2, s2, w2 = run(donate=False)
    assert p1 == p2 == 5
    np.testing.assert_array_equal(a1, a2)
    assert s1["donated_carries"] == 1 and s2["donated_carries"] == 0
    assert w2["basis_loans"] == 0 and w2["basis_adopts"] == 0


# ------------------------------------------------ upload/compute overlap

@pytest.mark.parametrize("shard_min", [8, 1 << 30],
                         ids=["sharded", "single_device"])
def test_overlap_chained_matches_drained(shard_min):
    """A part dispatched while the previous one is still in flight
    (chained behind the donated carry) places exactly what a
    drain-first barrier would: the carry already holds the in-flight
    placements, bitwise."""
    cm = _world_cm(256, seed=11)
    N = cm.n_rows
    bg = _group_fields(cm, 7)

    def run(overlap):
        eng = PlacementEngine(shard_min_nodes=shard_min)
        eng.overlap = eng.overlap and overlap
        try:
            parts = [[_bulk_req(cm, bg, 7, f"ns-{j}-{i}") for j in range(2)]
                     for i in range(3)]
            # direct dispatch: each part goes out while the previous is
            # still pending, deterministically exercising the chain
            for part in parts:
                eng._dispatch(part)
            eng._drain_pending()
            res = _results([r for part in parts for r in part])
            stats = dict(eng.stats)
            for *_r, t in res:
                eng.complete(t)
            return res, stats
        finally:
            eng.stop()

    chained, s_chained = run(overlap=True)
    drained, s_drained = run(overlap=False)
    assert s_chained["overlap_chained"] >= 1
    assert s_drained["overlap_chained"] == 0
    for (a1, p1, sc1, _t1), (a2, p2, sc2, _t2) in zip(chained, drained):
        assert p1 == p2 == 7
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_allclose(sc1, sc2, rtol=1e-5)


def test_overlap_windows_recorded():
    """The engine records (t0, t1) host upload/dispatch windows and
    device windows; interval_overlap_s over them is the BENCH
    pipeline_overlap_s metric."""
    from nomad_tpu.parallel.stage_probe import interval_overlap_s

    cm = _world_cm(64, seed=2)
    N = cm.n_rows
    bg = _group_fields(cm, 4)
    eng = PlacementEngine()
    try:
        for i in range(2):
            *_r, t = eng.place_bulk(
                cm, feasible=bg.feasible, affinity=bg.affinity,
                has_affinity=bg.has_affinity, desired=4,
                penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
                demand=bg.demand, count=4, wave_key=f"ns-{i}")
            eng.complete(t)
        assert len(eng.upload_windows) >= 2
        assert len(eng.device_windows) >= 2
        assert all(t1 >= t0 for t0, t1 in eng.upload_windows)
        assert interval_overlap_s(list(eng.upload_windows),
                                  list(eng.device_windows)) >= 0.0
    finally:
        eng.stop()


# ------------------------------------------------------ laned parity

@pytest.mark.parametrize("bucket", ["sparse", "dense"])
def test_laned_sharded_parity_with_single_device(bucket):
    """The 2-D laned dispatch — distinct wave_keys scored concurrently
    across the mesh's wave columns — places each lane exactly as the
    single-device engine chains that lane in isolation (lanes are blind
    within a dispatch by construction), covering the sparse (count <=
    SPARSE_CAP) and dense output buckets plus preemption delta rows."""
    cm = _world_cm(256, seed=17)
    N = cm.n_rows
    counts = [5, 9, 12, 7] if bucket == "sparse" else [140, 6, 130, 9]
    bgs = {c: _group_fields(cm, c) for c in set(counts)}
    # preemption rows on one request: usage freed on specific rows
    free = [(3, -bgs[counts[1]].demand.astype(np.float32) * 2.0),
            (17, -bgs[counts[1]].demand.astype(np.float32))]

    def build_reqs():
        reqs = []
        for i, c in enumerate(counts):
            reqs.append(_bulk_req(cm, bgs[c], c, wave_key=f"ns-{i % 3}",
                                  deltas=free if i == 1 else None,
                                  seed=100 + i))
        return reqs

    eng = PlacementEngine(shard_min_nodes=8)
    try:
        mesh = eng._mesh_for(N)
        assert mesh is not None and mesh.shape.get("wave", 1) == 2
        reqs = build_reqs()
        eng._dispatch(reqs)
        eng._drain_pending()
        sharded_res = _results(reqs)
        assert eng.stats["wave_lanes"] == 2
        assert eng.stats["lane_evals"] == len(counts)
        for *_r, t in sharded_res:
            eng.complete(t)
    finally:
        eng.stop()

    # reference: each lane in isolation through the single-device engine
    # (chained within the lane, blind to the other lane)
    bins, mapping = PlacementEngine._lane_bins(build_reqs(), 2)
    ref_by_slot = {}
    for lane, lane_reqs in enumerate(bins):
        if not lane_reqs:
            continue
        ref = PlacementEngine(shard_min_nodes=1 << 30)
        try:
            ref._dispatch(lane_reqs)
            ref._drain_pending()
            for slot, (a, p, sc, t) in enumerate(_results(lane_reqs)):
                ref_by_slot[(lane, slot)] = (a, p, sc)
                ref.complete(t)
        finally:
            ref.stop()

    for i, (a, p, sc, _t) in enumerate(sharded_res):
        ra, rp, rsc = ref_by_slot[mapping[i]]
        assert p == rp == counts[i]
        np.testing.assert_array_equal(a, ra)
        # the sparse output bucket materializes scores for assigned rows
        # only (-inf elsewhere); compare where a placement landed
        rows = a > 0
        np.testing.assert_allclose(sc[rows], rsc[rows], rtol=1e-5)


def test_single_wave_key_matches_pre_laned_semantics():
    """One distinct wave_key degenerates to a single active lane: the
    2-D dispatch chains ALL evals sequentially, identical to the
    single-device fused dispatch."""
    cm = _world_cm(256, seed=23)
    bgs = [_group_fields(cm, c) for c in (6, 6, 6)]

    def run(shard_min):
        eng = PlacementEngine(shard_min_nodes=shard_min)
        try:
            reqs = [_bulk_req(cm, bg, 6, wave_key="only") for bg in bgs]
            eng._dispatch(reqs)
            eng._drain_pending()
            res = _results(reqs)
            for *_r, t in res:
                eng.complete(t)
            return res
        finally:
            eng.stop()

    sharded = run(8)
    single = run(1 << 30)
    for (a1, p1, sc1, _), (a2, p2, sc2, _) in zip(sharded, single):
        assert p1 == p2 == 6
        np.testing.assert_array_equal(a1, a2)
        rows = a1 > 0           # sparse bucket: ref scores only at rows
        np.testing.assert_allclose(sc1[rows], sc2[rows], rtol=1e-5)
