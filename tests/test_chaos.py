"""Seeded chaos layer + failure-recovery hardening.

Unit legs pin the contracts one at a time: spec grammar, zero behavior
change when disabled, seeded determinism, the native circuit breaker,
exactly-once plan commit under injected applier crashes, plan-id replay
dedup, broker lease-expiry redelivery, bounded worker nack retry, the
heartbeat invalidate retry path, and ApiClient GET retries.

The soak leg boots a real in-process 3-server cluster under a fixed-seed
fault schedule (drops, delays, instant lease expiry, applier crashes,
partitions) plus a seeded isolate/heal schedule, then turns chaos off and
asserts the control plane converges: full placement, every eval terminal,
no outstanding leases.
"""
import os
import random
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from nomad_tpu import chaos, mock, native
from nomad_tpu.api.client import ApiClient, ApiError
from nomad_tpu.chaos import ChaosError, ChaosRegistry
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.broker import EvalBroker
from nomad_tpu.core.heartbeat import HeartbeatTracker
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.core.server import ServerConfig
from nomad_tpu.core.worker import TRANSIENT_ERRORS, RemoteWorker
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc.endpoints import RpcError
from nomad_tpu.state.store import AppliedPlanResults, StateStore
from nomad_tpu.structs import EvalStatus, Evaluation
from nomad_tpu.structs.node import NodeStatus
from nomad_tpu.structs.plan import Plan
from nomad_tpu.utils import generate_uuid

import numpy as np


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    """Every test starts and ends with chaos disabled."""
    chaos.uninstall()
    yield
    chaos.uninstall()


# ---------------------------------------------------------------- registry


def test_spec_grammar_roundtrip():
    reg = ChaosRegistry.from_spec(
        "seed=42; rpc.drop=0.05;delay_ms=5;plan.crash_after_commit=1")
    assert reg.seed == 42
    assert reg.delay_ms == 5.0
    assert reg.rates["rpc.drop"] == 0.05
    assert reg.rates["plan.crash_after_commit"] == 1.0
    assert reg.rates["raft.partition"] == 0.0
    # spec() round-trips through the parser
    again = ChaosRegistry.from_spec(reg.spec())
    assert again.seed == reg.seed
    assert again.rates == reg.rates
    assert again.delay_ms == reg.delay_ms


def test_spec_grammar_rejects_garbage():
    with pytest.raises(ValueError, match="unknown chaos fault point"):
        ChaosRegistry.from_spec("seed=1;rpc.dorp=0.1")
    with pytest.raises(ValueError, match=r"must be in \[0, 1\]"):
        ChaosRegistry.from_spec("rpc.drop=1.5")
    with pytest.raises(ValueError, match="want key=value"):
        ChaosRegistry.from_spec("rpc.drop")
    with pytest.raises(ValueError):
        ChaosRegistry.from_spec("seed=abc")


def test_disabled_is_default_and_inert():
    assert chaos.active is None
    assert chaos.should("rpc.drop") is False
    chaos.fire("plan.crash_before_commit")   # no-op, must not raise
    chaos.maybe_delay()


def test_installed_registry_never_touches_global_random():
    random.seed(1234)
    want = [random.random() for _ in range(8)]
    random.seed(1234)
    prev = chaos.install(ChaosRegistry(seed=7, rates={"rpc.drop": 0.5}))
    try:
        for _ in range(100):
            chaos.should("rpc.drop")
        got = [random.random() for _ in range(8)]
    finally:
        chaos.install(prev)
    assert got == want


def test_seeded_determinism():
    rates = {"rpc.drop": 0.3, "broker.lease_expire": 0.2}
    seq = [ChaosRegistry(seed=7, rates=rates).should("rpc.drop")
           for _ in range(1)]  # noqa: F841  (warm-up, single draw)
    a = ChaosRegistry(seed=7, rates=rates)
    b = ChaosRegistry(seed=7, rates=rates)
    c = ChaosRegistry(seed=8, rates=rates)
    seq_a = [a.should("rpc.drop") for _ in range(64)]
    seq_b = [b.should("rpc.drop") for _ in range(64)]
    seq_c = [c.should("rpc.drop") for _ in range(64)]
    assert seq_a == seq_b
    assert seq_a != seq_c
    assert a.stats["rpc.drop"] == sum(seq_a)
    # zero-rate points never draw, so they can't shift the schedule
    assert a.should("native.fail") is False


def test_env_var_installs_registry_at_import():
    code = ("from nomad_tpu import chaos; "
            "print(chaos.active.spec() if chaos.active else 'None')")
    env = dict(os.environ, NOMAD_TPU_CHAOS="seed=9;rpc.drop=0.25")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "seed=9" in out.stdout
    assert "rpc.drop=0.25" in out.stdout

    env.pop("NOMAD_TPU_CHAOS")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "None"


# ---------------------------------------------------------- native breaker


def test_native_circuit_breaker_trips_and_resets():
    if native._load() is None:
        pytest.skip("no native toolchain")
    br = native.breaker
    br.reset()
    cap = np.full((4, 6), 100.0, np.float32)
    used = np.zeros((4, 6), np.float32)
    demand = np.full(6, 10.0, np.float32)
    want = native.allocs_fit(cap, used, demand)
    assert want.all()

    prev = chaos.install(ChaosRegistry(seed=1, rates={"native.fail": 1.0}))
    try:
        trips_before = br.stats["trips"]
        for _ in range(br.threshold):
            assert not br.open
            # every native attempt raises; the Python fallback still
            # returns the right answer
            got = native.allocs_fit(cap, used, demand)
            assert (got == want).all()
        assert br.open
        assert br.stats["trips"] == trips_before + 1
        # circuit open: native is skipped entirely, so chaos at rate 1.0
        # can no longer fail the call
        failures = br.stats["failures"]
        got = native.allocs_fit(cap, used, demand)
        assert (got == want).all()
        assert br.stats["failures"] == failures
    finally:
        chaos.install(prev)
        br.reset()
    assert not br.open
    assert (native.allocs_fit(cap, used, demand) == want).all()


# ------------------------------------------------- plan applier crash legs


def _applier_rig():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    applier = PlanApplier(store)
    queue = PlanQueue()
    queue.set_enabled(True)
    stop = threading.Event()
    loop = threading.Thread(target=applier.run_loop, args=(queue, stop),
                            daemon=True)
    loop.start()
    return store, node, applier, queue, stop, loop


def _plan_on(node, cpu=100):
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = cpu
    j.task_groups[0].tasks[0].resources.memory_mb = 64
    alloc = mock.alloc_for(j, node_id=node.id)
    plan = Plan(eval_id=generate_uuid(), job=j)
    plan.append_alloc(alloc, j)
    return plan, alloc


def test_crash_before_commit_resolves_futures_and_commits_nothing():
    store, node, applier, queue, stop, loop = _applier_rig()
    try:
        plans = [_plan_on(node)[0] for _ in range(3)]
        chaos.install(ChaosRegistry(
            seed=3, rates={"plan.crash_before_commit": 1.0}))
        futures = [queue.enqueue(p).future for p in plans]
        # every future resolves exactly once, with the injected error
        for f in futures:
            with pytest.raises(ChaosError):
                f.result(timeout=10)
        assert store.allocs() == []

        chaos.uninstall()
        # the submitter's retry path: the same plans go through clean
        for p in plans:
            r = queue.enqueue(p).future.result(timeout=10)
            assert r.node_allocation and not r.rejected_nodes
        assert len(store.allocs()) == 3
    finally:
        stop.set()
        loop.join(5)


def test_crash_after_commit_replay_dedups_on_plan_id():
    store, node, applier, queue, stop, loop = _applier_rig()
    try:
        plan, alloc = _plan_on(node)
        chaos.install(ChaosRegistry(
            seed=3, rates={"plan.crash_after_commit": 1.0}))
        with pytest.raises(ChaosError):
            queue.enqueue(plan).future.result(timeout=10)
        # the write landed even though the submitter saw an error
        assert [a.id for a in store.allocs()] == [alloc.id]
        index_after_crash = store.latest_index

        chaos.uninstall()
        # the submitter retries the same plan: replay must be a no-op
        r = queue.enqueue(plan).future.result(timeout=10)
        assert r.node_allocation and not r.rejected_nodes
        assert [a.id for a in store.allocs()] == [alloc.id]
        live = store.alloc_by_id(alloc.id)
        assert live is not None and not live.terminal_status()
        assert store.latest_index >= index_after_crash
    finally:
        stop.set()
        loop.join(5)


def test_store_dedups_applied_plan_results_by_plan_id():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    j = mock.job()
    a1 = mock.alloc_for(j, node_id=node.id)
    pid = generate_uuid()
    store.upsert_plan_results(2, AppliedPlanResults(
        allocs_to_place=[a1], eval_id="e1", plan_id=pid))
    assert store.alloc_by_id(a1.id) is not None
    # a replay carrying the same plan_id is ignored wholesale
    # (index 1: the live-name guard would drop a re-used name anyway)
    a2 = mock.alloc_for(j, node_id=node.id, index=1)
    store.upsert_plan_results(3, AppliedPlanResults(
        allocs_to_place=[a2], eval_id="e1", plan_id=pid))
    assert store.alloc_by_id(a2.id) is None
    # a fresh plan_id applies normally
    store.upsert_plan_results(4, AppliedPlanResults(
        allocs_to_place=[a2], eval_id="e1", plan_id=generate_uuid()))
    assert store.alloc_by_id(a2.id) is not None


def test_store_drops_placement_duplicating_live_name():
    """Racing plans for one redelivered eval both pass the submit-time
    token gate; the loser's same-name placement is dropped at apply."""
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    j = mock.job()
    live = mock.alloc_for(j, node_id=node.id, index=0)
    store.upsert_plan_results(2, AppliedPlanResults(
        allocs_to_place=[live], eval_id="e1", plan_id=generate_uuid()))
    racer = mock.alloc_for(j, node_id=node.id, index=0)
    store.upsert_plan_results(3, AppliedPlanResults(
        allocs_to_place=[racer], eval_id="e1", plan_id=generate_uuid()))
    assert store.alloc_by_id(racer.id) is None
    assert store.alloc_by_id(live.id) is not None
    # a different name from the same job still applies
    other = mock.alloc_for(j, node_id=node.id, index=1)
    store.upsert_plan_results(4, AppliedPlanResults(
        allocs_to_place=[other], eval_id="e1", plan_id=generate_uuid()))
    assert store.alloc_by_id(other.id) is not None
    # system jobs share one name per node by design: same name on a
    # DIFFERENT node applies, same node is the duplicate
    node2 = mock.node()
    store.upsert_node(5, node2)
    sj = mock.system_job()
    s1 = mock.alloc_for(sj, node_id=node.id, index=0)
    s2 = mock.alloc_for(sj, node_id=node2.id, index=0)
    s3 = mock.alloc_for(sj, node_id=node.id, index=0)
    store.upsert_plan_results(6, AppliedPlanResults(
        allocs_to_place=[s1, s2, s3], eval_id="e2",
        plan_id=generate_uuid()))
    assert store.alloc_by_id(s1.id) is not None
    assert store.alloc_by_id(s2.id) is not None
    assert store.alloc_by_id(s3.id) is None


def test_store_allows_same_name_when_holder_stops_in_same_plan():
    """Destructive update: stop old + place new under one name rides a
    single plan; alloc_updates apply first, so the placement lands."""
    from nomad_tpu.structs import AllocDesiredStatus
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    j = mock.job()
    old = mock.alloc_for(j, node_id=node.id, index=0)
    store.upsert_plan_results(2, AppliedPlanResults(
        allocs_to_place=[old], eval_id="e1", plan_id=generate_uuid()))
    stopped = old.copy()
    stopped.desired_status = AllocDesiredStatus.STOP
    repl = mock.alloc_for(j, node_id=node.id, index=0)
    store.upsert_plan_results(3, AppliedPlanResults(
        alloc_updates=[stopped], allocs_to_place=[repl],
        eval_id="e2", plan_id=generate_uuid()))
    assert store.alloc_by_id(repl.id) is not None
    assert store.alloc_by_id(old.id).desired_status == AllocDesiredStatus.STOP


def test_store_applies_update_of_existing_alloc_despite_dup_name():
    """Updates (same alloc id already in the store) are never dropped,
    even when a duplicate-name sibling exists — the reconciler's dedup
    stop must be able to land."""
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    j = mock.job()
    a1 = mock.alloc_for(j, node_id=node.id, index=0)
    store.upsert_plan_results(2, AppliedPlanResults(
        allocs_to_place=[a1], eval_id="e1", plan_id=generate_uuid()))
    a2 = mock.alloc_for(j, node_id=node.id, index=0)
    # force the duplicate in (simulates pre-guard history)
    store._allocs[a2.id] = a2
    store._allocs_by_job[(a2.namespace, a2.job_id)].add(a2.id)
    upd = a1.copy()
    upd.deployment_id = "d-join"
    store.upsert_plan_results(3, AppliedPlanResults(
        allocs_to_place=[upd], eval_id="e1", plan_id=generate_uuid()))
    assert store.alloc_by_id(a1.id).deployment_id == "d-join"


# ----------------------------------------------------- broker lease expiry


def _eval(job_id="job-1"):
    return Evaluation(id=generate_uuid(), namespace="default", priority=50,
                      type="service", triggered_by="job-register",
                      job_id=job_id, status=EvalStatus.PENDING)


def test_expired_lease_auto_nacks_and_redelivers():
    broker = EvalBroker(nack_timeout=60.0)
    broker.set_enabled(True)
    ev = _eval()
    broker.enqueue(ev)
    chaos.install(ChaosRegistry(
        seed=5, rates={"broker.lease_expire": 1.0}))
    got, token = broker.dequeue(["service"], timeout=1.0)
    chaos.uninstall()
    assert got is ev
    # the lease expired the moment it was handed out: the next broker
    # operation settles it, so the token reads as stale everywhere
    assert broker.outstanding(ev.id) is None
    assert broker.ack(ev.id, token) is False
    # ...and the eval redelivers with the attempt count bumped
    got2, token2 = broker.dequeue(["service"], timeout=2.0)
    assert got2 is ev and token2 != token
    assert broker._attempts[ev.id] == 1
    assert broker.ack(ev.id, token2) is True


# ------------------------------------------------ device-world scatter loss


def test_world_scatter_fail_invalidates_then_reuploads():
    """Injected loss of the device-side rank-1 scatter: the host
    snapshot keeps the commit (it is authoritative), the resident basis
    is dropped rather than served stale, and the next update() restores
    device parity with one full re-upload — counted as a steady-state
    re-upload, which is how the bench gate sees injected device loss."""
    import jax

    from nomad_tpu.parallel.world import DeviceWorld

    N, R = 16, 4
    world = DeviceWorld(mesh=None)
    capacity = np.full((N, R), 100.0, np.float32)
    world.update(capacity, np.zeros((N, R), np.float32))

    rows = np.array([0, 3], np.int32)
    demand = np.array([5.0, 2.0, 0.0, 0.0], np.float32)
    chaos.install(ChaosRegistry(seed=3, rates={"world.scatter_fail": 1.0}))
    try:
        world.apply_rank1(rows, np.ones(2, np.int32), demand)
    finally:
        chaos.uninstall()

    expect = np.zeros((N, R), np.float32)
    expect[rows] = demand
    np.testing.assert_array_equal(world.host_basis(), expect)
    assert world.stats["chaos_invalidations"] == 1
    _, basis_dev = world.device_arrays()
    assert basis_dev is None

    _, basis_dev = world.update(capacity, expect)
    got = np.asarray(jax.device_get(basis_dev))
    np.testing.assert_array_equal(got, expect)
    assert world.stats["steady_reuploads"] == 1


# -------------------------------------------------- worker retry surfaces


class _FlakyLeader:
    """Stand-in server whose rpc_leader fails the first `fail_n` calls."""

    def __init__(self, fail_n, kind="internal"):
        self.calls = 0
        self.fail_n = fail_n
        self.kind = kind

    def rpc_leader(self, method, args):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise RpcError(self.kind, "injected")
        return {"ok": True}


def test_remote_worker_nack_retries_then_succeeds():
    srv = _FlakyLeader(fail_n=2)
    w = RemoteWorker(srv)
    assert w._nack("ev-1", "tok-1") is True
    assert srv.calls == 3


def test_remote_worker_nack_is_bounded():
    srv = _FlakyLeader(fail_n=100)
    w = RemoteWorker(srv)
    t0 = time.monotonic()
    assert w._nack("ev-1", "tok-1") is False
    assert srv.calls == 3                    # three attempts, no more
    assert time.monotonic() - t0 < 5.0       # bounded, not a spin


def test_remote_worker_rpc_retries_leadership_churn_only():
    # retryable kind: keeps trying until the fake leader answers
    srv = _FlakyLeader(fail_n=3, kind="no_leader")
    w = RemoteWorker(srv)
    assert w._rpc("Eval.Ack", {}, deadline=5.0) == {"ok": True}
    assert srv.calls == 4
    # non-retryable kind: a real answer, surfaced immediately
    srv = _FlakyLeader(fail_n=100, kind="stale_eval_token")
    w = RemoteWorker(srv)
    with pytest.raises(RpcError, match="injected"):
        w._rpc("Plan.Submit", {}, deadline=5.0)
    assert srv.calls == 1


# -------------------------------------------------- heartbeat invalidate


class _FlakyHeartbeatServer:
    def __init__(self, node, fail_times=1):
        self.node = node
        self.fail_times = fail_times
        self.status_calls = []
        outer = self

        class _Store:
            def node_by_id(self, node_id):
                return outer.node

            def allocs_by_node(self, node_id):
                return []

        self.store = _Store()

    def update_node_status(self, node_id, status):
        self.status_calls.append((node_id, status))
        if len(self.status_calls) <= self.fail_times:
            raise RuntimeError("lost quorum mid-invalidate")


def test_heartbeat_invalidate_failure_rearms_retry():
    node = mock.node(status=NodeStatus.READY)
    srv = _FlakyHeartbeatServer(node, fail_times=1)
    hb = HeartbeatTracker(srv, ttl=0.15, tick=0.02)
    hb.start()
    try:
        hb.heartbeat(node.id)
        # first invalidate at ~0.15s raises; the re-armed retry deadline
        # (min(ttl, 1.0)) fires a second invalidate that lands
        assert _wait(lambda: len(srv.status_calls) >= 2, timeout=3.0)
    finally:
        hb.stop()
    assert all(c == (node.id, NodeStatus.DOWN) for c in srv.status_calls)


# ------------------------------------------------------- api client retry


class _RetryHandler(BaseHTTPRequestHandler):
    gets = 0
    puts = 0
    fail_first_gets = 1

    def _respond(self, code, body, retry_after=None):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", retry_after)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        cls = type(self)
        cls.gets += 1
        if cls.gets <= cls.fail_first_gets:
            self._respond(503, b'"busy"', retry_after="0")
        else:
            self._respond(200, b"[]")

    def do_PUT(self):
        type(self).puts += 1
        self._respond(503, b'"busy"')

    def log_message(self, *args):
        pass


def test_api_client_retries_idempotent_gets_only():
    _RetryHandler.gets = 0
    _RetryHandler.puts = 0
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _RetryHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        client = ApiClient(f"http://127.0.0.1:{httpd.server_port}",
                           retries=2, retry_backoff=0.01)
        # GET: first answer is a 503 with Retry-After; the retry succeeds
        assert client.get("/v1/jobs") == []
        assert _RetryHandler.gets == 2
        # PUT: never retried — the server may have applied the write
        with pytest.raises(ApiError) as exc:
            client.put("/v1/jobs", {"Job": {}})
        assert exc.value.status == 503
        assert _RetryHandler.puts == 1
        # GET exhausting its budget surfaces the last error
        _RetryHandler.gets = 0
        _RetryHandler.fail_first_gets = 100
        with pytest.raises(ApiError):
            client.get("/v1/jobs")
        assert _RetryHandler.gets == 3       # initial + 2 retries
    finally:
        _RetryHandler.fail_first_gets = 1
        httpd.shutdown()
        httpd.server_close()


# ------------------------------------------------------------------- soak


SOAK_RATES = {
    "rpc.drop": 0.02,
    "rpc.delay": 0.05,
    "raft.partition": 0.01,
    "broker.lease_expire": 0.05,
    "plan.crash_before_commit": 0.05,
    "plan.crash_after_commit": 0.05,
}


def _on_leader(cluster, fn, timeout=10.0):
    """Run fn(leader), retrying across leadership churn / chaos drops."""
    deadline = time.time() + timeout
    while True:
        try:
            return fn(cluster.leader(timeout=5.0))
        except TRANSIENT_ERRORS + (TimeoutError,):
            if time.time() >= deadline:
                raise
            time.sleep(0.05)


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_chaos_soak_converges(seed):
    reg = ChaosRegistry(seed=seed, rates=SOAK_RATES, delay_ms=1.0)
    cfg = ServerConfig(num_schedulers=2, heartbeat_ttl=60.0,
                       failed_eval_followup_delay=0.3)
    cluster = Cluster(3, config=cfg, raft_config=RaftConfig(
        heartbeat_interval=0.02, election_timeout=0.1))
    for s in cluster.servers:
        # quick redelivery so injected nacks resolve inside the test
        s.broker.nack_timeout = 1.0
        s.broker.initial_nack_delay = 0.05
        s.broker.subsequent_nack_delay = 0.1
    rng = random.Random(seed)
    job = mock.job()
    job.task_groups[0].count = 3
    try:
        chaos.install(reg)
        cluster.start()
        try:
            nodes = [mock.node() for _ in range(4)]
            for n in nodes:
                _on_leader(cluster, lambda ld, n=n: ld.register_node(n))
            _on_leader(cluster, lambda ld: ld.register_job(job))
            # seeded kill/heal schedule: isolating the leader forces a
            # failover and the restoration path; a follower just churns
            for _ in range(2):
                victim = cluster.servers[rng.randrange(len(cluster.servers))]
                cluster.isolate(victim)
                time.sleep(0.3)
                cluster.heal(victim)
                cluster.leader(timeout=10.0)
            time.sleep(0.5)   # let the fault schedule bite mid-flight work
        finally:
            chaos.uninstall()

        def converged():
            try:
                ld = cluster.leader(timeout=2.0)
            except TimeoutError:
                return False
            live = [a for a in ld.store.allocs_by_job("default", job.id)
                    if not a.terminal_status()]
            if len(live) != 3:
                return False
            if any(not EvalStatus.terminal(e.status)
                   for e in ld.store.evals()):
                return False
            # nothing leased, nothing queued, nothing in flight
            return not ld.broker._unack and not ld.plan_queue._heap

        if not _wait(converged, timeout=20.0):
            ld = cluster.leader(timeout=5.0)
            live = [a for a in ld.store.allocs_by_job("default", job.id)
                    if not a.terminal_status()]
            stuck = [(e.id[:8], e.status, e.triggered_by, e.wait_until)
                     for e in ld.store.evals()
                     if not EvalStatus.terminal(e.status)]
            pytest.fail(
                f"seed {seed}: cluster did not converge; "
                f"chaos fired: {dict(reg.stats)}; leader={ld.name} "
                f"live={len(live)} stuck_evals={stuck} "
                f"unack={list(ld.broker._unack)} "
                f"queue={len(ld.plan_queue._heap)} "
                f"broker={dict(ld.broker.stats)}")
    finally:
        chaos.uninstall()
        cluster.stop()


# ------------------------------------------------- phased chaos schedules


def test_phase_grammar_roundtrip():
    reg = ChaosRegistry.from_spec(
        "seed=7;phase=storm:0.5-3.0;phase=calm2:4-6;"
        "rpc.drop=0.01;broker.lease_expire=0.4@storm;"
        "node.churn_kill=0.6@storm;scale.burst=0.2@calm2")
    assert reg.phases == {"storm": (0.5, 3.0), "calm2": (4.0, 6.0)}
    assert reg.phased["broker.lease_expire"]["storm"] == 0.4
    assert reg.phased["node.churn_kill"]["storm"] == 0.6
    assert reg.phased["scale.burst"]["calm2"] == 0.2
    assert reg.rates["rpc.drop"] == 0.01
    again = ChaosRegistry.from_spec(reg.spec())
    assert again.phases == reg.phases
    assert again.phased == reg.phased
    assert again.rates == reg.rates


def test_phase_grammar_rejects_garbage():
    with pytest.raises(ValueError, match="undeclared phase"):
        ChaosRegistry.from_spec("rpc.drop=0.1@ghost")
    with pytest.raises(ValueError, match="window must have"):
        ChaosRegistry.from_spec("phase=storm:3.0-1.0")
    with pytest.raises(ValueError, match="bad chaos phase"):
        ChaosRegistry.from_spec("phase=storm:oops")
    with pytest.raises(ValueError, match=r"must be in \[0, 1\]"):
        ChaosRegistry.from_spec("phase=s:0-1;rpc.drop=1.5@s")
    with pytest.raises(ValueError, match="unknown chaos fault point"):
        ChaosRegistry.from_spec("phase=s:0-1;rpc.dorp=0.5@s")
    with pytest.raises(ValueError, match="empty phase"):
        ChaosRegistry.from_spec("rpc.drop=0.5@")


def test_phased_rates_gated_by_arm_and_window():
    reg = ChaosRegistry.from_spec(
        "seed=1;phase=storm:10-20;node.churn_kill=1.0@storm")
    # not armed: phase rates contribute nothing
    assert reg.effective_rate("node.churn_kill") == 0.0
    assert reg.phase_now() == ()
    # armed, inside the window (arm with a monotonic anchor 15s ago)
    reg.arm(now=time.monotonic() - 15)
    assert reg.phase_now() == ("storm",)
    assert reg.effective_rate("node.churn_kill") == 1.0
    assert reg.should("node.churn_kill") is True
    # armed, after the window closes
    reg.arm(now=time.monotonic() - 25)
    assert reg.phase_now() == ()
    assert reg.effective_rate("node.churn_kill") == 0.0


def test_phased_rate_max_with_base_rate():
    reg = ChaosRegistry.from_spec(
        "phase=s:0-100;rpc.drop=0.3;rpc.drop=0.1@s")
    reg.arm(now=time.monotonic() - 1)
    # the open phase cannot LOWER a base rate: effective is the max
    assert reg.effective_rate("rpc.drop") == 0.3


def test_node_churn_kill_swallows_heartbeat_rearm():
    node = mock.node(status=NodeStatus.READY)
    srv = _FlakyHeartbeatServer(node, fail_times=0)
    hb = HeartbeatTracker(srv, ttl=0.15, tick=0.02)
    hb.start()
    try:
        chaos.install(ChaosRegistry(seed=3,
                                    rates={"node.churn_kill": 1.0}))
        hb.heartbeat(node.id)          # swallowed: TTL never re-armed
        assert _wait(lambda: len(srv.status_calls) == 0, timeout=0.3)
        chaos.uninstall()
        hb.heartbeat(node.id)          # real re-arm, then expire
        assert _wait(lambda: len(srv.status_calls) >= 1, timeout=3.0)
    finally:
        chaos.uninstall()
        hb.stop()
    assert srv.status_calls[0] == (node.id, NodeStatus.DOWN)
