"""ClusterMatrix / AttrTable incremental-mirror tests."""
import numpy as np

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix, RES_CPU, RES_MEM, pad_to_bucket
from nomad_tpu.encode.attrs import AttrTable, hash_code


def test_pad_to_bucket():
    assert pad_to_bucket(1) == 8
    assert pad_to_bucket(8) == 8
    assert pad_to_bucket(9) == 16
    assert pad_to_bucket(1000) == 1024


def test_upsert_node_and_grow():
    cm = ClusterMatrix()
    nodes = [mock.node() for _ in range(20)]  # forces growth past 8 and 16
    rows = [cm.upsert_node(n) for n in nodes]
    assert cm.n_rows == 32
    assert len(set(rows)) == 20
    r0 = cm.row_of[nodes[0].id]
    assert cm.capacity[r0, RES_CPU] == 4000
    assert cm.ready[r0]
    assert cm.attrs.column("node.datacenter").values[r0] == "dc1"


def test_alloc_usage_tracking():
    cm = ClusterMatrix()
    n = mock.node()
    cm.upsert_node(n)
    j = mock.job()
    a = mock.alloc_for(j, n.id)
    cm.upsert_alloc(a)
    r = cm.row_of[n.id]
    assert cm.used[r, RES_CPU] == 500
    assert cm.used[r, RES_MEM] == 256
    # terminal update removes usage
    a.client_status = "failed"
    cm.upsert_alloc(a)
    assert cm.used[r, RES_CPU] == 0


def test_node_removal_recycles_row():
    cm = ClusterMatrix()
    n1, n2 = mock.node(), mock.node()
    r1 = cm.upsert_node(n1)
    cm.remove_node(n1.id)
    assert not cm.ready[r1]
    r2 = cm.upsert_node(n2)
    assert r2 == r1  # recycled


def test_port_accounting():
    cm = ClusterMatrix()
    n = mock.node()
    n.reserved_resources.reserved_ports = [22, 80]
    cm.upsert_node(n)
    free = cm.static_ports_free([22])
    r = cm.row_of[n.id]
    assert not free[r]
    assert cm.static_ports_free([8080])[r]
    # dynamic port count excludes claims inside the dynamic range
    base_free = cm.free_dynamic_ports()[r]
    assert base_free == 12001
    j = mock.job()
    a = mock.alloc_for(j, n.id)
    from nomad_tpu.structs.resources import NetworkPort, NetworkResource
    a.allocated_resources.shared_ports = [NetworkPort(label="http", value=20005)]
    cm.upsert_alloc(a)
    assert cm.free_dynamic_ports()[r] == 12000
    assert not cm.static_ports_free([20005])[r]


def test_attr_ordinals_lexical():
    t = AttrTable(4)
    col = t.column("attr.ver")
    for i, v in enumerate(["1.10", "1.9", None, "2.0"]):
        col.set(i, v)
    ords = col.ordinals()
    # lexical: "1.10" < "1.9" < "2.0"
    assert ords[0] < ords[1] < ords[3]
    assert ords[2] == -1
    r, exact = col.ordinal_of("1.9")
    assert exact and r == ords[1]


def test_hash_code_stable_nonzero():
    assert hash_code("x") == hash_code("x")
    assert hash_code("x") != hash_code("y")
    assert hash_code("") != 0


def test_dc_mask():
    cm = ClusterMatrix()
    a = mock.node(datacenter="dc1")
    b = mock.node(datacenter="dc2")
    cm.upsert_node(a)
    cm.upsert_node(b)
    m = cm.dc_mask(["dc2"])
    assert m[cm.row_of[b.id]] and not m[cm.row_of[a.id]]
