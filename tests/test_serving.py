"""Serving-plane tests: raft ReadIndex + leader leases, consistency-mode
follower reads over RPC and HTTP, blocking queries under churn, and the
backpressured event broker (reference: nomad/rpc.go blockingRPC +
QueryOptions, stream/event_broker.go, stream/subscription.go)."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import chaos, mock
from nomad_tpu.agent.http import HTTPServer
from nomad_tpu.chaos import ChaosRegistry
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.events import Event, EventBroker
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.serving import (
    CONSISTENT, DEFAULT, STALE, EventStreamer, mode_from_query,
)


def _wait(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster():
    c = Cluster(3)
    c.start()
    yield c
    c.stop()


def _leader_among(servers, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [s for s in servers
                   if s.raft is not None and s.raft.is_leader
                   and s._established]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise TimeoutError("no leader among subset")


class _ShimAgent:
    """Just enough agent surface for HTTPServer to front one Server of a
    cluster (the per-server HTTP listener the reference runs)."""

    def __init__(self, server):
        self.server = server

    def rpc(self, method, args, consistency=None):
        return self.server.rpc_leader(method, args)


def _get(port, path, timeout=30.0):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return (resp.status, json.loads(resp.read() or b"null"),
                dict(resp.headers))


# ===================================================================== raft


def test_read_index_reflects_committed_writes(cluster):
    leader = cluster.leader()
    leader.register_node(mock.node())
    commit = leader.raft.commit_index
    idx = leader.raft.read_index(lease_ok=False)
    assert idx >= commit


def test_concurrent_read_index_batches_into_few_rounds(cluster):
    leader = cluster.leader()
    leader.register_node(mock.node())
    # stretch each confirmation round so concurrent readers provably
    # pile onto an in-flight batch instead of each paying their own
    prev = chaos.install(ChaosRegistry(
        seed=11, rates={"read.index_stall": 1.0}, delay_ms=50.0))
    try:
        rounds0 = leader.raft.read_rounds
        results = []
        errs = []

        def reader():
            try:
                results.append(
                    leader.raft.read_index(timeout=10.0, lease_ok=False))
            except Exception as e:              # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=reader) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rounds_used = leader.raft.read_rounds - rounds0
    finally:
        chaos.uninstall()
    assert not errs
    assert len(results) == 16
    # 16 concurrent readers must share rounds (amortized ReadIndex)
    assert rounds_used <= 5, f"{rounds_used} rounds for 16 readers"


def test_lease_serves_reads_with_zero_rounds(cluster):
    leader = cluster.leader()
    leader.register_node(mock.node())
    assert _wait(lambda: leader.raft.lease_valid(), 5.0), \
        "steady-state heartbeat acks must establish the lease"
    rounds0 = leader.raft.read_rounds
    for _ in range(50):
        leader.raft.read_index()        # default (lease) mode
    assert leader.raft.read_rounds == rounds0, \
        "lease reads must cost zero confirmation rounds"


def test_lease_duration_bounded_by_election_timeout_minus_skew(cluster):
    leader = cluster.leader()
    assert _wait(lambda: leader.raft.lease_valid(), 5.0)
    cfg = leader.raft.config
    remaining = leader.raft._lease_until - time.monotonic()
    assert remaining > 0
    assert remaining <= cfg.election_timeout * (1 - cfg.lease_clock_skew)


def test_deposed_leader_lease_never_overlaps_new_leader(cluster):
    old = cluster.leader()
    others = [s for s in cluster.servers if s is not old]
    cluster.isolate(old)
    # stickiness: a successor needs a full election_timeout of quorum
    # silence first, which strictly exceeds the old lease's lifetime
    new = _leader_among(others)
    assert not old.raft.lease_valid(), \
        "old leader's lease outlived the new leader's election"
    with pytest.raises((NotLeaderError, TimeoutError)):
        old.raft.read_index(timeout=1.0, lease_ok=False)
    # the new leader serves linearizable reads for the majority side
    new.register_node(mock.node())
    assert new.raft.read_index(lease_ok=False) >= new.raft.commit_index
    cluster.heal(old)


def test_follower_reads_see_latest_write(cluster):
    leader = cluster.leader()
    follower = cluster.followers()[0]
    node = mock.node()
    leader.register_node(node)
    for mode in (CONSISTENT, DEFAULT):
        result, ctx = follower.read("Node.List", {}, consistency=mode)
        assert any(n.id == node.id for n in result), \
            f"{mode} follower read missed a committed write"
        assert ctx.known_leader


def test_stale_read_serves_local_store(cluster):
    follower = cluster.followers()[0]
    result, ctx = follower.read("Node.List", {}, consistency=STALE)
    assert isinstance(result, list)
    assert ctx.mode == STALE
    assert ctx.last_contact_ms >= 0


def test_rpc_consistency_arg_routes_through_gate(cluster):
    leader = cluster.leader()
    follower = cluster.followers()[0]
    node = mock.node()
    leader.register_node(node)
    out = follower.endpoints.handle(
        "Node.List", {"consistency": "consistent"})
    assert any(n.id == node.id for n in out)
    # stale works even though this server is not the leader
    out = follower.endpoints.handle("Node.List", {"consistency": "stale"})
    assert isinstance(out, list)


# ===================================================================== http


def test_http_modes_and_staleness_headers(cluster):
    leader = cluster.leader()
    follower = cluster.followers()[0]
    job = mock.job()
    leader.register_job(job)
    idx = leader.store.latest_index
    assert cluster.wait_replication(idx)
    http = HTTPServer(_ShimAgent(follower), port=0)
    http.start()
    try:
        for qs in ("?stale=true", "?consistent", ""):
            status, body, hdrs = _get(http.port, f"/v1/jobs{qs}")
            assert status == 200
            assert any(j["ID"] == job.id for j in body), qs
            assert hdrs["X-Nomad-KnownLeader"] == "true"
            assert int(hdrs["X-Nomad-LastContact"]) >= 0
            assert int(hdrs["X-Nomad-Index"]) >= idx
    finally:
        http.stop()


def test_partition_stale_serves_while_consistent_fails_fast(cluster):
    cluster.leader()
    follower = cluster.followers()[0]
    http = HTTPServer(_ShimAgent(follower), port=0)
    http.start()
    cluster.isolate(follower)
    try:
        # stale keeps serving from the local store on the minority side
        status, body, hdrs = _get(http.port, "/v1/jobs?stale=true")
        assert status == 200
        # linearizable reads fail fast: the leader is unreachable
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(http.port, "/v1/jobs?consistent")
        assert exc.value.code == 503
        assert time.monotonic() - t0 < 3.0, "must fail fast, not hang"
    finally:
        cluster.heal(follower)
        http.stop()


def test_blocking_query_wakes_on_index_advance(cluster):
    leader = cluster.leader()
    follower = cluster.followers()[0]
    idx = leader.store.latest_index
    assert cluster.wait_replication(idx)
    http = HTTPServer(_ShimAgent(follower), port=0)
    http.start()
    out = {}

    def blocker():
        t0 = time.monotonic()
        status, body, hdrs = _get(
            http.port, f"/v1/jobs?index={idx}&wait=10s")
        out["elapsed"] = time.monotonic() - t0
        out["status"] = status
        out["index"] = int(hdrs["X-Nomad-Index"])

    try:
        t = threading.Thread(target=blocker)
        t.start()
        time.sleep(0.4)                 # let it park on the index wait
        assert t.is_alive(), "blocking query returned before any advance"
        leader.register_node(mock.node())
        t.join(8.0)
        assert not t.is_alive()
        assert out["status"] == 200
        # woke on the advance (one wakeup), not on the 10s wait cap
        assert 0.3 <= out["elapsed"] < 8.0
        assert out["index"] > idx
    finally:
        http.stop()


def test_blocking_query_never_returns_lower_index(cluster):
    cluster.leader()
    follower = cluster.followers()[0]
    http = HTTPServer(_ShimAgent(follower), port=0)
    http.start()
    try:
        given = 10 ** 9
        status, body, hdrs = _get(
            http.port, f"/v1/jobs?index={given}&wait=200ms")
        assert status == 200
        assert int(hdrs["X-Nomad-Index"]) >= given
    finally:
        http.stop()


def test_blocking_query_honors_wait_cap_during_transfer(cluster):
    leader = cluster.leader()
    follower = cluster.followers()[0]
    idx = follower.store.latest_index
    http = HTTPServer(_ShimAgent(follower), port=0)
    http.start()
    cluster.isolate(leader)
    try:
        t0 = time.monotonic()
        try:
            status, _, hdrs = _get(
                http.port, f"/v1/jobs?index={idx}&wait=1s", timeout=30.0)
            assert int(hdrs["X-Nomad-Index"]) >= idx
        except urllib.error.HTTPError as e:
            status = e.code             # 503 while leadership is vacant
        elapsed = time.monotonic() - t0
        assert status in (200, 503)
        assert elapsed < 8.0, \
            f"blocking query overshot its wait cap: {elapsed:.1f}s"
    finally:
        cluster.heal(leader)
        http.stop()


# =================================================================== broker


def _ev(i, key="k", topic="Node"):
    return Event(topic, "NodeRegistration", key, "", i, {"i": i})


def test_subscription_queue_is_bounded_under_stalled_consumer():
    b = EventBroker(buffer_size=64)
    sub = b.subscribe({"*": ["*"]}, max_queue=8)
    for i in range(1, 101):
        b.publish([_ev(i)])
    st = b.stats()["subs"][0]
    assert st["queue_len"] <= 8
    assert st["dropped"] > 0
    assert st["evictions"] >= 1
    assert st["catching_up"]


def test_evicted_subscriber_catches_up_exactly_once_in_order():
    b = EventBroker(buffer_size=256)
    sub = b.subscribe({"*": ["*"]}, max_queue=8)
    for i in range(1, 51):
        b.publish([_ev(i)])
    got = []
    while True:
        ev = sub.next(timeout=0.2)
        if ev is None:
            break
        got.append(ev.index)
    assert got == list(range(1, 51)), \
        "catch-up must replay every retained event exactly once, in order"
    st = b.stats()["subs"][0]
    assert not st["catching_up"]
    assert st["delivered"] == 50


def test_catchup_applies_topic_filters():
    b = EventBroker(buffer_size=256)
    sub = b.subscribe({"Node": ["a"]}, max_queue=4)
    for i in range(1, 41):
        b.publish([_ev(i, key="a" if i % 2 else "b")])
    got = []
    while True:
        ev = sub.next(timeout=0.2)
        if ev is None:
            break
        got.append(ev.index)
    assert got == [i for i in range(1, 41) if i % 2]


def test_from_index_replays_retained_buffer():
    b = EventBroker()
    for i in range(1, 11):
        b.publish([_ev(i)])
    sub = b.subscribe({"*": ["*"]}, from_index=5)
    got = [sub.next(0.2).index for _ in range(5)]
    assert got == [6, 7, 8, 9, 10]
    assert sub.next(0.05) is None


def test_live_subscriber_sees_no_drops():
    b = EventBroker()
    sub = b.subscribe({"*": ["*"]}, max_queue=64)
    got = []
    stop = threading.Event()

    def consume():
        while not stop.is_set() or sub.queue:
            ev = sub.next(timeout=0.05)
            if ev is not None:
                got.append(ev.index)

    t = threading.Thread(target=consume)
    t.start()
    for i in range(1, 201):
        b.publish([_ev(i)])
        if i % 50 == 0:
            time.sleep(0.01)
    _wait(lambda: len(got) == 200, 5.0)
    stop.set()
    t.join(2.0)
    assert got == list(range(1, 201))
    st = b.stats()["subs"][0]
    assert st["dropped"] == 0 and st["evictions"] == 0


# =================================================================== stream


def test_stream_heartbeat_interval_is_configurable():
    b = EventBroker()
    s = EventStreamer(b.subscribe({"*": ["*"]}), heartbeat=0.1)
    frames = []
    s.run(frames.append, 0.35)
    assert 1 <= s.heartbeats <= 5
    assert all(f == b"{}\n" for f in frames)
    s2 = EventStreamer(b.subscribe({"*": ["*"]}), heartbeat=30.0)
    frames2 = []
    s2.run(frames2.append, 0.3)
    assert s2.heartbeats == 0 and frames2 == []


def test_stream_emits_ndjson_event_frames():
    b = EventBroker()
    sub = b.subscribe({"*": ["*"]})
    s = EventStreamer(sub, heartbeat=30.0)
    frames = []
    t = threading.Thread(target=lambda: s.run(frames.append, 1.0))
    t.start()
    time.sleep(0.1)
    b.publish([_ev(3)])
    t.join(3.0)
    events = [json.loads(f) for f in frames if f != b"{}\n"]
    assert events and events[0]["Index"] == 3
    assert events[0]["Events"][0]["Topic"] == "Node"


# ==================================================================== chaos


def test_chaos_lease_expire_forces_full_round(cluster):
    leader = cluster.leader()
    assert _wait(lambda: leader.raft.lease_valid(), 5.0)
    prev = chaos.install(ChaosRegistry(
        seed=1, rates={"read.lease_expire": 1.0}))
    try:
        r0 = leader.raft.read_rounds
        leader.raft.read_index()
        leader.raft.read_index()
        assert leader.raft.read_rounds >= r0 + 2, \
            "an expired lease must force the confirmation round"
    finally:
        chaos.uninstall()
        if prev is not None:
            chaos.install(prev)


def test_chaos_subscriber_stall_keeps_memory_bounded():
    b = EventBroker(buffer_size=64)
    sub = b.subscribe({"*": ["*"]}, max_queue=8)
    frames = []
    prev = chaos.install(ChaosRegistry(
        seed=2, rates={"stream.subscriber_stall": 1.0}, delay_ms=20.0))
    try:
        s = EventStreamer(sub, heartbeat=30.0)
        t = threading.Thread(target=lambda: s.run(frames.append, 0.6))
        t.start()
        for i in range(1, 301):
            b.publish([_ev(i)])
            time.sleep(0.001)
        t.join(5.0)
    finally:
        chaos.uninstall()
        if prev is not None:
            chaos.install(prev)
    assert len(sub.queue) <= 8, "stalled consumer must not grow the queue"


# ===================================================================== misc


def test_mode_from_query():
    assert mode_from_query({}) == DEFAULT
    assert mode_from_query({"stale": "true"}) == STALE
    assert mode_from_query({"stale": ""}) == STALE
    assert mode_from_query({"stale": "false"}) == DEFAULT
    assert mode_from_query({"consistent": ""}) == CONSISTENT
    assert mode_from_query({"consistent": "", "stale": "true"}) == CONSISTENT
