"""Multi-region federation tests: WAN gossip pool, cross-region RPC
forwarding, region validation, and multi-region job deployment
(reference analogs: nomad/serf_test.go WAN join, nomad/rpc_test.go
forwardRegion, nomad/job_endpoint_test.go multiregion)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.cluster import FederatedCluster
from nomad_tpu.core.server import ServerConfig
from nomad_tpu.federation import MAX_FORWARD_HOPS, WanPool
from nomad_tpu.jobspec import parse_job
from nomad_tpu.raft import InMemTransport, RaftConfig
from nomad_tpu.raft.transport import Unreachable
from nomad_tpu.rpc.endpoints import RpcError
from nomad_tpu.structs import (
    AllocClientStatus,
    AllocDesiredStatus,
    DeploymentStatus,
    Multiregion,
    MultiregionRegion,
)

FAST_RAFT = dict(heartbeat_interval=0.02, election_timeout=0.1)


def make_fed(n: int = 1, regions=("global", "west")) -> FederatedCluster:
    fc = FederatedCluster(
        regions=regions, n=n,
        config=ServerConfig(num_schedulers=2, heartbeat_ttl=60.0),
        raft_config=RaftConfig(**FAST_RAFT))
    fc.start()
    fc.wait_federated(20.0)
    return fc


def wait_for(cond, timeout=15.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {msg}")


def drive_healthy(server, namespace, job_id, timeout=20.0, min_version=0):
    """Mark a job's live allocs running+healthy through the real
    Node.UpdateAlloc RPC until its latest deployment (for at least job
    version `min_version`) goes SUCCESSFUL."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        d = server.store.latest_deployment_by_job_id(namespace, job_id)
        if d is not None and d.job_version < min_version:
            d = None
        updates = []
        for a in server.store.allocs_by_job(namespace, job_id):
            if a.desired_status == AllocDesiredStatus.RUN \
                    and not a.is_healthy():
                u = a.copy()
                u.client_status = AllocClientStatus.RUNNING
                u.deployment_status = {"healthy": True}
                updates.append(u)
        if updates:
            server.endpoints.handle("Node.UpdateAlloc", {"allocs": updates})
        if d is not None and d.status == DeploymentStatus.SUCCESSFUL:
            return d
        time.sleep(0.05)
    raise TimeoutError(f"deployment for {job_id} never became SUCCESSFUL")


# -------------------------------------------------------------- WAN pool


def test_wan_pool_regions_and_leader_tags():
    t = InMemTransport()
    pools = [
        WanPool(t, "g-1", ("g-1", 0), region="global", is_leader=True,
                interval=0.05),
        WanPool(t, "g-2", ("g-2", 0), region="global", interval=0.05),
        WanPool(t, "w-1", ("w-1", 0), region="west", is_leader=True,
                interval=0.05),
    ]
    try:
        for p in pools:
            p.start()
        for p in pools[1:]:
            p.join([("g-1", ("g-1", 0))])
        wait_for(lambda: all(p.regions() == ["global", "west"]
                             for p in pools), msg="WAN convergence")
        assert pools[2].region_leader("global") == "g-1"
        assert pools[0].region_leader("west") == "w-1"
        assert pools[2].region_servers("global") == ["g-1", "g-2"]
        # leadership moves by re-tagging: the new claim's bumped
        # incarnation outranks the old one everywhere
        pools[0].set_leader(False)
        pools[1].set_leader(True)
        wait_for(lambda: pools[2].region_leader("global") == "g-2",
                 msg="leader re-tag propagation")
    finally:
        for p in pools:
            p.stop()


def test_wan_pool_reaps_left_region_leader():
    """A region leader that gracefully leaves is reaped into a tombstone;
    stale gossip at the old incarnation cannot resurrect it, only a
    strictly higher incarnation can."""
    t = InMemTransport()
    a = WanPool(t, "a", ("a", 0), region="global", interval=0.05,
                suspect_after=0.3, fail_after=0.6, reap_after=0.3)
    b = WanPool(t, "b", ("b", 0), region="west", is_leader=True,
                interval=0.05)
    try:
        a.start()
        b.start()
        b.join([("a", ("a", 0))])
        wait_for(lambda: a.region_leader("west") == "b",
                 msg="west leader visible")
        b.leave()
        b.stop()
        with b._lock:
            left_inc = b.members["b"].incarnation
        # LEFT propagates, then the silent entry is reaped into a
        # tombstone holding its final incarnation
        wait_for(lambda: "b" not in a.members
                 and a._tombstones.get("b") == left_inc,
                 msg="LEFT member reaped into tombstone")
        assert a.region_leader("west") is None
        assert a.regions() == ["global"]
        # a stale pre-leave ALIVE entry (incarnation <= tombstone) is a
        # ghost: the merge must reject it
        stale = {"name": "b", "addr": ("b", 0), "incarnation": left_inc,
                 "status": "alive", "tags": {"region": "west",
                                             "leader": True}}
        a._merge([stale])
        assert "b" not in a.members
        assert a.region_leader("west") is None
        # only a strictly higher incarnation (a real rejoin) clears it
        fresh = dict(stale, incarnation=left_inc + 1)
        a._merge([fresh])
        assert "b" in a.members
        assert a.region_leader("west") == "b"
    finally:
        a.stop()
        b.stop()


# ----------------------------------------------------- forwarding + routing


@pytest.fixture(scope="module")
def fed():
    fc = make_fed(n=1)
    yield fc
    fc.stop()


def test_status_regions_is_wan_backed(fed):
    for region in ("global", "west"):
        lead = fed.leader(region)
        assert lead.endpoints.handle("Status.Regions", {}) == \
            ["global", "west"]


def test_cross_region_job_register_forwards_by_job_region(fed):
    gl, wl = fed.leader("global"), fed.leader("west")
    for s in (gl, wl):
        for _ in range(2):
            s.register_node(mock.node())
    job = mock.job()
    job.region = "west"
    resp = gl.endpoints.handle("Job.Register", {"job": job})
    assert resp["eval_id"]
    wait_for(lambda: wl.store.job_by_id("default", job.id) is not None,
             msg="job forwarded to west")
    assert gl.store.job_by_id("default", job.id) is None


def test_cross_region_read_via_args_region(fed):
    gl, wl = fed.leader("global"), fed.leader("west")
    job = mock.job()
    job.region = "west"
    wl.register_job(job)
    args = {"namespace": "default", "job_id": job.id, "region": "west"}
    snapshot = dict(args)
    got = gl.endpoints.handle("Job.GetJob", args)
    assert got is not None and got.id == job.id
    # the caller's dict must come back untouched (it may be retried
    # against another server, which needs the region field intact)
    assert args == snapshot


def test_forward_hop_counter_breaks_loops(fed):
    gl = fed.leader("global")
    with pytest.raises(RpcError) as e:
        gl.endpoints.handle("Job.GetJob", {
            "namespace": "default", "job_id": "nope", "region": "west",
            "_forward_hops": MAX_FORWARD_HOPS})
    assert e.value.kind == "forward_loop"


def test_unknown_region_rejected_with_known_regions(fed):
    gl = fed.leader("global")
    job = mock.job()
    job.region = "mars"
    with pytest.raises(RpcError) as e:
        gl.register_job(job)
    assert e.value.kind == "unknown_region"
    assert "global" in str(e.value) and "west" in str(e.value)


def test_stale_serves_locally_while_remote_dark_consistent_fails_fast(fed):
    gl, wl = fed.leader("global"), fed.leader("west")
    fed.partition_region("west")
    try:
        # the dark region still serves stale reads from its own store
        assert isinstance(
            wl.endpoints.handle("Job.List", {"namespace": None,
                                             "consistency": "stale"}),
            list)
        # a consistent read INTO the dark region fails fast, not hangs
        t0 = time.monotonic()
        with pytest.raises((Unreachable, RpcError)):
            gl.endpoints.handle("Job.GetJob", {
                "namespace": "default", "job_id": "nope",
                "region": "west", "consistency": "consistent"})
        assert time.monotonic() - t0 < 5.0
    finally:
        fed.heal_region("west")


def test_forwarding_survives_remote_leader_churn():
    fc = make_fed(n=3)
    try:
        gl = fc.leader("global")
        for _ in range(2):
            fc.leader("west").register_node(mock.node())
        old = fc.leader("west")
        fc.kill(old)
        job = mock.job()
        job.region = "west"

        def submit():
            try:
                return gl.endpoints.handle("Job.Register", {"job": job})
            except (Unreachable, RpcError, TimeoutError):
                return None
        resp = wait_for(submit, timeout=20.0,
                        msg="forward through west leader churn")
        assert resp["eval_id"]
        new = fc.leader("west", timeout=10.0)
        assert new is not old
        wait_for(lambda: new.store.job_by_id("default", job.id) is not None,
                 msg="job landed on new west leader")
    finally:
        fc.stop()


class _AgentShim:
    """Just enough of an Agent for HTTPServer to front a cluster Server."""

    def __init__(self, server):
        self.server = server

    def rpc(self, method, args, consistency=None):
        return self.server.rpc_leader(method, args)


def test_http_and_cli_region_threading(fed):
    """`?region=` on the HTTP API (and the SDK/CLI surfaces that emit
    it) forwards the request to the target region's servers."""
    from nomad_tpu.agent.http import HTTPServer
    from nomad_tpu.api import ApiClient
    from nomad_tpu.command.cli import main

    gl, wl = fed.leader("global"), fed.leader("west")
    job = mock.job()
    job.region = "west"
    wl.register_job(job)
    http = HTTPServer(_AgentShim(gl))
    http.start()
    try:
        addr = f"http://127.0.0.1:{http.port}"
        # SDK: region= rides every request as `?region=`
        west_api = ApiClient(addr, region="west")
        assert west_api.jobs.info(job.id).id == job.id
        assert west_api.system.regions() == ["global", "west"]
        # without the region the global servers answer from their own
        # store, where this job does not exist
        local_api = ApiClient(addr)
        assert job.id not in [j["ID"] for j in local_api.jobs.list()]
        # CLI: the global -region flag routes the same way
        import io
        out = io.StringIO()
        rc = main(["-address", addr, "-region", "west",
                   "job", "status", job.id], out=out)
        assert rc == 0
        assert job.id in out.getvalue()
    finally:
        http.stop()


# --------------------------------------------------- multiregion deployment


def test_multiregion_jobspec_parse():
    job = parse_job("""
    job "fleet" {
      datacenters = ["dc1"]
      multiregion {
        strategy {
          max_parallel = 1
          on_failure   = "fail_local"
        }
        region "global" { count = 3 }
        region "west" {
          count       = 2
          datacenters = ["dc2"]
        }
      }
      group "g" {
        task "t" { driver = "exec" }
      }
    }
    """)
    mr = job.multiregion
    assert mr is not None
    assert mr.strategy.max_parallel == 1
    assert mr.strategy.on_failure == "fail_local"
    assert mr.region_names() == ["global", "west"]
    assert mr.lookup("west").count == 2
    assert mr.lookup("west").datacenters == ["dc2"]


def test_multiregion_sequential_rollout():
    """Submitting a multiregion job registers only the first region; the
    next region is kicked only after the first's deployment succeeds,
    with per-region count overrides applied."""
    fc = make_fed(n=1)
    try:
        gl, wl = fc.leader("global"), fc.leader("west")
        for s in (gl, wl):
            for _ in range(4):
                s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        job.multiregion = Multiregion(regions=[
            MultiregionRegion(name="global", count=2),
            MultiregionRegion(name="west", count=1),
        ])
        gl.register_job(job)
        wait_for(lambda: gl.store.job_by_id("default", job.id) is not None,
                 msg="primary region registration")
        # region 2 must NOT be registered until region 1 succeeds
        assert wl.store.job_by_id("default", job.id) is None
        rollout = gl.store.job_by_id("default", job.id) \
            .meta["multiregion.rollout"]
        assert rollout
        d = drive_healthy(gl, "default", job.id)
        # SUCCESSFUL primary deployment kicks the next region exactly once
        wait_for(lambda: wl.store.job_by_id("default", job.id) is not None,
                 msg="rollout reached west")
        wjob = wl.store.job_by_id("default", job.id)
        assert wjob.region == "west"
        assert wjob.task_groups[0].count == 1          # count override
        assert wjob.meta["multiregion.rollout"] == rollout
        wait_for(lambda: gl.store.deployment_by_id(d.id).multiregion_kicked,
                 msg="kick flag replicated")
        # last region: completes without kicking anything further
        wd = drive_healthy(wl, "default", job.id)
        wait_for(lambda: wl.store.deployment_by_id(wd.id).multiregion_kicked,
                 msg="terminal region marked done")
    finally:
        fc.stop()


def test_multiregion_rollout_halts_at_partition_and_resumes():
    fc = make_fed(n=1)
    try:
        gl, wl = fc.leader("global"), fc.leader("west")
        for s in (gl, wl):
            for _ in range(4):
                s.register_node(mock.node())
        job = mock.job()
        job.multiregion = Multiregion(regions=[
            MultiregionRegion(name="global"),
            MultiregionRegion(name="west", count=1),
        ])
        fc.partition_region("west")
        gl.register_job(job)
        d = drive_healthy(gl, "default", job.id)
        # the kick cannot cross the partition: the rollout halts at the
        # region boundary without failing or corrupting anything
        time.sleep(1.0)
        assert wl.store.job_by_id("default", job.id) is None
        assert gl.store.deployment_by_id(d.id).status == \
            DeploymentStatus.SUCCESSFUL
        assert not gl.store.deployment_by_id(d.id).multiregion_kicked
        fc.heal_region("west")
        # ...and resumes after heal (the watcher retries the kick)
        wait_for(lambda: wl.store.job_by_id("default", job.id) is not None,
                 timeout=20.0, msg="rollout resumed post-heal")
        wait_for(lambda: gl.store.deployment_by_id(d.id).multiregion_kicked,
                 msg="kick flag set post-heal")
    finally:
        fc.stop()


def test_multiregion_failure_propagates_and_reverts_peer():
    """A failed deployment in one region fails the rollout's siblings:
    the peer region's already-SUCCESSFUL copy reverts to its latest
    stable version."""
    fc = make_fed(n=1)
    try:
        gl, wl = fc.leader("global"), fc.leader("west")
        for s in (gl, wl):
            for _ in range(4):
                s.register_node(mock.node())
        # v0: a plain stable job in the primary region (the revert target)
        job = mock.job()
        job.task_groups[0].count = 2
        gl.register_job(job)
        drive_healthy(gl, "default", job.id)
        gl.set_job_stability("default", job.id, 0, True)
        v0_config = dict(job.task_groups[0].tasks[0].config)
        # v1: a destructive multiregion update
        job2 = gl.store.job_by_id("default", job.id).copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        job2.multiregion = Multiregion(regions=[
            MultiregionRegion(name="global", count=2),
            MultiregionRegion(name="west", count=1),
        ])
        gl.register_job(job2)
        v1 = wait_for(lambda: gl.store.job_by_id("default", job.id).version
                      or None, msg="v1 registered")
        drive_healthy(gl, "default", job.id, min_version=v1)
        wait_for(lambda: wl.store.job_by_id("default", job.id) is not None,
                 msg="rollout reached west")
        # west's copy fails: its allocs report unhealthy
        wd = wait_for(lambda: wl.store.latest_deployment_by_job_id(
            "default", job.id), msg="west deployment")

        def fail_west():
            for a in wl.store.allocs_by_job("default", job.id):
                if not a.terminal_status():
                    u = a.copy()
                    u.client_status = AllocClientStatus.FAILED
                    u.deployment_status = {"healthy": False}
                    wl.endpoints.handle("Node.UpdateAlloc",
                                        {"allocs": [u]})
            d = wl.store.deployment_by_id(wd.id)
            return d is not None and d.status == DeploymentStatus.FAILED
        wait_for(fail_west, msg="west deployment failure")
        # the failure propagates back: global reverts to stable v0
        wait_for(lambda: gl.store.job_by_id("default", job.id)
                 .task_groups[0].tasks[0].config == v0_config,
                 timeout=20.0, msg="peer region reverted to stable")
        assert gl.store.job_by_id("default", job.id).version > job2.version
    finally:
        fc.stop()
