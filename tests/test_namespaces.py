"""Namespace tests (reference analogs: nomad/state/state_store_test.go
namespace cases, nomad/namespace_endpoint_test.go, api/namespace_test.go):
replicated CRUD, namespace-scoped job IDs, list threading + wildcard,
and unknown-namespace rejection at both RPC and HTTP layers."""
import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient, ApiError
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.rpc.endpoints import RpcError
from nomad_tpu.state import StateStore


# ------------------------------------------------------------------ store

def test_store_seeds_default_namespace():
    store = StateStore()
    names = [ns.name for ns in store.namespaces()]
    assert names == ["default"]


def test_store_namespace_crud():
    store = StateStore()
    store.upsert_namespace(1, "team-a", description="team a")
    ns = store.namespace("team-a")
    assert ns is not None and ns.description == "team a"
    assert ns.create_index == 1 and ns.modify_index == 1
    # upsert preserves create_index
    store.upsert_namespace(2, "team-a", description="renamed")
    ns = store.namespace("team-a")
    assert ns.create_index == 1 and ns.modify_index == 2
    assert ns.description == "renamed"
    store.delete_namespace(3, "team-a")
    assert store.namespace("team-a") is None


def test_store_default_namespace_undeletable():
    store = StateStore()
    with pytest.raises(ValueError):
        store.delete_namespace(1, "default")


def test_store_namespace_with_jobs_undeletable():
    store = StateStore()
    store.upsert_namespace(1, "busy")
    j = mock.job()
    j.namespace = "busy"
    store.upsert_job(2, j)
    with pytest.raises(ValueError):
        store.delete_namespace(3, "busy")


def test_namespace_scoped_job_ids():
    """The same job ID coexists in two namespaces without collision."""
    store = StateStore()
    store.upsert_namespace(1, "a")
    store.upsert_namespace(2, "b")
    ja = mock.job(id="shared-id")
    ja.namespace = "a"
    jb = mock.job(id="shared-id")
    jb.namespace = "b"
    store.upsert_job(3, ja)
    store.upsert_job(4, jb)
    assert store.job_by_id("a", "shared-id") is ja
    assert store.job_by_id("b", "shared-id") is jb
    store.delete_job(5, "a", "shared-id")
    assert store.job_by_id("a", "shared-id") is None
    assert store.job_by_id("b", "shared-id") is jb


# ------------------------------------------------------------------ server

def test_server_namespace_replicated_crud():
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        s.upsert_namespace("team-a", description="a", quota="")
        assert {ns.name for ns in s.namespaces()} == {"default", "team-a"}
        s.delete_namespace("team-a")
        assert {ns.name for ns in s.namespaces()} == {"default"}
    finally:
        s.stop()


def test_register_job_unknown_namespace_names_known_set():
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        s.upsert_namespace("known-ns")
        j = mock.job()
        j.namespace = "nope"
        with pytest.raises(RpcError) as e:
            s.register_job(j)
        assert "nope" in str(e.value)
        assert "known-ns" in str(e.value)      # error names the known set
    finally:
        s.stop()


def test_namespace_quota_must_exist():
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        with pytest.raises((RpcError, ValueError)):
            s.upsert_namespace("team-a", quota="missing-spec")
    finally:
        s.stop()


# ------------------------------------------------------------------ http

@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(http_port=0, num_schedulers=2,
                          heartbeat_ttl=60.0))
    a.start()
    for _ in range(4):
        a.server.register_node(mock.node())
    yield a
    a.stop()


@pytest.fixture(scope="module")
def api(agent):
    return ApiClient(agent.http_addr)


def test_http_namespace_crud(api):
    api.namespaces.register("team-http", description="via http")
    names = {ns["name"] for ns in api.namespaces.list()}
    assert {"default", "team-http"} <= names
    info = api.namespaces.info("team-http")
    assert info["description"] == "via http"
    with pytest.raises(ApiError) as e:
        api.namespaces.info("ghost")
    assert e.value.status == 404
    api.namespaces.delete("team-http")
    assert "team-http" not in {ns["name"] for ns in api.namespaces.list()}


def test_http_list_threading_and_wildcard(api, agent):
    api.namespaces.register("team-a")
    api.namespaces.register("team-b")
    ja = mock.job(id="ns-threaded-job")
    ja.namespace = "team-a"
    ja.task_groups[0].count = 2
    jb = mock.job(id="ns-threaded-job")
    jb.namespace = "team-b"
    jb.task_groups[0].count = 2
    api.jobs.register(ja)
    api.jobs.register(jb)
    agent.server.wait_for_idle(10.0)

    a_client = ApiClient(agent.http_addr, namespace="team-a")
    a_jobs = a_client.jobs.list()
    assert [j["ID"] for j in a_jobs] == ["ns-threaded-job"]
    assert all(j["Namespace"] == "team-a" for j in a_jobs)

    # wildcard sees both copies
    star = ApiClient(agent.http_addr, namespace="*")
    star_jobs = [j for j in star.jobs.list()
                 if j["ID"] == "ns-threaded-job"]
    assert {j["Namespace"] for j in star_jobs} == {"team-a", "team-b"}

    # evals and allocs thread the same parameter
    a_evals = a_client.evaluations.list()
    assert a_evals and all(e.namespace == "team-a" for e in a_evals)
    a_allocs = a_client.allocations.list()
    assert a_allocs and all(
        al["Namespace"] == "team-a" for al in a_allocs)

    # default-namespace view is not polluted
    assert "ns-threaded-job" not in [j["ID"] for j in api.jobs.list()]


def test_http_unknown_namespace_rejected_naming_known(api):
    bogus = ApiClient(api.address, namespace="no-such-ns")
    with pytest.raises(ApiError) as e:
        bogus.jobs.list()
    assert e.value.status == 400
    assert "no-such-ns" in str(e.value)
    assert "default" in str(e.value)           # names the known set


# ------------------------------------------------------------------ cli

def test_cli_namespace_flag_and_env(monkeypatch):
    from nomad_tpu.command.cli import build_parser
    p = build_parser()
    args = p.parse_args(["-namespace", "team-a", "job", "status"])
    assert args.namespace == "team-a"
    # the quota-usage positional must not clobber the global flag
    args = p.parse_args(["-namespace", "team-a", "quota", "usage"])
    assert args.namespace == "team-a" and not args.usage_ns
    # env default is captured at parser build time, like NOMAD_REGION
    monkeypatch.setenv("NOMAD_NAMESPACE", "from-env")
    args = build_parser().parse_args(["job", "status"])
    assert args.namespace == "from-env"
