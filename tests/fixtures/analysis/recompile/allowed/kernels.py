"""Same unregistered kernels, suppressed on the jit-site lines."""
import jax

_RECOMPILE_TRACKED = True


@jax.jit
def scan_kernel(x):                         # analysis: allow(recompile-budget) — fixture: exercises the suppression path
    return x * 2


bulk_kernel = jax.jit(lambda x: x + 1)      # analysis: allow(recompile-budget) — fixture: exercises the suppression path
