"""Every jit site registered: decorator form via a trailing register,
assign form via the same."""
import jax

from nomad_tpu.analysis import recompile

_RECOMPILE_TRACKED = True


@jax.jit
def scan_kernel(x):
    return x * 2


bulk_kernel = jax.jit(lambda x: x + 1)

recompile.register("fixture.scan", scan_kernel)
recompile.register("fixture.bulk", bulk_kernel)
