"""Two jitted kernels in a tracked module, neither handed to the
recompile budget registry."""
import jax

_RECOMPILE_TRACKED = True


@jax.jit
def scan_kernel(x):
    return x * 2


bulk_kernel = jax.jit(lambda x: x + 1)
