"""Same seeded violations as bad/, every one fenced with the allow
comment — including the call edge into `_stamp`, which must prune the
transitive finding behind it."""
import time as _time
import uuid


class MiniFSM:
    def __init__(self, store):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        payload["submit_time"] = _time.time()        # analysis: allow(fsm-determinism) — fixture: exercises the suppression path
        payload["id"] = str(uuid.uuid4())            # analysis: allow(fsm-determinism) — fixture: exercises the suppression path
        doomed = set(payload.get("doomed", ()))
        for d in doomed:                             # analysis: allow(fsm-determinism) — fixture: exercises the suppression path
            self.store.pop(d, None)
        self._stamp(payload)                         # analysis: allow(fsm-determinism) — fixture: exercises the suppression path

    def _stamp(self, payload):
        payload["nonce"] = uuid.uuid4().hex          # reached only via the allowed edge
