"""Deterministic apply cone: timestamps ride in the log payload and
set-like tables are iterated in sorted order."""


class MiniFSM:
    def __init__(self, store):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        payload.setdefault("submit_time", 0.0)       # stamped at propose time
        doomed = set(payload.get("doomed", ()))
        for d in sorted(doomed):
            self.store.pop(d, None)
