"""Seeded fsm-determinism violations: wall-clock, entropy (direct and
transitive), and unordered-set iteration inside the FSM apply cone."""
import time as _time
import uuid


class MiniFSM:
    def __init__(self, store):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        payload["submit_time"] = _time.time()        # wall-clock
        payload["id"] = str(uuid.uuid4())            # entropy
        doomed = set(payload.get("doomed", ()))
        for d in doomed:                             # unordered iteration
            self.store.pop(d, None)
        self._stamp(payload)

    def _stamp(self, payload):
        payload["nonce"] = uuid.uuid4().hex          # transitive entropy
