"""Injection sites: one names an unregistered point; nothing fires
`dead.point`."""
import chaos


def rpc_send(msg):
    if chaos.active is not None and chaos.active.should("rpc.drop"):
        return False
    chaos.fire("unknown.point")              # not in FAULT_POINTS
    return True


def commit_plan(plan):
    chaos.fire("plan.crash")
    return plan


def tick(node_id):
    chaos.fire("node.churn_kill")            # pin names heartbeat
    return node_id
