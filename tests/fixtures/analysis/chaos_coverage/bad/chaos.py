"""Fixture chaos registry with one dead point, a required site pinned
to a function that does not carry it, and a required point that is not
registered at all."""

FAULT_POINTS = ("rpc.drop", "plan.crash", "dead.point",
                "node.churn_kill")

REQUIRED_SITES = {
    "plan.crash": ("apply_plan",),      # commit_plan fires it, not apply_plan
    "ghost.point": ("rpc_send",),       # not in FAULT_POINTS
    "node.churn_kill": ("heartbeat",),  # fired in tick, not heartbeat
}


class ChaosRegistry:
    def should(self, point):
        return False


active = None
