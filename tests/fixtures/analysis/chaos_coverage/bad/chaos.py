"""Fixture chaos registry with one dead point."""

FAULT_POINTS = ("rpc.drop", "plan.crash", "dead.point")


class ChaosRegistry:
    def should(self, point):
        return False


active = None
