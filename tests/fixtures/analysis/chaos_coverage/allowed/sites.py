"""The unregistered-point site, suppressed on its line."""
import chaos


def rpc_send(msg):
    if chaos.active is not None and chaos.active.should("rpc.drop"):
        return False
    chaos.fire("unknown.point")              # analysis: allow(chaos-coverage) — fixture: exercises the suppression path
    return True


def commit_plan(plan):
    chaos.fire("plan.crash")
    return plan


def tick(node_id):
    chaos.fire("node.churn_kill")            # pin suppressed at REQUIRED_SITES
    return node_id
