"""Registry with a dead point and an unmet required site, each
suppressed at its declaration line."""

FAULT_POINTS = ("rpc.drop", "plan.crash", "dead.point", "node.churn_kill")   # analysis: allow(chaos-coverage) — fixture: exercises the suppression path

REQUIRED_SITES = {"plan.crash": ("apply_plan",), "node.churn_kill": ("heartbeat",)}   # analysis: allow(chaos-coverage) — fixture: exercises the suppression path


class ChaosRegistry:
    def should(self, point):
        return False


active = None
