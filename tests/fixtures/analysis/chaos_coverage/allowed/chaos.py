"""Registry with a dead point, suppressed at the declaration line."""

FAULT_POINTS = ("rpc.drop", "plan.crash", "dead.point")   # analysis: allow(chaos-coverage)


class ChaosRegistry:
    def should(self, point):
        return False


active = None
