"""Registry and sites in agreement."""

FAULT_POINTS = ("rpc.drop", "plan.crash")


class ChaosRegistry:
    def should(self, point):
        return False


active = None
