"""Registry and sites in agreement, required site satisfied."""

FAULT_POINTS = ("rpc.drop", "plan.crash", "node.churn_kill")

REQUIRED_SITES = {"plan.crash": ("commit_plan",),
                  "node.churn_kill": ("heartbeat",)}


class ChaosRegistry:
    def should(self, point):
        return False


active = None
