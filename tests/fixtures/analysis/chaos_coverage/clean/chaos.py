"""Registry and sites in agreement, required site satisfied."""

FAULT_POINTS = ("rpc.drop", "plan.crash")

REQUIRED_SITES = {"plan.crash": ("commit_plan",)}


class ChaosRegistry:
    def should(self, point):
        return False


active = None
