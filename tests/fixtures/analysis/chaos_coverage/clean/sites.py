"""Every registered point has a site, every site is registered."""
import chaos


def rpc_send(msg):
    if chaos.active is not None and chaos.active.should("rpc.drop"):
        return False
    return True


def commit_plan(plan):
    chaos.fire("plan.crash")
    return plan
