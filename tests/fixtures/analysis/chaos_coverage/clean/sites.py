"""Every registered point has a site, every site is registered."""
import chaos


def rpc_send(msg):
    if chaos.active is not None and chaos.active.should("rpc.drop"):
        return False
    return True


def commit_plan(plan):
    chaos.fire("plan.crash")
    return plan


def heartbeat(node_id):
    # swallow the re-arm: the node misses its TTL under a churn storm
    if chaos.active is not None and chaos.active.should("node.churn_kill"):
        return None
    return node_id
