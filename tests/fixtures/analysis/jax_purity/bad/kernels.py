"""Seeded jax-purity violations: host coercion, eager numpy, tracer
branching, and a transitive .item() through a call-form jit."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_norm(x):
    total = float(x.sum())                   # host coercion of a tracer
    arr = np.asarray(x)                      # eager numpy at trace time
    return x / (total + arr.shape[0])


@functools.partial(jax.jit, static_argnames=("k",))
def bad_gate(scores, k):
    if scores > 0:                           # branch on traced param
        return scores * k
    return scores


def _pull(x):
    return x.item()                          # transitive host pull


def body(x):
    return _pull(x) + 1


kernel = jax.jit(body)
