"""Same escapes as bad/, each fenced with the allow comment."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_norm(x):
    total = float(x.sum())                   # analysis: allow(jax-purity) — fixture: exercises the suppression path
    arr = np.asarray(x)                      # analysis: allow(jax-purity) — fixture: exercises the suppression path
    return x / (total + arr.shape[0])


@functools.partial(jax.jit, static_argnames=("k",))
def bad_gate(scores, k):
    if scores > 0:                           # analysis: allow(jax-purity) — fixture: exercises the suppression path
        return scores * k
    return scores


def _pull(x):
    return x.item()                          # analysis: allow(jax-purity) — fixture: exercises the suppression path


def body(x):
    return _pull(x) + 1


kernel = jax.jit(body)
