"""Traceable kernels: jnp everywhere, branching only on statics."""
import functools

import jax
import jax.numpy as jnp


@jax.jit
def norm(x):
    return x / jnp.sum(x)


@functools.partial(jax.jit, static_argnames=("k",))
def gate(scores, k):
    if k > 2:                                # static: resolved at trace time
        scores = scores * 2.0
    return jnp.where(scores > 0, scores, 0.0)
