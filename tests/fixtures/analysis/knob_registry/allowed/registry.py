"""Fixture registry: the dead entry carries a reasoned allow."""


class Knob:
    def __init__(self, default, kind, doc):
        self.default, self.kind, self.doc = default, kind, doc


_KNOB_REGISTRY = True

KNOBS = {
    "NOMAD_TPU_BETA": Knob("2", "int", "beta factor"),
    "NOMAD_TPU_RETIRED": Knob("0", "int", "retired"),  # analysis: allow(knob-registry) — kept one release for rollback compatibility
}
