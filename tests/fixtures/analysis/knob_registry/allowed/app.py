"""Fixture app: every violation carries a reasoned allow."""
import os


def reads(knobs):
    beta = knobs.get_int("NOMAD_TPU_BETA")
    legacy = os.environ.get("NOMAD_TPU_LEGACY")  # analysis: allow(knob-registry) — migration shim reads the retired spelling once at import
    probe = knobs.get_str("NOMAD_TPU_PROBE")  # analysis: allow(knob-registry) — probe knob is injected by the chaos harness, never registered
    return beta, legacy, probe
