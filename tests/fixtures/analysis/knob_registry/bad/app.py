"""Fixture app: raw environ reads and an unregistered accessor."""
import os

env = os.environ


def raw_reads():
    a = os.environ.get("NOMAD_TPU_RAW_GET")
    b = os.getenv("NOMAD_TPU_RAW_GETENV", "0")
    c = env.pop("NOMAD_TPU_RAW_ALIAS", None)
    os.environ["NOMAD_TPU_RAW_WRITE"] = "1"
    return a, b, c


def accessor_reads(knobs):
    alpha = knobs.get_int("NOMAD_TPU_ALPHA")
    undoc = knobs.get_bool("NOMAD_TPU_UNDOC")
    ghost = knobs.get_str("NOMAD_TPU_GHOST")
    return alpha, undoc, ghost
