"""Fixture knob registry with one dead and one undocumented entry."""


class Knob:
    def __init__(self, default, kind, doc):
        self.default, self.kind, self.doc = default, kind, doc


_KNOB_REGISTRY = True

KNOBS = {
    "NOMAD_TPU_ALPHA": Knob("1", "int", "alpha factor"),
    "NOMAD_TPU_DEAD": Knob("0", "int", "never read anywhere"),
    "NOMAD_TPU_UNDOC": Knob("0", "bool", "missing from the README"),
}
