"""Fixture app: registered knobs read only through typed accessors."""


def reads(knobs):
    alpha = knobs.get_int("NOMAD_TPU_ALPHA")
    gamma = knobs.get_float("NOMAD_TPU_GAMMA")
    return alpha, gamma
