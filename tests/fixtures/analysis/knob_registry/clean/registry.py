"""Fixture registry: every entry is read through an accessor."""


class Knob:
    def __init__(self, default, kind, doc):
        self.default, self.kind, self.doc = default, kind, doc


_KNOB_REGISTRY = True

KNOBS = {
    "NOMAD_TPU_ALPHA": Knob("1", "int", "alpha factor"),
    "NOMAD_TPU_GAMMA": Knob("0.5", "float", "gamma damping"),
}
