"""Fixture reserved-key contract with seeded declaration defects."""

_RESERVED_KEYS = {
    "_trace": "trace context",
    "_deadline": "deadline budget",
    "_ghost": "registered but never used anywhere",
}

_THREAD_KEYS = ("_trace", "_deadline")

_FORWARDING_SITES = {
    "Router.forward": ("forward", ("_deadline",)),
    "Router.originate": ("origin", ("_deadline",)),
    "Router.vanished": ("forward", ("_deadline",)),
}

_ALLOWED_STRIPS = {}

_WIRE_HEADERS = {"X-Fixture-Deadline": "_deadline"}
