"""Fixture forwarding sites that drop reserved keys."""


class Router:
    def forward(self, args):
        out = dict(args)
        out.pop("_deadline", None)
        out["_mystery"] = 1
        out = {k: v for k, v in out.items() if not k.startswith("_")}
        return self.send(out)

    def originate(self, req):
        fresh = {"op": req.op}
        fresh["_deadline"] = req.budget
        return self.send(fresh)

    def helper(self, args):
        args.pop("_trace", None)
        return args
