"""Fixture site honoring the contract: alias stamp, declared strip,
wire-header stamp, and a restamp covering the thread keys."""

DEADLINE_KEY = "_deadline"


def restamp(args):
    args.setdefault("_trace", "trace-0")
    args.setdefault("_deadline", 9.0)
    return args


class Router:
    def forward(self, args):
        out = dict(args)
        out.pop("_trace", None)
        out[DEADLINE_KEY] = args.get(DEADLINE_KEY)
        headers = {}
        self.stamp(headers, "X-Fixture-Deadline", args.get(DEADLINE_KEY))
        restamp(out)
        return self.send(out, headers)
