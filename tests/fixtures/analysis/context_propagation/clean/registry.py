"""Fixture contract with a declared strip and a wire header."""

_RESERVED_KEYS = {
    "_trace": "trace context",
    "_deadline": "deadline budget",
}

_THREAD_KEYS = ("_trace", "_deadline")

_FORWARDING_SITES = {
    "Router.forward": ("forward", ("_deadline", "_trace")),
}

_ALLOWED_STRIPS = {"Router.forward": ("_trace",)}

_WIRE_HEADERS = {"X-Fixture-Deadline": "_deadline"}
