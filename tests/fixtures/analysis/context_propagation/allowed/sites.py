"""Fixture site: every violation carries a reasoned allow."""


class Router:
    def forward(self, args):
        out = dict(args)
        out["_deadline"] = args.get("_deadline")
        out.pop("_trace", None)  # analysis: allow(context-propagation) — trace is re-derived from wire headers on the next hop
        out = {k: v for k, v in out.items() if k != "payload"}  # analysis: allow(context-propagation) — filter drops only the payload key; reserved keys pass through
        return self.send(out)
