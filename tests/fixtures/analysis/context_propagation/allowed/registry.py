"""Fixture contract: the retired key carries a reasoned allow."""

_RESERVED_KEYS = {
    "_trace": "trace context",
    "_deadline": "deadline budget",
    "_legacy": "retired",  # analysis: allow(context-propagation) — retired key stays registered until the v2 wire format lands
}

_THREAD_KEYS = ("_trace", "_deadline")

_FORWARDING_SITES = {
    "Router.forward": ("forward", ("_deadline",)),
}

_ALLOWED_STRIPS = {}

_WIRE_HEADERS = {}
