"""Hot-path module doing it right: the module is a sanctioned upload
site, operands ship via explicit device_put, results drain via explicit
device_get."""
import jax
import numpy as np

_TRANSFER_HOT_PATH = True
_TRANSFER_UPLOAD_SITE = True


@jax.jit
def scatter_kernel(basis, rows):
    return basis + rows


def upload(basis):
    return jax.device_put(basis)


def dispatch(basis_dev):
    rows = np.zeros((4, 2), np.float32)
    rows_dev = jax.device_put(rows)
    return scatter_kernel(basis_dev, rows_dev)


def drain(out_dev):
    return np.asarray(jax.device_get(out_dev))
