"""Seeded transfer-purity violations on a declared hot-path module:
an unsanctioned upload, four flavors of implicit device->host sync, and
a numpy operand smuggled into a jitted kernel."""
import jax
import numpy as np

_TRANSFER_HOT_PATH = True


@jax.jit
def scatter_kernel(basis, rows):
    return basis + rows


def upload(basis):
    return jax.device_put(basis)            # not an upload site


def drain(out_dev):
    total = float(out_dev)                  # host coercion
    first = out_dev.item()                  # .item() sync
    host = np.asarray(out_dev)              # implicit sync
    if out_dev:                             # __bool__ sync
        total += 1
    return total, first, host


def dispatch(basis_dev):
    rows = np.zeros((4, 2), np.float32)
    return scatter_kernel(basis_dev, rows)  # implicit host->device
