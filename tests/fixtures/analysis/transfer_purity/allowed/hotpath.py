"""Same escapes as bad/, each fenced with the allow comment."""
import jax
import numpy as np

_TRANSFER_HOT_PATH = True


@jax.jit
def scatter_kernel(basis, rows):
    return basis + rows


def upload(basis):
    return jax.device_put(basis)            # analysis: allow(transfer-purity) — fixture: exercises the suppression path


def drain(out_dev):
    total = float(out_dev)                  # analysis: allow(transfer-purity) — fixture: exercises the suppression path
    first = out_dev.item()                  # analysis: allow(transfer-purity) — fixture: exercises the suppression path
    host = np.asarray(out_dev)              # analysis: allow(transfer-purity) — fixture: exercises the suppression path
    if out_dev:                             # analysis: allow(transfer-purity) — fixture: exercises the suppression path
        total += 1
    return total, first, host


def dispatch(basis_dev):
    rows = np.zeros((4, 2), np.float32)
    return scatter_kernel(basis_dev, rows)  # analysis: allow(transfer-purity) — fixture: exercises the suppression path
