"""Seeded wait-graph violations: an opposite-order nesting cycle, locks
held across blocking calls (directly and through a callee), and a
reasonless _LOCK_BLOCKING_OK declaration."""
import os
import threading


class Pair:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def ba(self):
        with self._lb:
            with self._la:               # opposite order -> cycle
                pass

    def flush(self, fd):
        with self._la:
            os.fsync(fd)                 # held across fsync

    def drain(self, fd):
        with self._lb:
            self._sync(fd)               # held across callee's fsync

    def _sync(self, fd):
        os.fsync(fd)


class Wal:
    _LOCK_BLOCKING_OK = {"_lock": ""}    # reasonless declaration

    def __init__(self):
        self._lock = threading.Lock()
