"""The same hazards, suppressed: the reverse-order acquire and the
held-blocking sites carry reasoned allows, and the WAL lock declares why
it may span its fsync."""
import os
import threading


class Pair:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def ba(self):
        with self._lb:
            with self._la:   # analysis: allow(wait-graph) — shutdown-only path, never concurrent with ab (guarded by the stopped flag)
                pass

    def flush(self, fd):
        with self._la:   # analysis: allow(wait-graph) — flush is the lock's purpose; contenders need the fsync ordering
            os.fsync(fd)

    def drain(self, fd):
        with self._lb:   # analysis: allow(wait-graph) — drain serializes the final sync on shutdown
            self._sync(fd)

    def _sync(self, fd):
        os.fsync(fd)


class Wal:
    _LOCK_BLOCKING_OK = {
        "_lock": "append+fsync must stay atomic per record",
    }

    def __init__(self):
        self._lock = threading.Lock()

    def append(self, fd):
        with self._lock:
            os.fsync(fd)
