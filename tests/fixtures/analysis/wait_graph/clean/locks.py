"""Disciplined locking: one global nesting order, blocking I/O only
under a lock declared (with reason) to serialize it, and condition
waits only on the condition wrapping the held lock."""
import os
import threading


class Pair:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:
                pass

    def also_ab(self):
        with self._la:
            with self._lb:
                pass


class Wal:
    _LOCK_BLOCKING_OK = {
        "_lock": "append+fsync must stay atomic per record",
    }

    def __init__(self):
        self._lock = threading.Lock()

    def append(self, fd):
        with self._lock:
            os.fsync(fd)


class Queue:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items = []

    def get(self):
        with self._cv:
            while not self._items:
                self._cv.wait()          # waits on the held lock's cv
            return self._items.pop(0)
