"""A healthy suppression: reasoned, and consulted by the checker it
names during the run."""
import time as _time


class MiniFSM:
    def __init__(self, store):
        self.store = store

    def apply(self, index, msg_type, payload):
        self._apply_touch(index, payload)

    def _apply_touch(self, index, payload):
        payload["t"] = _time.time()   # analysis: allow(fsm-determinism) — fixture keeps the legacy stamp-in-apply shape; propose pre-stamps in production
