"""Seeded allow-audit violations: a reasonless allow (even though it
suppresses a real finding), a dead named allow, and a dead allow(*)."""
import time as _time


class MiniFSM:
    def __init__(self, store):
        self.store = store

    def apply(self, index, msg_type, payload):
        self._apply_touch(index, payload)

    def _apply_touch(self, index, payload):
        payload["t"] = _time.time()   # analysis: allow(fsm-determinism)
        limit = 1                     # analysis: allow(lock-discipline) — nothing here ever needed suppressing
        return limit                  # analysis: allow(*) — stale blanket suppression left behind
