"""An allow that names allow-audit itself opts out of the unused-allow
check (its finding only exists at runtime), but still needs a reason."""


def prestamp(payload):
    payload["t"] = 0.0   # analysis: allow(fsm-determinism, allow-audit) — the runtime replay gate flags this path; the static cone cannot reach it from any FSM
    return payload
