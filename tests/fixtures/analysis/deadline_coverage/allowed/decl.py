"""Fixture contract: the reserved stage carries a reasoned allow."""

_DEADLINE_STAGES = (
    "rpc",
    "ghost",  # analysis: allow(deadline-coverage) — stage reserved for the next release's federation hop
)

_SERVING_ROOTS = ("Server.handle",)

_SERVING_MODULES = ("serving",)
