"""Fixture serving path: every violation carries a reasoned allow."""


class Server:
    def handle(self, req):
        deadline = req.deadline
        deadline.check("rpc")
        deadline.check(req.stage)  # analysis: allow(deadline-coverage) — stage names come from the closed dispatch table above
        return self.park(req)

    def park(self, req):
        self.ready.wait()  # analysis: allow(deadline-coverage) — startup barrier, armed before serving begins
        return req
