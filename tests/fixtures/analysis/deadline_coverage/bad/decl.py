"""Fixture deadline contract with a dead stage seeded."""

_DEADLINE_STAGES = ("rpc", "queue", "ghost")

_SERVING_ROOTS = ("Server.handle",)

_SERVING_MODULES = ("serving",)
