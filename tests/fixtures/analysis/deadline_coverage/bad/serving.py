"""Fixture serving path with unchecked waits in the cone."""
import time


class Server:
    def handle(self, req):
        deadline = req.deadline
        deadline.check("rpc")
        deadline.check("queue")
        deadline.check(req.stage)
        deadline.check("unknown")
        self.park(req)
        return self.drain(req)

    def park(self, req):
        self.ready.wait()
        return self.inbox.get()

    def drain(self, req):
        while not self.done:
            time.sleep(0.05)
        return self.fut.result()
