"""Fixture deadline contract, fully honored."""

_DEADLINE_STAGES = ("rpc", "queue")

_SERVING_ROOTS = ("Server.handle",)

_SERVING_MODULES = ("serving",)
