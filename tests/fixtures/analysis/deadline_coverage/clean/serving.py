"""Fixture serving path: every wait consults the deadline."""


class Server:
    def handle(self, req):
        deadline = req.deadline
        deadline.check("rpc")
        return self.park(req, deadline)

    def park(self, req, deadline):
        deadline.check("queue")
        rem = deadline.remaining()
        self.ready.wait(rem)
        return self.inbox.get(timeout=rem)
