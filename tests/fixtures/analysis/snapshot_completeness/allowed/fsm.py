"""The same seeded drift, every finding suppressed with a reasoned
allow — on the finding's line or its enclosing def line."""
import pickle
import threading


class MiniStore:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({
        "_jobs", "_orphans", "_ghost", "_phantom", "_by_job"})
    _SNAPSHOT_DERIVED = {   # analysis: allow(snapshot-completeness) — fixture models a half-migrated declaration
        "_by_job": "_index_job_locked",
        "_absent": "_no_such_builder",
    }

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}
        self._orphans = {}
        self._ghost = {}
        self._phantom = {}
        self._by_job = {}

    def _index_job_locked(self, job):   # analysis: allow(snapshot-completeness) — builder kept for the next migration step
        self._by_job[job["id"]] = job["name"]


class MiniFSM:
    def __init__(self, store: MiniStore):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        job = payload["job"]
        self.store._jobs[job["id"]] = job
        self.store._orphans[job["id"]] = index   # analysis: allow(snapshot-completeness) — debug counter, deliberately process-local

    def snapshot(self):   # analysis: allow(snapshot-completeness) — legacy record shape frozen until the format version bump
        s = self.store
        return pickle.dumps({
            "jobs": dict(s._jobs),
            "ghost": dict(s._ghost),
            "legacy": 1,
        })

    def restore(self, blob):   # analysis: allow(snapshot-completeness) — restore still speaks the pre-migration record
        data = pickle.loads(blob)
        s = self.store
        s._jobs = dict(data["jobs"])
        s._phantom = {"seen": True}
        if data.get("missing"):
            s._jobs.clear()
        for job in s._jobs.values():
            s._by_job[job["id"]] = job["name"]
