"""A complete snapshot round trip: every mutated table is persisted and
restored, derived indexes rebuild through the one shared builder the
apply path also uses, and the ephemeral cache is declared."""
import pickle
import threading


class MiniStore:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs", "_by_job", "_cache"})
    _SNAPSHOT_DERIVED = {"_by_job": "_index_job_locked"}
    _SNAPSHOT_EPHEMERAL = frozenset({"_cache"})

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}
        self._by_job = {}
        self._cache = None

    def _index_job_locked(self, job):
        self._by_job[job["id"]] = job["name"]


class MiniFSM:
    def __init__(self, store: MiniStore):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        job = payload["job"]
        self.store._jobs[job["id"]] = job
        self.store._index_job_locked(job)
        self.store._cache = None

    def snapshot(self):
        s = self.store
        return pickle.dumps({"jobs": dict(s._jobs)})

    def restore(self, blob):
        data = pickle.loads(blob)
        s = self.store
        s._jobs = dict(data["jobs"])
        s._by_job = {}
        for job in s._jobs.values():
            s._index_job_locked(job)
