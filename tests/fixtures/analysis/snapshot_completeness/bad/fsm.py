"""Seeded snapshot-completeness violations: a write-only table, a
persist-only table, a restore-only table, record-key drift both ways, an
inline derived-index rebuild, and builder-declaration drift (missing
method, unreachable from restore, incremental yet unshared with apply)."""
import pickle
import threading


class MiniStore:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({
        "_jobs", "_orphans", "_ghost", "_phantom", "_by_job"})
    _SNAPSHOT_DERIVED = {
        "_by_job": "_index_job_locked",
        "_absent": "_no_such_builder",
    }

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}
        self._orphans = {}
        self._ghost = {}
        self._phantom = {}
        self._by_job = {}

    def _index_job_locked(self, job):
        self._by_job[job["id"]] = job["name"]


class MiniFSM:
    def __init__(self, store: MiniStore):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        job = payload["job"]
        self.store._jobs[job["id"]] = job
        self.store._orphans[job["id"]] = index       # write-only table

    def snapshot(self):
        s = self.store
        return pickle.dumps({
            "jobs": dict(s._jobs),
            "ghost": dict(s._ghost),                 # persist-only table
            "legacy": 1,                             # key never read back
        })

    def restore(self, blob):
        data = pickle.loads(blob)
        s = self.store
        s._jobs = dict(data["jobs"])
        s._phantom = {"seen": True}                  # restore-only table
        if data.get("missing"):                      # key never written
            s._jobs.clear()
        for job in s._jobs.values():
            s._by_job[job["id"]] = job["name"]       # inline rebuild
