"""Seeded lock-discipline violation: a declared protected table written
outside the lock."""
import threading


class Store:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs"})

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}

    def put(self, job_id, job):
        self._jobs[job_id] = job                     # no lock held
