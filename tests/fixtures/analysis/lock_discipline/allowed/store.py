"""The same unlocked write, suppressed by the allow comment."""
import threading


class Store:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs"})

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}

    def put(self, job_id, job):
        self._jobs[job_id] = job                     # analysis: allow(lock-discipline) — fixture: exercises the suppression path
