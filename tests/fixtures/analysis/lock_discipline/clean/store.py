"""Disciplined accesses: under `with self._lock`, in a @requires_lock
helper, or in the owner's __init__ (construction precedes sharing)."""
import threading

from nomad_tpu.utils import requires_lock


class Store:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs"})

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}

    def put(self, job_id, job):
        with self._lock:
            self._jobs[job_id] = job

    @requires_lock("_lock")
    def _put_locked(self, job_id, job):
        self._jobs[job_id] = job
