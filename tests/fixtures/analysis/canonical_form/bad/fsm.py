"""Seeded canonical-form violations: a set pickled into the snapshot
record, an id()-keyed table, hash-order float accumulation, a
read-path defaultdict materialization, and _CANONICAL drift (missing
canonicalizer + an in-place mutation bypassing the declared one)."""
import pickle
import threading
from collections import defaultdict


class MiniStore:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs", "_tags", "_usage", "_counts"})
    _CANONICAL = {
        "_counts": "_counts_add",
        "_ghost": "_no_such_canonicalizer",
    }

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}
        self._tags = set()
        self._weights = set()
        self._usage = defaultdict(dict)
        self._counts = {}

    def _counts_add(self, key, delta):
        total = self._counts.get(key, 0) + delta
        if total:
            self._counts[key] = total
        else:
            self._counts.pop(key, None)

    def bump(self, key):
        self._counts[key] = self._counts.get(key, 0) + 1   # bypass


class MiniFSM:
    def __init__(self, store: MiniStore):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        job = payload["job"]
        s = self.store
        s._jobs[id(job)] = job                       # id()-keyed row
        s._tags.add(job["tag"])
        job["weight"] = sum(s._weights)              # hash-order fold

    def snapshot(self):
        s = self.store
        return pickle.dumps({
            "jobs": dict(s._jobs),
            "tags": list(s._tags),                   # hash-order pickle
        })

    def restore(self, blob):
        data = pickle.loads(blob)
        s = self.store
        s._jobs = dict(data["jobs"])
        s._tags = set(data["tags"])

    def usage_for(self, namespace):
        return self.store._usage[namespace]          # read materializes
