"""Canonical replication state: sorted set persistence, value-keyed
rows, order-independent accumulation, .get() on read paths, and every
_counts mutation routed through the declared canonicalizer."""
import pickle
import threading
from collections import defaultdict


class MiniStore:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs", "_tags", "_usage", "_counts"})
    _CANONICAL = {"_counts": "_counts_add"}

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}
        self._tags = set()
        self._weights = set()
        self._usage = defaultdict(dict)
        self._counts = {}

    def _counts_add(self, key, delta):
        total = self._counts.get(key, 0) + delta
        if total:
            self._counts[key] = total
        else:
            self._counts.pop(key, None)


class MiniFSM:
    def __init__(self, store: MiniStore):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):
        job = payload["job"]
        s = self.store
        s._jobs[job["id"]] = job
        s._tags.add(job["tag"])
        job["weight"] = sum(sorted(s._weights))
        s._counts_add(job["id"], 1)

    def snapshot(self):
        s = self.store
        return pickle.dumps({
            "jobs": dict(s._jobs),
            "tags": sorted(s._tags),
        })

    def restore(self, blob):
        data = pickle.loads(blob)
        s = self.store
        s._jobs = dict(data["jobs"])
        s._tags = set(data["tags"])

    def usage_for(self, namespace):
        return self.store._usage.get(namespace, {})
