"""The same canonical-form drift, each site suppressed with a reasoned
allow on the finding line (or its enclosing def line)."""
import pickle
import threading
from collections import defaultdict


class MiniStore:
    _LOCK_NAME = "_lock"
    _LOCK_PROTECTED = frozenset({"_jobs", "_tags", "_usage", "_counts"})
    _CANONICAL = {   # analysis: allow(canonical-form) — fixture models a half-migrated declaration
        "_counts": "_counts_add",
        "_ghost": "_no_such_canonicalizer",
    }

    def __init__(self):
        self._lock = threading.RLock()
        self._jobs = {}
        self._tags = set()
        self._weights = set()
        self._usage = defaultdict(dict)
        self._counts = {}

    def _counts_add(self, key, delta):
        total = self._counts.get(key, 0) + delta
        if total:
            self._counts[key] = total
        else:
            self._counts.pop(key, None)

    def bump(self, key):
        self._counts[key] = self._counts.get(key, 0) + 1   # analysis: allow(canonical-form) — single-threaded bootstrap path, runs before replication starts

    def reset_usage(self, namespace):
        return self._usage[namespace]   # analysis: allow(canonical-form) — materialization deliberate: the namespace row must exist after this call


class MiniFSM:
    def __init__(self, store: MiniStore):
        self.store = store

    def apply(self, index, msg_type, payload):
        if msg_type == "job":
            self._apply_job(index, payload)

    def _apply_job(self, index, payload):   # analysis: allow(canonical-form) — legacy payload shape kept until the format version bump
        job = payload["job"]
        s = self.store
        s._jobs[id(job)] = job
        s._tags.add(job["tag"])
        job["weight"] = sum(s._weights)

    def snapshot(self):
        s = self.store
        return pickle.dumps({
            "jobs": dict(s._jobs),
            "tags": list(s._tags),   # analysis: allow(canonical-form) — tag order normalized by the consumer on load
        })

    def restore(self, blob):
        data = pickle.loads(blob)
        s = self.store
        s._jobs = dict(data["jobs"])
        s._tags = set(data["tags"])
