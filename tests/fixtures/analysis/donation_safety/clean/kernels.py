"""Fixture donate site with its protocol declared."""
import jax


def _place(basis, delta):
    return basis + delta


place_donate = jax.jit(_place, donate_argnums=(0,))

_DONATE_PROTOCOL = {
    "place_donate": "arg 0 is the loaned basis; caller adopts the output",
}
