"""Fixture loan flow honoring the protocol: adopt on success,
invalidate on failure, no reads between dispatch and adoption."""


class Engine:
    def dispatch(self, world, delta):
        loaned = world.loan_basis()
        try:
            out = self.place(delta, loaned)
            world.adopt_basis(out)
        except Exception:
            world.invalidate_basis()
            raise
        return out
