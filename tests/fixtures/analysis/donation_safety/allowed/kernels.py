"""Fixture donate sites: every violation carries a reasoned allow."""
import jax


def _scatter(basis, delta):
    return basis + delta


scatter_donate = jax.jit(_scatter, donate_argnums=(0,))  # analysis: allow(donation-safety) — contract documented in the module docstring pending registry migration

_DONATE_PROTOCOL = {
    "retired_site": "removed jit site",  # analysis: allow(donation-safety) — entry kept declared one release for the changelog
}
