"""Fixture loan flows: every violation carries a reasoned allow."""


class Engine:
    def fire_and_forget(self, world, delta):
        loaned = world.loan_basis()  # analysis: allow(donation-safety) — adoption happens in the completion callback registered by place()
        return self.place(delta, loaned)

    def debug_probe(self, world, delta):
        loaned = world.loan_basis()
        out = self.place(delta, loaned)
        shape = loaned.shape  # analysis: allow(donation-safety) — .shape reads host-side metadata, not the donated device buffer
        world.adopt_basis(out)
        return shape
