"""Fixture loan/adopt flows that misuse donated buffers."""


class Engine:
    def dispatch_no_adopt(self, world, delta):
        loaned = world.loan_basis()
        return self.place(delta, loaned)

    def read_after_dispatch(self, world, delta):
        loaned = world.loan_basis()
        basis = loaned
        out = self.place(delta, basis)
        norm = self.norm(basis)
        world.adopt_basis(out)
        return norm

    def cache_alias(self, world, delta):
        loaned = world.loan_basis()
        self.cache["basis"] = loaned
        out = self.place(delta, loaned)
        world.adopt_basis(out)
        return out
