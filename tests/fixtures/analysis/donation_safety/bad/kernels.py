"""Fixture donate sites missing their protocol declarations."""
import jax


def _place(basis, delta):
    return basis + delta


place_donate = jax.jit(_place, donate_argnums=(0,))

maybe_donate = jax.jit(_place, donate_argnums=(0,)) if True \
    else jax.jit(_place)

_DONATE_PROTOCOL = {
    "phantom": "declared but no such jit site exists",
}
