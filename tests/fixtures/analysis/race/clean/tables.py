"""Declaration and hooks in agreement: the traced attribute and its
lock both exist, every declared key is hooked, every hook is declared."""
import threading

from nomad_tpu.analysis import race


class Store:
    _RACE_TRACED = {"_ring": "_lock"}

    def __init__(self):
        self._ring = []
        self._lock = threading.Lock()

    def put(self, x):
        with self._lock:
            race.write("Store._ring", self)
            self._ring.append(x)

    def snapshot(self):
        with self._lock:
            race.read("Store._ring", self)
            return list(self._ring)
