"""Same drift as bad/, suppressed at the declaration and hook lines."""
import threading

from nomad_tpu.analysis import race


class BadDecl:
    _RACE_TRACED = ["_ring"]                # analysis: allow(happens-before) — fixture: exercises the suppression path

    def __init__(self):
        self._ring = []


class Store:
    _RACE_TRACED = {"_ring": "_lock", "_ghost": "_lock2"}   # analysis: allow(happens-before) — fixture: exercises the suppression path

    def __init__(self):
        self._ring = []
        self._lock = threading.Lock()

    def put(self, x):
        with self._lock:
            race.write("Store._ring", self)
            self._ring.append(x)


def rogue(obj):
    race.read("Phantom._tbl", obj)          # analysis: allow(happens-before) — fixture: exercises the suppression path
