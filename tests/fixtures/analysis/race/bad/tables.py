"""Seeded happens-before drift: a malformed declaration, a declared
attribute and lock the class never assigns, a declaration nothing
traces, and a hook nobody declares."""
import threading

from nomad_tpu.analysis import race


class BadDecl:
    _RACE_TRACED = ["_ring"]                # not a literal str->str dict

    def __init__(self):
        self._ring = []


class Store:
    _RACE_TRACED = {"_ring": "_lock", "_ghost": "_lock2"}

    def __init__(self):
        self._ring = []
        self._lock = threading.Lock()

    def put(self, x):
        with self._lock:
            race.write("Store._ring", self)
            self._ring.append(x)


def rogue(obj):
    race.read("Phantom._tbl", obj)          # hook nobody declares
