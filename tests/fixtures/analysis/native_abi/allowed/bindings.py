"""The same drifted bindings as bad/, all findings suppressed on their
anchor lines."""
import ctypes

import numpy as np

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")

lib = ctypes.CDLL("libfixture.so")
if lib.nomad_native_abi_version() != 1:                # analysis: allow(native-abi) — fixture: exercises the suppression path
    raise RuntimeError("abi mismatch")

lib.scale_rows.argtypes = [_f32p, ctypes.c_int]        # analysis: allow(native-abi) — fixture: exercises the suppression path
lib.sum_ids.argtypes = [_f32p, ctypes.c_int]           # analysis: allow(native-abi) — fixture: exercises the suppression path
lib.sum_ids.restype = ctypes.c_int
lib.old_fn.argtypes = [ctypes.c_int]                   # analysis: allow(native-abi) — fixture: exercises the suppression path
