"""Bindings in lockstep with the fixture's extern "C" surface."""
import ctypes

import numpy as np

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")

lib = ctypes.CDLL("libfixture.so")
if lib.nomad_native_abi_version() != 2:
    raise RuntimeError("abi mismatch")

lib.scale_rows.argtypes = [_f32p, ctypes.c_int, ctypes.c_float]
lib.scale_rows.restype = None
lib.sum_ids.argtypes = [_i32p, ctypes.c_int]
lib.sum_ids.restype = ctypes.c_int
