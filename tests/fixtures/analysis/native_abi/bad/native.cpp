// Fixture ABI surface for the native-abi checker.
#include <cstdint>

extern "C" {

int nomad_native_abi_version() { return 2; }

void scale_rows(float* rows, int n, float factor) {
    for (int i = 0; i < n; ++i) rows[i] *= factor;
}

int sum_ids(const int32_t* ids, int n) {
    int s = 0;
    for (int i = 0; i < n; ++i) s += ids[i];
    return s;
}

}
