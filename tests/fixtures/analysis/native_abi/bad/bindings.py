"""Seeded native-abi violations: wrong gate version, argument count
drift, dtype mismatch, missing void restype, and a stale binding."""
import ctypes

import numpy as np

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")

lib = ctypes.CDLL("libfixture.so")
if lib.nomad_native_abi_version() != 1:                # gate vs .cpp's 2
    raise RuntimeError("abi mismatch")

lib.scale_rows.argtypes = [_f32p, ctypes.c_int]        # 2 args vs 3; void restype unset
lib.sum_ids.argtypes = [_f32p, ctypes.c_int]           # arg 0 wants int32*
lib.sum_ids.restype = ctypes.c_int
lib.old_fn.argtypes = [ctypes.c_int]                   # not exported anymore
