"""Raft + RPC over real TCP sockets and gossip membership
(reference nomad/raft_rpc.go, nomad/rpc.go, nomad/serf.go): a 3-server
cluster on loopback elects a leader, replicates scheduling state, survives
a hard leader kill, and gossips membership from a single seed."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.membership import ALIVE, FAILED, LEFT, Membership
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.raft import RaftConfig
from nomad_tpu.raft.transport import TcpTransport


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TcpCluster:
    def __init__(self, n=3):
        self.names = [f"tcp-{i}" for i in range(n)]
        self.transports = [TcpTransport() for _ in range(n)]
        cfg = RaftConfig(heartbeat_interval=0.03, election_timeout=0.15)
        self.servers = []
        for i, nm in enumerate(self.names):
            srv = Server(ServerConfig(num_schedulers=2), name=nm,
                         peers=self.names, raft_transport=self.transports[i],
                         raft_config=cfg)
            self.servers.append(srv)
        # every member seeds every address (the gossip test exercises
        # single-seed discovery separately)
        for i, t in enumerate(self.transports):
            for j, nm in enumerate(self.names):
                if i != j:
                    t.add_peer(nm, self.transports[j].address)

    def start(self):
        for s in self.servers:
            s.start()

    def stop(self):
        for s in self.servers:
            try:
                s.stop()
            except Exception:       # noqa: BLE001
                pass
        for t in self.transports:
            t.close()

    def leader(self, timeout=8.0, among=None):
        servers = among or self.servers
        deadline = time.time() + timeout
        while time.time() < deadline:
            leaders = [s for s in servers
                       if s.raft is not None and s.raft.is_leader
                       and s._established]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.02)
        raise TimeoutError("no leader over TCP")


def test_tcp_cluster_schedules_and_survives_leader_kill():
    c = TcpCluster(3)
    c.start()
    try:
        leader = c.leader()
        for _ in range(3):
            leader.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        leader.register_job(job)
        assert _wait(lambda: len(
            leader.store.allocs_by_job("default", job.id)) == 2, 15)

        # replication reached followers over real sockets
        idx = leader.store.latest_index
        others = [s for s in c.servers if s is not leader]
        assert _wait(lambda: all(
            s.store.latest_index >= idx for s in others), 10)

        # hard-kill the leader: listener closed AND raft stopped, like a
        # process death (outbound heartbeats must cease or no election
        # would ever start)
        dead = leader
        i = c.servers.index(dead)
        c.transports[i].close()
        dead.raft.stop()
        dead._stop.set()
        dead._revoke_leadership()

        survivor = c.leader(among=others, timeout=10.0)
        job2 = mock.job()
        job2.task_groups[0].count = 2
        survivor.register_job(job2)
        assert _wait(lambda: len(
            survivor.store.allocs_by_job("default", job2.id)) == 2, 15), \
            "new leader must keep scheduling after the kill"
    finally:
        c.stop()


def test_gossip_single_seed_convergence_and_failure_detection():
    transports = [TcpTransport() for _ in range(3)]
    names = ["g-0", "g-1", "g-2"]
    members = [Membership(t, nm, t.address, interval=0.05,
                          suspect_after=0.3, fail_after=0.8)
               for t, nm in zip(transports, names)]
    try:
        # g-1 and g-2 know ONLY the seed g-0; g-0 knows nobody
        members[1].join([("g-0", transports[0].address)])
        members[2].join([("g-0", transports[0].address)])
        for m in members:
            m.start()

        # full convergence: everyone sees all three alive
        assert _wait(lambda: all(
            len(m.alive_members()) == 3 for m in members), 10), \
            [[e["name"] for e in m.member_list()] for m in members]
        # addresses were learned transitively (g-1 knows g-2's addr)
        assert transports[1].peer_addr("g-2") == transports[2].address

        # graceful leave propagates as LEFT
        members[2].leave()
        assert _wait(lambda: all(
            any(e["name"] == "g-2" and e["status"] == LEFT
                for e in m.member_list())
            for m in members[:2]), 10)

        # hard kill g-1: close its transport; g-0 marks it failed
        transports[1].close()
        members[1].stop()
        assert _wait(lambda: any(
            e["name"] == "g-1" and e["status"] == FAILED
            for e in members[0].member_list()), 10), \
            members[0].member_list()
    finally:
        for m in members:
            try:
                m.stop()
            except Exception:       # noqa: BLE001
                pass
        for t in transports:
            try:
                t.close()
            except Exception:       # noqa: BLE001
                pass


def test_members_rpc_reports_gossip_table():
    t = TcpTransport()
    srv = Server(ServerConfig(num_schedulers=1), name="solo")
    srv.membership = Membership(t, "solo", t.address, interval=0.1)
    srv.start()
    try:
        out = srv.endpoints.handle("Status.Members", {})
        assert out and out[0]["name"] == "solo"
        assert out[0]["status"] == ALIVE
    finally:
        srv.stop()
        t.close()
