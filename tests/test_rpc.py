"""RPC layer tests (reference analogs: nomad/rpc_test.go leader
forwarding, worker_test.go RPC dequeue, api client round-trips)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.rpc import RpcError, TcpRpcClient, TcpRpcServer


@pytest.fixture
def dev_server():
    s = Server(ServerConfig(num_schedulers=2))
    s.start()
    yield s
    s.stop()


def _wait(fn, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(0.05)
    return False


# ------------------------------------------------------------- endpoints


def test_endpoint_job_lifecycle(dev_server):
    s = dev_server
    ep = s.endpoints
    for _ in range(3):
        ep.handle("Node.Register", {"node": mock.node()})
    job = mock.job()
    job.task_groups[0].count = 2
    resp = ep.handle("Job.Register", {"job": job})
    assert resp["eval_id"]
    assert _wait(lambda: len(ep.handle(
        "Job.Allocations", {"job_id": job.id})) == 2)
    got = ep.handle("Job.GetJob", {"job_id": job.id})
    assert got is not None and got.id == job.id
    assert len(ep.handle("Node.List", {})) == 3
    ev = ep.handle("Eval.GetEval", {"eval_id": resp["eval_id"]})
    assert ev is not None

    # stop one alloc; job reschedules it
    alloc = ep.handle("Job.Allocations", {"job_id": job.id})[0]
    ep.handle("Alloc.Stop", {"alloc_id": alloc.id})
    assert _wait(lambda: ep.handle(
        "Alloc.GetAlloc", {"alloc_id": alloc.id}).desired_status == "stop")

    resp = ep.handle("Job.Deregister", {"job_id": job.id})
    assert resp["eval_id"]
    assert _wait(lambda: all(
        a.desired_status in ("stop", "evict")
        for a in ep.handle("Job.Allocations", {"job_id": job.id})))


def test_endpoint_unknown_method(dev_server):
    with pytest.raises(RpcError) as e:
        dev_server.endpoints.handle("No.Such", {})
    assert e.value.kind == "unknown_method"


def test_operator_scheduler_config(dev_server):
    ep = dev_server.endpoints
    cfg = ep.handle("Operator.SchedulerGetConfiguration", {})
    assert cfg.scheduler_algorithm == "binpack"
    from nomad_tpu.structs.config import SchedulerConfiguration
    ep.handle("Operator.SchedulerSetConfiguration",
              {"config": SchedulerConfiguration(
                  scheduler_algorithm="spread")})
    cfg = ep.handle("Operator.SchedulerGetConfiguration", {})
    assert cfg.scheduler_algorithm == "spread"


# ------------------------------------------------------------- tcp


def test_tcp_rpc_roundtrip(dev_server):
    srv = TcpRpcServer(dev_server.endpoints)
    srv.start()
    try:
        client = TcpRpcClient(srv.address)
        assert client.call("Status.Ping")["ok"]
        for _ in range(3):
            client.call("Node.Register", {"node": mock.node()})
        nodes = client.call("Node.List")
        assert len(nodes) == 3
        job = mock.job()
        job.task_groups[0].count = 2
        resp = client.call("Job.Register", {"job": job})
        assert resp["eval_id"]
        assert _wait(lambda: len(client.call(
            "Job.Allocations", {"job_id": job.id}))
            == job.task_groups[0].count)
        client.close()
    finally:
        srv.stop()


def test_tcp_rpc_error_surface(dev_server):
    srv = TcpRpcServer(dev_server.endpoints)
    srv.start()
    try:
        client = TcpRpcClient(srv.address)
        with pytest.raises(RpcError):
            client.call("No.Such", {})
        client.close()
    finally:
        srv.stop()


# ------------------------------------------------------------- cluster


def test_follower_write_forwarding():
    c = Cluster(3)
    c.start()
    try:
        leader = c.leader()
        follower = c.followers()[0]
        # writes submitted on a follower forward to the leader and commit
        for _ in range(3):
            follower.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        follower.register_job(job)
        assert _wait(lambda: len(
            leader.store.allocs_by_job("default", job.id))
            == job.task_groups[0].count)
    finally:
        c.stop()


def test_remote_workers_on_followers_schedule():
    """Only follower workers run: the leader's own scheduling is disabled,
    so every placement must flow through RPC dequeue + plan submit."""
    c = Cluster(3)
    c.start()
    try:
        leader = c.leader()
        for w in leader.remote_workers:
            w.stop()
        for _ in range(4):
            leader.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        leader.register_job(job)
        assert _wait(lambda: len(
            leader.store.allocs_by_job("default", job.id)) == 3, 15)
        # follower workers did the scheduling (stats tick after the ack
        # round-trips, which trail the alloc commit — wait, don't sample)
        assert _wait(lambda: sum(
            w.stats["processed"]
            for f in c.followers() for w in f.remote_workers) >= 1, 5)
    finally:
        c.stop()


def test_status_endpoints_cluster():
    c = Cluster(3)
    c.start()
    try:
        leader = c.leader()
        follower = c.followers()[0]
        assert follower.endpoints.handle("Status.Leader", {}) == leader.name
        peers = follower.endpoints.handle("Status.Peers", {})
        assert len(peers) == 3
    finally:
        c.stop()
