"""Double-buffered commit waves (r15): the pipelined path — applier
resolves `evaluated` at overlay registration, the worker defers the
COMPLETE/ack settle until the durable commit lands — must commit
byte-identical FSM state to strict serial execution, and a commit that
fails mid-flight must discard the speculative continuation (tickets
released, eval redelivered) rather than half-apply it.

Also covers the r15 satellites: the engine stats shape (the once-dead
batched_evals/single_evals counters), the broker's wave dequeue, and the
wave feeder that fronts the local worker pool.
"""
import copy
import threading
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.core.broker import EvalBroker, EvalWaveFeeder
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs import Evaluation
from nomad_tpu.structs.plan import Plan


# ---------------------------------------------------------------- stats shape

def test_engine_stats_shape_and_live_counters():
    """The engine stats dict carries every key bench/telemetry read, and
    the batch counters actually move: batched_evals on a >1 part,
    single_evals on a singleton, bulk_parts/bulk_groups on bulk waves
    (they were dead-always-0 before r15)."""
    from concurrent.futures import Future

    from nomad_tpu.encode import ClusterMatrix
    from nomad_tpu.parallel.engine import PlacementEngine, _Request
    from nomad_tpu.scheduler.stack import DenseStack

    eng = PlacementEngine()
    try:
        expected = {"dispatches", "batched_evals", "single_evals",
                    "max_batch_seen", "tickets_open", "stack_s", "put_s",
                    "device_s", "resolve_s", "cache_hits", "cache_misses",
                    "bulk_evals", "waves", "max_waves_seen",
                    "bulk_groups", "bulk_parts", "donated_carries",
                    "wave_lanes", "lane_evals", "lane_slots",
                    "overlap_chained"}
        assert expected <= set(eng.stats), \
            f"missing stats keys: {expected - set(eng.stats)}"
        for key in expected:
            assert eng.stats[key] == 0, f"{key} must start at 0"

        cm = ClusterMatrix(initial_rows=8)
        for i in range(8):
            cm.upsert_node(mock.node())

        def req(count):
            job = mock.batch_job()
            job.task_groups[0].count = count
            stack = DenseStack(cm)
            groups = [stack.compile_group(job, tg)
                      for tg in job.task_groups]
            inputs = stack.build_inputs(job, groups, [0] * count, {},
                                        used_override=cm.used.copy())
            return _Request(cm=cm, inputs=inputs, deltas=[],
                            spread_algorithm=False, future=Future())

        batch = [req(2) for _ in range(3)]
        eng._dispatch(batch)
        for r in batch:
            _res, ticket = r.future.result(timeout=30)
            eng.complete(ticket)
        assert eng.stats["batched_evals"] == 3
        assert eng.stats["single_evals"] == 0

        solo = [req(2)]
        eng._dispatch(solo)
        _res, ticket = solo[0].future.result(timeout=30)
        eng.complete(ticket)
        assert eng.stats["single_evals"] == 1
        assert eng.stats["batched_evals"] == 3
    finally:
        eng.stop()


# ------------------------------------------------------------- wave dequeue

def _eval(ns="default", job="j", prio=50):
    return Evaluation(id=mock._uuid(), namespace=ns, priority=prio,
                      type="batch", job_id=job)


def test_broker_dequeue_batch_drains_ready_without_waiting():
    broker = EvalBroker()
    broker.set_enabled(True)
    evs = [_eval(job=f"j{i}") for i in range(6)]
    for ev in evs:
        broker.enqueue(ev)
    t0 = time.time()
    wave = broker.dequeue_batch(["batch"], max_n=4, timeout=5.0)
    # drains up to max_n in ONE pass, and does NOT wait for the batch
    # to fill beyond what is ready
    assert len(wave) == 4
    assert time.time() - t0 < 1.0
    got_ids = {ev.id for ev, _tok in wave}
    assert got_ids <= {ev.id for ev in evs}
    # each entry carries a real lease
    for ev, tok in wave:
        assert broker.ack(ev.id, tok)
    # remaining two still dequeue
    rest = broker.dequeue_batch(["batch"], max_n=8, timeout=1.0)
    assert len(rest) == 2


def test_broker_dequeue_batch_times_out_empty():
    broker = EvalBroker()
    broker.set_enabled(True)
    t0 = time.time()
    assert broker.dequeue_batch(["batch"], max_n=4, timeout=0.2) == []
    assert 0.15 < time.time() - t0 < 2.0


def test_wave_feeder_buffers_and_closes():
    broker = EvalBroker()
    broker.set_enabled(True)
    for i in range(5):
        broker.enqueue(_eval(job=f"j{i}"))
    feeder = EvalWaveFeeder(broker, max_n=5)
    first = feeder.get(["batch"], timeout=1.0)
    assert first is not None
    # the filler drained the whole wave: peers get buffered entries
    # without touching the broker
    assert feeder.stats["waves"] == 1
    assert feeder.stats["wave_evals"] == 5
    second = feeder.get(["batch"], timeout=0.0)
    assert second is not None and second[0].id != first[0].id
    # close() nacks what is still buffered so no lease is stranded
    feeder.close()
    assert broker.stats["nacked"] == 3


# ------------------------------------------------- pipelined == serial parity

def _rand_world(rng, n_nodes=6):
    store = StateStore()
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        store.upsert_node(store.latest_index + 1, n)
        nodes.append(n)
    return store, nodes


def _rand_plan(rng, nodes, k):
    """A plan placing 1-3 allocs on random nodes; sizes randomized so a
    fraction overcommits and exercises partial rejection."""
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = int(rng.integers(200, 2600))
    j.task_groups[0].tasks[0].resources.memory_mb = \
        int(rng.integers(200, 5200))
    plan = Plan(eval_id=f"eval-{k}", job=j)
    plan.plan_id = f"plan-{k}"
    for i in range(int(rng.integers(1, 4))):
        node = nodes[int(rng.integers(0, len(nodes)))]
        # distinct per-alloc name index: two live allocs of one job may
        # never share a name (the store's duplicate-name guard dedups
        # them at apply, which no well-formed scheduler plan triggers)
        alloc = mock.alloc_for(j, node_id=node.id, index=i)
        alloc.id = f"alloc-{k}-{i}-{node.id[:8]}"
        plan.append_alloc(alloc, j)
    return plan


def _fsm_fingerprint(store):
    """The comparable committed state: usage matrix bytes plus the exact
    (alloc id -> node) placement map."""
    allocs = tuple(sorted((a.id, a.node_id, a.desired_status)
                          for a in store._allocs.values()))
    return store.matrix.used.tobytes(), allocs


@pytest.mark.parametrize("seed", [0, 7, 1234])
def test_pipelined_commits_identical_state_to_serial(seed):
    """Randomized parity: plans pushed through the pipelined applier
    loop (evaluate(N+1) overlapping commit(N), batched commits, the
    `evaluated` future resolving early) land byte-identical FSM state to
    the same plans applied strictly serially."""
    rng = np.random.default_rng(seed)
    store_p, nodes = _rand_world(rng)
    plans = [_rand_plan(rng, nodes, k) for k in range(24)]

    # serial reference on an identical world: same node ids, same plan
    # payloads (deep-copied so committed allocs are distinct objects)
    store_s = StateStore()
    for n in nodes:
        store_s.upsert_node(store_s.latest_index + 1, copy.deepcopy(n))
    serial = PlanApplier(store_s)
    for p in plans:
        serial.apply(copy.deepcopy(p))

    # pipelined: run_loop + a commit_fn that stalls, forcing the next
    # batch's evaluation to overlap the in-flight commit
    def slow_commit(applied):
        time.sleep(0.003)
        idx = store_p.latest_index + 1
        if isinstance(applied, list):
            store_p.upsert_plan_results_many(idx, applied)
        else:
            store_p.upsert_plan_results(idx, applied)
        return idx

    pipelined = PlanApplier(store_p, commit_fn=slow_commit)
    pipelined.batch_n = 4
    queue = PlanQueue()
    queue.set_enabled(True)
    stop = threading.Event()
    t = threading.Thread(target=pipelined.run_loop, args=(queue, stop),
                         daemon=True)
    t.start()
    try:
        pendings = [queue.enqueue(p) for p in plans]
        for pend in pendings:
            # the evaluated future resolves no later than the commit
            ev_res = pend.evaluated.result(timeout=30)
            final = pend.future.result(timeout=30)
            # content identical: only alloc_index is commit-side
            assert ev_res is final
    finally:
        stop.set()
        t.join(5)

    assert pipelined.stats["pipelined"] > 0, \
        "the loop never overlapped a commit — parity not exercised"
    fp_p, fp_s = _fsm_fingerprint(store_p), _fsm_fingerprint(store_s)
    assert fp_p[1] == fp_s[1]
    assert fp_p[0] == fp_s[0]
    assert not pipelined._overlay and not serial._overlay


# ------------------------------------------------- mid-flight commit failure

def test_commit_failure_discards_speculative_wave():
    """commit(N) fails mid-flight: every submitter in the batch gets the
    error on its durable future even though `evaluated` already resolved
    (the speculative continuation must be discarded), engine tickets are
    released, the overlay drains, and NOTHING from the failed batch is
    visible in committed state — a clean resubmit then succeeds."""
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)

    fail_once = {"armed": True}

    def flaky_commit(applied):
        if fail_once["armed"]:
            fail_once["armed"] = False
            raise RuntimeError("raft apply lost leadership mid-fsync")
        idx = store.latest_index + 1
        if isinstance(applied, list):
            store.upsert_plan_results_many(idx, applied)
        else:
            store.upsert_plan_results(idx, applied)
        return idx

    applier = PlanApplier(store, commit_fn=flaky_commit)
    applier.batch_n = 4
    queue = PlanQueue()
    queue.set_enabled(True)
    stop = threading.Event()
    t = threading.Thread(target=applier.run_loop, args=(queue, stop),
                         daemon=True)
    t.start()
    try:
        rng = np.random.default_rng(3)
        plans = [_rand_plan(rng, [node], k) for k in range(3)]
        pendings = [queue.enqueue(copy.deepcopy(p)) for p in plans]
        evaluated = [p.evaluated.result(timeout=30) for p in pendings]
        assert any(r.node_allocation for r in evaluated)
        for pend in pendings:
            with pytest.raises(RuntimeError, match="mid-fsync"):
                pend.future.result(timeout=30)
        # nothing from the failed wave landed
        assert len(store._allocs) == 0
        # overlay drained — the next evaluation sees clean state
        deadline = time.time() + 5
        while time.time() < deadline and applier._overlay:
            time.sleep(0.01)
        assert not applier._overlay

        # the crash-redelivery path: resubmitting the same plans (same
        # plan_id) now commits cleanly
        retry = [queue.enqueue(copy.deepcopy(p)) for p in plans]
        results = [p.future.result(timeout=30) for p in retry]
        committed = sum(len(v) for r in results
                        for v in r.node_allocation.values())
        assert committed == len(store._allocs) > 0
    finally:
        stop.set()
        t.join(5)


def test_commit_failure_releases_engine_tickets():
    """The applier's commit-failure path must hand back the scheduler's
    engine tickets (the pipelined submitter skipped its early release),
    or a failed wave leaks phantom usage into every later dispatch."""
    from nomad_tpu.parallel import engine as engine_mod

    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)

    eng = engine_mod.PlacementEngine()
    with engine_mod._engine_lock:
        prev, engine_mod._engine = engine_mod._engine, eng
    try:
        cm = store.matrix
        ticket = eng.register_external(
            cm, [(0, np.ones(cm.used.shape[1], np.float32))])
        assert eng._tickets

        def bad_commit(applied):
            raise RuntimeError("commit exploded")

        applier = PlanApplier(store, commit_fn=bad_commit)
        queue = PlanQueue()
        queue.set_enabled(True)
        stop = threading.Event()
        t = threading.Thread(target=applier.run_loop,
                             args=(queue, stop), daemon=True)
        t.start()
        try:
            plan = _rand_plan(np.random.default_rng(5), [node], 0)
            plan.engine_tickets = [ticket]
            pend = queue.enqueue(plan)
            with pytest.raises(RuntimeError, match="exploded"):
                pend.future.result(timeout=30)
            deadline = time.time() + 5
            while time.time() < deadline and eng._tickets:
                time.sleep(0.01)
            assert not eng._tickets, \
                "failed commit leaked the engine overlay ticket"
        finally:
            stop.set()
            t.join(5)
    finally:
        with engine_mod._engine_lock:
            engine_mod._engine = prev
        eng.stop()


# ----------------------------------------------------- worker deferred settle

class _FakeBrokerServer:
    """Just enough server surface for Worker._settle_eval."""

    def __init__(self):
        self.acked, self.nacked, self.updated = [], [], []
        self.eval_feeder = None

    class _Broker:
        def __init__(self, outer):
            self.outer = outer

        def ack(self, eval_id, token):
            self.outer.acked.append((eval_id, token))
            return True

        def nack(self, eval_id, token):
            self.outer.nacked.append((eval_id, token))
            return True

    @property
    def broker(self):
        return self._Broker(self)

    def update_eval(self, ev):
        self.updated.append(ev)


def test_worker_settle_discards_on_commit_failure():
    from concurrent.futures import Future

    from nomad_tpu.core.plan_queue import PendingPlan
    from nomad_tpu.core.worker import Worker

    srv = _FakeBrokerServer()
    w = Worker.__new__(Worker)           # skip thread/env plumbing
    w.server = srv
    w.stats = {"processed": 0, "failed": 0,
               "pipelined_evals": 0, "pipeline_discards": 0}

    ev = _eval()
    pend = PendingPlan.__new__(PendingPlan)
    pend.future = Future()
    pend.future.set_exception(RuntimeError("commit failed"))
    w._settle_eval(ev, "tok-1", [pend])
    assert srv.nacked == [(ev.id, "tok-1")]
    assert not srv.acked and not srv.updated
    assert w.stats["pipeline_discards"] == 1

    ok = PendingPlan.__new__(PendingPlan)
    ok.future = Future()
    ok.future.set_result(object())
    ev2 = _eval(job="j2")
    w._settle_eval(ev2, "tok-2", [ok])
    assert srv.acked == [(ev2.id, "tok-2")]
    assert srv.updated and srv.updated[0] is ev2
    assert w.stats["processed"] == 1
    assert w.stats["pipelined_evals"] == 1


def test_expired_lease_behind_stalled_commit_settles_exactly_once():
    """A lease that expires while its eval's settle sits pipelined behind
    a stalled commit must auto-nack and redeliver exactly ONCE, and the
    late settle with the stale token must be a no-op against the real
    broker — the redelivered lease is the only one that ever settles."""
    from concurrent.futures import Future

    from nomad_tpu.core.plan_queue import PendingPlan
    from nomad_tpu.core.worker import Worker

    broker = EvalBroker(nack_timeout=0.1, initial_nack_delay=60.0)
    broker.set_enabled(True)

    class _Srv:
        def __init__(self, broker):
            self.broker = broker
            self.updated = []

        def update_eval(self, ev):
            self.updated.append(ev)

    srv = _Srv(broker)
    w = Worker.__new__(Worker)           # skip thread/env plumbing
    w.server = srv
    w.stats = {"processed": 0, "failed": 0,
               "pipelined_evals": 0, "pipeline_discards": 0}

    ev = _eval()
    broker.enqueue(ev)
    got, stale_token = broker.dequeue(["batch"], timeout=1.0)
    assert got is not None and got.id == ev.id

    # the commit this settle waits on is stalled: park the settle on an
    # unresolved future in a thread, exactly like the pipelined worker
    stalled = PendingPlan.__new__(PendingPlan)
    stalled.future = Future()
    settle = threading.Thread(
        target=w._settle_eval, args=(got, stale_token, [stalled]),
        daemon=True)
    settle.start()

    # the lease expires under the parked settle; the broker's timer poll
    # auto-nacks (requeue_now: the expiry already cost nack_timeout) and
    # the eval redelivers exactly once, under a FRESH token
    deadline = time.time() + 5
    ev2, fresh_token = None, ""
    while time.time() < deadline and ev2 is None:
        ev2, fresh_token = broker.dequeue(["batch"], timeout=0.05)
    assert ev2 is not None and ev2.id == ev.id
    assert fresh_token != stale_token
    assert broker.stats["nacked"] == 1
    # only the fresh lease is live: the stale token must not be reported
    assert broker.outstanding(ev.id) == fresh_token

    # the stalled commit finally lands; the parked settle wakes with the
    # STALE token and must not settle: the ack is refused, nothing is
    # counted, and the fresh lease stays outstanding
    stalled.future.set_result(object())
    settle.join(5)
    assert not settle.is_alive()
    assert w.stats["processed"] == 0
    assert w.stats["pipelined_evals"] == 0
    assert broker.stats["acked"] == 0
    assert broker.outstanding(ev.id) == fresh_token

    # the redelivered lease settles exactly once
    landed = PendingPlan.__new__(PendingPlan)
    landed.future = Future()
    landed.future.set_result(object())
    w._settle_eval(ev2, fresh_token, [landed])
    assert w.stats["processed"] == 1
    assert broker.stats["acked"] == 1
    assert broker.stats["nacked"] == 1       # exactly one redelivery, ever
    assert broker.outstanding(ev.id) is None
    # nothing left behind: no duplicate copy ever re-enters the queue
    again, _ = broker.dequeue(["batch"], timeout=0.1)
    assert again is None
    assert broker.unacked_count() == 0
