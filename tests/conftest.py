"""Test configuration: force an 8-device virtual CPU platform BEFORE jax
import so multi-chip sharding paths are exercised without TPU hardware
(matches the driver's dryrun_multichip environment).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
