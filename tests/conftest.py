"""Test configuration: force an 8-device virtual CPU platform so
multi-chip sharding paths are exercised without TPU hardware (matches the
driver's dryrun_multichip environment).

The ambient environment pins the 'axon' TPU platform via a sitecustomize
that imports jax at interpreter startup, so plain env vars are too late —
override through jax.config before any backend is initialized.
"""
import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the persistent compile cache is for TPU serving; sharing it with the
# CPU test platform risks AOT feature-mismatch loads (SIGILL warnings)
os.environ.setdefault("NOMAD_TPU_JAX_CACHE", "0")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


@pytest.fixture(scope="session", autouse=True)
def _lock_order_guard(request):
    """NOMAD_TPU_LOCK_ORDER=1 wraps every lock allocated during the run
    and fails the session if the acquisition graph has a cycle (latent
    deadlock).  Off by default: the wrapper adds per-acquire overhead.

    The observed acquisition graph is dumped (LockOrderRecorder.dump,
    the corpus format the static wait-graph checker merges via
    `python -m nomad_tpu.analysis --lock-corpus`) to
    NOMAD_TPU_LOCK_ORDER_DUMP when set, and always on a failing
    session so CI failures keep the interleaving evidence."""
    if os.environ.get("NOMAD_TPU_LOCK_ORDER", "0") in ("", "0"):
        yield
        return
    from nomad_tpu.analysis.lock_order import LockOrderRecorder
    rec = LockOrderRecorder().install()
    yield
    rec.uninstall()
    cycles = rec.cycles()
    dump = os.environ.get("NOMAD_TPU_LOCK_ORDER_DUMP", "")
    if not dump and (cycles or request.session.testsfailed):
        dump = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "lock-order-corpus.json")
    if dump:
        rec.dump(dump)
    assert not cycles, "\n" + rec.render_cycles()


@pytest.fixture(scope="session", autouse=True)
def _race_guard():
    """NOMAD_TPU_RACE=1 installs the happens-before detector for the
    whole run: every lock is clock-carrying, every race.read/race.write
    hook in production code is checked, and the session fails on any
    unordered access pair or lock-order cycle.  Off by default (vector
    clocks cost more than the plain lock-order recorder)."""
    if os.environ.get("NOMAD_TPU_RACE", "0") in ("", "0"):
        yield
        return
    from nomad_tpu.analysis import race as race_mod
    from nomad_tpu.analysis.race import RaceDetector
    det = RaceDetector().install()
    prev, race_mod.active = race_mod.active, det
    yield
    race_mod.active = prev
    det.uninstall()
    assert det.races == [], "\n" + det.render_races()
    assert det.cycles() == [], "\n" + det.render_cycles()
