"""Commit-pipeline correctness under concurrency + the CI smoke leg of
the C2M-1M headline bench.

The stress test drives the broker-shaped path (many submitter threads ->
PlanQueue -> batched pipelined PlanApplier -> StateStore) and asserts
the invariants the coalescing/pipelining must preserve: every submitted
alloc lands exactly once, committed usage equals the sum of demands, the
overlay drains, and plan.submit latency stays bounded.
"""
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.state.store import StateStore
from nomad_tpu.structs.plan import Plan

N_THREADS = 16
PLANS_PER_THREAD = 20
N_NODES = 32


def _run_commit_stress():
    store = StateStore()
    nodes = [mock.node() for _ in range(N_NODES)]
    for i, n in enumerate(nodes):
        store.upsert_node(i + 1, n)

    applier = PlanApplier(store)
    queue = PlanQueue()
    queue.set_enabled(True)
    stop = threading.Event()
    loop = threading.Thread(target=applier.run_loop, args=(queue, stop),
                            daemon=True)
    loop.start()

    submitted_ids = [set() for _ in range(N_THREADS)]
    latencies = [[] for _ in range(N_THREADS)]
    errors = []
    start_gate = threading.Event()

    def submitter(ti: int) -> None:
        start_gate.wait()
        for k in range(PLANS_PER_THREAD):
            j = mock.job()
            j.task_groups[0].tasks[0].resources.cpu = 10
            j.task_groups[0].tasks[0].resources.memory_mb = 10
            node = nodes[(ti * PLANS_PER_THREAD + k) % N_NODES]
            alloc = mock.alloc_for(j, node_id=node.id)
            plan = Plan(eval_id=mock._uuid(), job=j)
            plan.append_alloc(alloc, j)
            t0 = time.monotonic()
            try:
                r = queue.enqueue(plan).future.result(timeout=30)
            except Exception as e:                   # noqa: BLE001
                errors.append((ti, k, repr(e)))
                return
            latencies[ti].append(time.monotonic() - t0)
            if r.rejected_nodes or not r.node_allocation:
                errors.append((ti, k, f"rejected: {r.rejected_nodes}"))
                return
            submitted_ids[ti].add(alloc.id)

    threads = [threading.Thread(target=submitter, args=(ti,), daemon=True)
               for ti in range(N_THREADS)]
    try:
        for t in threads:
            t.start()
        start_gate.set()
        for t in threads:
            t.join(120)
        assert not errors, errors[:5]

        want = set().union(*submitted_ids)
        assert len(want) == N_THREADS * PLANS_PER_THREAD

        # exactly-once: the store holds every submitted alloc, and no
        # extras (dict-keyed by id, so duplicates would overwrite — the
        # usage check below would catch a double-commit instead)
        got = set(store._allocs.keys())
        assert got == want, (f"lost={len(want - got)} "
                             f"extra={len(got - want)}")

        # committed usage equals the sum of the demands exactly: a plan
        # committed twice (or an overlay leaked into the matrix) would
        # show up here
        assert float(store.matrix.used[:, 0].sum()) == \
            10.0 * N_THREADS * PLANS_PER_THREAD

        # the in-flight overlay drains once everything has committed
        deadline = time.time() + 5
        while time.time() < deadline and applier._overlay:
            time.sleep(0.01)
        assert not applier._overlay

        assert applier.stats["rejected_nodes"] == 0
        assert applier.stats["partial"] == 0

        # bounded latency: generous for a 1-core CI host, but a commit
        # path that serializes per-alloc Python work behind the applier
        # lock blows far past this
        flat = sorted(x for ls in latencies for x in ls)
        p99 = flat[int(len(flat) * 0.99) - 1]
        assert p99 < 5.0, f"plan.submit p99 {p99:.2f}s"
    finally:
        stop.set()
        loop.join(2)


def test_concurrent_submitters_no_lost_or_duplicate_allocs():
    _run_commit_stress()


def test_commit_stress_is_race_free():
    """The same 16-thread stress with the happens-before detector armed:
    every lock the pipeline allocates carries a vector clock and every
    race.read/race.write hook on the traced tables (store dedup ring,
    applier overlay, broker leases, world snapshot) is checked for
    unordered access pairs.  Equivalent to running this file under
    NOMAD_TPU_RACE=1, but always on, so a dropped lock acquisition on
    the commit path fails tier-1 rather than only the chaos CI leg."""
    from nomad_tpu.analysis import race as race_mod
    from nomad_tpu.analysis.race import RaceDetector

    if race_mod.active is not None:
        pytest.skip("session-level race guard already installed")
    det = RaceDetector().install()
    race_mod.active = det
    try:
        _run_commit_stress()
    finally:
        race_mod.active = None
        det.uninstall()
    assert det.races == [], "\n" + det.render_races()
    assert det.cycles() == [], "\n" + det.render_cycles()


def test_bench_smoke_leg():
    """The bench.py --smoke leg (C2M-1M shape shrunk to CI scale) runs
    the full spine — bulk kernel -> native materialization -> plan queue
    -> batched applier -> store — and must place every alloc.  The rate
    floor is deliberately loose; it exists to catch order-of-magnitude
    commit-path regressions, not to benchmark CI hardware."""
    import bench

    rate, placed, want = bench.bench_smoke(workers=8)
    assert placed == want, f"smoke placed {placed}/{want}"
    assert rate > 10.0, f"smoke rate {rate:.1f} allocs/s"
