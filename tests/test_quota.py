"""Quota + fair-dequeue tests (reference analogs: nomad/state quota
cases, nomad/eval_broker_test.go fairness extension, blocked_evals
quota-keyed unblock).  Covers: usage accounting canonical form, the
FSM-side double-admit guard (leader-churn regression), the propose-side
quota filter, quota-blocked eval release on spec raise (including the
missed-unblock race), stride fair dequeue + starvation bound, and the
live-tunable SchedulerConfiguration knobs.  The broker stress test here
is the CI `race` leg's fair-dequeue payload."""
import threading
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.blocked import BlockedEvals
from nomad_tpu.core.broker import EvalBroker
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.state import StateStore
from nomad_tpu.state.store import AppliedPlanResults
from nomad_tpu.structs import QuotaSpec, alloc_quota_usage
from nomad_tpu.structs.config import SchedulerConfiguration
from nomad_tpu.structs.plan import Plan


def _wait(cond, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ------------------------------------------------------------------ spec

def test_quota_spec_admits_at_limit():
    spec = QuotaSpec(name="s", cpu=1000, allocs=4)
    assert spec.admits({"cpu": 1000, "allocs": 4})       # at-limit admits
    assert not spec.admits({"cpu": 1001, "allocs": 4})
    assert not spec.admits({"cpu": 0, "allocs": 5})
    # unset dimensions are unlimited
    assert spec.admits({"memory_mb": 10**9, "cpu": 1000, "allocs": 0})
    assert spec.exceeded_dims({"cpu": 1001, "allocs": 5}) == \
        ["cpu", "allocs"]


# ------------------------------------------------------------------ store

def _capped_store(alloc_limit=1, index=1):
    store = StateStore()
    store.upsert_quota_spec(index, QuotaSpec(name="small",
                                             allocs=alloc_limit))
    store.upsert_namespace(index + 1, "capped", quota="small")
    return store


def test_store_usage_accounting_canonical():
    store = StateStore()
    n = mock.node()
    store.upsert_node(1, n)
    j = mock.job()
    store.upsert_job(2, j)
    a = mock.alloc_for(j, n.id)
    store.upsert_allocs(3, [a])
    expect = alloc_quota_usage(a)
    assert store.quota_usage("default") == expect
    assert expect["allocs"] == 1 and expect["cpu"] > 0
    # terminal transition releases usage, and the all-zero entry is
    # dropped entirely (canonical form: byte-identical across replicas)
    stop = mock.alloc_for(j, n.id)
    stop.id = a.id
    stop.client_status = "failed"
    store.upsert_allocs(4, [stop])
    assert store.quota_usages() == {}


def test_store_quota_spec_crud_and_referenced_delete():
    store = _capped_store()
    assert [s.name for s in store.quota_specs()] == ["small"]
    with pytest.raises(ValueError):
        store.delete_quota_spec(5, "small")    # referenced by "capped"
    store.upsert_namespace(6, "capped", quota="")
    store.delete_quota_spec(7, "small")
    assert store.quota_specs() == []


def test_fsm_quota_guard_drops_double_admit():
    """Leader-churn regression: two leaders each propose a within-budget
    plan that only overflows combined.  The log serializes them; the
    second one's placements must be dropped by the replica-deterministic
    FSM-side check — identically on every replica."""
    store = _capped_store(alloc_limit=1)
    n = mock.node()
    store.upsert_node(3, n)
    j = mock.job()
    j.namespace = "capped"
    store.upsert_job(4, j)
    a1 = mock.alloc_for(j, n.id, index=0)
    a2 = mock.alloc_for(j, n.id, index=1)
    a1.namespace = a2.namespace = "capped"
    r1 = AppliedPlanResults(allocs_to_place=[a1], plan_id="p1")
    r2 = AppliedPlanResults(allocs_to_place=[a2], plan_id="p2")
    store.upsert_plan_results(5, r1)
    store.upsert_plan_results(6, r2)
    assert r1.quota_dropped == []
    assert r2.quota_dropped == [(a2.id, "small")]
    live = [a for a in store.allocs_by_job("capped", j.id)
            if not a.terminal_status()]
    assert [a.id for a in live] == [a1.id]
    assert store.quota_usage("capped")["allocs"] == 1


def test_fsm_quota_guard_counts_same_plan_frees():
    """A plan that stops one alloc and places its replacement stays
    within an allocs=1 quota: stops apply before the admission check."""
    store = _capped_store(alloc_limit=1)
    n = mock.node()
    store.upsert_node(3, n)
    j = mock.job()
    j.namespace = "capped"
    store.upsert_job(4, j)
    a1 = mock.alloc_for(j, n.id, index=0)
    a1.namespace = "capped"
    store.upsert_plan_results(
        5, AppliedPlanResults(allocs_to_place=[a1], plan_id="p1"))
    stop = mock.alloc_for(j, n.id, index=0)
    stop.id, stop.namespace = a1.id, "capped"
    stop.desired_status = "stop"
    stop.client_status = "complete"
    a2 = mock.alloc_for(j, n.id, index=1)
    a2.namespace = "capped"
    r = AppliedPlanResults(alloc_updates=[stop], allocs_to_place=[a2],
                           plan_id="p2")
    store.upsert_plan_results(6, r)
    assert r.quota_dropped == []
    assert store.quota_usage("capped")["allocs"] == 1


# ------------------------------------------------------------------ applier

def test_plan_applier_quota_filter_drops_and_marks():
    store = _capped_store(alloc_limit=1)
    n = mock.node()
    store.upsert_node(3, n)
    j = mock.job()
    j.namespace = "capped"
    store.upsert_job(4, j)
    applier = PlanApplier(store)
    a1 = mock.alloc_for(j, n.id, index=0)
    a2 = mock.alloc_for(j, n.id, index=1)
    a1.namespace = a2.namespace = "capped"
    plan = Plan(eval_id="e1", job=j)
    plan.append_alloc(a1, j)
    plan.append_alloc(a2, j)
    result = applier.apply(plan)
    placed = [a.id for allocs in result.node_allocation.values()
              for a in allocs]
    assert len(placed) == 1
    assert result.quota_limit_reached == "small"
    full, expected, actual = result.full_commit(plan)
    assert not full and expected == 2 and actual == 1
    # a second plan for the other placement is now fully over quota
    plan2 = Plan(eval_id="e2", job=j)
    a3 = mock.alloc_for(j, n.id, index=1)
    a3.namespace = "capped"
    plan2.append_alloc(a3, j)
    result2 = applier.apply(plan2)
    assert result2.quota_limit_reached == "small"
    assert not any(result2.node_allocation.values())


# ------------------------------------------------------------------ blocked

def make_broker():
    b = EvalBroker(nack_timeout=5.0, initial_nack_delay=0.0,
                   subsequent_nack_delay=0.0)
    b.set_enabled(True)
    return b


def test_blocked_quota_keyed_unblock():
    b = make_broker()
    blocked = BlockedEvals(b)
    blocked.set_enabled(True)
    ev = mock.eval()
    ev.status = "blocked"
    ev.quota_limit_reached = "small"
    blocked.block(ev)
    assert blocked.blocked_count() == 1
    # raising an unrelated quota releases nothing
    assert blocked.unblock_quota("other", 10) == []
    released = blocked.unblock_quota("small", 11)
    assert [e.id for e in released] == [ev.id]
    assert b.ready_count() == 1
    assert blocked.blocked_count() == 0


def test_blocked_quota_missed_unblock_requeues():
    """Regression: a quota raise that lands between the eval's snapshot
    and its block() call must requeue the eval immediately — parking it
    would strand the job until the NEXT quota change."""
    b = make_broker()
    blocked = BlockedEvals(b)
    blocked.set_enabled(True)
    blocked.unblock_quota("small", index=100)   # raise, nothing parked
    ev = mock.eval()
    ev.status = "blocked"
    ev.quota_limit_reached = "small"
    ev.snapshot_index = 50                      # planned before the raise
    blocked.block(ev)
    assert blocked.blocked_count() == 0
    assert b.ready_count() == 1                 # requeued, not parked
    # an eval that already saw the raise parks normally
    ev2 = mock.eval()
    ev2.status = "blocked"
    ev2.quota_limit_reached = "small"
    ev2.snapshot_index = 200
    blocked.block(ev2)
    assert blocked.blocked_count() == 1
    assert b.ready_count() == 1


# ------------------------------------------------------------------ server

def test_server_quota_end_to_end_block_and_raise():
    s = Server(ServerConfig(num_schedulers=2))
    s.start()
    try:
        s.register_node(mock.node())
        s.upsert_quota_spec(QuotaSpec(name="small", allocs=1))
        s.upsert_namespace("capped", quota="small")
        j = mock.job()
        j.namespace = "capped"
        j.task_groups[0].count = 3
        s.register_job(j)

        def live():
            return [a for a in s.store.allocs_by_job("capped", j.id)
                    if not a.terminal_status()]
        assert _wait(lambda: len(live()) == 1)
        assert _wait(lambda: s.blocked_evals.blocked_count() == 1)
        assert s.store.quota_usage("capped")["allocs"] == 1
        # quota raise releases the blocked eval and the rest places
        s.upsert_quota_spec(QuotaSpec(name="small", allocs=3))
        assert _wait(lambda: len(live()) == 3)
        assert s.store.quota_usage("capped")["allocs"] == 3
    finally:
        s.stop()


def test_server_delete_quota_spec_referenced_rejected():
    from nomad_tpu.rpc.endpoints import RpcError
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        s.upsert_quota_spec(QuotaSpec(name="small", allocs=1))
        s.upsert_namespace("capped", quota="small")
        with pytest.raises((RpcError, ValueError)):
            s.delete_quota_spec("small")
        s.upsert_namespace("capped", quota="")
        s.delete_quota_spec("small")
        assert s.quota_specs() == []
    finally:
        s.stop()


# ------------------------------------------------------------------ fairness

def _drain(b, n, ack=True):
    got = []
    for _ in range(n):
        ev, token = b.dequeue(["service"])
        if ev is None:
            break
        got.append(ev)
        if ack:
            b.ack(ev.id, token)
    return got


def test_fair_dequeue_alternates_namespaces():
    b = make_broker()
    for i in range(4):
        b.enqueue(mock.eval(namespace="heavy", job_id=f"h{i}"))
    for i in range(2):
        b.enqueue(mock.eval(namespace="light", job_id=f"l{i}"))
    order = [e.namespace for e in _drain(b, 6)]
    assert order[:4] == ["heavy", "light", "heavy", "light"]
    assert order[4:] == ["heavy", "heavy"]


def test_fair_dequeue_respects_weights():
    b = make_broker()
    cfg = SchedulerConfiguration()
    cfg.namespace_weights = {"paid": 3}
    b.set_fair_config(cfg)
    for i in range(6):
        b.enqueue(mock.eval(namespace="paid", job_id=f"p{i}"))
    for i in range(6):
        b.enqueue(mock.eval(namespace="free", job_id=f"f{i}"))
    first8 = [e.namespace for e in _drain(b, 8)]
    assert first8.count("paid") == 6     # stride 1000/3 vs 1000
    assert first8.count("free") == 2


def test_fair_dequeue_disabled_is_global_fifo():
    b = make_broker()
    cfg = SchedulerConfiguration()
    cfg.fair_dequeue_enabled = False
    b.set_fair_config(cfg)
    evs = [mock.eval(namespace=f"ns{i % 3}", job_id=f"j{i}")
           for i in range(9)]
    for e in evs:
        b.enqueue(e)
    got = [e.id for e in _drain(b, 9)]
    assert got == [e.id for e in evs]    # pure (-priority, seq) order


def test_fair_dequeue_starvation_bound():
    """A namespace arriving late is served within one full round of the
    runnable set: its pass floors to the runnable minimum (sleeping
    banks no credit) so at most every current head precedes it once."""
    b = make_broker()
    heavies = [f"bulk{i}" for i in range(10)]
    for ns in heavies:
        for i in range(20):
            b.enqueue(mock.eval(namespace=ns, job_id=f"{ns}-{i}"))
    _drain(b, 50)                        # advance the bulk passes
    b.enqueue(mock.eval(namespace="victim", job_id="v0"))
    tail = [e.namespace for e in _drain(b, len(heavies) + 1)]
    assert "victim" in tail
    st = b.fair_stats()
    assert st["enabled"] and st["picks"] > 0


def test_fair_dequeue_sleeper_banks_no_credit():
    b = make_broker()
    for i in range(10):
        b.enqueue(mock.eval(namespace="busy", job_id=f"b{i}"))
    _drain(b, 6)
    b.enqueue(mock.eval(namespace="sleeper", job_id="s0"))
    # the sleeper gets its fair next slot, not a 6-deep repayment burst
    order = [e.namespace for e in _drain(b, 4)]
    assert order.count("sleeper") == 1


def test_scheduler_config_tunes_broker_live():
    from nomad_tpu.raft.fsm import MessageType
    s = Server(ServerConfig(num_schedulers=0))
    s.start()
    try:
        assert s.broker.fair_stats()["enabled"]
        cfg = SchedulerConfiguration()
        cfg.fair_dequeue_enabled = False
        cfg.default_namespace_weight = 7
        cfg.namespace_weights = {"paid": 3}
        s.apply(MessageType.SCHEDULER_CONFIG, {"config": cfg})
        assert _wait(lambda: not s.broker.fair_stats()["enabled"], 5.0)
        st = s.broker.fair_stats()
        assert st["default_weight"] == 7
        assert st["weights"] == {"paid": 3}
    finally:
        s.stop()


def test_fair_dequeue_concurrent_stress():
    """CI race-leg payload: concurrent multi-namespace enqueue against a
    pool of dequeue+ack consumers; every eval is served exactly once."""
    b = EvalBroker(nack_timeout=10.0, initial_nack_delay=0.0,
                   subsequent_nack_delay=0.0)
    b.set_enabled(True)
    total = 200
    served = set()
    lock = threading.Lock()

    def produce(ns, count):
        for i in range(count):
            b.enqueue(mock.eval(namespace=ns, job_id=f"{ns}-{i}"))

    def consume():
        while True:
            with lock:
                if len(served) >= total:
                    return
            ev, token = b.dequeue(["service"], timeout=0.2)
            if ev is None:
                continue
            b.ack(ev.id, token)
            with lock:
                served.add(ev.id)

    producers = [threading.Thread(target=produce, args=(f"ns{i}", 50))
                 for i in range(4)]
    consumers = [threading.Thread(target=consume) for _ in range(4)]
    for t in producers + consumers:
        t.start()
    for t in producers:
        t.join(10.0)
    for t in consumers:
        t.join(30.0)
    assert len(served) == total
    assert b.ready_count() == 0
