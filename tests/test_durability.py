"""Crash-safe durability tests: checksummed WAL, persisted term/vote,
hardened snapshots, client state DB recovery, and the seeded hard-kill /
restart soak (reference analogs: raft-boltdb's torture tests plus the
crash-consistency failure taxonomy of Pillai et al., OSDI 2014).

Unit legs pin one contract each: WAL record framing + torn-tail repair,
mid-stream corruption refusal, legacy pickle migration, fsync policy
semantics under simulated power loss, corrupt-read retry, durable meta
round-trip + refusal paths, snapshot CRC fallback + reap floor +
partial-write injection, and ClientStateDB corruption/checkpoint
behavior.

The soak leg boots a data_dir-backed 3-server cluster under seeded disk
faults (torn writes, fsync failures, corrupt reads, partial snapshot
writes), hard-kills members mid-commit and restarts them from disk, then
asserts the safety properties: never two leaders in one term, exactly
the requested allocs per job (no committed plan lost or applied twice),
and byte-identical FSM state across all members.
"""
import json
import os
import pickle
import random
import signal
import threading
import time

import pytest

from nomad_tpu import chaos, mock
from nomad_tpu.chaos import ChaosError, ChaosRegistry
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.state import ClientStateDB
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.core.worker import TRANSIENT_ERRORS
from nomad_tpu.raft import (
    DurableMeta,
    FileSnapshotStore,
    InMemTransport,
    LogStore,
    MessageType,
    MetaPersistError,
    NomadFSM,
    RaftConfig,
    RaftNode,
    WALCorruptionError,
)
from nomad_tpu.raft.log import (
    LogEntry,
    WAL_MAGIC,
    encode_record,
    fsync_policy_from_env,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import EvalStatus, Job, Task, TaskGroup

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout=0.1)


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


# ------------------------------------------------------------------ WAL


def test_wal_new_format_roundtrip(tmp_path):
    path = str(tmp_path / "raft.log")
    st = LogStore(path, fsync="always")
    for i in range(1, 6):
        st.append(LogEntry(i, 1, "Noop", {"i": i}))
    st.close()
    with open(path, "rb") as fh:
        assert fh.read(len(WAL_MAGIC)) == WAL_MAGIC
    st2 = LogStore(path, fsync="off")
    assert st2.last_index == 5
    assert st2.get(3).payload == {"i": 3}
    st2.close()


def test_wal_torn_tail_truncated_on_open(tmp_path):
    path = str(tmp_path / "raft.log")
    st = LogStore(path, fsync="always")
    for i in range(1, 4):
        st.append(LogEntry(i, 1, "Noop", i))
    st.close()
    good = os.path.getsize(path)
    # crash mid-append: a partial record past the last good one
    rec = encode_record(pickle.dumps(("entry", 4, 1, "Noop", 4)))
    with open(path, "ab") as fh:
        fh.write(rec[:-3])
    st2 = LogStore(path, fsync="off")
    assert st2.last_index == 3
    st2.close()
    assert os.path.getsize(path) == good     # tail truncated away
    # torn header variant (fewer bytes than a length prefix)
    with open(path, "ab") as fh:
        fh.write(b"\x05\x00")
    st3 = LogStore(path, fsync="off")
    assert st3.last_index == 3
    st3.close()
    assert os.path.getsize(path) == good


def test_wal_midstream_corruption_refuses_to_open(tmp_path):
    path = str(tmp_path / "raft.log")
    st = LogStore(path, fsync="always")
    for i in range(1, 4):
        st.append(LogEntry(i, 1, "Noop", "x" * 50))
    st.close()
    # flip a payload byte in the FIRST record: valid records follow, so
    # this is damaged committed history, not a torn tail
    with open(path, "r+b") as fh:
        fh.seek(len(WAL_MAGIC) + 8 + 2)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(WALCorruptionError, match="refusing"):
        LogStore(path, fsync="off")


def test_wal_legacy_pickle_migration(tmp_path):
    path = str(tmp_path / "raft.log")
    with open(path, "wb") as fh:
        for i in range(1, 5):
            pickle.dump(("entry", i, 1, "Noop", {"i": i}), fh,
                        protocol=pickle.HIGHEST_PROTOCOL)
        fh.write(b"\x80\x05\x03")            # truncated trailing record
    st = LogStore(path, fsync="off")
    assert st.last_index == 4
    st.close()
    assert os.path.exists(path + ".legacy")
    with open(path, "rb") as fh:
        assert fh.read(len(WAL_MAGIC)) == WAL_MAGIC
    # reopens as new-format (no second migration) with entries intact
    st2 = LogStore(path, fsync="off")
    assert st2.last_index == 4
    assert st2.get(2).payload == {"i": 2}
    st2.close()
    assert not os.path.exists(path + ".legacy.legacy")


def test_fsync_policy_env_parsing(monkeypatch):
    monkeypatch.delenv("NOMAD_TPU_FSYNC", raising=False)
    assert fsync_policy_from_env() == "batch"
    for pol in ("always", "batch", "off"):
        monkeypatch.setenv("NOMAD_TPU_FSYNC", pol)
        assert fsync_policy_from_env() == pol
    monkeypatch.setenv("NOMAD_TPU_FSYNC", "sometimes")
    with pytest.raises(ValueError, match="NOMAD_TPU_FSYNC"):
        fsync_policy_from_env()


def test_power_loss_respects_fsync_policy(tmp_path):
    """always/batch: append() returning means the record survives power
    loss.  off: page cache only, the crash loses it."""
    for pol, survives in (("always", True), ("batch", True), ("off", False)):
        path = str(tmp_path / f"wal-{pol}.log")
        st = LogStore(path, fsync=pol)
        st.append(LogEntry(1, 1, "Noop", "payload"))
        st.simulate_crash()
        st2 = LogStore(path, fsync="off")
        assert (st2.last_index == 1) is survives, pol
        st2.close()


def test_append_batch_group_commit_durable(tmp_path):
    path = str(tmp_path / "raft.log")
    st = LogStore(path, fsync="batch")
    st.append_batch([LogEntry(i, 1, "Noop", i) for i in range(1, 51)])
    st.simulate_crash()
    st2 = LogStore(path, fsync="off")
    assert st2.last_index == 50
    st2.close()


def test_corrupt_read_is_caught_and_retried(tmp_path):
    path = str(tmp_path / "raft.log")
    st = LogStore(path, fsync="always")
    for i in range(1, 6):
        st.append(LogEntry(i, 1, "Noop", i))
    st.close()
    # every record read is corrupted on its first attempt; the CRC catches
    # it and the retry (from pristine data) succeeds
    chaos.install(ChaosRegistry(seed=2, rates={"disk.corrupt_read": 1.0}))
    st2 = LogStore(path, fsync="off")
    assert st2.last_index == 5
    st2.close()


# ----------------------------------------------------------- durable meta


def test_meta_roundtrip_and_noop_persist(tmp_path):
    path = str(tmp_path / "raft_meta.json")
    m = DurableMeta(path)
    assert m.state() == (0, None)
    m.persist(3, "server-1")
    with open(path, "rb") as fh:
        before = fh.read()
    m.persist(3, "server-1")                 # unchanged: no rewrite
    with open(path, "rb") as fh:
        assert fh.read() == before
    m2 = DurableMeta(path)
    assert m2.state() == (3, "server-1")


def test_meta_corruption_refuses_to_load(tmp_path):
    path = str(tmp_path / "raft_meta.json")
    DurableMeta(path).persist(2, "b")
    with open(path, "r+b") as fh:
        fh.write(b"{garbage")
    with pytest.raises(MetaPersistError):
        DurableMeta(path)
    # a parseable file whose CRC does not cover its contents is just as
    # untrustworthy — it may advertise a vote the node never made
    with open(path, "w") as fh:
        json.dump({"v": 1, "term": 9, "voted_for": "evil", "crc": 1}, fh)
    with pytest.raises(MetaPersistError, match="crc mismatch"):
        DurableMeta(path)


def test_vote_refused_when_meta_fsync_fails(tmp_path):
    meta = DurableMeta(str(tmp_path / "raft_meta.json"))
    tr = InMemTransport()
    n = RaftNode("a", ["a", "b"], tr, NomadFSM(StateStore()),
                 config=FAST, meta=meta)
    req = {"term": 1, "candidate": "b",
           "last_log_index": 0, "last_log_term": 0}
    chaos.install(ChaosRegistry(seed=1, rates={"disk.fsync_fail": 1.0}))
    resp = n._on_request_vote(dict(req))
    chaos.uninstall()
    # an unpersistable vote must not be granted (it could be forgotten)
    assert not resp["granted"]
    assert n.voted_for is None
    resp = n._on_request_vote(dict(req))     # disk healthy again
    assert resp["granted"]
    assert DurableMeta(meta.path).state() == (1, "b")
    tr.deregister("a")


# -------------------------------------------------------------- snapshots


def test_snapshot_fallback_to_older_valid(tmp_path):
    snaps = FileSnapshotStore(str(tmp_path), retain=3)
    snaps.save(10, 1, b"old-state")
    newest = snaps.save(20, 2, b"new-state")
    with open(newest, "r+b") as fh:          # tear the newest snapshot
        fh.seek(-1, os.SEEK_END)
        fh.truncate()
    assert snaps.latest() == (10, 1, b"old-state")


def test_snapshot_reap_never_deletes_newest_valid(tmp_path):
    snaps = FileSnapshotStore(str(tmp_path), retain=0)
    snaps.save(1, 1, b"a")
    snaps.save(2, 1, b"b")
    # retention misconfigured to 0: the restart anchor must survive
    assert snaps.latest() == (2, 1, b"b")
    assert len(snaps._snap_names()) == 1


def test_snapshot_partial_write_fails_save_and_is_skipped(tmp_path):
    snaps = FileSnapshotStore(str(tmp_path), retain=2)
    snaps.save(5, 1, b"good")
    chaos.install(ChaosRegistry(
        seed=4, rates={"snapshot.partial_write": 1.0}))
    with pytest.raises(ChaosError):
        snaps.save(9, 1, b"torn-" * 100)
    chaos.uninstall()
    # the torn file landed under its final name; latest() skips it
    assert len(snaps._snap_names()) == 2
    assert snaps.latest() == (5, 1, b"good")


def test_snapshot_legacy_bare_pickle_readable(tmp_path):
    snaps = FileSnapshotStore(str(tmp_path))
    legacy = os.path.join(str(tmp_path),
                          "snapshot-0000000001-000000000007.snap")
    with open(legacy, "wb") as fh:
        pickle.dump({"index": 7, "term": 1, "data": b"seed"}, fh)
    assert snaps.latest() == (7, 1, b"seed")


def test_force_snapshot_failure_keeps_log(tmp_path):
    snaps = FileSnapshotStore(str(tmp_path / "snaps"))
    tr = InMemTransport()
    n = RaftNode("a", ["a"], tr, NomadFSM(StateStore()), config=FAST,
                 snapshots=snaps,
                 log_store=LogStore(str(tmp_path / "wal"), fsync="off"))
    n.start()
    try:
        assert _wait(lambda: n.is_leader, 3.0)
        for _ in range(5):
            n.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        chaos.install(ChaosRegistry(
            seed=1, rates={"snapshot.partial_write": 1.0}))
        n.force_snapshot()       # must not raise and must NOT compact —
        chaos.uninstall()        # the log is the only durable copy now
        assert n.log.first_index == 1
        assert n._last_snapshot_index == 0
        n.force_snapshot()       # healthy retry lands and compacts
        assert snaps.latest() is not None
        assert n.log.first_index > 1
    finally:
        chaos.uninstall()
        n.stop()


def test_install_snapshot_unpersistable_is_rejected(tmp_path):
    """A follower that cannot durably save an installed snapshot must
    refuse it outright: accepting in memory lets later appends land past
    a hole the leader already compacted away, and the next restart
    replays around the hole — committed entries silently vanish."""
    snaps = FileSnapshotStore(str(tmp_path / "snaps"))
    tr = InMemTransport()
    n = RaftNode("a", ["a", "b", "c"], tr, NomadFSM(StateStore()),
                 config=FAST, snapshots=snaps,
                 log_store=LogStore(str(tmp_path / "wal"), fsync="off"))
    donor = StateStore()
    donor_fsm = NomadFSM(donor)
    donor_fsm.apply(1, MessageType.NODE_REGISTER, {"node": mock.node()})
    blob = donor_fsm.snapshot()
    args = {"term": 1, "leader": "b", "last_index": 9, "last_term": 1,
            "data": blob}
    chaos.install(ChaosRegistry(
        seed=1, rates={"snapshot.partial_write": 1.0}))
    try:
        resp = n._on_install_snapshot(dict(args))
    finally:
        chaos.uninstall()
    assert resp["success"] is False
    assert n.last_applied == 0 and n.commit_index == 0
    assert n._last_snapshot_index == 0      # nothing accepted
    assert len(n.fsm.store.nodes()) == 0    # FSM untouched
    resp = n._on_install_snapshot(dict(args))   # healthy retry lands
    assert resp["success"] is True
    assert n.last_applied == 9 and n._last_snapshot_index == 9
    assert len(n.fsm.store.nodes()) == 1


def test_log_store_refuses_gapped_append(tmp_path):
    ls = LogStore(str(tmp_path / "wal"), fsync="off")
    ls.append(LogEntry(1, 1, "Noop", None))
    with pytest.raises(ValueError, match="non-contiguous"):
        ls.append(LogEntry(5, 1, "Noop", None))
    ls.close()


# ---------------------------------------------------------- client state


def test_client_db_corrupt_file_moved_aside(tmp_path):
    path = str(tmp_path / "client_state.db")
    with open(path, "wb") as fh:
        fh.write(b"this is not a sqlite database at all")
    db = ClientStateDB(path)                 # recovers instead of raising
    db.put_alloc("a1", {"x": 1})
    assert db.get_allocs() == {"a1": {"x": 1}}
    db.close()
    with open(path + ".corrupt", "rb") as fh:
        assert fh.read().startswith(b"this is not")


def test_client_db_wal_checkpoint_on_close(tmp_path):
    path = str(tmp_path / "client_state.db")
    db = ClientStateDB(path)
    db.put_alloc("a1", {"x": 1})
    db.close()
    wal = path + "-wal"
    assert (not os.path.exists(wal)) or os.path.getsize(wal) == 0
    db2 = ClientStateDB(path)
    assert db2.get_allocs() == {"a1": {"x": 1}}
    db2.close()


def test_client_db_survives_unclean_shutdown(tmp_path):
    path = str(tmp_path / "client_state.db")
    db = ClientStateDB(path)
    db.put_alloc("a1", {"x": 1})
    # crash: the connection is abandoned; the sqlite WAL sidecar holds
    # the write and the next open replays it
    db2 = ClientStateDB(path)
    assert db2.get_allocs() == {"a1": {"x": 1}}
    db2.close()
    db._db.close()


def _sleep_job():
    job = Job(id=f"batch-{time.time_ns()}", name="batch", type="batch",
              task_groups=[TaskGroup(name="g", count=1, tasks=[
                  Task(name="t", driver="raw_exec",
                       config={"command": "/bin/sleep", "args": ["30"]})])])
    job.canonicalize()
    return job


def test_client_crash_restart_recovers_task(tmp_path):
    """A hard-killed client (state DB never closed — the sqlite WAL
    sidecar is what the dead process leaves behind) restarts from its
    data_dir and re-attaches the still-running task."""
    server = Server(ServerConfig(num_schedulers=2, heartbeat_ttl=30.0))
    server.start()
    data_dir = str(tmp_path / "client")
    client = Client(ClientConfig(node_name="c1", data_dir=data_dir,
                                 watch_interval=0.05),
                    rpc=server.endpoints.handle)
    client.start()
    pid = None
    try:
        job = _sleep_job()
        server.register_job(job)
        assert _wait(lambda: [
            a for a in server.store.allocs_by_job("default", job.id)
            if a.client_status == "running"], 15.0)
        client._stop.set()                   # crash: no clean shutdown
        time.sleep(0.3)
        pid = next(iter(client.alloc_runners.values())) \
            .task_runners["t"].handle.pid

        c2 = Client(ClientConfig(node_name="c1", data_dir=data_dir,
                                 watch_interval=0.05),
                    rpc=server.endpoints.handle)
        c2.start()
        try:
            assert _wait(lambda: c2.num_allocs() == 1, 5.0)
            ar = next(iter(c2.alloc_runners.values()))
            assert _wait(lambda: ar.client_status == "running", 5.0)
            assert ar.task_runners["t"].handle.pid == pid
        finally:
            c2.stop()
            client.state_db.close()
    finally:
        server.stop()
        if pid is not None:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass


# ------------------------------------------------------------------- soak


DISK_RATES = {
    "disk.torn_write": 0.25,
    "disk.fsync_fail": 0.05,
    "disk.corrupt_read": 0.05,
    "snapshot.partial_write": 0.10,
}


def _canon(blob):
    """Canonicalize an FSM snapshot for equality: pickle memoizes shared
    object references, so two byte-different blobs can encode identical
    state (a replayed server shares objects differently than a
    snapshot-restored one).  Re-pickle each item standalone, order-free."""
    data = pickle.loads(blob)
    out = {}
    for key, val in sorted(data.items()):
        if isinstance(val, list):
            out[key] = sorted(pickle.dumps(v) for v in val)
        elif isinstance(val, dict):
            out[key] = {k: pickle.dumps(v) for k, v in sorted(val.items())}
        else:
            out[key] = pickle.dumps(val)
    return out


def _on_leader(cluster, fn, timeout=15.0):
    """Run fn(leader), retrying across leadership churn."""
    deadline = time.time() + timeout
    while True:
        try:
            return fn(cluster.leader(timeout=5.0))
        except TRANSIENT_ERRORS + (TimeoutError,):
            if time.time() >= deadline:
                raise
            time.sleep(0.05)


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_durability_soak_kill_restart(seed, tmp_path):
    """Hard-kill members mid-commit under seeded disk faults, restart
    them from data_dir, and assert the safety properties hold: one leader
    per term, exactly-once plan application, identical FSM state."""
    reg = ChaosRegistry(seed=seed, rates=DISK_RATES)
    cfg = ServerConfig(num_schedulers=2, heartbeat_ttl=60.0,
                       failed_eval_followup_delay=0.3)
    cluster = Cluster(3, config=cfg,
                      raft_config=RaftConfig(heartbeat_interval=0.02,
                                             election_timeout=0.1),
                      data_dir=str(tmp_path))
    def _tune(s):
        # keep redelivery fast on every incarnation: restart() builds a
        # fresh Server, so the replacement reverts to the 60s production
        # defaults and a lease it holds would outlive the whole soak
        s.broker.nack_timeout = 1.0
        s.broker.initial_nack_delay = 0.05
        s.broker.subsequent_nack_delay = 0.1

    for s in cluster.servers:
        _tune(s)
    rng = random.Random(seed)

    # election-safety monitor: sample every member's (state, term) under
    # its lock for the whole run; two names in one term = safety broken
    leaders_by_term = {}
    stop_mon = threading.Event()

    def _monitor():
        while not stop_mon.is_set():
            for s in list(cluster.servers):
                r = s.raft
                if r is None:
                    continue
                with r._lock:
                    if r.state == "leader":
                        leaders_by_term.setdefault(
                            r.term, set()).add(s.name)
            time.sleep(0.005)

    mon = threading.Thread(target=_monitor, daemon=True)
    jobs = []

    def _add_job():
        j = mock.job()
        j.task_groups[0].count = 2
        jobs.append(j)
        _on_leader(cluster, lambda ld: ld.register_job(j))

    try:
        try:
            chaos.install(reg)
            cluster.start()
            mon.start()
            for _ in range(4):
                nd = mock.node()
                _on_leader(cluster, lambda ld, nd=nd: ld.register_node(nd))
            _add_job()
            for _ in range(2):
                _add_job()           # a commit in flight around the kill
                victim = cluster.servers[
                    rng.randrange(len(cluster.servers))]
                cluster.hard_kill(victim)
                time.sleep(0.2)
                for s in cluster.servers:
                    if s is not victim:      # exercise snapshot faults
                        s.raft.force_snapshot()
                _tune(cluster.restart(victim))
                try:
                    cluster.leader(timeout=10.0)
                except TimeoutError:
                    raftdump = "; ".join(
                        f"{s.name}(state={s.raft.state} term={s.raft.term} "
                        f"est={s._established} "
                        f"commit={s.raft.commit_index} "
                        f"applied={s.raft.last_applied} "
                        f"last_log={s.raft.log.last_index})"
                        for s in cluster.servers if s.raft is not None)
                    pytest.fail(
                        f"seed {seed}: no leader after restart of "
                        f"{victim.name}; {raftdump}; "
                        f"chaos fired: {dict(reg.stats)}")
        finally:
            chaos.uninstall()

        def converged():
            try:
                ld = cluster.leader(timeout=2.0)
            except TimeoutError:
                return False
            for j in jobs:
                live = [a for a in ld.store.allocs_by_job("default", j.id)
                        if not a.terminal_status()]
                if len(live) != j.task_groups[0].count:
                    return False
            if any(not EvalStatus.terminal(e.status)
                   for e in ld.store.evals()):
                return False
            return not ld.broker._unack and not ld.plan_queue._heap

        if not _wait(converged, timeout=30.0):
            # raft-level state first: "no leader" and "leader but stuck
            # work" need different triage, so dump both on the way out
            raftdump = "; ".join(
                f"{s.name}(state={s.raft.state} term={s.raft.term} "
                f"est={s._established} commit={s.raft.commit_index} "
                f"applied={s.raft.last_applied} "
                f"last_log={s.raft.log.last_index})"
                for s in cluster.servers if s.raft is not None)
            try:
                ld = cluster.leader(timeout=5.0)
            except TimeoutError:
                pytest.fail(f"seed {seed}: no leader after soak; {raftdump}; "
                            f"chaos fired: {dict(reg.stats)}")
            counts = {f"job{i}": len(
                [a for a in ld.store.allocs_by_job("default", j.id)
                 if not a.terminal_status()]) for i, j in enumerate(jobs)}
            evdump = "; ".join(
                f"{e.id[-8:]}(type={e.type} status={e.status} "
                f"trig={e.triggered_by})"
                for e in ld.store.evals()
                if not EvalStatus.terminal(e.status))
            pytest.fail(f"seed {seed}: no convergence; live={counts}; "
                        f"open evals: [{evdump}]; "
                        f"unacked={len(ld.broker._unack)} "
                        f"plan_heap={len(ld.plan_queue._heap)}; "
                        f"{raftdump}; chaos fired: {dict(reg.stats)}")

        # exactly-once across restarts: every job has its requested count,
        # never a duplicate placement from a replayed plan
        ld = cluster.leader()
        for j in jobs:
            live = [a for a in ld.store.allocs_by_job("default", j.id)
                    if not a.terminal_status()]
            assert len(live) == j.task_groups[0].count
            assert len({a.id for a in live}) == len(live)

        # identical FSM state on every member once all have applied
        # through the leader's index (barrier commits the whole prefix)
        ld.raft.barrier()
        assert cluster.wait_replication(ld.store.latest_index, timeout=10.0)
        assert _wait(lambda: all(
            s.raft.last_applied >= ld.raft.last_applied
            for s in cluster.servers), 10.0)
        blobs = {s.name: _canon(s.raft.fsm.snapshot())
                 for s in cluster.servers}
        ref = blobs[ld.name]
        for name, blob in blobs.items():
            assert blob == ref, f"seed {seed}: FSM divergence on {name}"

        # election safety held for the entire soak
        multi = {t: sorted(names) for t, names in leaders_by_term.items()
                 if len(names) > 1}
        assert not multi, \
            f"seed {seed}: two leaders in one term: {multi}"
        # the fault schedule actually bit (the soak isn't vacuous)
        assert sum(reg.stats.values()) > 0
    finally:
        stop_mon.set()
        mon.join(2.0)
        chaos.uninstall()
        cluster.stop()
