"""CSI subsystem end-to-end: plugin derivation from node fingerprints,
volume registration/claims, the dense CSIVolumeChecker, claim taking on
plan commit, and the volume watcher releasing claims of dead allocs
(reference scheduler/feasible.go:212-358, nomad/structs/csi.go,
nomad/volumewatcher/)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import csi as csistructs
from nomad_tpu.structs.csi import CSIVolume, CSIVolumeClaim
from nomad_tpu.structs.job import VolumeRequest


def _csi_job(vol_id, read_only=False, count=1):
    j = mock.job()
    tg = j.task_groups[0]
    tg.count = count
    tg.volumes = {"vol": VolumeRequest(
        name="vol", type="csi", source=vol_id, read_only=read_only)}
    return j


def _run(h, job):
    h.store.upsert_job(h.next_index(), job)
    h.process(job.type, mock.eval(job_id=job.id, type=job.type))
    return h.store.allocs_by_job("default", job.id)


# --------------------------------------------------------------- store

def test_plugin_derived_from_node_fingerprint():
    h = Harness()
    n1 = mock.csi_node(healthy=True)
    n2 = mock.csi_node(healthy=False)
    h.store.upsert_node(h.next_index(), n1)
    h.store.upsert_node(h.next_index(), n2)
    plug = h.store.csi_plugin_by_id("ebs-plugin")
    assert plug is not None
    assert plug.nodes_healthy == 1 and len(plug.nodes) == 2

    # node drops the plugin -> plugin row updates
    n1.csi_node_plugins = {}
    h.store.upsert_node(h.next_index(), n1)
    plug = h.store.csi_plugin_by_id("ebs-plugin")
    assert len(plug.nodes) == 1 and plug.nodes_healthy == 0


def test_volume_schedulability_denormalized():
    h = Harness()
    vol = mock.csi_volume("v1")
    h.store.upsert_csi_volume(h.next_index(), vol)
    assert not h.store.csi_volume_by_id("default", "v1").schedulable

    h.store.upsert_node(h.next_index(), mock.csi_node())
    assert h.store.csi_volume_by_id("default", "v1").schedulable


def test_claim_lifecycle_single_writer():
    vol = CSIVolume(id="v", plugin_id="p")
    vol.claim(CSIVolumeClaim(alloc_id="a1", node_id="n1",
                             mode=csistructs.CLAIM_WRITE))
    assert vol.access_mode == csistructs.ACCESS_SINGLE_WRITER
    assert not vol.has_free_write_claims()
    assert vol.in_use()
    vol.release("a1")
    assert vol.has_free_write_claims()
    assert vol.access_mode == csistructs.ACCESS_UNKNOWN
    assert not vol.in_use()


# ----------------------------------------------------------- scheduling

def test_csi_job_places_only_on_plugin_nodes():
    h = Harness()
    plain = [mock.node() for _ in range(3)]
    plugged = mock.csi_node()
    for n in plain + [plugged]:
        h.store.upsert_node(h.next_index(), n)
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v1"))

    allocs = _run(h, _csi_job("v1"))
    assert len(allocs) == 1
    assert allocs[0].node_id == plugged.id

    # the commit took a write claim for the alloc
    vol = h.store.csi_volume_by_id("default", "v1")
    assert allocs[0].id in vol.write_claims
    assert vol.write_claims[allocs[0].id].node_id == plugged.id


def test_single_writer_blocks_second_job():
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.csi_node())
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v1"))

    assert len(_run(h, _csi_job("v1"))) == 1
    second = _csi_job("v1")
    allocs = _run(h, second)
    assert len(allocs) == 0
    assert h.last_scheduler.failed_tg_allocs

    # readers are still fine on a multi-reader volume
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume(
        "v2", access_mode=csistructs.ACCESS_MULTI_READER))
    assert len(_run(h, _csi_job("v2", read_only=True))) == 1
    assert len(_run(h, _csi_job("v2", read_only=True))) == 1


def test_unhealthy_plugin_infeasible():
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.csi_node(healthy=False))
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v1"))
    assert len(_run(h, _csi_job("v1"))) == 0


def test_max_volumes_enforced():
    h = Harness()
    node = mock.csi_node(max_volumes=1)
    h.store.upsert_node(h.next_index(), node)
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v1"))
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v2"))

    assert len(_run(h, _csi_job("v1"))) == 1
    # second volume on the same node exceeds the plugin's MaxVolumes
    assert len(_run(h, _csi_job("v2"))) == 0


# ------------------------------------------------------- volume watcher

def test_volume_watcher_releases_claims_of_dead_allocs():
    from nomad_tpu.core.server import Server, ServerConfig
    from nomad_tpu.raft.fsm import MessageType

    s = Server(ServerConfig(num_schedulers=1, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    try:
        s.register_node(mock.csi_node())
        s.apply(MessageType.CSI_VOLUME_REGISTER,
                {"volume": mock.csi_volume("v1")})

        job = _csi_job("v1")
        s.register_job(job)
        deadline = time.time() + 10
        allocs = []
        while time.time() < deadline:
            allocs = s.store.allocs_by_job("default", job.id)
            if allocs:
                break
            time.sleep(0.05)
        assert allocs, "alloc never placed"
        vol = s.store.csi_volume_by_id("default", "v1")
        assert allocs[0].id in vol.write_claims

        # client reports the alloc complete -> watcher releases the claim
        a = allocs[0].copy()
        a.client_status = "complete"
        s.apply(MessageType.ALLOC_CLIENT_UPDATE, {"allocs": [a]})
        deadline = time.time() + 10
        while time.time() < deadline:
            vol = s.store.csi_volume_by_id("default", "v1")
            if not vol.write_claims:
                break
            time.sleep(0.05)
        assert not vol.write_claims, "claim not released by volume watcher"
        assert vol.access_mode == csistructs.ACCESS_UNKNOWN

        # volume is immediately writable by a new job
        job2 = _csi_job("v1")
        s.register_job(job2)
        deadline = time.time() + 10
        got = []
        while time.time() < deadline:
            got = s.store.allocs_by_job("default", job2.id)
            if got:
                break
            time.sleep(0.05)
        assert got, "released volume not schedulable again"
    finally:
        s.stop()


# ------------------------------------------------------------ HTTP/CLI

def test_volume_http_and_cli_surface():
    import io

    from nomad_tpu.agent.agent import Agent, AgentConfig
    from nomad_tpu.command import cli

    agent = Agent(AgentConfig(http_port=0, num_schedulers=1,
                              heartbeat_ttl=3600.0))
    agent.start()
    try:
        addr = agent.http_addr
        agent.server.register_node(mock.csi_node())

        out = io.StringIO()
        rc = cli.main(["-address", addr, "volume", "status"], out=out)
        assert rc == 0

        import json as _json
        import tempfile
        vol = {"ID": "web-data", "Name": "web-data",
               "PluginID": "ebs-plugin"}
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            _json.dump(vol, f)
            path = f.name
        out = io.StringIO()
        rc = cli.main(["-address", addr, "volume", "register", path],
                      out=out)
        assert rc == 0

        out = io.StringIO()
        rc = cli.main(["-address", addr, "volume", "status", "web-data"],
                      out=out)
        assert rc == 0 and "web-data" in out.getvalue()

        out = io.StringIO()
        rc = cli.main(["-address", addr, "plugin", "status"], out=out)
        assert rc == 0 and "ebs-plugin" in out.getvalue()

        out = io.StringIO()
        rc = cli.main(["-address", addr, "volume", "deregister",
                       "web-data"], out=out)
        assert rc == 0

        out = io.StringIO()
        rc = cli.main(["-address", addr, "volume", "status"], out=out)
        assert "web-data" not in out.getvalue()
    finally:
        agent.stop()


# ---------------------------------------------------------- client hook

def test_csi_hook_stage_publish_lifecycle(tmp_path):
    from nomad_tpu.client.csi import CSIHook, FakeCSIPlugin

    job = _csi_job("v1")
    alloc = mock.alloc_for(job, node_id="n1")
    plugin = FakeCSIPlugin()
    hook = CSIHook(alloc, str(tmp_path), plugins={"*": plugin})

    mounts = hook.prerun()
    assert "vol" in mounts
    import os
    assert os.path.isdir(mounts["vol"])
    assert os.path.exists(os.path.join(mounts["vol"], ".csi_published"))
    assert ("stage", "v1", os.path.join(str(tmp_path), "csi", "staging",
                                        "v1")) in plugin.calls

    hook.postrun()
    assert not os.path.exists(mounts["vol"])
    assert any(c[0] == "unstage" for c in plugin.calls)


def test_applier_rejects_concurrent_single_writer_claims():
    """Two plans claiming the same single-writer volume: the serialized
    applier admits the first and rejects the second, even though both
    passed the scheduler's checker against pre-claim state."""
    from nomad_tpu.core.plan_apply import PlanApplier
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs.plan import Plan

    store = StateStore()
    node = mock.csi_node()
    store.upsert_node(1, node)
    store.upsert_csi_volume(2, mock.csi_volume("v1"))
    applier = PlanApplier(store)

    def plan_for(job):
        tg = job.task_groups[0]
        tg.volumes = {"vol": VolumeRequest(name="vol", type="csi",
                                           source="v1")}
        alloc = mock.alloc_for(job, node_id=node.id)
        p = Plan(eval_id=mock._uuid(), job=job)
        p.append_alloc(alloc, job)
        return p

    r1 = applier.apply(plan_for(mock.job()))
    assert r1.node_allocation and not r1.rejected_nodes
    r2 = applier.apply(plan_for(mock.job()))
    assert r2.rejected_nodes == [node.id]
    vol = store.csi_volume_by_id("default", "v1")
    assert len(vol.write_claims) == 1


def test_reregister_preserves_live_claims():
    h = Harness()
    h.store.upsert_node(h.next_index(), mock.csi_node())
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v1"))
    assert len(_run(h, _csi_job("v1"))) == 1
    before = h.store.csi_volume_by_id("default", "v1")
    assert before.write_claims

    # operator re-registers the same volume id
    h.store.upsert_csi_volume(h.next_index(), mock.csi_volume("v1"))
    after = h.store.csi_volume_by_id("default", "v1")
    assert after.write_claims == before.write_claims
    assert after.access_mode == csistructs.ACCESS_SINGLE_WRITER
