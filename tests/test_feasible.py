"""Constraint-program semantics (regressions from review findings included)."""
import numpy as np

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.scheduler import feasible as fz
from nomad_tpu.scheduler.stack import DenseStack
from nomad_tpu.scheduler.version import version_matches
from nomad_tpu.structs.job import Constraint, Operand, Task, TaskGroup
from nomad_tpu.structs.resources import NodeDevice


def test_version_matching_semantics():
    assert version_matches("1.2.3", ">= 1.0.0, < 2.0.0")
    assert not version_matches("2.0.1", ">= 1.0.0, < 2.0.0")
    assert version_matches("1.4.9", "~> 1.4")
    assert not version_matches("2.0.0", "~> 1.4")
    assert version_matches("1.4.5", "~> 1.4.3")
    assert not version_matches("1.5.0", "~> 1.4.3")
    # semver: prerelease only matches prerelease constraints
    assert not version_matches("1.3.0-beta1", ">= 0.6.1", semver=True)
    assert version_matches("1.3.0-beta1", ">= 1.3.0-beta1", semver=True)
    assert version_matches("1.3.0-beta1", ">= 0.6.1")  # plain version mode


def test_swapped_version_operands():
    """Literal version on the left, column carrying the spec on the right."""
    cm = ClusterMatrix()
    n = mock.node()
    n.attributes["allowed"] = ">= 1.0"
    cm.upsert_node(n)
    mask = fz.constraint_mask(cm, Constraint("1.2.3", "${attr.allowed}", Operand.VERSION))
    assert mask[cm.row_of[n.id]]
    mask = fz.constraint_mask(cm, Constraint("0.5.0", "${attr.allowed}", Operand.VERSION))
    assert not mask[cm.row_of[n.id]]


def test_neq_against_missing_column():
    cm = ClusterMatrix()
    n = mock.node()
    cm.upsert_node(n)
    r = cm.row_of[n.id]
    # nil != found-value -> True (reference checkConstraint "!=")
    assert fz.constraint_mask(cm, Constraint("${attr.kernel.name}", "${attr.never}", Operand.NEQ))[r]
    # nil == nil -> equal -> NEQ False
    assert not fz.constraint_mask(cm, Constraint("${attr.nope}", "${attr.never}", Operand.NEQ))[r]
    # EQ with a missing side is never satisfied
    assert not fz.constraint_mask(cm, Constraint("${attr.kernel.name}", "${attr.never}", Operand.EQ))[r]


def test_is_set_operators():
    cm = ClusterMatrix()
    n = mock.node()
    cm.upsert_node(n)
    r = cm.row_of[n.id]
    assert fz.constraint_mask(cm, Constraint("${attr.kernel.name}", "", Operand.ATTRIBUTE_IS_SET))[r]
    assert not fz.constraint_mask(cm, Constraint("${attr.zzz}", "", Operand.ATTRIBUTE_IS_SET))[r]
    assert fz.constraint_mask(cm, Constraint("${attr.zzz}", "", Operand.ATTRIBUTE_IS_NOT_SET))[r]


def test_set_contains():
    cm = ClusterMatrix()
    n = mock.node()
    n.attributes["features"] = "avx,sse4,aes"
    cm.upsert_node(n)
    r = cm.row_of[n.id]
    assert fz.constraint_mask(cm, Constraint("${attr.features}", "avx,aes", Operand.SET_CONTAINS))[r]
    assert not fz.constraint_mask(cm, Constraint("${attr.features}", "avx,foo", Operand.SET_CONTAINS))[r]
    assert fz.constraint_mask(cm, Constraint("${attr.features}", "foo,aes", Operand.SET_CONTAINS_ANY))[r]


def test_device_caps_cleared_on_reregister():
    cm = ClusterMatrix()
    n = mock.node()
    n.node_resources.devices = [NodeDevice("nvidia", "gpu", "t4", ["i0", "i1"])]
    cm.upsert_node(n)
    class Req:
        name = "gpu"
        count = 1
    assert fz.device_mask(cm, [Req()])[cm.row_of[n.id]]
    n.node_resources.devices = []
    cm.upsert_node(n)
    assert not fz.device_mask(cm, [Req()])[cm.row_of[n.id]]


def test_tg_level_distinct_hosts_scoped_to_group():
    cm = ClusterMatrix()
    node = mock.node()
    cm.upsert_node(node)
    j = mock.job()
    j.task_groups.append(TaskGroup(name="b", count=1, tasks=[Task(name="b", driver="exec")]))
    j.task_groups[0].constraints.append(Constraint(operand=Operand.DISTINCT_HOSTS))
    st = DenseStack(cm)
    groups = [st.compile_group(j, tg) for tg in j.task_groups]
    b_alloc = mock.alloc_for(j, node.id)
    b_alloc.task_group = "b"
    inp = st.build_inputs(j, groups, [0], {"b": [b_alloc]})
    # a group-level constraint on "web" must not collide with "b"'s alloc
    assert inp.feasible[0, cm.row_of[node.id]]
    # but a job-level one must
    j2 = mock.job()
    j2.task_groups.append(TaskGroup(name="b", count=1, tasks=[Task(name="b", driver="exec")]))
    j2.constraints.append(Constraint(operand=Operand.DISTINCT_HOSTS))
    groups2 = [st.compile_group(j2, tg) for tg in j2.task_groups]
    inp2 = st.build_inputs(j2, groups2, [0], {"b": [b_alloc]})
    assert not inp2.feasible[0, cm.row_of[node.id]]
