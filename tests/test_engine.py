"""PlacementEngine batch-path parity: a chained batch dispatch must be
exactly equivalent to sequential single-eval processing (same node picks,
same scores), including sparse usage deltas, and concurrent callers must
coalesce through the public API without changing results."""
import threading

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.ops.place import place_eval
from nomad_tpu.parallel.engine import PlacementEngine, _Request
from nomad_tpu.scheduler.stack import DenseStack
from concurrent.futures import Future


def _world(n_nodes=16):
    cm = ClusterMatrix(initial_rows=n_nodes)
    for i in range(n_nodes):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 4}"
        cm.upsert_node(n)
    return cm


def _request(cm, count=5, deltas=()):
    job = mock.batch_job()
    job.task_groups[0].count = count
    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg) for tg in job.task_groups]
    used = cm.used.copy()
    for row, vec in deltas:
        used[row] += vec
    inputs = stack.build_inputs(job, groups, [0] * count, {},
                                used_override=used)
    return _Request(cm=cm, inputs=inputs, deltas=list(deltas),
                    spread_algorithm=False, future=Future())


def _serial_reference(cm, reqs):
    """Sequential processing with the chained-usage semantics the batch
    kernel implements: each eval starts from the usage left by the last."""
    used = cm.used.copy()
    results = []
    for r in reqs:
        u = used.copy()
        for row, vec in r.deltas:
            u[row] += vec
        inp = r.inputs
        inp.used = u
        res = place_eval(inp, r.spread_algorithm)
        results.append(res)
        used = u
        for si in range(inp.demand.shape[0]):
            row = int(res.node[si])
            if row >= 0:
                used[row] += inp.demand[si]
    return results


def test_batch_matches_serial_chained():
    cm = _world()
    engine = PlacementEngine()
    try:
        reqs = [_request(cm, count=3) for _ in range(4)]
        expected = _serial_reference(cm, [_request(cm, count=3)
                                          for _ in range(4)])
        engine._dispatch(reqs)
        for r, exp in zip(reqs, expected):
            got, ticket = r.future.result(timeout=30)
            np.testing.assert_array_equal(got.node[:3], exp.node[:3])
            np.testing.assert_allclose(got.score[:3], exp.score[:3],
                                       rtol=1e-5)
            assert int(got.nodes_evaluated[0]) == int(exp.nodes_evaluated[0])
            engine.complete(ticket)
        assert engine.stats["batched_evals"] == 4
        # all tickets released -> overlay fully drained
        assert not engine._tickets and not engine._overlays
    finally:
        engine.stop()


def test_batch_applies_deltas():
    cm = _world(n_nodes=8)
    engine = PlacementEngine()
    try:
        # free a full node's worth on row 0, consume most of row 1
        free = np.array([-2000.0, -2000.0, 0.0, 0.0], np.float32)
        eat = np.array([3500.0, 7500.0, 0.0, 0.0], np.float32)
        reqs = [_request(cm, count=2, deltas=[(0, free)]),
                _request(cm, count=2, deltas=[(1, eat)])]
        expected = _serial_reference(
            cm, [_request(cm, count=2, deltas=[(0, free)]),
                 _request(cm, count=2, deltas=[(1, eat)])])
        engine._dispatch(reqs)
        for r, exp in zip(reqs, expected):
            got, ticket = r.future.result(timeout=30)
            np.testing.assert_array_equal(got.node[:2], exp.node[:2])
            np.testing.assert_allclose(got.score[:2], exp.score[:2],
                                       rtol=1e-5)
            engine.complete(ticket)
    finally:
        engine.stop()


def test_concurrent_callers_coalesce():
    cm = _world()
    engine = PlacementEngine()
    try:
        # hold the dispatcher busy with one request so the rest queue up
        # and form a batch
        n_callers = 6
        barrier = threading.Barrier(n_callers)
        results = [None] * n_callers
        errors = []

        tickets = []

        def call(i):
            try:
                r = _request(cm, count=3)
                barrier.wait()
                res, ticket = engine.place(cm, r.inputs, r.deltas,
                                           r.spread_algorithm)
                results[i] = res
                tickets.append(ticket)
            except Exception as e:              # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(n_callers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # in the real flow a ticket is released only after its plan
        # commits into cm.used; here nothing commits, so release at the
        # end to keep every in-flight contribution visible to later
        # batches
        for t_ in tickets:
            engine.complete(t_)
        assert not errors
        assert all(r is not None for r in results)
        # every caller placed all 3 allocs somewhere valid
        for r in results:
            assert (r.node[:3] >= 0).all()
        # chained usage: total demand across callers must fit --
        # reconstruct usage and check no node is over capacity
        total = cm.used.copy()
        demand = _request(cm, count=3).inputs.demand
        for r in results:
            for si in range(3):
                total[int(r.node[si])] += demand[si]
        assert (total <= cm.capacity + 1e-3).all()
    finally:
        engine.stop()


def test_packed_cache_hits_and_single_path():
    """The content-addressed device cache dedupes identical heavy blocks
    across evals (same job state -> hit -> zero bytes shipped) and the
    packed single-eval path matches the raw kernel."""
    cm = _world()
    engine = PlacementEngine()
    try:
        # single-eval path parity vs place_eval
        r = _request(cm, count=3)
        exp = place_eval(_request(cm, count=3).inputs, False)
        engine._dispatch([r])
        got, ticket = r.future.result(timeout=30)
        np.testing.assert_array_equal(got.node[:3], exp.node[:3])
        np.testing.assert_allclose(got.score[:3], exp.score[:3], rtol=1e-5)
        engine.complete(ticket)
        assert engine._cache.misses >= 1

        # identical-content batch: every heavy block after the first hits
        misses0 = engine._cache.misses
        reqs = [_request(cm, count=3) for _ in range(4)]
        engine._dispatch(reqs)
        for rq in reqs:
            _, t = rq.future.result(timeout=30)
            engine.complete(t)
        assert engine._cache.misses == misses0   # all heavy blocks cached
        assert engine._cache.hits >= 4
    finally:
        engine.stop()


def test_device_world_upload_never_aliases_host_snapshot():
    """Regression: on the CPU backend `jax.device_put` zero-copy aliases
    the numpy buffer, so uploading `_basis_last` itself let apply_rank1's
    NATIVE host scatter mutate the "device" array in place — the jitted
    scatter then added the delta again and the device basis drifted to
    snapshot + demand on every commit.  The upload must own its bytes."""
    import jax

    from nomad_tpu.parallel.world import DeviceWorld

    N, R = 16, 4
    world = DeviceWorld(mesh=None)
    capacity = np.full((N, R), 100.0, np.float32)
    world.update(capacity, np.zeros((N, R), np.float32))

    rows = np.array([0, 3], np.int32)
    demand = np.array([5.0, 2.0, 0.0, 0.0], np.float32)
    world.apply_rank1(rows, np.ones(2, np.int32), demand)

    _, basis_dev = world.device_arrays()
    got = np.asarray(jax.device_get(basis_dev)).copy()
    expect = np.zeros((N, R), np.float32)
    expect[rows] = demand
    np.testing.assert_array_equal(got, expect)
    np.testing.assert_array_equal(world.host_basis(), expect)


def test_engine_single_device_world_resident_across_evals():
    """The unsharded engine path keeps the world device-resident: the
    second eval's dispatch diffs clean against the post-commit snapshot
    (zero rows scattered, no second full upload) and placements match a
    from-scratch engine seeing the same committed state."""
    cm = ClusterMatrix()
    for _ in range(32):
        cm.upsert_node(mock.node())
    j = mock.batch_job()
    j.task_groups[0].count = 8
    st = DenseStack(cm)
    g = st.compile_group(j, j.task_groups[0])
    N = cm.n_rows
    demand = np.zeros(cm.used.shape[1], np.float32)
    dm = np.asarray(g.demand, np.float32)
    demand[:min(len(dm), len(demand))] = dm[:len(demand)]
    bulk = dict(feasible=g.feasible, affinity=g.affinity.astype(np.float32),
                has_affinity=bool(g.has_affinity), desired=8,
                penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
                demand=g.demand.astype(np.float32), count=8)

    def one_eval(eng):
        assign, placed, _e, _x, _s, ticket = eng.place_bulk(cm, **bulk)
        rows = np.flatnonzero(assign)
        for r in rows:
            cm.used[r] += assign[r] * demand
        if ticket is not None:
            eng.complete(ticket)
        return np.asarray(assign).copy()

    used0 = cm.used.copy()
    eng = PlacementEngine(shard_min_nodes=1 << 30)   # force single-device
    try:
        a1 = one_eval(eng)
        a2 = one_eval(eng)
        world = next(iter(eng._worlds.values()))
        assert world.stats["full_uploads"] == 1
        assert world.stats["rows_scattered"] == 0    # commits kept it clean
        assert world.stats["rank1_applies"] >= 1
    finally:
        eng.stop()

    committed = cm.used.copy()
    cm.used[:] = used0
    for r in np.flatnonzero(a1):
        cm.used[r] += a1[r] * demand
    fresh = PlacementEngine(shard_min_nodes=1 << 30)
    try:
        a2_fresh = one_eval(fresh)
    finally:
        fresh.stop()
    np.testing.assert_array_equal(a2, a2_fresh)
    np.testing.assert_array_equal(cm.used, committed)
