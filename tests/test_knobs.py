"""The NOMAD_TPU_* knob registry (`nomad_tpu/knobs.py`).

Every env knob the runtime consults is declared once in `knobs.KNOBS`
and read through the typed accessors; the `knob-registry` static
checker enforces the other side (no raw `os.environ` reads of
`NOMAD_TPU_*` outside the registry).  These tests pin the accessor
semantics the call sites rely on — in particular that every registered
knob parses its own default.
"""
import os

import pytest

from nomad_tpu import knobs

_GETTER = {"str": knobs.get_str, "int": knobs.get_int,
           "float": knobs.get_float, "bool": knobs.get_bool}


@pytest.mark.parametrize("name", sorted(knobs.KNOBS))
def test_every_registered_knob_parses_its_own_default(name):
    knob = knobs.KNOBS[name]
    assert knob.type in _GETTER, f"{name}: unknown type {knob.type!r}"
    assert knob.doc.strip(), f"{name}: empty doc"
    # unset environment (env={}) must resolve the registry default
    # without raising; an empty default means "auto" (None/""/False)
    value = _GETTER[knob.type](name, env={})
    if knob.default == "":
        assert value in (None, "", False)
    elif knob.type == "int":
        assert value == int(knob.default)
    elif knob.type == "float":
        assert value == float(knob.default)
    elif knob.type == "bool":
        assert isinstance(value, bool)
    else:
        assert value == knob.default


def test_env_value_beats_registry_and_call_site_default():
    env = {"NOMAD_TPU_PLAN_BATCH": "7"}
    assert knobs.get_int("NOMAD_TPU_PLAN_BATCH", env=env) == 7
    assert knobs.get_int("NOMAD_TPU_PLAN_BATCH", default=99,
                         env=env) == 7


def test_call_site_default_beats_registry_default():
    assert knobs.get_int("NOMAD_TPU_WAVE", default=6, env={}) == 6
    assert knobs.get_float("NOMAD_TPU_HEARTBEAT_BATCH_MS",
                           default=25.0, env={}) == 25.0


def test_empty_string_counts_as_unset():
    env = {"NOMAD_TPU_WAVE_SHARDS": ""}
    assert knobs.get_int("NOMAD_TPU_WAVE_SHARDS", env=env) is None
    assert knobs.get_bool("NOMAD_TPU_FUSE",
                          env={"NOMAD_TPU_FUSE": ""}) is True


@pytest.mark.parametrize("raw,want", [
    ("0", False), ("false", False), ("No", False), ("OFF", False),
    ("1", True), ("true", True), ("yes", True), ("2", True),
])
def test_bool_parse_table(raw, want):
    assert knobs.get_bool("NOMAD_TPU_TRACE",
                          env={"NOMAD_TPU_TRACE": raw}) is want


def test_unregistered_knob_is_a_hard_error():
    with pytest.raises(KeyError):
        knobs.get_str("NOMAD_TPU_NO_SUCH_KNOB")
    with pytest.raises(KeyError):
        with knobs.override("NOMAD_TPU_NO_SUCH_KNOB", "1"):
            pass


def test_override_scopes_and_restores():
    assert "NOMAD_TPU_PLAN_BATCH" not in os.environ
    with knobs.override("NOMAD_TPU_PLAN_BATCH", 5):
        assert knobs.get_int("NOMAD_TPU_PLAN_BATCH") == 5
        with knobs.override("NOMAD_TPU_PLAN_BATCH", None):
            assert knobs.get_int("NOMAD_TPU_PLAN_BATCH") == 64
        assert os.environ["NOMAD_TPU_PLAN_BATCH"] == "5"
    assert "NOMAD_TPU_PLAN_BATCH" not in os.environ


def test_markdown_table_covers_every_knob():
    table = knobs.markdown_table()
    for name in knobs.KNOBS:
        assert f"`{name}`" in table
