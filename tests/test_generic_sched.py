"""End-to-end scheduler tests through the harness (reference analog:
scheduler/generic_sched_test.go)."""
import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import AllocClientStatus, AllocDesiredStatus, EvalStatus
from nomad_tpu.structs.evaluation import EvalTrigger


def make_world(h, n_nodes=10):
    nodes = [mock.node() for _ in range(n_nodes)]
    for n in nodes:
        h.store.upsert_node(h.next_index(), n)
    return nodes


def register_and_eval(h, job):
    h.store.upsert_job(h.next_index(), job)
    ev = mock.eval(job_id=job.id, type=job.type, priority=job.priority)
    h.store.upsert_evals(h.next_index(), [ev])
    return ev


def test_service_job_register_places_all():
    h = Harness()
    make_world(h, 10)
    job = mock.job()                      # count=10
    ev = register_and_eval(h, job)
    h.process("service", ev)

    assert len(h.plans) == 1
    placed = h.store.allocs_by_job("default", job.id)
    assert len(placed) == 10
    nodes_used = {a.node_id for a in placed}
    assert len(nodes_used) == 10          # anti-affinity spreads
    for a in placed:
        assert a.desired_status == AllocDesiredStatus.RUN
        assert a.metrics.nodes_evaluated == 10
        assert a.metrics.score_meta            # top-K recorded
    assert ev.queued_allocations == {"web": 0}


def test_insufficient_capacity_creates_blocked_eval():
    h = Harness()
    make_world(h, 2)
    job = mock.job()
    job.task_groups[0].count = 4
    job.task_groups[0].tasks[0].resources.cpu = 3000   # only one per node
    ev = register_and_eval(h, job)
    h.process("service", ev)

    placed = h.store.allocs_by_job("default", job.id)
    assert len(placed) == 2
    assert ev.queued_allocations["web"] == 2
    blocked = [e for e in h.create_evals_list if e.status == EvalStatus.BLOCKED]
    assert len(blocked) == 1
    assert ev.blocked_eval == blocked[0].id
    assert blocked[0].class_eligibility    # keyed for unblocking


def test_no_feasible_nodes():
    h = Harness()
    make_world(h, 3)
    from nomad_tpu.structs.job import Constraint
    job = mock.job()
    job.constraints.append(Constraint("${attr.kernel.name}", "windows"))
    ev = register_and_eval(h, job)
    h.process("service", ev)
    assert h.store.allocs_by_job("default", job.id) == []
    assert ev.queued_allocations["web"] == 10


def test_job_update_destructive_honors_max_parallel():
    h = Harness()
    make_world(h, 10)
    job = mock.job()
    job.update.max_parallel = 3
    ev = register_and_eval(h, job)
    h.process("service", ev)
    assert len(h.store.allocs_by_job("default", job.id)) == 10

    # update the job destructively (new env)
    job2 = job.copy()
    job2.task_groups[0].tasks[0].config = {"command": "/bin/sleep"}
    job2.update = job.update
    h.store.upsert_job(h.next_index(), job2)
    ev2 = mock.eval(job_id=job.id, triggered_by=EvalTrigger.JOB_REGISTER)
    h.process("service", ev2)

    allocs = h.store.allocs_by_job("default", job.id)
    stopped = [a for a in allocs if a.desired_status == AllocDesiredStatus.STOP]
    new_version = [a for a in allocs if a.desired_status == AllocDesiredStatus.RUN
                   and a.job is not None and a.job.version == job2.version]
    assert len(stopped) == 3               # max_parallel
    assert len(new_version) == 3


def test_job_update_inplace_when_compatible():
    h = Harness()
    make_world(h, 5)
    job = mock.job()
    job.task_groups[0].count = 5
    ev = register_and_eval(h, job)
    h.process("service", ev)
    before = {a.id for a in h.store.allocs_by_job("default", job.id)}

    job2 = job.copy()
    job2.priority = 70                     # non-destructive change
    h.store.upsert_job(h.next_index(), job2)
    ev2 = mock.eval(job_id=job.id)
    h.process("service", ev2)

    allocs = h.store.allocs_by_job("default", job.id)
    run = [a for a in allocs if a.desired_status == AllocDesiredStatus.RUN]
    assert {a.id for a in run} == before   # same allocs, updated in place
    assert all(a.job.version == job2.version for a in run)


def test_scale_down_stops_highest_indices():
    h = Harness()
    make_world(h, 6)
    job = mock.job()
    job.task_groups[0].count = 6
    ev = register_and_eval(h, job)
    h.process("service", ev)

    job2 = job.copy()
    job2.task_groups[0].count = 2
    h.store.upsert_job(h.next_index(), job2)
    h.process("service", mock.eval(job_id=job.id))

    allocs = h.store.allocs_by_job("default", job.id)
    run = [a for a in allocs if a.desired_status == AllocDesiredStatus.RUN]
    assert len(run) == 2
    assert sorted(a.index() for a in run) == [0, 1]


def test_stop_job_stops_everything():
    h = Harness()
    make_world(h, 4)
    job = mock.job()
    job.task_groups[0].count = 4
    h.process("service", register_and_eval(h, job))
    job2 = job.copy()
    job2.stop = True
    h.store.upsert_job(h.next_index(), job2)
    h.process("service", mock.eval(job_id=job.id, triggered_by=EvalTrigger.JOB_DEREGISTER))
    allocs = h.store.allocs_by_job("default", job.id)
    assert all(a.desired_status == AllocDesiredStatus.STOP for a in allocs)


def test_failed_alloc_batch_reschedules_immediately():
    h = Harness()
    nodes = make_world(h, 3)
    job = mock.batch_job()
    job.task_groups[0].count = 1
    ev = register_and_eval(h, job)
    h.process("batch", ev)
    allocs = h.store.allocs_by_job("default", job.id)
    assert len(allocs) == 1

    failed = allocs[0].copy()
    failed.client_status = AllocClientStatus.FAILED
    h.store.update_allocs_from_client(h.next_index(), [failed])
    h.process("batch", mock.eval(job_id=job.id, type="batch",
                                 triggered_by=EvalTrigger.RETRY_FAILED_ALLOC))
    allocs = h.store.allocs_by_job("default", job.id)
    run = [a for a in allocs if a.desired_status == AllocDesiredStatus.RUN
           and not a.client_terminal_status()]
    assert len(run) == 1
    assert run[0].previous_allocation == failed.id
    assert run[0].reschedule_tracker is not None
    # penalized away from the failed node when alternatives exist
    assert run[0].node_id != failed.node_id


def test_failed_service_alloc_creates_delayed_followup():
    h = Harness()
    make_world(h, 2)
    job = mock.job()
    job.task_groups[0].count = 1
    h.process("service", register_and_eval(h, job))
    a = h.store.allocs_by_job("default", job.id)[0].copy()
    a.client_status = AllocClientStatus.FAILED
    h.store.update_allocs_from_client(h.next_index(), [a])

    h.process("service", mock.eval(job_id=job.id))
    followups = [e for e in h.create_evals_list if e.wait_until > 0]
    assert len(followups) == 1
    assert followups[0].triggered_by == EvalTrigger.RETRY_FAILED_ALLOC


def test_node_down_replaces_allocs():
    h = Harness()
    nodes = make_world(h, 3)
    job = mock.job()
    job.task_groups[0].count = 3
    h.process("service", register_and_eval(h, job))

    victim = h.store.allocs_by_job("default", job.id)[0]
    h.store.update_node_status(h.next_index(), victim.node_id, "down")
    h.process("service", mock.eval(job_id=job.id, triggered_by=EvalTrigger.NODE_UPDATE))

    allocs = h.store.allocs_by_job("default", job.id)
    lost = [a for a in allocs if a.client_status == AllocClientStatus.LOST]
    assert len(lost) == 1 and lost[0].id == victim.id
    run = [a for a in allocs if a.desired_status == AllocDesiredStatus.RUN
           and a.client_status != AllocClientStatus.LOST]
    assert len(run) == 3
    assert all(a.node_id != victim.node_id for a in run)


def test_partial_plan_rejection_retries():
    h = Harness()
    make_world(h, 4)
    job = mock.job()
    job.task_groups[0].count = 4
    ev = register_and_eval(h, job)
    h.reject_plan = True
    with pytest.raises(Exception):
        h.process("service", ev)
    assert len(h.plans) == 5               # MAX_SERVICE_SCHEDULE_ATTEMPTS


def test_system_job_places_one_per_node():
    h = Harness()
    nodes = make_world(h, 5)
    job = mock.system_job()
    ev = register_and_eval(h, job)
    h.process("system", ev)
    allocs = h.store.allocs_by_job("default", job.id)
    assert len(allocs) == 5
    assert {a.node_id for a in allocs} == {n.id for n in nodes}
    # a new node arriving gets the system job too
    extra = mock.node()
    h.store.upsert_node(h.next_index(), extra)
    h.process("system", mock.eval(job_id=job.id, type="system",
                                  triggered_by=EvalTrigger.NODE_UPDATE))
    allocs = h.store.allocs_by_job("default", job.id)
    assert len(allocs) == 6


def test_sysbatch_does_not_rerun_completed():
    h = Harness()
    nodes = make_world(h, 2)
    job = mock.sysbatch_job()
    h.process("sysbatch", register_and_eval(h, job))
    allocs = h.store.allocs_by_job("default", job.id)
    assert len(allocs) == 2
    done = allocs[0].copy()
    done.client_status = AllocClientStatus.COMPLETE
    h.store.update_allocs_from_client(h.next_index(), [done])
    h.process("sysbatch", mock.eval(job_id=job.id, type="sysbatch"))
    allocs = h.store.allocs_by_job("default", job.id)
    assert len(allocs) == 2                # no rerun
