"""HTTP API + SDK tests (reference analog: command/agent/*_endpoint_test.go
and api/ tests run against a dev agent)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient, ApiError
from nomad_tpu.api.codec import from_wire, to_wire
from nomad_tpu.structs import Job


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(http_port=0, num_schedulers=2,
                          heartbeat_ttl=60.0))
    a.start()
    for _ in range(4):
        a.server.register_node(mock.node())
    yield a
    a.stop()


@pytest.fixture(scope="module")
def api(agent):
    return ApiClient(agent.http_addr)


def test_codec_roundtrip():
    job = mock.job()
    wire = to_wire(job)
    back = from_wire(Job, wire)
    assert back.id == job.id
    assert back.task_groups[0].tasks[0].resources.cpu == \
        job.task_groups[0].tasks[0].resources.cpu
    assert back.task_groups[0].count == job.task_groups[0].count


def test_status_and_agent(api):
    assert api.system.leader() is not None
    assert api.system.peers()
    self_info = api.system.agent_self()
    assert self_info["stats"]["server"] is True


def test_node_list_and_info(api):
    nodes = api.nodes.list()
    assert len(nodes) == 4
    info = api.nodes.info(nodes[0]["ID"])
    assert info.id == nodes[0]["ID"]
    assert info.status == "ready"


def test_job_register_flow(api, agent):
    job = mock.job()
    resp = api.jobs.register(job)
    assert resp["EvalID"]
    agent.server.wait_for_idle(10.0)
    got = api.jobs.info(job.id)
    assert got.id == job.id
    allocs = api.jobs.allocations(job.id)
    assert len(allocs) == job.task_groups[0].count
    evals = api.jobs.evaluations(job.id)
    assert any(e.status == "complete" for e in evals)
    # eval detail + allocations
    ev = api.evaluations.info(resp["EvalID"])
    assert ev.job_id == job.id
    # alloc detail + stop
    alloc = api.allocations.info(allocs[0]["ID"])
    assert alloc.job_id == job.id
    stop = api.allocations.stop(alloc.id)
    assert stop["eval_id"]


def test_job_deregister(api, agent):
    job = mock.job()
    api.jobs.register(job)
    agent.server.wait_for_idle(10.0)
    api.jobs.deregister(job.id)
    agent.server.wait_for_idle(10.0)
    got = api.jobs.info(job.id)
    assert got.stop is True


def test_missing_job_404(api):
    with pytest.raises(ApiError) as e:
        api.jobs.info("nope-" + "0" * 8)
    assert e.value.status == 404


def test_operator_scheduler_config(api):
    cfg = api.operator.scheduler_get_configuration()
    assert cfg.scheduler_algorithm in ("binpack", "spread")
    cfg.scheduler_algorithm = "spread"
    api.operator.scheduler_set_configuration(cfg)
    got = api.operator.scheduler_get_configuration()
    assert got.scheduler_algorithm == "spread"
    got.scheduler_algorithm = "binpack"
    api.operator.scheduler_set_configuration(got)


def test_search(api, agent):
    job = mock.job()
    api.jobs.register(job)
    agent.server.wait_for_idle(5.0)
    res = api.system.search(job.id[:8], "jobs")
    assert job.id in res["Matches"]["jobs"]


def test_namespaces(api):
    api.namespaces.register("ops", "ops namespace")
    names = {n["name"] for n in api.namespaces.list()}
    assert {"default", "ops"} <= names
    api.namespaces.delete("ops")
    names = {n["name"] for n in api.namespaces.list()}
    assert "ops" not in names


def test_metrics_endpoint(api):
    from nomad_tpu.telemetry import global_metrics
    global_metrics.incr("test.counter")
    snap = api.system.metrics()
    assert any(c["Name"] == "test.counter" for c in snap["Counters"])


def test_blocking_query_returns_after_index(api, agent):
    idx = agent.server.store.latest_index
    t0 = time.time()
    # a blocking query on a stale index returns immediately
    api._request("GET", "/v1/jobs", {"index": "0", "wait": "2s"})
    assert time.time() - t0 < 1.0
    # on the current index it waits ~the wait time unless something changes
    t0 = time.time()
    api._request("GET", "/v1/jobs", {"index": str(idx + 1000), "wait": "300ms"})
    assert time.time() - t0 >= 0.25


def test_job_plan_dry_run(api, agent):
    job = mock.job()
    resp = api.jobs.plan(job)
    assert resp["placements"] == job.task_groups[0].count
    # nothing was committed
    with pytest.raises(ApiError):
        api.jobs.info(job.id)


def test_job_dispatch_parameterized(api, agent):
    job = mock.job()
    from nomad_tpu.structs.job import ParameterizedJobConfig
    job.parameterized = ParameterizedJobConfig(
        payload="optional", meta_required=["env"])
    api.jobs.register(job)
    agent.server.wait_for_idle(5.0)
    resp = api.jobs.dispatch(job.id, payload="aGk=", meta={"env": "prod"})
    assert resp["dispatched_job_id"].startswith(job.id + "/dispatch-")
    agent.server.wait_for_idle(5.0)
    child = api.jobs.info(resp["dispatched_job_id"])
    assert child.parent_id == job.id
    # missing required meta rejected
    with pytest.raises(ApiError):
        api.jobs.dispatch(job.id, meta={})


def test_event_stream(api, agent):
    seen = []
    import threading

    def consume():
        try:
            for frame in api.system.event_stream(
                    topics=["Job"], timeout=2.0):
                seen.extend(frame.get("Events", []))
        except Exception:
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)
    job = mock.job()
    api.jobs.register(job)
    t.join(5.0)
    assert any(e.get("Key") == job.id for e in seen)


def test_agent_pprof_and_monitor(api, agent):
    """VERDICT r3 item 9: /v1/agent/pprof/profile serves a real cProfile
    dump and /v1/agent/monitor streams real log lines."""
    import json
    import logging
    import threading
    import urllib.request

    prof = api.get("/v1/agent/pprof/profile?seconds=0.2")
    assert prof["seconds"] == 0.2
    assert "cumulative" in prof["profile"] or "ncalls" in prof["profile"]

    stacks = api.get("/v1/agent/pprof/goroutine")
    assert "Thread" in stacks["stacks"] or "File" in stacks["stacks"]

    # monitor: emit a log line while the stream is open and find it
    def emit():
        time.sleep(0.3)
        logging.getLogger("nomad_tpu.test").info("monitor-probe-line")
    t = threading.Thread(target=emit, daemon=True)
    t.start()
    with urllib.request.urlopen(
            f"{agent.http_addr}/v1/agent/monitor?timeout=2.0",
            timeout=10) as resp:
        body = resp.read().decode()
    t.join()
    assert "monitor-probe-line" in body


def test_agent_pprof_kinds(api):
    """r12 satellite: the 'threads' alias serves stacks, a zero-length
    ?seconds= window returns immediately with a stats dump, and unknown
    pprof kinds 404 instead of profiling."""
    stacks = api.get("/v1/agent/pprof/threads")
    assert "Thread" in stacks["stacks"]

    t0 = time.time()
    prof = api.get("/v1/agent/pprof/profile?seconds=0")
    assert time.time() - t0 < 5.0
    assert prof["seconds"] == 0
    assert "profile" in prof

    with pytest.raises(ApiError) as e:
        api.get("/v1/agent/pprof/heap")
    assert e.value.status == 404


def test_agent_monitor_stream_teardown(api, agent):
    """r12 satellite: the monitor stream must (a) terminate itself with
    the chunked terminator when ?timeout= expires and (b) absorb a client
    that slams the socket shut mid-stream without taking the agent
    down."""
    import logging
    import socket
    import urllib.request

    # (a) expiry terminator: the read completes when the window closes —
    # urllib only returns once the 0-length chunk arrives
    t0 = time.time()
    with urllib.request.urlopen(
            f"{agent.http_addr}/v1/agent/monitor?timeout=0.5",
            timeout=10) as resp:
        resp.read()
    assert time.time() - t0 < 8.0

    # (b) mid-stream disconnect: raw socket so we can hard-close while
    # the server is still following the log ring
    host, port = agent.http_addr.replace("http://", "").split(":")
    sk = socket.create_connection((host, int(port)), timeout=5)
    sk.sendall(b"GET /v1/agent/monitor?timeout=30 HTTP/1.1\r\n"
               b"Host: x\r\nConnection: close\r\n\r\n")
    sk.recv(4096)                       # status line (+ ring replay)
    sk.close()
    # force the server to write into the dead socket
    for _ in range(3):
        logging.getLogger("nomad_tpu.test").info("teardown-probe-line")
        time.sleep(0.2)
    assert api.system.leader() is not None


def test_job_scale_http(api, agent):
    from nomad_tpu.structs.job import ScalingPolicy
    j = mock.job(id="scale-http-job")
    tg = j.task_groups[0]
    tg.count = 1
    tg.scaling = ScalingPolicy(min=1, max=3)
    api.jobs.register(j)
    deadline = time.time() + 30
    while time.time() < deadline:
        if any(not a["ClientStatus"] == "lost"
               for a in api.jobs.allocations(j.id)):
            break
        time.sleep(0.1)
    resp = api.jobs.scale(j.id, tg.name, count=2)
    assert resp.get("eval_id")
    st = api.jobs.scale_status(j.id)
    assert st["task_groups"][tg.name]["desired"] == 2
    pols = api.get("/v1/scaling/policies")
    assert any(p["target"]["Job"] == j.id for p in pols)


def test_regions_endpoint(api):
    assert api.system.regions() == ["global"]
