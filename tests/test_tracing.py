"""Distributed tracing tests (r12 tentpole): context propagation end to
end through the spine, raft span attribution, federation-hop survival,
span-store bounds, sampling, and Chrome-trace export.  This file is also
the CI `tracing` leg's payload — it must stay green under
NOMAD_TPU_RACE=1."""
import io
import json
import threading
import time

import pytest

from nomad_tpu import mock, tracing
from nomad_tpu.tracing import TRACE_KEY, Tracer, chrome_trace


def _wait(cond, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def tracer():
    t = Tracer(sample_rate=1.0, seed=42)
    tracing.install(t)
    yield t
    tracing.uninstall()


def _assert_causal(spans):
    """Every non-root span's parent must be another span in the trace."""
    ids = {s.span_id for s in spans}
    roots = [s for s in spans if not s.parent_id]
    assert roots, [s.name for s in spans]
    for s in spans:
        if s.parent_id:
            assert s.parent_id in ids, (s.name, s.parent_id)


# ------------------------------------------------------------- unit layer


def test_sample_rate_zero_is_silent():
    t = Tracer(sample_rate=0.0, seed=3)
    assert all(t.new_context() is None for _ in range(100))
    assert t.traces() == []


def test_sampling_rate_is_honored():
    t = Tracer(sample_rate=0.25, seed=11)
    hits = sum(t.new_context() is not None for _ in range(4000))
    assert 800 < hits < 1200, hits


def test_uninstalled_guard_is_none():
    assert tracing.active is None
    assert tracing.current() is None


def test_span_store_ring_is_bounded():
    t = Tracer(sample_rate=1.0, seed=1, store_limit=64)
    ctx = t.new_context()
    for i in range(500):
        t.emit(ctx, f"s{i}", float(i), float(i) + 1.0, node="n1")
    assert len(t.store_for("n1")) == 64
    # the ring keeps the newest spans
    names = {s.name for s in t.spans(ctx["t"])}
    assert "s499" in names and "s0" not in names


def test_span_store_concurrent_add_and_snapshot(tracer):
    """Hammer one store from writers while snapshotting — the shape the
    race detector (NOMAD_TPU_RACE=1) audits via SpanStore._RACE_TRACED."""
    ctx = tracer.new_context()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            tracer.emit(ctx, "w", 0.0, 1.0, node="n")

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(50):
            tracer.spans(ctx["t"])
            tracer.traces()
    finally:
        stop.set()
        for th in threads:
            th.join()
    assert len(tracer.store_for("n")) <= tracer.store_limit


def test_eval_note_table_is_bounded():
    t = Tracer(sample_rate=1.0, seed=2)
    ctx = t.new_context()
    for i in range(t._NOTE_LIMIT + 100):
        t.note_eval(f"ev-{i}", ctx)
    assert len(t._eval_notes) == t._NOTE_LIMIT
    # oldest evicted first, newest retrievable
    assert t.take_eval_note("ev-0") is None
    assert t.take_eval_note(f"ev-{t._NOTE_LIMIT + 99}") is not None


def test_chrome_trace_export_shape():
    t = Tracer(sample_rate=1.0, seed=5)
    ctx = t.new_context()
    root = t.start(ctx, "root", "n1")
    child = t.start(t.child_ctx(ctx, root), "child", "n2")
    t.finish(child)
    t.finish(root)
    doc = chrome_trace([s.to_dict() for s in t.spans(ctx["t"])])
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert all(isinstance(e["pid"], int) for e in evs)
    meta = [e for e in evs
            if e.get("ph") == "M" and e["name"] == "process_name"]
    assert {m["args"]["name"] for m in meta} == {"n1", "n2"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 2
    assert all("ts" in e and "dur" in e and
               e["args"]["trace_id"] == ctx["t"] for e in xs)
    json.dumps(doc)     # must be JSON-serializable as-is


# ------------------------------------------------------ dev agent (HTTP)


def test_dev_agent_http_chain_and_api(tracer):
    """HTTP ingress starts the root span; the context rides the RPC args
    through scheduler invoke, plan submit, and the dev-mode apply; the
    trace is served back over /v1/traces and exports via ?format=chrome.
    Flipping the sample rate to 0 silences new requests entirely."""
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api import ApiClient

    a = Agent(AgentConfig(http_port=0, num_schedulers=2,
                          heartbeat_ttl=60.0))
    a.start()
    try:
        for _ in range(3):
            a.server.register_node(mock.node())
        api = ApiClient(a.http_addr)
        j = mock.job()
        api.jobs.register(j)
        a.server.wait_for_idle(10.0)

        reg = _wait_trace(tracer, "http.PUT /v1/jobs",
                          {"plan.submit", "raft.fsm_apply"})
        spans = tracer.spans(reg["trace_id"])
        names = {s.name for s in spans}
        for want in ("http.PUT /v1/jobs", "rpc.Job.Register",
                     "broker.wait", "plan.submit", "plan.queue_wait",
                     "plan.evaluate", "raft.fsm_apply"):
            assert want in names, (want, sorted(names))
        assert any(n.startswith("worker.invoke_scheduler.")
                   for n in names), sorted(names)
        assert len(spans) >= 6
        _assert_causal(spans)

        # the trace API serves what the store holds
        listed = api.operator.traces()
        assert any(t["trace_id"] == reg["trace_id"] for t in listed)
        got = api.operator.trace(reg["trace_id"])
        assert len(got["spans"]) == len(spans)
        doc = api.operator.trace_chrome(reg["trace_id"])
        assert len([e for e in doc["traceEvents"]
                    if e["ph"] == "X"]) == len(spans)

        # CLI: list, show, export
        from nomad_tpu.command.cli import main as cli_main
        out = io.StringIO()
        assert cli_main(["-address", a.http_addr, "operator", "trace"],
                        out=out) == 0
        assert reg["trace_id"] in out.getvalue()
        out = io.StringIO()
        assert cli_main(["-address", a.http_addr, "operator", "trace",
                         reg["trace_id"]], out=out) == 0
        assert "plan.submit" in out.getvalue()

        # sampling off: new requests produce no new traces
        tracer.sample_rate = 0.0
        before = len(tracer.traces())
        api.nodes.list()
        api.jobs.register(mock.job())
        a.server.wait_for_idle(10.0)
        time.sleep(0.2)
        assert len(tracer.traces()) == before
    finally:
        a.stop()


def _wait_trace(tracer, root_name, want_names, timeout=15.0):
    """Wait until a trace rooted at `root_name` contains `want_names`
    (spans land asynchronously as observe-time emission catches up)."""
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        for t in tracer.traces():
            if t["root"] == root_name:
                last = t
                names = {s.name for s in tracer.spans(t["trace_id"])}
                if want_names <= names:
                    return t
        time.sleep(0.1)
    raise AssertionError(
        f"no trace rooted at {root_name!r} grew spans {want_names}; "
        f"last={last}")


# --------------------------------------------------- 3-server raft spine


def test_cluster_plan_submit_trace_has_raft_spans(tracer):
    """The acceptance trace: one sampled register on a real 3-server
    raft spine shows the causally-linked chain rpc -> broker wait ->
    scheduler invoke -> plan submit/evaluate -> raft append (WAL+fsync
    window) -> commit -> fsm apply."""
    from nomad_tpu.core.cluster import Cluster

    c = Cluster(n=3)
    c.start()
    try:
        leader = c.leader(10.0)
        for _ in range(3):
            leader.register_node(mock.node())
        ctx = tracer.new_context()
        j = mock.job()
        j.task_groups[0].count = 2
        leader.endpoints.handle("Job.Register",
                                {"job": j, TRACE_KEY: ctx})
        assert _wait(lambda: len(
            leader.store.allocs_by_job("default", j.id)) == 2, 30)
        assert _wait(lambda: {"raft.fsm_apply", "plan.submit"} <=
                     {s.name for s in tracer.spans(ctx["t"])}, 10)

        spans = tracer.spans(ctx["t"])
        names = {s.name for s in spans}
        for want in ("rpc.Job.Register", "broker.wait", "plan.submit",
                     "plan.queue_wait", "plan.evaluate", "raft.append",
                     "raft.commit", "raft.fsm_apply"):
            assert want in names, (want, sorted(names))
        assert len(spans) >= 6
        _assert_causal(spans)
        # all spans share the one trace id; raft spans carry the index
        assert {s.trace_id for s in spans} == {ctx["t"]}
        assert any(s.name == "raft.append" and
                   s.attrs and "index" in s.attrs for s in spans)
    finally:
        c.stop()


# ------------------------------------------------------------ federation


def test_federation_hop_preserves_trace_id(tracer):
    """A forwarded RPC keeps its trace context across the WAN hop: the
    remote region's rpc span lands under the SAME trace_id, attributed
    to the remote server."""
    from nomad_tpu.core.cluster import FederatedCluster
    from nomad_tpu.core.server import ServerConfig
    from nomad_tpu.raft import RaftConfig

    fc = FederatedCluster(
        regions=("global", "west"), n=1,
        config=ServerConfig(num_schedulers=2, heartbeat_ttl=60.0),
        raft_config=RaftConfig(heartbeat_interval=0.02,
                               election_timeout=0.1))
    fc.start()
    fc.wait_federated(20.0)
    try:
        g = fc.leader("global", 10.0)
        w = fc.leader("west", 10.0)
        w.register_node(mock.node())
        ctx = tracer.new_context()
        j = mock.job()
        j.region = "west"
        j.task_groups[0].count = 1
        g.endpoints.handle("Job.Register", {"job": j, TRACE_KEY: ctx})
        assert _wait(lambda: any(
            s.name == "rpc.Job.Register"
            for s in tracer.spans(ctx["t"])), 10)
        assert _wait(lambda: w.name in {
            s.node for s in tracer.spans(ctx["t"])
            if s.name == "rpc.Job.Register"}, 10)
        spans = tracer.spans(ctx["t"])
        rpc_spans = [s for s in spans if s.name == "rpc.Job.Register"]
        # the ingress dispatch on global AND the forwarded handling on
        # west both land under the SAME trace id
        assert {s.node for s in rpc_spans} == {g.name, w.name}
        assert {s.trace_id for s in spans} == {ctx["t"]}
        # the register landed where it was routed
        assert _wait(lambda: w.store.job_by_id("default", j.id) is not None, 10)
        assert g.store.job_by_id("default", j.id) is None
    finally:
        fc.stop()


def test_spans_carry_no_token_material(tracer, monkeypatch):
    """Multi-tenant guarantee: ACL secrets never land in span names,
    nodes, or attrs — whether the token arrives via the X-Nomad-Token
    header or the ?token= query fallback.  A leaked secret in the trace
    plane would hand every operator with read access to /v1/traces a
    management credential."""
    import json as _json

    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.api import ApiClient

    monkeypatch.setenv("NOMAD_TPU_ACL", "1")
    a = Agent(AgentConfig(http_port=0, num_schedulers=2,
                          heartbeat_ttl=60.0))
    a.start()
    try:
        a.server.register_node(mock.node())
        boot = a.server.bootstrap_acl()
        secret = boot.secret_id
        api = ApiClient(a.http_addr, token=secret)
        j = mock.job()
        j.task_groups[0].count = 1
        api.jobs.register(j)
        a.server.wait_for_idle(10.0)
        # query-param token path (the header-less fallback)
        bare = ApiClient(a.http_addr)
        bare.get(f"/v1/jobs?token={secret}")
        bare.put(f"/v1/namespaces?token={secret}",
                 {"Name": "traced-ns"})
        assert _wait(lambda: len(tracer.spans()) > 5)

        blob = _json.dumps([s.to_dict() for s in tracer.spans()])
        assert secret not in blob
        # accessor ids are not secrets, but the secret must not appear
        # in any recorded eval notes either
        assert all(secret not in str(v)
                   for v in tracer._eval_notes.values())
    finally:
        a.stop()
