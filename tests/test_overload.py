"""End-to-end deadline propagation, admission control and brownout
shedding (the overload tentpole).

Covers the deadline ctx module and its wire roundtrip (+ the
`overload.deadline_skew` chaos), the per-namespace AdmissionGate at the
HTTP front door and the Eval.Dequeue / Plan.Submit RPC edges, the
BrownoutMonitor's strict shed ordering (submissions first, stale reads
last, liveness never), deadline checks at every queueing stage (broker
dequeue, plan applier pre-commit, worker retry loops), and the
deadline-aware ApiClient retry satellite.  Every refusal must be an
EXPLICIT 503/504 with a Retry-After hint — never an accepted request
silently dropped.
"""
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from nomad_tpu import chaos, deadline, mock
from nomad_tpu.admission import (
    AdmissionDenied,
    AdmissionGate,
    BrownoutMonitor,
    SHED_NEVER,
)
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.api import ApiClient
from nomad_tpu.api.client import ApiError
from nomad_tpu.chaos import ChaosRegistry
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.core.worker import RemoteWorker
from nomad_tpu.deadline import DeadlineExceeded
from nomad_tpu.rpc.endpoints import RpcError
from nomad_tpu.state import StateStore
from nomad_tpu.structs.plan import Plan
from nomad_tpu.telemetry import global_metrics


def _counter(name):
    for c in global_metrics.snapshot()["Counters"]:
        if c["Name"] == name:
            return c["Count"]
    return 0.0


# ------------------------------------------------------ deadline module


def test_deadline_bind_remaining_expired():
    assert deadline.current() is None
    assert deadline.remaining() is None
    assert not deadline.expired()
    prev = deadline.bind(time.monotonic() + 5.0)
    try:
        assert prev is None
        rem = deadline.remaining()
        assert 4.0 < rem <= 5.0
        assert not deadline.expired()
    finally:
        deadline.bind(prev)
    assert deadline.current() is None


def test_deadline_check_counts_per_stage():
    before = _counter("deadline.expired.teststage")
    assert not deadline.check("teststage")      # unbound: never expired
    prev = deadline.bind(time.monotonic() - 0.01)
    try:
        assert deadline.check("teststage")
    finally:
        deadline.bind(prev)
    assert _counter("deadline.expired.teststage") == before + 1


def test_deadline_wire_roundtrip_is_relative():
    prev = deadline.bind(time.monotonic() + 3.0)
    try:
        budget = deadline.to_wire()
        assert 2.5 < budget <= 3.0
        # decode on the "other side": lands ~budget from local now —
        # absolute clock values never cross the wire
        dl = deadline.from_wire(budget)
        assert abs((dl - time.monotonic()) - budget) < 0.5
    finally:
        deadline.bind(prev)
    assert deadline.from_wire(-5.0) <= time.monotonic()  # clamped at 0


def test_deadline_default_budget_env(monkeypatch):
    monkeypatch.delenv("NOMAD_TPU_DEFAULT_DEADLINE", raising=False)
    assert deadline.default_budget() is None
    monkeypatch.setenv("NOMAD_TPU_DEFAULT_DEADLINE", "12.5")
    assert deadline.default_budget() == 12.5
    monkeypatch.setenv("NOMAD_TPU_DEFAULT_DEADLINE", "0")
    assert deadline.default_budget() is None
    monkeypatch.setenv("NOMAD_TPU_DEFAULT_DEADLINE", "bogus")
    assert deadline.default_budget() is None


def test_deadline_skew_chaos_is_seeded_and_bounded():
    def skewed(seed):
        reg = ChaosRegistry.from_spec(
            f"seed={seed};overload.deadline_skew=1.0")
        reg.arm(now=0.0)
        chaos.install(reg)
        try:
            return deadline.from_wire(10.0) - time.monotonic()
        finally:
            chaos.uninstall()

    a, b = skewed(7), skewed(7)
    assert abs(a - b) < 0.1                  # same seed, same skew
    assert 0.0 <= a <= 20.5                  # 0x..2x of the budget
    assert abs(skewed(8) - a) > 1e-6 or True  # different seed may differ


# ------------------------------------------------------- admission gate


def test_admission_gate_disabled_by_default(monkeypatch):
    monkeypatch.delenv("NOMAD_TPU_ADMIT_RATE", raising=False)
    monkeypatch.delenv("NOMAD_TPU_ADMIT_CONCURRENCY", raising=False)
    gate = AdmissionGate()
    assert not gate.enabled
    assert gate.try_acquire("any") is None
    gate.release("any")                      # no-op, no tracking


def test_admission_token_bucket_denies_then_refills():
    gate = AdmissionGate(rate=10.0, burst=2.0, max_concurrency=0)
    assert gate.enabled
    assert gate.try_acquire("ns1") is None
    assert gate.try_acquire("ns1") is None
    retry = gate.try_acquire("ns1")          # bucket empty
    assert retry is not None and retry > 0.0
    time.sleep(0.15)                         # ~1.5 tokens refill
    assert gate.try_acquire("ns1") is None


def test_admission_denial_is_per_namespace():
    gate = AdmissionGate(rate=1.0, burst=1.0, max_concurrency=0)
    assert gate.try_acquire("abuser") is None
    assert gate.try_acquire("abuser") is not None   # abuser sheds...
    assert gate.try_acquire("victim") is None       # ...victim admitted


def test_admission_concurrency_slots_and_release():
    gate = AdmissionGate(rate=0.0, max_concurrency=1)
    assert gate.try_acquire("ns") is None
    retry = gate.try_acquire("ns")
    assert retry is not None                 # slot held
    gate.release("ns")
    assert gate.try_acquire("ns") is None    # slot freed
    gate.release("ns")


def test_admission_admit_raises_with_retry_hint():
    gate = AdmissionGate(rate=1.0, burst=1.0)
    gate.admit("ns")
    with pytest.raises(AdmissionDenied) as ei:
        gate.admit("ns")
    assert ei.value.retry_after > 0.0


def test_admission_bucket_table_is_bounded():
    gate = AdmissionGate(rate=100.0, burst=1.0)
    for i in range(1500):
        gate.try_acquire(f"ns-{i}")
    with gate._lock:
        assert len(gate._buckets) <= 1024


# ----------------------------------------------------- brownout monitor


class _StubRaft:
    def __init__(self, depth):
        self._depth = depth
        self.commit_index = 0
        self.last_applied = 0

    def proposal_depth(self):
        return self._depth


class _StubServer:
    def __init__(self, depth):
        self.raft = _StubRaft(depth)


def _brownout(depth):
    # interval=0: re-sample every call so the stub depth takes effect
    return BrownoutMonitor(_StubServer(depth), interval=0.0)


def test_brownout_level_thresholds():
    assert _brownout(0).level() == 0
    assert _brownout(256).level() == 1       # depth_hi default 256
    assert _brownout(512).level() == 2
    assert _brownout(1024).level() == 3


def test_brownout_sheds_submissions_first_reads_later():
    b1 = _brownout(256)                      # level 1
    assert b1.shed("Job.Register") is not None
    assert b1.shed("Job.List", "default") is None
    assert b1.shed("Job.List", "stale") is None

    b2 = _brownout(512)                      # level 2
    assert b2.shed("Job.Register") is not None
    assert b2.shed("Job.List", "default") is not None
    assert b2.shed("Job.List", "stale") is None   # stale reads survive

    b3 = _brownout(5000)                     # level 3: full brownout
    assert b3.shed("Job.List", "stale") is not None


def test_brownout_never_sheds_liveness_or_settlement():
    b3 = _brownout(100000)
    for method in SHED_NEVER:
        assert b3.shed(method) is None, \
            f"{method} must never shed — it is the liveness path"


def test_brownout_apply_lag_is_a_trigger_too():
    srv = _StubServer(0)
    srv.raft.commit_index = 4096
    srv.raft.last_applied = 0                # lag 4096 >> lag_hi 512
    assert BrownoutMonitor(srv, interval=0.0).level() == 3


# --------------------------------------- deadline at the queueing edges


def _plan_for(job, node_id, cpu=500, mem=512):
    j = job
    j.task_groups[0].tasks[0].resources.cpu = cpu
    j.task_groups[0].tasks[0].resources.memory_mb = mem
    alloc = mock.alloc_for(j, node_id=node_id)
    plan = Plan(eval_id=mock._uuid(), job=j)
    plan.append_alloc(alloc, j)
    return plan


def test_applier_rejects_expired_plan_before_commit():
    """An expired pending plan dies with DeadlineExceeded BEFORE the
    commit edge: no raft append, no store write, futures resolved."""
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    applier = PlanApplier(store)
    queue = PlanQueue()
    queue.set_enabled(True)
    before = _counter("deadline.expired.applier")
    prev = deadline.bind(time.monotonic() - 1.0)    # already expired
    try:
        pending = queue.enqueue(_plan_for(mock.job(), node.id))
    finally:
        deadline.bind(prev)
    assert pending.deadline is not None
    stop = threading.Event()
    t = threading.Thread(target=applier.run_loop, args=(queue, stop),
                         daemon=True)
    t.start()
    try:
        with pytest.raises(DeadlineExceeded):
            pending.future.result(timeout=5.0)
        with pytest.raises(DeadlineExceeded):
            pending.evaluated.result(timeout=1.0)
    finally:
        stop.set()
        t.join(2)
    assert applier.stats["applied"] == 0            # commit never ran
    assert store.latest_index == 1                  # store untouched
    assert _counter("deadline.expired.applier") == before + 1


def test_live_deadline_plan_still_commits():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    applier = PlanApplier(store)
    queue = PlanQueue()
    queue.set_enabled(True)
    prev = deadline.bind(time.monotonic() + 30.0)
    try:
        pending = queue.enqueue(_plan_for(mock.job(), node.id))
    finally:
        deadline.bind(prev)
    stop = threading.Event()
    t = threading.Thread(target=applier.run_loop, args=(queue, stop),
                         daemon=True)
    t.start()
    try:
        result = pending.future.result(timeout=5.0)
        assert result.node_allocation
    finally:
        stop.set()
        t.join(2)


def test_remote_worker_rpc_gives_up_when_budget_gone():
    calls = []

    class _Srv:
        def rpc_leader(self, method, args):
            calls.append(method)
            raise RpcError("no_leader", "election in flight")

    w = RemoteWorker.__new__(RemoteWorker)
    w.server = _Srv()
    w._stop = threading.Event()
    before = _counter("deadline.expired.worker")
    # generous enough that a loaded CI machine still lands at least one
    # attempt before the budget dies, far below the 30s rpc deadline
    prev = deadline.bind(time.monotonic() + 0.75)
    try:
        t0 = time.monotonic()
        with pytest.raises(RpcError) as ei:
            w._rpc("Eval.Ack", {}, deadline=30.0)
        assert ei.value.kind == "deadline_exceeded"
        assert time.monotonic() - t0 < 2.0   # clamped, not the full 30s
    finally:
        deadline.bind(prev)
    assert calls, "should have tried at least once before the budget died"
    assert _counter("deadline.expired.worker") == before + 1


def test_remote_worker_rpc_unbound_keeps_prior_behavior():
    class _Srv:
        def rpc_leader(self, method, args):
            raise RpcError("no_leader", "election in flight")

    w = RemoteWorker.__new__(RemoteWorker)
    w.server = _Srv()
    w._stop = threading.Event()
    with pytest.raises(RpcError) as ei:
        w._rpc("Eval.Ack", {}, deadline=0.1)
    assert ei.value.kind == "no_leader"      # original error surfaces


# -------------------------------------------------- HTTP ingress (agent)


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(http_port=0, num_schedulers=1,
                          heartbeat_ttl=60.0))
    a.start()
    a.server.register_node(mock.node())
    yield a
    a.stop()


def _get(agent, path, headers=None):
    req = urllib.request.Request(f"{agent.http_addr}{path}")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_http_deadline_header_expired_is_504(agent):
    code, _, body = _get(agent, "/v1/jobs",
                         {"X-Nomad-Deadline": "0"})
    assert code == 504
    assert b"deadline" in body.lower() or b"budget" in body.lower()


def test_http_deadline_header_generous_is_200(agent):
    code, _, _ = _get(agent, "/v1/jobs", {"X-Nomad-Deadline": "30"})
    assert code == 200


def test_http_deadline_header_invalid_is_400(agent):
    code, _, _ = _get(agent, "/v1/jobs", {"X-Nomad-Deadline": "soon"})
    assert code == 400


def test_http_admission_denies_with_retry_after(agent):
    saved = agent.server.admission
    agent.server.admission = AdmissionGate(rate=0.001, burst=1.0)
    try:
        code, _, _ = _get(agent, "/v1/jobs")
        assert code == 200                   # the one token
        code, headers, body = _get(agent, "/v1/jobs")
        assert code == 503
        assert int(headers["Retry-After"]) >= 1
        assert b"admission" in body.lower()
    finally:
        agent.server.admission = saved


def test_http_admission_concurrency_released_per_request(agent):
    saved = agent.server.admission
    gate = AdmissionGate(rate=0.0, max_concurrency=1)
    agent.server.admission = gate
    try:
        # sequential requests all admit: the finally-release in
        # _dispatch hands the slot back even under keep-alive
        for _ in range(3):
            code, _, _ = _get(agent, "/v1/jobs")
            assert code == 200
        with gate._lock:
            assert gate._inflight == {}
    finally:
        agent.server.admission = saved


def test_http_ingress_flood_chaos_sheds_503(agent):
    reg = ChaosRegistry.from_spec("seed=3;overload.ingress_flood=1.0")
    reg.arm(now=0.0)
    chaos.install(reg)
    try:
        code, headers, _ = _get(agent, "/v1/jobs")
        assert code == 503
        assert "Retry-After" in headers
    finally:
        chaos.uninstall()
    code, _, _ = _get(agent, "/v1/jobs")
    assert code == 200


def test_http_brownout_sheds_submits_not_reads(agent):
    saved = agent.server.brownout
    agent.server.brownout = _brownout(256)   # level 1
    try:
        job = mock.job()
        from nomad_tpu.api.codec import to_wire
        req = urllib.request.Request(
            f"{agent.http_addr}/v1/jobs",
            data=json.dumps({"Job": to_wire(job)}).encode(),
            method="PUT")
        try:
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                code, headers = resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            code, headers = e.code, dict(e.headers)
        assert code == 503                   # Job.Register shed first
        assert "Retry-After" in headers
        code, _, _ = _get(agent, "/v1/jobs")
        assert code == 200                   # reads survive level 1
    finally:
        agent.server.brownout = saved


def test_http_brownout_stale_reads_shed_last(agent):
    """The local HTTP dispatch path must classify the request's
    consistency mode for the shed decision: at level 2 a default read
    sheds but ``?stale=true`` still serves (regression — the HTTP tier
    establishes the read point itself, so without threading the mode
    through, endpoints.handle shed stale reads as default reads)."""
    saved = agent.server.brownout
    agent.server.brownout = _brownout(512)   # level 2
    try:
        code, headers, _ = _get(agent, "/v1/jobs")
        assert code == 503                   # default read sheds
        assert "Retry-After" in headers
        code, _, _ = _get(agent, "/v1/jobs?stale=true")
        assert code == 200                   # stale read survives
        agent.server.brownout = _brownout(1024)  # level 3: full brownout
        code, _, _ = _get(agent, "/v1/jobs?stale=true")
        assert code == 503                   # nothing survives level 3
    finally:
        agent.server.brownout = saved


def test_rpc_eval_dequeue_admission_denied(agent):
    saved = agent.server.admission
    gate = AdmissionGate(rate=0.0, max_concurrency=1)
    agent.server.admission = gate
    try:
        assert gate.try_acquire("default") is None   # hold the one slot
        with pytest.raises(RpcError) as ei:
            agent.server.endpoints.handle(
                "Eval.Dequeue", {"schedulers": ["service"],
                                 "timeout": 0.01, "namespace": "default"})
        assert ei.value.kind == "admission_denied"
        assert ei.value.retry_after > 0.0
        gate.release("default")
        # with the slot free the dequeue reaches the broker (empty)
        resp = agent.server.endpoints.handle(
            "Eval.Dequeue", {"schedulers": ["service"],
                             "timeout": 0.01, "namespace": "default"})
        assert resp is None
        with gate._lock:
            assert gate._inflight == {}      # released after the call
    finally:
        agent.server.admission = saved


def test_rpc_dequeue_with_expired_deadline_mints_no_lease(agent):
    before = _counter("deadline.expired.broker")
    with pytest.raises(RpcError) as ei:
        agent.server.endpoints.handle(
            "Eval.Dequeue", {"schedulers": ["service"], "timeout": 0.01,
                             deadline.DEADLINE_KEY: 0.0})
    # budget dead on arrival: refused at dispatch, before the broker
    assert ei.value.kind == "deadline_exceeded"
    # an expired budget that survives to the broker is also refused
    prev = deadline.bind(time.monotonic() - 0.01)
    try:
        ev, token = agent.server.broker.dequeue(["service"], timeout=0.5)
    finally:
        deadline.bind(prev)
    assert (ev, token) == (None, "")
    assert _counter("deadline.expired.broker") >= before + 1


# ----------------------------------------- deadline-aware client retries


class _Always503(BaseHTTPRequestHandler):
    retry_after = "0.2"

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        body = json.dumps({"error": "overloaded"}).encode()
        self.send_response(503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After", self.retry_after)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def overloaded_server():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Always503)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def test_client_deadline_bounds_retry_storm(overloaded_server):
    api = ApiClient(overloaded_server, retries=50, retry_backoff=0.05,
                    deadline=0.5)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        api.get("/v1/jobs")
    assert time.monotonic() - t0 < 3.0       # gave up, not 50 retries


def test_client_without_deadline_surfaces_api_error(overloaded_server):
    api = ApiClient(overloaded_server, retries=1, retry_backoff=0.01)
    with pytest.raises(ApiError) as ei:
        api.get("/v1/jobs")
    assert ei.value.status == 503


def test_client_per_call_deadline_overrides(overloaded_server):
    api = ApiClient(overloaded_server, retries=50, retry_backoff=0.05)
    with pytest.raises(DeadlineExceeded):
        api.get("/v1/jobs", deadline=0.3)


def test_client_sends_deadline_header(agent):
    # a bound client budget rides X-Nomad-Deadline: tiny budget + the
    # agent's ingress stamping = an honest 504, not a hang
    api = ApiClient(agent.http_addr, retries=0, deadline=0.00001)
    with pytest.raises((ApiError, DeadlineExceeded)) as ei:
        api.get("/v1/jobs")
    if isinstance(ei.value, ApiError):
        assert ei.value.status == 504
