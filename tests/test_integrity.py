"""Replica-integrity plane tests: log-stamped state digests, divergence
quarantine, anti-entropy self-repair, and the fingerprint-delta batch
path (reference ideas: Paxos Made Live's periodic state checksums,
Dynamo's anti-entropy repair)."""
import json
import random
import time
import urllib.error
import urllib.request

import pytest

from nomad_tpu import chaos, mock
from nomad_tpu.agent.http import HTTPServer
from nomad_tpu.chaos import ChaosRegistry
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.heartbeat import HeartbeatBatcher
from nomad_tpu.core.server import ServerConfig
from nomad_tpu.raft import MessageType, NomadFSM, RaftConfig
from nomad_tpu.raft.integrity import IntegrityTracker
from nomad_tpu.rpc import RpcError
from nomad_tpu.state import StateStore
from nomad_tpu.state import digest as state_digest

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout=0.1)


def _wait(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def cluster(tmp_path):
    """3-server cluster with a fast checkpoint cadence, every
    checkpoint a full walk (silent corruption marks nothing dirty)."""
    c = Cluster(3, config=ServerConfig(
        num_schedulers=1, integrity_interval=0.1, integrity_full_every=1),
        raft_config=FAST, data_dir=str(tmp_path))
    c.start()
    yield c
    c.stop()


def _follower(c):
    ld = c.leader(timeout=10.0)
    return ld, [s for s in c.servers if s is not ld][0]


# ============================================== digest <-> canon property


# ops whose interleaving exercises list tables (allocs), dict tables
# (jobs/nodes), deletes, and shared-reference pickling
def _random_ops(rng, n=40):
    jobs, nodes = [], []
    ops = []
    for i in range(n):
        k = rng.random()
        if k < 0.35 or not jobs:
            j = mock.job()
            jobs.append(j)
            ops.append((MessageType.JOB_REGISTER, {"job": j}))
        elif k < 0.6 or not nodes:
            node = mock.node()
            nodes.append(node)
            ops.append((MessageType.NODE_REGISTER, {"node": node}))
        elif k < 0.8:
            j = jobs[rng.randrange(len(jobs))]
            node = nodes[rng.randrange(len(nodes))]
            ops.append((MessageType.ALLOC_UPDATE,
                        {"allocs": [mock.alloc_for(j, node.id)]}))
        else:
            j = jobs.pop(rng.randrange(len(jobs)))
            ops.append((MessageType.JOB_DEREGISTER,
                        {"namespace": "default", "job_id": j.id,
                         "purge": True}))
    return ops


def _apply_all(ops):
    store = StateStore()
    fsm = NomadFSM(store)
    for i, (mt, payload) in enumerate(ops):
        fsm.apply(i + 1, mt, payload)
    return fsm


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_digest_equal_iff_canon_byte_equal(seed):
    """Satellite: ONE shared canonical-encoding helper.  Two replicas
    applying the same randomized op sequence agree on both the battery's
    canonical bytes AND the runtime digest; a single corrupted record
    flips both, never one without the other."""
    rng = random.Random(seed)
    ops = _random_ops(rng)
    a, b = _apply_all(ops), _apply_all(ops)
    assert state_digest.canon(a.snapshot()) == state_digest.canon(
        b.snapshot())
    da = state_digest.combine(state_digest.tables_digests(
        a.snapshot_tables()))
    db = state_digest.combine(state_digest.tables_digests(
        b.snapshot_tables()))
    assert da == db
    # corrupt exactly one record on b: digests AND canon must both split
    hit = b.store.chaos_bitflip(rng.random())
    assert hit
    assert state_digest.canon(a.snapshot()) != state_digest.canon(
        b.snapshot())
    db2 = state_digest.combine(state_digest.tables_digests(
        b.snapshot_tables()))
    assert da != db2
    # ... and the divergent table the operator sees is the corrupted one
    table = state_digest.first_divergence(
        state_digest.tables_digests(a.snapshot_tables()),
        state_digest.tables_digests(b.snapshot_tables()))
    assert table == hit.split("/")[0]


class _StubNode:
    def __init__(self, fsm, name="stub"):
        self.name = name
        self.fsm = fsm


@pytest.mark.parametrize("seed", [7, 8])
def test_incremental_digest_matches_full_walk(seed):
    """The per-type dirty map (_APPLY_TOUCHES) must be a SUPERSET of
    what each apply really touches: interleave checkpoints with random
    ops and the incrementally-maintained digest must equal a fresh full
    walk every time."""
    rng = random.Random(seed)
    store = StateStore()
    fsm = NomadFSM(store)
    tracker = IntegrityTracker(_StubNode(fsm))
    fsm.dirty_hook = tracker.note_dirty
    idx = 0
    for round_no in range(6):
        for mt, payload in _random_ops(rng, n=8):
            idx += 1
            fsm.apply(idx, mt, payload)
        idx += 1
        rec = tracker.on_checkpoint(idx, {"seq": round_no, "full": False})
        ground = state_digest.combine(state_digest.tables_digests(
            fsm.snapshot_tables()))
        assert rec["digest"] == ground, \
            f"round {round_no}: stale dirty map (incremental != full)"
    assert tracker.counters["checkpoints"] == 6
    # only the boot checkpoint full-walked; the rest rode the cache
    assert tracker.counters["full_walks"] == 1


# ======================================================== leader voting


def _tracker_with_checkpoint(name="leader"):
    store = StateStore()
    fsm = NomadFSM(store)
    fsm.apply(1, MessageType.NODE_REGISTER, {"node": mock.node()})
    t = IntegrityTracker(_StubNode(fsm, name))
    rec = t.on_checkpoint(5, {"seq": 1, "full": True})
    return t, rec


def test_ack_without_digest_is_unverified_never_quarantined():
    """Satellite: a mixed-version peer acks without the digest field —
    counted as unverified, excluded from the vote, NEVER convicted."""
    t, rec = _tracker_with_checkpoint()
    t.observe_ack("old-peer", None)
    t.observe_ack("old-peer", None)
    t.observe_ack("new-peer", {"index": 5, "digest": rec["digest"],
                               "per_table": rec["per_table"]})
    actions = t.evaluate(["leader", "old-peer", "new-peer"])
    assert actions == {"divergent": {}, "self_outlier": False,
                       "repair": []}
    assert t.counters["unverified_acks"] == 2
    assert t.peer_divergent("old-peer") is None
    view = t.operator_view()
    assert view["peers"]["old-peer"]["unverified_acks"] == 2
    assert view["peers"]["old-peer"]["divergent"] is None


def test_vote_convicts_minority_on_full_checkpoint_only():
    t, rec = _tracker_with_checkpoint()
    bad = {"index": 5, "digest": "deadbeefdeadbeef",
           "per_table": dict(rec["per_table"], nodes="deadbeefdeadbeef")}
    good = {"index": 5, "digest": rec["digest"],
            "per_table": rec["per_table"]}
    t.observe_ack("healthy", good)
    t.observe_ack("corrupt", bad)
    actions = t.evaluate(["leader", "healthy", "corrupt"])
    assert actions["divergent"] == {"corrupt": "nodes"}
    assert actions["repair"] == ["corrupt"]
    assert not actions["self_outlier"]
    assert t.peer_divergent("corrupt") == "nodes"
    # conviction is idempotent across re-evaluation
    t.evaluate(["leader", "healthy", "corrupt"])
    assert t.counters["repairs_started"] == 1


def test_incremental_mismatch_escalates_but_never_convicts():
    """A stale dirty map must not false-convict: incremental mismatch
    raises the alarm and escalates the NEXT checkpoint to a full walk;
    conviction waits for ground truth."""
    t, rec = _tracker_with_checkpoint()
    t.last = dict(t.last, full=False)
    bad = {"index": 5, "digest": "deadbeefdeadbeef",
           "per_table": dict(rec["per_table"], nodes="deadbeefdeadbeef")}
    t.observe_ack("healthy", {"index": 5, "digest": rec["digest"],
                              "per_table": rec["per_table"]})
    t.observe_ack("suspect", bad)
    actions = t.evaluate(["leader", "healthy", "suspect"])
    assert actions["divergent"] == {}
    assert t.peer_divergent("suspect") is None
    assert t.counters["alarms"] == 1
    assert t.escalation_pending()
    assert t.take_escalation()
    assert not t.escalation_pending()


def test_vote_without_quorum_alarms_only():
    """Too many unverified peers: no digest reaches quorum, so nobody
    can be convicted (alarm only)."""
    t, rec = _tracker_with_checkpoint()
    t.observe_ack("old-1", None)
    t.observe_ack("old-2", None)
    bad = {"index": 5, "digest": "deadbeefdeadbeef",
           "per_table": {"nodes": "deadbeefdeadbeef"}}
    t.observe_ack("suspect", bad)
    actions = t.evaluate(["leader", "old-1", "old-2", "suspect", "x5"])
    assert actions["divergent"] == {}
    assert not actions["self_outlier"]
    assert t.counters["alarms"] == 1


def test_leader_as_outlier_flags_self():
    t, rec = _tracker_with_checkpoint()
    bad = {"index": 5, "digest": "deadbeefdeadbeef",
           "per_table": {"nodes": "deadbeefdeadbeef"}}
    t.observe_ack("p1", bad)
    t.observe_ack("p2", bad)
    actions = t.evaluate(["leader", "p1", "p2"])
    assert actions["self_outlier"]
    assert actions["divergent"] == {}


# ================================================= quarantine read path


def test_quarantined_follower_refuses_local_reads_still_replicates(
        cluster):
    ld, follower = _follower(cluster)
    follower.raft.integrity.quarantine("test verdict (table nodes)")
    # stale AND lease/default local serving refused with the hint
    for mode in ("stale", "default"):
        with pytest.raises(RpcError) as exc:
            follower.read("Node.List", {}, consistency=mode, timeout=2.0)
        assert exc.value.kind == "quarantined"
        assert "quarantine" in exc.value.detail
    # ... but the replica still replicates: a write through the leader
    # lands on the quarantined follower's FSM
    node = mock.node()
    ld.register_node(node)
    assert _wait(lambda: follower.store.node_by_id(node.id) is not None,
                 5.0), "quarantined follower stopped replicating"
    # re-admission restores local serving
    follower.raft.integrity.clear_quarantine("test over")
    out, _ = follower.read("Node.List", {}, consistency="stale",
                           timeout=2.0)
    assert any(n.id == node.id for n in out)


def test_quarantined_follower_503s_over_http(cluster):
    _, follower = _follower(cluster)
    follower.raft.integrity.quarantine("test verdict (table jobs)")

    class _Shim:
        server = follower

        def rpc(self, method, args, consistency=None):
            return follower.rpc_leader(method, args)

    http = HTTPServer(_Shim(), port=0)
    http.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/v1/jobs?stale=true",
                timeout=10.0)
        assert exc.value.code == 503
        body = exc.value.read().decode()
        assert "quarantined" in body
    finally:
        http.stop()
        follower.raft.integrity.clear_quarantine("test over")


def test_quarantined_follower_reports_unhealthy_to_autopilot(cluster):
    ld, follower = _follower(cluster)
    assert _wait(lambda: ld.raft.server_healthy(follower.name), 5.0)
    follower.raft.integrity.quarantine("test verdict")
    # the leader's conviction map drives server_healthy for promote
    # decisions; simulate the convicted state leader-side
    ld.raft.integrity._divergent[follower.name] = "nodes"
    assert not ld.raft.server_healthy(follower.name)
    ld.raft.integrity.repair_result(follower.name, True)
    follower.raft.integrity.clear_quarantine("test over")
    assert _wait(lambda: ld.raft.server_healthy(follower.name), 5.0)


# ================================================ end-to-end self-repair


def test_corrupt_follower_detected_quarantined_repaired(cluster):
    """The whole story on a live cluster: silent corruption on one
    follower -> majority vote convicts it -> quarantine -> anti-entropy
    snapshot repair -> digest-verified re-admission -> byte-identical
    state everywhere, exactly one verified repair."""
    ld, victim = _follower(cluster)
    for _ in range(3):
        ld.register_node(mock.node())
    _wait(lambda: victim.raft.integrity.last is not None, 5.0)
    hit = victim.store.chaos_bitflip(0.5)
    assert hit
    vt = victim.raft.integrity
    assert _wait(lambda: vt.counters["quarantines"] > 0, 10.0), \
        "corruption never detected/quarantined"
    assert _wait(lambda: not vt.quarantined
                 and not ld.raft.integrity.peer_divergent(victim.name),
                 10.0), "repair never re-admitted the victim"
    assert ld.raft.integrity.counters["repairs_verified"] >= 1
    # repaired to byte-identical state (the battery's own invariant)
    idx = ld.store.latest_index
    assert cluster.wait_replication(idx, timeout=10.0)
    blobs = [state_digest.canon(s.raft.fsm.snapshot())
             for s in cluster.servers]
    assert blobs[0] == blobs[1] == blobs[2]


def test_repair_rejected_when_snapshot_predates_follower_compaction(
        cluster):
    """A repair rewind below the follower's own compaction point has no
    log tail to replay through: the follower must refuse it (leader
    retries with a fresher snapshot) instead of wedging its apply loop."""
    ld, follower = _follower(cluster)
    for _ in range(3):
        ld.register_node(mock.node())
    idx = ld.store.latest_index
    assert cluster.wait_replication(idx, timeout=10.0)
    follower.raft.force_snapshot()
    stale_idx = follower.raft._last_snapshot_index - 1
    resp = follower.raft._install_snapshot_blob(
        {"term": follower.raft.term, "leader": ld.name, "repair": True,
         "last_index": stale_idx, "last_term": 1}, b"not-a-snapshot")
    assert resp["success"] is False


# =============================================== operator surface + CLI


def test_operator_integrity_endpoint_and_api(cluster):
    ld, follower = _follower(cluster)
    _wait(lambda: ld.raft.integrity.last is not None, 5.0)
    view = ld.endpoints.handle("Operator.Integrity", {})
    assert view["server"] == ld.name
    assert view["leader"] is True
    assert view["quarantined"] is False
    assert view["last"]["digest"]
    assert view["counters"]["checkpoints"] >= 1
    # served locally on the follower too: a quarantined replica must
    # still answer its own integrity query
    follower.raft.integrity.quarantine("test verdict")
    fview = follower.endpoints.handle("Operator.Integrity", {})
    assert fview["quarantined"] is True
    assert fview["leader"] is False
    follower.raft.integrity.clear_quarantine("test over")


# =========================================== chaos targeting semantics


def test_chaos_target_fires_only_on_where_match_once():
    reg = ChaosRegistry.from_spec("seed=1")
    reg.arm(now=0.0)
    reg.target("fsm.apply_skip", "server-1", count=2)
    assert reg.pending_target("fsm.apply_skip", "server-1") == 2
    # wrong replica: never fires, target not consumed
    assert not reg.should("fsm.apply_skip", "server-0")
    assert reg.pending_target("fsm.apply_skip", "server-1") == 2
    # right replica: fires exactly `count` times, then never again
    assert reg.should("fsm.apply_skip", "server-1")
    assert reg.should("fsm.apply_skip", "server-1")
    assert not reg.should("fsm.apply_skip", "server-1")
    assert reg.pending_target("fsm.apply_skip", "server-1") == 0
    # count<=0 disarms: a re-armed drill revokes its previous target
    reg.target("fsm.apply_skip", "server-1", count=2)
    reg.target("fsm.apply_skip", "server-1", count=0)
    assert reg.pending_target("fsm.apply_skip", "server-1") == 0
    assert not reg.should("fsm.apply_skip", "server-1")
    with pytest.raises(ValueError):
        reg.target("not.a.point", "server-1")


def test_targeted_point_does_not_fire_by_rate():
    """Divergence points are targeted-only: a rate would fire on every
    in-process replica and destroy the healthy majority."""
    reg = ChaosRegistry.from_spec("seed=1;store.bitflip=1.0")
    reg.arm(now=0.0)
    reg.target("store.bitflip", "server-2")
    # rate=1.0 but armed targets exist: only the where-match fires
    assert not reg.should("store.bitflip", "server-0")
    assert reg.should("store.bitflip", "server-2")


# ====================================== fingerprint-delta batched path


class _StubServer:
    class _Cfg:
        heartbeat_ttl = 10.0

    def __init__(self):
        self.store = StateStore()
        self.config = self._Cfg()
        self.applies = []
        self.heartbeat_batch = None

    def apply(self, msg_type, payload):
        self.applies.append((msg_type, payload))

    def create_node_evals(self, node_id):
        pass


def test_fingerprint_storm_commits_one_entry_per_flush_tick():
    """Satellite: a 1K-node fingerprint churn storm coalesces through
    the batcher into O(flush-ticks) raft entries, not O(nodes)."""
    srv = _StubServer()
    b = HeartbeatBatcher(srv, interval=3600.0)   # manual flush only
    b.pending_max = 10_000
    for tick in range(3):
        for i in range(1000):
            b.note_fingerprint(f"n{i}", {"attributes": {"tick": tick}})
            # repeated deltas for the same node coalesce in place
            b.note_fingerprint(f"n{i}", {"devices": [tick]})
        b.flush()
    assert len(srv.applies) == 3                 # O(flush-ticks), not 6000
    for _, payload in srv.applies:
        assert len(payload["updates"]) == 1000
    msg_type, payload = srv.applies[-1]
    assert msg_type == MessageType.NODE_FINGERPRINT_BATCH
    u = {x["node_id"]: x for x in payload["updates"]}
    assert u["n7"]["attributes"] == {"tick": 2}
    assert u["n7"]["devices"] == [2]
    b.flush()                                    # drained: no extra entry
    assert len(srv.applies) == 3


def test_fsm_applies_fingerprint_batch():
    store = StateStore()
    fsm = NomadFSM(store)
    nodes = [mock.node() for _ in range(2)]
    for i, n in enumerate(nodes):
        fsm.apply(i + 1, MessageType.NODE_REGISTER, {"node": n})
    devs = list(nodes[0].node_resources.devices)
    fsm.apply(5, MessageType.NODE_FINGERPRINT_BATCH, {"updates": [
        {"node_id": nodes[0].id, "attributes": {"driver.docker": "1"},
         "devices": devs},
        {"node_id": "ghost", "attributes": {"x": "y"}},
    ]})
    got = store.node_by_id(nodes[0].id)
    assert got.attributes["driver.docker"] == "1"
    # merged, not replaced: pre-existing attributes survive the delta
    assert len(got.attributes) > 1
    assert store.latest_index == 5
    # the untouched node's record is not copied/churned
    assert store.node_by_id(nodes[1].id).attributes.get(
        "driver.docker") is None


def test_node_update_fingerprint_rpc_end_to_end(cluster):
    ld, _ = _follower(cluster)
    node = mock.node()
    ld.register_node(node)
    resp = ld.endpoints.handle("Node.UpdateFingerprint", {
        "node_id": node.id, "attributes": {"driver.docker": "20.10"}})
    assert resp["known"] is True
    assert _wait(lambda: ld.store.node_by_id(node.id).attributes.get(
        "driver.docker") == "20.10", 5.0)
    # unknown node: the client falls back to full Node.Register
    resp = ld.endpoints.handle("Node.UpdateFingerprint", {
        "node_id": "no-such-node", "attributes": {"a": "b"}})
    assert resp["known"] is False
