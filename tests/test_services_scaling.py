"""Nomad-native service registration, job scaling, server-side search,
and multi-region federation (VERDICT r3 items 6 + 7)."""
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.structs.job import ScalingPolicy, Service


def _server(region="global", workers=2):
    s = Server(ServerConfig(num_schedulers=workers, heartbeat_ttl=3600.0,
                            gc_interval=3600.0, region=region))
    s.start()
    return s


def _wait(cond, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


# --------------------------------------------------------------- scaling

def test_job_scale_up_down_and_events():
    s = _server()
    try:
        j = mock.job()
        tg = j.task_groups[0]
        tg.count = 2
        tg.scaling = ScalingPolicy(min=1, max=5)
        for _ in range(6):
            s.register_node(mock.node())
        s.register_job(j)
        assert _wait(lambda: len([a for a in
                                  s.store.allocs_by_job("default", j.id)
                                  if not a.terminal_status()]) == 2)

        ev = s.endpoints.handle("Job.Scale", {
            "namespace": "default", "job_id": j.id,
            "group": tg.name, "count": 4, "message": "scale up"})
        assert ev["eval_id"]
        assert _wait(lambda: len([a for a in
                                  s.store.allocs_by_job("default", j.id)
                                  if not a.terminal_status()]) == 4)

        # bounds enforced
        from nomad_tpu.rpc.endpoints import RpcError
        with pytest.raises(RpcError):
            s.endpoints.handle("Job.Scale", {
                "namespace": "default", "job_id": j.id,
                "group": tg.name, "count": 99})
        with pytest.raises(RpcError):
            s.endpoints.handle("Job.Scale", {
                "namespace": "default", "job_id": j.id,
                "group": tg.name, "count": 0})

        # error=True records an event without changing counts
        s.endpoints.handle("Job.Scale", {
            "namespace": "default", "job_id": j.id, "group": tg.name,
            "count": None, "error": True, "message": "autoscaler woes"})
        st = s.endpoints.handle("Job.ScaleStatus",
                                {"namespace": "default", "job_id": j.id})
        g = st["task_groups"][tg.name]
        assert g["desired"] == 4
        msgs = [e.message for e in g["events"]]
        assert "autoscaler woes" in msgs and "scale up" in msgs

        pols = s.endpoints.handle("Scaling.ListPolicies", {})
        assert len(pols) == 1 and pols[0]["max"] == 5
        pol = s.endpoints.handle("Scaling.GetPolicy",
                                 {"id": pols[0]["id"]})
        assert pol["min"] == 1
    finally:
        s.stop()


# --------------------------------------------------------------- search

def test_prefix_search_server_side():
    s = _server()
    try:
        for _ in range(3):
            s.register_node(mock.node())
        j = mock.job(id="websrv-alpha")
        j2 = mock.job(id="websrv-beta")
        j3 = mock.job(id="other")
        for job in (j, j2, j3):
            s.register_job(job)
        resp = s.endpoints.handle("Search.PrefixSearch",
                                  {"prefix": "websrv", "context": "jobs"})
        assert resp["matches"]["jobs"] == ["websrv-alpha", "websrv-beta"]
        assert resp["truncations"]["jobs"] is False
        # all-context search includes evals/nodes keys
        resp = s.endpoints.handle("Search.PrefixSearch",
                                  {"prefix": "", "context": "all"})
        assert set(resp["matches"]) >= {"jobs", "nodes", "evals",
                                        "allocs", "deployment"}
        assert resp["truncations"]["nodes"] is False
    finally:
        s.stop()


# --------------------------------------------------------------- services

def _service_world():
    """Server + real client so the alloc runner's service hook runs."""
    from nomad_tpu.client.client import Client, ClientConfig
    s = _server()
    c = Client(ClientConfig(node_name="svc-client",
                            drivers=["mock", "mock_driver"]),
               rpc=s.rpc_leader)
    c.start()
    return s, c


def test_service_registration_lifecycle():
    s, c = _service_world()
    try:
        j = mock.job()
        tg = j.task_groups[0]
        tg.count = 1
        tg.tasks[0].driver = "mock_driver"
        tg.tasks[0].config = {"run_for": 60.0}
        tg.services = [Service(name="web", provider="nomad",
                               checks=[{"type": "tcp"}])]
        s.register_job(j)

        # first registration may land "critical" (task still starting);
        # the check runner flips it to passing once the task runs
        assert _wait(lambda: any(
            r.health == "passing"
            for r in s.store.services_by_name("default", "web")),
            timeout=30)
        regs = s.store.services_by_name("default", "web")
        assert len(regs) == 1 and regs[0].job_id == j.id
        listing = s.endpoints.handle("Service.List", {})
        assert listing == [{"namespace": "default",
                            "service_name": "web", "instances": 1}]

        # stop the job: the client deregisters the alloc's services
        s.deregister_job("default", j.id)
        assert _wait(lambda: not s.store.services_by_name(
            "default", "web"), timeout=30)
    finally:
        c.stop()
        s.stop()


def test_service_gc_sweeps_orphans():
    from nomad_tpu.structs.service import ServiceRegistration
    s = _server()
    try:
        from nomad_tpu.raft.fsm import MessageType
        s.apply(MessageType.SERVICE_REGISTER, {"services": [
            ServiceRegistration(id="orphan-1", service_name="ghost",
                                alloc_id="no-such-alloc")]})
        assert s.store.services_by_name("default", "ghost")
        stats = s.core_scheduler.process("service-gc")
        assert stats["services"] == 1
        assert not s.store.services_by_name("default", "ghost")
    finally:
        s.stop()


def test_deployment_health_via_service_checks():
    """health_check='checks': alloc health requires every nomad service
    registration passing, feeding the deployment watcher."""
    s, c = _service_world()
    try:
        j = mock.job()
        tg = j.task_groups[0]
        tg.count = 1
        tg.tasks[0].driver = "mock_driver"
        tg.tasks[0].config = {"run_for": 60.0}
        tg.services = [Service(name="db", provider="nomad",
                               checks=[{"type": "tcp"}])]
        tg.update = j.update
        j.update.health_check = "checks"
        j.update.min_healthy_time_s = 0.1
        s.register_job(j)

        def healthy():
            allocs = s.store.allocs_by_job("default", j.id)
            return any((a.deployment_status or {}).get("healthy") is True
                       for a in allocs)
        assert _wait(healthy, timeout=45)
    finally:
        c.stop()
        s.stop()


# --------------------------------------------------------------- regions

def test_multi_region_federation():
    a = _server(region="global")
    b = _server(region="west")
    try:
        a.federate(b)
        assert a.regions() == ["global", "west"]
        assert b.endpoints.handle("Status.Regions", {}) == \
            ["global", "west"]

        for _ in range(3):
            b.register_node(mock.node())
        # a job whose region is 'west' registered at the global server
        # lands in west's state store
        j = mock.batch_job()
        j.region = "west"
        j.task_groups[0].count = 2
        a.register_job(j)
        assert _wait(lambda: len([x for x in
                                  b.store.allocs_by_job("default", j.id)
                                  if not x.terminal_status()]) == 2)
        assert a.store.job_by_id("default", j.id) is None

        # explicit region-tagged RPC forwards too
        got = a.endpoints.handle("Job.GetJob",
                                 {"namespace": "default", "job_id": j.id,
                                  "region": "west"})
        assert got is not None and got.id == j.id
    finally:
        a.stop()
        b.stop()


def test_inplace_update_joins_new_deployment():
    """An in-place-only job update (group meta change) creates a new
    deployment; the running allocs join it without a restart, re-prove
    health, and the deployment promotes (reference allocUpdateFnInplace
    sets DeploymentID on the updated alloc)."""
    s, c = _service_world()
    try:
        j = mock.job()
        tg = j.task_groups[0]
        tg.count = 1
        tg.tasks[0].driver = "mock_driver"
        tg.tasks[0].config = {"run_for": 120.0}
        tg.update = j.update
        j.update.min_healthy_time_s = 0.1
        s.register_job(j)

        def dep_ok(version):
            ds = [d for d in s.store.deployments()
                  if d.job_id == j.id and d.job_version == version]
            return any(d.status == "successful" for d in ds)
        assert _wait(lambda: dep_ok(0))
        first = {a.id for a in s.store.allocs_by_job("default", j.id)
                 if not a.terminal_status()}
        assert first

        # in-place change: group meta only (tasks_updated == False)
        j2 = j.copy()
        j2.task_groups[0].meta = {"rev": "2"}
        j2.task_groups[0].update = j2.update
        s.register_job(j2)
        assert _wait(lambda: dep_ok(1), timeout=45)
        live = [a for a in s.store.allocs_by_job("default", j.id)
                if not a.terminal_status()]
        assert {a.id for a in live} == first, "in-place update restarted allocs"
        d1 = next(d for d in s.store.deployments()
                  if d.job_id == j.id and d.job_version == 1)
        assert all(a.deployment_id == d1.id for a in live)
    finally:
        s.stop()
