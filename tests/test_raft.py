"""Raft consensus + FSM + snapshot tests (reference analogs:
nomad/fsm_test.go, nomad/leader_test.go, raft failover via
nomad.TestServer in-memory clusters)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.raft import (
    DurableMeta,
    FileSnapshotStore,
    InMemTransport,
    LogStore,
    MessageType,
    NomadFSM,
    RaftConfig,
    RaftNode,
)
from nomad_tpu.state import StateStore

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout=0.1)


# --------------------------------------------------------------------- FSM


def test_fsm_apply_job_and_node():
    store = StateStore()
    fsm = NomadFSM(store)
    job = mock.job()
    fsm.apply(1, MessageType.JOB_REGISTER, {"job": job})
    assert store.job_by_id("default", job.id) is not None
    node = mock.node()
    fsm.apply(2, MessageType.NODE_REGISTER, {"node": node})
    assert store.node_by_id(node.id) is not None
    assert store.latest_index == 2
    fsm.apply(3, MessageType.JOB_DEREGISTER,
              {"namespace": "default", "job_id": job.id, "purge": True})
    assert store.job_by_id("default", job.id) is None


def test_fsm_snapshot_restore_roundtrip():
    store = StateStore()
    fsm = NomadFSM(store)
    job = mock.job()
    node = mock.node()
    fsm.apply(1, MessageType.JOB_REGISTER, {"job": job})
    fsm.apply(2, MessageType.NODE_REGISTER, {"node": node})
    alloc = mock.alloc_for(job, node.id)
    fsm.apply(3, MessageType.ALLOC_UPDATE, {"allocs": [alloc]})
    blob = fsm.snapshot()

    store2 = StateStore()
    fsm2 = NomadFSM(store2)
    fsm2.restore(blob)
    assert store2.latest_index == 3
    assert store2.job_by_id("default", job.id) is not None
    assert store2.node_by_id(node.id) is not None
    assert store2.alloc_by_id(alloc.id) is not None
    # dense mirror rebuilt: node occupies a row, alloc usage accounted
    assert node.id in store2.matrix.row_of
    row = store2.matrix.row_of[node.id]
    assert store2.matrix.used[row][0] > 0


# --------------------------------------------------------------------- raft


def _mk_node(name, peers, transport, cfg=FAST, **kw):
    return RaftNode(name, peers, transport, NomadFSM(StateStore()),
                    config=cfg, **kw)


def test_single_node_election_and_apply():
    tr = InMemTransport()
    n = _mk_node("a", ["a"], tr)
    n.start()
    try:
        deadline = time.monotonic() + 2
        while not n.is_leader and time.monotonic() < deadline:
            time.sleep(0.01)
        assert n.is_leader
        idx = n.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        assert idx >= 1
        assert len(n.fsm.store.nodes()) == 1
    finally:
        n.stop()


def test_three_node_replication():
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = [_mk_node(nm, names, tr) for nm in names]
    for n in nodes:
        n.start()
    try:
        deadline = time.monotonic() + 3
        leader = None
        while leader is None and time.monotonic() < deadline:
            leaders = [n for n in nodes if n.is_leader]
            leader = leaders[0] if len(leaders) == 1 else None
            time.sleep(0.01)
        assert leader is not None
        for _ in range(5):
            leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            if all(len(n.fsm.store.nodes()) == 5 for n in nodes):
                break
            time.sleep(0.02)
        for n in nodes:
            assert len(n.fsm.store.nodes()) == 5
            assert n.fsm.store.latest_index == leader.fsm.store.latest_index
    finally:
        for n in nodes:
            n.stop()


def test_leader_failover():
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = {nm: _mk_node(nm, names, tr) for nm in names}
    for n in nodes.values():
        n.start()
    try:
        deadline = time.monotonic() + 3
        leader = None
        while leader is None and time.monotonic() < deadline:
            ls = [n for n in nodes.values() if n.is_leader]
            leader = ls[0] if ls else None
            time.sleep(0.01)
        leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        # kill the leader; a new one must take over with the entry intact
        tr.set_down(leader.name)
        leader.stop()
        rest = [n for n in nodes.values() if n is not leader]
        deadline = time.monotonic() + 3
        new_leader = None
        while new_leader is None and time.monotonic() < deadline:
            ls = [n for n in rest if n.is_leader]
            new_leader = ls[0] if ls else None
            time.sleep(0.01)
        assert new_leader is not None
        assert len(new_leader.fsm.store.nodes()) == 1
        new_leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        assert len(new_leader.fsm.store.nodes()) == 2
    finally:
        for n in nodes.values():
            if not n._stop.is_set():
                n.stop()


def test_log_persistence_restart(tmp_path):
    path = str(tmp_path / "raft.log")
    tr = InMemTransport()
    n = _mk_node("a", ["a"], tr, log_store=LogStore(path))
    n.start()
    deadline = time.monotonic() + 2
    while not n.is_leader and time.monotonic() < deadline:
        time.sleep(0.01)
    node_ids = []
    for _ in range(3):
        nd = mock.node()
        node_ids.append(nd.id)
        n.apply(MessageType.NODE_REGISTER, {"node": nd})
    n.stop()

    # restart: the persisted log tail is applied once the node re-elects
    # itself and commits its no-op (uncommitted entries must never be
    # FSM-applied at boot — a new leader may truncate them)
    n2 = _mk_node("a", ["a"], InMemTransport(), log_store=LogStore(path))
    n2.start()
    try:
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if {x.id for x in n2.fsm.store.nodes()} == set(node_ids):
                break
            time.sleep(0.02)
        assert {x.id for x in n2.fsm.store.nodes()} == set(node_ids)
    finally:
        n2.stop()


def test_restart_preserves_vote_no_double_grant(tmp_path):
    """Raft Figure 2: votedFor lives on stable storage.  A node that
    granted a vote, crashed, and restarted in the same term must refuse a
    different candidate — a forgotten vote can elect two leaders in one
    term."""
    meta_path = str(tmp_path / "raft_meta.json")
    tr = InMemTransport()
    n = _mk_node("a", ["a", "b", "c"], tr, meta=DurableMeta(meta_path))
    req_b = {"term": 5, "candidate": "b",
             "last_log_index": 0, "last_log_term": 0}
    resp = n._on_request_vote(dict(req_b))
    assert resp["granted"] and resp["term"] == 5
    tr.deregister("a")   # never started: no threads to stop

    # crash-restart: term + vote come back from disk
    n2 = _mk_node("a", ["a", "b", "c"], InMemTransport(),
                  meta=DurableMeta(meta_path))
    assert (n2.term, n2.voted_for) == (5, "b")
    resp = n2._on_request_vote({"term": 5, "candidate": "c",
                                "last_log_index": 10, "last_log_term": 5})
    assert not resp["granted"]
    # the original candidate retransmitting its request is still granted
    assert n2._on_request_vote(dict(req_b))["granted"]


def test_snapshot_compaction_and_restart(tmp_path):
    tr = InMemTransport()
    snaps = FileSnapshotStore(str(tmp_path / "snaps"))
    cfg = RaftConfig(heartbeat_interval=0.02, election_timeout=0.1,
                     snapshot_threshold=10)
    n = _mk_node("a", ["a"], tr, cfg=cfg, snapshots=snaps,
                 log_store=LogStore(str(tmp_path / "raft.log")))
    n.start()
    deadline = time.monotonic() + 2
    while not n.is_leader and time.monotonic() < deadline:
        time.sleep(0.01)
    for _ in range(25):
        n.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
    deadline = time.monotonic() + 3
    while snaps.latest() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    assert snaps.latest() is not None
    n.stop()

    # restart from snapshot + compacted log: snapshot state is available
    # immediately, the log tail lands after re-election
    n2 = _mk_node("a", ["a"], InMemTransport(), cfg=cfg, snapshots=snaps,
                  log_store=LogStore(str(tmp_path / "raft.log")))
    assert len(n2.fsm.store.nodes()) >= 10   # snapshot covers ≥ threshold
    n2.start()
    try:
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if len(n2.fsm.store.nodes()) == 25:
                break
            time.sleep(0.02)
        assert len(n2.fsm.store.nodes()) == 25
    finally:
        n2.stop()


# ----------------------------------------------------------------- cluster


def test_cluster_schedules_through_raft():
    c = Cluster(3)
    c.start()
    try:
        leader = c.leader()
        for _ in range(5):
            leader.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 3
        leader.register_job(job)
        deadline = time.monotonic() + 10
        placed = []
        while time.monotonic() < deadline:
            placed = [a for a in leader.store.allocs_by_job("default", job.id)
                      if a.desired_status == "run"]
            if len(placed) == 3:
                break
            time.sleep(0.05)
        assert len(placed) == 3
        # replicated to followers
        assert c.wait_replication(leader.store.latest_index)
        for f in c.followers():
            assert len(f.store.allocs_by_job("default", job.id)) == 3
    finally:
        c.stop()


def test_cluster_leader_failover_preserves_state():
    c = Cluster(3)
    c.start()
    try:
        leader = c.leader()
        for _ in range(3):
            leader.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        leader.register_job(job)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(leader.store.allocs_by_job("default", job.id)) == 2:
                break
            time.sleep(0.05)
        c.wait_replication(leader.store.latest_index)
        c.kill(leader)
        # a follower takes over with full state and keeps scheduling
        deadline = time.monotonic() + 5
        new_leader = None
        while new_leader is None and time.monotonic() < deadline:
            ls = [s for s in c.servers if s is not leader
                  and s.raft.is_leader and s._established]
            new_leader = ls[0] if ls else None
            time.sleep(0.02)
        assert new_leader is not None
        assert len(new_leader.store.allocs_by_job("default", job.id)) == 2
        job2 = mock.job()
        new_leader.register_job(job2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(new_leader.store.allocs_by_job("default", job2.id)) \
                    == job2.task_groups[0].count:
                break
            time.sleep(0.05)
        assert len(new_leader.store.allocs_by_job("default", job2.id)) \
            == job2.task_groups[0].count
    finally:
        c.stop()


def test_leadership_flap_components_restart():
    """A server that loses and regains leadership must come back with live
    leader subsystems (stop Events are per-tenure, not one-shot)."""
    from nomad_tpu.core.server import Server, ServerConfig

    s = Server(ServerConfig(num_schedulers=1, heartbeat_ttl=0.3))
    s._establish_leadership()
    try:
        node = mock.node()
        s.register_node(node)
        s._revoke_leadership()
        s._establish_leadership()
        s.heartbeats.heartbeat(node.id)
        # heartbeat loop must still expire TTLs after the flap
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            n = s.store.node_by_id(node.id)
            if n.status == "down":
                break
            time.sleep(0.05)
        assert s.store.node_by_id(node.id).status == "down"
    finally:
        s.stop()


# ----------------------------------------------------------- server snapshot


def test_server_snapshot_save_restore(tmp_path):
    s = Server(ServerConfig(num_schedulers=1))
    s.start()
    try:
        for _ in range(3):
            s.register_node(mock.node())
        job = mock.job()
        s.register_job(job)
        s.wait_for_idle()
        path = str(tmp_path / "state.snap")
        s.save_snapshot(path)

        s2 = Server(ServerConfig(num_schedulers=1))
        s2.restore_snapshot(path)
        assert len(s2.store.nodes()) == 3
        assert s2.store.job_by_id("default", job.id) is not None
        assert len(s2.store.allocs_by_job("default", job.id)) \
            == len(s.store.allocs_by_job("default", job.id))
    finally:
        s.stop()
