"""Log rotation + client fs/logs endpoints (reference client/logmon/
logmon.go, client/fs_endpoint.go, command/agent/fs_endpoint.go,
command/alloc_logs.go)."""
import io
import os
import threading
import time

import pytest

from nomad_tpu.client.logmon import (
    RotatingFile,
    log_files,
    log_size,
    open_log_pipe,
    read_log,
)


def test_rotating_file(tmp_path):
    base = str(tmp_path / "t.stdout")
    rf = RotatingFile(base, max_size=10, max_files=3)
    for i in range(8):
        rf.write(b"x" * 10)      # each write triggers a rotation
    rf.close()
    frags = log_files(str(tmp_path), "t", "stdout")
    # active file + 2 rotated = max_files
    assert len(frags) == 3
    # logical size stays absolute: pruned bytes still count
    assert log_size(str(tmp_path), "t", "stdout") == 80
    # an offset inside pruned history resumes at the oldest survivor
    data, nxt = read_log(str(tmp_path), "t", "stdout", 0)
    assert data == b"x" * 20 and nxt == 80


def test_read_log_spans_fragments(tmp_path):
    base = str(tmp_path / "t.stdout")
    rf = RotatingFile(base, max_size=4, max_files=10)
    rf.write(b"abcd")            # rotates
    rf.write(b"efgh")            # rotates
    rf.write(b"ij")
    rf.close()
    data, nxt = read_log(str(tmp_path), "t", "stdout", 0)
    assert data == b"abcdefghij" and nxt == 10
    data, _ = read_log(str(tmp_path), "t", "stdout", 3, limit=4)
    assert data == b"defg"
    data, _ = read_log(str(tmp_path), "t", "stdout", -4)
    assert data == b"ghij"


def test_log_pipe_pumps(tmp_path):
    base = str(tmp_path / "p.stdout")
    fd = open_log_pipe(base, max_size=1 << 20)
    os.write(fd, b"hello from the child\n")
    os.close(fd)
    deadline = time.time() + 5
    while time.time() < deadline:
        if os.path.exists(base) and os.path.getsize(base) > 0:
            break
        time.sleep(0.02)
    assert open(base, "rb").read() == b"hello from the child\n"


@pytest.fixture()
def dev_agent():
    from nomad_tpu.agent import Agent, AgentConfig
    a = Agent(AgentConfig(http_port=0, num_schedulers=2,
                          heartbeat_ttl=600.0, client_enabled=True))
    a.start()
    yield a
    a.stop()


def _run_job(agent, script, job_id=None, run_secs=None):
    from nomad_tpu.structs.job import Job, Task, TaskGroup
    job = Job(id=job_id or f"logs-{time.time_ns()}", name="l",
              type="service",
              task_groups=[TaskGroup(name="g", count=1, tasks=[
                  Task(name="t", driver="raw_exec",
                       config={"command": "/bin/sh",
                               "args": ["-c", script]})])])
    job.canonicalize()
    agent.server.register_job(job)
    deadline = time.time() + 20
    while time.time() < deadline:
        allocs = agent.server.store.allocs_by_job("default", job.id)
        live = [a for a in allocs if a.client_status == "running"]
        if live:
            return live[0]
        time.sleep(0.1)
    raise AssertionError(
        [(a.client_status, a.task_states) for a in allocs])


def test_fs_logs_endpoints_and_cli(dev_agent):
    alloc = _run_job(dev_agent,
                     'echo line-1; echo err-1 >&2; sleep 60')
    from nomad_tpu.api.client import ApiClient
    api = ApiClient(dev_agent.http_addr)

    deadline = time.time() + 10
    while time.time() < deadline:
        if api.allocations.logs(alloc.id, "t").strip():
            break
        time.sleep(0.1)
    assert api.allocations.logs(alloc.id, "t") == b"line-1\n"
    assert api.allocations.logs(alloc.id, "t", "stderr") == b"err-1\n"

    # fs ls / stat / cat
    entries = api.allocations.fs_list(alloc.id, "alloc/logs")
    names = [e["Name"] for e in entries]
    assert "t.stdout" in names and "t.stderr" in names
    assert api.allocations.fs_cat(
        alloc.id, "alloc/logs/t.stdout") == b"line-1\n"
    st = api.allocations.fs_stat(alloc.id, "alloc/logs/t.stdout")
    assert st["Size"] == 7 and not st["IsDir"]

    # sandbox: escaping paths rejected
    from nomad_tpu.api.client import ApiError
    with pytest.raises(ApiError):
        api.allocations.fs_cat(alloc.id, "../../../etc/passwd")

    # CLI one-shot
    from nomad_tpu.command.cli import main
    out = io.StringIO()
    code = main(["-address", dev_agent.http_addr,
                 "alloc", "logs", alloc.id], out=out)
    assert code == 0 and out.getvalue() == "line-1\n"
    out = io.StringIO()
    code = main(["-address", dev_agent.http_addr,
                 "alloc", "fs", alloc.id, "alloc/logs"], out=out)
    assert code == 0 and "t.stdout" in out.getvalue()


def test_logs_tail_follow(dev_agent):
    """tail -f semantics: a follower sees lines appended AFTER it
    attached (origin=end)."""
    alloc = _run_job(
        dev_agent,
        'echo early; sleep 2; echo late-1; sleep 0.3; echo late-2; '
        'sleep 60')
    from nomad_tpu.api.client import ApiClient
    api = ApiClient(dev_agent.http_addr)

    got = []
    done = threading.Event()

    def follow():
        try:
            for chunk in api.allocations.logs_follow(
                    alloc.id, "t", timeout=8.0):
                got.append(chunk)
                if b"late-2" in b"".join(got):
                    return
        finally:
            done.set()

    t = threading.Thread(target=follow, daemon=True)
    t.start()
    assert done.wait(15.0)
    data = b"".join(got)
    assert b"late-1\n" in data and b"late-2\n" in data


def test_rotate_copytruncate(tmp_path):
    """The client log janitor's rotation for direct-append writers."""
    from nomad_tpu.client.logmon import rotate_copytruncate
    base = str(tmp_path / "t.stdout")
    # a writer holding an O_APPEND fd, janitor rotating behind it
    fh = open(base, "ab")
    fh.write(b"a" * 30)
    fh.flush()
    assert rotate_copytruncate(base, max_size=20, max_files=3)
    assert os.path.getsize(base) == 0
    fh.write(b"b" * 30)        # O_APPEND lands at new EOF
    fh.flush()
    assert os.path.getsize(base) == 30
    assert rotate_copytruncate(base, max_size=20, max_files=3)
    fh.write(b"c" * 5)
    fh.flush()
    fh.close()
    data, _ = read_log(str(tmp_path), "t", "stdout", 0)
    assert data == b"a" * 30 + b"b" * 30 + b"c" * 5
    assert log_size(str(tmp_path), "t", "stdout") == 65
    # not over the limit -> no-op
    assert not rotate_copytruncate(base, max_size=20, max_files=3)
