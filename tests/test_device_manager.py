"""Client-side device plugin manager (reference client/devicemanager/
manager.go + plugins/device/device.go:25-37): fingerprint from
config-built plugins, node reports devices, scheduler assigns
instances, client reserves them and hands the env to the task."""
import time

import pytest

from nomad_tpu.client.devices import (
    DeviceManager,
    DeviceReservationError,
    FakeDevicePlugin,
)


def test_fake_plugin_fingerprint_and_reserve():
    p = FakeDevicePlugin({"vendor": "nvidia", "type": "gpu",
                          "name": "a100", "count": 2})
    devs = p.fingerprint()
    assert devs[0].instance_ids == ["a100-0", "a100-1"]
    env = p.reserve(["a100-1"])
    assert env == {"NOMAD_DEVICE_GPU": "a100-1"}
    with pytest.raises(DeviceReservationError):
        p.reserve(["nope"])


def test_manager_exclusivity_and_free():
    m = DeviceManager([FakeDevicePlugin(
        {"vendor": "nvidia", "type": "gpu", "name": "a100",
         "instance_ids": ["g0", "g1"]})])
    spec = [{"vendor": "nvidia", "type": "gpu", "name": "a100",
             "device_ids": ["g0"]}]
    env = m.reserve("alloc-1", spec)
    assert env == {"NOMAD_DEVICE_GPU": "g0"}
    # double-booking by another alloc is rejected
    with pytest.raises(DeviceReservationError, match="already held"):
        m.reserve("alloc-2", spec)
    # idempotent for the same alloc (restore path)
    m.reserve("alloc-1", spec)
    assert m.free("alloc-1") == 1
    m.reserve("alloc-2", spec)


def test_manager_all_or_nothing():
    m = DeviceManager([FakeDevicePlugin(
        {"vendor": "n", "type": "gpu", "name": "g",
         "instance_ids": ["g0"]})])
    m.reserve("a1", [{"vendor": "n", "type": "gpu", "name": "g",
                      "device_ids": ["g0"]}])
    with pytest.raises(DeviceReservationError):
        m.reserve("a2", [
            {"vendor": "n", "type": "fpga", "name": "f",
             "device_ids": ["f0"]},          # no plugin -> whole call fails
        ])
    assert m.in_use() == {"n/gpu/g": ["g0"]}


def _wait(cond, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_device_job_end_to_end(tmp_path):
    """configs[3]-shaped: the CLIENT fingerprints devices (not the
    server), a device-requesting job schedules onto it, and the task
    sees its reserved instances in env."""
    from nomad_tpu.client.client import Client, ClientConfig
    from nomad_tpu.core.server import Server, ServerConfig
    from nomad_tpu.structs.job import Job, Task, TaskGroup
    from nomad_tpu.structs.resources import DeviceRequest

    s = Server(ServerConfig(num_schedulers=1, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    c = Client(ClientConfig(
        node_name="dev-client",
        data_dir=str(tmp_path / "client"),
        drivers=["raw_exec", "mock", "mock_driver"],
        device_plugins=[{"vendor": "nvidia", "type": "gpu",
                         "name": "a100", "count": 2}]),
        rpc=s.rpc_leader)
    c.start()
    try:
        # the server sees client-reported devices
        node = s.store.node_by_id(c.node.id)
        assert node is not None
        assert node.node_resources.devices[0].instance_ids == \
            ["a100-0", "a100-1"]

        proof = tmp_path / "devices.txt"
        t = Task(name="t", driver="raw_exec",
                 config={"command": "/bin/sh",
                         "args": ["-c",
                                  f'echo "$NOMAD_DEVICE_GPU" > {proof}'
                                  '; sleep 30']})
        t.resources.devices = [DeviceRequest(name="gpu", count=2)]
        job = Job(id=f"dev-{time.time_ns()}", name="dev", type="service",
                  task_groups=[TaskGroup(name="g", count=1, tasks=[t])])
        job.canonicalize()
        s.register_job(job)
        assert _wait(lambda: any(
            a.client_status == "running"
            for a in s.store.allocs_by_job("default", job.id))), \
            [(a.client_status, a.task_states, a.client_description)
             for a in s.store.allocs_by_job("default", job.id)]
        assert _wait(lambda: proof.exists() and proof.read_text().strip())
        assert proof.read_text().strip() == "a100-0,a100-1"
        # client-side accounting holds the instances
        assert c.device_manager.in_use() == {
            "nvidia/gpu/a100": ["a100-0", "a100-1"]}

        # a second device job cannot place (no free instances)
        j2 = Job(id=f"dev2-{time.time_ns()}", name="d2", type="service",
                 task_groups=[TaskGroup(name="g", count=1, tasks=[
                     Task(name="t", driver="mock_driver",
                          config={"run_for": 5.0})])])
        j2.task_groups[0].tasks[0].resources.devices = [
            DeviceRequest(name="gpu", count=1)]
        j2.canonicalize()
        s.register_job(j2)
        time.sleep(2.0)
        assert not any(a.client_status == "running"
                       for a in s.store.allocs_by_job("default", j2.id))

        # stopping the first job frees the instances
        s.deregister_job("default", job.id)
        assert _wait(lambda: c.device_manager.in_use() == {}, 15.0)
    finally:
        s.stop()
