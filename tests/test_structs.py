"""Data-model semantics tests (reference parity: nomad/structs/funcs.go)."""
import math

import pytest

from nomad_tpu import mock
from nomad_tpu.structs import (
    AllocClientStatus,
    AllocDesiredStatus,
    ComparableResources,
    allocs_fit_host,
    score_fit_binpack_host,
    score_fit_spread_host,
)
from nomad_tpu.structs.node import compute_node_class


def test_allocs_fit_empty():
    n = mock.node()
    fit, dim, used = allocs_fit_host(n, [])
    assert fit and dim == ""
    assert used.cpu_shares == 0


def test_allocs_fit_exact_capacity():
    n = mock.node()  # 4000 MHz, 8192 MB
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = 4000
    j.task_groups[0].tasks[0].resources.memory_mb = 8192
    j.task_groups[0].ephemeral_disk.size_mb = 0
    a = mock.alloc_for(j, n.id)
    fit, dim, used = allocs_fit_host(n, [a])
    assert fit, dim
    fit2, dim2, _ = allocs_fit_host(n, [a, a])
    assert not fit2 and dim2 == "cpu"


def test_allocs_fit_ignores_terminal():
    n = mock.node()
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = 4000
    a1 = mock.alloc_for(j, n.id)
    a2 = mock.alloc_for(j, n.id)
    a2.desired_status = AllocDesiredStatus.STOP
    fit, _, used = allocs_fit_host(n, [a1, a2])
    assert fit
    assert used.cpu_shares == 4000


def test_allocs_fit_respects_node_reserved():
    n = mock.node()
    n.reserved_resources.cpu_shares = 3800
    j = mock.job()
    j.task_groups[0].tasks[0].resources.cpu = 500
    a = mock.alloc_for(j, n.id)
    fit, dim, _ = allocs_fit_host(n, [a])
    assert not fit and dim == "cpu"


def test_allocs_fit_core_overlap():
    n = mock.node()
    j = mock.job()
    a1 = mock.alloc_for(j, n.id)
    a2 = mock.alloc_for(j, n.id)
    for a in (a1, a2):
        tr = a.allocated_resources.tasks["web"]
        tr.reserved_cores = (0, 1)
    fit, dim, _ = allocs_fit_host(n, [a1, a2])
    assert not fit and dim == "cores"


def test_score_fit_binpack_known_values():
    """Empty node scores 0; perfectly full node scores 18 (funcs.go:259-279)."""
    n = mock.node()
    empty = ComparableResources()
    assert score_fit_binpack_host(n, empty) == pytest.approx(0.0)
    full = ComparableResources(cpu_shares=4000, memory_mb=8192)
    assert score_fit_binpack_host(n, full) == pytest.approx(18.0)
    # half-utilized: 20 - 2*10^0.5
    half = ComparableResources(cpu_shares=2000, memory_mb=4096)
    assert score_fit_binpack_host(n, half) == pytest.approx(20 - 2 * math.sqrt(10))
    # spread is the mirror image
    assert score_fit_spread_host(n, empty) == pytest.approx(18.0)
    assert score_fit_spread_host(n, full) == pytest.approx(0.0)


def test_computed_node_class_stability():
    n1 = mock.node()
    n2 = mock.node()
    # unique.* attrs must not affect the class
    assert n1.attributes["unique.hostname"] != n2.attributes["unique.hostname"]
    assert compute_node_class(n1) == compute_node_class(n2)
    n2.attributes["kernel.name"] = "windows"
    assert compute_node_class(n1) != compute_node_class(n2)


def test_alloc_terminal_status():
    a = mock.alloc()
    assert not a.terminal_status()
    a.client_status = AllocClientStatus.FAILED
    assert a.terminal_status()
    b = mock.alloc()
    b.desired_status = AllocDesiredStatus.EVICT
    assert b.terminal_status()


def test_alloc_name_index():
    a = mock.alloc()
    a.name = "job.web[7]"
    assert a.index() == 7


def test_plan_append_stopped_alloc():
    from nomad_tpu.structs import Plan
    p = Plan()
    a = mock.alloc()
    p.append_stopped_alloc(a, "node drain", client_status="lost")
    assert len(p.node_update[a.node_id]) == 1
    stopped = p.node_update[a.node_id][0]
    assert stopped.desired_status == AllocDesiredStatus.STOP
    assert stopped.client_status == "lost"
    # the original alloc is untouched
    assert a.desired_status == AllocDesiredStatus.RUN
