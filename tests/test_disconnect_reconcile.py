"""Disconnect -> reconnect reconciliation (reference: reconcile_util.go
filterByTainted + reconcile.go computeGroup disconnect handling).

A group with max_client_disconnect_s keeps allocs on a DISCONNECTED node
in UNKNOWN instead of losing them outright: the reconciler marks them,
schedules a MAX_DISCONNECT_TIMEOUT follow-up eval, and places a
replacement.  If the node reconnects before the deadline the unknown
alloc resumes RUNNING; if the deadline passes first the alloc is lost
and replaced for good.
"""
from __future__ import annotations

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.reconcile import (
    ALLOC_LOST,
    ALLOC_UNKNOWN,
    AllocReconciler,
)
from nomad_tpu.structs import (
    AllocClientStatus,
    EvalStatus,
    EvalTrigger,
    NodeStatus,
)

NOW = 1_000_000.0
DISCONNECT_S = 30.0


def _job(count: int = 3):
    j = mock.job()
    tg = j.task_groups[0]
    tg.count = count
    tg.max_client_disconnect_s = DISCONNECT_S
    return j


def _allocs(j, nodes):
    return [mock.alloc_for(j, n.id, index=i,
                           client_status=AllocClientStatus.RUNNING)
            for i, n in enumerate(nodes)]


def _reconcile(j, existing, tainted, now=NOW):
    r = AllocReconciler(j, j.id, existing, tainted, deployment=None, now=now)
    return r.compute()


def test_disconnect_marks_unknown_and_schedules_timeout_followup():
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]})

    assert set(res.disconnect_updates) == {allocs[0].id}
    u = res.disconnect_updates[allocs[0].id]
    assert u.client_status == AllocClientStatus.UNKNOWN
    assert u.desired_description == ALLOC_UNKNOWN
    assert u.disconnected_at == NOW

    evs = res.desired_followup_evals[j.task_groups[0].name]
    assert len(evs) == 1
    ev = evs[0]
    assert ev.triggered_by == EvalTrigger.MAX_DISCONNECT_TIMEOUT
    assert ev.status == EvalStatus.PENDING
    assert ev.wait_until == NOW + DISCONNECT_S
    assert u.followup_eval_id == ev.id

    # a replacement places while the original sits in unknown; nothing
    # stops — the unknown alloc may still come back
    assert len(res.place) == 1
    assert not res.stop


def test_disconnect_without_group_support_is_lost():
    j = _job()
    j.task_groups[0].max_client_disconnect_s = None
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]})

    assert not res.disconnect_updates
    assert [sr.alloc.id for sr in res.stop] == [allocs[0].id]
    assert res.stop[0].status_description == ALLOC_LOST
    assert res.stop[0].client_status == AllocClientStatus.LOST
    assert len(res.place) == 1


def test_unknown_alloc_waits_out_the_disconnect_window():
    # the follow-up eval fires early (or another eval runs): deadline not
    # reached, node still gone -> no churn, the unknown alloc holds its slot
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]},
                     now=NOW + DISCONNECT_S / 2)

    assert not res.stop
    assert not res.place
    assert not res.disconnect_updates
    assert not res.reconnect_updates


def test_unknown_alloc_expires_to_lost_with_replacement():
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]},
                     now=NOW + DISCONNECT_S + 1.0)

    assert [sr.alloc.id for sr in res.stop] == [allocs[0].id]
    assert res.stop[0].client_status == AllocClientStatus.LOST
    assert len(res.place) == 1
    assert res.place[0].previous_alloc is allocs[0]
    assert not res.reconnect_updates


@pytest.mark.parametrize("tainted_entry", [True, False])
def test_reconnect_restores_running(tainted_entry):
    # node came back: either it shows up in tainted as READY (status just
    # flipped) or it has already dropped out of the tainted set entirely
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    tainted = {}
    if tainted_entry:
        nodes[0].status = NodeStatus.READY
        tainted[nodes[0].id] = nodes[0]
    res = _reconcile(j, allocs, tainted, now=NOW + 5.0)

    assert set(res.reconnect_updates) == {allocs[0].id}
    u = res.reconnect_updates[allocs[0].id]
    assert u.client_status == AllocClientStatus.RUNNING
    assert u.disconnected_at == 0.0
    # the reconnected alloc fills its own slot: no replacement, no stop
    assert not res.place
    assert not res.stop


def test_reconnect_after_replacement_scales_down_surplus():
    # disconnect placed a replacement; the original then reconnects while
    # both are live -> group is over count and one of the pair stops
    j = _job()
    nodes = [mock.node() for _ in range(4)]
    allocs = _allocs(j, nodes[:3])
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    replacement = mock.alloc_for(j, nodes[3].id, index=0,
                                 client_status=AllocClientStatus.RUNNING)
    res = _reconcile(j, allocs + [replacement], {}, now=NOW + 5.0)

    assert set(res.reconnect_updates) == {allocs[0].id}
    assert not res.place
    # surplus scale-down trims exactly one live alloc (the highest index
    # in the name space) so the group converges back to count
    stopped = {sr.alloc.id for sr in res.stop}
    assert len(stopped) == 1
    live = {a.id for a in allocs} | {replacement.id}
    assert stopped < live
    assert allocs[0].id not in stopped or replacement.id not in stopped
