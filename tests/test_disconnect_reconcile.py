"""Disconnect -> reconnect reconciliation (reference: reconcile_util.go
filterByTainted + reconcile.go computeGroup disconnect handling).

A group with max_client_disconnect_s keeps allocs on a DISCONNECTED node
in UNKNOWN instead of losing them outright: the reconciler marks them,
schedules a MAX_DISCONNECT_TIMEOUT follow-up eval, and places a
replacement.  If the node reconnects before the deadline the unknown
alloc resumes RUNNING; if the deadline passes first the alloc is lost
and replaced for good.
"""
from __future__ import annotations

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.reconcile import (
    ALLOC_LOST,
    ALLOC_UNKNOWN,
    AllocReconciler,
)
from nomad_tpu.structs import (
    AllocClientStatus,
    EvalStatus,
    EvalTrigger,
    NodeStatus,
)

NOW = 1_000_000.0
DISCONNECT_S = 30.0


def _job(count: int = 3):
    j = mock.job()
    tg = j.task_groups[0]
    tg.count = count
    tg.max_client_disconnect_s = DISCONNECT_S
    return j


def _allocs(j, nodes):
    return [mock.alloc_for(j, n.id, index=i,
                           client_status=AllocClientStatus.RUNNING)
            for i, n in enumerate(nodes)]


def _reconcile(j, existing, tainted, now=NOW):
    r = AllocReconciler(j, j.id, existing, tainted, deployment=None, now=now)
    return r.compute()


def test_disconnect_marks_unknown_and_schedules_timeout_followup():
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]})

    assert set(res.disconnect_updates) == {allocs[0].id}
    u = res.disconnect_updates[allocs[0].id]
    assert u.client_status == AllocClientStatus.UNKNOWN
    assert u.desired_description == ALLOC_UNKNOWN
    assert u.disconnected_at == NOW

    evs = res.desired_followup_evals[j.task_groups[0].name]
    assert len(evs) == 1
    ev = evs[0]
    assert ev.triggered_by == EvalTrigger.MAX_DISCONNECT_TIMEOUT
    assert ev.status == EvalStatus.PENDING
    assert ev.wait_until == NOW + DISCONNECT_S
    assert u.followup_eval_id == ev.id

    # a replacement places while the original sits in unknown; nothing
    # stops — the unknown alloc may still come back
    assert len(res.place) == 1
    assert not res.stop


def test_disconnect_without_group_support_is_lost():
    j = _job()
    j.task_groups[0].max_client_disconnect_s = None
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]})

    assert not res.disconnect_updates
    assert [sr.alloc.id for sr in res.stop] == [allocs[0].id]
    assert res.stop[0].status_description == ALLOC_LOST
    assert res.stop[0].client_status == AllocClientStatus.LOST
    assert len(res.place) == 1


def test_unknown_alloc_waits_out_the_disconnect_window():
    # the follow-up eval fires early (or another eval runs): deadline not
    # reached, node still gone -> no churn, the unknown alloc holds its slot
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]},
                     now=NOW + DISCONNECT_S / 2)

    assert not res.stop
    assert not res.place
    assert not res.disconnect_updates
    assert not res.reconnect_updates


def test_unknown_alloc_expires_to_lost_with_replacement():
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    nodes[0].status = NodeStatus.DISCONNECTED
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    res = _reconcile(j, allocs, {nodes[0].id: nodes[0]},
                     now=NOW + DISCONNECT_S + 1.0)

    assert [sr.alloc.id for sr in res.stop] == [allocs[0].id]
    assert res.stop[0].client_status == AllocClientStatus.LOST
    assert len(res.place) == 1
    assert res.place[0].previous_alloc is allocs[0]
    assert not res.reconnect_updates


@pytest.mark.parametrize("tainted_entry", [True, False])
def test_reconnect_restores_running(tainted_entry):
    # node came back: either it shows up in tainted as READY (status just
    # flipped) or it has already dropped out of the tainted set entirely
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    tainted = {}
    if tainted_entry:
        nodes[0].status = NodeStatus.READY
        tainted[nodes[0].id] = nodes[0]
    res = _reconcile(j, allocs, tainted, now=NOW + 5.0)

    assert set(res.reconnect_updates) == {allocs[0].id}
    u = res.reconnect_updates[allocs[0].id]
    assert u.client_status == AllocClientStatus.RUNNING
    assert u.disconnected_at == 0.0
    # the reconnected alloc fills its own slot: no replacement, no stop
    assert not res.place
    assert not res.stop


def test_reconnect_after_replacement_scales_down_surplus():
    # disconnect placed a replacement; the original then reconnects while
    # both are live -> group is over count and one of the pair stops
    j = _job()
    nodes = [mock.node() for _ in range(4)]
    allocs = _allocs(j, nodes[:3])
    allocs[0].client_status = AllocClientStatus.UNKNOWN
    allocs[0].disconnected_at = NOW
    replacement = mock.alloc_for(j, nodes[3].id, index=0,
                                 client_status=AllocClientStatus.RUNNING)
    res = _reconcile(j, allocs + [replacement], {}, now=NOW + 5.0)

    assert set(res.reconnect_updates) == {allocs[0].id}
    assert not res.place
    # surplus scale-down trims exactly one live alloc (the highest index
    # in the name space) so the group converges back to count
    stopped = {sr.alloc.id for sr in res.stop}
    assert len(stopped) == 1
    live = {a.id for a in allocs} | {replacement.id}
    assert stopped < live
    assert allocs[0].id not in stopped or replacement.id not in stopped


# ------------------------- node state beats drain state (churn mid-drain)


def _draining(node):
    from nomad_tpu.structs.node import DrainStrategy
    node.drain_strategy = DrainStrategy(deadline_s=3600.0,
                                        force_deadline=NOW + 3600.0,
                                        started_at=NOW)
    return node


def test_down_while_draining_allocs_lost_and_replaced_exactly_once():
    """A node hard-killed mid-drain has LOST its allocs: they must route
    through the lost path (stop + client LOST + same-name replacement),
    not wait behind the dead node's drainer migrate slots."""
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    dead = _draining(nodes[0])
    dead.status = NodeStatus.DOWN
    res = _reconcile(j, allocs, {dead.id: dead})

    stops = [s for s in res.stop if s.alloc.id == allocs[0].id]
    assert len(stops) == 1
    assert stops[0].status_description == ALLOC_LOST
    assert stops[0].client_status == AllocClientStatus.LOST
    # exactly one replacement, reusing the lost alloc's name
    places = [p for p in res.place if p.previous_alloc is allocs[0]]
    assert len(places) == 1
    assert places[0].name == allocs[0].name
    # nothing about the dead node rides the migrate path
    assert not any(s.status_description == "alloc is being migrated"
                   for s in res.stop)


def test_draining_ready_node_still_migrates():
    """Sanity: the down-beats-draining reordering must not swallow the
    normal drain path on a live draining node."""
    from nomad_tpu.structs.alloc import DesiredTransition
    j = _job()
    nodes = [mock.node() for _ in range(3)]
    allocs = _allocs(j, nodes)
    allocs[0].desired_transition = DesiredTransition(migrate=True)
    res = _reconcile(j, allocs, {nodes[0].id: _draining(nodes[0])})
    places = [p for p in res.place if p.previous_alloc is allocs[0]]
    assert len(places) == 1
    assert not any(s.client_status == AllocClientStatus.LOST
                   for s in res.stop)


# ----------------------- canary naming vs lost replacements (churn storms)


def _canary_update(j):
    from nomad_tpu.structs.job import UpdateStrategy
    j.task_groups[0].update = UpdateStrategy(
        max_parallel=1, canary=1, auto_revert=True, auto_promote=True,
        health_check="checks")
    return j


def test_canary_name_does_not_collide_with_lost_replacement():
    """Storm scenario: a v0 alloc's node dies while a canary deployment
    wants its first canary.  The lost alloc's in-flight replacement keeps
    its name, so the canary must pick a DIFFERENT index — two live
    allocs with one name breaks every name-keyed dedup downstream."""
    j0 = _canary_update(_job(count=4))
    j0.version = 0
    j1 = j0.copy()
    j1.version = 1
    j1.task_groups[0].tasks[0].config = {"command": "/bin/new"}
    nodes = [mock.node() for _ in range(4)]
    allocs = _allocs(j0, nodes)
    nodes[2].status = NodeStatus.DOWN
    res = _reconcile(j1, allocs, {nodes[2].id: nodes[2]})

    names = [p.name for p in res.place]
    assert len(names) == len(set(names)), f"duplicate placement {names}"
    canaries = [p for p in res.place if p.is_canary]
    assert len(canaries) == 1
    lost_repl = [p for p in res.place if p.previous_alloc is allocs[2]]
    assert len(lost_repl) == 1
    assert canaries[0].name != lost_repl[0].name


def test_lost_canary_replaced_through_canary_path_only():
    """A canary whose node dies must come back as a canary — one canary
    placement, no generic count-slot replacement for it."""
    j0 = _canary_update(_job(count=3))
    j0.version = 0
    j1 = j0.copy()
    j1.version = 1
    j1.task_groups[0].tasks[0].config = {"command": "/bin/new"}
    nodes = [mock.node() for _ in range(4)]
    # only 2 of 3 count slots filled: a free slot is exactly what would
    # tempt a generic lost-replacement of the canary
    allocs = _allocs(j0, nodes[:2])
    dead_canary = mock.alloc_for(
        j1, nodes[3].id, index=3,
        client_status=AllocClientStatus.RUNNING,
        deployment_status={"canary": True})
    nodes[3].status = NodeStatus.DOWN
    res = _reconcile(j1, allocs + [dead_canary],
                     {nodes[3].id: nodes[3]})

    # the dead canary is stopped as lost
    lost_stops = [s for s in res.stop if s.alloc.id == dead_canary.id]
    assert len(lost_stops) == 1
    assert lost_stops[0].client_status == AllocClientStatus.LOST
    # replaced exactly once, through the canary path
    canaries = [p for p in res.place if p.is_canary]
    assert len(canaries) == 1
    assert not any(p.previous_alloc is dead_canary for p in res.place)
    names = [p.name for p in res.place]
    assert len(names) == len(set(names))


# ------------------------------------------------- duplicate alloc names


def _plain_job(count: int = 3):
    j = mock.job()
    j.task_groups[0].count = count
    return j


def test_duplicate_name_allocs_dedup_to_one():
    """Two live allocs holding the same index (racing plans under churn)
    must not wedge the group: live == count hides the surplus, and the
    missing sibling index can never be placed.  The reconciler stops all
    but one holder and re-places the missing name."""
    from nomad_tpu.scheduler.reconcile import ALLOC_DUPLICATE

    j = _plain_job(3)
    nodes = [mock.node() for _ in range(4)]
    a0 = mock.alloc_for(j, nodes[0].id, index=0,
                        client_status=AllocClientStatus.RUNNING)
    dup_old = mock.alloc_for(j, nodes[1].id, index=2,
                             client_status=AllocClientStatus.RUNNING)
    dup_old.create_index = 10
    dup_new = mock.alloc_for(j, nodes[2].id, index=2,
                             client_status=AllocClientStatus.RUNNING)
    dup_new.create_index = 20

    res = _reconcile(j, [a0, dup_old, dup_new], {})

    dup_stops = [s for s in res.stop
                 if s.status_description == ALLOC_DUPLICATE]
    assert [s.alloc.id for s in dup_stops] == [dup_old.id]
    # the freed slot re-places the missing index 1
    assert [p.name for p in res.place] == [a0.name.replace("[0]", "[1]")]


def test_duplicate_name_prefers_healthy_holder():
    from nomad_tpu.scheduler.reconcile import ALLOC_DUPLICATE

    j = _plain_job(2)
    nodes = [mock.node() for _ in range(3)]
    a0 = mock.alloc_for(j, nodes[0].id, index=0,
                        client_status=AllocClientStatus.RUNNING)
    healthy = mock.alloc_for(j, nodes[1].id, index=1,
                             client_status=AllocClientStatus.RUNNING,
                             deployment_status={"healthy": True})
    healthy.create_index = 10
    unhealthy_newer = mock.alloc_for(
        j, nodes[2].id, index=1,
        client_status=AllocClientStatus.RUNNING)
    unhealthy_newer.create_index = 20

    res = _reconcile(j, [a0, healthy, unhealthy_newer], {})

    dup_stops = [s for s in res.stop
                 if s.status_description == ALLOC_DUPLICATE]
    assert [s.alloc.id for s in dup_stops] == [unhealthy_newer.id]
    assert not res.place


def test_unique_names_are_left_alone():
    from nomad_tpu.scheduler.reconcile import ALLOC_DUPLICATE

    j = _plain_job(3)
    nodes = [mock.node() for _ in range(3)]
    allocs = [mock.alloc_for(j, n.id, index=i,
                             client_status=AllocClientStatus.RUNNING)
              for i, n in enumerate(nodes)]
    res = _reconcile(j, allocs, {})
    assert not [s for s in res.stop
                if s.status_description == ALLOC_DUPLICATE]
    assert not res.place


def test_current_version_alloc_outside_active_deployment_joins_it():
    """A lost-alloc replacement placed from a snapshot that predates the
    deployment carries no deployment_id; the watcher would wait on its
    health forever and the rollout wedges RUNNING.  The reconciler joins
    such allocs to the active deployment (deployment_status reset so
    health is re-proven)."""
    from nomad_tpu.structs import (Deployment, DeploymentState,
                                   DeploymentStatus)
    from nomad_tpu.structs.job import UpdateStrategy

    j = _plain_job(2)
    tg = j.task_groups[0]
    tg.update = UpdateStrategy(max_parallel=1, health_check="checks")
    d = Deployment(namespace=j.namespace, job_id=j.id,
                   job_version=j.version, job_create_index=j.create_index,
                   status=DeploymentStatus.RUNNING)
    d.task_groups[tg.name] = DeploymentState(desired_total=2)

    nodes = [mock.node() for _ in range(2)]
    inside = mock.alloc_for(j, nodes[0].id, index=0,
                            client_status=AllocClientStatus.RUNNING,
                            deployment_status={"healthy": True})
    inside.deployment_id = d.id
    stranded = mock.alloc_for(j, nodes[1].id, index=1,
                              client_status=AllocClientStatus.RUNNING)
    assert stranded.deployment_id == ""

    r = AllocReconciler(j, j.id, [inside, stranded], {},
                        deployment=d, now=NOW)
    res = r.compute()

    u = res.attribute_updates.get(stranded.id)
    assert u is not None
    assert u.deployment_id == d.id
    assert u.deployment_status is None
    assert not res.place and not res.stop
    # the alloc already inside is left alone
    assert inside.id not in res.attribute_updates
