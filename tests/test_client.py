"""Client (node agent) tests — fingerprint, drivers, task/alloc runners,
restart policies, state recovery, and the full server+client data plane
(reference analogs: client/client_test.go, taskrunner tests,
drivers/mock/driver_test.go)."""
import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.client import Client, ClientConfig
from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.drivers import (
    DriverRegistry,
    ExitResult,
    MockDriver,
    RawExecDriver,
    TaskHandle,
)
from nomad_tpu.client.fingerprint import fingerprint_node
from nomad_tpu.client.state import ClientStateDB
from nomad_tpu.client.taskenv import build_task_env, interpolate
from nomad_tpu.client.taskrunner import RestartTracker
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.structs import Job, Node, RestartPolicy, Task, TaskGroup


def _wait(cond, timeout=10.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ------------------------------------------------------------ units

def test_fingerprint_node():
    n = Node(id="n1", name="test")
    fingerprint_node(n, {"raw_exec": {"detected": True, "healthy": True}})
    assert n.attributes["kernel.name"] == "linux"
    assert int(n.attributes["cpu.numcores"]) >= 1
    assert n.node_resources.memory_mb > 0
    assert n.node_resources.cpu.cpu_shares > 0
    assert n.attributes["driver.raw_exec"] == "1"


def test_taskenv_interpolation():
    alloc = mock.alloc()
    alloc.name = "web.fe[2]"
    task = alloc.job.task_groups[0].tasks[0]
    task.env = {"LISTEN": "${NOMAD_ALLOC_INDEX}",
                "WHO": "${meta.owner}"}
    task.meta = {"owner": "ops"}
    node = mock.node()
    env = build_task_env(alloc, task, node, "/tmp/x")
    assert env["NOMAD_ALLOC_INDEX"] == "2"
    assert env["NOMAD_TASK_NAME"] == task.name
    assert env["LISTEN"] == "2"
    assert env["WHO"] == "ops"
    assert interpolate("${attr.kernel.name}", env, node) == "linux"
    assert interpolate("${unknown.thing}", env, node) == "${unknown.thing}"


def test_restart_tracker_fail_mode():
    rt = RestartTracker(RestartPolicy(attempts=2, interval_s=300.0,
                                      delay_s=1.0, mode="fail"))
    assert rt.next(ExitResult(exit_code=1), now=100.0) == ("restart", 1.0)
    assert rt.next(ExitResult(exit_code=1), now=101.0) == ("restart", 1.0)
    assert rt.next(ExitResult(exit_code=1), now=102.0) == ("fail", None)
    # new window resets the budget
    v, _ = rt.next(ExitResult(exit_code=1), now=500.0)
    assert v == "restart"


def test_restart_tracker_delay_mode():
    rt = RestartTracker(RestartPolicy(attempts=1, interval_s=100.0,
                                      delay_s=5.0, mode="delay"))
    assert rt.next(ExitResult(exit_code=1), now=0.0) == ("restart", 5.0)
    verdict, delay = rt.next(ExitResult(exit_code=1), now=10.0)
    assert verdict == "restart"
    assert delay >= 90.0           # waits out the window


def test_mock_driver_run_for():
    drv = MockDriver()
    task = Task(name="t", driver="mock_driver",
                config={"run_for": 0.1, "exit_code": 0})
    h = TaskHandle(driver="mock_driver", task_name="t")
    drv.start_task(h, task, {}, "/tmp")
    res = drv.wait_task(h)
    assert res.successful()


def test_mock_driver_exit_code_and_kill():
    drv = MockDriver()
    task = Task(name="t", config={"run_for": 0.05, "exit_code": 3})
    h = TaskHandle()
    drv.start_task(h, task, {}, "/tmp")
    assert drv.wait_task(h).exit_code == 3
    # long-running task killed
    task2 = Task(name="t2", config={"run_for": 60})
    h2 = TaskHandle()
    drv.start_task(h2, task2, {}, "/tmp")
    drv.stop_task(h2)
    res = drv.wait_task(h2)
    assert res.signal == 9


def test_raw_exec_driver(tmp_path):
    drv = RawExecDriver()
    ad = AllocDir(str(tmp_path), "a1")
    ad.build()
    task_dir = ad.build_task_dir("sh")
    task = Task(name="sh", driver="raw_exec",
                config={"command": "/bin/sh",
                        "args": ["-c", "echo hello-$FOO; exit 0"]})
    h = TaskHandle()
    drv.start_task(h, task, {"FOO": "bar"}, task_dir)
    res = drv.wait_task(h)
    assert res.successful()
    # the detached logmon pump drains the pipe asynchronously
    path = os.path.join(ad.logs_dir(), "sh.stdout")
    deadline = time.time() + 5.0
    out = ""
    while time.time() < deadline and "hello-bar" not in out:
        out = open(path).read()
        time.sleep(0.05)
    assert "hello-bar" in out


def test_raw_exec_stop(tmp_path):
    drv = RawExecDriver()
    ad = AllocDir(str(tmp_path), "a2")
    ad.build()
    task_dir = ad.build_task_dir("sleeper")
    task = Task(name="sleeper", driver="raw_exec",
                config={"command": "/bin/sleep", "args": ["60"]})
    h = TaskHandle()
    drv.start_task(h, task, {}, task_dir)
    t0 = time.time()
    drv.stop_task(h, timeout_s=2.0)
    res = drv.wait_task(h)
    assert time.time() - t0 < 5.0
    assert not res.successful()


def test_client_state_db(tmp_path):
    db = ClientStateDB(str(tmp_path / "state.db"))
    db.put_alloc("a1", {"job_id": "j"})
    h = TaskHandle(driver="raw_exec", task_name="t", pid=1234)
    db.put_task_state("a1", "t", "running", False, 2, h)
    assert db.get_allocs()["a1"]["job_id"] == "j"
    st, failed, restarts, got = db.get_task_states("a1")["t"]
    assert (st, failed, restarts) == ("running", False, 2)
    assert got.pid == 1234
    db.delete_alloc("a1")
    assert db.get_allocs() == {}
    db.close()


# ------------------------------------------------------------ E2E

@pytest.fixture
def cluster(tmp_path):
    """Dev server + one real client wired over the in-proc RPC."""
    server = Server(ServerConfig(num_schedulers=2, heartbeat_ttl=30.0))
    server.start()
    client = Client(
        ClientConfig(node_name="c1", data_dir=str(tmp_path / "client"),
                     watch_interval=0.05),
        rpc=server.endpoints.handle)
    client.start()
    yield server, client
    client.stop()
    server.stop()


def _batch_job(command="/bin/true", args=None, **cfg):
    job = Job(id=f"batch-{time.time_ns()}", name="batch", type="batch",
              task_groups=[TaskGroup(name="g", count=1, tasks=[
                  Task(name="t", driver="raw_exec",
                       config={"command": command,
                               "args": args or [], **cfg})])])
    job.canonicalize()
    return job


def test_e2e_batch_job_completes(cluster, tmp_path):
    server, client = cluster
    out_file = tmp_path / "proof.txt"
    job = _batch_job("/bin/sh", ["-c", f"echo done > {out_file}"])
    server.register_job(job)
    assert _wait(lambda: [
        a for a in server.store.allocs_by_job("default", job.id)
        if a.client_status == "complete"], 15.0), \
        [(a.client_status, a.task_states) for a in
         server.store.allocs_by_job("default", job.id)]
    assert out_file.read_text().strip() == "done"
    allocs = server.store.allocs_by_job("default", job.id)
    ts = allocs[0].task_states["t"]
    assert ts.state == "dead" and not ts.failed
    assert any(e["type"] == "Started" for e in ts.events)


def test_e2e_service_job_runs_and_stops(cluster):
    server, client = cluster
    job = Job(id="svc-e2e", name="svc", type="service",
              task_groups=[TaskGroup(name="g", count=2, tasks=[
                  Task(name="t", driver="mock_driver",
                       config={"run_for": 0})])])
    job.canonicalize()
    server.register_job(job)
    assert _wait(lambda: len([
        a for a in server.store.allocs_by_job("default", job.id)
        if a.client_status == "running"]) == 2, 15.0)
    # job stop: clients should kill tasks, allocs go complete
    server.deregister_job("default", job.id)
    assert _wait(lambda: all(
        a.client_terminal_status()
        for a in server.store.allocs_by_job("default", job.id)), 15.0), \
        [(a.desired_status, a.client_status)
         for a in server.store.allocs_by_job("default", job.id)]


def test_e2e_failed_task_restarts_then_reschedules(cluster):
    server, client = cluster
    job = Job(id="fail-e2e", name="f", type="batch",
              task_groups=[TaskGroup(
                  name="g", count=1,
                  restart_policy=RestartPolicy(attempts=1, interval_s=300.0,
                                               delay_s=0.05, mode="fail"),
                  tasks=[Task(name="t", driver="raw_exec",
                              config={"command": "/bin/false"})])])
    job.canonicalize()
    job.task_groups[0].reschedule_policy.attempts = 0
    job.task_groups[0].reschedule_policy.unlimited = False
    server.register_job(job)
    assert _wait(lambda: [
        a for a in server.store.allocs_by_job("default", job.id)
        if a.client_status == "failed"], 15.0)
    a = [x for x in server.store.allocs_by_job("default", job.id)
         if x.client_status == "failed"][0]
    assert a.task_states["t"].restarts == 1
    assert a.task_states["t"].failed


def test_e2e_node_fingerprint_visible(cluster):
    server, client = cluster
    assert _wait(lambda: server.store.node_by_id(client.node.id)
                 is not None, 5.0)
    n = server.store.node_by_id(client.node.id)
    assert n.status == "ready"
    assert n.attributes.get("driver.raw_exec") == "1"


def test_client_restart_recovery(tmp_path):
    """A client restart recovers a still-running raw_exec task from the
    state DB (reference: persisted task handles + RecoverTask)."""
    server = Server(ServerConfig(num_schedulers=2, heartbeat_ttl=30.0))
    server.start()
    data_dir = str(tmp_path / "client")
    client = Client(ClientConfig(node_name="c1", data_dir=data_dir,
                                 watch_interval=0.05),
                    rpc=server.endpoints.handle)
    client.start()
    try:
        job = _batch_job("/bin/sleep", ["30"])
        server.register_job(job)
        assert _wait(lambda: [
            a for a in server.store.allocs_by_job("default", job.id)
            if a.client_status == "running"], 15.0)
        # hard-stop the client without killing tasks (simulated crash):
        client._stop.set()
        time.sleep(0.3)
        pid = next(iter(client.alloc_runners.values())) \
            .task_runners["t"].handle.pid
        client.state_db.close()

        c2 = Client(ClientConfig(node_name="c1", data_dir=data_dir,
                                 watch_interval=0.05),
                    rpc=server.endpoints.handle)
        c2.start()
        try:
            assert c2.num_allocs() == 1
            ar = next(iter(c2.alloc_runners.values()))
            assert _wait(lambda: ar.client_status == "running", 5.0)
            tr = ar.task_runners["t"]
            assert tr.handle.pid == pid
            os.kill(pid, 15)       # the recovered task exiting is seen
            assert _wait(lambda: tr.state.state == "dead", 10.0)
        finally:
            c2.stop()
    finally:
        server.stop()
        import signal as _sig
        try:
            os.kill(pid, _sig.SIGKILL)
        except ProcessLookupError:
            pass


def test_stop_after_client_disconnect(tmp_path):
    """heartbeatstop (client/heartbeatstop.go:158): a disconnected client
    stops allocs whose group sets stop_after_client_disconnect once the
    deadline passes the last successful heartbeat."""
    server = Server(ServerConfig(num_schedulers=2, heartbeat_ttl=2.0))
    server.start()
    rpc_ok = {"v": True}

    def gated_rpc(method, args):
        if not rpc_ok["v"]:
            raise ConnectionError("network partitioned")
        return server.endpoints.handle(method, args)

    client = Client(
        ClientConfig(node_name="c-dc", data_dir=str(tmp_path / "c"),
                     watch_interval=0.05),
        rpc=gated_rpc)
    client.start()
    try:
        job = Job(id="svc-dc", name="svc", type="service",
                  task_groups=[TaskGroup(
                      name="g", count=1,
                      stop_after_client_disconnect_s=1.0,
                      tasks=[Task(name="t", driver="mock_driver",
                                  config={"run_for": 0})])])
        job.canonicalize()
        server.register_job(job)
        assert _wait(lambda: any(
            ar.client_status == "running"
            for ar in client.alloc_runners.values()), 15.0)

        # partition the client from the server
        rpc_ok["v"] = False
        assert _wait(lambda: any(
            ar.client_status == "lost"
            for ar in client.alloc_runners.values()), 15.0), \
            [(ar.client_status, ar.client_description)
             for ar in client.alloc_runners.values()]
        ar = next(iter(client.alloc_runners.values()))
        assert "client disconnect" in ar.client_description
        assert all(tr.state.state == "dead"
                   for tr in ar.task_runners.values())
    finally:
        rpc_ok["v"] = True
        client.stop()
        server.stop()


# ------------------------------------------------------------ exec driver

def _exec_task(command, args=None, cpu=100, mem=64):
    from nomad_tpu.structs.resources import Resources
    return Task(name="e", driver="exec",
                config={"command": command, "args": args or []},
                resources=Resources(cpu=cpu, memory_mb=mem))


def test_exec_driver_runs_in_cgroup(tmp_path):
    from nomad_tpu.client.drivers import ExecDriver, TaskHandle

    drv = ExecDriver()
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    h = TaskHandle(driver="exec", task_name="e")
    task = _exec_task("/bin/sh", ["-c", "cat /proc/self/cgroup > out.txt"])
    drv.start_task(h, task, {}, str(task_dir))
    res = drv.wait_task(h)
    assert res.exit_code == 0
    cg = (task_dir / "out.txt").read_text()
    if os.access("/sys/fs/cgroup/memory", os.W_OK):
        assert "nomad_tpu" in cg, cg
    stats = drv.inspect_task(h)
    assert stats["cgroup"] == os.access("/sys/fs/cgroup/memory", os.W_OK)
    drv.destroy_task(h)


def test_exec_driver_stop_and_signal(tmp_path):
    from nomad_tpu.client.drivers import ExecDriver, TaskHandle

    drv = ExecDriver()
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    h = TaskHandle(driver="exec", task_name="e")
    drv.start_task(h, _exec_task("/bin/sleep", ["300"]), {}, str(task_dir))
    t0 = time.time()
    done = {}

    def waiter():
        done["res"] = drv.wait_task(h)

    import threading
    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.2)
    drv.stop_task(h, timeout_s=2.0)
    t.join(10.0)
    assert "res" in done and done["res"].signal in (15, 9)
    assert time.time() - t0 < 10
    drv.destroy_task(h)


def test_exec_driver_reattach_after_driver_restart(tmp_path):
    """The executor process outlives the driver object: a brand-new
    driver instance recovers the task from the handle's socket path and
    still observes its exit (go-plugin reattach semantics)."""
    from nomad_tpu.client.drivers import ExecDriver, TaskHandle

    drv1 = ExecDriver()
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    h = TaskHandle(driver="exec", task_name="e")
    proof = task_dir / "done.txt"
    drv1.start_task(
        h, _exec_task("/bin/sh", ["-c", f"sleep 0.5; echo ok > {proof}"]),
        {}, str(task_dir))
    del drv1                          # "client restart"

    drv2 = ExecDriver()
    assert drv2.recover_task(h), "reattach over the socket failed"
    res = drv2.wait_task(h)
    assert res.exit_code == 0
    assert proof.read_text().strip() == "ok"
    drv2.destroy_task(h)
    assert not drv2.recover_task(h)
