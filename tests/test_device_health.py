"""Device plugin health stream (VERDICT r3 item 8): the client's device
fingerprint loop updates per-instance health, unhealthy instances carry no
scheduling capacity, and allocations holding a dead instance reschedule
onto healthy hardware."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.structs.resources import DeviceRequest, NodeDevice


def _wait(cond, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_unhealthy_instances_excluded_from_capacity_and_assignment():
    from nomad_tpu.encode import ClusterMatrix
    from nomad_tpu.scheduler.devices import assign_device_instances

    n = mock.node()
    n.node_resources.devices = [NodeDevice(
        vendor="nvidia", type="gpu", name="a100",
        instance_ids=["g0", "g1"], unhealthy_ids=["g0"])]
    cm = ClusterMatrix()
    row = cm.upsert_node(n)
    assert int(cm.device_caps["nvidia/gpu/a100"][row]) == 1

    got = assign_device_instances(n, [], DeviceRequest(name="gpu", count=1))
    assert got["device_ids"] == ["g1"]
    assert assign_device_instances(
        n, [], DeviceRequest(name="gpu", count=2)) is None


def test_device_death_reschedules_allocs():
    """A re-registration marking an instance unhealthy migrates the alloc
    holding it; the replacement lands on a node with healthy devices."""
    s = Server(ServerConfig(num_schedulers=2, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    try:
        nodes = []
        for i in range(2):
            n = mock.node()
            n.node_resources.devices = [NodeDevice(
                vendor="nvidia", type="gpu", name="a100",
                instance_ids=[f"n{i}-g0"])]
            nodes.append(n)
            s.register_node(n)

        j = mock.batch_job()
        tg = j.task_groups[0]
        tg.count = 1
        tg.tasks[0].resources.devices = [DeviceRequest(name="gpu", count=1)]
        s.register_job(j)

        def live():
            return [a for a in s.store.allocs_by_job("default", j.id)
                    if not a.terminal_status()
                    and not a.desired_transition.should_force_reschedule()]
        assert _wait(lambda: len(live()) == 1)
        a0 = live()[0]
        victim = next(n for n in nodes if n.id == a0.node_id)
        survivor = next(n for n in nodes if n.id != a0.node_id)

        # the device fingerprint now reports the held instance unhealthy
        victim.node_resources.devices[0].unhealthy_ids = list(
            victim.node_resources.devices[0].instance_ids)
        s.register_node(victim)

        def rescheduled():
            allocs = live()
            return (len(allocs) == 1 and allocs[0].id != a0.id
                    and allocs[0].node_id == survivor.id)
        assert _wait(rescheduled, timeout=30), \
            [(a.id[:8], a.node_id[:8], a.desired_status)
             for a in s.store.allocs_by_job("default", j.id)]
    finally:
        s.stop()


def test_client_device_monitor_pushes_health():
    """The client's fingerprint loop re-registers the node when device
    health changes."""
    from nomad_tpu.client.client import Client, ClientConfig

    s = Server(ServerConfig(num_schedulers=1, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    devices = [NodeDevice(vendor="amd", type="gpu", name="mi300",
                          instance_ids=["d0", "d1"])]
    c = Client(ClientConfig(node_name="dev-client",
                            device_fingerprint=lambda: devices,
                            device_poll_interval=0.1),
               rpc=s.rpc_leader)
    c.start()
    try:
        def caps():
            node = s.store.node_by_id(c.node.id)
            if node is None or not node.node_resources.devices:
                return None
            return len(node.node_resources.devices[0].healthy_ids())
        assert _wait(lambda: caps() == 2)
        devices[0] = NodeDevice(vendor="amd", type="gpu", name="mi300",
                                instance_ids=["d0", "d1"],
                                unhealthy_ids=["d1"])
        assert _wait(lambda: caps() == 1, timeout=15)
    finally:
        c.stop()
        s.stop()
