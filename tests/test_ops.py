"""Kernel golden tests: device ops vs host reference semantics."""
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.ops import fits_after, score_fit, validate_capacity
from nomad_tpu.structs import ComparableResources, score_fit_binpack_host, score_fit_spread_host


def _matrix(n=5):
    cm = ClusterMatrix()
    nodes = [mock.node() for _ in range(n)]
    for nd in nodes:
        cm.upsert_node(nd)
    return cm, nodes


def test_score_fit_matches_host_reference():
    cm, nodes = _matrix()
    rng = np.random.default_rng(0)
    util = np.zeros_like(cm.used)
    rows = [cm.row_of[n.id] for n in nodes]
    for r in rows:
        util[r, 0] = rng.integers(0, 4000)
        util[r, 1] = rng.integers(0, 8192)
    dev_bp = np.asarray(score_fit(cm.capacity, util, False))
    dev_sp = np.asarray(score_fit(cm.capacity, util, True))
    for n in nodes:
        r = cm.row_of[n.id]
        u = ComparableResources(cpu_shares=int(util[r, 0]), memory_mb=int(util[r, 1]))
        assert dev_bp[r] == pytest.approx(score_fit_binpack_host(n, u), rel=1e-5)
        assert dev_sp[r] == pytest.approx(score_fit_spread_host(n, u), rel=1e-5)


def test_score_fit_zero_capacity_rows():
    """Padded rows (capacity 0) must not produce NaNs."""
    cm, _ = _matrix(2)
    util = np.zeros_like(cm.used)
    s = np.asarray(score_fit(cm.capacity, util, False))
    assert not np.isnan(s).any()


def test_fits_after_and_validate():
    cm, nodes = _matrix(2)
    r = cm.row_of[nodes[0].id]
    d = np.array([4000.0, 8192.0, 0.0, 0.0], np.float32)
    f = np.asarray(fits_after(cm.capacity, cm.used, d))
    assert f[r]
    used = cm.used.copy()
    used[r] = [4001, 0, 0, 0]
    assert not np.asarray(validate_capacity(cm.capacity, used))[r]
