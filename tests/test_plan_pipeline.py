"""Pipelined plan apply (reference plan_apply.go:71-178): plan N+1 is
evaluated while plan N's commit is in flight, and the in-flight overlay
makes conflicting placements fail validation even before N commits."""
import threading
import time

import numpy as np

from nomad_tpu import mock
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.state.store import AppliedPlanResults, StateStore
from nomad_tpu.structs.plan import Plan


def _world():
    store = StateStore()
    node = mock.node()
    store.upsert_node(1, node)
    return store, node


def _plan_for(job, node_id, cpu=3000, mem=6000):
    j = job
    j.task_groups[0].tasks[0].resources.cpu = cpu
    j.task_groups[0].tasks[0].resources.memory_mb = mem
    alloc = mock.alloc_for(j, node_id=node_id)
    plan = Plan(eval_id=mock._uuid(), job=j)
    plan.append_alloc(alloc, j)
    return plan


def test_pipeline_overlaps_commit_and_rejects_conflicts():
    store, node = _world()

    gate = threading.Event()          # blocks the first commit
    committed = []

    def slow_commit(applied: AppliedPlanResults) -> int:
        if not committed:
            gate.wait(timeout=10)
        idx = store.latest_index + 1
        store.upsert_plan_results(idx, applied)
        committed.append(idx)
        return idx

    applier = PlanApplier(store, commit_fn=slow_commit)
    queue = PlanQueue()
    queue.set_enabled(True)
    stop = threading.Event()
    t = threading.Thread(target=applier.run_loop, args=(queue, stop),
                         daemon=True)
    t.start()
    try:
        # plan A eats most of the node (4000 cpu / 8192 mem capacity)
        pa = queue.enqueue(_plan_for(mock.job(), node.id))
        # plan B wants the same resources: must be REJECTED against the
        # in-flight overlay even though A has not committed yet
        pb = queue.enqueue(_plan_for(mock.job(), node.id))

        # B's evaluation happens while A's commit is gated; give it time
        deadline = time.time() + 5
        while time.time() < deadline and applier.stats["partial"] == 0:
            time.sleep(0.02)
        assert applier.stats["partial"] == 1, \
            "plan B should have been rejected against the overlay"
        assert not committed, "A must still be in flight"

        gate.set()
        ra = pa.future.result(timeout=10)
        rb = pb.future.result(timeout=10)
        assert ra.node_allocation and not ra.rejected_nodes
        assert rb.rejected_nodes == [node.id]
        assert rb.refresh_index >= 1
    finally:
        stop.set()
        gate.set()
        t.join(2)


def test_pipeline_overlay_cleared_after_commit():
    store, node = _world()
    applier = PlanApplier(store)
    queue = PlanQueue()
    queue.set_enabled(True)
    stop = threading.Event()
    t = threading.Thread(target=applier.run_loop, args=(queue, stop),
                         daemon=True)
    t.start()
    try:
        # sequential small plans all commit; overlay drains to empty
        for _ in range(3):
            p = queue.enqueue(_plan_for(mock.job(), node.id,
                                        cpu=500, mem=512))
            r = p.future.result(timeout=10)
            assert r.node_allocation
        deadline = time.time() + 2
        while time.time() < deadline and applier._overlay:
            time.sleep(0.01)
        assert not applier._overlay
        # committed usage reflects all three
        row = store.matrix.row_of[node.id]
        assert store.matrix.used[row, 0] == 1500.0
    finally:
        stop.set()
        t.join(2)
