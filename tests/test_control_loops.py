"""Control-loop tests: heartbeats, deployments, drainer, periodic, events,
GC (reference analogs: heartbeat_test.go, deploymentwatcher tests,
drainer tests, periodic_test.go, core_sched_test.go)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.structs import (
    AllocClientStatus,
    AllocDesiredStatus,
    DeploymentStatus,
    EvalStatus,
)
from nomad_tpu.structs.job import PeriodicConfig, UpdateStrategy


def make_server(**kw):
    s = Server(ServerConfig(num_schedulers=2, **kw))
    s.start()
    return s


# --------------------------------------------------------------- heartbeat

def test_heartbeat_expiry_marks_node_down_and_replaces():
    s = make_server(heartbeat_ttl=0.3)
    try:
        nodes = [mock.node() for _ in range(2)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job)
        # keep both nodes alive until the first placement lands (first jit
        # compile can exceed the short TTL)
        deadline = time.time() + 30
        while time.time() < deadline:
            for n in nodes:
                s.node_heartbeat(n.id)
            if s.store.allocs_by_job("default", job.id):
                break
            time.sleep(0.05)
        victim = s.store.allocs_by_job("default", job.id)[0]
        other = [n for n in nodes if n.id != victim.node_id][0]
        # keep the other node alive, let the victim's node expire
        deadline = time.time() + 3.0
        while time.time() < deadline:
            s.node_heartbeat(other.id)
            if s.store.node_by_id(victim.node_id) and \
               s.store._nodes[victim.node_id].status == "down":
                break
            time.sleep(0.05)
        assert s.store._nodes[victim.node_id].status == "down"
        s.wait_for_idle(30.0)
        run = [a for a in s.store.allocs_by_job("default", job.id)
               if a.desired_status == AllocDesiredStatus.RUN
               and a.client_status != AllocClientStatus.LOST]
        assert len(run) == 1 and run[0].node_id == other.id
    finally:
        s.stop()


# ------------------------------------------------------------- deployments

def test_deployment_succeeds_when_allocs_healthy():
    s = make_server()
    try:
        for _ in range(4):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        s.register_job(job)
        s.wait_for_idle(30.0)
        # destructive update creates a deployment
        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        assert d is not None and d.status == DeploymentStatus.RUNNING
        # mark new-version allocs healthy as a client would
        deadline = time.time() + 20
        while time.time() < deadline:
            allocs = [a for a in s.store.allocs_by_job("default", job.id)
                      if a.deployment_id == d.id
                      and a.desired_status == AllocDesiredStatus.RUN]
            for a in allocs:
                if not a.is_healthy():
                    u = a.copy()
                    u.client_status = AllocClientStatus.RUNNING
                    u.deployment_status = {"healthy": True}
                    s.store.update_allocs_from_client(s.next_index(), [u])
            dd = s.store.deployment_by_id(d.id)
            if dd.status == DeploymentStatus.SUCCESSFUL:
                break
            s.wait_for_idle(5.0)
            time.sleep(0.05)
        assert s.store.deployment_by_id(d.id).status == DeploymentStatus.SUCCESSFUL
        assert s.store.job_by_id("default", job.id).stable
    finally:
        s.stop()


def test_deployment_fails_on_unhealthy_and_autoreverts():
    s = make_server()
    try:
        for _ in range(4):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        job.update = UpdateStrategy(max_parallel=2, auto_revert=True)
        job.task_groups[0].update = None
        s.register_job(job)
        s.wait_for_idle(30.0)
        # v0 healthy -> stable
        for a in s.store.allocs_by_job("default", job.id):
            u = a.copy()
            u.client_status = AllocClientStatus.RUNNING
            u.deployment_status = {"healthy": True}
            s.store.update_allocs_from_client(s.next_index(), [u])
        s.store.job_by_id("default", job.id).stable = True

        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/bad"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        # new allocs report unhealthy
        for a in s.store.allocs_by_job("default", job.id):
            if a.deployment_id == d.id and not a.terminal_status():
                u = a.copy()
                u.deployment_status = {"healthy": False}
                s.store.update_allocs_from_client(s.next_index(), [u])
        deadline = time.time() + 20
        while time.time() < deadline:
            if s.store.deployment_by_id(d.id).status == DeploymentStatus.FAILED:
                break
            time.sleep(0.05)
        assert s.store.deployment_by_id(d.id).status == DeploymentStatus.FAILED
        # auto-revert registered a new version with the old config
        deadline = time.time() + 20
        while time.time() < deadline:
            j = s.store.job_by_id("default", job.id)
            if j.version > job2.version:
                break
            time.sleep(0.05)
        j = s.store.job_by_id("default", job.id)
        assert j.task_groups[0].tasks[0].config == {"command": "/bin/date"}
    finally:
        s.stop()


# ----------------------------------------------------------------- drainer

def test_drain_migrates_allocs_off_node():
    s = make_server()
    try:
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 3
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        victim_alloc = s.store.allocs_by_job("default", job.id)[0]
        s.drainer.drain_node(victim_alloc.node_id, deadline_s=30.0)
        deadline = time.time() + 20
        while time.time() < deadline:
            run = [a for a in s.store.allocs_by_job("default", job.id)
                   if a.desired_status == AllocDesiredStatus.RUN
                   and not a.terminal_status()]
            if len(run) == 3 and all(a.node_id != victim_alloc.node_id
                                     for a in run):
                break
            time.sleep(0.05)
        run = [a for a in s.store.allocs_by_job("default", job.id)
               if a.desired_status == AllocDesiredStatus.RUN
               and not a.terminal_status()]
        assert len(run) == 3
        assert all(a.node_id != victim_alloc.node_id for a in run)
        # drain completes: strategy cleared, node ineligible
        deadline = time.time() + 10
        while time.time() < deadline:
            n = s.store._nodes[victim_alloc.node_id]
            if n.drain_strategy is None:
                break
            time.sleep(0.05)
        n = s.store._nodes[victim_alloc.node_id]
        assert n.drain_strategy is None
        assert n.scheduling_eligibility == "ineligible"
    finally:
        s.stop()


# ---------------------------------------------------------------- periodic

def test_periodic_dispatch_creates_child_jobs():
    from nomad_tpu.core.periodic import next_cron_after
    # cron parsing
    nxt = next_cron_after("*/5 * * * *", 0.0)
    assert nxt == 300.0
    assert next_cron_after("@every 30s", 100.0) == 130.0

    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        job.periodic = PeriodicConfig(spec="@every 0.2s")
        s.register_job(job)
        deadline = time.time() + 10
        children = []
        while time.time() < deadline:
            children = [j for j in s.store.jobs() if j.parent_id == job.id]
            if children:
                break
            time.sleep(0.05)
        assert children, "no periodic child launched"
        assert children[0].id.startswith(f"{job.id}/periodic-")
        assert children[0].periodic is None
        s.wait_for_idle(30.0)
        assert len(s.store.allocs_by_job("default", children[0].id)) == 1
    finally:
        s.stop()


# ------------------------------------------------------------------ events

def test_event_stream_delivers_filtered_events():
    s = make_server()
    try:
        sub = s.event_broker.subscribe({"Job": ["*"]})
        s.register_node(mock.node())
        job = mock.job()
        s.register_job(job)
        ev = sub.next(timeout=5.0)
        assert ev is not None and ev.topic == "Job"
        assert ev.type == "JobRegistered" and ev.key == job.id
        sub.close()
    finally:
        s.stop()


# --------------------------------------------------------------------- GC

def test_core_gc_collects_dead_jobs_and_evals():
    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        s.deregister_job("default", job.id)
        assert s.wait_for_idle(30.0)
        # allocs stopped but client still reports: mark complete
        for a in s.store.allocs_by_job("default", job.id):
            u = a.copy()
            u.client_status = AllocClientStatus.COMPLETE
            s.store.update_allocs_from_client(s.next_index(), [u])
        stats = s.core_scheduler.process("force-gc", force=True)
        assert stats["jobs"] == 1
        assert s.store.job_by_id("default", job.id) is None
        assert s.store.allocs_by_job("default", job.id) == []
    finally:
        s.stop()


def test_node_gc_removes_down_nodes():
    s = make_server()
    try:
        n = mock.node()
        s.register_node(n)
        s.update_node_status(n.id, "down")
        stats = s.core_scheduler.process("node-gc", force=True)
        assert stats["nodes"] == 1
        assert s.store.node_by_id(n.id) is None or \
            s.store.snapshot().node_by_id(n.id) is None
    finally:
        s.stop()
