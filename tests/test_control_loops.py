"""Control-loop tests: heartbeats, deployments, drainer, periodic, events,
GC (reference analogs: heartbeat_test.go, deploymentwatcher tests,
drainer tests, periodic_test.go, core_sched_test.go)."""
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.structs import (
    AllocClientStatus,
    AllocDesiredStatus,
    DeploymentStatus,
    EvalStatus,
)
from nomad_tpu.structs.job import PeriodicConfig, UpdateStrategy


def make_server(**kw):
    s = Server(ServerConfig(num_schedulers=2, **kw))
    s.start()
    return s


# --------------------------------------------------------------- heartbeat

def test_heartbeat_expiry_marks_node_down_and_replaces():
    s = make_server(heartbeat_ttl=0.3)
    try:
        nodes = [mock.node() for _ in range(2)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job)
        # keep both nodes alive until the first placement lands (first jit
        # compile can exceed the short TTL)
        deadline = time.time() + 30
        while time.time() < deadline:
            for n in nodes:
                s.node_heartbeat(n.id)
            if s.store.allocs_by_job("default", job.id):
                break
            time.sleep(0.05)
        victim = s.store.allocs_by_job("default", job.id)[0]
        other = [n for n in nodes if n.id != victim.node_id][0]
        # keep the other node alive, let the victim's node expire
        deadline = time.time() + 3.0
        while time.time() < deadline:
            s.node_heartbeat(other.id)
            if s.store.node_by_id(victim.node_id) and \
               s.store._nodes[victim.node_id].status == "down":
                break
            time.sleep(0.05)
        assert s.store._nodes[victim.node_id].status == "down"
        s.wait_for_idle(30.0)
        run = [a for a in s.store.allocs_by_job("default", job.id)
               if a.desired_status == AllocDesiredStatus.RUN
               and a.client_status != AllocClientStatus.LOST]
        assert len(run) == 1 and run[0].node_id == other.id
    finally:
        s.stop()


# ------------------------------------------------------------- deployments

def test_deployment_succeeds_when_allocs_healthy():
    s = make_server()
    try:
        for _ in range(4):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        s.register_job(job)
        s.wait_for_idle(30.0)
        # destructive update creates a deployment
        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        assert d is not None and d.status == DeploymentStatus.RUNNING
        # mark new-version allocs healthy as a client would
        deadline = time.time() + 20
        while time.time() < deadline:
            allocs = [a for a in s.store.allocs_by_job("default", job.id)
                      if a.deployment_id == d.id
                      and a.desired_status == AllocDesiredStatus.RUN]
            for a in allocs:
                if not a.is_healthy():
                    u = a.copy()
                    u.client_status = AllocClientStatus.RUNNING
                    u.deployment_status = {"healthy": True}
                    s.store.update_allocs_from_client(s.next_index(), [u])
            dd = s.store.deployment_by_id(d.id)
            if dd.status == DeploymentStatus.SUCCESSFUL:
                break
            s.wait_for_idle(5.0)
            time.sleep(0.05)
        assert s.store.deployment_by_id(d.id).status == DeploymentStatus.SUCCESSFUL
        assert s.store.job_by_id("default", job.id).stable
    finally:
        s.stop()


def test_deployment_fails_on_unhealthy_and_autoreverts():
    s = make_server()
    try:
        for _ in range(4):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        job.update = UpdateStrategy(max_parallel=2, auto_revert=True)
        job.task_groups[0].update = None
        s.register_job(job)
        s.wait_for_idle(30.0)
        # v0 healthy -> stable
        for a in s.store.allocs_by_job("default", job.id):
            u = a.copy()
            u.client_status = AllocClientStatus.RUNNING
            u.deployment_status = {"healthy": True}
            s.store.update_allocs_from_client(s.next_index(), [u])
        s.store.job_by_id("default", job.id).stable = True

        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/bad"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        # new allocs report unhealthy
        for a in s.store.allocs_by_job("default", job.id):
            if a.deployment_id == d.id and not a.terminal_status():
                u = a.copy()
                u.deployment_status = {"healthy": False}
                s.store.update_allocs_from_client(s.next_index(), [u])
        deadline = time.time() + 20
        while time.time() < deadline:
            if s.store.deployment_by_id(d.id).status == DeploymentStatus.FAILED:
                break
            time.sleep(0.05)
        assert s.store.deployment_by_id(d.id).status == DeploymentStatus.FAILED
        # auto-revert registered a new version with the old config
        deadline = time.time() + 20
        while time.time() < deadline:
            j = s.store.job_by_id("default", job.id)
            if j.version > job2.version:
                break
            time.sleep(0.05)
        j = s.store.job_by_id("default", job.id)
        assert j.task_groups[0].tasks[0].config == {"command": "/bin/date"}
    finally:
        s.stop()


# ----------------------------------------------------------------- drainer

def test_drain_migrates_allocs_off_node():
    s = make_server()
    try:
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 3
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        victim_alloc = s.store.allocs_by_job("default", job.id)[0]
        s.drainer.drain_node(victim_alloc.node_id, deadline_s=30.0)
        deadline = time.time() + 20
        while time.time() < deadline:
            run = [a for a in s.store.allocs_by_job("default", job.id)
                   if a.desired_status == AllocDesiredStatus.RUN
                   and not a.terminal_status()]
            if len(run) == 3 and all(a.node_id != victim_alloc.node_id
                                     for a in run):
                break
            time.sleep(0.05)
        run = [a for a in s.store.allocs_by_job("default", job.id)
               if a.desired_status == AllocDesiredStatus.RUN
               and not a.terminal_status()]
        assert len(run) == 3
        assert all(a.node_id != victim_alloc.node_id for a in run)
        # drain completes: strategy cleared, node ineligible
        deadline = time.time() + 10
        while time.time() < deadline:
            n = s.store._nodes[victim_alloc.node_id]
            if n.drain_strategy is None:
                break
            time.sleep(0.05)
        n = s.store._nodes[victim_alloc.node_id]
        assert n.drain_strategy is None
        assert n.scheduling_eligibility == "ineligible"
    finally:
        s.stop()


# ---------------------------------------------------------------- periodic

def test_periodic_dispatch_creates_child_jobs():
    from nomad_tpu.core.periodic import next_cron_after
    # cron parsing
    nxt = next_cron_after("*/5 * * * *", 0.0)
    assert nxt == 300.0
    assert next_cron_after("@every 30s", 100.0) == 130.0

    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        job.periodic = PeriodicConfig(spec="@every 0.2s")
        s.register_job(job)
        deadline = time.time() + 10
        children = []
        while time.time() < deadline:
            children = [j for j in s.store.jobs() if j.parent_id == job.id]
            if children:
                break
            time.sleep(0.05)
        assert children, "no periodic child launched"
        assert children[0].id.startswith(f"{job.id}/periodic-")
        assert children[0].periodic is None
        s.wait_for_idle(30.0)
        assert len(s.store.allocs_by_job("default", children[0].id)) == 1
    finally:
        s.stop()


# ------------------------------------------------------------------ events

def test_event_stream_delivers_filtered_events():
    s = make_server()
    try:
        sub = s.event_broker.subscribe({"Job": ["*"]})
        s.register_node(mock.node())
        job = mock.job()
        s.register_job(job)
        ev = sub.next(timeout=5.0)
        assert ev is not None and ev.topic == "Job"
        assert ev.type == "JobRegistered" and ev.key == job.id
        sub.close()
    finally:
        s.stop()


# --------------------------------------------------------------------- GC

def test_core_gc_collects_dead_jobs_and_evals():
    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        s.deregister_job("default", job.id)
        assert s.wait_for_idle(30.0)
        # allocs stopped but client still reports: mark complete
        for a in s.store.allocs_by_job("default", job.id):
            u = a.copy()
            u.client_status = AllocClientStatus.COMPLETE
            s.store.update_allocs_from_client(s.next_index(), [u])
        stats = s.core_scheduler.process("force-gc", force=True)
        assert stats["jobs"] == 1
        assert s.store.job_by_id("default", job.id) is None
        assert s.store.allocs_by_job("default", job.id) == []
    finally:
        s.stop()


def test_node_gc_removes_down_nodes():
    s = make_server()
    try:
        n = mock.node()
        s.register_node(n)
        s.update_node_status(n.id, "down")
        stats = s.core_scheduler.process("node-gc", force=True)
        assert stats["nodes"] == 1
        assert s.store.node_by_id(n.id) is None or \
            s.store.snapshot().node_by_id(n.id) is None
    finally:
        s.stop()


# ------------------------------- drainer under churn (deadline + down node)

def test_drain_deadline_force_stops_and_replaces_atomically():
    """Deadline expiry force-stops the remaining allocs and their
    replacement evals ride the same raft entry: afterwards the job is
    back at count elsewhere, each stopped alloc replaced exactly once,
    and the drain completes."""
    s = make_server()
    try:
        nodes = [mock.node() for _ in range(3)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 3
        job.update = None
        job.task_groups[0].update = None
        # no migrate slots: nothing moves before the deadline fires
        job.task_groups[0].migrate.max_parallel = 0
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        victim = s.store.allocs_by_job("default", job.id)[0].node_id
        on_victim = [a.id for a in s.store.allocs_by_node(victim)
                     if not a.terminal_status()]
        assert on_victim
        s.drainer.drain_node(victim, deadline_s=0.3)
        deadline = time.time() + 20
        while time.time() < deadline:
            live = [a for a in s.store.allocs_by_job("default", job.id)
                    if not a.terminal_status()]
            if (len(live) == 3
                    and all(a.node_id != victim for a in live)
                    and s.store.node_by_id(victim).drain_strategy is None):
                break
            time.sleep(0.05)
        live = [a for a in s.store.allocs_by_job("default", job.id)
                if not a.terminal_status()]
        assert len(live) == 3
        assert all(a.node_id != victim for a in live)
        names = [a.name for a in live]
        assert len(set(names)) == len(names)
        # the force-stopped allocs carry the deadline description
        stopped = [a for a in s.store.allocs_by_job("default", job.id)
                   if a.id in on_victim]
        assert all("drain deadline" in (a.desired_description or "")
                   for a in stopped)
        assert s.store.node_by_id(victim).drain_strategy is None
    finally:
        s.stop()


def test_node_down_mid_drain_hands_allocs_to_lost_path():
    """A node hard-killed mid-drain: the reconciler's lost path (not the
    drainer) replaces its allocs — exactly once — and the drain then
    completes on the emptied node."""
    s = make_server(heartbeat_ttl=60.0)
    try:
        nodes = [mock.node() for _ in range(2)]
        for n in nodes:
            s.register_node(n)
        job = mock.job()
        job.task_groups[0].count = 2
        job.update = None
        job.task_groups[0].update = None
        # no migrate slots + far deadline: the drain is stuck, so the DOWN
        # transition is the only way the allocs can leave the node
        job.task_groups[0].migrate.max_parallel = 0
        s.register_job(job)
        assert s.wait_for_idle(30.0)
        victim = s.store.allocs_by_job("default", job.id)[0].node_id
        s.drainer.drain_node(victim, deadline_s=600.0)
        time.sleep(0.2)
        from nomad_tpu.structs.node import NodeStatus
        s.update_node_status(victim, NodeStatus.DOWN)
        deadline = time.time() + 20
        while time.time() < deadline:
            live = [a for a in s.store.allocs_by_job("default", job.id)
                    if not a.terminal_status()]
            if len(live) == 2 and all(a.node_id != victim for a in live):
                break
            time.sleep(0.05)
        live = [a for a in s.store.allocs_by_job("default", job.id)
                if not a.terminal_status()]
        assert len(live) == 2
        assert all(a.node_id != victim for a in live)
        names = [a.name for a in live]
        assert len(set(names)) == len(names)
        # lost allocs went through the node-update path, not the drainer
        lost = [a for a in s.store.allocs_by_job("default", job.id)
                if a.client_status == AllocClientStatus.LOST]
        assert lost
        # the dead node emptied out, so the drain completed
        assert s.wait_for_idle(10.0)
        deadline = time.time() + 10
        while (time.time() < deadline
               and s.store.node_by_id(victim).drain_strategy is not None):
            time.sleep(0.05)
        assert s.store.node_by_id(victim).drain_strategy is None
    finally:
        s.stop()


# ---------------------- deployment revert retry / redelivery idempotence

def _healthy_report(s, job_id, healthy=True, deployment_id=None):
    for a in s.store.allocs_by_job("default", job_id):
        if a.terminal_status():
            continue
        if deployment_id is not None and a.deployment_id != deployment_id:
            continue
        u = a.copy()
        u.client_status = AllocClientStatus.RUNNING
        u.deployment_status = {"healthy": healthy}
        s.store.update_allocs_from_client(s.next_index(), [u])


def test_failed_autorevert_deployment_retries_lost_revert():
    """A deployment committed as FAILED whose auto-revert register was
    lost (leadership churn between the two writes) must still revert:
    the watcher retries while the job sits at the deployment's version,
    and the version guard makes the retry fire exactly once."""
    from nomad_tpu.raft import MessageType
    s = make_server()
    try:
        for _ in range(2):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        job.update = UpdateStrategy(max_parallel=2, auto_revert=True)
        job.task_groups[0].update = None
        s.register_job(job)
        s.wait_for_idle(30.0)
        _healthy_report(s, job.id)
        s.store.job_by_id("default", job.id).stable = True

        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/bad"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        assert d is not None and d.job_version == job2.version

        # simulate the strand: FAILED lands, the revert register did not
        failed = d.copy()
        failed.status = DeploymentStatus.FAILED
        failed.status_description = DeploymentStatus.DESC_FAILED_ALLOCATIONS
        failed.modify_time = time.time()
        s.apply(MessageType.DEPLOYMENT_UPSERT, {"deployment": failed})

        deadline = time.time() + 20
        while time.time() < deadline:
            j = s.store.job_by_id("default", job.id)
            if j.version > job2.version:
                break
            time.sleep(0.05)
        j = s.store.job_by_id("default", job.id)
        assert j.version > job2.version
        assert j.task_groups[0].tasks[0].config == {"command": "/bin/date"}

        # the revert's own deployment completes once its allocs are healthy
        s.wait_for_idle(30.0)
        _healthy_report(s, job.id)
        s.wait_for_idle(30.0)
        settled_version = s.store.job_by_id("default", job.id).version
        # watcher keeps passing over the FAILED deployment: the version
        # guard must make every later pass a no-op (no double revert)
        for _ in range(3):
            s.deployment_watcher.reconcile_all()
            time.sleep(0.1)
        assert s.store.job_by_id("default", job.id).version == settled_version
    finally:
        s.stop()


def test_retry_revert_is_noop_for_superseded_deployment():
    """_retry_revert must not touch a FAILED deployment the job has
    already moved past — reverting it would resurrect a dead version."""
    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        job.update = UpdateStrategy(max_parallel=1, auto_revert=True)
        job.task_groups[0].update = None
        s.register_job(job)
        s.wait_for_idle(30.0)
        _healthy_report(s, job.id)
        s.store.job_by_id("default", job.id).stable = True
        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/new"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        fake = d.copy()
        fake.status = DeploymentStatus.FAILED
        fake.job_version = job.version           # superseded by job2
        version_before = s.store.job_by_id("default", job.id).version
        s.deployment_watcher._retry_revert(fake)
        assert s.store.job_by_id("default", job.id).version == version_before
    finally:
        s.stop()


def test_redelivered_deployment_evals_do_not_flap_healthy_deployment():
    """broker.lease_expire storms redeliver deployment-watcher evals;
    processing the same watch eval again (and watcher re-passes) must
    leave a SUCCESSFUL deployment and its job untouched."""
    from nomad_tpu.structs import Evaluation, EvalStatus
    from nomad_tpu.structs.evaluation import EvalTrigger
    s = make_server()
    try:
        for _ in range(2):
            s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        job.update = UpdateStrategy(max_parallel=2, auto_revert=True)
        job.task_groups[0].update = None
        s.register_job(job)
        s.wait_for_idle(30.0)
        _healthy_report(s, job.id)
        s.store.job_by_id("default", job.id).stable = True
        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = {"command": "/bin/v2"}
        s.register_job(job2)
        s.wait_for_idle(30.0)
        d = s.store.latest_deployment_by_job_id("default", job.id)
        _healthy_report(s, job.id, deployment_id=d.id)
        deadline = time.time() + 20
        while time.time() < deadline:
            if (s.store.deployment_by_id(d.id).status
                    == DeploymentStatus.SUCCESSFUL):
                break
            time.sleep(0.05)
        assert (s.store.deployment_by_id(d.id).status
                == DeploymentStatus.SUCCESSFUL)
        version = s.store.job_by_id("default", job.id).version
        # storm of redelivered watch evals + watcher re-passes
        for _ in range(4):
            s.create_evals([Evaluation(
                namespace="default", priority=50, type=job.type,
                job_id=job.id, deployment_id=d.id,
                triggered_by=EvalTrigger.DEPLOYMENT_WATCHER,
                status=EvalStatus.PENDING)])
            s.deployment_watcher.reconcile_all()
        assert s.wait_for_idle(30.0)
        assert (s.store.deployment_by_id(d.id).status
                == DeploymentStatus.SUCCESSFUL)
        assert s.store.job_by_id("default", job.id).version == version
        live = [a for a in s.store.allocs_by_job("default", job.id)
                if not a.terminal_status()]
        assert len(live) == 2
    finally:
        s.stop()


# ------------------- duplicate deployments / stranded blocked evals (storm)

def test_plan_apply_dedups_deployment_per_job_version():
    """Two evals for the same registration can race: each plans a fresh
    deployment against a snapshot that predates the other's commit.  The
    second plan's deployment must fold into the first — its placements
    remapped — instead of stranding a RUNNING deployment nothing will
    ever report health for."""
    from nomad_tpu.state.store import AppliedPlanResults
    from nomad_tpu.structs import Deployment

    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        # no update stanza: registration must not create a deployment of
        # its own, so the two racing plans below are the only writers
        job.update = None
        job.task_groups[0].update = None
        s.register_job(job)
        s.wait_for_idle(30.0)
        jv = s.store.job_by_id("default", job.id)

        def mk_deployment():
            return Deployment(
                namespace="default", job_id=job.id, job_version=jv.version,
                job_modify_index=jv.job_modify_index,
                job_create_index=jv.create_index,
                status=DeploymentStatus.RUNNING)

        d1, d2 = mk_deployment(), mk_deployment()
        a1 = mock.alloc_for(jv, s.store.nodes()[0].id, index=7)
        a1.deployment_id = d1.id
        a2 = mock.alloc_for(jv, s.store.nodes()[0].id, index=8)
        a2.deployment_id = d2.id
        s.store.upsert_plan_results(s.next_index(), AppliedPlanResults(
            allocs_to_place=[a1], deployment=d1, plan_id="dup-d1"))
        s.store.upsert_plan_results(s.next_index(), AppliedPlanResults(
            allocs_to_place=[a2], deployment=d2, plan_id="dup-d2"))

        assert s.store.deployment_by_id(d1.id) is not None
        assert s.store.deployment_by_id(d2.id) is None
        by_job = [d for d in s.store.deployments()
                  if d.job_id == job.id and d.job_version == jv.version]
        assert len(by_job) == 1
        # the loser's placement joined the winner
        got = next(a for a in s.store.allocs_by_job("default", job.id)
                   if a.id == a2.id)
        assert got.deployment_id == d1.id
    finally:
        s.stop()


def test_failed_deployment_can_be_superseded_by_new_one():
    """The per-version dedup must not eat a legitimate retry after the
    prior deployment failed."""
    from nomad_tpu.state.store import AppliedPlanResults
    from nomad_tpu.structs import Deployment

    s = make_server()
    try:
        d1 = Deployment(namespace="default", job_id="j", job_version=3,
                        job_create_index=5, status=DeploymentStatus.FAILED)
        d2 = Deployment(namespace="default", job_id="j", job_version=3,
                        job_create_index=5, status=DeploymentStatus.RUNNING)
        s.store.upsert_plan_results(s.next_index(), AppliedPlanResults(
            deployment=d1, plan_id="sup-d1"))
        s.store.upsert_plan_results(s.next_index(), AppliedPlanResults(
            deployment=d2, plan_id="sup-d2"))
        assert s.store.deployment_by_id(d1.id) is not None
        assert s.store.deployment_by_id(d2.id) is not None
    finally:
        s.stop()


def test_restored_blocked_eval_gets_one_reevaluation():
    """Leader failover loses the missed-unblock indexes: a blocked eval
    restored from the store would otherwise wait forever on a capacity
    change that already happened.  _restore_evals must hand every
    restored blocked eval one clean re-evaluation."""
    from nomad_tpu.structs import Evaluation
    from nomad_tpu.structs.evaluation import EvalTrigger

    s = make_server()
    try:
        s.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 1
        s.register_job(job)
        s.wait_for_idle(30.0)
        # a blocked eval left over from a deposed leader: its snapshot
        # predates the node recovery that made the job placeable, and
        # this "new leader" has no unblock index covering it
        stale = Evaluation(
            namespace="default", priority=50, type=job.type, job_id=job.id,
            triggered_by=EvalTrigger.NODE_UPDATE, status=EvalStatus.BLOCKED,
            status_description="queued-allocs", snapshot_index=10 ** 9)
        s.create_evals([stale])
        stuck = s.store.eval_by_id(stale.id)
        stuck.status = EvalStatus.BLOCKED
        s._restore_evals()
        deadline = time.time() + 15
        while time.time() < deadline:
            ev = s.store.eval_by_id(stale.id)
            if EvalStatus.terminal(ev.status):
                break
            time.sleep(0.05)
        assert EvalStatus.terminal(s.store.eval_by_id(stale.id).status), \
            s.store.eval_by_id(stale.id).status
    finally:
        s.stop()
