"""Scenario-matrix runner (nomad_tpu.scenarios): schedule grammar,
cell wiring, chaos-carrying drivers, and one real cell end-to-end.

The full 23-cell matrix is CI's job (`bench.py --matrix`); here we keep
the cheap structural checks plus a single soak cell so a broken runner
fails tier-1 before it fails a 3-seed CI leg.
"""
from __future__ import annotations

import json
import os

import pytest

from nomad_tpu import chaos
from nomad_tpu.chaos import FAULT_POINTS, ChaosRegistry
from nomad_tpu.scenarios import (
    ALL_CELLS,
    FLEET_CELLS,
    SCHEDULES,
    SHAPES,
    SMOKE_CELLS,
    AutoscaleDriver,
    CellCtx,
    run_cell,
)


# ------------------------------------------------------- matrix structure


def test_matrix_covers_every_shape_schedule_pair():
    # the core product: every single-cluster shape crossed with every
    # single-cluster schedule; the federated, multi-tenant, overload,
    # divergence, and fleet shapes ride exactly their first-class cells
    # (region_partition is multi_region-only; multi_tenant and
    # overload_storm gate storm + lease_flap; divergence_drill gates
    # storm + server_replace; the 10K-agent fleet cells live in
    # FLEET_CELLS, not ALL_CELLS)
    core_shapes = [sh for sh in SHAPES
                   if sh not in ("multi_region", "multi_tenant",
                                 "fleet_soak", "overload_storm",
                                 "divergence_drill")]
    core_scheds = [sc for sc in SCHEDULES if sc != "region_partition"]
    expected = {(sh, sc) for sh in core_shapes for sc in core_scheds}
    expected |= {("multi_region", "storm"),
                 ("multi_region", "region_partition")}
    expected |= {("multi_tenant", "storm"),
                 ("multi_tenant", "lease_flap")}
    expected |= {("overload_storm", "storm"),
                 ("overload_storm", "lease_flap")}
    expected |= {("divergence_drill", "storm"),
                 ("divergence_drill", "server_replace")}
    assert set(ALL_CELLS) == expected
    assert len(ALL_CELLS) == len(expected) == 29
    # no duplicate cells
    assert len(ALL_CELLS) == len(set(ALL_CELLS))
    assert set(FLEET_CELLS) == {("fleet_soak", "storm"),
                                ("fleet_soak", "server_replace")}
    assert not set(FLEET_CELLS) & set(ALL_CELLS)


def test_matrix_batch_jobs_reschedule_unlimited():
    """Exact-count batch jobs must survive a storm killing an alloc more
    times than the default batch policy's single attempt — exhaustion
    would leave `live 0` as a stable, invariant-violating state."""
    from nomad_tpu.scenarios import _batch_job
    pol = _batch_job(4).task_groups[0].reschedule_policy
    assert pol.unlimited
    assert pol.delay_s < 1.0


def test_smoke_cells_are_a_curated_subset():
    assert set(SMOKE_CELLS) <= set(ALL_CELLS)
    # the smoke subset must exercise both schedules and the two
    # first-class lifecycle shapes the issue calls out
    assert {sc for _, sc in SMOKE_CELLS} == set(SCHEDULES)
    assert {"rolling_deploy", "autoscale_ramp"} <= {sh for sh, _ in SMOKE_CELLS}


@pytest.mark.parametrize("seed", [1, 2, 7, 1337])
@pytest.mark.parametrize("name", sorted(SCHEDULES))
def test_schedule_specs_parse_for_any_seed(name, seed):
    sched = SCHEDULES[name]
    reg = ChaosRegistry.from_spec(sched.spec.format(seed=seed))
    # every phased rate references a registered fault point
    assert set(reg.phased) <= set(FAULT_POINTS)
    # windows sit inside the schedule's chaos duration
    for start, end in reg.phases.values():
        assert 0.0 <= start < end <= sched.duration_s


def test_schedule_phases_actually_open():
    """Every declared phase window must carry at least one live rate —
    a window that never fires soaks nothing."""
    import time as _time
    for name, sched in SCHEDULES.items():
        reg = ChaosRegistry.from_spec(sched.spec.format(seed=1))
        for phase, (start, end) in reg.phases.items():
            carried = [p for p, per_phase in reg.phased.items()
                       if per_phase.get(phase, 0.0) > 0.0]
            assert carried, f"{name}: phase {phase} carries no rates"
            # effective_rate goes live mid-window once armed
            reg.arm(now=_time.monotonic() - (start + end) / 2)
            assert any(reg.effective_rate(p) > 0.0 for p in carried), \
                f"{name}: phase {phase} never opens"


# ------------------------------------------------ chaos-carrying drivers


class _StubLeader:
    def __init__(self):
        self.calls = []

    def scale_job(self, namespace, job_id, group, count, message=""):
        self.calls.append(count)


class _StubCluster:
    def __init__(self, leader):
        self._leader = leader

    def leader(self, timeout=5.0):
        return self._leader


def test_autoscale_driver_burst_amplifies_to_policy_max():
    ld = _StubLeader()
    drv = AutoscaleDriver(_StubCluster(ld), CellCtx(), "svc", "web",
                          waves=[3, 5, 2], policy_max=10, interval=0.0)
    reg = ChaosRegistry.from_spec("seed=1;scale.burst=1.0")
    reg.arm(now=0.0)
    chaos.install(reg)
    try:
        for t in (0.0, 0.1, 0.2):
            drv.tick(now=t)
    finally:
        chaos.uninstall()
    # every wave fired and every wave was amplified to the policy max
    assert ld.calls == [10, 10, 10]
    assert drv.bursts == 3
    assert drv.applied == [10, 10, 10]


def test_autoscale_driver_quiet_without_chaos():
    ld = _StubLeader()
    drv = AutoscaleDriver(_StubCluster(ld), CellCtx(), "svc", "web",
                          waves=[3, 5], policy_max=10, interval=0.0)
    for t in (0.0, 0.1, 0.2):
        drv.tick(now=t)
    assert ld.calls == [3, 5]
    assert drv.bursts == 0


def test_autoscale_driver_retries_lost_wave():
    class _DownLeader(_StubLeader):
        def __init__(self):
            super().__init__()
            self.fail = 2

        def scale_job(self, *a, **kw):
            if self.fail:
                self.fail -= 1
                raise TimeoutError("chaos ate it")
            super().scale_job(*a, **kw)

    ld = _DownLeader()
    drv = AutoscaleDriver(_StubCluster(ld), CellCtx(), "svc", "web",
                          waves=[4], policy_max=10, interval=0.0)
    # _on_leader itself retries within its window; the driver re-queues
    # the wave if the whole attempt times out
    drv.tick(now=0.0)
    assert ld.calls == [4]
    assert drv.applied == [4]


# ------------------------------------------------------ one real soak cell


def test_single_cell_end_to_end(tmp_path):
    """Run the cheapest verified cell for real: chaos fires, the cluster
    converges, and the trajectory JSON lands with a convergence block."""
    result = run_cell("e2e_spine", "storm", seed=1, out_dir=str(tmp_path))
    assert result["convergence"]["converged"], result["convergence"]
    assert result["chaos_fired"], "storm schedule fired nothing"
    assert result["allocs_placed"] > 0
    path = os.path.join(str(tmp_path), "BENCH_matrix_e2e_spine_storm.json")
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["metric"] == "matrix_e2e_spine_storm"
    assert on_disk["convergence"]["converged"]
