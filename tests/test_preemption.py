"""Preemption tests (reference analog: scheduler/preemption_test.go)."""
import numpy as np

from nomad_tpu import mock
from nomad_tpu.ops.preempt import preempt_for_task_group, preemption_score
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import AllocDesiredStatus
from nomad_tpu.structs.config import PreemptionConfig, SchedulerConfiguration


def test_kernel_picks_lowest_priority_first():
    # one node, 3 candidates: prio 20 (big), prio 10 (small), prio 40
    cand_res = np.array([[[2000, 2000, 0], [1000, 1000, 0], [3000, 3000, 0]]],
                        np.float32)
    cand_prio = np.array([[20, 10, 40]], np.int32)
    cand_valid = np.ones((1, 3), bool)
    remaining = np.array([[0, 0, 0]], np.float32)
    ask = np.array([800, 800, 0], np.float32)
    met, picked, avail = preempt_for_task_group(
        cand_res, cand_prio, cand_valid, remaining, ask, max_steps=4)
    assert bool(met[0])
    assert picked[0].tolist() == [False, True, False]   # prio 10 suffices


def test_kernel_spans_priority_tiers_when_needed():
    cand_res = np.array([[[500, 500, 0], [600, 600, 0]]], np.float32)
    cand_prio = np.array([[10, 20]], np.int32)
    cand_valid = np.ones((1, 2), bool)
    remaining = np.array([[0, 0, 0]], np.float32)
    ask = np.array([1000, 1000, 0], np.float32)
    met, picked, _ = preempt_for_task_group(
        cand_res, cand_prio, cand_valid, remaining, ask, max_steps=4)
    assert bool(met[0]) and picked[0].all()


def test_kernel_unmet_when_insufficient():
    cand_res = np.array([[[100, 100, 0]]], np.float32)
    cand_prio = np.array([[10]], np.int32)
    cand_valid = np.ones((1, 1), bool)
    remaining = np.array([[0, 0, 0]], np.float32)
    ask = np.array([1000, 1000, 0], np.float32)
    met, _, _ = preempt_for_task_group(
        cand_res, cand_prio, cand_valid, remaining, ask, max_steps=2)
    assert not bool(met[0])


def test_preemption_score_logistic():
    assert preemption_score(2048.0) == 0.5
    assert preemption_score(0.0) > 0.99
    assert preemption_score(10000.0) < 0.01


def _enable_service_preemption(h):
    cfg = SchedulerConfiguration(
        preemption_config=PreemptionConfig(service_scheduler_enabled=True,
                                           system_scheduler_enabled=True))
    h.store.set_scheduler_config(h.next_index(), cfg)


def test_service_scheduler_preempts_lower_priority():
    h = Harness()
    _enable_service_preemption(h)
    node = mock.node()
    h.store.upsert_node(h.next_index(), node)

    low = mock.job(priority=20)
    low.task_groups[0].tasks[0].resources.cpu = 3500
    low.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), low)
    h.process("service", mock.eval(job_id=low.id, priority=20))
    assert len(h.store.allocs_by_job("default", low.id)) == 1

    high = mock.job(priority=70)
    high.task_groups[0].tasks[0].resources.cpu = 3500
    high.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), high)
    h.process("service", mock.eval(job_id=high.id, priority=70))

    high_allocs = [a for a in h.store.allocs_by_job("default", high.id)
                   if a.desired_status == AllocDesiredStatus.RUN]
    assert len(high_allocs) == 1
    low_allocs = h.store.allocs_by_job("default", low.id)
    assert low_allocs[0].desired_status == AllocDesiredStatus.EVICT
    assert low_allocs[0].preempted_by_allocation == high_allocs[0].id
    assert high_allocs[0].preempted_allocations == [low_allocs[0].id]


def test_no_preemption_within_priority_delta():
    h = Harness()
    _enable_service_preemption(h)
    node = mock.node()
    h.store.upsert_node(h.next_index(), node)
    low = mock.job(priority=50)
    low.task_groups[0].tasks[0].resources.cpu = 3500
    h.store.upsert_job(h.next_index(), low)
    h.process("service", mock.eval(job_id=low.id))

    close = mock.job(priority=55)      # delta < 10: not preemptible
    close.task_groups[0].tasks[0].resources.cpu = 3500
    close.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), close)
    h.process("service", mock.eval(job_id=close.id, priority=55))
    assert len([a for a in h.store.allocs_by_job("default", close.id)
                if a.desired_status == AllocDesiredStatus.RUN]) == 0
    assert h.store.allocs_by_job("default", low.id)[0].desired_status == \
        AllocDesiredStatus.RUN


def test_system_job_preempts_by_default():
    h = Harness()   # default config: system preemption enabled
    node = mock.node()
    h.store.upsert_node(h.next_index(), node)
    svc = mock.job(priority=50)
    svc.task_groups[0].tasks[0].resources.cpu = 3500
    h.store.upsert_job(h.next_index(), svc)
    h.process("service", mock.eval(job_id=svc.id))

    sysj = mock.system_job()           # priority 100
    sysj.task_groups[0].tasks[0].resources.cpu = 1000
    h.store.upsert_job(h.next_index(), sysj)
    h.process("system", mock.eval(job_id=sysj.id, type="system", priority=100))
    placed = [a for a in h.store.allocs_by_job("default", sysj.id)
              if a.desired_status == AllocDesiredStatus.RUN]
    assert len(placed) == 1
    assert h.store.allocs_by_job("default", svc.id)[0].desired_status == \
        AllocDesiredStatus.EVICT


def test_superset_filter_minimizes_evictions():
    """Placing a small ask on a node with several low-prio allocs should
    evict as few as possible."""
    h = Harness()
    _enable_service_preemption(h)
    node = mock.node()
    h.store.upsert_node(h.next_index(), node)
    low = mock.job(priority=20)
    low.task_groups[0].tasks[0].resources.cpu = 1300
    low.task_groups[0].tasks[0].resources.memory_mb = 2000
    low.task_groups[0].count = 3
    h.store.upsert_job(h.next_index(), low)
    h.process("service", mock.eval(job_id=low.id, priority=20))
    assert len(h.store.allocs_by_job("default", low.id)) == 3

    high = mock.job(priority=70)
    high.task_groups[0].tasks[0].resources.cpu = 1000
    high.task_groups[0].tasks[0].resources.memory_mb = 1500
    high.task_groups[0].count = 1
    h.store.upsert_job(h.next_index(), high)
    h.process("service", mock.eval(job_id=high.id, priority=70))
    evicted = [a for a in h.store.allocs_by_job("default", low.id)
               if a.desired_status == AllocDesiredStatus.EVICT]
    assert len(evicted) == 1           # one eviction covers the ask
