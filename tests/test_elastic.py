"""Elastic control plane tests: replicated raft membership
(AddVoter/RemoveServer configuration entries, effective on append per
Raft §4.1), leadership transfer (§3.10 TimeoutNow), the autopilot
join/catch-up/promote lifecycle, SWIM flap/rejoin races, and the seeded
leader-destroy/replace soak (reference analogs: hashicorp/raft
membership tests, nomad/autopilot_test.go, serf's refutation and
tombstone semantics)."""
import concurrent.futures as cf
import pickle
import random
import threading
import time

import pytest

from nomad_tpu import chaos, mock
from nomad_tpu.core.cluster import Cluster
from nomad_tpu.core.membership import (
    ALIVE,
    FAILED,
    LEFT,
    SUSPECT,
    Membership,
)
from nomad_tpu.core.server import ServerConfig
from nomad_tpu.core.worker import TRANSIENT_ERRORS
from nomad_tpu.raft import (
    CONFIGURATION_MSG,
    InMemTransport,
    MessageType,
    NomadFSM,
    NotLeaderError,
    RaftConfig,
    RaftNode,
)
from nomad_tpu.state import StateStore

FAST = RaftConfig(heartbeat_interval=0.02, election_timeout=0.1)
# the soak uses a wider election timeout so the "transfer beats one
# election timeout" assertion has headroom over CI GIL pauses
SOAK = RaftConfig(heartbeat_interval=0.02, election_timeout=0.3)


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _mk_node(name, peers, transport, cfg=FAST, **kw):
    return RaftNode(name, peers, transport, NomadFSM(StateStore()),
                    config=cfg, **kw)


def _elect(nodes, timeout=3.0, exclude=None):
    """Wait for exactly one leader among `nodes` (optionally one that
    isn't `exclude`)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [n for n in nodes if n.is_leader and n is not exclude]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise TimeoutError("no single leader elected")


def _canon(blob):
    """Canonicalize an FSM snapshot for equality (pickle memoizes shared
    references, so byte-different blobs can encode identical state)."""
    data = pickle.loads(blob)
    out = {}
    for key, val in sorted(data.items()):
        if isinstance(val, list):
            out[key] = sorted(pickle.dumps(v) for v in val)
        elif isinstance(val, dict):
            out[key] = {k: pickle.dumps(v) for k, v in sorted(val.items())}
        else:
            out[key] = pickle.dumps(val)
    return out


def _on_leader(cluster, fn, timeout=15.0):
    deadline = time.time() + timeout
    while True:
        try:
            return fn(cluster.leader(timeout=5.0))
        except TRANSIENT_ERRORS + (TimeoutError,):
            if time.time() >= deadline:
                raise
            time.sleep(0.05)


# ------------------------------------------------- SWIM flap/rejoin races


def test_restart_with_stale_incarnation_reasserts_aliveness():
    """A member that restarts as a fresh process (incarnation 0) while
    the cluster still carries a lingering SUSPECT/FAILED/LEFT claim about
    its previous life must refute past it: merging the stale claim bumps
    its own incarnation above the claim's, so its next ALIVE outranks it.
    Without the LEFT arm a cleanly-departed member could NEVER rejoin."""
    tr = InMemTransport()
    for lingering in (SUSPECT, FAILED, LEFT):
        m = Membership(tr, "a", ("127.0.0.1", 0))
        try:
            m._merge([{"name": "a", "addr": ("127.0.0.1", 0),
                       "incarnation": 4, "status": lingering}])
            with m._lock:
                me = m.members["a"]
                assert me.status == ALIVE, lingering
                assert me.incarnation == 5, lingering
        finally:
            m.stop()


def test_leaving_member_does_not_refute_its_own_left():
    """The refutation must not fire while the member is deliberately
    leaving: hearing our own LEFT echoed back mid-goodbye would bump our
    incarnation and resurrect us as ALIVE."""
    tr = InMemTransport()
    m = Membership(tr, "a", ("127.0.0.1", 0))
    try:
        with m._lock:
            me = m.members["a"]
            me.status = LEFT
            me.incarnation = 3
        m._merge([{"name": "a", "addr": ("127.0.0.1", 0),
                   "incarnation": 3, "status": LEFT}])
        with m._lock:
            assert m.members["a"].status == LEFT
            assert m.members["a"].incarnation == 3
    finally:
        m.stop()


def test_left_member_not_resurrected_by_stale_sync():
    """LEFT entries reap into incarnation tombstones: an old push-pull
    sync replaying the pre-leave ALIVE entry (same incarnation) must not
    re-insert the member.  Only a genuine rejoin — a strictly higher
    incarnation — clears the tombstone."""
    tr = InMemTransport()
    m = Membership(tr, "a", ("127.0.0.1", 0), reap_after=0.0)
    try:
        m._merge([{"name": "b", "addr": ("127.0.0.1", 1),
                   "incarnation": 3, "status": LEFT}])
        with m._lock:
            m.members["b"].heard_at -= 1.0
        m._sweep()
        with m._lock:
            assert "b" not in m.members
            assert m._tombstones["b"] == 3
        # the stale resurrection: a peer that never saw the leave syncs
        # its old table over
        m._merge([{"name": "b", "addr": ("127.0.0.1", 1),
                   "incarnation": 3, "status": ALIVE}])
        with m._lock:
            assert "b" not in m.members
        # the genuine rejoin (fresh process that already refuted past
        # the old incarnation) clears the tombstone
        m._merge([{"name": "b", "addr": ("127.0.0.1", 2),
                   "incarnation": 4, "status": ALIVE}])
        with m._lock:
            assert m.members["b"].status == ALIVE
            assert m.members["b"].addr == ("127.0.0.1", 2)
            assert "b" not in m._tombstones
    finally:
        m.stop()


# --------------------------------------------------- quorum transitions


def test_add_voter_raises_quorum_on_append_not_commit():
    """Raft §4.1: a configuration entry takes effect the moment it is
    APPENDED.  With AddVoter in flight making 4 voters, two servers
    (leader + one follower) were a majority of the old 3-voter config
    but must NOT commit under the new one — 2-of-4 committing here is
    exactly the split-brain window the effective-on-append rule closes."""
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = {nm: _mk_node(nm, names, tr) for nm in names}
    d = _mk_node("d", ["d"], tr, join=True)
    for n in list(nodes.values()) + [d]:
        n.start()
    try:
        leader = _elect(list(nodes.values()))
        followers = [nm for nm in names if nm != leader.name]
        # cut off one follower and the (not-yet-added) joiner: after the
        # append the leader can reach only itself + one follower
        tr.set_down(followers[1])
        tr.set_down("d")
        with pytest.raises((TimeoutError, cf.TimeoutError)):
            leader.add_server("d", voter=True, timeout=0.4)
        cfg = leader.configuration()
        assert "d" in cfg["voters"]           # effective on append
        idx = cfg["index"]
        assert leader.commit_index < idx      # 2 of 4 did not commit
        # a third voter coming back supplies the majority of the NEW set
        tr.set_down(followers[1], down=False)
        assert _wait(lambda: leader.commit_index >= idx, 5.0)
        tr.set_down("d", down=False)
        assert _wait(lambda: "d" in d.configuration()["voters"], 5.0)
    finally:
        for n in list(nodes.values()) + [d]:
            n.stop()


def test_remove_leader_transfers_then_demotes():
    """RemoveServer of the leader itself is transfer-then-demote: the
    leader hands leadership off and raises NotLeaderError so the caller
    retries against the successor, which commits the removal.  The
    deposed leader learns the config from replication and stops being a
    voter (it must never campaign again)."""
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = {nm: _mk_node(nm, names, tr) for nm in names}
    for n in nodes.values():
        n.start()
    try:
        leader = _elect(list(nodes.values()))
        with pytest.raises(NotLeaderError):
            leader.remove_server(leader.name, timeout=5.0)
        successor = _elect(list(nodes.values()), exclude=leader)
        successor.remove_server(leader.name, timeout=5.0)
        cfg = successor.configuration()
        assert leader.name not in cfg["voters"]
        assert leader.name not in cfg["nonvoters"]
        # the 2-voter remnant still commits
        successor.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        # the removed server goes stale (it left the replication set the
        # moment the entry appended) but must not disrupt: its log now
        # trails the remnant's, so pre-vote refuses it and the successor
        # holds leadership at a stable term
        term = successor.configuration()["term"]
        deadline = time.monotonic() + 0.6
        while time.monotonic() < deadline:
            assert successor.is_leader
            assert not leader.is_leader
            assert successor.configuration()["term"] == term
            time.sleep(0.02)
    finally:
        for n in nodes.values():
            n.stop()


def test_remove_last_voter_refused():
    tr = InMemTransport()
    n = _mk_node("a", ["a"], tr)
    n.start()
    try:
        assert _wait(lambda: n.is_leader, 3.0)
        with pytest.raises(ValueError, match="last voter"):
            n.remove_server("a")
    finally:
        n.stop()


# --------------------------------------------------- leadership transfer


def test_transfer_leadership_beats_election_timeout():
    """TimeoutNow skips pre-vote and leader stickiness: the handoff
    completes in replication round-trips, not an election timeout, and
    no committed entry is lost across it."""
    cfg = RaftConfig(heartbeat_interval=0.05, election_timeout=1.0)
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = {nm: _mk_node(nm, names, tr, cfg=cfg) for nm in names}
    for n in nodes.values():
        n.start()
    try:
        leader = _elect(list(nodes.values()), timeout=5.0)
        for _ in range(3):
            leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        t0 = time.monotonic()
        assert leader.transfer_leadership() is True
        elapsed = time.monotonic() - t0
        assert elapsed < cfg.election_timeout, \
            f"transfer took {elapsed:.3f}s"
        successor = _elect(list(nodes.values()), exclude=leader)
        assert successor.name == leader.leader_id or successor is not leader
        successor.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        assert len(successor.fsm.store.nodes()) == 4
    finally:
        for n in nodes.values():
            n.stop()


def test_transfer_fences_proposals():
    """While a transfer is in flight the leader refuses new proposals
    (the target must catch up to a FIXED last_index); after a failed
    transfer it resumes service."""
    tr = InMemTransport()
    names = ["a", "b", "c"]
    nodes = {nm: _mk_node(nm, names, tr) for nm in names}
    for n in nodes.values():
        n.start()
    try:
        leader = _elect(list(nodes.values()))
        target = next(nm for nm in names if nm != leader.name)
        leader._transfer_target = target
        with pytest.raises(NotLeaderError):
            leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
        leader._transfer_target = None
        leader.apply(MessageType.NODE_REGISTER, {"node": mock.node()})
    finally:
        for n in nodes.values():
            n.stop()


def test_graceful_stop_transfers_leadership():
    """A leaving leader hands leadership off before closing: the cluster
    keeps a leader (and every committed entry) across the departure."""
    cluster = Cluster(3, config=ServerConfig(num_schedulers=2,
                                             heartbeat_ttl=60.0),
                      raft_config=FAST)
    cluster.start()
    try:
        leader = cluster.leader()
        node = mock.node()
        leader.register_node(node)
        old_name = leader.name
        leader.stop()
        survivors = [s for s in cluster.servers if s.name != old_name]
        new_leader = None
        deadline = time.monotonic() + 5.0
        while new_leader is None and time.monotonic() < deadline:
            ls = [s for s in survivors
                  if s.raft is not None and s.raft.is_leader
                  and s._established]
            new_leader = ls[0] if len(ls) == 1 else None
            time.sleep(0.01)
        assert new_leader is not None
        assert new_leader.store.node_by_id(node.id) is not None
    finally:
        cluster.stop()


# ------------------------------------------- join / catch-up / promote


def test_blank_server_joins_catches_up_and_promotes(tmp_path):
    """A blank server boots in join mode (empty config, never
    campaigns), is added as a non-voter, catches up via
    InstallSnapshot + log replication, and autopilot promotes it to
    voter once it stabilizes — ending byte-identical to the leader."""
    cluster = Cluster(3, config=ServerConfig(num_schedulers=2,
                                             heartbeat_ttl=60.0),
                      raft_config=FAST, data_dir=str(tmp_path))
    cluster.start()
    try:
        leader = cluster.leader()
        for _ in range(3):
            leader.register_node(mock.node())
        job = mock.job()
        job.task_groups[0].count = 2
        _on_leader(cluster, lambda ld: ld.register_job(job))
        assert _wait(lambda: len(
            [a for a in cluster.leader().store.allocs_by_job(
                "default", job.id) if not a.terminal_status()]) == 2, 15.0)
        # compact so the joiner must restore through InstallSnapshot
        _on_leader(cluster, lambda ld: ld.raft.force_snapshot())

        joiner = cluster.add_server()
        assert joiner.raft is not None and not joiner.raft.is_leader
        cluster.wait_voter(joiner.name, timeout=10.0)
        cfg = cluster.leader().raft.configuration()
        assert joiner.name in cfg["voters"]

        ld = cluster.leader()
        ld.raft.barrier()
        assert cluster.wait_replication(ld.store.latest_index,
                                        timeout=10.0)
        assert _wait(lambda: joiner.raft.last_applied
                     >= ld.raft.last_applied, 10.0)
        assert _canon(joiner.raft.fsm.snapshot()) \
            == _canon(ld.raft.fsm.snapshot())
        # the promoted voter participates in commitment
        _on_leader(cluster, lambda ld: ld.register_node(mock.node()))
    finally:
        cluster.stop()


def test_config_survives_restart(tmp_path):
    """The replicated configuration is durable: a restarted member
    recovers the expanded voter set from its WAL/snapshot/meta, not the
    static seed list it booted with."""
    cluster = Cluster(3, config=ServerConfig(num_schedulers=2,
                                             heartbeat_ttl=60.0),
                      raft_config=FAST, data_dir=str(tmp_path))
    cluster.start()
    try:
        joiner = cluster.add_server()
        cluster.wait_voter(joiner.name, timeout=10.0)
        victim = next(s for s in cluster.servers
                      if s is not joiner and not s.raft.is_leader)
        cluster.hard_kill(victim)
        revived = cluster.restart(victim)
        assert _wait(lambda: joiner.name in
                     revived.raft.configuration()["voters"], 10.0)
    finally:
        cluster.stop()


# ----------------------------------------------------------------- soak


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_elastic_soak_leader_destroy_replace(seed, tmp_path):
    """The production server-loss drill, seeded: mid-workload the LEADER
    is permanently destroyed (hard_kill, data_dir abandoned — it never
    comes back), removed from the configuration, and a blank replacement
    joins, catches up, and is promoted.  Asserts across the NEW voter
    set: single leader per term for the whole run, exactly-once
    placement, every committed entry retained, byte-identical FSM state,
    and a graceful transfer landing under one election timeout."""
    cfg = ServerConfig(num_schedulers=2, heartbeat_ttl=60.0,
                       failed_eval_followup_delay=0.3)
    cluster = Cluster(3, config=cfg, raft_config=SOAK,
                      data_dir=str(tmp_path))

    def _tune(s):
        s.broker.nack_timeout = 1.0
        s.broker.initial_nack_delay = 0.05
        s.broker.subsequent_nack_delay = 0.1

    for s in cluster.servers:
        _tune(s)
    rng = random.Random(seed)

    leaders_by_term = {}
    stop_mon = threading.Event()

    def _monitor():
        while not stop_mon.is_set():
            for s in list(cluster.servers):
                r = s.raft
                if r is None:
                    continue
                with r._lock:
                    if r.state == "leader":
                        leaders_by_term.setdefault(
                            r.term, set()).add(s.name)
            time.sleep(0.005)

    mon = threading.Thread(target=_monitor, daemon=True)
    jobs = []

    def _add_job():
        j = mock.job()
        j.task_groups[0].count = 2
        jobs.append(j)
        _on_leader(cluster, lambda ld: ld.register_job(j))

    try:
        cluster.start()
        mon.start()
        for _ in range(4):
            nd = mock.node()
            _on_leader(cluster, lambda ld, nd=nd: ld.register_node(nd))
        _add_job()

        # a graceful handoff first: must land inside one election timeout
        ld = cluster.leader(timeout=10.0)
        t0 = time.monotonic()
        assert ld.raft.transfer_leadership() is True
        assert time.monotonic() - t0 < SOAK.election_timeout

        # the drill: a commit in flight around the leader's destruction;
        # survivors snapshot first on some seeds so the replacement
        # exercises the InstallSnapshot catch-up path
        _add_job()
        victim = cluster.leader(timeout=10.0)
        if rng.random() < 0.5:
            for s in cluster.servers:
                if s is not victim:
                    s.raft.force_snapshot()
        replacement = cluster.replace_server(victim, timeout=30.0)
        _tune(replacement)
        assert victim.name not in [s.name for s in cluster.servers]

        _add_job()                       # the new voter set keeps serving

        voters = sorted(_on_leader(
            cluster, lambda ld: ld.raft.configuration()["voters"]))
        assert victim.name not in voters
        assert replacement.name in voters
        assert len(voters) == 3

        def converged():
            try:
                ld = cluster.leader(timeout=2.0)
            except TimeoutError:
                return False
            for j in jobs:
                live = [a for a in ld.store.allocs_by_job("default", j.id)
                        if not a.terminal_status()]
                if len(live) != j.task_groups[0].count:
                    return False
            from nomad_tpu.structs import EvalStatus
            if any(not EvalStatus.terminal(e.status)
                   for e in ld.store.evals()):
                return False
            return not ld.broker._unack and not ld.plan_queue._heap

        assert _wait(converged, timeout=30.0), \
            f"seed {seed}: no convergence after replace"

        # exactly-once: requested counts exactly, no duplicate placement
        ld = cluster.leader()
        for j in jobs:
            live = [a for a in ld.store.allocs_by_job("default", j.id)
                    if not a.terminal_status()]
            assert len(live) == j.task_groups[0].count
            assert len({a.id for a in live}) == len(live)

        # byte-identical FSM across the post-replacement voter set
        ld.raft.barrier()
        assert cluster.wait_replication(ld.store.latest_index,
                                        timeout=10.0)
        assert _wait(lambda: all(
            s.raft.last_applied >= ld.raft.last_applied
            for s in cluster.servers), 10.0)
        blobs = {s.name: _canon(s.raft.fsm.snapshot())
                 for s in cluster.servers}
        ref = blobs[ld.name]
        for name, blob in blobs.items():
            assert blob == ref, f"seed {seed}: FSM divergence on {name}"

        # election safety held across destruction + replacement
        multi = {t: sorted(names) for t, names in leaders_by_term.items()
                 if len(names) > 1}
        assert not multi, f"seed {seed}: two leaders in one term: {multi}"

        # the config history is log-carried: every surviving member can
        # reconstruct the final voter set
        for s in cluster.servers:
            assert _wait(lambda s=s: sorted(
                s.raft.configuration()["voters"]) == voters, 10.0), \
                f"seed {seed}: {s.name} never learned the final config"
        assert any(e.msg_type == CONFIGURATION_MSG
                   for e in ld.raft.log.entries_of_type(CONFIGURATION_MSG))
    finally:
        stop_mon.set()
        mon.join(2.0)
        cluster.stop()
