"""Artifact getter (reference client/allocrunner/taskrunner/getter/
getter_test.go: file fetch, checksum pass/fail, dir mode, archive
extraction, sandbox escape rejection)."""
import hashlib
import os
import tarfile

import pytest

from nomad_tpu.client.getter import ArtifactError, fetch_artifact


@pytest.fixture()
def world(tmp_path):
    src = tmp_path / "src"
    task = tmp_path / "task"
    src.mkdir()
    task.mkdir()
    return src, task


def test_fetch_local_file(world):
    src, task = world
    f = src / "payload.bin"
    f.write_bytes(b"hello artifact")
    out = fetch_artifact({"source": str(f)}, str(task))
    assert out == str(task / "local" / "payload.bin")
    assert open(out, "rb").read() == b"hello artifact"


def test_fetch_file_url_and_env_interp(world):
    src, task = world
    f = src / "data.txt"
    f.write_text("x")
    art = {"source": "file://" + str(src) + "/${NOMAD_META_name}.txt",
           "destination": "local/deps/"}
    out = fetch_artifact(art, str(task), {"NOMAD_META_name": "data"})
    assert out == str(task / "local" / "deps" / "data.txt")


def test_checksum_pass_and_fail(world):
    src, task = world
    f = src / "blob"
    f.write_bytes(b"abc123")
    digest = hashlib.sha256(b"abc123").hexdigest()
    ok = fetch_artifact(
        {"source": str(f), "options": {"checksum": f"sha256:{digest}"}},
        str(task))
    assert os.path.exists(ok)
    with pytest.raises(ArtifactError, match="checksum mismatch"):
        fetch_artifact(
            {"source": str(f), "destination": "local/two/",
             "options": {"checksum": "sha256:" + "0" * 64}},
            str(task))


def test_dir_mode(world):
    src, task = world
    (src / "tree").mkdir()
    (src / "tree" / "a.txt").write_text("a")
    (src / "tree" / "sub").mkdir()
    (src / "tree" / "sub" / "b.txt").write_text("b")
    out = fetch_artifact(
        {"source": str(src / "tree"), "mode": "dir",
         "destination": "local/tree"}, str(task))
    assert open(os.path.join(out, "sub", "b.txt")).read() == "b"


def test_archive_auto_extract(world):
    src, task = world
    (src / "inner.txt").write_text("inside")
    tar = src / "bundle.tar.gz"
    with tarfile.open(tar, "w:gz") as t:
        t.add(src / "inner.txt", arcname="inner.txt")
    out = fetch_artifact({"source": str(tar)}, str(task))
    assert open(os.path.join(out, "inner.txt")).read() == "inside"
    assert not os.path.exists(os.path.join(out, "bundle.tar.gz"))


def test_file_mode_renames(world):
    src, task = world
    (src / "tool").write_text("#!/bin/sh\n")
    out = fetch_artifact(
        {"source": str(src / "tool"), "mode": "file",
         "destination": "local/bin/mytool"}, str(task))
    assert out == str(task / "local" / "bin" / "mytool")


def test_sandbox_escape_rejected(world):
    src, task = world
    (src / "f").write_text("x")
    with pytest.raises(ArtifactError, match="escapes"):
        fetch_artifact({"source": str(src / "f"),
                        "destination": "../../outside/"}, str(task))


def test_missing_source(world):
    _, task = world
    with pytest.raises(ArtifactError, match="not found"):
        fetch_artifact({"source": "/nope/missing.bin"}, str(task))


def test_task_consumes_artifact_e2e(tmp_path):
    """A raw_exec task fetches an artifact and reads it (artifact hook
    wired into the taskrunner prestart pipeline)."""
    import time

    from nomad_tpu.client.client import Client, ClientConfig
    from nomad_tpu.core.server import Server, ServerConfig
    from nomad_tpu.structs.job import Job, Task, TaskGroup

    art_src = tmp_path / "artifact.txt"
    art_src.write_text("artifact-content")
    proof = tmp_path / "proof.txt"

    s = Server(ServerConfig(num_schedulers=1, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    c = Client(ClientConfig(node_name="getter-client",
                            data_dir=str(tmp_path / "client"),
                            drivers=["raw_exec"]),
               rpc=s.rpc_leader)
    c.start()
    try:
        t = Task(name="t", driver="raw_exec",
                 config={"command": "/bin/sh",
                         "args": ["-c",
                                  "cp ${NOMAD_TASK_DIR}/artifact.txt "
                                  + str(proof)]})
        t.artifacts = [{"source": str(art_src), "destination": "local/"}]
        job = Job(id=f"art-{time.time_ns()}", name="art", type="batch",
                  task_groups=[TaskGroup(name="g", count=1, tasks=[t])])
        job.canonicalize()
        s.register_job(job)
        deadline = time.time() + 20
        while time.time() < deadline:
            allocs = s.store.allocs_by_job("default", job.id)
            if any(a.client_status == "complete" for a in allocs):
                break
            time.sleep(0.1)
        assert proof.read_text() == "artifact-content", \
            [(a.client_status, a.task_states)
             for a in s.store.allocs_by_job("default", job.id)]
    finally:
        s.stop()
