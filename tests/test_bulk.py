"""Bulk wavefront kernel parity: for identical slots with spreads
inactive, place_bulk_jit must produce the same per-node assignment counts
as the sequential per-slot scan kernel (which is itself golden-tested
against the reference's semantics)."""
import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.ops.place import place_bulk_jit, place_eval, unpack_bulk
from nomad_tpu.scheduler.stack import DenseStack


def _world(n_nodes, seed=0, heterogeneous=True):
    rng = np.random.default_rng(seed)
    cm = ClusterMatrix(initial_rows=n_nodes)
    for i in range(n_nodes):
        n = mock.node()
        if heterogeneous:
            n.node_resources.cpu.cpu_shares = int(rng.integers(2000, 8000))
            n.node_resources.memory_mb = int(rng.integers(4096, 16384))
        cm.upsert_node(n)
    return cm


def _run_both(cm, count, cpu=500, mem=256, existing=None):
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    tg.ephemeral_disk.size_mb = 0
    stack = DenseStack(cm)
    g = stack.compile_group(job, tg)
    allocs_by_tg = {tg.name: existing or []}

    # sequential scan
    inputs = stack.build_inputs(job, [g], [0] * count, allocs_by_tg)
    res = place_eval(inputs)
    scan_counts = np.zeros(cm.n_rows, np.int64)
    for si in range(count):
        row = int(res.node[si])
        if row >= 0:
            scan_counts[row] += 1

    # bulk wavefront
    import jax
    coll0 = np.zeros(cm.n_rows, np.int32)
    for a in allocs_by_tg[tg.name]:
        row = cm.row_of.get(a.node_id)
        if row is not None:
            coll0[row] += 1
    packed = place_bulk_jit(
        np.ascontiguousarray(cm.capacity),
        np.ascontiguousarray(cm.used.astype(np.float32)),
        g.feasible, g.affinity.astype(np.float32), bool(g.has_affinity),
        np.int32(max(tg.count, 1)), np.zeros(cm.n_rows, bool), coll0,
        g.demand.astype(np.float32), np.int32(count))
    assign, placed, n_eval, n_exh, scores, waves, used_f = unpack_bulk(
        jax.device_get(packed))
    return scan_counts, np.asarray(assign).astype(np.int64), int(placed)


@pytest.mark.parametrize("n_nodes,count,seed", [
    (8, 12, 1), (16, 40, 2), (32, 100, 3), (16, 7, 4),
])
def test_bulk_matches_scan(n_nodes, count, seed):
    cm = _world(n_nodes, seed=seed)
    scan, bulk, placed = _run_both(cm, count)
    assert placed == scan.sum() == count
    np.testing.assert_array_equal(bulk, scan)


def test_bulk_matches_scan_with_existing_collisions():
    cm = _world(8, seed=5, heterogeneous=False)
    job = mock.batch_job()
    nodes = list(cm.row_of)
    existing = [mock.alloc_for(job, node_id=nodes[0]),
                mock.alloc_for(job, node_id=nodes[0], index=1)]
    # the helper builds its own job; patch task_group names to match
    scan, bulk, placed = _run_both(cm, 20, existing=existing)
    np.testing.assert_array_equal(bulk, scan)


def test_bulk_overflow_partial_placement():
    """More instances than the cluster fits: bulk places what fits and
    reports the rest unplaced, like the scan."""
    cm = _world(4, seed=6, heterogeneous=False)
    scan, bulk, placed = _run_both(cm, 200, cpu=900, mem=2000)
    assert placed < 200
    assert placed == scan.sum()
    np.testing.assert_array_equal(bulk, scan)


def test_bulk_filling_regime():
    """Demand so small that anti-affinity is negligible vs fit gains:
    the filling regime (singleton + fill) must stay exact."""
    cm = _world(4, seed=7, heterogeneous=False)
    scan, bulk, placed = _run_both(cm, 64, cpu=50, mem=100)
    assert placed == 64
    np.testing.assert_array_equal(bulk, scan)


def test_generic_scheduler_uses_bulk_path():
    """End-to-end through the Harness: a large batch job exercises the
    bulk path and lands the same world as before."""
    from nomad_tpu.scheduler.testing import Harness

    h = Harness()
    for _ in range(16):
        h.store.upsert_node(h.next_index(), mock.node())
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = 600                  # >= BULK_MIN
    tg.tasks[0].resources.cpu = 50
    tg.tasks[0].resources.memory_mb = 100
    tg.ephemeral_disk.size_mb = 0
    h.store.upsert_job(h.next_index(), job)
    h.process("batch", mock.eval(job_id=job.id, type="batch"))
    allocs = h.store.allocs_by_job("default", job.id)
    assert len(allocs) == 600
    # usage actually committed and within capacity
    assert (h.store.matrix.used <= h.store.matrix.capacity + 1e-3).all()
    # placement metadata present
    assert allocs[0].metrics.nodes_evaluated > 0


def test_engine_bulk_batch_matches_serial():
    """Concurrent engine.place_bulk calls coalesce into one chained
    dispatch (place_bulk_batch_jit) and must equal sequential bulk
    processing: each eval's placements land on usage that includes the
    previous eval's, and no node ends over capacity."""
    import threading

    import jax
    from nomad_tpu.ops.place import place_bulk_jit
    from nomad_tpu.parallel.engine import PlacementEngine

    cm = _world(32, heterogeneous=True)
    job = mock.batch_job()
    tg = job.task_groups[0]
    tg.count = 12
    tg.tasks[0].resources.cpu = 700
    tg.tasks[0].resources.memory_mb = 900
    tg.ephemeral_disk.size_mb = 0
    stack = DenseStack(cm)
    g = stack.compile_group(job, tg)
    N = cm.n_rows
    zero = np.zeros(N, np.int32)
    demand = g.demand.astype(np.float32)

    # serial chained reference with the raw kernel
    used = cm.used.astype(np.float32).copy()
    serial = []
    for _ in range(4):
        packed = place_bulk_jit(
            np.ascontiguousarray(cm.capacity),
            np.ascontiguousarray(used), g.feasible,
            g.affinity.astype(np.float32), bool(g.has_affinity),
            np.int32(12), np.zeros(N, bool), zero, demand, np.int32(12))
        assign, placed, *_ , used_f = unpack_bulk(jax.device_get(packed))
        serial.append((assign.copy(), placed))
        used = np.array(used_f)

    engine = PlacementEngine()
    try:
        results = [None] * 4
        barrier = threading.Barrier(4)

        def call(i):
            barrier.wait()
            results[i] = engine.place_bulk(
                cm, feasible=g.feasible, affinity=g.affinity,
                has_affinity=g.has_affinity, desired=12,
                penalty=np.zeros(N, bool), coll0=zero, demand=demand,
                count=12)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(r is not None for r in results)
        # chained: total assignment counts equal the serial totals and
        # respect capacity
        total = np.zeros(N, np.int64)
        for assign, placed, n_eval, n_exh, scores, ticket in results:
            assert placed == 12
            total += assign
            engine.complete(ticket)
        serial_total = sum(a for a, _ in serial)
        np.testing.assert_array_equal(total, serial_total)
        over = cm.used + total[:, None] * demand[None, :]
        assert (over <= cm.capacity + 1e-3).all()
        assert engine.stats["bulk_evals"] >= 4
        assert not engine._tickets     # drained
    finally:
        engine.stop()


def test_engine_bulk_overflow_deltas_not_double_counted():
    """An eval with more deltas than the fixed slot bucket folds them
    into a private basis; the returned used matrix must count each delta
    exactly once (regression: the resolve path re-applied them)."""
    from nomad_tpu.parallel.engine import PlacementEngine, _DELTA_BUCKET

    cm = _world(128, heterogeneous=False)
    N = cm.n_rows
    demand = np.array([100.0, 64.0, 0.0, 0.0], np.float32)
    # one positive delta per row, more than the bucket holds
    n_d = _DELTA_BUCKET + 8
    vec = np.array([50.0, 10.0, 0.0, 0.0], np.float32)
    deltas = [(i, vec) for i in range(n_d)]

    engine = PlacementEngine()
    try:
        assign, placed, n_eval, n_exh, scores, ticket = \
            engine.place_bulk(
                cm, feasible=np.ones(N, bool),
                affinity=np.zeros(N, np.float32), has_affinity=False,
                desired=4, penalty=np.zeros(N, bool),
                coll0=np.zeros(N, np.int32), demand=demand, count=4,
                deltas=deltas)
        assert placed == 4
        # the in-flight overlay must carry the PLACEMENTS only — folded
        # deltas (this eval's private stops) never register there
        overlay = engine._overlays[id(cm)]
        expected = np.outer(assign.astype(np.float32), demand)
        np.testing.assert_allclose(overlay[:, :expected.shape[1]],
                                   expected, rtol=1e-6)
        engine.complete(ticket)
    finally:
        engine.stop()
