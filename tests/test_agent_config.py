"""Agent HCL config files (reference command/agent/config.go) and the
cloud-environment fingerprints."""
import tempfile

from nomad_tpu.agent.config_file import load_config_file


def test_agent_hcl_config_parses():
    hcl = '''
name       = "prod-1"
region     = "eu"
datacenter = "dc7"
data_dir   = "/tmp/nomad-data"
bind_addr  = "0.0.0.0"

ports { http = 5656 }

server {
  enabled            = true
  num_schedulers     = 8
  enabled_schedulers = ["service", "batch"]
  heartbeat_grace    = "30s"
}

client { enabled = true }
acl    { enabled = true }
'''
    with tempfile.NamedTemporaryFile("w", suffix=".hcl",
                                     delete=False) as f:
        f.write(hcl)
        path = f.name
    cfg = load_config_file(path)
    assert cfg.name == "prod-1"
    assert cfg.region == "eu"
    assert cfg.datacenter == "dc7"
    assert cfg.data_dir == "/tmp/nomad-data"
    assert cfg.http_host == "0.0.0.0"
    assert cfg.http_port == 5656
    assert cfg.server_enabled and cfg.client_enabled and cfg.acl_enabled
    assert cfg.num_schedulers == 8
    assert cfg.enabled_schedulers == ["service", "batch"]
    assert cfg.heartbeat_ttl == 30.0
    assert not cfg.dev_mode


def test_cloud_fingerprint_no_crash():
    from nomad_tpu.client.fingerprint import fingerprint_cloud
    attrs = fingerprint_cloud()
    assert isinstance(attrs, dict)   # empty off-cloud, never raises
