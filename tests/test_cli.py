"""CLI tests (reference analog: command/*_test.go run against a dev
agent)."""
import io
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.command.cli import main


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(http_port=0, num_schedulers=2,
                          heartbeat_ttl=60.0))
    a.start()
    for _ in range(3):
        a.server.register_node(mock.node())
    yield a
    a.stop()


def run_cli(agent, *argv):
    out = io.StringIO()
    code = main(["-address", agent.http_addr, *argv], out=out)
    return code, out.getvalue()


JOBSPEC = '''
job "cli-demo" {
  type = "service"
  group "web" {
    count = 2
    task "t" {
      driver = "exec"
      config { command = "/bin/true" }
      resources { cpu = 100  memory = 64 }
    }
  }
}
'''


@pytest.fixture(scope="module")
def jobfile(tmp_path_factory):
    p = tmp_path_factory.mktemp("specs") / "demo.nomad"
    p.write_text(JOBSPEC)
    return str(p)


def test_job_validate(agent, jobfile):
    code, out = run_cli(agent, "job", "validate", jobfile)
    assert code == 0
    assert "successful" in out


def test_job_plan(agent, jobfile):
    code, out = run_cli(agent, "job", "plan", jobfile)
    assert code == 0
    assert "Placements: 2" in out


def test_job_run_and_status(agent, jobfile):
    code, out = run_cli(agent, "job", "run", jobfile)
    assert code == 0, out
    assert "finished with status \"complete\"" in out
    agent.server.wait_for_idle(10)

    code, out = run_cli(agent, "job", "status", "cli-demo")
    assert code == 0
    assert "ID            = cli-demo" in out
    assert "web" in out

    code, out = run_cli(agent, "job", "status")
    assert "cli-demo" in out

    code, out = run_cli(agent, "node", "status")
    assert code == 0
    assert "ready" in out

    # eval + alloc drill-down
    evs = [l for l in out.splitlines()]
    allocs = agent.server.store.allocs_by_job("default", "cli-demo")
    code, out = run_cli(agent, "alloc", "status", allocs[0].id,
                        "-verbose")
    assert code == 0
    assert "Client Status" in out

    code, out = run_cli(agent, "eval", "status", allocs[0].eval_id)
    assert code == 0
    assert "complete" in out


def test_job_inspect(agent, jobfile):
    code, out = run_cli(agent, "job", "inspect", "cli-demo")
    assert code == 0
    import json
    data = json.loads(out)
    assert data["id"] == "cli-demo"


def test_node_eligibility_and_drain(agent):
    node_id = agent.server.store.nodes()[0].id
    code, out = run_cli(agent, "node", "eligibility", node_id, "-disable")
    assert code == 0
    assert agent.server.store.node_by_id(node_id) \
        .scheduling_eligibility == "ineligible"
    code, out = run_cli(agent, "node", "eligibility", node_id, "-enable")
    assert agent.server.store.node_by_id(node_id) \
        .scheduling_eligibility == "eligible"
    code, out = run_cli(agent, "node", "drain", node_id,
                        "-deadline", "60")
    assert code == 0
    time.sleep(0.3)
    code, out = run_cli(agent, "node", "drain", node_id, "-disable")
    assert code == 0
    time.sleep(0.3)
    assert agent.server.store.node_by_id(node_id) \
        .scheduling_eligibility == "eligible"


def test_operator_scheduler_config(agent):
    code, out = run_cli(agent, "operator", "scheduler", "get-config")
    assert code == 0
    assert "Scheduler Algorithm" in out
    code, out = run_cli(agent, "operator", "scheduler", "set-config",
                        "-scheduler-algorithm", "spread")
    assert code == 0
    code, out = run_cli(agent, "operator", "scheduler", "get-config")
    assert "spread" in out
    run_cli(agent, "operator", "scheduler", "set-config",
            "-scheduler-algorithm", "binpack")


def test_server_members_and_version(agent):
    code, out = run_cli(agent, "server", "members")
    assert code == 0
    assert "leader" in out
    code, out = run_cli(agent, "version")
    assert code == 0
    assert "nomad-tpu" in out


def test_namespace_cmds(agent):
    code, _ = run_cli(agent, "namespace", "apply", "team-x")
    assert code == 0
    code, out = run_cli(agent, "namespace", "list")
    assert "team-x" in out
    code, _ = run_cli(agent, "namespace", "delete", "team-x")
    assert code == 0


def test_job_stop(agent):
    code, out = run_cli(agent, "job", "stop", "-detach", "cli-demo")
    assert code == 0
    agent.server.wait_for_idle(10)
    job = agent.server.store.job_by_id("default", "cli-demo")
    assert job.stop is True


def test_error_paths(agent):
    code, _ = run_cli(agent, "job", "status", "no-such-job")
    assert code == 1
    code, _ = run_cli(agent, "alloc", "status", "bogus")
    assert code == 1


def test_job_validate_and_run_with_vars(agent, tmp_path_factory):
    """A jobspec using variables/locals/functions round-trips through
    job validate and job run -var (VERDICT r4 item 10)."""
    spec = tmp_path_factory.mktemp("vars") / "varjob.nomad"
    spec.write_text('''
variable "name" { type = string }
variable "replicas" {
  type    = number
  default = 2
}
locals { full = format("%s-svc", var.name) }
job "var-demo" {
  type = "service"
  meta { rendered = local.full }
  group "g" {
    count = var.replicas
    task "t" {
      driver = "mock_driver"
      config { run_for = 30 }
    }
  }
}
''')
    code, out = run_cli(agent, "job", "validate",
                        "-var", "name=alpha", str(spec))
    assert code == 0 and "successful" in out

    # missing required var fails validation
    code, out = run_cli(agent, "job", "validate", str(spec))
    assert code == 1 and "no value" in out

    code, out = run_cli(agent, "job", "run", "-detach",
                        "-var", "name=alpha", str(spec))
    assert code == 0
    deadline = time.time() + 15
    while time.time() < deadline:
        job = agent.server.store.job_by_id("default", "var-demo")
        if job is not None:
            break
        time.sleep(0.1)
    assert job is not None
    assert job.meta["rendered"] == "alpha-svc"
    assert job.task_groups[0].count == 2
