"""Jobspec HCL parser tests (reference analog: jobspec2/parse_test.go)."""
import pytest

from nomad_tpu.jobspec import parse_hcl, parse_job, HclParseError
from nomad_tpu.jobspec.parse import parse_duration

EXAMPLE = '''
# An example service job
job "web" {
  type        = "service"
  priority    = 70
  datacenters = ["dc1", "dc2"]

  constraint {
    attribute = "${attr.kernel.name}"
    value     = "linux"
  }

  update {
    max_parallel     = 2
    canary           = 1
    auto_revert      = true
    min_healthy_time = "15s"
    healthy_deadline = "5m"
  }

  meta {
    owner = "team-web"
  }

  group "frontend" {
    count = 3

    spread {
      attribute = "${node.datacenter}"
      weight    = 50
      target "dc1" { percent = 70 }
      target "dc2" { percent = 30 }
    }

    restart {
      attempts = 3
      interval = "30m"
      delay    = "10s"
      mode     = "delay"
    }

    ephemeral_disk {
      size   = 500
      sticky = true
    }

    network {
      mode = "bridge"
      port "http" { to = 8080 }
      port "admin" { static = 9090 }
    }

    volume "data" {
      type   = "host"
      source = "data-vol"
    }

    task "server" {
      driver = "mock"

      config {
        image = "nginx:1.21"
        args  = ["-p", "8080"]
      }

      env {
        PORT = "8080"
        MODE = "production"
      }

      resources {
        cpu    = 500
        memory = 256

        device "nvidia/gpu" {
          count = 1
        }
      }

      service {
        name = "web-frontend"
        port = "http"
        tags = ["urlprefix-/web"]
      }

      template {
        destination = "local/config.json"
        data        = <<EOF
{"listen": "${PORT}"}
EOF
      }

      kill_timeout = "20s"
    }

    task "sidecar" {
      driver = "mock"
      lifecycle {
        hook    = "prestart"
        sidecar = true
      }
    }
  }

  group "batchers" {
    count = 2
    reschedule {
      attempts  = 5
      unlimited = false
      interval  = "1h"
    }
    task "worker" {
      driver = "mock"
    }
  }
}
'''


def test_parse_duration():
    assert parse_duration("30s") == 30.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration(15) == 15.0
    assert parse_duration(None, 7.5) == 7.5
    with pytest.raises(HclParseError):
        parse_duration("bogus")


def test_parse_full_job():
    job = parse_job(EXAMPLE)
    assert job.id == "web"
    assert job.type == "service"
    assert job.priority == 70
    assert job.datacenters == ["dc1", "dc2"]
    assert job.constraints[0].ltarget == "${attr.kernel.name}"
    assert job.constraints[0].rtarget == "linux"
    assert job.update.max_parallel == 2
    assert job.update.canary == 1
    assert job.update.auto_revert is True
    assert job.update.min_healthy_time_s == 15.0
    assert job.meta["owner"] == "team-web"

    assert len(job.task_groups) == 2
    fe = job.task_groups[0]
    assert fe.name == "frontend"
    assert fe.count == 3
    assert fe.spreads[0].attribute == "${node.datacenter}"
    assert fe.spreads[0].targets[0].value == "dc1"
    assert fe.spreads[0].targets[0].percent == 70
    assert fe.restart_policy.attempts == 3
    assert fe.restart_policy.interval_s == 1800.0
    assert fe.ephemeral_disk.size_mb == 500
    assert fe.ephemeral_disk.sticky is True
    assert fe.networks[0].mode == "bridge"
    assert fe.networks[0].dynamic_ports[0].label == "http"
    assert fe.networks[0].dynamic_ports[0].to == 8080
    assert fe.networks[0].reserved_ports[0].value == 9090
    assert fe.volumes["data"].source == "data-vol"

    server = fe.tasks[0]
    assert server.driver == "mock"
    assert server.config["image"] == "nginx:1.21"
    assert server.config["args"] == ["-p", "8080"]
    assert server.env["PORT"] == "8080"
    assert server.resources.cpu == 500
    assert server.resources.memory_mb == 256
    assert server.resources.devices[0].name == "nvidia/gpu"
    assert server.services[0].name == "web-frontend"
    assert server.kill_timeout_s == 20.0
    assert '"listen"' in server.templates[0]["data"]

    sidecar = fe.tasks[1]
    assert sidecar.lifecycle.hook == "prestart"
    assert sidecar.lifecycle.sidecar is True

    batch = job.task_groups[1]
    assert batch.reschedule_policy.attempts == 5
    assert batch.reschedule_policy.unlimited is False

    # canonicalize propagated the job-level update into the group
    assert fe.update is not None
    assert fe.update.canary == 1


def test_parse_periodic_and_parameterized():
    src = '''
job "cron" {
  type = "batch"
  periodic {
    cron             = "*/15 * * * *"
    prohibit_overlap = true
  }
  group "g" { task "t" { driver = "mock" } }
}
'''
    job = parse_job(src)
    assert job.periodic.spec == "*/15 * * * *"
    assert job.periodic.prohibit_overlap is True

    src2 = '''
job "proc" {
  type = "batch"
  parameterized {
    payload       = "required"
    meta_required = ["input"]
  }
  group "g" { task "t" { driver = "mock" } }
}
'''
    job2 = parse_job(src2)
    assert job2.parameterized.payload == "required"
    assert job2.parameterized.meta_required == ["input"]


def test_parse_errors():
    with pytest.raises(HclParseError):
        parse_job("group {}")          # no job block
    with pytest.raises(HclParseError):
        parse_hcl('job "x" {')         # unterminated
    with pytest.raises(HclParseError):
        parse_hcl('job = = "x"')


def test_comments_and_heredoc():
    root = parse_hcl('''
// line comment
/* block
   comment */
a = 1  # trailing
b = <<EOT
line1
line2
EOT
''')
    assert root.attrs["a"] == 1
    assert root.attrs["b"] == "line1\nline2"


# ------------------------------------------------------- HCL2 expressions

def test_variables_locals_functions():
    """jobspec2/parse.go ParseWithConfig: variable blocks + -var
    overrides, locals, and the cty function set."""
    src = '''
variable "region" {
  type    = string
  default = "us-east"
}
variable "count" {
  type    = number
  default = 3
}
locals {
  svc_name = format("web-%s", var.region)
  doubled  = max(var.count, 2)
}
job "api" {
  type        = "service"
  datacenters = [var.region]
  meta {
    service = local.svc_name
    upper   = upper(local.svc_name)
    joined  = join(",", concat(["a"], ["b", "c"]))
  }
  group "g" {
    count = local.doubled
    task "t" {
      driver = "mock_driver"
      env {
        REGION = "${var.region}"
        MIXED  = "pre-${var.region}-post"
        RUNTIME = "${NOMAD_TASK_DIR}/x"
      }
    }
  }
}
'''
    job = parse_job(src)
    assert job.datacenters == ["us-east"]
    assert job.meta["service"] == "web-us-east"
    assert job.meta["upper"] == "WEB-US-EAST"
    assert job.meta["joined"] == "a,b,c"
    tg = job.task_groups[0]
    assert tg.count == 3
    env = tg.tasks[0].env
    assert env["REGION"] == "us-east"
    assert env["MIXED"] == "pre-us-east-post"
    # runtime interpolation stays literal for the client's taskenv
    assert env["RUNTIME"] == "${NOMAD_TASK_DIR}/x"


def test_variable_overrides_and_errors():
    src = '''
variable "who" { type = string }
job "j" {
  type = "batch"
  group "g" {
    task "t" {
      driver = "mock_driver"
      meta { who = var.who }
    }
  }
}
'''
    job = parse_job(src, {"who": "ops"})
    assert job.task_groups[0].tasks[0].meta["who"] == "ops"
    with pytest.raises(HclParseError, match="has no value"):
        parse_job(src)
    with pytest.raises(HclParseError, match="undeclared"):
        parse_job(src, {"who": "x", "nope": "y"})


def test_locals_dependency_chain_and_functions():
    from nomad_tpu.jobspec.hcl import parse_hcl as ph
    from nomad_tpu.jobspec.expr import evaluate
    root = ph('''
locals {
  c = upper(local.b)
  b = format("%s-%d", local.a, 2)
  a = "x"
}
v1 = local.c
v2 = length([1, 2, 3])
v3 = jsonencode({k = "v"})
v4 = coalesce("", "fallback")
v5 = replace("a.b.c", ".", "-")
''')
    evaluate(root)
    assert root.attrs["v1"] == "X-2"
    assert root.attrs["v2"] == 3
    assert root.attrs["v3"] == '{"k":"v"}'
    assert root.attrs["v4"] == "fallback"
    assert root.attrs["v5"] == "a-b-c"


def test_unknown_function_and_var():
    from nomad_tpu.jobspec.expr import evaluate
    from nomad_tpu.jobspec.hcl import parse_hcl as ph
    with pytest.raises(HclParseError, match="unknown function"):
        evaluate(ph('x = frobnicate("a")'))
    with pytest.raises(HclParseError, match="undefined variable"):
        evaluate(ph('x = var.missing'))
