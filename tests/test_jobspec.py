"""Jobspec HCL parser tests (reference analog: jobspec2/parse_test.go)."""
import pytest

from nomad_tpu.jobspec import parse_hcl, parse_job, HclParseError
from nomad_tpu.jobspec.parse import parse_duration

EXAMPLE = '''
# An example service job
job "web" {
  type        = "service"
  priority    = 70
  datacenters = ["dc1", "dc2"]

  constraint {
    attribute = "${attr.kernel.name}"
    value     = "linux"
  }

  update {
    max_parallel     = 2
    canary           = 1
    auto_revert      = true
    min_healthy_time = "15s"
    healthy_deadline = "5m"
  }

  meta {
    owner = "team-web"
  }

  group "frontend" {
    count = 3

    spread {
      attribute = "${node.datacenter}"
      weight    = 50
      target "dc1" { percent = 70 }
      target "dc2" { percent = 30 }
    }

    restart {
      attempts = 3
      interval = "30m"
      delay    = "10s"
      mode     = "delay"
    }

    ephemeral_disk {
      size   = 500
      sticky = true
    }

    network {
      mode = "bridge"
      port "http" { to = 8080 }
      port "admin" { static = 9090 }
    }

    volume "data" {
      type   = "host"
      source = "data-vol"
    }

    task "server" {
      driver = "mock"

      config {
        image = "nginx:1.21"
        args  = ["-p", "8080"]
      }

      env {
        PORT = "8080"
        MODE = "production"
      }

      resources {
        cpu    = 500
        memory = 256

        device "nvidia/gpu" {
          count = 1
        }
      }

      service {
        name = "web-frontend"
        port = "http"
        tags = ["urlprefix-/web"]
      }

      template {
        destination = "local/config.json"
        data        = <<EOF
{"listen": "${PORT}"}
EOF
      }

      kill_timeout = "20s"
    }

    task "sidecar" {
      driver = "mock"
      lifecycle {
        hook    = "prestart"
        sidecar = true
      }
    }
  }

  group "batchers" {
    count = 2
    reschedule {
      attempts  = 5
      unlimited = false
      interval  = "1h"
    }
    task "worker" {
      driver = "mock"
    }
  }
}
'''


def test_parse_duration():
    assert parse_duration("30s") == 30.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration(15) == 15.0
    assert parse_duration(None, 7.5) == 7.5
    with pytest.raises(HclParseError):
        parse_duration("bogus")


def test_parse_full_job():
    job = parse_job(EXAMPLE)
    assert job.id == "web"
    assert job.type == "service"
    assert job.priority == 70
    assert job.datacenters == ["dc1", "dc2"]
    assert job.constraints[0].ltarget == "${attr.kernel.name}"
    assert job.constraints[0].rtarget == "linux"
    assert job.update.max_parallel == 2
    assert job.update.canary == 1
    assert job.update.auto_revert is True
    assert job.update.min_healthy_time_s == 15.0
    assert job.meta["owner"] == "team-web"

    assert len(job.task_groups) == 2
    fe = job.task_groups[0]
    assert fe.name == "frontend"
    assert fe.count == 3
    assert fe.spreads[0].attribute == "${node.datacenter}"
    assert fe.spreads[0].targets[0].value == "dc1"
    assert fe.spreads[0].targets[0].percent == 70
    assert fe.restart_policy.attempts == 3
    assert fe.restart_policy.interval_s == 1800.0
    assert fe.ephemeral_disk.size_mb == 500
    assert fe.ephemeral_disk.sticky is True
    assert fe.networks[0].mode == "bridge"
    assert fe.networks[0].dynamic_ports[0].label == "http"
    assert fe.networks[0].dynamic_ports[0].to == 8080
    assert fe.networks[0].reserved_ports[0].value == 9090
    assert fe.volumes["data"].source == "data-vol"

    server = fe.tasks[0]
    assert server.driver == "mock"
    assert server.config["image"] == "nginx:1.21"
    assert server.config["args"] == ["-p", "8080"]
    assert server.env["PORT"] == "8080"
    assert server.resources.cpu == 500
    assert server.resources.memory_mb == 256
    assert server.resources.devices[0].name == "nvidia/gpu"
    assert server.services[0].name == "web-frontend"
    assert server.kill_timeout_s == 20.0
    assert '"listen"' in server.templates[0]["data"]

    sidecar = fe.tasks[1]
    assert sidecar.lifecycle.hook == "prestart"
    assert sidecar.lifecycle.sidecar is True

    batch = job.task_groups[1]
    assert batch.reschedule_policy.attempts == 5
    assert batch.reschedule_policy.unlimited is False

    # canonicalize propagated the job-level update into the group
    assert fe.update is not None
    assert fe.update.canary == 1


def test_parse_periodic_and_parameterized():
    src = '''
job "cron" {
  type = "batch"
  periodic {
    cron             = "*/15 * * * *"
    prohibit_overlap = true
  }
  group "g" { task "t" { driver = "mock" } }
}
'''
    job = parse_job(src)
    assert job.periodic.spec == "*/15 * * * *"
    assert job.periodic.prohibit_overlap is True

    src2 = '''
job "proc" {
  type = "batch"
  parameterized {
    payload       = "required"
    meta_required = ["input"]
  }
  group "g" { task "t" { driver = "mock" } }
}
'''
    job2 = parse_job(src2)
    assert job2.parameterized.payload == "required"
    assert job2.parameterized.meta_required == ["input"]


def test_parse_errors():
    with pytest.raises(HclParseError):
        parse_job("group {}")          # no job block
    with pytest.raises(HclParseError):
        parse_hcl('job "x" {')         # unterminated
    with pytest.raises(HclParseError):
        parse_hcl('job = = "x"')


def test_comments_and_heredoc():
    root = parse_hcl('''
// line comment
/* block
   comment */
a = 1  # trailing
b = <<EOT
line1
line2
EOT
''')
    assert root.attrs["a"] == 1
    assert root.attrs["b"] == "line1\nline2"
