"""Network (bandwidth + static ports) and device preemption
(reference scheduler/preemption.go PreemptForNetwork:270-454,
PreemptForDevice:472-555), plus device instance assignment in the
placement path (scheduler/device.go AllocateDevice)."""
import numpy as np

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.resources import (
    DeviceRequest,
    NetworkPort,
    NetworkResource,
    NodeDevice,
)


def _harness(n_nodes=3, node_fn=None):
    h = Harness()
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        if node_fn:
            node_fn(n)
        h.store.upsert_node(h.next_index(), n)
        nodes.append(n)
    return h, nodes


def _enable_preemption(h):
    cfg = h.store.scheduler_config
    cfg.preemption_config.service_scheduler_enabled = True
    cfg.preemption_config.batch_scheduler_enabled = True


def _run_job(h, job):
    ev = mock.eval(job_id=job.id, type=job.type, priority=job.priority)
    h.store.upsert_job(h.next_index(), job)
    h.process(job.type, ev)
    return h.store.allocs_by_job("default", job.id)


def test_bandwidth_preemption_via_net_dimension():
    """Low-priority allocs saturating a node's MBits are evicted for a
    higher-priority job that needs the bandwidth (PreemptForNetwork's
    bandwidth dimension rides RES_NET in the dense design)."""
    h, nodes = _harness(n_nodes=1)
    _enable_preemption(h)

    low = mock.job()
    low.priority = 20
    tg = low.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 100
    tg.tasks[0].resources.networks = [NetworkResource(mbits=900)]
    assert len(_run_job(h, low)) == 1

    high = mock.job()
    high.priority = 70
    tg = high.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 100
    tg.tasks[0].resources.networks = [NetworkResource(mbits=500)]
    allocs = _run_job(h, high)
    assert len(allocs) == 1
    assert allocs[0].preempted_allocations, \
        "high-priority job should preempt the bandwidth hog"


def test_static_port_preemption():
    """A static-port conflict with a lower-priority alloc is resolved by
    evicting the port holder (PreemptForNetwork reserved-port path)."""
    h, nodes = _harness(n_nodes=1)
    _enable_preemption(h)

    low = mock.job()
    low.priority = 20
    tg = low.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.networks = [NetworkResource(
        reserved_ports=[NetworkPort(label="http", value=8080)])]
    assert len(_run_job(h, low)) == 1

    high = mock.job()
    high.priority = 70
    tg = high.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.networks = [NetworkResource(
        reserved_ports=[NetworkPort(label="http", value=8080)])]
    allocs = _run_job(h, high)
    assert len(allocs) == 1
    evicted = allocs[0].preempted_allocations
    assert evicted, "port holder should be preempted"


def test_static_port_held_by_higher_priority_not_preempted():
    """Ports held by non-preemptible (similar priority) allocs make the
    node ineligible (filteredReservedPorts semantics)."""
    h, nodes = _harness(n_nodes=1)
    _enable_preemption(h)

    first = mock.job()
    first.priority = 65
    tg = first.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.networks = [NetworkResource(
        reserved_ports=[NetworkPort(label="http", value=8080)])]
    assert len(_run_job(h, first)) == 1

    second = mock.job()
    second.priority = 70    # delta < 10: not preemptible
    tg = second.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.networks = [NetworkResource(
        reserved_ports=[NetworkPort(label="http", value=8080)])]
    allocs = _run_job(h, second)
    assert len(allocs) == 0
    sched = h.last_scheduler
    assert sched.failed_tg_allocs, "placement must fail, not preempt"


def _gpu_node(n):
    n.node_resources.devices = [NodeDevice(
        vendor="nvidia", type="gpu", name="1080ti",
        instance_ids=["gpu0", "gpu1"])]


def test_device_instance_assignment():
    """Placements carry concrete device instance ids."""
    h, nodes = _harness(n_nodes=1, node_fn=_gpu_node)

    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 2
    tg.tasks[0].resources.devices = [DeviceRequest(name="gpu", count=1)]
    allocs = _run_job(h, job)
    assert len(allocs) == 2
    got = set()
    for a in allocs:
        devs = a.allocated_resources.tasks["web"].devices
        assert len(devs) == 1 and devs[0]["vendor"] == "nvidia"
        got.update(devs[0]["device_ids"])
    assert got == {"gpu0", "gpu1"}, "each alloc gets a distinct instance"


def test_device_preemption():
    """When all instances are claimed by a lower-priority job, a
    higher-priority job preempts enough allocs to free instances
    (PreemptForDevice)."""
    h, nodes = _harness(n_nodes=1, node_fn=_gpu_node)
    _enable_preemption(h)

    low = mock.job()
    low.priority = 20
    tg = low.task_groups[0]
    tg.count = 2
    tg.tasks[0].resources.devices = [DeviceRequest(name="gpu", count=1)]
    assert len(_run_job(h, low)) == 2

    high = mock.job()
    high.priority = 70
    tg = high.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.devices = [DeviceRequest(name="gpu", count=1)]
    allocs = _run_job(h, high)
    assert len(allocs) == 1
    assert allocs[0].preempted_allocations
    devs = allocs[0].allocated_resources.tasks["web"].devices
    assert devs and devs[0]["device_ids"]


def test_device_exhausted_without_preemption_fails():
    h, nodes = _harness(n_nodes=1, node_fn=_gpu_node)

    low = mock.job()
    low.priority = 50
    tg = low.task_groups[0]
    tg.count = 2
    tg.tasks[0].resources.devices = [DeviceRequest(name="gpu", count=1)]
    assert len(_run_job(h, low)) == 2

    nxt = mock.job()
    nxt.priority = 50
    tg = nxt.task_groups[0]
    tg.count = 1
    tg.tasks[0].resources.devices = [DeviceRequest(name="gpu", count=1)]
    allocs = _run_job(h, nxt)
    assert len(allocs) == 0
    assert h.last_scheduler.failed_tg_allocs
