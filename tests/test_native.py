"""Native C++ kernel tests — and parity between the native and numpy
fallback paths (reference analogs: structs/funcs_test.go AllocsFit/
ScoreFit tests, plan_apply_test.go node validation)."""
import numpy as np
import pytest

from nomad_tpu import native


@pytest.fixture(scope="module")
def lib_available():
    native._load()
    return native.NATIVE_AVAILABLE


def test_native_library_builds(lib_available):
    # the toolchain is part of the environment contract; the native
    # path must actually be exercised in CI, not silently skipped
    assert lib_available, "g++ build of native/nomad_native.cpp failed"


def test_allocs_fit():
    cap = np.array([[1000, 1000, 1000], [100, 100, 100]], np.float32)
    used = np.array([[500, 500, 500], [90, 90, 90]], np.float32)
    fit = native.allocs_fit(cap, used, np.array([100, 100, 100], np.float32))
    assert fit.tolist() == [True, False]
    # exact boundary fits
    fit = native.allocs_fit(cap, used, np.array([500, 500, 500], np.float32))
    assert fit.tolist() == [True, False]


def test_score_fit_matches_host_reference():
    from nomad_tpu.structs import (
        ComparableResources,
        score_fit_binpack_host,
    )
    cap = np.array([[4000, 8192, 0]], np.float32)
    used = np.array([[1000, 2048, 0]], np.float32)
    demand = np.array([500, 1024, 0], np.float32)
    got = native.score_fit(cap, used, demand)[0]
    node = ComparableResources(cpu_shares=4000, memory_mb=8192)
    util = ComparableResources(cpu_shares=1500, memory_mb=3072)
    # native.score_fit returns the /18-normalized score in [0, 1]
    want = score_fit_binpack_host(node, util) / 18.0
    assert got == pytest.approx(want, abs=1e-4)


def test_score_fit_binpack_prefers_fuller_node():
    cap = np.array([[1000, 1000, 0], [1000, 1000, 0]], np.float32)
    used = np.array([[800, 800, 0], [100, 100, 0]], np.float32)
    s = native.score_fit(cap, used, np.array([50, 50, 0], np.float32))
    assert s[0] > s[1]                        # binpack packs fuller node
    s2 = native.score_fit(cap, used, np.array([50, 50, 0], np.float32),
                          spread=True)
    assert s2[1] > s2[0]                      # spread prefers emptier


def test_ports_roundtrip():
    words = np.zeros((2, 2048), np.uint32)
    native.ports_set(words, 0, [80, 443, 20000], True)
    assert not native.ports_check(words, 0, [80])
    assert native.ports_check(words, 0, [8080])
    assert native.ports_check(words, 1, [80])          # other row clean
    # freed ports count as free
    assert native.ports_check(words, 0, [443], freed=[443])
    # duplicates within a request collide
    assert not native.ports_check(words, 0, [8080, 8080])
    native.ports_set(words, 0, [80], False)
    assert native.ports_check(words, 0, [80])


def test_scatter_add():
    used = np.zeros((4, 3), np.float32)
    native.scatter_add(used, [1, 1, 3],
                       np.array([[1, 2, 3], [1, 2, 3], [5, 5, 5]],
                                np.float32))
    assert used[1].tolist() == [2, 4, 6]
    assert used[3].tolist() == [5, 5, 5]
    assert used[0].tolist() == [0, 0, 0]


def test_validate_plan_batch():
    cap = np.array([[1000, 1000, 1000]] * 3, np.float32)
    used = np.array([[0, 0, 0], [950, 0, 0], [500, 500, 500]], np.float32)
    words = np.zeros((3, 2048), np.uint32)
    native.ports_set(words, 2, [9090], True)
    ok = native.validate_plan(
        cap, used, words,
        rows=[0, 1, 2, -1],
        demand=np.array([[100, 100, 100], [100, 0, 0],
                         [100, 100, 100], [1, 1, 1]], np.float32),
        freed=np.array([[0, 0, 0], [0, 0, 0], [0, 0, 0], [0, 0, 0]],
                       np.float32),
        group_ports=[[80], [], [9090], []],
        group_freed_ports=[[], [], [], []])
    assert ok.tolist() == [True, False, False, False]
    # with 9090 freed by a stop in the same plan, node 2 passes
    ok2 = native.validate_plan(
        cap, used, words, rows=[2],
        demand=np.array([[100, 100, 100]], np.float32),
        freed=np.array([[0, 0, 0]], np.float32),
        group_ports=[[9090]], group_freed_ports=[[9090]])
    assert ok2.tolist() == [True]


def test_native_numpy_parity():
    """The numpy fallback and C++ path agree on random inputs."""
    if not native.NATIVE_AVAILABLE:
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(42)
    cap = rng.uniform(100, 5000, (64, 3)).astype(np.float32)
    used = (cap * rng.uniform(0, 1.2, (64, 3))).astype(np.float32)
    demand = rng.uniform(0, 500, 3).astype(np.float32)

    lib, native._lib = native._lib, None
    avail = native.NATIVE_AVAILABLE
    native.NATIVE_AVAILABLE = False
    try:
        import unittest.mock as m
        with m.patch.object(native, "_load", return_value=None):
            fit_np = native.allocs_fit(cap, used, demand)
            score_np = native.score_fit(cap, used, demand)
    finally:
        native._lib = lib
        native.NATIVE_AVAILABLE = avail
    fit_c = native.allocs_fit(cap, used, demand)
    score_c = native.score_fit(cap, used, demand)
    assert (fit_np == fit_c).all()
    np.testing.assert_allclose(score_np, score_c, atol=1e-4)
