"""Regression tests for the reserved-key propagation and deadline-
coverage fixes the PR-19 static checkers flushed out (targeted tests
outside the analysis fixture corpora):

- `Server.rpc_region` / `Server.rpc_leader` rebuilt args without
  re-encoding the deadline budget, so a cross-region (or transport-
  forwarded) request ran unbounded on the remote side — both now go
  through `reserved.restamp`.
- `Plan.Submit` parked on the applier future for a fixed 30 s and
  never consulted the deadline; it now sheds expired submissions
  before enqueue (`deadline.expired.plan.submit`) and clamps the wait
  to the remaining budget.
- `Node.GetClientAllocs` and the HTTP blocking-query park honored only
  the caller's `timeout`/`wait`, not the request deadline.
"""
import concurrent.futures
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from nomad_tpu import deadline, mock, tracing
from nomad_tpu.agent import Agent, AgentConfig
from nomad_tpu.core.server import Server
from nomad_tpu.rpc import reserved
from nomad_tpu.rpc.endpoints import Endpoints, RpcError
from nomad_tpu.telemetry import global_metrics


def _counter(name):
    for c in global_metrics.snapshot()["Counters"]:
        if c["Name"] == name:
            return c["Count"]
    return 0.0


def _bound(budget):
    return deadline.bind(time.monotonic() + budget)


# ------------------------------------------------------------ restamp


def test_restamp_attaches_deadline_and_preserves_args():
    prev = _bound(5.0)
    try:
        args = {"x": 1, "_forward_hops": 2}
        out = reserved.restamp(args)
        assert out is not args and args == {"x": 1, "_forward_hops": 2}
        assert out["x"] == 1 and out["_forward_hops"] == 2
        assert 0.0 < out[deadline.DEADLINE_KEY] <= 5.0
    finally:
        deadline.bind(prev)


def test_restamp_never_overwrites_an_existing_budget():
    prev = _bound(5.0)
    try:
        out = reserved.restamp({deadline.DEADLINE_KEY: 1.25})
        assert out[deadline.DEADLINE_KEY] == 1.25
    finally:
        deadline.bind(prev)


def test_restamp_unbound_thread_adds_nothing():
    out = reserved.restamp({"x": 1})
    assert deadline.DEADLINE_KEY not in out
    assert tracing.TRACE_KEY not in out


def test_restamp_attaches_trace_context():
    tracer = tracing.Tracer(sample_rate=1.0)
    prev_active = tracing.active
    tracing.active = tracer
    tprev = tracing.bind(tracer.new_context())
    try:
        out = reserved.restamp({})
        assert tracing.TRACE_KEY in out
    finally:
        tracing.bind(tprev)
        tracing.active = prev_active


# ------------------------------------- forwarding sites re-stamp args


def test_rpc_region_restamps_deadline_budget():
    srv = object.__new__(Server)
    calls = []
    srv.region_router = SimpleNamespace(
        route=lambda region, method, args:
            calls.append((region, method, args)) or "routed")
    prev = _bound(5.0)
    try:
        assert Server.rpc_region(srv, "west", "Status.Ping",
                                 {"q": 1}) == "routed"
    finally:
        deadline.bind(prev)
    (_, _, args), = calls
    assert args["q"] == 1
    assert 0.0 < args[deadline.DEADLINE_KEY] <= 5.0


def test_rpc_leader_transport_hop_restamps_deadline_budget():
    srv = object.__new__(Server)
    srv.name = "follower-1"
    srv.raft = SimpleNamespace(is_leader=False, leader_id="leader-0")
    calls = []
    srv._transport = SimpleNamespace(
        call=lambda src, dst, method, args:
            calls.append((dst, method, args)) or "forwarded")
    prev = _bound(5.0)
    try:
        assert Server.rpc_leader(srv, "Job.Register",
                                 {"job": "j"}) == "forwarded"
    finally:
        deadline.bind(prev)
    (dst, _, args), = calls
    assert dst == "rpc:leader-0"
    assert 0.0 < args[deadline.DEADLINE_KEY] <= 5.0


# ------------------------------------------- Plan.Submit deadline gate


def _submit_stub(future):
    server = SimpleNamespace(
        enqueue_plan=lambda plan: SimpleNamespace(future=future))
    return SimpleNamespace(server=server)


def test_plan_submit_sheds_expired_before_enqueue():
    plan = SimpleNamespace(job=None)
    before = _counter("deadline.expired.plan.submit")
    prev = deadline.bind(time.monotonic() - 1.0)
    try:
        with pytest.raises(RpcError) as ei:
            Endpoints.rpc_Plan__Submit(
                _submit_stub(concurrent.futures.Future()),
                {"plan": plan})
    finally:
        deadline.bind(prev)
    assert ei.value.kind == "deadline_exceeded"
    assert _counter("deadline.expired.plan.submit") == before + 1


def test_plan_submit_wait_clamped_to_remaining_budget():
    plan = SimpleNamespace(job=None)
    never = concurrent.futures.Future()          # applier never answers
    prev = _bound(0.3)
    t0 = time.monotonic()
    try:
        with pytest.raises(concurrent.futures.TimeoutError):
            Endpoints.rpc_Plan__Submit(_submit_stub(never),
                                       {"plan": plan})
    finally:
        deadline.bind(prev)
    assert time.monotonic() - t0 < 5.0           # not the fixed 30 s


def test_plan_submit_unbound_keeps_full_window():
    plan = SimpleNamespace(job=None)
    done = concurrent.futures.Future()
    done.set_result({"applied": True})
    out = Endpoints.rpc_Plan__Submit(_submit_stub(done), {"plan": plan})
    assert out == {"applied": True}


# --------------------------------------- blocking queries honor budget


def test_get_client_allocs_park_clamped_to_budget():
    seen = {}

    def wait_for_index(idx, timeout=None):
        seen["timeout"] = timeout

    store = SimpleNamespace(wait_for_index=wait_for_index,
                            latest_index=7,
                            allocs_by_node=lambda node_id: [])
    ep = SimpleNamespace(server=SimpleNamespace(store=store))
    prev = _bound(0.5)
    try:
        out = Endpoints.rpc_Node__GetClientAllocs(
            ep, {"node_id": "n1", "min_index": 3, "timeout": 30.0})
    finally:
        deadline.bind(prev)
    assert out["index"] == 7
    assert seen["timeout"] <= 0.5


@pytest.fixture(scope="module")
def agent():
    a = Agent(AgentConfig(http_port=0, num_schedulers=1,
                          heartbeat_ttl=60.0))
    a.start()
    a.server.register_node(mock.node())
    yield a
    a.stop()


def test_http_blocking_query_park_clamped_to_deadline(agent):
    # pre-fix the park honored only `wait` (60 s here) and the 504 came
    # a minute late; the clamp makes the refusal (or the current-state
    # answer, if the budget outlives the park) arrive within budget
    latest = agent.server.store.latest_index
    req = urllib.request.Request(
        f"{agent.http_addr}/v1/jobs?index={latest + 1000}&wait=60s")
    req.add_header("X-Nomad-Deadline", "0.4")
    t0 = time.monotonic()
    try:
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
    assert code in (200, 504)
    assert time.monotonic() - t0 < 5.0           # not the 60 s wait
