// Native host-runtime kernels (C ABI, loaded via ctypes).
//
// Reference analogs:
//  - structs.AllocsFit / ScoreFitBinPack / ScoreFitSpread
//    (nomad/structs/funcs.go:166-297) vectorized over the node axis
//  - the plan applier's per-node validation fan-out
//    (nomad/plan_apply_pool.go EvaluatePool + plan_apply.go:640
//    evaluateNodePlan) as one dense pass
//  - NetworkIndex port bitset accounting (nomad/structs/network.go)
//
// The device (XLA/TPU) path owns scheduling-time scoring; these kernels
// serve the HOST runtime: plan validation, columnar-mirror maintenance,
// and host-side fit checks, where a Python loop would otherwise sit in
// the commit path.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libnomad_native.so
//        nomad_native.cpp    (driven by nomad_tpu/native/__init__.py)

#include <cstdint>
#include <cmath>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------
// allocs_fit_dense: for every node row, does `demand` fit in
// capacity-used?  out_fit[i] = 1 if fits.  dims = resource dimensions
// (cpu, mem, disk).
void allocs_fit_dense(const float* capacity, const float* used,
                      const float* demand, int n_rows, int dims,
                      uint8_t* out_fit) {
    for (int i = 0; i < n_rows; ++i) {
        const float* cap = capacity + (size_t)i * dims;
        const float* use = used + (size_t)i * dims;
        uint8_t ok = 1;
        for (int d = 0; d < dims; ++d) {
            if (use[d] + demand[d] > cap[d] + 1e-6f) { ok = 0; break; }
        }
        out_fit[i] = ok;
    }
}

// ---------------------------------------------------------------------
// score_fit_binpack / spread over all rows given a demand vector.
// binpack: 20 - 10^(free_cpu_frac) - 10^(free_mem_frac), normalized /18
// (structs/funcs.go:259-297).  spread negates the exponent terms' sense
// by scoring the *unused* fraction.
void score_fit_dense(const float* capacity, const float* used,
                     const float* demand, int n_rows, int dims,
                     int spread, float* out_score) {
    for (int i = 0; i < n_rows; ++i) {
        const float* cap = capacity + (size_t)i * dims;
        const float* use = used + (size_t)i * dims;
        float total = 0.0f;
        // dimension 0 = cpu, 1 = memory (disk excluded, matching the
        // reference which scores cpu+mem only)
        for (int d = 0; d < 2; ++d) {
            float c = cap[d];
            if (c <= 0.0f) { total = 40.0f; break; }
            float free_frac = (c - (use[d] + demand[d])) / c;
            if (free_frac < 0.0f) free_frac = 0.0f;
            if (free_frac > 1.0f) free_frac = 1.0f;
            total += spread ? powf(10.0f, 1.0f - free_frac)
                            : powf(10.0f, free_frac);
        }
        float score = (20.0f - total) / 18.0f;
        if (score < 0.0f) score = 0.0f;
        if (score > 1.0f) score = 1.0f;
        out_score[i] = score;
    }
}

// ---------------------------------------------------------------------
// Port bitsets: words-per-row layout matches ClusterMatrix.port_words.

static inline int port_in(const int32_t* ports, int n, int32_t p) {
    for (int i = 0; i < n; ++i) if (ports[i] == p) return 1;
    return 0;
}

// ports_check: for one row, are all `ports` free (or in freed set)?
int32_t ports_check(const uint32_t* port_words, int words_per_row,
                    int row, const int32_t* ports, int n_ports,
                    const int32_t* freed, int n_freed) {
    const uint32_t* w = port_words + (size_t)row * words_per_row;
    for (int i = 0; i < n_ports; ++i) {
        int32_t p = ports[i];
        if (p < 0 || (p >> 5) >= words_per_row) return 0;
        // duplicate within the request?
        for (int j = 0; j < i; ++j) if (ports[j] == p) return 0;
        if ((w[p >> 5] >> (p & 31)) & 1u) {
            if (!port_in(freed, n_freed, p)) return 0;
        }
    }
    return 1;
}

void ports_set(uint32_t* port_words, int words_per_row, int row,
               const int32_t* ports, int n_ports, int value) {
    uint32_t* w = port_words + (size_t)row * words_per_row;
    for (int i = 0; i < n_ports; ++i) {
        int32_t p = ports[i];
        if (p < 0 || (p >> 5) >= words_per_row) continue;
        if (value) w[p >> 5] |= (1u << (p & 31));
        else       w[p >> 5] &= ~(1u << (p & 31));
    }
}

// ---------------------------------------------------------------------
// scatter_add: used[rows[k]] += deltas[k] — the columnar mirror's alloc
// usage maintenance (incremental UpsertPlanResults bookkeeping).
void scatter_add(float* used, int dims, const int32_t* rows,
                 const float* deltas, int n) {
    for (int k = 0; k < n; ++k) {
        float* dst = used + (size_t)rows[k] * dims;
        const float* src = deltas + (size_t)k * dims;
        for (int d = 0; d < dims; ++d) dst[d] += src[d];
    }
}

// ---------------------------------------------------------------------
// validate_plan: the EvaluatePool equivalent — validate P placement
// groups (one per node) in a single call.
//
// Inputs per group g:
//   rows[g]            node row (-1 = unknown node -> reject)
//   demand[g*dims..]   summed placement demand on that node
//   freed[g*dims..]    resources freed by this plan's stops on that node
//   group port ranges  ports_off[g]..ports_off[g+1] into ports[]
//   freed port ranges  freed_off[g]..freed_off[g+1] into freed_ports[]
// Output: ok[g] = 1 if the node can take the placements.
void validate_plan(const float* capacity, const float* used,
                   const uint32_t* port_words, int words_per_row,
                   int dims,
                   const int32_t* rows, const float* demand,
                   const float* freed, const int32_t* ports,
                   const int32_t* ports_off, const int32_t* freed_ports,
                   const int32_t* freed_off, int n_groups,
                   uint8_t* ok) {
    for (int g = 0; g < n_groups; ++g) {
        int32_t row = rows[g];
        if (row < 0) { ok[g] = 0; continue; }
        const float* cap = capacity + (size_t)row * dims;
        const float* use = used + (size_t)row * dims;
        const float* dem = demand + (size_t)g * dims;
        const float* fre = freed + (size_t)g * dims;
        uint8_t fits = 1;
        for (int d = 0; d < dims; ++d) {
            if (use[d] + dem[d] - fre[d] > cap[d] + 1e-6f) {
                fits = 0; break;
            }
        }
        if (!fits) { ok[g] = 0; continue; }
        ok[g] = (uint8_t)ports_check(
            port_words, words_per_row, row,
            ports + ports_off[g], ports_off[g + 1] - ports_off[g],
            freed_ports + freed_off[g],
            freed_off[g + 1] - freed_off[g]);
    }
}

// ---------------------------------------------------------------------
// Bulk alloc materialization: the host-side commit path's per-alloc
// Python loop replaced by one call per dispatch.
//
// expand_pairs: flatten the resolved sparse bulk output — (row, count,
// score) triples from the device kernel — into per-alloc row/score
// arrays in placement order.  Returns the number of allocs written, or
// -1 if the total would exceed `cap` (caller sized the outputs wrong).
int32_t expand_pairs(const int32_t* rows, const int32_t* counts,
                     const float* scores, int n,
                     int32_t* out_rows, float* out_scores, int32_t cap) {
    int32_t w = 0;
    for (int k = 0; k < n; ++k) {
        int32_t c = counts[k];
        if (c <= 0) continue;
        if (w + c > cap) return -1;
        int32_t r = rows[k];
        float s = scores[k];
        for (int32_t j = 0; j < c; ++j) {
            out_rows[w] = r;
            out_scores[w] = s;
            ++w;
        }
    }
    return w;
}

// format_uuids: batch-format n 16-byte random blocks into the canonical
// 36-char 8-4-4-4-12 form (same layout as utils.generate_uuid, which
// hex-formats os.urandom(16)).  out must hold 36*n bytes.
void format_uuids(const uint8_t* rnd, int n, char* out) {
    static const char hexd[] = "0123456789abcdef";
    for (int i = 0; i < n; ++i) {
        const uint8_t* b = rnd + (size_t)i * 16;
        char* o = out + (size_t)i * 36;
        int pos = 0;
        for (int j = 0; j < 16; ++j) {
            uint8_t v = b[j];
            *o++ = hexd[v >> 4];
            *o++ = hexd[v & 15];
            pos += 2;
            if (pos == 8 || pos == 12 || pos == 16 || pos == 20)
                *o++ = '-';
        }
    }
}

// scatter_add_rank1: used[rows[k]] += counts[k] * demand — the resolve
// path's overlay/usage update for a bulk eval, without materializing the
// [K, dims] delta matrix on the Python side.
void scatter_add_rank1(float* used, int dims, const int32_t* rows,
                       const int32_t* counts, const float* demand,
                       int n) {
    for (int k = 0; k < n; ++k) {
        float c = (float)counts[k];
        if (c == 0.0f) continue;
        float* dst = used + (size_t)rows[k] * dims;
        for (int d = 0; d < dims; ++d) dst[d] += c * demand[d];
    }
}

int32_t nomad_native_abi_version(void) { return 2; }

}  // extern "C"
