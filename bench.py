"""Benchmark runner (BASELINE.json scenarios).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Headline: end-to-end scheduling throughput (allocs placed per second through
the full eval->reconcile->dense-kernel->plan->applier spine) on the
'1K nodes / 5K batch allocations, binpack' configuration (BASELINE.json
configs[1]).  vs_baseline compares against the north-star C2M rate
(1M allocs / 30 s = 33,333 allocs/s on a v5e-8; this runs on ONE chip).

Supplementary numbers (kernel-only placement rate at C2M node scale) go to
stderr so the driver still sees a single JSON line on stdout.
"""
import json
import os
import sys
import time

if os.environ.get("BENCH_FORCE_CPU") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def bench_e2e_1k_nodes_5k_allocs():
    from nomad_tpu import mock
    from nomad_tpu.scheduler.testing import Harness

    h = Harness()
    t0 = time.time()
    for _ in range(1000):
        h.store.upsert_node(h.next_index(), mock.node())
    log(f"world build (1000 nodes): {time.time()-t0:.2f}s")

    jobs = []
    for _ in range(50):
        j = mock.batch_job()
        j.task_groups[0].count = 100
        h.store.upsert_job(h.next_index(), j)
        jobs.append(j)

    # warm the jit cache with one eval shape
    warm = mock.batch_job()
    warm.task_groups[0].count = 100
    h.store.upsert_job(h.next_index(), warm)
    h.process("batch", mock.eval(job_id=warm.id, type="batch"))

    t0 = time.time()
    for j in jobs:
        ev = mock.eval(job_id=j.id, type="batch", priority=j.priority)
        h.process("batch", ev)
    dt = time.time() - t0

    placed = sum(len(h.store.allocs_by_job("default", j.id)) for j in jobs)
    log(f"e2e: placed {placed} allocs in {dt:.2f}s "
        f"({placed/dt:.0f} allocs/s, {50/dt:.1f} evals/s)")
    assert placed == 5000, placed
    return placed / dt


def bench_kernel_c2m_scale():
    """Kernel-only: one dense placement scan at 10K-node scale."""
    import numpy as np

    from nomad_tpu import mock
    from nomad_tpu.encode import ClusterMatrix
    from nomad_tpu.ops.place import place_eval
    from nomad_tpu.scheduler.stack import DenseStack

    cm = ClusterMatrix(initial_rows=16384)
    t0 = time.time()
    for i in range(10000):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 50}"
        cm.upsert_node(n)
    log(f"world build (10000 nodes): {time.time()-t0:.2f}s")

    job = mock.job()
    job.task_groups[0].count = 1024
    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg) for tg in job.task_groups]
    inp = stack.build_inputs(job, groups, [0] * 1024, {})

    res = stack.place(inp)          # compile + run
    t0 = time.time()
    res = stack.place(inp)
    dt = time.time() - t0
    placed = int((res.node[:1024] >= 0).sum())
    log(f"kernel: {placed} placements over 10K nodes in {dt:.3f}s "
        f"({placed/dt:.0f} placements/s on one chip)")
    return placed / dt


def main():
    e2e_rate = bench_e2e_1k_nodes_5k_allocs()
    try:
        kernel_rate = bench_kernel_c2m_scale()
    except Exception as e:          # noqa: BLE001
        log("kernel bench failed:", e)
        kernel_rate = 0.0

    target = 1_000_000 / 30.0       # north-star C2M rate (v5e-8)
    print(json.dumps({
        "metric": "e2e_allocs_per_sec_1knodes_5kallocs",
        "value": round(e2e_rate, 1),
        "unit": "allocs/s",
        "vs_baseline": round(e2e_rate / target, 4),
    }), flush=True)


if __name__ == "__main__":
    main()
