"""Benchmark runner (BASELINE.json scenarios).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Headline: end-to-end scheduling throughput through the FULL server spine —
job register -> eval broker -> N concurrent scheduler workers -> batched
device dispatch (PlacementEngine) -> plan queue -> serialized applier ->
state store — on the '1K nodes / 5K batch allocations, binpack'
configuration (BASELINE.json configs[1]).  vs_baseline compares against
the north-star C2M rate (1M allocs / 30 s = 33,333 allocs/s on a v5e-8;
this runs on ONE chip).

Supplementary numbers (other BASELINE.json scenarios, kernel-only rate at
C2M node scale) go to stderr so the driver still sees a single JSON line
on stdout.
"""
import json
import os
import sys
import time

if os.environ.get("BENCH_FORCE_CPU") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _wait_allocs(store, jobs, want, timeout=300.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        placed = sum(len(store.allocs_by_job("default", j.id)) for j in jobs)
        if placed >= want:
            return placed
        time.sleep(0.01)
    return sum(len(store.allocs_by_job("default", j.id)) for j in jobs)


def bench_e2e_spine(n_nodes=1000, n_jobs=50, count=100, workers=16):
    """configs[1]: 1K nodes / 5K batch allocs, binpack, through the spine."""
    from nomad_tpu import mock
    from nomad_tpu.core.server import Server, ServerConfig

    s = Server(ServerConfig(num_schedulers=workers, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    t0 = time.time()
    for _ in range(n_nodes):
        s.register_node(mock.node())
    log(f"world build ({n_nodes} nodes): {time.time()-t0:.2f}s")

    # warm the jit caches: single-eval shape AND the batched shape
    warm = []
    for _ in range(9):
        j = mock.batch_job()
        j.task_groups[0].count = count
        warm.append(j)
        s.register_job(j)
    _wait_allocs(s.store, warm, 9 * count)
    log(f"warm: {time.time()-t0:.2f}s")

    jobs = []
    t0 = time.time()
    for _ in range(n_jobs):
        j = mock.batch_job()
        j.task_groups[0].count = count
        jobs.append(j)
        s.register_job(j)
    placed = _wait_allocs(s.store, jobs, n_jobs * count)
    dt = time.time() - t0

    from nomad_tpu.parallel.engine import get_engine
    eng = get_engine()
    if eng:
        log(f"engine stats: {eng.stats}")
    s.stop()
    log(f"e2e spine: placed {placed} allocs in {dt:.2f}s "
        f"({placed/dt:.0f} allocs/s, {n_jobs/dt:.1f} evals/s, "
        f"{workers} workers)")
    assert placed == n_jobs * count, placed
    return placed / dt


def bench_kernel_c2m_scale():
    """Kernel-only: one dense placement scan at 10K-node scale."""
    from nomad_tpu import mock
    from nomad_tpu.encode import ClusterMatrix
    from nomad_tpu.scheduler.stack import DenseStack

    cm = ClusterMatrix(initial_rows=16384)
    t0 = time.time()
    for i in range(10000):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 50}"
        cm.upsert_node(n)
    log(f"world build (10000 nodes): {time.time()-t0:.2f}s")

    job = mock.job()
    job.task_groups[0].count = 1024
    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg) for tg in job.task_groups]
    inp = stack.build_inputs(job, groups, [0] * 1024, {})

    res = stack.place(inp)          # compile + run
    t0 = time.time()
    res = stack.place(inp)
    dt = time.time() - t0
    placed = int((res.node[:1024] >= 0).sum())
    log(f"kernel: {placed} placements over 10K nodes in {dt:.3f}s "
        f"({placed/dt:.0f} placements/s on one chip)")
    return placed / dt


def main():
    e2e_rate = bench_e2e_spine()
    try:
        kernel_rate = bench_kernel_c2m_scale()
    except Exception as e:          # noqa: BLE001
        log("kernel bench failed:", e)
        kernel_rate = 0.0

    target = 1_000_000 / 30.0       # north-star C2M rate (v5e-8)
    print(json.dumps({
        "metric": "e2e_spine_allocs_per_sec_1knodes_5kallocs",
        "value": round(e2e_rate, 1),
        "unit": "allocs/s",
        "vs_baseline": round(e2e_rate / target, 4),
    }), flush=True)


if __name__ == "__main__":
    main()
