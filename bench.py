"""Benchmark runner (BASELINE.json scenarios).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Headline: the north-star C2M-1M shape at its ACTUAL size — 10K nodes /
1M allocations (10,000 jobs x 10 task groups x count 10) through the
FULL server spine: job register -> eval broker -> 48 concurrent
scheduler workers -> batched device dispatch (PlacementEngine) -> plan
queue -> batched pipelined applier -> state store.  vs_baseline compares
against the north-star C2M rate (1M allocs / 30 s = 33,333 allocs/s on a
v5e-8; this runs on ONE chip).

`--smoke` runs the same shape shrunk to seconds (small world) for CI —
tests/test_commit_pipeline.py invokes it so commit-path throughput
regressions fail tier-1 instead of only showing up in BENCH_r*.json.

Supplementary numbers (other BASELINE.json scenarios, kernel-only rate at
C2M node scale) go to stderr so the driver still sees a single JSON line
on stdout.
"""
import json
import os
import sys
import tempfile
import threading
import time

if os.environ.get("BENCH_FORCE_CPU") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# per-scenario plan.submit/plan.evaluate latency summaries, folded into
# the stdout BENCH JSON so the latency trajectory (ROADMAP item 3) is
# regression-gatable, not just logged
_PLAN_STATS: dict = {}

# per-scenario steady-state purity report (transfer guard + recompile
# budget + world re-upload watch), folded into the BENCH JSON; any
# violation fails the --smoke leg.  NOMAD_TPU_BENCH_GUARD=0 opts out.
_STEADY_STATE: dict = {}

# per-scenario kernel-stage attribution (stage_probe.device_stages):
# measured device_s split across feasibility/fit/score/argmax/scatter,
# folded into the BENCH JSON so BENCH_r06 names the stage to fuse first
_DEVICE_STAGES: dict = {}

# per-scenario engine-stats snapshot taken before the server stops; the
# --smoke fused-path gate reads it after the run (fused dispatch means
# one device dispatch per wave group: bulk_parts == bulk_groups)
_ENGINE_SNAP: dict = {}


class _SteadyGate:
    """Arms the steady-state dispatch discipline around a measured
    window, AFTER warmup: jax's transfer guard flips to "disallow" (any
    implicit host<->device transfer raises inside the dispatch loop),
    the recompile budget snapshots every registered kernel's jit cache
    (post-warmup growth is a shape-bucketing regression), and
    DeviceWorld stats are diffed (a full [N, R] re-upload after the
    epoch's first means the scatter path leaked).  Results land in
    `_STEADY_STATE[scenario]`."""

    def __init__(self, scenario: str):
        self.scenario = scenario
        self.enabled = \
            os.environ.get("NOMAD_TPU_BENCH_GUARD", "1") != "0"
        self._guard = None
        self._eng = None

    def __enter__(self):
        if not self.enabled:
            return self
        from nomad_tpu.analysis import recompile, transfer_purity
        from nomad_tpu.parallel.engine import get_engine
        self._eng = get_engine()
        self.budget = recompile.Budget()
        self._world0 = self._eng.world_stats() if self._eng else {}
        self._eng0 = dict(self._eng.stats) if self._eng else {}
        self._guard = transfer_purity.steady_state_guard()
        self._guard.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._guard is not None:
            self._guard.__exit__(exc_type, exc, tb)
        if not self.enabled or exc_type is not None:
            return False
        from nomad_tpu.telemetry import global_metrics
        rep = self.budget.report()
        wstats = self._eng.world_stats() if self._eng else {}
        reuploads = wstats.get("steady_reuploads", 0) - \
            self._world0.get("steady_reuploads", 0)
        violations = self.budget.violations()
        if reuploads > 0:
            violations.append(
                f"{reuploads} full world re-upload(s) during the "
                f"measured window (steady state must scatter rows only)")
        estats = dict(self._eng.stats) if self._eng else {}
        donated = estats.get("donated_carries", 0) - \
            self._eng0.get("donated_carries", 0)
        bulk_parts = estats.get("bulk_parts", 0) - \
            self._eng0.get("bulk_parts", 0)
        adopts = wstats.get("basis_adopts", 0) - \
            self._world0.get("basis_adopts", 0)
        if self._eng is not None and getattr(self._eng, "donate", False) \
                and bulk_parts > 0 and (donated <= 0 or adopts <= 0):
            violations.append(
                f"donation enabled but {bulk_parts} bulk dispatch(es) "
                f"produced donated_carries={donated} basis_adopts={adopts} "
                f"(steady state must keep the usage basis resident via "
                f"donated carries, not re-download + re-upload it)")
        self.budget.publish(global_metrics)
        _STEADY_STATE[self.scenario] = {
            "transfer_guard": "disallow",
            "recompiled": rep["recompiled"],
            "compile_events": rep["compile_events"],
            "steady_reuploads": reuploads,
            "donated_carries": donated,
            "basis_adopts": adopts,
            "world": wstats,
            "violations": violations,
        }
        log(f"{self.scenario} steady-state: "
            f"compiles={rep['compile_events']} reuploads={reuploads} "
            f"violations={violations or 'none'}")
        return False


def _log_plan_submit(scenario: str) -> dict:
    """Per-scenario p50/p99 plan-submit latency (the BASELINE.json metric
    is evals/sec + p99 plan-submit; reference metric nomad.nomad.plan.submit).
    Resets the series so scenarios don't pollute each other."""
    from nomad_tpu.telemetry import global_metrics
    s = global_metrics.take_sample("nomad.plan.submit")
    ev = global_metrics.take_sample("nomad.plan.evaluate")

    def _ms(m):
        return {"p50": round(m["p50"], 2), "p99": round(m["p99"], 2),
                "mean": round(m["mean"], 2), "max": round(m["max"], 2),
                "count": m["count"]}
    _PLAN_STATS[scenario] = {"submit_ms": _ms(s), "evaluate_ms": _ms(ev)}
    log(f"{scenario}: plan.submit p50 {s['p50']:.1f} / p99 {s['p99']:.1f} ms "
        f"(mean {s['mean']:.1f} ms, n={s['count']}); "
        f"plan.evaluate p50 {ev['p50']:.1f} / p99 {ev['p99']:.1f} ms")
    return _PLAN_STATS[scenario]


def _wait_allocs(store, jobs, want, timeout=300.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        placed = sum(len(store.allocs_by_job("default", j.id)) for j in jobs)
        if placed >= want:
            return placed
        time.sleep(0.01)
    return sum(len(store.allocs_by_job("default", j.id)) for j in jobs)


def bench_e2e_spine(n_nodes=1000, n_jobs=50, count=100, workers=48):
    """configs[1]: 1K nodes / 5K batch allocs, binpack, through the spine."""
    from nomad_tpu import mock
    from nomad_tpu.core.server import Server, ServerConfig

    s = Server(ServerConfig(num_schedulers=workers, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    t0 = time.time()
    for _ in range(n_nodes):
        s.register_node(mock.node())
    log(f"world build ({n_nodes} nodes): {time.time()-t0:.2f}s")

    # deterministic kernel warm: compile EVERY E-bucket variant of both
    # dispatch kernels for the run's shapes (organic warming depends on
    # queue timing and can leave a bucket to compile mid-measurement);
    # warmup discards results, so the measured world stays empty
    t0 = time.time()
    wj = mock.batch_job()
    wj.task_groups[0].count = count
    _warm_engine(s, scan_job=wj, bulk_job=wj)
    log(f"warm: {time.time()-t0:.2f}s")

    jobs = []
    t0 = time.time()
    for _ in range(n_jobs):
        j = mock.batch_job()
        j.task_groups[0].count = count
        jobs.append(j)
        s.register_job(j)
    placed = _wait_allocs(s.store, jobs, n_jobs * count)
    dt = time.time() - t0

    from nomad_tpu.parallel.engine import get_engine
    eng = get_engine()
    if eng:
        log(f"engine stats: {eng.stats}")
    s.stop()
    log(f"e2e spine: placed {placed} allocs in {dt:.2f}s "
        f"({placed/dt:.0f} allocs/s, {n_jobs/dt:.1f} evals/s, "
        f"{workers} workers)")
    _log_plan_submit("e2e_spine")
    assert placed == n_jobs * count, placed
    return placed / dt


def _batch_job(count, cpu=100, mem=64):
    from nomad_tpu import mock
    j = mock.batch_job()
    tg = j.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    tg.ephemeral_disk.size_mb = 0
    return j


def _service_job(count, cpu=100, mem=64, spread=True, priority=None):
    from nomad_tpu import mock
    from nomad_tpu.structs.job import Affinity, Spread
    j = mock.job()
    tg = j.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = cpu
    tg.tasks[0].resources.memory_mb = mem
    tg.ephemeral_disk.size_mb = 0
    if spread:
        tg.spreads = [Spread("${attr.rack}", 50, ())]
        tg.affinities = [Affinity("${node.datacenter}", "dc1", "=", 50)]
    if priority is not None:
        j.priority = priority
    return j


def _server(workers=8):
    from nomad_tpu.core.server import Server, ServerConfig
    s = Server(ServerConfig(num_schedulers=workers, heartbeat_ttl=3600.0,
                            gc_interval=3600.0))
    s.start()
    return s


def _fill_nodes(s, n, racks=50, node_fn=None):
    from nomad_tpu import mock
    for i in range(n):
        node = mock.node()
        node.attributes["rack"] = f"r{i % racks}"
        if node_fn:
            node_fn(node, i)
        s.store.upsert_node(s.next_index(), node)


def _warm_engine(s, scan_job=None, bulk_job=None):
    """Precompile every E-bucket kernel variant for THIS server's matrix
    shapes (engine.warmup) so XLA compiles never land inside a measured
    window — compiles are shape-keyed, so each world size needs its own
    warm."""
    import numpy as np

    from nomad_tpu.parallel.engine import get_engine
    from nomad_tpu.scheduler.stack import DenseStack
    eng = get_engine()
    if eng is None:
        return
    cm = s.store.matrix
    inputs = None
    bulk = None
    if scan_job is not None:
        st = DenseStack(cm)
        groups = [st.compile_group(scan_job, tg)
                  for tg in scan_job.task_groups]
        count = max(scan_job.task_groups[0].count, 1)
        inputs = st.build_inputs(scan_job, groups, [0] * count, {})
    if bulk_job is not None:
        st = DenseStack(cm)
        g = st.compile_group(bulk_job, bulk_job.task_groups[0])
        N = cm.n_rows
        bulk = dict(
            feasible=g.feasible, affinity=g.affinity.astype(np.float32),
            has_affinity=bool(g.has_affinity),
            desired=max(bulk_job.task_groups[0].count, 1),
            penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
            demand=g.demand.astype(np.float32),
            count=bulk_job.task_groups[0].count)
    eng.warmup(cm, inputs=inputs, bulk=bulk)


def bench_dev_agent_sim():
    """configs[0]: 1 service job, 3 task groups, 5-node dev-agent sim —
    end-to-end registration->placement latency."""
    from nomad_tpu import mock
    s = _server(workers=2)
    try:
        _fill_nodes(s, 5)
        lat = []
        for trial in range(6):
            j = mock.job()
            tgs = []
            for k in range(3):
                tg = j.task_groups[0].copy() if k else j.task_groups[0]
                tg.name = f"g{k}"
                tg.count = 2
                tgs.append(tg)
            j.task_groups = tgs
            t0 = time.time()
            s.register_job(j)
            placed = _wait_allocs(s.store, [j], 6, timeout=30)
            lat.append(time.time() - t0)
            assert placed == 6, placed
        lat.sort()
        log(f"dev-agent sim: p50 register->placed latency "
            f"{lat[len(lat)//2]*1000:.0f} ms (6 allocs, 3 tgs, 5 nodes)")
        _log_plan_submit("dev_agent")
        return lat[len(lat)//2]
    finally:
        s.stop()


def bench_c2m(n_nodes=10000, n_batch=96, batch_count=1000,
              n_service=40, service_count=100, workers=48):
    """configs[2]: C2M — 10K nodes / 100K allocs, mixed service+batch,
    spread + node-affinity scoring, through the full spine."""
    s = _server(workers=workers)
    try:
        t0 = time.time()
        _fill_nodes(s, n_nodes)
        log(f"C2M world build ({n_nodes} nodes): {time.time()-t0:.1f}s")
        _warm_engine(s, scan_job=_service_job(service_count),
                     bulk_job=_batch_job(batch_count))
        w1, w2 = _batch_job(100), _service_job(50)
        s.register_job(w1)
        s.register_job(w2)
        _wait_allocs(s.store, [w1, w2], 150, timeout=300)
        log(f"C2M warm done: {time.time()-t0:.1f}s")

        jobs = [_batch_job(batch_count) for _ in range(n_batch)] + \
               [_service_job(service_count) for _ in range(n_service)]
        want = n_batch * batch_count + n_service * service_count
        t0 = time.time()
        for j in jobs:
            s.register_job(j)
        placed = _wait_allocs(s.store, jobs, want, timeout=600)
        dt = time.time() - t0
        log(f"C2M spine: {placed}/{want} allocs in {dt:.1f}s "
            f"({placed/dt:.0f} allocs/s)")
        _log_plan_submit("c2m")
        return placed / dt
    finally:
        s.stop()


def bench_c2m_1m(n_nodes=10000, n_jobs=10000, groups_per_job=10,
                 group_count=10, workers=48, deadline_s=3600.0,
                 scenario="c2m_1m"):
    """The north-star C2M at its ACTUAL size (BASELINE.json configs[2] /
    north_star): 1M allocations over 100K task groups on 10K nodes,
    through the full spine.  10,000 jobs x 10 task groups x count 10;
    allocs sized so the cluster holds them (30 cpu / 60 mb each)."""
    from nomad_tpu import mock

    s = _server(workers=workers)
    try:
        t0 = time.time()
        _fill_nodes(s, n_nodes)
        log(f"{scenario} world build ({n_nodes} nodes): "
            f"{time.time()-t0:.1f}s")

        def make_job():
            j = mock.batch_job()
            base = j.task_groups[0]
            base.count = group_count
            base.tasks[0].resources.cpu = 30
            base.tasks[0].resources.memory_mb = 60
            base.ephemeral_disk.size_mb = 0
            tgs = []
            for k in range(groups_per_job):
                tg = base.copy() if k else base
                tg.name = f"g{k}"
                tgs.append(tg)
            j.task_groups = tgs
            return j

        t0 = time.time()
        _warm_engine(s, scan_job=make_job(), bulk_job=make_job())
        wj = make_job()
        s.register_job(wj)
        _wait_allocs(s.store, [wj], groups_per_job * group_count,
                     timeout=300)
        log(f"{scenario} warm: {time.time()-t0:.1f}s")

        want = n_jobs * groups_per_job * group_count
        base_allocs = len(s.store._allocs)
        t0 = time.time()
        # measured window runs under the steady-state purity gate: the
        # warm epoch's world is resident, so from here on the dispatch
        # loop must scatter rows, never re-ship or recompile
        with _SteadyGate(scenario):
            for _ in range(n_jobs):
                s.register_job(make_job())
            reg_dt = time.time() - t0
            log(f"{scenario} registered {n_jobs} jobs in {reg_dt:.1f}s")
            deadline = time.time() + deadline_s
            placed = 0
            while time.time() < deadline:
                placed = len(s.store._allocs) - base_allocs
                if placed >= want:
                    break
                time.sleep(0.2 if deadline_s < 600 else 1.0)
        dt = time.time() - t0
        log(f"{scenario} spine: {placed}/{want} allocs in {dt:.1f}s "
            f"({placed/dt:.0f} allocs/s on one chip; "
            f"{n_jobs * groups_per_job} task groups)")
        if s.applier.stats.get("coalesced"):
            log(f"{scenario} applier stats: {s.applier.stats}")
        from nomad_tpu.parallel.engine import get_engine
        eng = get_engine()
        if eng:
            log(f"{scenario} engine stats: {eng.stats}")
            _ENGINE_SNAP[scenario] = dict(eng.stats)
            # stage attribution runs strictly AFTER the steady gate has
            # exited: the probe compiles its own kernels and moves data,
            # which must not count against the gate's purity budgets
            try:
                from nomad_tpu.ops.place import fill_grid_for
                from nomad_tpu.parallel import stage_probe
                # tentpole metric: host upload/dispatch windows for wave
                # N+1 hidden under wave N's in-flight device windows
                pipe_overlap = stage_probe.interval_overlap_s(
                    list(eng.upload_windows),
                    list(eng.device_windows))
                # device time the commit pipeline hid under raft
                # append + fsync: engine device-blocked windows against
                # the applier's commit windows
                commit_overlap = stage_probe.interval_overlap_s(
                    list(eng.device_windows),
                    list(s.applier.commit_windows))
                ds = stage_probe.device_stages(
                    eng.stats, n_nodes,
                    fill_grid=fill_grid_for(group_count),
                    pipeline_overlap_s=pipe_overlap,
                    commit_overlap_s=commit_overlap,
                    wave=eng.stats)
                if ds is not None:
                    _DEVICE_STAGES[scenario] = ds
                    log(f"{scenario} device stages: dominant="
                        f"{ds['dominant_stage']} {ds['stages_s']} "
                        f"pipeline_overlap={ds['pipeline_overlap_s']}s "
                        f"commit_overlap={ds['commit_overlap_s']}s "
                        f"wave={ds.get('wave')} fused={ds['fused']}")
            except Exception as e:  # noqa: BLE001
                log(f"{scenario} stage probe failed: {e}")
        _log_plan_submit(scenario)
        return placed / dt, placed, want
    finally:
        s.stop()


def bench_smoke(workers=8):
    """The C2M-1M shape shrunk to CI scale: a small world that finishes
    in seconds, exercising the identical commit pipeline (bulk kernel ->
    native materialization -> plan queue -> batched applier -> store).
    Returns allocs/s; tests assert a generous floor so only real
    commit-path regressions trip it."""
    return bench_c2m_1m(n_nodes=128, n_jobs=30, groups_per_job=5,
                        group_count=4, workers=workers, deadline_s=240.0,
                        scenario="smoke")


def _smoke_trace_checks() -> dict:
    """Tracing leg of --smoke (r12): (1) with no tracer installed the
    guard every hot site uses must cost one module-attribute load —
    measured here and capped at 1 us/op, which is "nil" against a
    multi-ms plan submit; (2) a fully sampled run through the real spine
    must produce causally linked spans that export as well-formed
    Chrome-trace JSON (the file Perfetto loads)."""
    from nomad_tpu import mock, tracing

    out = {"disabled_overhead_ns_per_op": None, "spans": 0,
           "perfetto_file": "", "perfetto_events": 0, "violations": []}
    if tracing.active is not None:
        tracing.uninstall()
    n = 1_000_000
    t0 = time.perf_counter()
    for _ in range(n):
        if tracing.active is not None:  # the exact hot-site idiom
            raise AssertionError("tracer installed mid-check")
    per_ns = (time.perf_counter() - t0) / n * 1e9
    out["disabled_overhead_ns_per_op"] = round(per_ns, 1)
    if per_ns > 1000.0:
        out["violations"].append(
            f"disabled-tracing guard costs {per_ns:.0f} ns/op (> 1 us)")

    tracing.install(tracing.Tracer(sample_rate=1.0, seed=7))
    s = _server(workers=4)
    try:
        tracer = tracing.active
        for _ in range(32):
            s.register_node(mock.node())
        j = mock.batch_job()
        j.task_groups[0].count = 8
        # bench drives the server directly (no HTTP front), so open the
        # root span the agent's HTTP layer would normally start
        ctx = tracer.new_context()
        root = tracer.start(ctx, "bench.register_job", s.name)
        prev = tracing.bind(tracer.child_ctx(ctx, root))
        try:
            s.register_job(j)
        finally:
            tracer.finish(root)
            tracing.bind(prev)
        _wait_allocs(s.store, [j], 8, timeout=60)
        time.sleep(0.2)     # let the applier's observe-time spans land
        spans = tracer.spans(ctx["t"])
        out["spans"] = len(spans)
        names = {sp.name for sp in spans}
        for want_name in ("bench.register_job", "plan.submit",
                          "plan.evaluate", "raft.fsm_apply"):
            if want_name not in names:
                out["violations"].append(
                    f"sampled run missing span {want_name!r} "
                    f"(got {sorted(names)})")
        doc = tracing.chrome_trace([sp.to_dict() for sp in spans])
        evs = doc.get("traceEvents", [])
        out["perfetto_events"] = len(evs)
        if not any(e.get("ph") == "X" and "ts" in e and "dur" in e
                   for e in evs):
            out["violations"].append("chrome trace has no X events")
        path = os.path.join(tempfile.gettempdir(),
                            "nomad_tpu_smoke_trace.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        with open(path) as f:
            reloaded = json.load(f)
        if reloaded.get("displayTimeUnit") != "ms" or \
                len(reloaded.get("traceEvents", [])) != len(evs):
            out["violations"].append("perfetto file did not round-trip")
        else:
            out["perfetto_file"] = path
    finally:
        s.stop()
        tracing.uninstall()
    log(f"trace checks: {out['disabled_overhead_ns_per_op']} ns/op "
        f"disabled; {out['spans']} spans sampled; "
        f"{out['perfetto_events']} perfetto events")
    return out


def bench_serving_plane(n_watchers=1200, n_blockers=12, idle_samples=200,
                        busy_samples=400, scenario="serving_plane"):
    """Serving-plane scenario: N concurrent event watchers (bounded
    broker subscriptions) plus follower blocking queries over HTTP on a
    3-server cluster, while a commit spine registers jobs through the
    leader.  Reports follower lease-read p50/p99 idle vs busy and the
    broker's drop/eviction counters; the hard invariant is that no
    subscriber queue ever exceeds its bound (zero unbounded growth)."""
    from nomad_tpu import mock
    from nomad_tpu.agent.http import HTTPServer
    from nomad_tpu.core.cluster import Cluster

    class _Shim:
        """agent surface for a per-server HTTP listener"""

        def __init__(self, server):
            self.server = server

        def rpc(self, method, args, consistency=None):
            return self.server.rpc_leader(method, args)

    c = Cluster(3)
    c.start()
    stop = threading.Event()
    threads = []
    http = None
    try:
        leader = c.leader()
        follower = c.followers()[0]
        deadline = time.time() + 30.0
        while not leader.raft.lease_valid() and time.time() < deadline:
            time.sleep(0.02)

        # watchers: bounded subscriptions on the follower's broker —
        # subscriptions are objects, not threads, so >=1K of them is
        # cheap; a small consumer pool drains them round-robin
        subs = [follower.event_broker.subscribe({"*": ["*"]}, max_queue=64)
                for _ in range(n_watchers)]
        consumed = [0] * 4

        def drain(slot, chunk):
            while not stop.is_set():
                idle = True
                for sub in chunk:
                    while True:
                        ev = sub.next(timeout=0.0)
                        if ev is None:
                            break
                        idle = False
                        consumed[slot] += 1
                if idle:
                    time.sleep(0.005)

        for k in range(4):
            t = threading.Thread(target=drain, args=(k, subs[k::4]),
                                 daemon=True)
            t.start()
            threads.append(t)

        # follower blocking queries through the real HTTP path
        # (?index&wait): each loop parks on the follower's store index
        # and must wake with a reply index >= the one it gave
        http = HTTPServer(_Shim(follower), port=0)
        http.start()
        wakeups = [0] * n_blockers
        block_errs = [0]

        def blocker(slot):
            import urllib.request
            while not stop.is_set():
                idx = follower.store.latest_index
                url = (f"http://127.0.0.1:{http.port}/v1/jobs"
                       f"?index={idx}&wait=300ms")
                try:
                    with urllib.request.urlopen(url, timeout=15.0) as r:
                        got = int(r.headers["X-Nomad-Index"])
                        r.read()
                    if got < idx:
                        block_errs[0] += 1
                    wakeups[slot] += 1
                except Exception:       # noqa: BLE001
                    if not stop.is_set():
                        block_errs[0] += 1

        for k in range(n_blockers):
            t = threading.Thread(target=blocker, args=(k,), daemon=True)
            t.start()
            threads.append(t)

        def sample(n):
            lats = []
            for _ in range(n):
                t0 = time.perf_counter()
                follower.read("Job.List", {}, consistency="default")
                lats.append(time.perf_counter() - t0)
            lats.sort()
            return lats

        # idle baseline: watchers + blockers attached, no commit spine
        # (a short discarded warmup absorbs first-read cold paths so the
        # idle p99 is a real steady-state denominator)
        sample(20)
        idle = sample(idle_samples)

        # commit spine on the leader (register -> eval -> schedule ->
        # raft commit -> store apply -> broker publish on every server)
        def spine():
            while not stop.is_set():
                j = mock.batch_job()
                j.task_groups[0].count = 10
                try:
                    leader.register_job(j)
                except Exception:       # noqa: BLE001
                    pass
                time.sleep(0.002)

        t = threading.Thread(target=spine, daemon=True)
        t.start()
        threads.append(t)
        time.sleep(0.3)                 # let the spine reach the broker
        busy = sample(busy_samples)

        stop.set()
        for t in threads:
            t.join(10.0)

        st = follower.event_broker.stats()
        max_q = max((s["queue_len"] for s in st["subs"]), default=0)
        bounded = all(s["queue_len"] <= s["max_queue"] for s in st["subs"])
        result = {
            "watchers": n_watchers,
            "blockers": n_blockers,
            "events_consumed": sum(consumed),
            "blocking_wakeups": sum(wakeups),
            "blocking_errors": block_errs[0],
            "read_p50_idle_ms": round(idle[len(idle) // 2] * 1000, 3),
            "read_p99_idle_ms": round(idle[int(len(idle) * .99)] * 1000, 3),
            "read_p50_busy_ms": round(busy[len(busy) // 2] * 1000, 3),
            "read_p99_busy_ms": round(busy[int(len(busy) * .99)] * 1000, 3),
            "dropped": sum(s["dropped"] for s in st["subs"]),
            "evictions": sum(s["evictions"] for s in st["subs"]),
            "max_queue_len": max_q,
            "bounded": bounded,
            "lease_reads": True,
        }
        log(f"{scenario}: {n_watchers} watchers / {n_blockers} blockers; "
            f"read p50/p99 idle {result['read_p50_idle_ms']}/"
            f"{result['read_p99_idle_ms']} ms, busy "
            f"{result['read_p50_busy_ms']}/{result['read_p99_busy_ms']} ms; "
            f"consumed {result['events_consumed']} events, "
            f"{result['blocking_wakeups']} blocking wakeups, "
            f"dropped {result['dropped']} (evictions "
            f"{result['evictions']}), max queue {max_q}, "
            f"bounded={bounded}")
        return result
    finally:
        stop.set()
        if http is not None:
            http.stop()
        c.stop()


def bench_scan_spread(n_nodes=10000, n_jobs=60, count=100, workers=48):
    """The SCAN path at C2M shape: spread+affinity service jobs (the
    workload class the bulk wavefront excludes — spreads are active), so
    every placement goes through place_batch_packed_jit's chained
    lax.scan.  Reports allocs/s + batched_evals so the path's coverage
    is visible (VERDICT r4 weak #4)."""
    from nomad_tpu.parallel.engine import get_engine
    s = _server(workers=workers)
    try:
        t0 = time.time()
        _fill_nodes(s, n_nodes)
        log(f"scan-spread world build ({n_nodes} nodes): "
            f"{time.time()-t0:.1f}s")
        _warm_engine(s, scan_job=_service_job(count))
        w = _service_job(50)
        s.register_job(w)
        _wait_allocs(s.store, [w], 50, timeout=300)

        eng = get_engine()
        base_batched = eng.stats["batched_evals"] if eng else 0
        jobs = [_service_job(count) for _ in range(n_jobs)]
        want = n_jobs * count
        t0 = time.time()
        for j in jobs:
            s.register_job(j)
        placed = _wait_allocs(s.store, jobs, want, timeout=600)
        dt = time.time() - t0
        batched = (eng.stats["batched_evals"] - base_batched) if eng else 0
        log(f"scan-spread: {placed}/{want} spread-service allocs in "
            f"{dt:.1f}s ({placed/dt:.0f} allocs/s, "
            f"batched_evals={batched})")
        if eng:
            log(f"scan-spread engine stats: {eng.stats}")
        _log_plan_submit("scan_spread")
        return placed / dt
    finally:
        s.stop()


def bench_device_constrained(n_nodes=10000, n_jobs=20, count=100,
                             warm_count=50):
    """configs[3]: 10K nodes, half with GPU device groups; jobs with
    device requests and job anti-affinity."""
    from nomad_tpu.structs.resources import DeviceRequest, NodeDevice
    s = _server(workers=8)
    try:
        def node_fn(node, i):
            if i % 2 == 0:
                node.node_resources.devices = [NodeDevice(
                    vendor="nvidia", type="gpu", name="a100",
                    instance_ids=[f"gpu-{i}-0", f"gpu-{i}-1"])]
        t0 = time.time()
        _fill_nodes(s, n_nodes, node_fn=node_fn)
        log(f"device world build: {time.time()-t0:.1f}s")
        warm = _batch_job(warm_count)
        warm.task_groups[0].tasks[0].resources.devices = [
            DeviceRequest(name="gpu", count=1)]
        s.register_job(warm)
        _wait_allocs(s.store, [warm], warm_count, timeout=300)

        jobs = []
        for _ in range(n_jobs):
            j = _batch_job(count)
            j.task_groups[0].tasks[0].resources.devices = [
                DeviceRequest(name="gpu", count=1)]
            jobs.append(j)
        want = n_jobs * count
        t0 = time.time()
        for j in jobs:
            s.register_job(j)
        placed = _wait_allocs(s.store, jobs, want, timeout=300)
        dt = time.time() - t0
        log(f"device-constrained: {placed}/{want} GPU allocs in {dt:.1f}s "
            f"({placed/dt:.0f} allocs/s)")
        _log_plan_submit("device")
        return placed / dt
    finally:
        s.stop()


def bench_preemption_heavy(n_nodes=10000, workers=48, n_service=10,
                           service_count=50):
    """configs[4]: 10K nodes at ~95% utilization of low-priority work;
    high-priority service jobs must preempt across priority tiers."""
    s = _server(workers=workers)
    try:
        cfg = s.store.scheduler_config
        cfg.preemption_config.service_scheduler_enabled = True
        cfg.preemption_config.batch_scheduler_enabled = True
        _fill_nodes(s, n_nodes)
        # fill to ~95%: nodes are 4000cpu/8192mb; 9 allocs x 420cpu = 94.5%
        fillers = [_batch_job(n_nodes * 3, cpu=420, mem=850)
                   for _ in range(3)]
        fillers_prio = []
        for i, j in enumerate(fillers):
            j.priority = 20 + i * 10
            fillers_prio.append(j)
            s.register_job(j)
        _wait_allocs(s.store, fillers, n_nodes * 9, timeout=600)

        jobs = [_service_job(service_count, cpu=420, mem=850, spread=False,
                             priority=90) for _ in range(n_service)]
        want = n_service * service_count
        t0 = time.time()
        for j in jobs:
            s.register_job(j)
        placed = _wait_allocs(s.store, jobs, want, timeout=300)
        dt = time.time() - t0
        preempted = sum(
            1 for a in s.store._allocs.values()
            if a.desired_status == "evict")
        log(f"preemption-heavy: {placed}/{want} high-prio allocs in "
            f"{dt:.1f}s ({placed/dt:.0f} allocs/s, {preempted} preempted)")
        _log_plan_submit("preemption")
        return placed / dt
    finally:
        s.stop()


def bench_kernel_c2m_scale():
    """Kernel-only: one dense placement scan at 10K-node scale."""
    from nomad_tpu import mock
    from nomad_tpu.encode import ClusterMatrix
    from nomad_tpu.scheduler.stack import DenseStack

    cm = ClusterMatrix(initial_rows=16384)
    t0 = time.time()
    for i in range(10000):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 50}"
        cm.upsert_node(n)
    log(f"world build (10000 nodes): {time.time()-t0:.2f}s")

    job = mock.job()
    job.task_groups[0].count = 1024
    stack = DenseStack(cm)
    groups = [stack.compile_group(job, tg) for tg in job.task_groups]
    inp = stack.build_inputs(job, groups, [0] * 1024, {})

    res = stack.place(inp)          # compile + run
    t0 = time.time()
    res = stack.place(inp)
    dt = time.time() - t0
    placed = int((res.node[:1024] >= 0).sum())
    log(f"kernel: {placed} placements over 10K nodes in {dt:.3f}s "
        f"({placed/dt:.0f} placements/s on one chip)")
    return placed / dt


def bench_kernel_100k_nodes(n_nodes=100_000, waves=12, per_wave=8,
                            count=512,
                            out_path="BENCH_kernel_100k_nodes.json"):
    """100K-node world on the serving mesh: the shape a single-host
    round-trip budget cannot reach (re-uploading f32[131072, R] every
    wave).  The world uploads ONCE into the device-resident DeviceWorld,
    then `waves` dispatches of `per_wave` concurrent bulk evals (batched
    into one chained device call each) place allocs whose commits flow
    back as rank-1 scatters — steady state ships zero world bytes.
    Emits its own trajectory JSON (p50/p99 per-wave dispatch latency,
    engine stats) to `out_path` and returns the parsed dict."""
    import numpy as np

    from nomad_tpu import mock
    from nomad_tpu.encode import ClusterMatrix
    from nomad_tpu.parallel.engine import PlacementEngine
    from nomad_tpu.scheduler.stack import DenseStack

    cm = ClusterMatrix(initial_rows=131072)
    t0 = time.time()
    for i in range(n_nodes):
        n = mock.node()
        n.attributes["rack"] = f"r{i % 200}"
        cm.upsert_node(n)
    log(f"kernel_100k world build ({n_nodes} nodes, {cm.n_rows} padded "
        f"rows): {time.time()-t0:.1f}s")

    job = mock.batch_job()
    job.task_groups[0].count = count
    st = DenseStack(cm)
    g = st.compile_group(job, job.task_groups[0])
    N = cm.n_rows
    demand = np.zeros(cm.used.shape[1], np.float32)
    dm = np.asarray(g.demand, np.float32)
    demand[:min(len(dm), len(demand))] = dm[:len(demand)]
    bulk = dict(feasible=g.feasible,
                affinity=g.affinity.astype(np.float32),
                has_affinity=bool(g.has_affinity), desired=count,
                penalty=np.zeros(N, bool), coll0=np.zeros(N, np.int32),
                demand=g.demand.astype(np.float32), count=count)

    # max_batch bounds which E-bucket variants warm at this row count
    # (each compile stages f32[E, 4N]; per_wave is all we dispatch)
    eng = PlacementEngine(max_batch=per_wave)
    try:
        t0 = time.time()
        eng.warmup(cm, bulk=bulk)
        log(f"kernel_100k warm: {time.time()-t0:.1f}s")

        lat_s = []
        placed_total = 0
        t_run = time.time()
        for _ in range(waves):
            t0 = time.time()
            futs = [eng.place_bulk_begin(cm, **bulk)
                    for _ in range(per_wave)]
            results = [f.result() for f in futs]
            lat_s.append(time.time() - t0)
            for assign, placed, _ev, _ex, _scores, ticket in results:
                placed_total += int(placed)
                rows = np.flatnonzero(assign)
                for r_ in rows:
                    cm.used[r_] += assign[r_] * demand
                if ticket is not None:
                    eng.complete(ticket)
        dt = time.time() - t_run

        import jax
        lat_ms = sorted(v * 1000.0 for v in lat_s)
        p50 = lat_ms[len(lat_ms) // 2]
        p99 = lat_ms[min(len(lat_ms) - 1, int(len(lat_ms) * 0.99))]
        stats = {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in eng.stats.items()}
        traj = {
            "metric": "kernel_100k_nodes_allocs_per_sec",
            "value": round(placed_total / dt, 1),
            "unit": "allocs/s",
            "n_nodes": n_nodes, "padded_rows": int(N),
            "devices": jax.device_count(),
            "waves": waves, "evals_per_wave": per_wave, "count": count,
            "placed": placed_total,
            "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
            "engine_stats": stats,
        }
        with open(out_path, "w") as f:
            json.dump(traj, f, indent=2)
            f.write("\n")
        log(f"kernel_100k_nodes: {placed_total} allocs in {dt:.1f}s "
            f"({placed_total/dt:.0f} allocs/s; wave p50 {p50:.0f} ms / "
            f"p99 {p99:.0f} ms on {traj['devices']} devices)")
        log(f"kernel_100k engine stats: {eng.stats}")
        return traj
    finally:
        eng.stop()


def main():
    target = 1_000_000 / 30.0       # north-star C2M rate (v5e-8)

    if "--fleet-soak" in sys.argv:
        # 10K-agent fleet cells (nomad_tpu/scenarios.py FleetSoakShape):
        # batched heartbeats, drain/churn storms, and the blank-join
        # gate with a leader hard-kill mid-snapshot-stream.  Minutes per
        # cell at full size; the CI leg shrinks the fleet via
        # NOMAD_TPU_FLEET_AGENTS.  A NOMAD_TPU_CHAOS env spec overrides
        # the schedule (cells collapse to (fleet_soak, env)).
        from nomad_tpu.scenarios import FLEET_CELLS, run_matrix
        seed = 1
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        summary = run_matrix(FLEET_CELLS, seed=seed, log=log)
        print(json.dumps({
            "metric": "fleet_soak",
            "seed": seed,
            "agents": int(os.environ.get("NOMAD_TPU_FLEET_AGENTS",
                                         "10000")),
            "cells": len(summary["cells"]),
            "passed": summary["passed"],
            "failed": summary["failed"],
            "per_cell": [{
                "shape": t.get("shape"), "schedule": t.get("schedule"),
                "converged": t["convergence"].get("converged"),
                "convergence_time_s":
                    t["convergence"].get("convergence_time_s"),
                "notes": t.get("notes"),
            } for t in summary["cells"]],
        }), flush=True)
        sys.exit(0 if summary["ok"] else 1)

    if "--matrix" in sys.argv:
        # chaos scenario matrix: workload shapes x phased chaos
        # schedules on a real 3-server cluster, each cell gated on
        # post-chaos convergence invariants (nomad_tpu/scenarios.py).
        # `--matrix --smoke` runs the curated CI subset; `--seed N`
        # picks the chaos seed; a NOMAD_TPU_CHAOS env spec overrides
        # the schedule for every cell.
        from nomad_tpu.scenarios import ALL_CELLS, SMOKE_CELLS, run_matrix
        seed = 1
        if "--seed" in sys.argv:
            seed = int(sys.argv[sys.argv.index("--seed") + 1])
        cells = SMOKE_CELLS if "--smoke" in sys.argv else ALL_CELLS
        summary = run_matrix(cells, seed=seed, log=log)
        print(json.dumps({
            "metric": "scenario_matrix",
            "seed": seed,
            "cells": len(summary["cells"]),
            "passed": summary["passed"],
            "failed": summary["failed"],
            "per_cell": [{
                "shape": t.get("shape"), "schedule": t.get("schedule"),
                "converged": t["convergence"].get("converged"),
                "convergence_time_s":
                    t["convergence"].get("convergence_time_s"),
                "allocs_per_sec": t.get("allocs_per_sec"),
                "plan_submit_ms": t.get("plan_submit_ms"),
            } for t in summary["cells"]],
        }), flush=True)
        sys.exit(0 if summary["ok"] else 1)

    if "--smoke" in sys.argv:
        # CI leg: the same shape in seconds (tier-1 invokes this)
        rate, placed, want = bench_smoke()
        steady = _STEADY_STATE.get("smoke", {})
        # serving-plane leg rides the smoke run: >=1K watchers +
        # follower blocking queries on a 3-server cluster while the
        # spine commits.  Hard-fails on unbounded subscriber queues or
        # busy read p99 blowing past 2x idle (5 ms floor absorbs CI
        # scheduler jitter on shared CPU runners).
        serving = bench_serving_plane(
            n_watchers=1024, n_blockers=8,
            idle_samples=150, busy_samples=300)
        # per-scenario regression gate: the spread / device / preemption
        # shapes shrunk to seconds, their plan.submit p99 capped.  The
        # cap is generous (it catches order-of-magnitude regressions in
        # a scenario's placement path, not CI-runner jitter) and
        # env-overridable for slow runners.
        p99_cap_ms = float(os.environ.get("NOMAD_TPU_SMOKE_P99_MS", "750"))
        scenario_violations = []
        for name, fn in (
                ("scan_spread", lambda: bench_scan_spread(
                    n_nodes=256, n_jobs=6, count=20, workers=8)),
                ("device", lambda: bench_device_constrained(
                    n_nodes=256, n_jobs=4, count=25, warm_count=10)),
                ("preemption", lambda: bench_preemption_heavy(
                    n_nodes=96, workers=8, n_service=2,
                    service_count=12))):
            fn()
            p99 = _PLAN_STATS.get(name, {}).get(
                "submit_ms", {}).get("p99", 0.0)
            if p99 > p99_cap_ms:
                scenario_violations.append(
                    f"{name}: plan.submit p99 {p99} ms > "
                    f"cap {p99_cap_ms} ms")
        # fused-path leg (r15): the smoke spine must have run every bulk
        # wave group as ONE device dispatch (NOMAD_TPU_FUSE default),
        # and the fused kernel must be registered with the recompile
        # budget and warm before the gate (its cache populated by
        # warmup, not the measured window).  The sharded twin is only
        # checkable on a multi-device host.
        fused_violations = []
        snap = _ENGINE_SNAP.get("smoke", {})
        groups = snap.get("bulk_groups", 0)
        parts = snap.get("bulk_parts", 0)
        if os.environ.get("NOMAD_TPU_FUSE", "1") != "0":
            if groups <= 0:
                fused_violations.append(
                    "no bulk wave groups dispatched (fused path unused)")
            elif parts != groups:
                fused_violations.append(
                    f"fused path inactive: {parts} device dispatches for "
                    f"{groups} wave groups (expected 1 per wave)")
        from nomad_tpu.analysis import recompile as _recompile
        kernel_sizes = _recompile.cache_sizes()
        # with donation on (default) the warmed unsharded kernel is the
        # donate_argnums variant; with it off, the plain one.  Either
        # satisfies the "bulk kernel warm" requirement — on multi-device
        # hosts the 2-D sharded kernel carries the waves instead, so the
        # unsharded check accepts whichever variant warmup compiled.
        if os.environ.get("NOMAD_TPU_DONATE", "1") != "0":
            want_kernels = [("place.bulk_batch_donate", "place.bulk_batch")]
        else:
            want_kernels = [("place.bulk_batch",)]
        try:
            import jax
            if jax.device_count() > 1:
                want_kernels.append(("sharded.bulk",))
        except Exception:   # noqa: BLE001
            pass
        for alts in want_kernels:
            if all(kernel_sizes.get(k) is None for k in alts):
                fused_violations.append(
                    f"kernel {alts[0]!r} missing a recompile.register entry")
            elif all((kernel_sizes.get(k) or 0) < 1 for k in alts):
                fused_violations.append(
                    f"kernel {alts[0]!r} registered but never warmed "
                    f"(cache empty after the run)")
        # tracing leg: disabled guards must be free, sampled run must
        # export a well-formed Perfetto file (r12)
        trace_checks = _smoke_trace_checks()
        print(json.dumps({
            "metric": "c2m_smoke_allocs_per_sec",
            "value": round(rate, 1),
            "unit": "allocs/s",
            "vs_baseline": round(rate / target, 4),
            "placed": placed,
            "want": want,
            "plan_latency_ms": _PLAN_STATS,
            "steady_state": steady,
            "serving_plane": serving,
            "device_stages": _DEVICE_STAGES.get("smoke"),
            "fused": {"bulk_groups": groups, "bulk_parts": parts,
                      "kernels": {k: kernel_sizes.get(k)
                                  for alts in want_kernels
                                  for k in alts},
                      "violations": fused_violations},
            "tracing": trace_checks,
        }), flush=True)
        if steady.get("violations"):
            log("steady-state violations:", steady["violations"])
            sys.exit(1)
        if fused_violations:
            for v in fused_violations:
                log("fused gate:", v)
            sys.exit(1)
        if trace_checks["violations"]:
            for v in trace_checks["violations"]:
                log("tracing gate:", v)
            sys.exit(1)
        if scenario_violations:
            for v in scenario_violations:
                log("scenario gate:", v)
            sys.exit(1)
        if not serving["bounded"]:
            log("serving_plane: subscriber queue exceeded its bound")
            sys.exit(1)
        p99_cap = max(2 * serving["read_p99_idle_ms"], 5.0)
        if serving["read_p99_busy_ms"] > p99_cap:
            log(f"serving_plane: busy read p99 "
                f"{serving['read_p99_busy_ms']} ms exceeds cap "
                f"{p99_cap:.1f} ms (2x idle, 5 ms floor)")
            sys.exit(1)
        return

    if "--100k" in sys.argv:
        # the 100K-node device-resident scenario, alone (own trajectory
        # JSON; the stdout line mirrors it for the driver)
        traj = bench_kernel_100k_nodes()
        print(json.dumps(traj), flush=True)
        return

    # headline: the REAL north-star number — C2M-1M at full size
    rate = 0.0
    try:
        rate, placed, want = bench_c2m_1m()
        if placed < want:
            log(f"c2m_1m INCOMPLETE: {placed}/{want} before deadline")
    except Exception as e:          # noqa: BLE001
        log("c2m_1m headline failed:", e)
    try:
        kernel_rate = bench_kernel_c2m_scale()
    except Exception as e:          # noqa: BLE001
        log("kernel bench failed:", e)
        kernel_rate = 0.0

    try:
        bench_kernel_100k_nodes()
    except Exception as e:          # noqa: BLE001
        log("kernel_100k bench failed:", e)

    serving = {}
    try:
        serving = bench_serving_plane()
    except Exception as e:          # noqa: BLE001
        log("serving_plane bench failed:", e)

    if os.environ.get("BENCH_ALL") == "1":
        # the full BASELINE.json scenario suite (tens of minutes)
        for name, fn in (("e2e_spine", bench_e2e_spine),
                         ("dev_agent", bench_dev_agent_sim),
                         ("c2m", bench_c2m),
                         ("scan_spread", bench_scan_spread),
                         ("device", bench_device_constrained),
                         ("preemption", bench_preemption_heavy)):
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                log(f"scenario {name} failed: {e}")

    print(json.dumps({
        "metric": "c2m_1m_allocs_per_sec_10knodes_1mallocs",
        "value": round(rate, 1),
        "unit": "allocs/s",
        "vs_baseline": round(rate / target, 4),
        "plan_latency_ms": _PLAN_STATS,
        "steady_state": _STEADY_STATE,
        "serving_plane": serving,
        "device_stages": _DEVICE_STAGES.get("c2m_1m"),
    }), flush=True)


if __name__ == "__main__":
    main()
