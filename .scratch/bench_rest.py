import sys; sys.path.insert(0, "/root/repo")
import sys, time, json
sys.argv = ['bench.py']
import bench

out = {}
for name, fn in (("c2m_1m", bench.bench_c2m_1m),
                 ("device", bench.bench_device_constrained),
                 ("preemption", bench.bench_preemption_heavy)):
    t0 = time.time()
    try:
        rate = fn()
        out[name] = {"allocs_per_sec": round(rate, 1),
                     "wall_s": round(time.time() - t0, 1)}
    except Exception as e:
        out[name] = {"error": str(e)}
    print("PARTIAL", json.dumps(out), flush=True)
print("FINAL", json.dumps(out), flush=True)
