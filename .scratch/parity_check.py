import numpy as np, jax
from nomad_tpu import mock
from nomad_tpu.encode import ClusterMatrix
from nomad_tpu.scheduler.stack import DenseStack
from nomad_tpu.structs.job import Affinity, Operand, Spread
from nomad_tpu.ops.place import (pack_heavy, pack_light, place_batch_packed_jit,
                                 unpack_outputs, heavy_dims,
                                 pack_bulk_heavy, pack_bulk_light,
                                 place_bulk_batch_jit, unpack_bulk_batch)
from nomad_tpu.parallel.sharded import (make_serving_mesh, place_batch_sharded,
                                        place_bulk_batch_sharded)

cm = ClusterMatrix(initial_rows=64)
rng = np.random.default_rng(0)
for i in range(64):
    n = mock.node()
    n.attributes["rack"] = f"r{i%4}"
    n.node_resources.cpu.cpu_shares = int(rng.integers(3000, 8000))
    cm.upsert_node(n)
j = mock.job()
tg = j.task_groups[0]; tg.count = 6
tg.spreads = [Spread("${attr.rack}", 70, ())]
j.affinities.append(Affinity("${attr.rack}", "r1", Operand.EQ, weight=30))
st = DenseStack(cm)
groups = [st.compile_group(j, tg) for tg in j.task_groups]
inp = st.build_inputs(j, groups, [0]*6, {})
E, D, R = 4, 8, 4
N = cm.n_rows
G, _, K, Vp1 = heavy_dims(inp)
S = inp.demand.shape[0]
deltas = [(3, np.array([200., 100., 0., 0.], np.float32))]

heavy = jax.device_put(pack_heavy(inp))
lights = [pack_light(inp, deltas if e==0 else [], D) for e in range(E)]
basis = np.ascontiguousarray(cm.used, np.float32)
dyn = np.concatenate([basis.ravel()] + lights)
packed, _ = place_batch_packed_jit(jax.device_put(np.ascontiguousarray(cm.capacity, np.float32)),
                                   tuple([heavy]*E), jax.device_put(dyn), (G, N, K, Vp1, S, D))
ref = unpack_outputs(np.asarray(jax.device_get(packed)))

mesh = make_serving_mesh()
fields = {f: np.stack([np.asarray(getattr(inp, f))]*E) for f in
          ("feasible","affinity","has_affinity","desired_count","penalty","tg_count",
           "spread_vidx","spread_desired","spread_targeted","spread_wfrac",
           "spread_counts","spread_active","place_cap","demand","slot_tg","slot_active")}
drows = np.full((E, D), N, np.int32); dvals = np.zeros((E, D, R), np.float32)
drows[0,0] = 3; dvals[0,0] = deltas[0][1]
packed_s, used_f = place_batch_sharded(mesh, np.ascontiguousarray(cm.capacity, np.float32),
                                       basis, fields, drows, dvals)
got = unpack_outputs(np.asarray(jax.device_get(packed_s)))
for a, b, name in zip(ref, got, ("node","score","fit","ne","nx","tn","ts")):
    if name in ("score","fit","ts"):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    elif name == "tn":
        pass
    else:
        np.testing.assert_array_equal(a, b)
print("scan-path sharded parity OK; nodes:", got[0][:, :6].tolist())

bj = mock.batch_job(); btg = bj.task_groups[0]; btg.count = 40
btg.tasks[0].resources.cpu = 300; btg.tasks[0].resources.memory_mb = 200
btg.ephemeral_disk.size_mb = 0
bst = DenseStack(cm); bg = bst.compile_group(bj, btg)
hb = jax.device_put(pack_bulk_heavy(bg.feasible, bg.affinity, np.zeros(N,bool), np.zeros(N,np.int32)))
lb = [pack_bulk_light(bg.has_affinity, 40, 40, bg.demand, deltas if e==0 else [], N, D) for e in range(E)]
dynb = np.concatenate([basis.ravel()] + lb)
pb, _ = place_bulk_batch_jit(jax.device_put(np.ascontiguousarray(cm.capacity, np.float32)),
                             tuple([hb]*E), jax.device_put(dynb), D)
ref_b = unpack_bulk_batch(np.asarray(jax.device_get(pb)))

ass, sc, placed, ne, nx, uf = place_bulk_batch_sharded(
    mesh, np.ascontiguousarray(cm.capacity, np.float32), basis,
    np.stack([bg.feasible]*E), np.stack([bg.affinity.astype(np.float32)]*E),
    np.array([bool(bg.has_affinity)]*E), np.array([40]*E, np.int32),
    np.stack([np.zeros(N, bool)]*E), np.stack([np.zeros(N, np.int32)]*E),
    np.stack([bg.demand.astype(np.float32)]*E), np.array([40]*E, np.int32),
    drows, dvals)
np.testing.assert_array_equal(np.asarray(ass), ref_b[0])
np.testing.assert_array_equal(np.asarray(placed), ref_b[2])
np.testing.assert_array_equal(np.asarray(ne), ref_b[3])
print("bulk sharded parity OK; placed:", np.asarray(placed).tolist())
