"""HTTP API layer (reference: api/ Go SDK + command/agent/http.go).

`nomad_tpu.api.codec` — generic dataclass<->JSON wire codec.
`nomad_tpu.api.client` — typed Python SDK over the agent's /v1 REST API.
"""
from nomad_tpu.api.codec import from_wire, to_wire
from nomad_tpu.api.client import ApiClient, ApiError

__all__ = ["ApiClient", "ApiError", "from_wire", "to_wire"]
