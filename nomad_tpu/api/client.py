"""Typed Python SDK over the agent's /v1 HTTP API.

Reference: api/ (api/api.go Client + per-resource wrappers api/jobs.go,
api/nodes.go, api/evaluations.go, api/allocations.go, api/event_stream.go).
Uses urllib only — the agent is local/cluster-internal.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from nomad_tpu.api.codec import from_wire, to_wire
from nomad_tpu.deadline import DeadlineExceeded
from nomad_tpu.structs import (
    Allocation,
    Deployment,
    Evaluation,
    Job,
    Node,
)
from nomad_tpu.structs.config import SchedulerConfiguration


class ApiError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class ApiClient:
    # status codes a GET may safely retry: the request either never ran
    # or is safe to re-run (reads only)
    RETRYABLE_STATUSES = (502, 503, 504)

    def __init__(self, address: str = "http://127.0.0.1:4646",
                 token: str = "", namespace: str = "default",
                 timeout: float = 30.0, retries: int = 2,
                 retry_backoff: float = 0.1,
                 consistency: Optional[str] = None,
                 region: Optional[str] = None,
                 deadline: Optional[float] = None):
        self.address = address.rstrip("/")
        self.token = token
        self.namespace = namespace
        # target region (reference api.Config.Region / QueryOptions
        # .Region): when set, every request carries `?region=` and the
        # contacted server forwards it over the WAN if it isn't local
        self.region = region
        self.timeout = timeout
        self.retries = retries
        self.retry_backoff = retry_backoff
        # client-wide read consistency: None/"default" (leader lease),
        # "stale" (any server, immediate), "consistent" (full read-index);
        # per-call `consistency=` kwargs on get() override it
        self.consistency = consistency
        # end-to-end budget (seconds) per request: shipped to the server
        # as X-Nomad-Deadline and enforced locally — per-attempt socket
        # timeouts and retry backoff are clamped to the remaining budget,
        # and a request out of budget fails with DeadlineExceeded instead
        # of sleeping into a retry nobody is waiting for
        self.deadline = deadline
        self.last_index = 0
        # staleness metadata from the most recent read (the reference's
        # QueryMeta.LastContact / KnownLeader)
        self.last_contact_ms = 0
        self.known_leader = True
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.evaluations = Evaluations(self)
        self.allocations = Allocations(self)
        self.deployments = Deployments(self)
        self.operator = Operator(self)
        self.acl = AclApi(self)
        self.namespaces = Namespaces(self)
        self.quotas = Quotas(self)
        self.volumes = Volumes(self)
        self.plugins = Plugins(self)
        self.system = SystemApi(self)
        self.services = ServicesApi(self)

    # ------------------------------------------------------------- transport

    def _request(self, method: str, path: str,
                 params: Optional[Dict[str, str]] = None,
                 body: Any = None, raw: bool = False,
                 consistency: Optional[str] = None,
                 deadline: Optional[float] = None):
        qs = dict(params or {})
        if self.region:
            qs.setdefault("region", self.region)
        if self.namespace:
            # every request carries the client's namespace unless the
            # caller set one explicitly ("*" lists across namespaces) —
            # the same threading as region above
            qs.setdefault("namespace", self.namespace)
        if method == "GET":
            mode = consistency if consistency is not None \
                else self.consistency
            if mode == "stale":
                qs.setdefault("stale", "true")
            elif mode == "consistent":
                qs.setdefault("consistent", "true")
        url = f"{self.address}{path}"
        if qs:
            # some section helpers bake a query string into `path`
            url += ("&" if "?" in path else "?") + urllib.parse.urlencode(
                {k: v for k, v in qs.items() if v is not None})  # analysis: allow(context-propagation) — qs is the URL query string, not an RPC args dict; the deadline rides X-Nomad-Deadline per attempt
        data = None
        if body is not None:
            data = json.dumps(body).encode()
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("X-Nomad-Token", self.token)
        budget = deadline if deadline is not None else self.deadline
        dl = time.monotonic() + budget if budget is not None else None
        # only idempotent reads retry; writes surface their error — the
        # server may have applied them before the connection dropped
        attempts_left = self.retries if method == "GET" else 0
        delay = self.retry_backoff
        while True:
            timeout = self.timeout
            if dl is not None:
                rem = dl - time.monotonic()
                if rem <= 0:
                    raise DeadlineExceeded(
                        f"{method} {path}: {budget:g}s budget exhausted")
                timeout = min(timeout, rem)
                # the server propagates the remaining budget end to end
                # (re-stamped per attempt so retries don't double-spend)
                req.add_header("X-Nomad-Deadline", f"{rem:.3f}")
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout) as resp:
                    payload = resp.read()
                    self.last_index = int(
                        resp.headers.get("X-Nomad-Index") or 0)
                    self.last_contact_ms = int(
                        resp.headers.get("X-Nomad-LastContact") or 0)
                    self.known_leader = \
                        resp.headers.get("X-Nomad-KnownLeader") != "false"
                break
            except urllib.error.HTTPError as e:
                body_text = e.read().decode(errors="replace")
                if attempts_left <= 0 or \
                        e.code not in self.RETRYABLE_STATUSES:
                    raise ApiError(e.code, body_text)
                retry_after = e.headers.get("Retry-After") \
                    if e.headers else None
                try:
                    wait = float(retry_after) if retry_after else delay
                except ValueError:
                    wait = delay
                wait = min(wait, 2.0)
                if dl is not None and time.monotonic() + wait >= dl:
                    # not enough budget for another round trip: surface
                    # the deadline instead of sleeping into it
                    raise DeadlineExceeded(
                        f"{method} {path}: {budget:g}s budget exhausted "
                        f"retrying HTTP {e.code}")
                time.sleep(wait)
            except (urllib.error.URLError, ConnectionError) as e:
                if attempts_left <= 0:
                    raise
                wait = min(delay, 2.0)
                if dl is not None and time.monotonic() + wait >= dl:
                    raise DeadlineExceeded(
                        f"{method} {path}: {budget:g}s budget exhausted "
                        f"retrying after {type(e).__name__}")
                time.sleep(wait)
            attempts_left -= 1
            delay = min(delay * 2.0, 2.0)
        if raw:
            return payload
        return json.loads(payload) if payload else None

    def get(self, path, params=None, consistency=None, deadline=None):
        return self._request("GET", path, params,
                             consistency=consistency, deadline=deadline)

    def put(self, path, body=None, params=None):
        return self._request("PUT", path, params, body)

    def delete(self, path, params=None):
        return self._request("DELETE", path, params)


class _Section:
    def __init__(self, client: ApiClient):
        self.c = client


class Jobs(_Section):
    def list(self, prefix: str = "") -> List[dict]:
        return self.c.get("/v1/jobs", {"prefix": prefix or None})

    def register(self, job: Job) -> dict:
        return self.c.put("/v1/jobs", {"Job": to_wire(job)})

    def info(self, job_id: str) -> Job:
        return from_wire(Job, self.c.get(
            f"/v1/job/{job_id}", {"namespace": self.c.namespace}))

    def deregister(self, job_id: str, purge: bool = False) -> dict:
        return self.c.delete(
            f"/v1/job/{job_id}",
            {"namespace": self.c.namespace,
             "purge": "true" if purge else None})

    def allocations(self, job_id: str) -> List[dict]:
        return self.c.get(f"/v1/job/{job_id}/allocations",
                          {"namespace": self.c.namespace})

    def evaluations(self, job_id: str) -> List[Evaluation]:
        return [from_wire(Evaluation, e) for e in self.c.get(
            f"/v1/job/{job_id}/evaluations",
            {"namespace": self.c.namespace})]

    def deployments(self, job_id: str) -> List[Deployment]:
        return [from_wire(Deployment, d) for d in self.c.get(
            f"/v1/job/{job_id}/deployments",
            {"namespace": self.c.namespace})]

    def latest_deployment(self, job_id: str) -> Optional[Deployment]:
        d = self.c.get(f"/v1/job/{job_id}/deployment",
                       {"namespace": self.c.namespace})
        return from_wire(Deployment, d) if d else None

    def summary(self, job_id: str) -> dict:
        return self.c.get(f"/v1/job/{job_id}/summary",
                          {"namespace": self.c.namespace})

    def versions(self, job_id: str) -> List[dict]:
        return self.c.get(f"/v1/job/{job_id}/versions",
                          {"namespace": self.c.namespace})

    def plan(self, job: Job, diff: bool = True) -> dict:
        return self.c.put(f"/v1/job/{job.id}/plan",
                          {"Job": to_wire(job), "Diff": diff})

    def evaluate(self, job_id: str) -> dict:
        return self.c.put(f"/v1/job/{job_id}/evaluate", {})

    def dispatch(self, job_id: str, payload: str = "",
                 meta: Optional[Dict[str, str]] = None) -> dict:
        return self.c.put(f"/v1/job/{job_id}/dispatch",
                          {"Payload": payload, "Meta": meta or {}})

    def revert(self, job_id: str, version: int) -> dict:
        return self.c.put(f"/v1/job/{job_id}/revert",
                          {"JobVersion": version})

    def periodic_force(self, job_id: str) -> dict:
        return self.c.put(f"/v1/job/{job_id}/periodic/force", {})

    def parse(self, hcl: str) -> dict:
        return self.c.put("/v1/jobs/parse", {"JobHCL": hcl})

    def scale(self, job_id: str, group: str, count: Optional[int] = None,
              message: str = "", error: bool = False,
              meta: Optional[dict] = None) -> dict:
        return self.c.put(f"/v1/job/{job_id}/scale", {
            "Target": {"Group": group}, "Count": count,
            "Message": message, "Error": error, "Meta": meta})

    def scale_status(self, job_id: str) -> dict:
        return self.c.get(f"/v1/job/{job_id}/scale")


class Nodes(_Section):
    def list(self, prefix: str = "") -> List[dict]:
        return self.c.get("/v1/nodes", {"prefix": prefix or None})

    def info(self, node_id: str) -> Node:
        return from_wire(Node, self.c.get(f"/v1/node/{node_id}"))

    def allocations(self, node_id: str) -> List[Allocation]:
        return [from_wire(Allocation, a) for a in
                self.c.get(f"/v1/node/{node_id}/allocations")]

    def drain(self, node_id: str, deadline_s: float = 3600.0,
              ignore_system_jobs: bool = False) -> dict:
        return self.c.put(
            f"/v1/node/{node_id}/drain",
            {"DrainSpec": {"Deadline": deadline_s,
                           "IgnoreSystemJobs": ignore_system_jobs}})

    def drain_disable(self, node_id: str) -> dict:
        return self.c.put(f"/v1/node/{node_id}/drain", {"DrainSpec": None})

    def eligibility(self, node_id: str, eligible: bool) -> dict:
        return self.c.put(
            f"/v1/node/{node_id}/eligibility",
            {"Eligibility": "eligible" if eligible else "ineligible"})

    def purge(self, node_id: str) -> dict:
        return self.c.put(f"/v1/node/{node_id}/purge", {})


class Evaluations(_Section):
    def list(self, prefix: str = "") -> List[Evaluation]:
        return [from_wire(Evaluation, e) for e in
                self.c.get("/v1/evaluations", {"prefix": prefix or None})]

    def info(self, eval_id: str) -> Evaluation:
        return from_wire(Evaluation, self.c.get(f"/v1/evaluation/{eval_id}"))

    def allocations(self, eval_id: str) -> List[Allocation]:
        return [from_wire(Allocation, a) for a in
                self.c.get(f"/v1/evaluation/{eval_id}/allocations")]


class Allocations(_Section):
    def list(self, prefix: str = "") -> List[dict]:
        return self.c.get("/v1/allocations", {"prefix": prefix or None})

    def info(self, alloc_id: str) -> Allocation:
        return from_wire(Allocation, self.c.get(f"/v1/allocation/{alloc_id}"))

    def stop(self, alloc_id: str) -> dict:
        return self.c.put(f"/v1/allocation/{alloc_id}/stop", {})

    # ------------------------------------------------------ fs / logs

    def logs(self, alloc_id: str, task: str, type_: str = "stdout",
             offset: int = 0, origin: str = "start") -> bytes:
        """One-shot task log read (api/fs.go Logs non-follow)."""
        return self.c._request(
            "GET", f"/v1/client/fs/logs/{alloc_id}",
            {"task": task, "type": type_, "offset": str(offset),
             "origin": origin}, raw=True)

    def logs_follow(self, alloc_id: str, task: str,
                    type_: str = "stdout", timeout: float = 30.0):
        """Generator of appended log chunks (api/fs.go Logs follow)."""
        import urllib.request
        url = (f"{self.c.address}/v1/client/fs/logs/{alloc_id}?"
               + urllib.parse.urlencode(
                   {"task": task, "type": type_, "follow": "true",
                    "origin": "end", "offset": "0",
                    "timeout": str(timeout)}))
        req = urllib.request.Request(url)
        if self.c.token:
            req.add_header("X-Nomad-Token", self.c.token)
        with urllib.request.urlopen(req, timeout=timeout + 10.0) as resp:
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    return
                yield chunk

    def fs_list(self, alloc_id: str, path: str = "/") -> List[dict]:
        return self.c.get(f"/v1/client/fs/ls/{alloc_id}", {"path": path})

    def fs_stat(self, alloc_id: str, path: str) -> dict:
        return self.c.get(f"/v1/client/fs/stat/{alloc_id}",
                          {"path": path})

    def fs_cat(self, alloc_id: str, path: str) -> bytes:
        return self.c._request("GET", f"/v1/client/fs/cat/{alloc_id}",
                               {"path": path}, raw=True)


class Deployments(_Section):
    def list(self) -> List[Deployment]:
        return [from_wire(Deployment, d) for d in
                self.c.get("/v1/deployments")]

    def info(self, deployment_id: str) -> Deployment:
        return from_wire(Deployment,
                         self.c.get(f"/v1/deployment/{deployment_id}"))

    def promote(self, deployment_id: str,
                groups: Optional[List[str]] = None) -> dict:
        return self.c.put(f"/v1/deployment/promote/{deployment_id}",
                          {"Groups": groups})

    def fail(self, deployment_id: str) -> dict:
        return self.c.put(f"/v1/deployment/fail/{deployment_id}", {})

    def pause(self, deployment_id: str, pause: bool = True) -> dict:
        return self.c.put(f"/v1/deployment/pause/{deployment_id}",
                          {"Pause": pause})


class Operator(_Section):
    def scheduler_get_configuration(self) -> SchedulerConfiguration:
        resp = self.c.get("/v1/operator/scheduler/configuration")
        return from_wire(SchedulerConfiguration, resp["SchedulerConfig"])

    def scheduler_set_configuration(self, cfg: SchedulerConfiguration) -> dict:
        return self.c.put("/v1/operator/scheduler/configuration",
                          to_wire(cfg))

    def raft_get_configuration(self) -> dict:
        """The raft membership: {"Index": n, "Servers": [{ID, Node,
        Voter, Leader}, ...]}."""
        return self.c.get("/v1/operator/raft/configuration")

    def raft_remove_peer(self, name: str) -> dict:
        return self.c.put("/v1/operator/raft/remove-peer", {"ID": name})

    def raft_transfer_leadership(self, name: Optional[str] = None) -> dict:
        return self.c.put("/v1/operator/raft/transfer-leadership",
                          {"ID": name})

    def integrity(self) -> dict:
        """The served replica's integrity-plane view: {"server", "leader",
        "quarantined", "quarantine_reason", "last": {index, digest,
        per_table, full, seq} | None, "peers": {name: {index, digest,
        lag, divergent, unverified_acks}}, "counters": {...}}."""
        return self.c.get("/v1/operator/integrity")

    # ----------------------------------------------------- tracing (r12)

    def traces(self) -> list:
        """Trace summaries from this server's span store, newest first:
        [{trace_id, root, start, duration, spans, nodes}, ...].  404s
        (ApiError) unless the agent runs with NOMAD_TPU_TRACE=1."""
        return self.c.get("/v1/traces")

    def trace(self, trace_id: str) -> dict:
        """One trace's spans, start-ordered: {"trace_id": ...,
        "spans": [{trace_id, span_id, parent_id, name, start, duration,
        node, attrs}, ...]}."""
        return self.c.get(f"/v1/traces/{trace_id}")

    def trace_chrome(self, trace_id: str) -> dict:
        """The same trace as Chrome-trace JSON — dump to a file and load
        it in Perfetto / chrome://tracing."""
        return self.c.get(f"/v1/traces/{trace_id}",
                          params={"format": "chrome"})


class AclApi(_Section):
    def bootstrap(self) -> dict:
        return self.c.put("/v1/acl/bootstrap", {})

    def upsert_policy(self, name: str, rules: str,
                      description: str = "") -> dict:
        return self.c.put(f"/v1/acl/policy/{name}",
                          {"Description": description, "Rules": rules})

    def policies(self) -> List[dict]:
        return self.c.get("/v1/acl/policies")

    def policy(self, name: str) -> dict:
        return self.c.get(f"/v1/acl/policy/{name}")

    def delete_policy(self, name: str) -> dict:
        return self.c.delete(f"/v1/acl/policy/{name}")

    def create_token(self, name: str = "", type_: str = "client",
                     policies: Optional[List[str]] = None) -> dict:
        return self.c.put("/v1/acl/token",
                          {"Name": name, "Type": type_,
                           "Policies": policies or []})

    def tokens(self) -> List[dict]:
        return self.c.get("/v1/acl/tokens")

    def self_token(self) -> dict:
        return self.c.get("/v1/acl/token/self")

    def delete_token(self, accessor_id: str) -> dict:
        return self.c.delete(f"/v1/acl/token/{accessor_id}")


class Namespaces(_Section):
    def list(self) -> List[dict]:
        return self.c.get("/v1/namespaces")

    def info(self, name: str) -> dict:
        return self.c.get(f"/v1/namespace/{name}")

    def register(self, name: str, description: str = "",
                 quota: str = "") -> dict:
        return self.c.put("/v1/namespaces",
                          {"Name": name, "Description": description,
                           "Quota": quota})

    def delete(self, name: str) -> dict:
        return self.c.delete(f"/v1/namespace/{name}")


class Quotas(_Section):
    """Per-namespace resource quotas (reference api/quota.go)."""

    def list(self) -> List[dict]:
        return self.c.get("/v1/quotas")

    def info(self, name: str) -> dict:
        return self.c.get(f"/v1/quota/{name}")

    def register(self, spec) -> dict:
        body = spec if isinstance(spec, dict) else to_wire(spec)
        return self.c.put("/v1/quotas", body)

    def delete(self, name: str) -> dict:
        return self.c.delete(f"/v1/quota/{name}")

    def usage(self, namespace: str) -> dict:
        return self.c.get(f"/v1/quota/usage/{namespace}")

    def usages(self) -> dict:
        return self.c.get("/v1/quota/usage")


class Volumes(_Section):
    """CSI volumes (reference api/csi.go CSIVolumes)."""
    def list(self, namespace: str = "default") -> List[dict]:
        return self.c.get(f"/v1/volumes?namespace={namespace}")

    def info(self, vol_id: str, namespace: str = "default") -> dict:
        return self.c.get(f"/v1/volume/csi/{vol_id}?namespace={namespace}")

    def register(self, volume: dict, namespace: str = "default") -> dict:
        return self.c.put(f"/v1/volume/csi/{volume.get('ID', '')}"
                          f"?namespace={namespace}", {"Volume": volume})

    def deregister(self, vol_id: str, namespace: str = "default",
                   force: bool = False) -> dict:
        f = "true" if force else "false"
        return self.c.delete(
            f"/v1/volume/csi/{vol_id}?namespace={namespace}&force={f}")


class Plugins(_Section):
    def list(self) -> List[dict]:
        return self.c.get("/v1/plugins")

    def info(self, plugin_id: str) -> dict:
        return self.c.get(f"/v1/plugin/csi/{plugin_id}")


class ServicesApi(_Section):
    """Nomad-native service registry (/v1/services, /v1/service/:name —
    reference api/services.go)."""

    def list(self) -> List[dict]:
        return self.c.get("/v1/services")

    def get(self, name: str) -> List[dict]:
        return self.c.get(f"/v1/service/{name}")

    def delete(self, name: str, reg_id: str) -> dict:
        return self.c.delete(f"/v1/service/{name}/{reg_id}")


class SystemApi(_Section):
    def regions(self) -> List[str]:
        return self.c.get("/v1/regions")

    def search(self, prefix: str, context: str = "all") -> dict:
        return self.c.put("/v1/search",
                          {"Prefix": prefix, "Context": context})


    def leader(self):
        return self.c.get("/v1/status/leader")

    def peers(self):
        return self.c.get("/v1/status/peers")

    def metrics(self) -> dict:
        return self.c.get("/v1/metrics")

    def members(self) -> dict:
        return self.c.get("/v1/agent/members")

    def agent_self(self) -> dict:
        return self.c.get("/v1/agent/self")

    def search(self, prefix: str, context: str = "all") -> dict:
        return self.c._request("POST", "/v1/search", None,
                               {"Prefix": prefix, "Context": context})

    def event_stream(self, topics: Optional[List[str]] = None,
                     timeout: float = 5.0) -> Iterator[dict]:
        """Yield event frames from /v1/event/stream (NDJSON)."""
        qs = [("timeout", str(timeout))]
        for t in topics or []:
            qs.append(("topic", t))
        url = (f"{self.c.address}/v1/event/stream?"
               + urllib.parse.urlencode(qs))
        req = urllib.request.Request(url)
        if self.c.token:
            req.add_header("X-Nomad-Token", self.c.token)
        with urllib.request.urlopen(req, timeout=timeout + 5) as resp:
            for line in resp:
                line = line.strip()
                if not line or line == b"{}":
                    continue
                yield json.loads(line)
