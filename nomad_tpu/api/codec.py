"""Generic dataclass <-> JSON wire codec.

The reference serializes its shared structs (nomad/structs/) with
msgpack for RPC and JSON for the HTTP API (command/agent/http.go).  Here
every control-plane object is a plain Python dataclass, so one
reflection-driven codec covers the whole API surface: `to_wire` walks
dataclasses/lists/dicts down to JSON-safe primitives (bytes -> base64),
and `from_wire` rebuilds typed objects from the declared field types.

Unknown keys are ignored on decode (forward compatibility, matching
the reference's JSON behavior); missing keys take dataclass defaults.
"""
from __future__ import annotations

import base64
import dataclasses
import sys
import typing
from typing import Any, Dict, Optional

_NoneType = type(None)

# cache: dataclass -> {field_name: resolved_type}
_HINTS: Dict[type, Dict[str, Any]] = {}


def _type_hints(cls: type) -> Dict[str, Any]:
    hints = _HINTS.get(cls)
    if hints is None:
        mod = sys.modules.get(cls.__module__)
        hints = typing.get_type_hints(cls, getattr(mod, "__dict__", None))
        _HINTS[cls] = hints
    return hints


def to_wire(obj: Any) -> Any:
    """Recursively convert an object graph to JSON-safe values."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, bytes):
        return {"__bytes__": base64.b64encode(obj).decode("ascii")}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[f.name] = to_wire(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {str(k): to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [to_wire(v) for v in obj]
    # numpy scalars and the like
    if hasattr(obj, "item"):
        return obj.item()
    if hasattr(obj, "to_dict"):
        return to_wire(obj.to_dict())
    raise TypeError(f"cannot serialize {type(obj).__name__} to wire")


def from_wire(typ: Any, data: Any) -> Any:
    """Rebuild a typed value from wire data based on the declared type."""
    if data is None:
        return None
    origin = typing.get_origin(typ)
    if origin is typing.Union:
        args = [a for a in typing.get_args(typ) if a is not _NoneType]
        if len(args) == 1:
            return from_wire(args[0], data)
        return data                                   # untyped union
    if typ in (Any, object) or typ is None:
        return _from_wire_untyped(data)
    if typ is bytes:
        if isinstance(data, dict) and "__bytes__" in data:
            return base64.b64decode(data["__bytes__"])
        if isinstance(data, str):
            return base64.b64decode(data)
        return bytes(data)
    if origin in (list, tuple, set, frozenset):
        args = typing.get_args(typ)
        elem = args[0] if args else Any
        vals = [from_wire(elem, v) for v in data]
        if origin is list:
            return vals
        return origin(vals)
    if origin is dict:
        args = typing.get_args(typ)
        vt = args[1] if len(args) == 2 else Any
        return {k: from_wire(vt, v) for k, v in data.items()}
    if isinstance(typ, type) and dataclasses.is_dataclass(typ):
        if not isinstance(data, dict):
            raise TypeError(f"expected object for {typ.__name__}, "
                            f"got {type(data).__name__}")
        hints = _type_hints(typ)
        kwargs = {}
        for f in dataclasses.fields(typ):
            if f.name in data:
                kwargs[f.name] = from_wire(hints.get(f.name, Any),
                                           data[f.name])
        return typ(**kwargs)
    if typ in (int, float, str, bool):
        return typ(data)
    return data


def _from_wire_untyped(data: Any) -> Any:
    if isinstance(data, dict):
        if "__bytes__" in data and len(data) == 1:
            return base64.b64decode(data["__bytes__"])
        return {k: _from_wire_untyped(v) for k, v in data.items()}
    if isinstance(data, list):
        return [_from_wire_untyped(v) for v in data]
    return data
