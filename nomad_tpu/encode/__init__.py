"""Dense encoding of cluster state into fixed-shape arrays.

This is the bridge between the host control plane (nomad_tpu.state /
nomad_tpu.core) and the device kernels (nomad_tpu.ops): a snapshot of
nodes/allocations becomes padded node x resource matrices, hashed/ordinal
attribute code matrices, and per-eval task-group demand tensors.
"""

from nomad_tpu.encode.attrs import AttrTable, hash_code, MISSING_CODE
from nomad_tpu.encode.matrixizer import (
    ClusterMatrix,
    EvalTensors,
    NUM_RESOURCE_DIMS,
    RES_CPU,
    RES_MEM,
    RES_DISK,
    pad_to_bucket,
)

__all__ = [k for k in dir() if not k.startswith("_")]
