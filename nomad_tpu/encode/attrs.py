"""Attribute codec: string node attributes -> numeric codes.

The reference evaluates constraints per node with string operations
(scheduler/feasible.go:769-841 resolveTarget/checkConstraint).  On TPU we
instead pre-encode every referenced attribute column into
- a **hash code** column (int64, stable blake2b) for =, !=, is_set ops, and
- an **ordinal code** column (int32 rank within the lexically sorted distinct
  values, -1 = missing) for <, <=, >, >= lexical ordering
so a constraint becomes a vectorized integer comparison over all nodes at
once.  regexp / version / semver / set_contains operators are evaluated on
the host over *distinct values only* and scattered into a boolean mask
column (the analog of the reference's "escaped" constraints,
scheduler/context.go:252-420).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

MISSING_CODE = np.int64(0)


def hash_code(value: str) -> np.int64:
    """Stable 63-bit non-zero hash of a string value."""
    h = int.from_bytes(hashlib.blake2b(value.encode(), digest_size=8).digest(),
                       "little") & 0x7FFF_FFFF_FFFF_FFFF
    if h == 0:
        h = 1
    return np.int64(h)


class AttrColumn:
    """One attribute column over the node axis."""

    __slots__ = ("name", "values", "hash_codes", "_ordinals", "_order_dirty")

    def __init__(self, name: str, n: int):
        self.name = name
        self.values: List[Optional[str]] = [None] * n
        self.hash_codes = np.zeros(n, dtype=np.int64)
        self._ordinals: Optional[np.ndarray] = None
        self._order_dirty = True

    def resize(self, n: int) -> None:
        cur = len(self.values)
        if n <= cur:
            return
        self.values.extend([None] * (n - cur))
        self.hash_codes = np.concatenate(
            [self.hash_codes, np.zeros(n - cur, dtype=np.int64)])
        self._order_dirty = True

    def set(self, row: int, value: Optional[str]) -> None:
        self.values[row] = value
        self.hash_codes[row] = MISSING_CODE if value is None else hash_code(value)
        self._order_dirty = True

    def ordinals(self) -> np.ndarray:
        """int32 rank of each row's value among the sorted distinct values;
        -1 where missing.  Lexical ordering matches the reference's
        checkLexicalOrder (plain string comparison)."""
        if self._order_dirty or self._ordinals is None:
            distinct = sorted({v for v in self.values if v is not None})
            rank = {v: i for i, v in enumerate(distinct)}
            self._ordinals = np.array(
                [rank[v] if v is not None else -1 for v in self.values],
                dtype=np.int32)
            self._order_dirty = False
        return self._ordinals

    def ordinal_of(self, value: str) -> Tuple[int, bool]:
        """(rank r, exact) such that value sorts at position r among distinct
        node values.  If not an exact member, r is the insertion point and
        callers must use half-open comparisons."""
        distinct = sorted({v for v in self.values if v is not None})
        import bisect
        i = bisect.bisect_left(distinct, value)
        exact = i < len(distinct) and distinct[i] == value
        return i, exact

    def distinct(self) -> List[str]:
        return sorted({v for v in self.values if v is not None})

    def host_mask(self, predicate) -> np.ndarray:
        """Evaluate `predicate(value)->bool` over distinct values, scatter to
        a bool mask over rows (missing rows -> False)."""
        table = {v: bool(predicate(v)) for v in {x for x in self.values if x is not None}}
        return np.array([table.get(v, False) for v in self.values], dtype=bool)


class AttrTable:
    """All attribute columns for a set of nodes.

    Column names follow the reference's interpolation targets
    (feasible.go:769-802): "node.unique.id", "node.datacenter",
    "node.unique.name", "node.class", "attr.<key>", "meta.<key>".
    Driver columns are exposed as "attr.driver.<name>" like the reference.
    """

    def __init__(self, n: int = 0):
        self.n = n
        self.columns: Dict[str, AttrColumn] = {}

    def column(self, name: str) -> AttrColumn:
        col = self.columns.get(name)
        if col is None:
            col = AttrColumn(name, self.n)
            self.columns[name] = col
        return col

    def resize(self, n: int) -> None:
        self.n = n
        for col in self.columns.values():
            col.resize(n)

    def set_node_row(self, row: int, node) -> None:
        """Populate every column for one node (creates columns on demand for
        attrs this node carries; other rows stay missing)."""
        self.column("node.unique.id").set(row, node.id)
        self.column("node.datacenter").set(row, node.datacenter)
        self.column("node.unique.name").set(row, node.name)
        self.column("node.class").set(row, node.node_class)
        seen = {"node.unique.id", "node.datacenter", "node.unique.name", "node.class"}
        for k, v in node.attributes.items():
            name = f"attr.{k}"
            self.column(name).set(row, str(v))
            seen.add(name)
        for k, v in node.meta.items():
            name = f"meta.{k}"
            self.column(name).set(row, str(v))
            seen.add(name)
        # clear stale values in columns this node doesn't define
        for name, col in self.columns.items():
            if name not in seen:
                col.set(row, None)

    def clear_row(self, row: int) -> None:
        for col in self.columns.values():
            col.set(row, None)

    @staticmethod
    def target_to_column(target: str) -> Optional[str]:
        """Map a constraint LTarget interpolation to a column name; a
        non-interpolated target is a literal (returns None).  Mirrors
        resolveTarget (feasible.go:769-802)."""
        if not target.startswith("${"):
            return None
        inner = target[2:-1] if target.endswith("}") else target[2:]
        if inner in ("node.unique.id", "node.datacenter", "node.unique.name",
                     "node.class"):
            return inner
        if inner.startswith("attr.") or inner.startswith("meta."):
            return inner
        return "__unresolvable__"
