"""ClusterMatrix: an incrementally-maintained columnar mirror of cluster
state, and the per-eval demand tensors shipped to the device kernels.

Reference analog: the scheduler's per-node object walks
(scheduler/rank.go BinPackIterator over RankedNode, nomad/state hot reads).
Here the state store maintains this mirror incrementally (SURVEY.md section
2.7 item 7) so an evaluation never rebuilds O(nodes) state from scratch —
it only assembles small per-job tensors plus views of resident arrays.

Axes and padding: the node axis is padded to power-of-two buckets (minimum
8) so XLA sees a small, stable set of shapes across evals (avoids
recompiles; SURVEY.md section 7 "dynamic shapes").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nomad_tpu.encode.attrs import AttrTable

# Resource dimension layout of the dense matrices.  Network bandwidth is a
# first-class dimension: where the reference accounts MBits inside
# NetworkIndex (structs/network.go:39,178), the dense design folds it into
# the same capacity/used matrices so fit checks, plan validation and the
# preemption kernel all cover bandwidth for free (ScoreFitBinPack still
# scores cpu+mem only, matching funcs.go:259-279).
RES_CPU, RES_MEM, RES_DISK, RES_NET = 0, 1, 2, 3
NUM_RESOURCE_DIMS = 4


def comparable_vec(cr) -> "np.ndarray":
    """f32[R] dense resource vector of a ComparableResources."""
    return np.array(
        [cr.cpu_shares, cr.memory_mb, cr.disk_mb,
         sum(n.mbits for n in cr.networks)], dtype=np.float32)

_PORT_WORDS = 65536 // 32


def pad_to_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class ClusterMatrix:
    """Dense, incrementally-updated node-axis mirror.

    Rows are stable: a node keeps its row for its lifetime; removed rows are
    recycled.  All arrays are kept at `capacity_rows` (a power-of-two
    bucket) and grown by re-bucketing when full.
    """

    def __init__(self, initial_rows: int = 8):
        cap = pad_to_bucket(initial_rows)
        self._n_rows = cap
        self.row_of: Dict[str, int] = {}
        self.node_ids: List[Optional[str]] = [None] * cap
        self._free_rows: List[int] = list(range(cap - 1, -1, -1))

        self.capacity = np.zeros((cap, NUM_RESOURCE_DIMS), dtype=np.float32)
        self.used = np.zeros((cap, NUM_RESOURCE_DIMS), dtype=np.float32)
        self.ready = np.zeros(cap, dtype=bool)
        self.attrs = AttrTable(cap)
        # used ports bitset per node (static collision + dynamic capacity)
        self.port_words = np.zeros((cap, _PORT_WORDS), dtype=np.uint32)
        self.dyn_port_lo = np.full(cap, 20000, dtype=np.int32)
        self.dyn_port_hi = np.full(cap, 32000, dtype=np.int32)
        # device-group id -> i32[N] instance capacity / committed usage
        self.device_caps: Dict[str, np.ndarray] = {}
        self.device_used: Dict[str, np.ndarray] = {}
        # computed-class ordinal per row (-1 = empty row): lets blocked-eval
        # class-eligibility reduce as a vectorized groupby instead of an
        # O(N) Python node walk (reference EvalEligibility keying)
        self.class_codes = np.full(cap, -1, dtype=np.int32)
        self.class_names: List[str] = []
        self._class_rank: Dict[str, int] = {}
        # generation counter bumped on any mutation (device cache invalidation)
        self.generation = 0
        # authoritative live-alloc usage, keyed by node id so it survives node
        # churn and alloc-before-node replay order:
        #   node_id -> {alloc_id: (res_vec, ports)}
        self._node_allocs: Dict[str, Dict[str, Tuple[np.ndarray, Tuple[int, ...]]]] = {}
        self._alloc_node: Dict[str, str] = {}  # alloc_id -> node_id

    # ------------------------------------------------------------- rows

    @property
    def n_rows(self) -> int:
        return self._n_rows

    def _grow(self) -> None:
        old = self._n_rows
        new = old * 2
        self.capacity = np.vstack([self.capacity, np.zeros((old, NUM_RESOURCE_DIMS), np.float32)])
        self.used = np.vstack([self.used, np.zeros((old, NUM_RESOURCE_DIMS), np.float32)])
        self.ready = np.concatenate([self.ready, np.zeros(old, bool)])
        self.port_words = np.vstack([self.port_words, np.zeros((old, _PORT_WORDS), np.uint32)])
        self.dyn_port_lo = np.concatenate([self.dyn_port_lo, np.full(old, 20000, np.int32)])
        self.dyn_port_hi = np.concatenate([self.dyn_port_hi, np.full(old, 32000, np.int32)])
        self.node_ids.extend([None] * old)
        self._free_rows.extend(range(new - 1, old - 1, -1))
        self.class_codes = np.concatenate(
            [self.class_codes, np.full(old, -1, np.int32)])
        self.attrs.resize(new)
        for k in self.device_caps:
            self.device_caps[k] = np.concatenate(
                [self.device_caps[k], np.zeros(old, np.int32)])
        for k in self.device_used:
            self.device_used[k] = np.concatenate(
                [self.device_used[k], np.zeros(old, np.int32)])
        self._n_rows = new

    # ------------------------------------------------------------- nodes

    def upsert_node(self, node) -> int:
        row = self.row_of.get(node.id)
        if row is None:
            if not self._free_rows:
                self._grow()
            row = self._free_rows.pop()
            self.row_of[node.id] = row
            self.node_ids[row] = node.id
        res = node.node_resources
        rr = node.reserved_resources
        self.capacity[row, RES_CPU] = res.cpu.cpu_shares - rr.cpu_shares
        self.capacity[row, RES_MEM] = res.memory_mb - rr.memory_mb
        self.capacity[row, RES_DISK] = res.disk_mb - rr.disk_mb
        self.capacity[row, RES_NET] = sum(n.mbits for n in res.networks)
        self.ready[row] = node.ready()
        cc = getattr(node, "computed_class", "") or ""
        code = self._class_rank.get(cc)
        if code is None:
            code = self._class_rank[cc] = len(self.class_names)
            self.class_names.append(cc)
        self.class_codes[row] = code
        self.attrs.set_node_row(row, node)
        # drivers become attr columns like the reference's driver.<name> attrs
        for name, info in node.drivers.items():
            healthy = info.get("detected") and info.get("healthy", True)
            self.attrs.column(f"attr.driver.{name}").set(
                row, "1" if healthy else None)
        # host volumes: column per volume name, value "ro"/"rw"
        for name, vol in node.host_volumes.items():
            self.attrs.column(f"hostvol.{name}").set(
                row, "ro" if vol.get("read_only") else "rw")
        # CSI node plugins: column per plugin id, "1" = healthy
        for pid, info in node.csi_node_plugins.items():
            self.attrs.column(f"csiplugin.{pid}").set(
                row, "1" if info.get("healthy") else None)
        # device capacity: numeric count column per device-group id (clear
        # stale groups first — re-registration may drop devices)
        for col in self.device_caps.values():
            col[row] = 0
        for dev in node.node_resources.devices:
            col = self.device_caps.setdefault(
                dev.id, np.zeros(self._n_rows, dtype=np.int32))
            # unhealthy instances don't count as schedulable capacity
            col[row] = len(dev.healthy_ids())
        self.dyn_port_lo[row] = res.min_dynamic_port
        self.dyn_port_hi[row] = res.max_dynamic_port
        words = np.zeros(_PORT_WORDS, dtype=np.uint32)
        for p in rr.reserved_ports:
            words[p >> 5] |= np.uint32(1 << (p & 31))
        # re-apply this node's live-alloc usage (covers allocs that arrived
        # before the node row existed, and node re-registration)
        self.used[row] = 0
        for col in self.device_used.values():
            col[row] = 0
        for vec, ports, devs in self._node_allocs.get(node.id, {}).values():
            self.used[row] += vec
            for p in ports:
                words[p >> 5] |= np.uint32(1 << (p & 31))
            for gid, cnt in devs.items():
                col = self.device_used.setdefault(
                    gid, np.zeros(self._n_rows, dtype=np.int32))
                col[row] += cnt
        self.port_words[row] = words
        self.generation += 1
        return row

    def remove_node(self, node_id: str) -> None:
        row = self.row_of.pop(node_id, None)
        if row is None:
            return
        self.node_ids[row] = None
        self.capacity[row] = 0
        self.used[row] = 0
        self.ready[row] = False
        self.class_codes[row] = -1
        self.port_words[row] = 0
        for col in self.device_caps.values():
            col[row] = 0
        for col in self.device_used.values():
            col[row] = 0
        self.attrs.clear_row(row)
        self._free_rows.append(row)
        self.generation += 1

    # ------------------------------------------------------------- allocs

    @staticmethod
    def _alloc_res_vec(alloc) -> np.ndarray:
        return comparable_vec(alloc.comparable_resources())

    @staticmethod
    def _alloc_devices(alloc) -> Dict[str, int]:
        """device group id -> instance count used by this alloc."""
        out: Dict[str, int] = {}
        for tr in alloc.allocated_resources.tasks.values():
            for d in tr.devices:
                gid = f"{d['vendor']}/{d['type']}/{d['name']}"
                out[gid] = out.get(gid, 0) + len(d.get("device_ids", []))
        return out

    @staticmethod
    def _alloc_ports(alloc) -> Tuple[int, ...]:
        ports = []
        for net in alloc.comparable_resources().networks:
            for p in net.reserved_ports:
                ports.append(p.value)
            for p in net.dynamic_ports:
                if p.value:
                    ports.append(p.value)
        for p in alloc.allocated_resources.shared_ports:
            ports.append(p.value)
        return tuple(ports)

    def _untrack(self, alloc_id: str) -> None:
        node_id = self._alloc_node.pop(alloc_id, None)
        if node_id is None:
            return
        vec, ports, devs = self._node_allocs[node_id].pop(alloc_id)
        row = self.row_of.get(node_id)
        if row is not None:
            self.used[row] -= vec
            for p in ports:
                self.port_words[row, p >> 5] &= ~np.uint32(1 << (p & 31))
            for gid, n in devs.items():
                col = self.device_used.get(gid)
                if col is not None:
                    col[row] -= n

    def upsert_alloc(self, alloc) -> None:
        """Track / untrack an allocation's resource usage on its node.
        Terminal allocations contribute nothing (AllocsFit semantics,
        funcs.go:174-178).  Usage is tracked even when the node row does not
        exist yet (restore/replay order), and applied when the node appears.
        """
        self._untrack(alloc.id)
        if not alloc.terminal_status() and alloc.node_id:
            vec = self._alloc_res_vec(alloc)
            ports = self._alloc_ports(alloc)
            devs = self._alloc_devices(alloc)
            self._node_allocs.setdefault(alloc.node_id, {})[alloc.id] = \
                (vec, ports, devs)
            self._alloc_node[alloc.id] = alloc.node_id
            row = self.row_of.get(alloc.node_id)
            if row is not None:
                self.used[row] += vec
                for p in ports:
                    self.port_words[row, p >> 5] |= np.uint32(1 << (p & 31))
                for gid, n in devs.items():
                    col = self.device_used.setdefault(
                        gid, np.zeros(self._n_rows, dtype=np.int32))
                    col[row] += n
        self.generation += 1

    def remove_alloc(self, alloc_id: str) -> None:
        if alloc_id in self._alloc_node:
            self._untrack(alloc_id)
            self.generation += 1

    # ------------------------------------------------------------- views

    def rows_for(self, node_ids: Sequence[str]) -> np.ndarray:
        return np.array([self.row_of[i] for i in node_ids if i in self.row_of],
                        dtype=np.int32)

    def dc_mask(self, datacenters: Sequence[str]) -> np.ndarray:
        col = self.attrs.column("node.datacenter")
        want = set(datacenters)
        return np.array([v in want for v in col.values], dtype=bool)

    def free_dynamic_ports(self) -> np.ndarray:
        """Count of free ports in each node's own dynamic range [lo, hi],
        exact at bit granularity.  Nodes are grouped by their (lo, hi) range
        (a handful of distinct values in practice) and each group gets a
        masked vectorized popcount over its own word window."""
        out = np.zeros(self._n_rows, dtype=np.int32)
        ranges: Dict[Tuple[int, int], List[int]] = {}
        for row in self.row_of.values():
            key = (int(self.dyn_port_lo[row]), int(self.dyn_port_hi[row]))
            ranges.setdefault(key, []).append(row)
        for (lo, hi), rows in ranges.items():
            rows_a = np.array(rows, dtype=np.int64)
            w0, w1 = lo >> 5, (hi >> 5) + 1
            words = self.port_words[rows_a, w0:w1].copy()
            # mask off bits below lo in the first word / above hi in the last
            words[:, 0] &= np.uint32(0xFFFFFFFF) << np.uint32(lo & 31)
            hi_bit = hi & 31
            last_mask = (np.uint64(1) << np.uint64(hi_bit + 1)) - np.uint64(1)
            words[:, -1] &= np.uint32(last_mask)
            byte_view = words.view(np.uint8)
            used = _POPCOUNT_TABLE[byte_view].reshape(words.shape[0], -1).sum(axis=1)
            out[rows_a] = (hi - lo + 1) - used
        return out

    def static_ports_free(self, ports: Sequence[int]) -> np.ndarray:
        """bool[N]: True where none of `ports` is already claimed."""
        if not ports:
            return np.ones(self._n_rows, dtype=bool)
        mask = np.ones(self._n_rows, dtype=bool)
        for p in ports:
            bit = (self.port_words[:, p >> 5] >> np.uint32(p & 31)) & np.uint32(1)
            mask &= bit == 0
        return mask


_POPCOUNT_TABLE = np.array([bin(i).count("1") for i in range(256)], dtype=np.int64)


@dataclass
class EvalTensors:
    """Everything one evaluation's placement pass needs, in dense form.

    Shapes: N = padded node rows, G = padded distinct task groups,
    S = padded placement slots (one per missing alloc instance).
    """
    # node axis (views/copies of ClusterMatrix state at snapshot time)
    capacity: np.ndarray          # f32[N, R]
    used: np.ndarray              # f32[N, R] — proposed usage basis for this eval
    # per-task-group
    feasible: np.ndarray          # bool[G, N] — constraints+driver+dc+ready+ports
    affinity: np.ndarray          # f32[G, N] — normalized affinity sum per node
    has_affinity: np.ndarray      # bool[G]
    desired_count: np.ndarray     # i32[G]
    penalty: np.ndarray           # bool[G, N] — rescheduling penalty nodes
    proposed_tg_count: np.ndarray # i32[G, N] — existing co-placed allocs of (job, tg)
    # spread scoring (zero-filled when the job has no spreads)
    spread_weight: np.ndarray     # f32[G] — sum of |weights| (0 = no spread)
    spread_boost: np.ndarray      # f32[G, N] — precomputed per-node spread boost
    # per-placement-slot
    demand: np.ndarray            # f32[S, R]
    slot_tg: np.ndarray           # i32[S] — index into G
    slot_active: np.ndarray       # bool[S]
    # metadata
    n_real_nodes: int = 0
    slot_names: List[str] = field(default_factory=list)      # alloc names per slot
    tg_names: List[str] = field(default_factory=list)
    node_rows: Optional[np.ndarray] = None                   # row -> ClusterMatrix row


def make_eval_tensors(n_nodes: int, n_groups: int, n_slots: int) -> EvalTensors:
    """Allocate zero-filled EvalTensors with padded shapes."""
    N = pad_to_bucket(max(n_nodes, 1))
    G = pad_to_bucket(max(n_groups, 1), minimum=1)
    S = pad_to_bucket(max(n_slots, 1), minimum=1)
    R = NUM_RESOURCE_DIMS
    return EvalTensors(
        capacity=np.zeros((N, R), np.float32),
        used=np.zeros((N, R), np.float32),
        feasible=np.zeros((G, N), bool),
        affinity=np.zeros((G, N), np.float32),
        has_affinity=np.zeros(G, bool),
        desired_count=np.ones(G, np.int32),
        penalty=np.zeros((G, N), bool),
        proposed_tg_count=np.zeros((G, N), np.int32),
        spread_weight=np.zeros(G, np.float32),
        spread_boost=np.zeros((G, N), np.float32),
        demand=np.zeros((S, R), np.float32),
        slot_tg=np.zeros(S, np.int32),
        slot_active=np.zeros(S, bool),
        n_real_nodes=n_nodes,
    )
