"""CLI (reference: command/ — mitchellh/cli subcommands registered in
command/commands.go; `nomad agent`, `nomad job run`, `nomad node status`,
...).  argparse-based; talks to the agent over the HTTP SDK."""
from nomad_tpu.command.cli import main

__all__ = ["main"]
