"""CLI command tree (reference: command/ — one module per subcommand
there; one dispatcher here).  Address/token resolution mirrors the
reference: -address / NOMAD_ADDR, -token / NOMAD_TOKEN.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from nomad_tpu.api import ApiClient, ApiError


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    cols = [header] + [[str(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(header))]
    lines = []
    for r in cols:
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(lines)


def _short(id_: str) -> str:
    return id_[:8] if id_ else ""


def _ago(ts: float) -> str:
    if not ts:
        return "-"
    d = time.time() - ts
    for unit, div in (("s", 1), ("m", 60), ("h", 3600), ("d", 86400)):
        if d < div * 100 or unit == "d":
            return f"{d/div:.0f}{unit} ago"
    return "-"


class Cli:
    def __init__(self, api: ApiClient, out=sys.stdout):
        self.api = api
        self.out = out

    def p(self, *args, end: str = "\n") -> None:
        print(*args, file=self.out, end=end)
        if end != "\n":
            self.out.flush()

    # ------------------------------------------------------------- agent

    def cmd_agent(self, args) -> int:
        from nomad_tpu.agent import Agent, AgentConfig
        if getattr(args, "config_file", ""):
            # reference merge order (command/agent/config.go): config
            # files first, CLI flags override the merged result
            from nomad_tpu.agent.config_file import load_config_file
            cfg = load_config_file(args.config_file)
            flag_overrides = {
                "name": ("name", "agent-1"),
                "bind": ("http_host", "127.0.0.1"),
                "port": ("http_port", 4646),
                "num_schedulers": ("num_schedulers", 4),
            }
            for flag, (attr, default) in flag_overrides.items():
                v = getattr(args, flag)
                if v != default:
                    setattr(cfg, attr, v)
            if args.dev:
                cfg.dev_mode = True
                cfg.server_enabled = cfg.client_enabled = True
            if args.server:
                cfg.server_enabled = True
            if args.client:
                cfg.client_enabled = True
            if args.acl_enabled:
                cfg.acl_enabled = True
            if args.data_dir:
                cfg.data_dir = args.data_dir
        else:
            cfg = AgentConfig(
                name=args.name,
                dev_mode=args.dev,
                server_enabled=args.dev or args.server,
                client_enabled=args.dev or args.client,
                http_host=args.bind,
                http_port=args.port,
                num_schedulers=args.num_schedulers,
                acl_enabled=args.acl_enabled,
                data_dir=args.data_dir or None,
            )
        agent = Agent(cfg)
        agent.start()
        self.p(f"==> nomad-tpu agent started: http={agent.http_addr} "
               f"server={cfg.server_enabled} client={cfg.client_enabled}")
        self.p("==> Ctrl-C to exit")
        try:
            while True:
                time.sleep(1.0)
        except KeyboardInterrupt:
            self.p("==> caught interrupt, shutting down")
            agent.stop()
        return 0

    # ------------------------------------------------------------- job

    @staticmethod
    def _job_vars(args) -> dict:
        """-var NAME=VALUE + -var-file files (HCL `name = value` lines,
        reference jobspec2 VarFiles/Vars)."""
        out = {}
        for path in getattr(args, "var_file", None) or []:
            from nomad_tpu.jobspec.expr import evaluate
            from nomad_tpu.jobspec.hcl import parse_hcl
            root = parse_hcl(open(path).read())
            evaluate(root)        # var files may use functions/locals
            out.update(root.attrs)
        for kv in getattr(args, "var", None) or []:
            name, _, value = kv.partition("=")
            out[name] = value
        return out

    def cmd_job_run(self, args) -> int:
        from nomad_tpu.api.codec import from_wire
        from nomad_tpu.jobspec import parse_job_file
        from nomad_tpu.structs import Job
        job = parse_job_file(args.file, self._job_vars(args))
        if args.check_index is not None:
            job.job_modify_index = args.check_index
        from nomad_tpu.api.codec import to_wire
        resp = self.api.jobs.register(job)
        self.p(f"==> Evaluation \"{_short(resp['EvalID'])}\" created")
        if args.detach:
            return 0
        return self._monitor_eval(resp["EvalID"])

    def _monitor_eval(self, eval_id: str, timeout: float = 60.0) -> int:
        deadline = time.time() + timeout
        last_status = ""
        while time.time() < deadline:
            ev = self.api.evaluations.info(eval_id)
            if ev.status != last_status:
                self.p(f"    Evaluation status: {ev.status}")
                last_status = ev.status
            if ev.status in ("complete", "failed", "canceled"):
                if ev.status == "complete":
                    allocs = self.api.evaluations.allocations(eval_id)
                    for a in allocs:
                        self.p(f"    Allocation \"{_short(a.id)}\" created "
                               f"on node \"{_short(a.node_id)}\"")
                self.p(f"==> Evaluation \"{_short(eval_id)}\" finished "
                       f"with status \"{ev.status}\"")
                return 0 if ev.status == "complete" else 2
            time.sleep(0.3)
        self.p("==> Timed out waiting for evaluation")
        return 1

    def cmd_job_status(self, args) -> int:
        if not args.job_id:
            jobs = self.api.jobs.list()
            if not jobs:
                self.p("No running jobs")
                return 0
            self.p(_fmt_table(
                [[j["ID"], j["Type"], str(j["Priority"]), j["Status"]]
                 for j in jobs],
                ["ID", "Type", "Priority", "Status"]))
            return 0
        job = self.api.jobs.info(args.job_id)
        self.p(f"ID            = {job.id}")
        self.p(f"Name          = {job.name}")
        self.p(f"Type          = {job.type}")
        self.p(f"Priority      = {job.priority}")
        self.p(f"Datacenters   = {','.join(job.datacenters)}")
        self.p(f"Namespace     = {job.namespace}")
        self.p(f"Status        = {job.status}")
        self.p(f"Version       = {job.version}")
        self.p("")
        summary = self.api.jobs.summary(job.id)
        if summary:
            self.p("Summary")
            rows = []
            for tg, counts in (summary.get("summary") or {}).items():
                rows.append([tg] + [str(counts.get(k, 0)) for k in
                                    ("queued", "starting", "running",
                                     "complete", "failed", "lost")])
            self.p(_fmt_table(rows, ["Task Group", "Queued", "Starting",
                                     "Running", "Complete", "Failed",
                                     "Lost"]))
            self.p("")
        allocs = self.api.jobs.allocations(args.job_id)
        if allocs:
            self.p("Allocations")
            self.p(_fmt_table(
                [[_short(a["ID"]), _short(a["NodeID"]), a["TaskGroup"],
                  a["DesiredStatus"], a["ClientStatus"]] for a in allocs],
                ["ID", "Node ID", "Task Group", "Desired", "Status"]))
        return 0

    def cmd_job_stop(self, args) -> int:
        resp = self.api.jobs.deregister(args.job_id, purge=args.purge)
        self.p(f"==> Evaluation \"{_short(resp['EvalID'] or '')}\" created")
        if args.detach or not resp["EvalID"]:
            return 0
        return self._monitor_eval(resp["EvalID"])

    def cmd_job_plan(self, args) -> int:
        from nomad_tpu.jobspec import parse_job_file
        job = parse_job_file(args.file, self._job_vars(args))
        resp = self.api.jobs.plan(job)
        ann = resp.get("annotations") or {}
        tg_updates = (ann.get("desired_tg_updates") or {})
        for tg, upd in tg_updates.items():
            self.p(f"Task Group: \"{tg}\"")
            for field in ("place", "stop", "migrate", "in_place_update",
                          "destructive_update", "canary", "ignore"):
                v = upd.get(field, 0) if isinstance(upd, dict) else \
                    getattr(upd, field, 0)
                if v:
                    self.p(f"  {field}: {v}")
        self.p(f"==> Placements: {resp['placements']}  "
               f"Preemptions: {resp['preemptions']}")
        failed = resp.get("failed_tg_allocs")
        if failed:
            self.p(f"==> WARNING: failed placements: {list(failed)}")
        self.p("Job Modify Index: "
               f"{resp.get('job_modify_index', 0)}")
        return 0

    def cmd_job_inspect(self, args) -> int:
        from nomad_tpu.api.codec import to_wire
        job = self.api.jobs.info(args.job_id)
        self.p(json.dumps(to_wire(job), indent=2, sort_keys=True))
        return 0

    def cmd_job_dispatch(self, args) -> int:
        import base64
        payload = ""
        if args.payload_file:
            with open(args.payload_file, "rb") as fh:
                payload = base64.b64encode(fh.read()).decode()
        meta = dict(kv.split("=", 1) for kv in args.meta or [])
        resp = self.api.jobs.dispatch(args.job_id, payload=payload,
                                      meta=meta)
        self.p(f"Dispatched Job ID = {resp['dispatched_job_id']}")
        self.p(f"Evaluation ID     = {_short(resp['eval_id'])}")
        return 0

    def cmd_job_history(self, args) -> int:
        for v in self.api.jobs.versions(args.job_id):
            self.p(f"Version     = {v['version']}")
            self.p(f"Stable      = {v['stable']}")
            self.p(f"Submit Date = {_ago(v.get('submit_time', 0))}")
            self.p("")
        return 0

    def cmd_job_revert(self, args) -> int:
        resp = self.api.jobs.revert(args.job_id, args.version)
        self.p(f"==> Reverted to version {resp['job_version']}; "
               f"evaluation \"{_short(resp['eval_id'])}\" created")
        return 0

    def cmd_job_periodic_force(self, args) -> int:
        resp = self.api.jobs.periodic_force(args.job_id)
        self.p(f"Dispatched Job ID = {resp['DispatchedJobID']}")
        return 0

    def cmd_job_validate(self, args) -> int:
        from nomad_tpu.jobspec import parse_job_file
        try:
            job = parse_job_file(args.file, self._job_vars(args))
        except Exception as e:                      # noqa: BLE001
            self.p(f"Job validation errors: {e}")
            return 1
        if not job.task_groups:
            self.p("Job validation errors: no task groups")
            return 1
        self.p("Job validation successful")
        return 0

    # ------------------------------------------------------------- node

    def cmd_node_status(self, args) -> int:
        if not args.node_id:
            nodes = self.api.nodes.list()
            self.p(_fmt_table(
                [[_short(n["ID"]), n["Name"], n["Datacenter"],
                  n["NodeClass"] or "<none>",
                  "true" if n["Drain"] else "false",
                  n["SchedulingEligibility"], n["Status"]] for n in nodes],
                ["ID", "Name", "DC", "Class", "Drain", "Eligibility",
                 "Status"]))
            return 0
        n = self.api.nodes.info(args.node_id)
        self.p(f"ID           = {n.id}")
        self.p(f"Name         = {n.name}")
        self.p(f"Datacenter   = {n.datacenter}")
        self.p(f"Class        = {n.node_class or '<none>'}")
        self.p(f"Status       = {n.status}")
        self.p(f"Eligibility  = {n.scheduling_eligibility}")
        self.p(f"Drain        = {n.drain_strategy is not None}")
        res = n.node_resources
        self.p(f"Resources    = cpu {res.cpu.cpu_shares} MHz, "
               f"mem {res.memory_mb} MiB, disk {res.disk_mb} MiB")
        allocs = self.api.nodes.allocations(n.id)
        live = [a for a in allocs if not a.terminal_status()]
        self.p(f"Allocations  = {len(live)} non-terminal")
        return 0

    def cmd_node_drain(self, args) -> int:
        if args.disable:
            self.api.nodes.drain_disable(args.node_id)
            self.p(f"Node \"{_short(args.node_id)}\" drain disabled")
        else:
            self.api.nodes.drain(args.node_id, deadline_s=args.deadline)
            self.p(f"Node \"{_short(args.node_id)}\" draining "
                   f"(deadline {args.deadline}s)")
        return 0

    def cmd_node_eligibility(self, args) -> int:
        self.api.nodes.eligibility(args.node_id, args.enable)
        state = "eligible" if args.enable else "ineligible"
        self.p(f"Node \"{_short(args.node_id)}\" marked {state}")
        return 0

    # ------------------------------------------------------------- eval/alloc

    def cmd_eval_status(self, args) -> int:
        ev = self.api.evaluations.info(args.eval_id)
        self.p(f"ID            = {_short(ev.id)}")
        self.p(f"Status        = {ev.status}")
        self.p(f"Type          = {ev.type}")
        self.p(f"TriggeredBy   = {ev.triggered_by}")
        self.p(f"Job ID        = {ev.job_id}")
        self.p(f"Priority      = {ev.priority}")
        if ev.status_description:
            self.p(f"Description   = {ev.status_description}")
        if ev.queued_allocations:
            self.p(f"Queued Allocs = {dict(ev.queued_allocations)}")
        return 0

    def cmd_eval_list(self, args) -> int:
        evs = self.api.evaluations.list()
        self.p(_fmt_table(
            [[_short(e.id), str(e.priority), e.triggered_by, e.job_id,
              e.status] for e in evs[:50]],
            ["ID", "Priority", "Triggered By", "Job ID", "Status"]))
        return 0

    def cmd_alloc_status(self, args) -> int:
        a = self.api.allocations.info(args.alloc_id)
        self.p(f"ID            = {_short(a.id)}")
        self.p(f"Name          = {a.name}")
        self.p(f"Node ID       = {_short(a.node_id)}")
        self.p(f"Job ID        = {a.job_id}")
        self.p(f"Client Status = {a.client_status}")
        self.p(f"Desired       = {a.desired_status}")
        if args.verbose and a.metrics:
            m = a.metrics
            self.p("")
            self.p("Placement Metrics")
            self.p(f"  Nodes Evaluated = {m.nodes_evaluated}")
            self.p(f"  Nodes Filtered  = {m.nodes_filtered}")
            self.p(f"  Nodes Exhausted = {m.nodes_exhausted}")
            for sm in m.score_meta or []:
                self.p(f"  {_short(sm.get('node_id', ''))} "
                       f"norm={sm.get('norm_score', 0):.3f}")
        for name, ts in (a.task_states or {}).items():
            self.p("")
            self.p(f"Task \"{name}\" is \"{ts.state}\"")
            for e in ts.events[-5:]:
                self.p(f"  {e.get('type')}: {e.get('detail', '')}")
        return 0

    def cmd_alloc_stop(self, args) -> int:
        resp = self.api.allocations.stop(args.alloc_id)
        self.p(f"==> Evaluation \"{_short(resp['eval_id'])}\" created")
        return 0

    def _resolve_task(self, alloc_id: str, task: str) -> str:
        if task:
            return task
        a = self.api.allocations.info(alloc_id)
        names = sorted((a.task_states or {}).keys())
        if len(names) != 1:
            raise SystemExit(
                f"allocation has {len(names)} tasks; pass one of "
                f"{names}")
        return names[0]

    def cmd_alloc_logs(self, args) -> int:
        """alloc logs [-stderr] [-f] <alloc_id> [task] (reference
        command/alloc_logs.go over client/fs_endpoint.go)."""
        kind = "stderr" if args.stderr else "stdout"
        task = self._resolve_task(args.alloc_id, args.task)
        if not args.follow:
            data = self.api.allocations.logs(args.alloc_id, task, kind)
            self.p(data.decode(errors="replace"), end="")
            return 0
        try:
            for chunk in self.api.allocations.logs_follow(
                    args.alloc_id, task, kind,
                    timeout=args.follow_timeout):
                self.p(chunk.decode(errors="replace"), end="")
        except KeyboardInterrupt:
            pass
        return 0

    def cmd_alloc_fs(self, args) -> int:
        """alloc fs <alloc_id> [path] — ls for dirs, cat for files."""
        path = args.path or "/"
        st = self.api.allocations.fs_stat(args.alloc_id, path)
        if st.get("IsDir"):
            for e in self.api.allocations.fs_list(args.alloc_id, path):
                kind = "dir " if e.get("IsDir") else "file"
                self.p(f"{kind}  {e.get('Size', 0):>10}  {e['Name']}")
        else:
            data = self.api.allocations.fs_cat(args.alloc_id, path)
            self.p(data.decode(errors="replace"), end="")
        return 0

    # ------------------------------------------------------------- deployment

    def cmd_deployment_list(self, args) -> int:
        deps = self.api.deployments.list()
        self.p(_fmt_table(
            [[_short(d.id), d.job_id, str(d.job_version), d.status]
             for d in deps],
            ["ID", "Job ID", "Job Version", "Status"]))
        return 0

    def cmd_deployment_status(self, args) -> int:
        d = self.api.deployments.info(args.deployment_id)
        self.p(f"ID          = {_short(d.id)}")
        self.p(f"Job ID      = {d.job_id}")
        self.p(f"Job Version = {d.job_version}")
        self.p(f"Status      = {d.status}")
        self.p(f"Description = {d.status_description}")
        rows = []
        for tg, st in (d.task_groups or {}).items():
            rows.append([tg, str(st.desired_total), str(st.placed_allocs),
                         str(st.healthy_allocs), str(st.unhealthy_allocs)])
        if rows:
            self.p("")
            self.p(_fmt_table(rows, ["Task Group", "Desired", "Placed",
                                     "Healthy", "Unhealthy"]))
        return 0

    def cmd_deployment_promote(self, args) -> int:
        self.api.deployments.promote(args.deployment_id)
        self.p("Deployment promoted")
        return 0

    def cmd_deployment_fail(self, args) -> int:
        self.api.deployments.fail(args.deployment_id)
        self.p("Deployment marked failed")
        return 0

    def cmd_deployment_pause(self, args) -> int:
        self.api.deployments.pause(args.deployment_id, not args.resume)
        self.p("Deployment " + ("resumed" if args.resume else "paused"))
        return 0

    # ------------------------------------------------------------- misc

    def cmd_server_members(self, args) -> int:
        members = self.api.system.members()  # analysis: allow(lock-discipline) — SystemApi.members is an HTTP client method, not Membership's lock-protected table
        leader = self.api.system.leader()
        rows = [[m["Name"], "leader" if m["Name"] == leader else "follower"]
                for m in members["Members"]]
        self.p(_fmt_table(rows, ["Name", "Raft Status"]))
        return 0

    def cmd_job_scale(self, args) -> int:
        resp = self.api.jobs.scale(args.job_id, args.group,
                                   count=args.count)
        self.p(f"Evaluation ID: {resp.get('eval_id')}")
        return 0

    def cmd_job_scale_status(self, args) -> int:
        st = self.api.jobs.scale_status(args.job_id)
        rows = [[g, d["desired"], d["placed"], d["running"], d["healthy"]]
                for g, d in sorted(st["task_groups"].items())]
        self.p(_fmt_table(rows, ["Group", "Desired", "Placed", "Running",
                                 "Healthy"]))
        return 0

    def cmd_service_list(self, args) -> int:
        rows = [[s["service_name"], s["namespace"], s["instances"]]
                for s in self.api.services.list()]
        self.p(_fmt_table(rows, ["Service", "Namespace", "Instances"]))
        return 0

    def cmd_service_info(self, args) -> int:
        rows = [[s.id, s.alloc_id[:8], s.address, s.port, s.health]
                for s in self.api.services.get(args.name)]
        self.p(_fmt_table(rows, ["ID", "Alloc", "Address", "Port",
                                 "Health"]))
        return 0

    def cmd_status(self, args) -> int:
        if getattr(args, "prefix", None):
            # server-side prefix search across contexts
            m = self.api.system.search(args.prefix)["Matches"]
            for ctx in sorted(m):
                for i in m[ctx]:
                    self.p(f"{ctx[:-1] if ctx.endswith('s') else ctx}\t{i}")
            return 0
        return self.cmd_job_status(args)

    def cmd_operator_scheduler_get(self, args) -> int:
        cfg = self.api.operator.scheduler_get_configuration()
        self.p(f"Scheduler Algorithm        = {cfg.scheduler_algorithm}")
        self.p(f"Memory Oversubscription    = "
               f"{cfg.memory_oversubscription_enabled}")
        self.p(f"Preemption (system jobs)   = "
               f"{cfg.preemption_config.system_scheduler_enabled}")
        self.p(f"Preemption (service jobs)  = "
               f"{cfg.preemption_config.service_scheduler_enabled}")
        self.p(f"Preemption (batch jobs)    = "
               f"{cfg.preemption_config.batch_scheduler_enabled}")
        self.p(f"Fair Dequeue               = {cfg.fair_dequeue_enabled}")
        self.p(f"Default Namespace Weight   = "
               f"{cfg.default_namespace_weight}")
        for ns, w in sorted((cfg.namespace_weights or {}).items()):
            self.p(f"Namespace Weight           = {ns}={w}")
        return 0

    def cmd_operator_scheduler_set(self, args) -> int:
        cfg = self.api.operator.scheduler_get_configuration()
        if args.scheduler_algorithm:
            cfg.scheduler_algorithm = args.scheduler_algorithm
        if args.memory_oversubscription is not None:
            cfg.memory_oversubscription_enabled = \
                args.memory_oversubscription == "true"
        if args.fair_dequeue is not None:
            cfg.fair_dequeue_enabled = args.fair_dequeue == "true"
        if args.default_namespace_weight is not None:
            cfg.default_namespace_weight = args.default_namespace_weight
        for kv in args.namespace_weight or []:
            ns, _, w = kv.partition("=")
            cfg.namespace_weights[ns] = int(w)
        self.api.operator.scheduler_set_configuration(cfg)
        self.p("Scheduler configuration updated!")
        return 0

    def cmd_operator_raft_list_peers(self, args) -> int:
        cfg = self.api.operator.raft_get_configuration()
        rows = [[s["ID"],
                 "leader" if s.get("Leader") else "follower",
                 "voter" if s.get("Voter") else "non-voter"]
                for s in cfg["Servers"]]
        self.p(_fmt_table(rows, ["Node", "State", "Voter"]))
        return 0

    def cmd_operator_raft_remove_peer(self, args) -> int:
        out = self.api.operator.raft_remove_peer(args.peer_id)
        self.p(f"Removed peer {args.peer_id} "
               f"(configuration index {out['Index']})")
        return 0

    def cmd_operator_transfer_leadership(self, args) -> int:
        out = self.api.operator.raft_transfer_leadership(
            getattr(args, "peer_id", None))
        if out.get("Transferred"):
            self.p(f"Leadership transferred to {out['Leader']}")
            return 0
        self.p("Leadership transfer did not complete")
        return 1

    def cmd_operator_integrity(self, args) -> int:
        v = self.api.operator.integrity()
        last = v.get("last") or {}
        self.p(f"Server              = {v.get('server')}"
               f"{' (leader)' if v.get('leader') else ''}")
        self.p(f"Quarantined         = {v.get('quarantined')}"
               + (f" ({v['quarantine_reason']})"
                  if v.get("quarantine_reason") else ""))
        self.p(f"Last Checkpoint     = "
               + (f"index {last['index']}  digest {last['digest']}  "
                  f"{'full' if last.get('full') else 'incremental'}"
                  if last else "<none>"))
        c = v.get("counters") or {}
        self.p(f"Checkpoints         = {c.get('checkpoints', 0)} "
               f"({c.get('full_walks', 0)} full walks)")
        self.p(f"Alarms / Repairs    = {c.get('alarms', 0)} alarms, "
               f"{c.get('repairs_started', 0)} repairs started, "
               f"{c.get('repairs_verified', 0)} verified")
        peers = v.get("peers") or {}
        if peers:
            rows = []
            for name in sorted(peers):
                p = peers[name]
                rows.append([
                    name,
                    str(p.get("index")) if p.get("index") is not None
                    else "-",
                    p.get("digest") or "-",
                    str(p.get("lag")) if p.get("lag") is not None
                    else "-",
                    p.get("divergent") or "",
                    str(p.get("unverified_acks", 0))])
            self.p(_fmt_table(rows, ["Peer", "Index", "Digest", "Lag",
                                     "Divergent", "Unverified"]))
        return 1 if v.get("quarantined") else 0

    def cmd_operator_trace(self, args) -> int:
        if not getattr(args, "trace_id", None):
            traces = self.api.operator.traces()
            if not traces:
                self.p("No traces sampled (is NOMAD_TPU_TRACE=1 set "
                       "on the agent?)")
                return 0
            rows = [[t["trace_id"], t["root"],
                     f"{t['duration'] * 1000.0:.2f}ms",
                     str(t["spans"]), ",".join(t["nodes"])]
                    for t in traces]
            self.p(_fmt_table(
                rows, ["Trace ID", "Root", "Duration", "Spans",
                       "Nodes"]))
            return 0
        if getattr(args, "chrome_out", None):
            doc = self.api.operator.trace_chrome(args.trace_id)
            with open(args.chrome_out, "w") as f:
                json.dump(doc, f)
            self.p(f"Wrote {len(doc['traceEvents'])} events to "
                   f"{args.chrome_out} (open in Perfetto / "
                   f"chrome://tracing)")
            return 0
        out = self.api.operator.trace(args.trace_id)
        spans = out["spans"]
        if not spans:
            self.p(f"No spans for trace {args.trace_id}")
            return 1
        t0 = min(sp["start"] for sp in spans)
        rows = [[f"+{(sp['start'] - t0) * 1000.0:.2f}ms",
                 f"{sp['duration'] * 1000.0:.2f}ms",
                 sp["node"], sp["name"],
                 "" if not sp["parent_id"] else sp["parent_id"][:8]]
                for sp in spans]
        self.p(f"Trace {args.trace_id} ({len(spans)} spans)")
        self.p(_fmt_table(rows, ["Start", "Duration", "Node", "Span",
                                 "Parent"]))
        return 0

    def cmd_acl_bootstrap(self, args) -> int:
        t = self.api.acl.bootstrap()
        self.p(f"Accessor ID = {t['AccessorID']}")
        self.p(f"Secret ID   = {t['SecretID']}")
        self.p(f"Type        = {t['Type']}")
        return 0

    def cmd_acl_policy_apply(self, args) -> int:
        with open(args.file) as fh:
            rules = fh.read()
        self.api.acl.upsert_policy(args.name, rules,
                                   args.description or "")
        self.p(f"Successfully wrote \"{args.name}\" ACL policy!")
        return 0

    def cmd_acl_token_create(self, args) -> int:
        t = self.api.acl.create_token(
            name=args.name or "", type_=args.type,
            policies=args.policy or [])
        self.p(f"Accessor ID = {t['AccessorID']}")
        self.p(f"Secret ID   = {t['SecretID']}")
        return 0

    def cmd_namespace_list(self, args) -> int:
        for ns in self.api.namespaces.list():
            self.p(f"{ns['name']}\t{ns.get('quota', '') or '<none>'}\t"
                   f"{ns.get('description', '')}")
        return 0

    def cmd_namespace_apply(self, args) -> int:
        self.api.namespaces.register(args.name, args.description or "",
                                     quota=args.quota or "")
        self.p(f"Successfully applied namespace \"{args.name}\"!")
        return 0

    def cmd_namespace_delete(self, args) -> int:
        self.api.namespaces.delete(args.name)
        self.p(f"Successfully deleted namespace \"{args.name}\"!")
        return 0

    # ------------------------------------------------------------- quota

    @staticmethod
    def _fmt_limit(v) -> str:
        return "-" if v is None else str(v)

    def cmd_quota_list(self, args) -> int:
        rows = [[s["name"], self._fmt_limit(s.get("cpu")),
                 self._fmt_limit(s.get("memory_mb")),
                 self._fmt_limit(s.get("devices")),
                 self._fmt_limit(s.get("allocs")),
                 s.get("description", "")]
                for s in self.api.quotas.list()]
        self.p(_fmt_table(rows, ["Name", "CPU", "Memory MiB", "Devices",
                                 "Allocs", "Description"]))
        return 0

    def cmd_quota_apply(self, args) -> int:
        spec = {"name": args.name, "description": args.description or ""}
        for dim in ("cpu", "memory_mb", "devices", "allocs"):
            v = getattr(args, dim)
            if v is not None:
                spec[dim] = v
        self.api.quotas.register(spec)
        self.p(f"Successfully applied quota specification \"{args.name}\"!")
        return 0

    def cmd_quota_delete(self, args) -> int:
        self.api.quotas.delete(args.name)
        self.p(f"Successfully deleted quota \"{args.name}\"!")
        return 0

    def cmd_quota_usage(self, args) -> int:
        if args.usage_ns:
            usages = {args.usage_ns: self.api.quotas.usage(
                args.usage_ns).get("Usage") or {}}
        else:
            usages = self.api.quotas.usages()
        rows = [[ns, str(u.get("cpu", 0)), str(u.get("memory_mb", 0)),
                 str(u.get("devices", 0)), str(u.get("allocs", 0))]
                for ns, u in sorted(usages.items())]
        self.p(_fmt_table(rows, ["Namespace", "CPU", "Memory MiB",
                                 "Devices", "Allocs"]))
        return 0

    def cmd_volume_register(self, args) -> int:
        import json as _json
        with open(args.file) as f:
            text = f.read()
        try:
            vol = _json.loads(text)
        except ValueError:
            from nomad_tpu.jobspec.hcl import parse_hcl
            body = parse_hcl(text)
            b = body.first("volume") or body
            vol = {
                "ID": b.get("id", ""), "Name": b.get("name", ""),
                "PluginID": b.get("plugin_id", ""),
                "AccessMode": b.get("access_mode", ""),
                "AttachmentMode": b.get("attachment_mode", ""),
            }
        self.api.volumes.register(vol, namespace=args.namespace)
        self.p(f"Successfully registered volume \"{vol.get('ID', '')}\"!")
        return 0

    def cmd_volume_status(self, args) -> int:
        if args.vol_id:
            v = self.api.volumes.info(args.vol_id, namespace=args.namespace)
            for k in ("ID", "Name", "PluginID", "AccessMode", "Schedulable",
                      "CurrentReaders", "CurrentWriters", "NodesHealthy",
                      "NodesExpected"):
                self.p(f"{k:<18} = {v.get(k)}")
        else:
            self.p("ID\tPlugin\tSchedulable\tAccess")
            for v in self.api.volumes.list(namespace=args.namespace):
                self.p(f"{v['ID']}\t{v['PluginID']}\t"
                       f"{v['Schedulable']}\t{v['AccessMode'] or '<none>'}")
        return 0

    def cmd_volume_deregister(self, args) -> int:
        self.api.volumes.deregister(args.vol_id, namespace=args.namespace,
                                    force=args.force)
        self.p(f"Successfully deregistered volume \"{args.vol_id}\"!")
        return 0

    def cmd_plugin_status(self, args) -> int:
        if args.plugin_id:
            v = self.api.plugins.info(args.plugin_id)
            for k in ("ID", "Provider", "ControllersHealthy",
                      "ControllersExpected", "NodesHealthy", "NodesExpected"):
                self.p(f"{k:<20} = {v.get(k)}")
        else:
            self.p("ID\tProvider\tControllers Healthy\tNodes Healthy")
            for v in self.api.plugins.list():
                self.p(f"{v['ID']}\t{v.get('Provider', '')}\t"
                       f"{v['ControllersHealthy']}/{v['ControllersExpected']}\t"
                       f"{v['NodesHealthy']}/{v['NodesExpected']}")
        return 0

    def cmd_version(self, args) -> int:
        from nomad_tpu import __version__
        self.p(f"nomad-tpu v{__version__}")
        return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nomad-tpu",
        description="TPU-native cluster scheduler (Nomad-capability)")
    p.add_argument("-address", default=os.environ.get(
        "NOMAD_ADDR", "http://127.0.0.1:4646"))
    p.add_argument("-token", default=os.environ.get("NOMAD_TOKEN", ""))
    p.add_argument("-namespace", default=os.environ.get(
        "NOMAD_NAMESPACE", "default"))
    # target region (reference -region): the contacted server forwards
    # the request over the WAN when the region is not its own
    p.add_argument("-region", default=os.environ.get("NOMAD_REGION", ""),
                   help="region to route the request to")
    # consistency mode for reads (reference -stale / -consistent): stale
    # lets any server answer from its local store; consistent forces a
    # full raft read-index round; default is leader lease reads
    p.add_argument("-stale", action="store_true",
                   help="allow any server to answer without forwarding")
    p.add_argument("-consistent", action="store_true",
                   help="force a fully linearizable read-index read")
    sub = p.add_subparsers(dest="cmd", required=True)

    ag = sub.add_parser("agent", help="run an agent")
    ag.add_argument("-dev", action="store_true")
    ag.add_argument("-server", action="store_true")
    ag.add_argument("-client", action="store_true")
    ag.add_argument("-bind", default="127.0.0.1")
    ag.add_argument("-port", type=int, default=4646)
    ag.add_argument("-name", default="agent-1")
    ag.add_argument("-num-schedulers", type=int, default=4,
                    dest="num_schedulers")
    ag.add_argument("-acl-enabled", action="store_true",
                    dest="acl_enabled")
    ag.add_argument("-data-dir", default="", dest="data_dir")
    ag.add_argument("-config", default="", dest="config_file",
                    help="HCL agent configuration file")
    ag.set_defaults(fn="cmd_agent")

    job = sub.add_parser("job", help="job commands").add_subparsers(
        dest="sub", required=True)
    j = job.add_parser("run")
    j.add_argument("file")
    j.add_argument("-var", action="append", dest="var",
                   default=[], metavar="NAME=VALUE")
    j.add_argument("-var-file", action="append",
                   dest="var_file", default=[])
    j.add_argument("-detach", action="store_true")
    j.add_argument("-check-index", type=int, default=None,
                   dest="check_index")
    j.set_defaults(fn="cmd_job_run")
    j = job.add_parser("status")
    j.add_argument("job_id", nargs="?")
    j.set_defaults(fn="cmd_job_status")
    j = job.add_parser("stop")
    j.add_argument("job_id")
    j.add_argument("-purge", action="store_true")
    j.add_argument("-detach", action="store_true")
    j.set_defaults(fn="cmd_job_stop")
    j = job.add_parser("plan")
    j.add_argument("file")
    j.add_argument("-var", action="append", dest="var",
                   default=[], metavar="NAME=VALUE")
    j.add_argument("-var-file", action="append",
                   dest="var_file", default=[])
    j.set_defaults(fn="cmd_job_plan")
    j = job.add_parser("inspect")
    j.add_argument("job_id")
    j.set_defaults(fn="cmd_job_inspect")
    j = job.add_parser("validate")
    j.add_argument("file")
    j.add_argument("-var", action="append", dest="var",
                   default=[], metavar="NAME=VALUE")
    j.add_argument("-var-file", action="append",
                   dest="var_file", default=[])
    j.set_defaults(fn="cmd_job_validate")
    j = job.add_parser("dispatch")
    j.add_argument("job_id")
    j.add_argument("payload_file", nargs="?")
    j.add_argument("-meta", action="append")
    j.set_defaults(fn="cmd_job_dispatch")
    j = job.add_parser("scale")
    j.add_argument("job_id")
    j.add_argument("group")
    j.add_argument("count", type=int)
    j.set_defaults(fn="cmd_job_scale")
    j = job.add_parser("scale-status")
    j.add_argument("job_id")
    j.set_defaults(fn="cmd_job_scale_status")
    j = job.add_parser("history")
    j.add_argument("job_id")
    j.set_defaults(fn="cmd_job_history")
    j = job.add_parser("revert")
    j.add_argument("job_id")
    j.add_argument("version", type=int)
    j.set_defaults(fn="cmd_job_revert")
    j = job.add_parser("periodic-force")
    j.add_argument("job_id")
    j.set_defaults(fn="cmd_job_periodic_force")

    node = sub.add_parser("node", help="node commands").add_subparsers(
        dest="sub", required=True)
    n = node.add_parser("status")
    n.add_argument("node_id", nargs="?")
    n.set_defaults(fn="cmd_node_status")
    n = node.add_parser("drain")
    n.add_argument("node_id")
    n.add_argument("-disable", action="store_true")
    n.add_argument("-deadline", type=float, default=3600.0)
    n.set_defaults(fn="cmd_node_drain")
    n = node.add_parser("eligibility")
    n.add_argument("node_id")
    g = n.add_mutually_exclusive_group(required=True)
    g.add_argument("-enable", dest="enable", action="store_true")
    g.add_argument("-disable", dest="enable", action="store_false")
    n.set_defaults(fn="cmd_node_eligibility")

    ev = sub.add_parser("eval", help="eval commands").add_subparsers(
        dest="sub", required=True)
    e = ev.add_parser("status")
    e.add_argument("eval_id")
    e.set_defaults(fn="cmd_eval_status")
    e = ev.add_parser("list")
    e.set_defaults(fn="cmd_eval_list")

    al = sub.add_parser("alloc", help="alloc commands").add_subparsers(
        dest="sub", required=True)
    a = al.add_parser("status")
    a.add_argument("alloc_id")
    a.add_argument("-verbose", action="store_true")
    a.set_defaults(fn="cmd_alloc_status")
    a = al.add_parser("stop")
    a.add_argument("alloc_id")
    a.set_defaults(fn="cmd_alloc_stop")
    a = al.add_parser("logs")
    a.add_argument("alloc_id")
    a.add_argument("task", nargs="?", default="")
    a.add_argument("-stderr", action="store_true")
    a.add_argument("-f", action="store_true", dest="follow")
    a.add_argument("-follow-timeout", type=float, default=30.0,
                   dest="follow_timeout")
    a.set_defaults(fn="cmd_alloc_logs")
    a = al.add_parser("fs")
    a.add_argument("alloc_id")
    a.add_argument("path", nargs="?", default="/")
    a.set_defaults(fn="cmd_alloc_fs")

    dep = sub.add_parser("deployment",
                         help="deployment commands").add_subparsers(
        dest="sub", required=True)
    d = dep.add_parser("list")
    d.set_defaults(fn="cmd_deployment_list")
    d = dep.add_parser("status")
    d.add_argument("deployment_id")
    d.set_defaults(fn="cmd_deployment_status")
    d = dep.add_parser("promote")
    d.add_argument("deployment_id")
    d.set_defaults(fn="cmd_deployment_promote")
    d = dep.add_parser("fail")
    d.add_argument("deployment_id")
    d.set_defaults(fn="cmd_deployment_fail")
    d = dep.add_parser("pause")
    d.add_argument("deployment_id")
    d.add_argument("-resume", action="store_true")
    d.set_defaults(fn="cmd_deployment_pause")

    srv = sub.add_parser("server", help="server commands").add_subparsers(
        dest="sub", required=True)
    s = srv.add_parser("members")
    s.set_defaults(fn="cmd_server_members")

    op = sub.add_parser("operator",
                        help="operator commands").add_subparsers(
        dest="sub", required=True)
    sch = op.add_parser("scheduler").add_subparsers(dest="sub2",
                                                    required=True)
    o = sch.add_parser("get-config")
    o.set_defaults(fn="cmd_operator_scheduler_get")
    o = sch.add_parser("set-config")
    o.add_argument("-scheduler-algorithm", dest="scheduler_algorithm",
                   choices=["binpack", "spread"], default=None)
    o.add_argument("-memory-oversubscription",
                   dest="memory_oversubscription",
                   choices=["true", "false"], default=None)
    o.add_argument("-fair-dequeue", dest="fair_dequeue",
                   choices=["true", "false"], default=None,
                   help="weighted fair eval dequeue across namespaces")
    o.add_argument("-default-namespace-weight", type=int, default=None,
                   dest="default_namespace_weight")
    o.add_argument("-namespace-weight", action="append",
                   dest="namespace_weight", metavar="NS=WEIGHT",
                   help="per-namespace dequeue weight (repeatable)")
    o.set_defaults(fn="cmd_operator_scheduler_set")
    rft = op.add_parser("raft").add_subparsers(dest="sub2", required=True)
    o = rft.add_parser("list-peers")
    o.set_defaults(fn="cmd_operator_raft_list_peers")
    o = rft.add_parser("remove-peer")
    o.add_argument("-peer-id", dest="peer_id", required=True)
    o.set_defaults(fn="cmd_operator_raft_remove_peer")
    o = op.add_parser("transfer-leadership")
    o.add_argument("-peer-id", dest="peer_id", default=None)
    o.set_defaults(fn="cmd_operator_transfer_leadership")
    o = op.add_parser("trace",
                      help="list sampled traces, show one, or export "
                           "Chrome-trace JSON for Perfetto")
    o.add_argument("trace_id", nargs="?", default=None)
    o.add_argument("-chrome", dest="chrome_out", default=None,
                   metavar="FILE")
    o.set_defaults(fn="cmd_operator_trace")
    o = op.add_parser("integrity",
                      help="replica-integrity plane: last checkpoint "
                           "digest, per-peer divergence, quarantine "
                           "state, repair counters")
    o.set_defaults(fn="cmd_operator_integrity")

    acl = sub.add_parser("acl", help="acl commands").add_subparsers(
        dest="sub", required=True)
    c = acl.add_parser("bootstrap")
    c.set_defaults(fn="cmd_acl_bootstrap")
    pol = acl.add_parser("policy").add_subparsers(dest="sub2",
                                                  required=True)
    c = pol.add_parser("apply")
    c.add_argument("name")
    c.add_argument("file")
    c.add_argument("-description", default="")
    c.set_defaults(fn="cmd_acl_policy_apply")
    tok = acl.add_parser("token").add_subparsers(dest="sub2",
                                                 required=True)
    c = tok.add_parser("create")
    c.add_argument("-name", default="")
    c.add_argument("-type", default="client")
    c.add_argument("-policy", action="append")
    c.set_defaults(fn="cmd_acl_token_create")

    ns = sub.add_parser("namespace",
                        help="namespace commands").add_subparsers(
        dest="sub", required=True)
    c = ns.add_parser("list")
    c.set_defaults(fn="cmd_namespace_list")
    c = ns.add_parser("apply")
    c.add_argument("name")
    c.add_argument("-description", default="")
    c.add_argument("-quota", default="",
                   help="quota spec governing this namespace")
    c.set_defaults(fn="cmd_namespace_apply")
    c = ns.add_parser("delete")
    c.add_argument("name")
    c.set_defaults(fn="cmd_namespace_delete")

    qt = sub.add_parser("quota",
                        help="resource quota commands").add_subparsers(
        dest="sub", required=True)
    c = qt.add_parser("list")
    c.set_defaults(fn="cmd_quota_list")
    c = qt.add_parser("apply")
    c.add_argument("name")
    c.add_argument("-description", default="")
    c.add_argument("-cpu", type=int, default=None,
                   help="CPU MHz limit (omit for unlimited)")
    c.add_argument("-memory", type=int, default=None, dest="memory_mb",
                   help="memory MiB limit")
    c.add_argument("-devices", type=int, default=None,
                   help="accelerator device-count limit")
    c.add_argument("-allocs", type=int, default=None,
                   help="live allocation-count limit")
    c.set_defaults(fn="cmd_quota_apply")
    c = qt.add_parser("delete")
    c.add_argument("name")
    c.set_defaults(fn="cmd_quota_delete")
    c = qt.add_parser("usage")
    # dest kept distinct from the global -namespace flag: a subparser
    # positional default would clobber the already-parsed global value
    c.add_argument("usage_ns", nargs="?", default="",
                   metavar="namespace")
    c.set_defaults(fn="cmd_quota_usage")

    vol = sub.add_parser("volume",
                         help="CSI volume commands").add_subparsers(
        dest="sub", required=True)
    c = vol.add_parser("register")
    c.add_argument("file")
    c.add_argument("-namespace", default="default")
    c.set_defaults(fn="cmd_volume_register")
    c = vol.add_parser("status")
    c.add_argument("vol_id", nargs="?")
    c.add_argument("-namespace", default="default")
    c.set_defaults(fn="cmd_volume_status")
    c = vol.add_parser("deregister")
    c.add_argument("vol_id")
    c.add_argument("-namespace", default="default")
    c.add_argument("-force", action="store_true")
    c.set_defaults(fn="cmd_volume_deregister")

    plug = sub.add_parser("plugin",
                          help="CSI plugin commands").add_subparsers(
        dest="sub", required=True)
    c = plug.add_parser("status")
    c.add_argument("plugin_id", nargs="?")
    c.set_defaults(fn="cmd_plugin_status")

    v = sub.add_parser("version")
    v.set_defaults(fn="cmd_version")

    st = sub.add_parser("status", help="job status shorthand")
    st.add_argument("job_id", nargs="?")
    st.add_argument("-prefix", default="",
                    help="server-side prefix search across all contexts")
    st.set_defaults(fn="cmd_status")

    svc = sub.add_parser("service",
                         help="nomad-native service registry").add_subparsers(
        dest="sub", required=True)
    sv = svc.add_parser("list")
    sv.set_defaults(fn="cmd_service_list")
    sv = svc.add_parser("info")
    sv.add_argument("name")
    sv.set_defaults(fn="cmd_service_info")
    return p


def main(argv: Optional[List[str]] = None, out=sys.stdout) -> int:
    args = build_parser().parse_args(argv)
    consistency = ("stale" if getattr(args, "stale", False) else
                   "consistent" if getattr(args, "consistent", False)
                   else None)
    api = ApiClient(address=args.address, token=args.token,
                    namespace=args.namespace, consistency=consistency,
                    region=getattr(args, "region", "") or None)
    cli = Cli(api, out=out)
    try:
        return getattr(cli, args.fn)(args)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"Error connecting to {args.address}: {e}", file=sys.stderr)
        return 1
