"""ACL system (reference: acl/acl.go, acl/policy.go — policy parse +
capability checks; nomad/acl.go token resolution)."""
from nomad_tpu.acl.policy import (
    ACL,
    ACLPolicy,
    ACLToken,
    CAPABILITIES,
    parse_policy,
    required_capability,
)

__all__ = ["ACL", "ACLPolicy", "ACLToken", "CAPABILITIES",
           "parse_policy", "required_capability"]
