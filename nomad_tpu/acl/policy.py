"""ACL policies, tokens, and capability checks.

Reference: acl/policy.go (policy spec: namespace rules with capability
lists or short-form `policy = "read|write|deny"`; node/agent/operator/
quota coarse rules) and acl/acl.go (the compiled ACL object with
`AllowNamespaceOperation`).  Tokens: nomad/structs (ACLToken with
management|client types) resolved in nomad/acl.go `ResolveToken`.

Policies here are JSON or a minimal HCL subset, e.g.:

    namespace "default" { policy = "write" }
    namespace "ops"     { capabilities = ["submit-job", "read-job"] }
    node    { policy = "read" }
    agent   { policy = "write" }
    operator { policy = "read" }
"""
from __future__ import annotations

import re
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# namespace capabilities (acl/policy.go:17-44)
CAP_DENY = "deny"
CAP_LIST_JOBS = "list-jobs"
CAP_READ_JOB = "read-job"
CAP_SUBMIT_JOB = "submit-job"
CAP_DISPATCH_JOB = "dispatch-job"
CAP_READ_LOGS = "read-logs"
CAP_READ_FS = "read-fs"
CAP_ALLOC_EXEC = "alloc-exec"
CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
CAP_CSI_ACCESS = "csi-access"
CAP_CSI_WRITE_VOLUME = "csi-write-volume"
CAP_CSI_READ_VOLUME = "csi-read-volume"
CAP_CSI_LIST_VOLUME = "csi-list-volume"
CAP_CSI_MOUNT_VOLUME = "csi-mount-volume"
CAP_LIST_SCALING_POLICIES = "list-scaling-policies"
CAP_READ_SCALING_POLICY = "read-scaling-policy"
CAP_READ_JOB_SCALING = "read-job-scaling"
CAP_SCALE_JOB = "scale-job"

CAPABILITIES = [
    CAP_DENY, CAP_LIST_JOBS, CAP_READ_JOB, CAP_SUBMIT_JOB,
    CAP_DISPATCH_JOB, CAP_READ_LOGS, CAP_READ_FS, CAP_ALLOC_EXEC,
    CAP_ALLOC_LIFECYCLE, CAP_CSI_ACCESS, CAP_CSI_WRITE_VOLUME,
    CAP_CSI_READ_VOLUME, CAP_CSI_LIST_VOLUME, CAP_CSI_MOUNT_VOLUME,
    CAP_LIST_SCALING_POLICIES, CAP_READ_SCALING_POLICY,
    CAP_READ_JOB_SCALING, CAP_SCALE_JOB,
]

# expansion of short-form `policy = "..."` (acl/policy.go:118-158)
_POLICY_CAPS = {
    "read": [CAP_LIST_JOBS, CAP_READ_JOB, CAP_CSI_LIST_VOLUME,
             CAP_CSI_READ_VOLUME, CAP_READ_JOB_SCALING,
             CAP_LIST_SCALING_POLICIES, CAP_READ_SCALING_POLICY],
    "write": [CAP_LIST_JOBS, CAP_READ_JOB, CAP_SUBMIT_JOB,
              CAP_DISPATCH_JOB, CAP_READ_LOGS, CAP_READ_FS,
              CAP_ALLOC_EXEC, CAP_ALLOC_LIFECYCLE, CAP_CSI_WRITE_VOLUME,
              CAP_CSI_MOUNT_VOLUME, CAP_CSI_LIST_VOLUME,
              CAP_CSI_READ_VOLUME, CAP_READ_JOB_SCALING, CAP_SCALE_JOB,
              CAP_LIST_SCALING_POLICIES, CAP_READ_SCALING_POLICY],
    "scale": [CAP_READ_JOB_SCALING, CAP_SCALE_JOB],
    "deny": [CAP_DENY],
}


@dataclass
class NamespaceRule:
    name: str = "default"
    policy: str = ""
    capabilities: List[str] = field(default_factory=list)

    def expanded(self) -> List[str]:
        caps = list(self.capabilities)
        if self.policy:
            caps.extend(_POLICY_CAPS.get(self.policy, []))
        return caps


@dataclass
class ACLPolicy:
    name: str = ""
    description: str = ""
    rules: str = ""                     # source text
    namespaces: List[NamespaceRule] = field(default_factory=list)
    node: str = ""                      # "" | read | write | deny
    agent: str = ""
    operator: str = ""
    quota: str = ""
    plugin: str = ""


_BLOCK_RE = re.compile(
    r'(namespace|host_volume)\s+"([^"]*)"\s*\{([^}]*)\}'
    r'|(node|agent|operator|quota|plugin)\s*\{([^}]*)\}', re.S)
_ATTR_RE = re.compile(r'(\w+)\s*=\s*("([^"]*)"|\[([^\]]*)\])')


def parse_policy(name: str, rules: str, description: str = "") -> ACLPolicy:
    """Parse the HCL-subset policy language (acl/policy.go Parse)."""
    p = ACLPolicy(name=name, description=description, rules=rules)
    for m in _BLOCK_RE.finditer(rules):
        if m.group(1) == "namespace":
            body = m.group(3)
            rule = NamespaceRule(name=m.group(2))
            for am in _ATTR_RE.finditer(body):
                key = am.group(1)
                if key == "policy" and am.group(3) is not None:
                    rule.policy = am.group(3)
                elif key == "capabilities" and am.group(4) is not None:
                    rule.capabilities = re.findall(r'"([^"]*)"', am.group(4))
            p.namespaces.append(rule)
        elif m.group(4):
            block = m.group(4)
            pol = ""
            for am in _ATTR_RE.finditer(m.group(5)):
                if am.group(1) == "policy" and am.group(3) is not None:
                    pol = am.group(3)
            setattr(p, block, pol)
    if not p.namespaces and not any(
            getattr(p, b) for b in ("node", "agent", "operator")):
        raise ValueError(f"policy {name!r}: no rules parsed")
    return p


@dataclass
class ACLToken:
    accessor_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    secret_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    name: str = ""
    type: str = "client"                # "client" | "management"
    policies: List[str] = field(default_factory=list)
    global_: bool = False
    create_index: int = 0
    modify_index: int = 0


class ACL:
    """Compiled ACL: union of policies with deny-overrides + glob
    namespace matching (acl/acl.go NewACL / AllowNamespaceOperation)."""

    def __init__(self, management: bool = False,
                 policies: Optional[List[ACLPolicy]] = None):
        self.management = management
        self._ns: Dict[str, set] = {}
        self._coarse: Dict[str, str] = {}
        for pol in policies or []:
            for rule in pol.namespaces:
                caps = self._ns.setdefault(rule.name, set())
                expanded = rule.expanded()
                if CAP_DENY in expanded:
                    caps.clear()
                    caps.add(CAP_DENY)
                elif CAP_DENY not in caps:
                    caps.update(expanded)
            for block in ("node", "agent", "operator", "quota", "plugin"):
                val = getattr(pol, block)
                if not val:
                    continue
                prev = self._coarse.get(block)
                if val == "deny" or prev == "deny":
                    self._coarse[block] = "deny"
                elif prev == "write" or val == "write":
                    self._coarse[block] = "write"
                else:
                    self._coarse[block] = val

    def _ns_caps(self, namespace: str) -> set:
        if namespace in self._ns:
            return self._ns[namespace]
        # glob match, longest-prefix wins (acl.go findClosestMatchingGlob)
        best, best_len = set(), -1
        for pat, caps in self._ns.items():
            if "*" in pat:
                regex = "^" + re.escape(pat).replace(r"\*", ".*") + "$"
                if re.match(regex, namespace) and len(pat) > best_len:
                    best, best_len = caps, len(pat)
        return best

    def allows(self, namespace: Optional[str], capability: str) -> bool:
        if self.management:
            return True
        if capability.startswith(("node:", "agent:", "operator:",
                                  "quota:", "plugin:")):
            block, _, level = capability.partition(":")
            have = self._coarse.get(block, "")
            if have == "deny":
                return False
            if level == "read":
                return have in ("read", "write")
            return have == "write"
        caps = self._ns_caps(namespace or "default")
        if CAP_DENY in caps:
            return False
        return capability in caps


# management singleton (acl/acl.go ManagementACL)
ACL_MANAGEMENT = ACL(management=True)


def required_capability(parts: List[str], method: str,
                        namespace: str = "default") \
        -> Tuple[Optional[str], Optional[str]]:
    """Map an HTTP route to the capability it needs (the per-endpoint
    checks in nomad/*_endpoint.go).  Returns (capability, namespace);
    (None, None) means anonymous-allowed (status endpoints)."""
    write = method in ("PUT", "POST", "DELETE")
    head = parts[0] if parts else ""
    ns = namespace or "default"
    if head in ("status", "metrics"):
        return (None, None)
    if head == "agent":
        # /v1/agent/health stays unauthenticated (reference agent
        # health checks); the rest enforce the agent coarse rule
        if parts[1:2] == ["health"]:
            return (None, None)
        return (f"agent:{'write' if write else 'read'}", None)
    if head in ("jobs", "job"):
        if write:
            cap = CAP_SUBMIT_JOB
            if len(parts) > 2 and parts[2] == "dispatch":
                cap = CAP_DISPATCH_JOB
            return (cap, ns)
        return (CAP_LIST_JOBS if head == "jobs" else CAP_READ_JOB, ns)
    if head in ("allocations", "allocation"):
        return ((CAP_ALLOC_LIFECYCLE if write else CAP_READ_JOB), ns)
    if head == "client":
        # /v1/client/fs/* (fs_endpoint.go): logs need read-logs, the
        # rest of the filesystem needs read-fs; the handler re-checks
        # against the alloc's own namespace
        if parts[1:2] == ["fs"]:
            cap = CAP_READ_LOGS if parts[2:3] == ["logs"] else CAP_READ_FS
            return (cap, ns)
        return (f"node:{'write' if write else 'read'}", None)
    if head in ("evaluations", "evaluation", "deployments", "deployment"):
        return ((CAP_SUBMIT_JOB if write else CAP_READ_JOB), ns)
    if head in ("nodes", "node"):
        return (f"node:{'write' if write else 'read'}", None)
    if head == "operator":
        return (f"operator:{'write' if write else 'read'}", None)
    if head == "acl":
        # bootstrap is anonymous by design; a token may always read
        # itself; everything else is management-only
        if parts[1:2] == ["bootstrap"]:
            return (None, None)
        if parts[1:3] == ["token", "self"] and not write:
            return (None, None)
        return ("acl:management", None)
    if head in ("volumes", "volume"):
        if write:
            return (CAP_CSI_WRITE_VOLUME, ns)
        return ((CAP_CSI_LIST_VOLUME if head == "volumes"
                 else CAP_CSI_READ_VOLUME), ns)
    if head in ("plugins", "plugin"):
        return (f"plugin:{'write' if write else 'read'}", None)
    if head in ("namespaces", "namespace"):
        return (f"operator:{'write' if write else 'read'}", None)
    if head in ("quotas", "quota"):
        return (f"quota:{'write' if write else 'read'}", None)
    if head == "search":
        return (CAP_LIST_JOBS, ns)
    if head == "event":
        return (CAP_READ_JOB, ns)
    if head in ("services", "service"):
        # nomad-native service registry (reference nsd endpoints use
        # read-job / submit-job in the service's namespace)
        return ((CAP_SUBMIT_JOB if write else CAP_READ_JOB), ns)
    if head == "scaling":
        return (CAP_LIST_JOBS, ns)
    if head == "regions":
        return (None, None)
    return (f"operator:{'write' if write else 'read'}", None)
