"""In-process multi-server cluster (reference: nomad.TestServer booting
full Servers with in-memory Raft + loopback Serf, nomad/testing.go:41-47,
used by leader_test.go / plan_apply_test.go).

Boots N Servers over one InMemTransport; Raft elects a leader which
establishes the leader-only subsystems (broker, workers, plan applier,
watchers).  Supports stopping members and network partitions for failover
tests.
"""
from __future__ import annotations

import copy
import time
from typing import List, Optional

from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.raft import InMemTransport, RaftConfig


class Cluster:
    def __init__(self, n: int = 3, config: Optional[ServerConfig] = None,
                 raft_config: Optional[RaftConfig] = None,
                 data_dir: Optional[str] = None):
        self.transport = InMemTransport()
        self._names = [f"server-{i}" for i in range(n)]
        self._config = config
        self._data_dir = data_dir
        # timeouts tolerate multi-hundred-ms GIL pauses (jit compiles in
        # neighboring tests share the process) without leader flapping
        self.raft_config = raft_config or RaftConfig(
            heartbeat_interval=0.05, election_timeout=0.3)
        self.servers: List[Server] = [self._make_server(nm)
                                      for nm in self._names]

    def _make_server(self, name: str) -> Server:
        cfg = self._config or ServerConfig(num_schedulers=2)
        if self._data_dir is not None:
            cfg = copy.copy(cfg)
            cfg.data_dir = self._data_dir
        return Server(cfg, name=name, peers=self._names,
                      raft_transport=self.transport,
                      raft_config=self.raft_config)

    def start(self) -> None:
        for s in self.servers:
            s.start()

    def stop(self) -> None:
        for s in self.servers:
            s.stop()

    def leader(self, timeout: float = 5.0) -> Server:
        """Wait for a single established leader."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [s for s in self.servers
                       if s.raft is not None and s.raft.is_leader
                       and s._established]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.01)
        raise TimeoutError("no leader elected")

    def followers(self) -> List[Server]:
        lead = self.leader()
        return [s for s in self.servers if s is not lead]

    def kill(self, server: Server) -> None:
        """Hard-stop a member (network drop + component shutdown)."""
        self.transport.set_down(server.name)
        server.stop()

    def hard_kill(self, server: Server) -> None:
        """Power-loss kill: the network drops and the server's WAL loses
        everything past its last fsync (Server.crash) — nothing is
        flushed or closed cleanly.  restart() brings the member back from
        its data_dir."""
        self.transport.set_down(server.name)
        server.crash()

    def restart(self, server: Server) -> Server:
        """Boot a fresh Server over the killed member's name + data_dir
        (the crashed process restarting on the same host).  Requires the
        cluster to have been built with a data_dir; returns the
        replacement, which also takes the old member's slot in
        `self.servers`."""
        if self._data_dir is None:
            raise RuntimeError("restart() needs a data_dir-backed cluster")
        replacement = self._make_server(server.name)
        self.servers[self.servers.index(server)] = replacement
        self.transport.set_down(server.name, down=False)
        replacement.start()
        return replacement

    def isolate(self, server: Server) -> None:
        """Cut a live member off the network (it keeps running — the
        asymmetric failure that forces a leader step-down, unlike kill)."""
        self.transport.set_down(server.name)

    def heal(self, server: Server) -> None:
        """Reconnect a member isolated with isolate()."""
        self.transport.set_down(server.name, down=False)

    def wait_replication(self, index: int, timeout: float = 5.0) -> bool:
        """Wait until every live member's store reaches `index`."""
        deadline = time.monotonic() + timeout
        live = [s for s in self.servers if not s._stop.is_set()]
        while time.monotonic() < deadline:
            if all(s.store.latest_index >= index for s in live):
                return True
            time.sleep(0.01)
        return False
