"""In-process multi-server cluster (reference: nomad.TestServer booting
full Servers with in-memory Raft + loopback Serf, nomad/testing.go:41-47,
used by leader_test.go / plan_apply_test.go).

Boots N Servers over one InMemTransport; Raft elects a leader which
establishes the leader-only subsystems (broker, workers, plan applier,
watchers).  Supports stopping members and network partitions for failover
tests.
"""
from __future__ import annotations

import copy
import time
from typing import List, Optional

from nomad_tpu.core.server import Server, ServerConfig
from nomad_tpu.raft import (ConfigurationInFlightError, InMemTransport,
                            NotLeaderError, RaftConfig)


class Cluster:
    def __init__(self, n: int = 3, config: Optional[ServerConfig] = None,
                 raft_config: Optional[RaftConfig] = None,
                 data_dir: Optional[str] = None,
                 transport=None, name_prefix: str = "server",
                 region: Optional[str] = None, wan: bool = False):
        # a FederatedCluster shares ONE transport across its regional
        # clusters (name_prefix keeps the raft spines disjoint); a
        # standalone cluster owns its own
        self.transport = transport if transport is not None else InMemTransport()
        self._prefix = name_prefix
        self._region = region
        self._wan = wan
        self._names = [f"{name_prefix}-{i}" for i in range(n)]
        self._next_id = n
        self._config = config
        self._data_dir = data_dir
        # timeouts tolerate multi-hundred-ms GIL pauses (jit compiles in
        # neighboring tests share the process) without leader flapping
        self.raft_config = raft_config or RaftConfig(
            heartbeat_interval=0.05, election_timeout=0.3)
        self.servers: List[Server] = [self._make_server(nm)
                                      for nm in self._names]

    def _make_server(self, name: str, join: bool = False) -> Server:
        cfg = self._config or ServerConfig(num_schedulers=2)
        if self._data_dir is not None or self._region is not None:
            cfg = copy.copy(cfg)
            if self._data_dir is not None:
                cfg.data_dir = self._data_dir
            if self._region is not None:
                cfg.region = self._region
        wan_pool = None
        if self._wan:
            from nomad_tpu.federation import WanPool
            wan_pool = WanPool(self.transport, name, addr=(name, 0),
                               region=cfg.region)
        return Server(cfg, name=name,
                      peers=[name] if join else self._names,
                      raft_transport=self.transport,
                      raft_config=self.raft_config,
                      raft_join=join,
                      wan_pool=wan_pool)

    def start(self) -> None:
        for s in self.servers:
            s.start()

    def stop(self) -> None:
        for s in self.servers:
            s.stop()

    def leader(self, timeout: float = 5.0) -> Server:
        """Wait for a single established leader."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [s for s in self.servers
                       if s.raft is not None and s.raft.is_leader
                       and s._established]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.01)
        raise TimeoutError("no leader elected")

    def followers(self) -> List[Server]:
        lead = self.leader()
        return [s for s in self.servers if s is not lead]

    def kill(self, server: Server) -> None:
        """Hard-stop a member (network drop + component shutdown)."""
        self.transport.set_down(server.name)
        server.stop()

    def hard_kill(self, server: Server) -> None:
        """Power-loss kill: the network drops and the server's WAL loses
        everything past its last fsync (Server.crash) — nothing is
        flushed or closed cleanly.  restart() brings the member back from
        its data_dir."""
        self.transport.set_down(server.name)
        server.crash()

    def restart(self, server: Server) -> Server:
        """Boot a fresh Server over the killed member's name + data_dir
        (the crashed process restarting on the same host).  Requires the
        cluster to have been built with a data_dir; returns the
        replacement, which also takes the old member's slot in
        `self.servers`."""
        if self._data_dir is None:
            raise RuntimeError("restart() needs a data_dir-backed cluster")
        replacement = self._make_server(server.name)
        self.servers[self.servers.index(server)] = replacement
        self.transport.set_down(server.name, down=False)
        replacement.start()
        self._refresh_address_book(replacement)
        return replacement

    def _refresh_address_book(self, server: Server) -> None:
        """A revived server may come back on a NEW port (TcpTransport):
        re-advertise its rpc/gossip addresses so peers don't keep dialing
        the dead one.  InMemTransport routes by name, so this is a no-op
        there."""
        add_peer = getattr(self.transport, "add_peer", None)
        if add_peer is None:
            return
        mem = server.membership
        if mem is None:
            return
        with mem._lock:
            me = mem.members.get(server.name)
        if me is not None:
            add_peer(server.name, me.addr)
            add_peer(f"rpc:{server.name}", me.addr)
            add_peer(f"gossip:{server.name}", me.addr)
            add_peer(f"wan:{server.name}", me.addr)

    # -------------------------------------------------- elastic membership

    def _on_leader_retry(self, fn, timeout: float = 10.0):
        """Run a leader-side membership operation against whichever server
        currently leads, retrying through leadership churn and the
        one-change-in-flight window."""
        deadline = time.monotonic() + timeout
        last_exc: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                lead = self.leader(timeout=max(
                    0.1, deadline - time.monotonic()))
                return fn(lead)
            except (NotLeaderError, ConfigurationInFlightError,
                    TimeoutError) as exc:
                last_exc = exc
                time.sleep(0.02)
        raise TimeoutError(
            f"membership operation did not complete: {last_exc}")

    def add_server(self, name: Optional[str] = None,
                   timeout: float = 10.0) -> Server:
        """Join a BLANK server to the running cluster: boot it with an
        empty configuration (join mode — it never campaigns), then ask
        the leader to add it as a non-voter.  It catches up via
        replication/InstallSnapshot and autopilot promotes it to voter
        once it stabilizes."""
        if name is None:
            name = f"{self._prefix}-{self._next_id}"
            self._next_id += 1
        joiner = self._make_server(name, join=True)
        self._names.append(name)
        self.servers.append(joiner)
        joiner.start()
        self._on_leader_retry(
            lambda lead: lead.raft.add_server(name, timeout=5.0),
            timeout=timeout)
        return joiner

    def remove_server(self, server: Server, timeout: float = 10.0) -> None:
        """Demote + drop a member from the raft configuration (it may
        already be dead); does not stop the process."""
        self._on_leader_retry(
            lambda lead: lead.raft.remove_server(server.name, timeout=5.0),
            timeout=timeout)

    def replace_server(self, server: Server,
                       timeout: float = 15.0) -> Server:
        """Permanently destroy a member (power loss, disk gone) and join a
        blank replacement under a NEW name — the production server-loss
        drill.  Returns the replacement once it is a voter."""
        deadline = time.monotonic() + timeout
        if not server._stop.is_set():
            self.hard_kill(server)
        self.servers.remove(server)
        self._names.remove(server.name)
        self._on_leader_retry(
            lambda lead: lead.raft.remove_server(server.name, timeout=5.0),
            timeout=max(0.5, deadline - time.monotonic()))
        replacement = self.add_server(
            timeout=max(0.5, deadline - time.monotonic()))
        self.wait_voter(replacement.name,
                        timeout=max(0.5, deadline - time.monotonic()))
        return replacement

    def wait_voter(self, name: str, timeout: float = 10.0) -> None:
        """Block until autopilot has promoted `name` to voter."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                lead = self.leader(timeout=max(
                    0.1, deadline - time.monotonic()))
            except TimeoutError:
                continue
            if name in lead.raft.configuration()["voters"]:
                return
            time.sleep(0.02)
        raise TimeoutError(f"{name} was not promoted to voter")

    def isolate(self, server: Server) -> None:
        """Cut a live member off the network (it keeps running — the
        asymmetric failure that forces a leader step-down, unlike kill)."""
        self.transport.set_down(server.name)

    def heal(self, server: Server) -> None:
        """Reconnect a member isolated with isolate()."""
        self.transport.set_down(server.name, down=False)

    def wait_replication(self, index: int, timeout: float = 5.0) -> bool:
        """Wait until every live member's store reaches `index`."""
        deadline = time.monotonic() + timeout
        live = [s for s in self.servers if not s._stop.is_set()]
        while time.monotonic() < deadline:
            if all(s.store.latest_index >= index for s in live):
                return True
            time.sleep(0.01)
        return False


class FederatedCluster:
    """N regional Clusters over ONE shared InMemTransport, WAN-joined
    (reference: nomad's multi-region test topology — each region runs
    its own raft spine, every *server* joins the shared WAN serf pool,
    nomad/serf.go).  Region `regions[0]` seeds the WAN gossip."""

    def __init__(self, regions=("global", "west"), n: int = 3,
                 config: Optional[ServerConfig] = None,
                 raft_config: Optional[RaftConfig] = None,
                 data_dir: Optional[str] = None):
        import os
        self.transport = InMemTransport()
        self.regions = list(regions)
        self.clusters = {}
        for r in self.regions:
            self.clusters[r] = Cluster(
                n=n, config=config, raft_config=raft_config,
                data_dir=(os.path.join(data_dir, r) if data_dir else None),
                transport=self.transport, name_prefix=f"{r}-server",
                region=r, wan=True)

    @property
    def servers(self) -> List[Server]:
        return [s for c in self.clusters.values() for s in c.servers]

    def start(self) -> None:
        for c in self.clusters.values():
            c.start()
        # WAN join: everyone seeds off the first region's first server
        seed = self.clusters[self.regions[0]].servers[0].name
        for s in self.servers:
            if s.name != seed and s.wan_pool is not None:
                s.wan_pool.join([(seed, (seed, 0))])

    def stop(self) -> None:
        for c in self.clusters.values():
            c.stop()

    def leader(self, region: Optional[str] = None,
               timeout: float = 5.0) -> Server:
        return self.clusters[region or self.regions[0]].leader(timeout)

    def wait_federated(self, timeout: float = 10.0) -> None:
        """Block until every server's WAN view covers all regions."""
        want = sorted(self.regions)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(s.wan_pool is not None and s.wan_pool.regions() == want
                   for s in self.servers):
                return
            time.sleep(0.02)
        raise TimeoutError("WAN pool did not converge on all regions")

    # ---- churn delegation: the matrix ChurnDriver drives a federated
    # cell with the same surface as a single Cluster; each op lands on
    # the regional cluster that owns the victim

    def _owner(self, server: Server) -> Cluster:
        for c in self.clusters.values():
            if server in c.servers:
                return c
        raise ValueError(f"{server.name} is not a member of any region")

    def kill(self, server: Server) -> None:
        self._owner(server).kill(server)

    def hard_kill(self, server: Server) -> None:
        self._owner(server).hard_kill(server)

    def restart(self, server: Server) -> Server:
        owner = self._owner(server)
        replacement = owner.restart(server)
        # a crashed server's WAN pool died without a goodbye and the
        # replacement boots with an empty WAN table: re-seed it off any
        # live peer so it rejoins the federation (its bumped-by-
        # refutation incarnation outranks the stale SUSPECT entries)
        if replacement.wan_pool is not None:
            seeds = [(s.name, (s.name, 0)) for s in self.servers
                     if s is not replacement and not s._stop.is_set()]
            if seeds:
                replacement.wan_pool.join(seeds[:1])
        return replacement

    def isolate(self, server: Server) -> None:
        self.transport.set_down(server.name)

    def heal(self, server: Server) -> None:
        self.transport.set_down(server.name, down=False)

    def wait_replication(self, index: int, timeout: float = 5.0) -> bool:
        return all(c.wait_replication(index, timeout)
                   for c in self.clusters.values())

    def partition_region(self, region: str, cut: bool = True) -> None:
        """Sever (or heal) every cross-region link touching `region` —
        the WAN cable cut.  Intra-region traffic keeps flowing, so the
        dark region keeps its own leader and serves local reads."""
        inside = [s.name for s in self.clusters[region].servers]
        for rc, c in self.clusters.items():
            if rc == region:
                continue
            for a in inside:
                for b in (s.name for s in c.servers):
                    self.transport.partition(a, b, cut=cut)

    def heal_region(self, region: str) -> None:
        self.partition_region(region, cut=False)
