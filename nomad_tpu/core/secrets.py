"""Vault-shaped secrets provider (reference: nomad/vault.go — the
server-side vaultClient that derives per-task tokens with TTL + renewal;
client/allocrunner/taskrunner/vault_hook.go — the client hook writing
the token into the task's secrets dir and renewing it; and
taskrunner/template/template.go — templates that render secrets and
re-render when they change).

No external Vault exists in this environment, so the provider embeds a
versioned KV store and a token-lease engine in the server process.  The
shape the rest of the system sees is the reference's: tasks declare a
`vault { policies = [...] }` stanza, the client derives a renewable
token scoped to those policies, the token lands in secrets/vault_token,
and templates read secrets through the token — never through ambient
server state.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nomad_tpu.utils import generate_uuid


class SecretsError(Exception):
    pass


@dataclass
class _Lease:
    token: str
    alloc_id: str
    task: str
    policies: List[str]
    ttl_s: float
    expires: float
    revoked: bool = False
    renewals: int = 0


@dataclass
class _Entry:
    data: Dict[str, str] = field(default_factory=dict)
    version: int = 1


class SecretsProvider:
    """Embedded KV + token leases.  Policies are path prefixes: a token
    carrying policy "db" may read secret paths "db" and "db/...", the
    reference's policy->path mapping reduced to its prefix core."""

    def __init__(self, default_ttl_s: float = 3600.0):
        self.default_ttl_s = default_ttl_s
        self._kv: Dict[str, _Entry] = {}
        self._leases: Dict[str, _Lease] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------------- kv

    def put(self, path: str, data: Dict[str, str]) -> int:
        """Write a secret; bumps the version (templates watch it)."""
        if not path:
            raise SecretsError("empty secret path")
        with self._lock:
            e = self._kv.get(path)
            if e is None:
                self._kv[path] = _Entry(dict(data))
                return 1
            e.data = dict(data)
            e.version += 1
            return e.version

    def delete(self, path: str) -> None:
        with self._lock:
            self._kv.pop(path, None)

    # ------------------------------------------------------------- tokens

    def derive_token(self, alloc_id: str, task: str,
                     policies: List[str],
                     ttl_s: Optional[float] = None) -> dict:
        """Per-task token derivation (vault.go CreateToken): renewable,
        scoped to the task's vault policies."""
        ttl = float(ttl_s or self.default_ttl_s)
        lease = _Lease(token=generate_uuid(), alloc_id=alloc_id,
                       task=task, policies=list(policies),
                       ttl_s=ttl, expires=time.time() + ttl)
        with self._lock:
            if len(self._leases) > 4096:
                self._prune_locked()
            self._leases[lease.token] = lease
        return {"token": lease.token, "ttl_s": ttl,
                "policies": lease.policies}

    def _prune_locked(self) -> None:
        """Drop revoked/expired leases (amortized; the reference's
        revocation daemon, vault.go revokeDaemon)."""
        now = time.time()
        dead = [t for t, l in self._leases.items()
                if l.revoked or l.expires < now]
        for t in dead:
            del self._leases[t]

    def renew(self, token: str) -> dict:
        """Extend the lease (vault.go RenewToken); expired/revoked
        tokens fail and the client's change_mode kicks in."""
        now = time.time()
        with self._lock:
            lease = self._leases.get(token)
            if lease is None or lease.revoked or lease.expires < now:
                raise SecretsError("token expired or revoked")
            lease.expires = now + lease.ttl_s
            lease.renewals += 1
            return {"ttl_s": lease.ttl_s, "renewals": lease.renewals}

    def revoke_for_alloc(self, alloc_id: str) -> int:
        """Revoke every lease of a terminal alloc (vault.go
        RevokeTokens on alloc GC/stop)."""
        with self._lock:
            dead = [t for t, l in self._leases.items()
                    if l.alloc_id == alloc_id]
            for t in dead:
                del self._leases[t]
        return len(dead)

    def _check(self, token: str, path: str) -> _Lease:
        now = time.time()
        lease = self._leases.get(token)
        if lease is None or lease.revoked or lease.expires < now:
            raise SecretsError("token expired or revoked")
        for pol in lease.policies:
            if path == pol or path.startswith(pol + "/"):
                return lease
        raise SecretsError(f"token policies {lease.policies} do not "
                           f"cover path {path!r}")

    # --------------------------------------------------------------- read

    def read(self, path: str, token: str) -> Tuple[Dict[str, str], int]:
        """Token-gated read -> (data, version)."""
        with self._lock:
            self._check(token, path)
            e = self._kv.get(path)
            if e is None:
                raise SecretsError(f"no secret at {path!r}")
            return dict(e.data), e.version

    def version(self, path: str, token: str) -> int:
        """Cheap change-watch primitive for template re-rendering."""
        with self._lock:
            self._check(token, path)
            e = self._kv.get(path)
            return e.version if e is not None else 0
