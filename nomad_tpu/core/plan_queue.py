"""Leader-side plan queue (reference: nomad/plan_queue.go).

Workers submit plans; the single plan-apply loop pops them in priority
order.  Each pending plan carries a future the submitting worker blocks on.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

from nomad_tpu import deadline, tracing
from nomad_tpu.structs.plan import Plan


class LeadershipLostError(Exception):
    """Raised to plan submitters when the plan queue is torn down by a
    leadership transition (reference: plan submission RPCs erroring when
    the leader's planQueue is disabled, plan_queue.go SetEnabled)."""


class PendingPlan:
    # trace: (ctx, enqueue_ts) for a sampled submission, else None —
    # the applier stitches queue-wait/evaluate/raft spans from it
    #
    # `evaluated` resolves with the PlanResult as soon as the applier has
    # validated the plan and registered its overlay — before the raft
    # append + fsync lands.  A pipelined worker continues scheduling off
    # this future while `future` (the durable commit) is still in
    # flight; if the commit later fails, `future` carries the error and
    # the worker discards the speculative continuation.
    # deadline: the submitter's absolute monotonic deadline (or None),
    # stamped at enqueue — the applier refuses an already-expired plan
    # BEFORE paying the raft append + fsync for it
    __slots__ = ("plan", "future", "evaluated", "trace", "deadline")

    def __init__(self, plan: Plan):
        self.plan = plan
        self.future: Future = Future()
        self.evaluated: Future = Future()
        self.trace = None
        self.deadline = deadline.current()
        if tracing.active is not None:
            ctx = tracing.current()
            if ctx is not None:
                self.trace = (ctx, time.time())


class PlanQueue:
    def __init__(self):
        self._lock = threading.Condition()
        self.enabled = False
        self._heap: List[Tuple[int, int, PendingPlan]] = []
        self._counter = itertools.count()
        self.stats = {"depth": 0}

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self.enabled = enabled
            if not enabled:
                for _, _, p in self._heap:
                    err = LeadershipLostError("plan queue disabled")
                    p.future.set_exception(err)
                    p.evaluated.set_exception(err)
                self._heap = []
            self._lock.notify_all()

    def enqueue(self, plan: Plan) -> PendingPlan:
        with self._lock:
            if not self.enabled:
                raise LeadershipLostError("plan queue is disabled")
            pending = PendingPlan(plan)
            heapq.heappush(self._heap, (-plan.priority, next(self._counter), pending))
            self.stats["depth"] = len(self._heap)
            self._lock.notify_all()
            return pending

    def dequeue(self, timeout: Optional[float] = None) -> Optional[PendingPlan]:
        with self._lock:
            if not self._lock.wait_for(lambda: self._heap or not self.enabled,
                                       timeout=timeout):
                return None
            if not self._heap:
                return None
            _, _, pending = heapq.heappop(self._heap)
            self.stats["depth"] = len(self._heap)
            return pending

    def dequeue_batch(self, max_n: int,
                      timeout: Optional[float] = None
                      ) -> List[PendingPlan]:
        """One blocking wait, then drain up to max_n queued plans in
        priority order.  The applier coalesces adjacent plans from a
        wide worker pool into one commit instead of one store/raft
        round trip per plan."""
        with self._lock:
            if not self._lock.wait_for(
                    lambda: self._heap or not self.enabled,
                    timeout=timeout):
                return []
            out: List[PendingPlan] = []
            while self._heap and len(out) < max_n:
                _, _, pending = heapq.heappop(self._heap)
                out.append(pending)
            self.stats["depth"] = len(self._heap)
            return out

    def depth(self) -> int:
        with self._lock:
            return len(self._heap)
