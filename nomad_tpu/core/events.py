"""Event broker (reference: nomad/stream/event_broker.go:30 — at-most-once
pub/sub of state-change events with per-topic filtering over a bounded ring
buffer; surfaced at /v1/event/stream as NDJSON).
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Tuple


class Event:
    __slots__ = ("topic", "type", "key", "namespace", "index", "payload", "time")

    def __init__(self, topic: str, type_: str, key: str, namespace: str,
                 index: int, payload):
        self.topic = topic
        self.type = type_
        self.key = key
        self.namespace = namespace
        self.index = index
        self.payload = payload
        self.time = _time.time()

    def to_dict(self) -> dict:
        return {"Topic": self.topic, "Type": self.type, "Key": self.key,
                "Namespace": self.namespace, "Index": self.index,
                "Payload": self.payload}


class Subscription:
    def __init__(self, broker: "EventBroker",
                 topics: Dict[str, List[str]], from_index: int = 0):
        # NOTE: constructed by EventBroker.subscribe while holding
        # broker._lock, so replay + registration are atomic w.r.t. publish
        self.broker = broker
        self.topics = topics      # topic -> keys ("*" wildcard)
        self.cv = threading.Condition()
        self.queue: deque = deque()
        self.closed = False
        for ev in broker._buffer:
            if ev.index > from_index and self.matches(ev):
                self.queue.append(ev)

    def matches(self, ev: Event) -> bool:
        for topic, keys in self.topics.items():
            if topic not in ("*", ev.topic):
                continue
            if "*" in keys or ev.key in keys or not keys:
                return True
        return False

    def deliver(self, ev: Event) -> None:
        with self.cv:
            if not self.closed:
                self.queue.append(ev)
                self.cv.notify_all()

    def next(self, timeout: float = 1.0) -> Optional[Event]:
        with self.cv:
            if not self.queue:
                self.cv.wait(timeout)
            return self.queue.popleft() if self.queue else None

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()
        self.broker.unsubscribe(self)


class EventBroker:
    """Bounded ring buffer + fan-out to subscriptions."""

    def __init__(self, buffer_size: int = 100):
        self._lock = threading.Lock()
        self._buffer: deque = deque(maxlen=buffer_size)
        self._subs: List[Subscription] = []

    def publish(self, events: List[Event]) -> None:
        with self._lock:
            subs = list(self._subs)
            for ev in events:
                self._buffer.append(ev)
        for sub in subs:
            for ev in events:
                if sub.matches(ev):
                    sub.deliver(ev)

    def subscribe(self, topics: Dict[str, List[str]],
                  from_index: int = 0) -> Subscription:
        with self._lock:
            sub = Subscription(self, topics, from_index)
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    # ------------------------------------------------------- state bridge

    def watch_state(self, table: str, obj) -> None:
        """StateStore watcher: convert writes to stream events (reference:
        state store event publishing into the broker)."""
        topic_map = {
            "nodes": ("Node", lambda o: (o.id, "")),
            "jobs": ("Job", lambda o: (o.id, o.namespace)),
            "jobs_deregistered": ("Job", lambda o: (o.id, o.namespace)),
            "evals": ("Evaluation", lambda o: (o.id, o.namespace)),
            "allocs": ("Allocation", lambda o: (o.id, o.namespace)),
            "deployments": ("Deployment", lambda o: (o.id, o.namespace)),
        }
        entry = topic_map.get(table)
        if entry is None:
            return
        topic, keyfn = entry
        key, ns = keyfn(obj)
        type_ = {"jobs": "JobRegistered",
                 "jobs_deregistered": "JobDeregistered",
                 "nodes": "NodeRegistration",
                 "evals": "EvaluationUpdated",
                 "allocs": "AllocationUpdated",
                 "deployments": "DeploymentStatusUpdate"}[table]
        self.publish([Event(topic, type_, key, ns,
                            getattr(obj, "modify_index", 0), obj)])
