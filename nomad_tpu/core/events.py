"""Event broker (reference: nomad/stream/event_broker.go:30 — pub/sub of
state-change events with per-topic filtering over a bounded ring buffer;
surfaced at /v1/event/stream as NDJSON).

Backpressure model (reference stream/subscription.go): every subscriber
queue is bounded.  A consumer that stops draining hits the high-water
mark, its backlog is evicted in one shot, and the subscription falls
back to *catch-up mode*: the consumer re-reads the retained ring from
the last sequence number it actually consumed, then flips back to live
delivery once the ring is drained.  Events that age out of the ring
before a laggard catches up are permanently lost to it (at-most-once),
but broker memory stays bounded no matter how slow any consumer is.

Sequence numbers are broker-assigned and strictly monotonic per broker —
raft indexes cannot play this role because one plan apply emits many
events at a single index.  Dedup between live delivery and ring replay
keys on seq.

Knobs: ``NOMAD_TPU_SUB_QUEUE`` (per-subscriber queue bound, default
1024), ``NOMAD_TPU_EVENT_BUFFER`` (retained ring size, default 256).
"""
from __future__ import annotations

import os
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional

from nomad_tpu import knobs
from nomad_tpu.analysis import race
from nomad_tpu.telemetry import global_metrics


def _default_sub_queue() -> int:
    return max(2, knobs.get_int("NOMAD_TPU_SUB_QUEUE"))


def _default_buffer() -> int:
    return max(8, knobs.get_int("NOMAD_TPU_EVENT_BUFFER"))


class Event:
    __slots__ = ("topic", "type", "key", "namespace", "index", "payload",
                 "time", "seq")

    def __init__(self, topic: str, type_: str, key: str, namespace: str,
                 index: int, payload, seq: int = 0):
        self.topic = topic
        self.type = type_
        self.key = key
        self.namespace = namespace
        self.index = index
        self.payload = payload
        self.time = _time.time()
        self.seq = seq          # broker-assigned at publish; 0 = unpublished

    def to_dict(self) -> dict:
        return {"Topic": self.topic, "Type": self.type, "Key": self.key,
                "Namespace": self.namespace, "Index": self.index,
                "Payload": self.payload}


class Subscription:
    # queue + drop accounting are touched from the publisher, the
    # consumer, and the broker's catch-up replay — all under `cv`
    _RACE_TRACED = {"queue": "cv", "dropped": "cv"}

    def __init__(self, broker: "EventBroker",
                 topics: Dict[str, List[str]], from_index: int = 0,
                 max_queue: Optional[int] = None):
        # NOTE: constructed by EventBroker.subscribe while holding
        # broker._lock, so replay + registration are atomic w.r.t. publish
        self.broker = broker
        self.topics = topics      # topic -> keys ("*" wildcard)
        self.from_index = from_index
        self.cv = threading.Condition()
        self.queue: deque = deque()
        self.max_queue = max_queue if max_queue else _default_sub_queue()
        self.closed = False
        # last_seq: last seq actually handed to the consumer.  _seen_seq:
        # highest seq queued-or-consumed in live mode (dedup vs replay);
        # reset to last_seq on eviction since the backlog was discarded.
        self.last_seq = 0
        self._seen_seq = 0
        self.delivered = 0
        self.dropped = 0          # evicted from the queue at the HWM
        self.evictions = 0        # HWM trips
        self.catching_up = False
        for ev in broker._buffer:
            if ev.index > from_index and self.matches(ev):
                if len(self.queue) >= self.max_queue:
                    # huge ring + small queue: start life in catch-up
                    self.catching_up = True
                    break
                self.queue.append(ev)
                self._seen_seq = ev.seq

    def matches(self, ev: Event) -> bool:
        for topic, keys in self.topics.items():
            if topic not in ("*", ev.topic):
                continue
            if "*" in keys or ev.key in keys or not keys:
                return True
        return False

    def deliver(self, ev: Event) -> None:
        with self.cv:
            if self.closed:
                return
            if self.catching_up:
                # the ring replay in next() covers this event; queueing it
                # here too would duplicate or reorder
                self.cv.notify_all()
                return
            if ev.seq <= self._seen_seq:
                return            # already seen via ring replay
            race.write("Subscription.queue", self)
            if len(self.queue) >= self.max_queue:
                # high-water mark: evict the whole backlog and fall back
                # to catch-up-from-ring — a stalled consumer costs
                # bounded memory, never an unbounded deque
                race.write("Subscription.dropped", self)
                self.dropped += len(self.queue)
                self.evictions += 1
                global_metrics.incr("stream.dropped", len(self.queue))
                global_metrics.incr("stream.evictions")
                self.queue.clear()
                self.catching_up = True
                self._seen_seq = self.last_seq
                self.cv.notify_all()
                return
            self.queue.append(ev)
            self._seen_seq = ev.seq
            self.cv.notify_all()

    def next(self, timeout: float = 1.0) -> Optional[Event]:
        deadline = _time.monotonic() + timeout
        while True:
            with self.cv:
                if self.queue:
                    race.write("Subscription.queue", self)
                    ev = self.queue.popleft()
                    self.last_seq = max(self.last_seq, ev.seq)
                    self.delivered += 1
                    return ev
                if self.closed:
                    return None
                if not self.catching_up:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return None
                    self.cv.wait(remaining)
                    continue
                after = self.last_seq
            # catch-up pull runs outside cv: lock order is strictly
            # broker._lock -> sub.cv, never the reverse
            if self.broker.replay_from(self, after) == 0 \
                    and _time.monotonic() >= deadline:
                return None

    def lag(self) -> int:
        """Events published that this subscriber has not consumed."""
        with self.broker._lock:
            seq = self.broker._seq
        with self.cv:
            return max(0, seq - self.last_seq - len(self.queue))

    def close(self) -> None:
        with self.cv:
            self.closed = True
            self.cv.notify_all()
        self.broker.unsubscribe(self)


class EventBroker:
    """Bounded ring buffer + fan-out to bounded subscriptions."""

    _RACE_TRACED = {"_subs": "_lock", "_buffer": "_lock"}

    def __init__(self, buffer_size: Optional[int] = None):
        self._lock = threading.Lock()
        self._buffer: deque = deque(maxlen=buffer_size or _default_buffer())
        self._subs: List[Subscription] = []
        self._seq = 0             # monotonic publish sequence (per broker)

    def publish(self, events: List[Event]) -> None:
        with self._lock:
            race.write("EventBroker._buffer", self)
            race.read("EventBroker._subs", self)
            for ev in events:
                self._seq += 1
                ev.seq = self._seq
                self._buffer.append(ev)
            subs = list(self._subs)
        for sub in subs:
            for ev in events:
                if sub.matches(ev):
                    sub.deliver(ev)

    def subscribe(self, topics: Dict[str, List[str]],
                  from_index: int = 0,
                  max_queue: Optional[int] = None) -> Subscription:
        with self._lock:
            race.write("EventBroker._subs", self)
            sub = Subscription(self, topics, from_index, max_queue)
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            race.write("EventBroker._subs", self)
            if sub in self._subs:
                self._subs.remove(sub)

    def replay_from(self, sub: Subscription, after_seq: int) -> int:
        """Catch-up pull: queue retained events newer than `after_seq`
        that match `sub`, up to its queue bound.  Flipping back to live
        mode happens here, under the broker lock, so no event published
        concurrently can fall between the ring and the live queue."""
        with self._lock:
            race.read("EventBroker._buffer", self)
            out = []
            for ev in self._buffer:
                if ev.seq > after_seq and ev.index > sub.from_index \
                        and sub.matches(ev):
                    out.append(ev)
                    if len(out) >= sub.max_queue:
                        break
            with sub.cv:
                if sub.closed:
                    return 0
                race.write("Subscription.queue", sub)
                for ev in out:
                    sub.queue.append(ev)
                    sub._seen_seq = max(sub._seen_seq, ev.seq)
                if len(out) < sub.max_queue:
                    sub.catching_up = False   # ring drained: back to live
                sub.cv.notify_all()
        return len(out)

    def stats(self) -> dict:
        """Per-subscriber lag/drop telemetry (surfaced in bench + tests)."""
        with self._lock:
            subs = list(self._subs)
            seq = self._seq
        per_sub = []
        for sub in subs:
            with sub.cv:
                race.read("Subscription.dropped", sub)
                per_sub.append({
                    "queue_len": len(sub.queue),
                    "max_queue": sub.max_queue,
                    "delivered": sub.delivered,
                    "dropped": sub.dropped,
                    "evictions": sub.evictions,
                    "catching_up": sub.catching_up,
                    "lag": max(0, seq - sub.last_seq - len(sub.queue)),
                })
        return {"published": seq, "subscribers": len(per_sub),
                "subs": per_sub}

    # ------------------------------------------------------- state bridge

    def watch_state(self, table: str, obj) -> None:
        """StateStore watcher: convert writes to stream events (reference:
        state store event publishing into the broker)."""
        topic_map = {
            "nodes": ("Node", lambda o: (o.id, "")),
            "jobs": ("Job", lambda o: (o.id, o.namespace)),
            "jobs_deregistered": ("Job", lambda o: (o.id, o.namespace)),
            "evals": ("Evaluation", lambda o: (o.id, o.namespace)),
            "allocs": ("Allocation", lambda o: (o.id, o.namespace)),
            "deployments": ("Deployment", lambda o: (o.id, o.namespace)),
        }
        entry = topic_map.get(table)
        if entry is None:
            return
        topic, keyfn = entry
        key, ns = keyfn(obj)
        type_ = {"jobs": "JobRegistered",
                 "jobs_deregistered": "JobDeregistered",
                 "nodes": "NodeRegistration",
                 "evals": "EvaluationUpdated",
                 "allocs": "AllocationUpdated",
                 "deployments": "DeploymentStatusUpdate"}[table]
        self.publish([Event(topic, type_, key, ns,
                            getattr(obj, "modify_index", 0), obj)])
