"""Single-process server: the control-plane spine wired together.

Reference analog: nomad/server.go + leader.go establishLeadership — state
store, eval broker, blocked evals, plan queue, the serialized plan-apply
loop, N scheduler workers, heartbeats and the periodic dispatcher.  This is
the in-memory '-dev agent' equivalent (no Raft/Serf: single region,
immediate consensus — multi-server replication is the RPC layer's job and
rides on the same indexed writes).
"""
from __future__ import annotations

import itertools
import threading
import time as _time
import uuid
from typing import Dict, List, Optional

from nomad_tpu.core.blocked import BlockedEvals
from nomad_tpu.core.broker import FAILED_QUEUE, EvalBroker
from nomad_tpu.core.core_gc import CoreScheduler
from nomad_tpu.core.deployments import DeploymentWatcher
from nomad_tpu.core.drainer import NodeDrainer
from nomad_tpu.core.events import EventBroker
from nomad_tpu.core.heartbeat import HeartbeatTracker
from nomad_tpu.core.periodic import PeriodicDispatcher
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.core.worker import Worker
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Evaluation,
    EvalStatus,
    Job,
    JobType,
    Node,
)
from nomad_tpu.structs.evaluation import EvalTrigger


class ServerConfig:
    def __init__(self, num_schedulers: int = 4,
                 enabled_schedulers: Optional[List[str]] = None,
                 heartbeat_ttl: float = 10.0,
                 gc_interval: float = 300.0):
        self.num_schedulers = num_schedulers
        self.enabled_schedulers = enabled_schedulers or \
            ["service", "batch", "system", "sysbatch"]
        self.heartbeat_ttl = heartbeat_ttl
        self.gc_interval = gc_interval


class Server:
    def __init__(self, config: Optional[ServerConfig] = None):
        self.config = config or ServerConfig()
        self.store = StateStore()
        self.broker = EvalBroker()
        self.blocked_evals = BlockedEvals(self.broker)
        self.plan_queue = PlanQueue()
        self.applier = PlanApplier(self.store)
        self.workers: List[Worker] = []
        self._raft_lock = threading.Lock()     # serializes indexed writes
        self._stop = threading.Event()
        self._plan_thread: Optional[threading.Thread] = None
        self._threads: List[threading.Thread] = []
        self.event_broker = EventBroker()
        self.heartbeats = HeartbeatTracker(self, ttl=self.config.heartbeat_ttl)
        self.deployment_watcher = DeploymentWatcher(self)
        self.drainer = NodeDrainer(self)
        self.periodic = PeriodicDispatcher(self)
        self.core_scheduler = CoreScheduler(self)
        self.store.watch(self.blocked_evals.watch_state)
        self.store.watch(self.event_broker.watch_state)
        self.store.watch(self._on_state_change)
        self.leader = False

    # ------------------------------------------------------------- indexes

    def next_index(self) -> int:
        with self._raft_lock:
            return self.store.latest_index + 1

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """establishLeadership (reference nomad/leader.go:277-357)."""
        self.leader = True
        self.broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.plan_queue.set_enabled(True)
        self._plan_thread = threading.Thread(
            target=self.applier.run_loop, args=(self.plan_queue, self._stop),
            name="plan-apply", daemon=True)
        self._plan_thread.start()
        for i in range(self.config.num_schedulers):
            w = Worker(self, i, self.config.enabled_schedulers)
            w.start()
            self.workers.append(w)
        self._restore_evals()
        t = threading.Thread(target=self._failed_eval_reaper,
                             name="eval-reaper", daemon=True)
        t.start()
        self._threads.append(t)
        self.heartbeats.start()
        self.deployment_watcher.start()
        self.drainer.start()
        self.periodic.start()
        gc_t = threading.Thread(target=self._gc_loop, name="core-gc",
                                daemon=True)
        gc_t.start()
        self._threads.append(gc_t)

    def stop(self) -> None:
        self._stop.set()
        self.heartbeats.stop()
        self.deployment_watcher.stop()
        self.drainer.stop()
        self.periodic.stop()
        for w in self.workers:
            w.stop()
        for w in self.workers:
            w.join(1.0)
        self.plan_queue.set_enabled(False)
        self.broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        if self._plan_thread:
            self._plan_thread.join(1.0)

    def _restore_evals(self) -> None:
        """On leadership: re-enqueue non-terminal evals (leader.go:572)."""
        for ev in list(self.store._evals.values()):
            if ev.should_enqueue():
                self.broker.enqueue(ev.copy())
            elif ev.should_block():
                self.blocked_evals.block(ev.copy())

    def _failed_eval_reaper(self) -> None:
        """Mark dead-lettered evals failed and create follow-ups
        (leader.go:842-884)."""
        while not self._stop.is_set():
            ev, token = self.broker.dequeue([FAILED_QUEUE], timeout=0.2)
            if ev is None:
                continue
            updated = ev.copy()
            updated.status = EvalStatus.FAILED
            updated.status_description = "maximum attempts reached"
            self.update_eval(updated)
            follow = Evaluation(
                namespace=ev.namespace, priority=ev.priority, type=ev.type,
                job_id=ev.job_id, triggered_by=EvalTrigger.FAILED_FOLLOW_UP,
                status=EvalStatus.PENDING,
                wait_until=_time.time() + 60.0)
            self.create_evals([follow])
            self.broker.ack(ev.id, token)

    def _gc_loop(self) -> None:
        """Leader periodic GC timers (reference leader.go:782-810 core-job
        eval scheduling, here invoked directly)."""
        while not self._stop.wait(self.config.gc_interval):
            try:
                self.core_scheduler.process("force-gc")
            except Exception:               # noqa: BLE001
                import logging
                logging.getLogger(__name__).exception("core gc")

    # ------------------------------------------------------------- watches

    def _on_state_change(self, table: str, obj) -> None:
        # alloc terminations free capacity: unblock that node's class
        if table == "allocs":
            a = obj
            if a.terminal_status():
                node = self.store._nodes.get(a.node_id)
                if node is not None:
                    self.blocked_evals.unblock(node.computed_class,
                                               self.store.latest_index)
            # preempted allocs need their job rescheduled (the reference
            # creates PreemptionEvals in applyPlan, plan_apply.go:204+)
            if a.preempted_by_allocation and a.desired_status == "evict" \
                    and not getattr(a, "_preemption_eval_created", False):
                a._preemption_eval_created = True
                job = a.job or self.store.job_by_id(a.namespace, a.job_id)
                if job is not None and not job.stopped():
                    self.create_evals([Evaluation(
                        namespace=a.namespace, priority=job.priority,
                        type=job.type, job_id=job.id,
                        triggered_by=EvalTrigger.PREEMPTION,
                        status=EvalStatus.PENDING)])

    # ------------------------------------------------------------- API ops
    # (these are what the RPC endpoints call; reference nomad/job_endpoint.go,
    #  node_endpoint.go, eval_endpoint.go)

    def update_eval(self, ev: Evaluation) -> None:
        with self._raft_lock:
            self.store.upsert_evals(self.store.latest_index + 1, [ev])

    def create_evals(self, evals: List[Evaluation]) -> None:
        copies = [e.copy() for e in evals]
        with self._raft_lock:
            self.store.upsert_evals(self.store.latest_index + 1, copies)
        for e in copies:
            if e.should_enqueue():
                self.broker.enqueue(e)
            elif e.should_block():
                # FSM leader hook: blocked evals go to the blocked tracker
                self.blocked_evals.block(e)

    def register_job(self, job: Job) -> Evaluation:
        """Job.Register (nomad/job_endpoint.go:81): upsert + eval."""
        with self._raft_lock:
            self.store.upsert_job(self.store.latest_index + 1, job)
        ev = Evaluation(
            namespace=job.namespace, priority=job.priority, type=job.type,
            job_id=job.id, triggered_by=EvalTrigger.JOB_REGISTER,
            status=EvalStatus.PENDING,
            job_modify_index=job.job_modify_index)
        ev.modify_index = job.modify_index
        if not job.is_periodic() and not job.is_parameterized():
            self.create_evals([ev])
        return ev

    def deregister_job(self, namespace: str, job_id: str, purge: bool = False) -> Optional[Evaluation]:
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        with self._raft_lock:
            if purge:
                self.store.delete_job(self.store.latest_index + 1, namespace, job_id)
            else:
                stopped = job.copy()
                stopped.stop = True
                self.store.upsert_job(self.store.latest_index + 1, stopped)
        self.blocked_evals.untrack(namespace, job_id)
        ev = Evaluation(
            namespace=namespace, priority=job.priority, type=job.type,
            job_id=job_id, triggered_by=EvalTrigger.JOB_DEREGISTER,
            status=EvalStatus.PENDING)
        self.create_evals([ev])
        return ev

    def set_job_stability(self, namespace: str, job_id: str, version: int,
                          stable: bool) -> None:
        with self._raft_lock:
            self.store.mark_job_stability(
                self.store.latest_index + 1, namespace, job_id, version, stable)

    def register_node(self, node: Node) -> None:
        """Node.Register (nomad/node_endpoint.go:79)."""
        with self._raft_lock:
            self.store.upsert_node(self.store.latest_index + 1, node)
        if self.leader:
            self.heartbeats.heartbeat(node.id)

    def node_heartbeat(self, node_id: str) -> float:
        """Node.UpdateStatus heartbeat path: reset TTL; a down node
        re-heartbeating is brought back to ready (init->ready handled by
        client re-registration)."""
        node = self.store.node_by_id(node_id)
        if node is not None and node.status in ("down", "disconnected"):
            self.update_node_status(node_id, "ready")
        return self.heartbeats.heartbeat(node_id)

    def update_node_status(self, node_id: str, status: str) -> List[Evaluation]:
        """Node.UpdateStatus: transition + evals for affected jobs."""
        with self._raft_lock:
            self.store.update_node_status(
                self.store.latest_index + 1, node_id, status, _time.time())
        return self.create_node_evals(node_id)

    def create_node_evals(self, node_id: str) -> List[Evaluation]:
        """Evaluate all jobs with allocs on the node plus system jobs
        (reference createNodeEvals, node_endpoint.go)."""
        evals = []
        seen = set()
        for a in self.store.allocs_by_node(node_id):
            job = a.job or self.store.job_by_id(a.namespace, a.job_id)
            if job is None or job.id in seen:
                continue
            seen.add(job.id)
            evals.append(Evaluation(
                namespace=a.namespace, priority=job.priority, type=job.type,
                job_id=job.id, triggered_by=EvalTrigger.NODE_UPDATE,
                node_id=node_id, status=EvalStatus.PENDING,
                modify_index=self.store.latest_index))
        for job in self.store.jobs():
            if job.type in (JobType.SYSTEM, JobType.SYSBATCH) \
                    and job.id not in seen and not job.stopped():
                seen.add(job.id)
                evals.append(Evaluation(
                    namespace=job.namespace, priority=job.priority,
                    type=job.type, job_id=job.id,
                    triggered_by=EvalTrigger.NODE_UPDATE, node_id=node_id,
                    status=EvalStatus.PENDING,
                    modify_index=self.store.latest_index))
        if evals:
            self.create_evals(evals)
        return evals

    # ------------------------------------------------------------- helpers

    def wait_for_idle(self, timeout: float = 10.0) -> bool:
        """Testing/bench helper: wait until no evals are queued or in
        flight."""
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if (self.broker.ready_count() == 0
                    and not self.broker._unack
                    and self.plan_queue.depth() == 0):
                return True
            _time.sleep(0.01)
        return False
