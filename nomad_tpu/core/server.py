"""Server: the control-plane spine wired together.

Reference analog: nomad/server.go + leader.go establishLeadership — state
store, eval broker, blocked evals, plan queue, the serialized plan-apply
loop, N scheduler workers, heartbeats and the periodic dispatcher.

Two consensus modes, mirroring the reference's raftInmem vs raft-boltdb:
 - dev (raft=None): single server, writes apply straight through the
   NomadFSM under a lock (the '-dev agent' in-memory Raft).
 - cluster: writes go through `RaftNode.apply` and every member's FSM
   replays them; leadership elections drive establish/revoke of the
   leader-only subsystems (nomad/leader.go:277,1099).
"""
from __future__ import annotations

import itertools
import logging
import os
import pickle
import threading
import time as _time
import uuid
from typing import Dict, List, Optional

from nomad_tpu import knobs, tracing
from nomad_tpu.core.blocked import BlockedEvals
from nomad_tpu.core.broker import FAILED_QUEUE, EvalBroker
from nomad_tpu.core.core_gc import CoreScheduler
from nomad_tpu.core.deployments import DeploymentWatcher
from nomad_tpu.core.drainer import NodeDrainer
from nomad_tpu.core.events import EventBroker
from nomad_tpu.core.heartbeat import HeartbeatBatcher, HeartbeatTracker
from nomad_tpu.core.periodic import PeriodicDispatcher
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue
from nomad_tpu.core.secrets import SecretsProvider
from nomad_tpu.serving.gate import ReadGate
from nomad_tpu.core.worker import Worker
from nomad_tpu.raft import (
    ConfigurationInFlightError,
    DurableMeta,
    FileSnapshotStore,
    LogStore,
    MessageType,
    NomadFSM,
    NotLeaderError,
    RaftNode,
)
from nomad_tpu.state import StateStore
from nomad_tpu.structs import (
    Evaluation,
    EvalStatus,
    Job,
    JobType,
    Node,
)
from nomad_tpu.structs.evaluation import EvalTrigger

log = logging.getLogger(__name__)


class ServerConfig:
    def __init__(self, num_schedulers: int = 4,
                 enabled_schedulers: Optional[List[str]] = None,
                 heartbeat_ttl: float = 10.0,
                 heartbeat_batch_interval: float = 0.05,
                 gc_interval: float = 300.0,
                 data_dir: Optional[str] = None,
                 region: str = "global",
                 failed_eval_followup_delay: float = 60.0,
                 integrity_interval: float = 2.0,
                 integrity_full_every: int = 4):
        self.num_schedulers = num_schedulers
        self.enabled_schedulers = enabled_schedulers or \
            ["service", "batch", "system", "sysbatch"]
        self.heartbeat_ttl = heartbeat_ttl
        # flush cadence of the leader's heartbeat/node-status coalescer
        # (one NodeHeartbeatBatch raft entry per flush);
        # NOMAD_TPU_HEARTBEAT_BATCH_MS overrides
        self.heartbeat_batch_interval = knobs.get_float(
            "NOMAD_TPU_HEARTBEAT_BATCH_MS",
            default=heartbeat_batch_interval * 1000.0) / 1000.0
        self.gc_interval = gc_interval
        self.data_dir = data_dir
        self.region = region
        self.failed_eval_followup_delay = failed_eval_followup_delay
        # replica-integrity plane: STATE_CHECKPOINT proposal cadence
        # (seconds; <= 0 disables) and the every-Nth full digest walk;
        # NOMAD_TPU_INTEGRITY_INTERVAL / _FULL_EVERY override
        self.integrity_interval = knobs.get_float(
            "NOMAD_TPU_INTEGRITY_INTERVAL", default=integrity_interval)
        self.integrity_full_every = max(1, knobs.get_int(
            "NOMAD_TPU_INTEGRITY_FULL_EVERY",
            default=integrity_full_every))


class Server:
    # wait-graph (nomad_tpu.analysis)
    _LOCK_BLOCKING_OK = {
        "_leader_lock": "establish/revoke are serialized on the raft "
                        "leadership dispatcher thread and no "
                        "raft-internal thread takes this lock, so the "
                        "commit barrier inside establishLeadership is "
                        "a bounded stall (its own timeout), never a "
                        "cycle — mirrors the reference leaderLoop",
    }

    def __init__(self, config: Optional[ServerConfig] = None,
                 name: str = "server-1",
                 peers: Optional[List[str]] = None,
                 raft_transport=None,
                 raft_config=None,
                 membership=None,
                 raft_join: bool = False,
                 wan_pool=None):
        self.config = config or ServerConfig()
        self.name = name
        self.store = StateStore()
        self.broker = EvalBroker()
        self.broker.node_name = name     # span attribution (tracing)
        self.blocked_evals = BlockedEvals(self.broker)
        self.plan_queue = PlanQueue()
        self.applier = PlanApplier(self.store, commit_fn=self._commit_plan)
        self.applier.node_name = name
        # PreemptionEvals are created by the applier AFTER the raft apply
        # returns (reference plan_apply.go applyPlan) — creating them from
        # inside the FSM's state-change watcher would re-enter the raft
        # write path under its own lock and deadlock the commit
        self.applier.on_preempted = self._create_preemption_evals
        self.workers: List[Worker] = []
        self.remote_workers: List[Worker] = []
        # dev-mode wave-aligned dequeue front (set at leadership)
        self.eval_feeder = None
        self._raft_lock = threading.Lock()     # serializes indexed writes
        self._stop = threading.Event()
        self._leader_stop = threading.Event()
        self._leader_lock = threading.Lock()
        self._plan_thread: Optional[threading.Thread] = None
        self._threads: List[threading.Thread] = []
        self.event_broker = EventBroker()
        self.heartbeats = HeartbeatTracker(self, ttl=self.config.heartbeat_ttl)
        self.heartbeat_batch = HeartbeatBatcher(
            self, interval=self.config.heartbeat_batch_interval)
        self.deployment_watcher = DeploymentWatcher(self)
        from nomad_tpu.core.volumes import VolumeWatcher
        self.volume_watcher = VolumeWatcher(self)
        # Vault-shaped secrets (core/secrets.py): leases are leader-local
        # like the reference's external-Vault client state, not raft state
        self.secrets = SecretsProvider()
        self.drainer = NodeDrainer(self)
        self.periodic = PeriodicDispatcher(self)
        self.core_scheduler = CoreScheduler(self)
        self.store.watch(self.blocked_evals.watch_state)
        self.store.watch(self.event_broker.watch_state)
        self.store.watch(self._on_state_change)
        self.leader = False
        self._established = False
        # deny-by-default token enforcement on HTTP/RPC mutation paths
        # (reference: `acl { enabled = true }` agent config)
        if knobs.get_bool("NOMAD_TPU_ACL"):
            self.acl_enabled = True

        self.fsm = NomadFSM(self.store, hooks=self)
        self.raft: Optional[RaftNode] = None
        self._transport = raft_transport
        from nomad_tpu.rpc.endpoints import Endpoints
        self.endpoints = Endpoints(self)
        # overload plane: per-namespace admission (off unless the env
        # knobs set limits) + leader brownout classification (always
        # on — level 0 until the raft signals cross the thresholds)
        from nomad_tpu.admission import AdmissionGate, BrownoutMonitor
        self.admission = AdmissionGate()
        self.brownout = BrownoutMonitor(self)
        # consistency-mode read gate: every server (leader or follower)
        # serves reads from its LOCAL store once the gate establishes a
        # read point (serving/gate.py)
        self.serving_gate = ReadGate(self)
        self.membership = membership   # LAN gossip (core.membership)
        # multi-region federation (nomad/serf.go WAN pool + nomad/rpc.go
        # forwardRegion): servers discover other regions over a second
        # SWIM instance (wan_pool, channel "wan") tagged with region +
        # leader-ness, and the router forwards RPCs to the remote
        # region's current leader.  `_region_peers` remains as the
        # static route table for in-process federation (dev mode).
        self.region = self.config.region
        self._region_peers: Dict[str, object] = {}
        self.wan_pool = wan_pool
        from nomad_tpu.federation import RegionRouter
        self.region_router = RegionRouter(self)
        if raft_transport is not None:
            raft_transport.register(f"rpc:{name}", self.endpoints.handle)
            data_dir = self.config.data_dir
            log_store = snapshots = meta = None
            if data_dir:
                sdir = os.path.join(data_dir, name)
                os.makedirs(sdir, exist_ok=True)
                log_store = LogStore(os.path.join(sdir, "raft.log"))
                snapshots = FileSnapshotStore(os.path.join(sdir, "snapshots"))
                # term + vote on stable storage: without this a restarted
                # server can grant a second vote in the same term
                meta = DurableMeta(os.path.join(sdir, "raft_meta.json"))
            self.raft = RaftNode(
                name, peers or [name], raft_transport, self.fsm,
                config=raft_config, log_store=log_store, snapshots=snapshots,
                meta=meta,
                on_leader=self._establish_leadership,
                on_follower=self._revoke_leadership,
                join=raft_join)
        # autopilot (reference nomad/autopilot.go): the leader promotes
        # caught-up non-voters after a stabilization window and, when
        # gossip runs, adds ALIVE members / removes LEFT ones / reaps
        # FAILED ones out of the raft configuration
        self._autopilot_interval = knobs.get_float(
            "NOMAD_TPU_AUTOPILOT_INTERVAL")
        self._autopilot_stabilization = knobs.get_float(
            "NOMAD_TPU_AUTOPILOT_STABILIZATION")
        self._autopilot_lag = knobs.get_int("NOMAD_TPU_AUTOPILOT_LAG")
        self._autopilot_reap_after = knobs.get_float(
            "NOMAD_TPU_AUTOPILOT_REAP_AFTER")
        self._nonvoter_since: Dict[str, float] = {}
        self._failed_since: Dict[str, float] = {}

    # ------------------------------------------------------------- writes

    def apply(self, msg_type: str, payload: dict) -> int:
        """The single write path: a (type, payload) log entry applied via
        the FSM — through Raft when clustered, directly in dev mode
        (reference raft.Apply → nomadFSM.Apply).  On a follower the write
        forwards to the leader over RPC (reference forwardLeader,
        nomad/rpc.go)."""
        try:
            return self.apply_local(msg_type, payload)
        except NotLeaderError:
            return self.rpc_leader("Raft.Apply",
                                   {"msg_type": msg_type, "payload": payload})

    def apply_local(self, msg_type: str, payload: dict) -> int:
        """Apply on THIS server (no forwarding) — the Raft.Apply endpoint
        target; raises NotLeaderError if a follower is asked directly."""
        if self.raft is not None:
            return self.raft.apply(msg_type, payload)
        tracer = tracing.active
        ctx = tracing.current() if tracer is not None else None
        t0 = _time.time() if ctx is not None else 0.0
        with self._raft_lock:
            index = self.store.latest_index + 1
            self.fsm.apply(index, msg_type, payload)
        if ctx is not None:
            # dev mode (no raft): observe-time apply span — timestamps
            # taken outside the FSM, which never reads the clock
            tracer.emit(ctx, "raft.fsm_apply", t0, _time.time(),
                        node=self.name, msg_type=msg_type, index=index)
        return index

    def rpc_leader(self, method: str, args: dict):
        """Invoke an RPC on the leader: short-circuits locally when this
        server is the leader (or in dev mode), else rides the transport
        (reference: rpc.go forward + helper/pool)."""
        if self.raft is None or self.raft.is_leader:
            return self.endpoints.handle(method, args)
        leader = self.raft.leader_id
        if leader is None or leader == self.name or self._transport is None:
            # leader == self.name while not is_leader = stale self-pointer
            # during a transition; forwarding would recurse into ourselves
            from nomad_tpu.rpc.endpoints import RpcError
            raise RpcError("no_leader", "no cluster leader")
        # the transport hop leaves this thread: re-attach the sampled
        # trace context and re-encode the remaining deadline budget so
        # the leader inherits both (reserved-key contract, rpc/reserved)
        from nomad_tpu.rpc import reserved
        return self._transport.call(self.name, f"rpc:{leader}", method,
                                    reserved.restamp(args))

    # ------------------------------------------------------------- reads

    def read(self, method: str, args: dict,
             consistency: str = "default", timeout: float = 5.0):
        """Serve a read RPC from THIS server's store at a gate-established
        read point; returns (result, ReadContext).  This is the follower-
        read path: nothing here touches the leader beyond what the
        consistency mode requires (zero rounds for a valid lease, one
        forwarded ReadIndex RPC otherwise, nothing at all for stale)."""
        ctx = self.serving_gate.begin_read(consistency, timeout)
        return self.endpoints.handle(method, args), ctx

    # ------------------------------------------------------------- regions

    def federate(self, other: "Server") -> None:
        """Two-way in-process federation (reference: WAN serf join,
        nomad/serf.go — each region learns a route to the other's
        servers).  Transitive routes propagate so a three-region mesh
        needs only pairwise joins."""
        self._region_peers[other.region] = other
        other._region_peers[self.region] = self
        for r, p in list(other._region_peers.items()):
            if r not in (self.region,) and r not in self._region_peers:
                self._region_peers[r] = p
        for r, p in list(self._region_peers.items()):
            if r not in (other.region,) and r not in other._region_peers:
                other._region_peers[r] = p

    def federate_name(self, region: str, server_name: str) -> None:
        """Static transport-based federation route: RPCs for `region` may
        forward to `server_name` over the shared transport.  The WAN
        gossip pool supersedes this once members are discovered; the
        static entry remains a seed/fallback."""
        self._region_peers[region] = server_name

    def regions(self) -> List[str]:
        """Known regions, sorted and deduped, always including ours:
        WAN-pool-discovered regions plus static federation routes."""
        regs = {self.region, *self._region_peers}
        if self.wan_pool is not None:
            regs.update(self.wan_pool.regions())
        return sorted(regs)

    def rpc_region(self, region: str, method: str, args: dict):
        """Route an RPC to the right region's leader (reference
        nomad/rpc.go:21 forwardRegion).  Local region short-circuits;
        remote regions go through the federation router (known-leader
        hints, bounded retry over remote churn, Unreachable fail-fast
        when the region is dark)."""
        # app-level forwards (job.region routing, leader handoffs) build
        # fresh args: re-attach this thread's sampled trace context AND
        # re-encode the remaining deadline budget so both survive the
        # hop like they do the _forward_hops path (before restamp() the
        # budget silently vanished here and the remote region served
        # the request unbounded)
        from nomad_tpu.rpc import reserved
        return self.region_router.route(region, method,
                                        reserved.restamp(args))

    def enqueue_plan(self, plan):
        """Plan-queue enqueue gated on the submitting worker still holding
        its eval lease (reference planner token check, plan_endpoint.go):
        if the lease expired (auto-nack) or moved to another worker, this
        plan is from a superseded scheduling pass and must not commit."""
        if plan.eval_id and plan.eval_token:
            if self.broker.outstanding(plan.eval_id) != plan.eval_token:
                from nomad_tpu.rpc.endpoints import RpcError
                raise RpcError(
                    "stale_eval_token",
                    f"eval {plan.eval_id}: lease expired or reassigned")
        return self.plan_queue.enqueue(plan)

    def _commit_plan(self, applied) -> int:
        """Commit applier output through the raft write path.  `applied`
        is one AppliedPlanResults or a LIST of them — the applier
        coalesces adjacent plans from the queue into one log entry (one
        raft apply, one index) and the FSM fans the batch out to the
        store under a single lock acquisition.

        Deliberately NOT leader-forwarded (apply_local, not apply): the
        eval-token gate runs at enqueue time against THIS server's
        broker, so a plan stranded in the applier when leadership moves
        must fail with NotLeaderError — forwarding it would commit a
        deposed leader's plan on the new leader, whose broker may have
        already redelivered the eval and committed a competing plan
        (double placement).  The failed future nacks the eval and it
        reschedules under the new leader's gate."""
        return self.apply_local(MessageType.APPLY_PLAN_RESULTS,
                                {"results": applied})

    def next_index(self) -> int:
        with self._raft_lock:
            return self.store.latest_index + 1

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        # placements can fail against transient in-flight over-reservation
        # (the engine overlay's double-count window); once the overlay
        # drains, give blocked evals another chance
        from nomad_tpu.parallel.engine import get_engine
        _eng = get_engine()
        if _eng is not None:
            _eng.on_drain = lambda: self.blocked_evals.unblock_all(
                self.store.latest_index)
        if self.membership is not None:
            self.membership.start()
        if self.wan_pool is not None:
            self.wan_pool.start()
        if self.raft is not None:
            # every server runs schedulers against its replicated snapshot,
            # RPCing the leader for dequeue/ack/plan-submit (reference:
            # workers run on all servers, nomad/worker.go:81-85)
            from nomad_tpu.core.worker import RemoteWorker
            for i in range(self.config.num_schedulers):
                w = RemoteWorker(self, i, self.config.enabled_schedulers)
                w.start()
                self.remote_workers.append(w)
            self.raft.start()
        else:
            self._establish_leadership()

    def _establish_leadership(self) -> None:
        """establishLeadership (reference nomad/leader.go:277-357)."""
        with self._leader_lock:
            if self._established:
                return
            self._established = True
            self.leader = True
            if self.wan_pool is not None:
                # leadership rides the WAN tags: remote regions route to
                # us once the re-tag gossips out (nomad/serf.go member
                # tags carrying raft leadership)
                self.wan_pool.set_leader(True)
            self._leader_stop = threading.Event()
            stop = self._leader_stop
            self.broker.set_enabled(True)
            # fairness knobs live in replicated SchedulerConfiguration;
            # a fresh leader's broker must adopt the committed values
            # (later changes arrive via the FSM's scheduler-config hook)
            self.broker.set_fair_config(self.store.scheduler_config)
            self.blocked_evals.set_enabled(True)
            self.plan_queue.set_enabled(True)
            self._plan_thread = threading.Thread(
                target=self.applier.run_loop, args=(self.plan_queue, stop),
                name="plan-apply", daemon=True)
            self._plan_thread.start()
            if self.raft is None:
                # dev mode: local workers; in cluster mode RemoteWorkers
                # already run on every member (started in start()).  The
                # wave feeder aligns the pool's dequeues: one broker lock
                # pass drains a whole ready wave so the engine coalesces
                # full-wave dispatch batches (NOMAD_TPU_WAVE caps it).
                from nomad_tpu.core.broker import EvalWaveFeeder
                wave_n = knobs.get_int(
                    "NOMAD_TPU_WAVE",
                    default=self.config.num_schedulers)
                self.eval_feeder = EvalWaveFeeder(self.broker, wave_n)
                for i in range(self.config.num_schedulers):
                    w = Worker(self, i, self.config.enabled_schedulers)
                    w.start()
                    self.workers.append(w)
            if self.raft is not None:
                # barrier before reading the store: a fresh leader may
                # still be replaying committed entries, and restoring
                # evals from a stale view would drop the tail of them
                self.raft.barrier(5.0)
            self._restore_evals()
            t = threading.Thread(target=self._failed_eval_reaper,
                                 args=(stop,), name="eval-reaper", daemon=True)
            t.start()
            self._threads.append(t)
            dup_t = threading.Thread(target=self._dup_blocked_reaper,
                                     args=(stop,), name="dup-blocked-reaper",
                                     daemon=True)
            dup_t.start()
            self._threads.append(dup_t)
            self.heartbeat_batch.start()
            self.heartbeats.start()
            # initializeHeartbeatTimers (leader.go:347): nodes registered
            # under a previous leader get timers on the new one, so a node
            # that died around the failover still expires
            for node in self.store.nodes():
                if not node.terminal_status():
                    self.heartbeats.heartbeat(node.id)
            self.deployment_watcher.start()
            self.volume_watcher.start()
            self.drainer.start()
            self.periodic.start()
            gc_t = threading.Thread(target=self._gc_loop, args=(stop,),
                                    name="core-gc", daemon=True)
            gc_t.start()
            self._threads.append(gc_t)
            if self.raft is not None:
                ap_t = threading.Thread(target=self._autopilot_loop,
                                        args=(stop,), name="autopilot",
                                        daemon=True)
                ap_t.start()
                self._threads.append(ap_t)
                if self.config.integrity_interval > 0:
                    it_t = threading.Thread(target=self._integrity_loop,
                                            args=(stop,), name="integrity",
                                            daemon=True)
                    it_t.start()
                    self._threads.append(it_t)

    # ------------------------------------------------------------- integrity

    def _integrity_loop(self, stop: threading.Event) -> None:
        """Leader-side STATE_CHECKPOINT proposer (Paxos-Made-Live
        log-stamped checksums): one checkpoint entry per interval, every
        `integrity_full_every`-th a full digest walk, plus an immediate
        full walk whenever a mismatch at an incremental checkpoint
        escalates.  The entry is stamped at PROPOSE time — the FSM never
        reads the clock — and applies as a deterministic no-op; the raft
        apply loop computes the digest at its log position."""
        interval = self.config.integrity_interval
        full_every = self.config.integrity_full_every
        seq = 0
        last = _time.monotonic()
        while not stop.wait(min(0.05, interval / 4.0)):
            raft = self.raft
            if raft is None or not raft.is_leader:
                continue
            escalated = raft.integrity.escalation_pending()
            if not escalated and _time.monotonic() - last < interval:
                continue
            seq += 1
            full = escalated or (seq % full_every == 0)
            if escalated:
                raft.integrity.take_escalation()
            last = _time.monotonic()
            try:
                self.apply_local(MessageType.STATE_CHECKPOINT, {
                    "seq": seq, "full": full,
                    "proposed_at": _time.time()})
            except Exception:                       # noqa: BLE001
                # deposed mid-propose or transient quorum loss: the
                # next tick retries (seq gaps are fine — the digest
                # protocol keys on log index, not seq)
                log.debug("integrity checkpoint propose failed",
                          exc_info=True)

    # ------------------------------------------------------------- autopilot

    def _autopilot_loop(self, stop: threading.Event) -> None:
        self._nonvoter_since.clear()
        self._failed_since.clear()
        while not stop.wait(self._autopilot_interval):
            try:
                self._autopilot_tick()
            except Exception:                       # noqa: BLE001
                log.debug("autopilot tick failed", exc_info=True)

    def _autopilot_tick(self) -> None:
        """One autopilot pass (leader only): promote stabilized
        non-voters; with gossip running, add ALIVE members to the
        configuration as non-voters, remove LEFT ones immediately, and
        reap FAILED ones after the reap window.  Membership changes are
        serialized by raft's one-in-flight rule — a conflict just means
        the next tick retries."""
        raft = self.raft
        if raft is None or not raft.is_leader:
            self._nonvoter_since.clear()
            self._failed_since.clear()
            return
        cfg = raft.configuration()
        now = _time.monotonic()
        for nv in cfg["nonvoters"]:
            if raft.server_healthy(nv, lag=self._autopilot_lag):
                since = self._nonvoter_since.setdefault(nv, now)
                if now - since >= self._autopilot_stabilization:
                    self._autopilot_change(raft.add_server, nv, voter=True)
                    self._nonvoter_since.pop(nv, None)
            else:
                # health flap: the stabilization window starts over
                self._nonvoter_since[nv] = now
        if self.membership is None:
            return
        in_cfg = set(cfg["voters"]) | set(cfg["nonvoters"])
        members = {m["name"]: m for m in self.membership.member_list()}
        for mname, m in members.items():
            if m["status"] == "alive" and mname not in in_cfg:
                self._autopilot_change(raft.add_server, mname)
            elif m["status"] == "left" and mname in in_cfg \
                    and mname != self.name:
                self._autopilot_change(raft.remove_server, mname)
            elif m["status"] == "failed" and mname in in_cfg \
                    and mname != self.name:
                since = self._failed_since.setdefault(mname, now)
                if now - since >= self._autopilot_reap_after:
                    self._autopilot_change(raft.remove_server, mname)
                    self._failed_since.pop(mname, None)
        for mname in list(self._failed_since):
            if members.get(mname, {}).get("status") != "failed":
                del self._failed_since[mname]

    def _autopilot_change(self, op, server: str, **kw) -> None:
        try:
            op(server, timeout=5.0, **kw)
        except (NotLeaderError, ConfigurationInFlightError):
            pass        # deposed or a change in flight: next tick retries
        except Exception:                           # noqa: BLE001
            log.debug("autopilot %s(%s) failed", op.__name__, server,
                      exc_info=True)

    def _revoke_leadership(self) -> None:
        """revokeLeadership (reference nomad/leader.go:1099-1132)."""
        with self._leader_lock:
            if not self._established:
                return
            self._established = False
            self.leader = False
            if self.wan_pool is not None:
                self.wan_pool.set_leader(False)
            self._leader_stop.set()
            self.heartbeats.stop()
            self.heartbeat_batch.stop()
            self.deployment_watcher.stop()
            self.volume_watcher.stop()
            self.drainer.stop()
            self.periodic.stop()
            for w in self.workers:
                w.stop()
            for w in self.workers:
                w.join(1.0)
            self.workers = []
            if self.eval_feeder is not None:
                self.eval_feeder.close()
                self.eval_feeder = None
            self.plan_queue.set_enabled(False)
            self.broker.set_enabled(False)
            self.blocked_evals.set_enabled(False)
            if self._plan_thread:
                self._plan_thread.join(1.0)
                self._plan_thread = None

    def stop(self) -> None:
        # graceful leave: a leader hands off BEFORE saying goodbye, so
        # followers elect a successor in milliseconds instead of waiting
        # out an election timeout of silence (transfer_leadership returns
        # False fast when no viable target exists)
        if self.raft is not None and self.raft.is_leader:
            try:
                self.raft.transfer_leadership()
            except Exception:                      # noqa: BLE001
                pass
        if self.membership is not None:
            try:
                self.membership.leave()
            except Exception:                      # noqa: BLE001
                pass
            self.membership = None
        if self.wan_pool is not None:
            # graceful goodbye on the WAN too: remote regions see LEFT
            # (and reap into a tombstone) instead of suspecting a failure
            try:
                self.wan_pool.leave()
            except Exception:                      # noqa: BLE001
                pass
            self.wan_pool = None
        self._stop.set()
        for w in self.remote_workers:
            w.stop()
        self._revoke_leadership()
        for w in self.remote_workers:
            w.join(1.0)
        self.remote_workers = []
        if self.raft is not None:
            self.raft.stop()

    def crash(self) -> None:
        """Hard-kill (power loss) simulation: threads stop, but nothing
        flushes — the raft WAL loses its unsynced tail (and may keep a
        torn record under chaos `disk.torn_write`).  The durability soak
        restarts a crashed server from the same data_dir and asserts no
        committed state was lost."""
        self._stop.set()
        for w in self.remote_workers:
            w.stop()
        self._revoke_leadership()
        for w in self.remote_workers:
            w.join(1.0)
        self.remote_workers = []
        if self.raft is not None:
            self.raft.crash()
        if self.wan_pool is not None:
            # no goodbye: remote regions must detect the failure through
            # the WAN failure detector, not a graceful LEFT
            self.wan_pool.stop()
            self.wan_pool = None
        if self._transport is not None:
            self._transport.deregister(f"rpc:{self.name}")

    # ------------------------------------------------------------- snapshots

    def save_snapshot(self, path: str) -> None:
        """Operator snapshot save (reference `nomad operator snapshot save`,
        helper/snapshot/)."""
        blob = self.fsm.snapshot()
        with open(path, "wb") as fh:
            pickle.dump({"index": self.store.latest_index,
                         "data": blob}, fh)

    def restore_snapshot(self, path: str) -> None:
        """Operator snapshot restore: replace state wholesale.  Dev-mode
        only — a clustered member restoring locally would diverge from its
        peers; clustered restore must flow through Raft's InstallSnapshot
        (the reference's operator restore goes through raft.Restore)."""
        if self.raft is not None:
            raise RuntimeError(
                "restore_snapshot on a clustered server would diverge "
                "from peers; restore the whole cluster from the snapshot "
                "via fresh data dirs instead")
        with open(path, "rb") as fh:
            rec = pickle.load(fh)
        self.fsm.restore(rec["data"])

    def _restore_evals(self) -> None:
        """On leadership: re-enqueue non-terminal evals (leader.go:572)."""
        for ev in self.store.evals():
            if ev.should_enqueue():
                self.broker.enqueue(ev.copy())
            elif ev.should_block():
                self.blocked_evals.block(ev.copy())
        # the missed-unblock indexes died with the old leader: a node that
        # recovered just before the failover is invisible to this tracker,
        # so a restored eval would block forever on its stale snapshot.
        # Give every restored eval one clean re-evaluation; the still
        # infeasible ones re-block with a fresh snapshot_index that this
        # leader's capacity watch covers.
        self.blocked_evals.unblock_once(self.store.latest_index)

    def _failed_eval_reaper(self, stop: threading.Event) -> None:
        """Mark dead-lettered evals failed and create follow-ups
        (leader.go:842-884)."""
        while not stop.is_set() and not self._stop.is_set():
            ev, token = self.broker.dequeue([FAILED_QUEUE], timeout=0.2)
            if ev is None:
                continue
            updated = ev.copy()
            updated.status = EvalStatus.FAILED
            updated.status_description = "maximum attempts reached"
            self.update_eval(updated)
            follow = Evaluation(
                namespace=ev.namespace, priority=ev.priority, type=ev.type,
                job_id=ev.job_id, triggered_by=EvalTrigger.FAILED_FOLLOW_UP,
                status=EvalStatus.PENDING,
                wait_until=_time.time() +
                self.config.failed_eval_followup_delay)
            self.create_evals([follow])
            self.broker.ack(ev.id, token)

    def _dup_blocked_reaper(self, stop: threading.Event) -> None:
        """Cancel duplicate blocked evals in the store (reference
        reapDupBlockedEvaluations, leader.go:815): the tracker keeps one
        blocked eval per job and drops the rest, but the dropped ones
        would otherwise sit BLOCKED in replicated state forever."""
        while not stop.wait(0.2):
            if self._stop.is_set():
                return
            for ev in self.blocked_evals.get_duplicates():
                cancelled = ev.copy()
                cancelled.status = EvalStatus.CANCELLED
                cancelled.status_description = \
                    "existing blocked evaluation exists for this job"
                try:
                    self.update_eval(cancelled)
                except Exception:               # noqa: BLE001
                    pass                        # deposed mid-write: drop

    def _gc_loop(self, stop: threading.Event) -> None:
        """Leader periodic GC timers (reference leader.go:782-810 core-job
        eval scheduling, here invoked directly)."""
        while not stop.wait(self.config.gc_interval):
            if self._stop.is_set():
                return
            try:
                self.core_scheduler.process("force-gc")
            except Exception:               # noqa: BLE001
                import logging
                logging.getLogger(__name__).exception("core gc")

    # ------------------------------------------------------------- watches

    def _on_state_change(self, table: str, obj) -> None:
        # alloc terminations free capacity: unblock that node's class
        if table == "allocs":
            a = obj
            if a.terminal_status():
                node = self.store.node_by_id(a.node_id)
                if node is not None:
                    self.blocked_evals.unblock(node.computed_class,
                                               self.store.latest_index)

    # ------------------------------------------------------------- API ops
    # (these are what the RPC endpoints call; reference nomad/job_endpoint.go,
    #  node_endpoint.go, eval_endpoint.go)

    def _create_preemption_evals(self, preempted) -> None:
        """One reschedule eval per job whose allocs were preempted
        (reference CreatePreemptionEvals, plan_apply.go:204+)."""
        seen = set()
        evals = []
        for a in preempted:
            key = (a.namespace, a.job_id)
            if key in seen:
                continue
            seen.add(key)
            job = a.job or self.store.job_by_id(a.namespace, a.job_id)
            if job is None or job.stopped():
                continue
            evals.append(Evaluation(
                namespace=a.namespace, priority=job.priority,
                type=job.type, job_id=job.id,
                triggered_by=EvalTrigger.PREEMPTION,
                status=EvalStatus.PENDING))
        if evals:
            self.create_evals(evals)

    def update_eval(self, ev: Evaluation) -> None:
        # timestamps ride in the log payload: the FSM must not read the
        # clock, or replicas/replay diverge (see nomad_tpu.analysis)
        ev.modify_time = _time.time()
        if not ev.create_time:
            ev.create_time = ev.modify_time
        self.apply(MessageType.EVAL_UPDATE, {"evals": [ev]})

    def create_evals(self, evals: List[Evaluation]) -> None:
        # pending evals are enqueued / blocked by the FSM's leader hook
        # (reference: fsm eval apply with the broker attached)
        now = _time.time()
        copies = []
        for e in evals:
            c = e.copy()
            c.modify_time = now
            if not c.create_time:
                c.create_time = now
            copies.append(c)
        tracer = tracing.active
        if tracer is not None:
            # propose-time trace note: the broker enqueue happens inside
            # the FSM apply cone where nothing may stamp the clock, so
            # the queue-wait span's start is noted here and emitted at
            # dequeue (see EvalBroker.dequeue)
            ctx = tracing.current()
            if ctx is not None:
                for c in copies:
                    tracer.note_eval(c.id, ctx, ts=now)
        self.apply(MessageType.EVAL_UPDATE, {"evals": copies})

    def register_job(self, job: Job) -> Evaluation:
        """Job.Register (nomad/job_endpoint.go:81): upsert + eval.  A job
        whose region is not ours forwards to that region's servers
        (job_endpoint.go forward via rpc.go forwardRegion); a region
        nobody has heard of is rejected outright — silently committing
        it locally (or forwarding it in a loop) would strand the job."""
        if job.multiregion is not None and job.multiregion.regions \
                and "multiregion.rollout" not in job.meta:
            return self._register_multiregion(job)
        if job.region and job.region != self.region:
            known = self.regions()
            if job.region not in known:
                from nomad_tpu.rpc.endpoints import RpcError
                raise RpcError(
                    "unknown_region",
                    f"job {job.id!r} submitted to unknown region "
                    f"{job.region!r} (known regions: "
                    f"{', '.join(known)})")
            resp = self.rpc_region(job.region, "Job.Register",
                                   {"job": job})
            return Evaluation(
                id=resp["eval_id"], namespace=job.namespace,
                job_id=job.id, type=job.type,
                triggered_by=EvalTrigger.JOB_REGISTER,
                status=EvalStatus.PENDING)
        ns = job.namespace or "default"
        if self.store.namespace(ns) is None:
            # same shape as the unknown-region rejection above: naming
            # the known set makes the typo obvious to the submitter
            from nomad_tpu.rpc.endpoints import RpcError
            known = sorted(n.name for n in self.store.namespaces())
            raise RpcError(
                "unknown_namespace",
                f"job {job.id!r} submitted to unknown namespace "
                f"{ns!r} (known namespaces: {', '.join(known)})")
        if not job.submit_time:
            job.submit_time = _time.time()   # propose-time, rides the log
        index = self.apply(MessageType.JOB_REGISTER, {"job": job})
        # when the write was forwarded, the leader mutated a pickled copy;
        # pull the committed indexes back onto the caller's object so the
        # eval (and the RPC response) carries the real job_modify_index
        self.store.wait_for_index(index)
        stored = self.store.job_by_id(job.namespace, job.id)
        if stored is not None:
            job.create_index = stored.create_index
            job.modify_index = stored.modify_index
            job.job_modify_index = stored.job_modify_index
            job.version = stored.version
        ev = Evaluation(
            namespace=job.namespace, priority=job.priority, type=job.type,
            job_id=job.id, triggered_by=EvalTrigger.JOB_REGISTER,
            status=EvalStatus.PENDING,
            job_modify_index=job.job_modify_index)
        ev.modify_index = job.modify_index
        if not job.is_periodic() and not job.is_parameterized():
            self.create_evals([ev])
        return ev

    def _register_multiregion(self, job: Job) -> Evaluation:
        """Expand a `multiregion` job into per-region copies and start
        the sequential rollout at the FIRST listed region (reference
        nomad/job_endpoint.go multiregion Register: later regions only
        deploy after the previous region's deployment is healthy — the
        deployment watcher kicks region N+1 when region N succeeds)."""
        regions = [r.name for r in job.multiregion.regions]
        known = self.regions()
        unknown = [r for r in regions if r not in known]
        if unknown:
            from nomad_tpu.rpc.endpoints import RpcError
            raise RpcError(
                "unknown_region",
                f"multiregion job {job.id!r} names unknown region(s) "
                f"{', '.join(repr(r) for r in unknown)} (known regions: "
                f"{', '.join(known)})")
        rollout = uuid.uuid4().hex
        first = job.multiregion_copy(regions[0], rollout)
        return self.register_job(first)

    def deregister_job(self, namespace: str, job_id: str, purge: bool = False) -> Optional[Evaluation]:
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        self.apply(MessageType.JOB_DEREGISTER,
                   {"namespace": namespace, "job_id": job_id, "purge": purge})
        self.blocked_evals.untrack(namespace, job_id)
        ev = Evaluation(
            namespace=namespace, priority=job.priority, type=job.type,
            job_id=job_id, triggered_by=EvalTrigger.JOB_DEREGISTER,
            status=EvalStatus.PENDING)
        self.create_evals([ev])
        return ev

    def scale_job(self, namespace: str, job_id: str, group: str,
                  count: Optional[int] = None, message: str = "",
                  error: bool = False, meta: Optional[dict] = None
                  ) -> Optional[Evaluation]:
        """Job.Scale (reference nomad/job_endpoint.go:967): adjust one
        task group's count within its scaling-policy bounds by
        registering the updated job (which creates the eval that
        reschedules), and record a ScalingEvent either way (error=True
        events are autoscaler annotations that never change counts)."""
        import time as _t

        from nomad_tpu.structs.job import ScalingEvent
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            raise ValueError(f"job {job_id!r} not found")
        tg = job.lookup_task_group(group)
        if tg is None:
            raise ValueError(
                f"task group {group!r} does not exist in job")
        prev = tg.count
        ev = None
        if count is not None and not error:
            if tg.scaling is not None and tg.scaling.enabled:
                if count < tg.scaling.min:
                    raise ValueError(
                        f"group count was less than scaling policy "
                        f"minimum: {count} < {tg.scaling.min}")
                if tg.scaling.max and count > tg.scaling.max:
                    raise ValueError(
                        f"group count was greater than scaling policy "
                        f"maximum: {count} > {tg.scaling.max}")
            new_job = job.copy()
            new_job.lookup_task_group(group).count = int(count)
            ev = self.register_job(new_job)
        event = ScalingEvent(
            time=_t.time(), previous_count=prev, count=count,
            message=message, error=error,
            eval_id=ev.id if ev is not None else "", meta=meta or {})
        self.apply(MessageType.SCALING_EVENT,
                   {"namespace": namespace, "job_id": job_id,
                    "group": group, "event": event})
        return ev

    def job_scale_status(self, namespace: str, job_id: str) -> Optional[dict]:
        """Job.ScaleStatus (job_endpoint.go:2038): desired vs placed vs
        healthy per group + the scaling-event log."""
        job = self.store.job_by_id(namespace, job_id)
        if job is None:
            return None
        allocs = self.store.allocs_by_job(namespace, job_id)
        events = self.store.scaling_events_by_job(namespace, job_id)
        groups = {}
        for tg in job.task_groups:
            live = [a for a in allocs if a.task_group == tg.name
                    and not a.terminal_status()]
            healthy = sum(1 for a in live if (a.deployment_status or {})
                          .get("healthy") is True)
            unhealthy = sum(1 for a in live if (a.deployment_status or {})
                            .get("healthy") is False)
            groups[tg.name] = {
                "desired": tg.count, "placed": len(live),
                "running": sum(1 for a in live
                               if a.client_status == "running"),
                "healthy": healthy, "unhealthy": unhealthy,
                "events": events.get(tg.name, []),
            }
        return {"job_id": job_id, "namespace": namespace,
                "job_modify_index": job.modify_index,
                "job_stopped": job.stopped(), "task_groups": groups}

    def set_job_stability(self, namespace: str, job_id: str, version: int,
                          stable: bool) -> None:
        self.apply(MessageType.JOB_STABILITY,
                   {"namespace": namespace, "job_id": job_id,
                    "version": version, "stable": stable})

    def register_node(self, node: Node) -> None:
        """Node.Register (nomad/node_endpoint.go:79).  The leader's FSM
        hook starts the TTL timer.  A re-registration whose device
        fingerprint marks instances unhealthy (the device plugin health
        stream, plugins/device/device.go:25-37) migrates the allocations
        holding those instances — dead hardware must not keep serving."""
        prev = self.store.node_by_id(node.id)
        if prev is not None and prev.secret_id and node.secret_id \
                and prev.secret_id != node.secret_id:
            # reference node_endpoint.go:141 — a re-registration may not
            # rotate another node's identity out from under it
            raise ValueError(f"node secret ID does not match: {node.id}")
        newly_bad: set = set()
        if prev is not None:
            prev_bad = {i for d in prev.node_resources.devices
                        for i in d.unhealthy_ids}
            now_bad = {i for d in node.node_resources.devices
                       for i in d.unhealthy_ids}
            newly_bad = now_bad - prev_bad
        self.apply(MessageType.NODE_REGISTER, {"node": node})
        if newly_bad:
            self._migrate_device_allocs(node.id, newly_bad)

    def _migrate_device_allocs(self, node_id: str, bad_ids: set) -> None:
        """DesiredTransition(force_reschedule) + eval for every alloc on
        the node holding a now-unhealthy device instance: the reconciler
        replaces it, and the replacement lands on healthy hardware
        because unhealthy instances carry no capacity."""
        from nomad_tpu.structs.alloc import DesiredTransition
        doomed = []
        for a in self.store.allocs_by_node(node_id):
            if a.terminal_status():
                continue
            held = {i for tr in a.allocated_resources.tasks.values()
                    for d in tr.devices
                    for i in d.get("device_ids", ())}
            if held & bad_ids:
                doomed.append(a)
        if not doomed:
            return
        for a in doomed:
            u = a.copy() if hasattr(a, "copy") else a
            # force_reschedule: migrate only moves allocs on DRAINING
            # nodes; a dead device on a healthy node needs the
            # unconditional replace path (the `nomad alloc stop` flow)
            u.desired_transition = DesiredTransition(force_reschedule=True)
            self.apply(MessageType.ALLOC_UPDATE_DESIRED_TRANSITION,
                       {"allocs": [u]})
        evs = []
        for (ns, job_id) in {(a.namespace, a.job_id) for a in doomed}:
            job = self.store.job_by_id(ns, job_id)
            if job is None:
                continue
            evs.append(Evaluation(
                namespace=ns, priority=job.priority, type=job.type,
                job_id=job_id, triggered_by=EvalTrigger.NODE_UPDATE,
                status=EvalStatus.PENDING))
        if evs:
            self.create_evals(evs)

    def node_heartbeat(self, node_id: str) -> float:
        """Node.UpdateStatus heartbeat path: reset TTL; a down node
        re-heartbeating is brought back to ready (init->ready handled by
        client re-registration).  TTL timers are leader-local soft state,
        so follower-received heartbeats forward (heartbeat.go:56)."""
        if self.raft is not None and not self.raft.is_leader:
            resp = self.rpc_leader("Node.UpdateStatus",
                                   {"node_id": node_id, "heartbeat": True})
            return resp["heartbeat_ttl"]
        node = self.store.node_by_id(node_id)
        if node is not None:
            if node.status in ("down", "disconnected"):
                # revival rides the heartbeat batch when it runs: one
                # coalesced FSM entry per flush tick, not one per node
                if self.heartbeat_batch.running:
                    self.heartbeat_batch.note(node_id, "ready")
                else:
                    self.update_node_status(node_id, "ready")
            elif self.heartbeat_batch.running:
                # periodic liveness stamp (rate-limited to half-TTL per
                # node inside the batcher) so a failed-over leader sees
                # reasonably fresh status_updated_at values
                self.heartbeat_batch.stamp(node_id, node.status)
        return self.heartbeats.heartbeat(node_id)

    def node_heartbeats(self, node_ids: List[str]) -> float:
        """Batched heartbeat for fleet-scale agent drivers: one
        forwarded RPC re-arms many TTLs; each node still takes the real
        node_heartbeat path (revival, liveness stamp, TTL wheel)."""
        if self.raft is not None and not self.raft.is_leader:
            resp = self.rpc_leader("Node.BatchHeartbeat",
                                   {"node_ids": list(node_ids)})
            return resp["heartbeat_ttl"]
        ttl = self.config.heartbeat_ttl
        for nid in node_ids:
            ttl = self.node_heartbeat(nid)
        return ttl

    def node_update_fingerprint(self, node_id: str, update: dict) -> dict:
        """Node.UpdateFingerprint: a device/attribute re-fingerprint
        DELTA from a registered client.  Rides the heartbeat batcher's
        coalesced write path (one NodeFingerprintBatch raft entry per
        flush tick) instead of a full Node.Register per change; an
        unknown node returns known=False so the client falls back to a
        full re-register."""
        if self.raft is not None and not self.raft.is_leader:
            args = dict(update)
            args["node_id"] = node_id
            return self.rpc_leader("Node.UpdateFingerprint", args)
        if self.store.node_by_id(node_id) is None:
            return {"known": False}
        payload = {k: v for k, v in update.items()
                   if k in ("devices", "attributes")}
        payload["node_id"] = node_id
        if self.heartbeat_batch.running:
            self.heartbeat_batch.note_fingerprint(node_id, payload)
        else:
            self.apply(MessageType.NODE_FINGERPRINT_BATCH,
                       {"updates": [payload]})
        return {"known": True}

    def update_node_status(self, node_id: str, status: str) -> List[Evaluation]:
        """Node.UpdateStatus: transition + evals for affected jobs."""
        self.apply(MessageType.NODE_UPDATE_STATUS,
                   {"node_id": node_id, "status": status,
                    "updated_at": _time.time()})
        return self.create_node_evals(node_id)

    def create_node_evals(self, node_id: str) -> List[Evaluation]:
        """Evaluate all jobs with allocs on the node plus system jobs
        (reference createNodeEvals, node_endpoint.go)."""
        evals = []
        seen = set()
        for a in self.store.allocs_by_node(node_id):
            job = a.job or self.store.job_by_id(a.namespace, a.job_id)
            if job is None or job.id in seen:
                continue
            seen.add(job.id)
            evals.append(Evaluation(
                namespace=a.namespace, priority=job.priority, type=job.type,
                job_id=job.id, triggered_by=EvalTrigger.NODE_UPDATE,
                node_id=node_id, status=EvalStatus.PENDING,
                modify_index=self.store.latest_index))
        for job in self.store.jobs():
            if job.type in (JobType.SYSTEM, JobType.SYSBATCH) \
                    and job.id not in seen and not job.stopped():
                seen.add(job.id)
                evals.append(Evaluation(
                    namespace=job.namespace, priority=job.priority,
                    type=job.type, job_id=job.id,
                    triggered_by=EvalTrigger.NODE_UPDATE, node_id=node_id,
                    status=EvalStatus.PENDING,
                    modify_index=self.store.latest_index))
        if evals:
            self.create_evals(evals)
        return evals

    # ------------------------------------------------------------- ACL

    acl_enabled = False

    def enable_acl(self) -> None:
        """Turn on ACL enforcement (reference acl block in agent config)."""
        self.acl_enabled = True

    def resolve_token(self, secret_id: str):
        """SecretID -> compiled ACL (reference nomad/acl.go ResolveToken).
        Anonymous (empty) tokens get the 'anonymous' policy if present."""
        from nomad_tpu.acl import ACL, parse_policy
        if not secret_id:
            anon = self.store.acl_policy("anonymous")
            if anon is None:
                return None
            return ACL(policies=[anon])
        token = self.store.acl_token_by_secret(secret_id)
        if token is None:
            return None
        if token.type == "management":
            return ACL(management=True)
        policies = [self.store.acl_policy(p) for p in token.policies]
        return ACL(policies=[p for p in policies if p is not None])

    def bootstrap_acl(self):
        """One-time management token mint (reference ACL.Bootstrap).
        The uniqueness invariant is enforced inside the replicated FSM
        apply (a losing concurrent bootstrap is dropped there), so after
        the commit we verify our token actually landed."""
        from nomad_tpu.acl import ACLToken
        t = ACLToken(name="Bootstrap Token", type="management",
                     global_=True)
        index = self.apply(MessageType.ACL_TOKEN_UPSERT,
                           {"token": t, "bootstrap": True})
        self.store.wait_for_index(index)
        if self.store.acl_token(t.accessor_id) is None:
            raise RuntimeError("ACL already bootstrapped")
        return t

    def upsert_acl_policy(self, name: str, description: str, rules: str):
        from nomad_tpu.acl import parse_policy
        policy = parse_policy(name, rules, description)
        self.apply(MessageType.ACL_POLICY_UPSERT, {"policy": policy})
        return policy

    def delete_acl_policy(self, name: str) -> None:
        self.apply(MessageType.ACL_POLICY_DELETE, {"name": name})

    def acl_policies(self):
        return self.store.acl_policies()

    def acl_policy(self, name: str):
        return self.store.acl_policy(name)

    def create_acl_token(self, name: str = "", type_: str = "client",
                         policies=None):
        from nomad_tpu.acl import ACLToken
        t = ACLToken(name=name, type=type_, policies=list(policies or []))
        self.apply(MessageType.ACL_TOKEN_UPSERT, {"token": t})
        return t

    def delete_acl_token(self, accessor_id: str) -> None:
        self.apply(MessageType.ACL_TOKEN_DELETE,
                   {"accessor_id": accessor_id})

    def acl_tokens(self):
        return self.store.acl_tokens()

    def acl_token(self, accessor_id: str):
        return self.store.acl_token(accessor_id)

    def acl_token_by_secret(self, secret_id: str):
        return self.store.acl_token_by_secret(secret_id)

    # ------------------------------------------------------------- namespaces

    def namespaces(self):
        return self.store.namespaces()

    def namespace(self, name: str):
        return self.store.namespace(name)

    def upsert_namespace(self, name: str, description: str = "",
                         quota: str = "") -> None:
        if quota and self.store.quota_spec(quota) is None:
            raise ValueError(f"quota spec {quota!r} does not exist")
        self.apply(MessageType.NAMESPACE_UPSERT,
                   {"name": name, "description": description,
                    "quota": quota})

    def delete_namespace(self, name: str) -> None:
        self.apply(MessageType.NAMESPACE_DELETE, {"name": name})

    # ------------------------------------------------------------- quotas

    def upsert_quota_spec(self, spec) -> None:
        self.apply(MessageType.QUOTA_SPEC_UPSERT, {"spec": spec})

    def delete_quota_spec(self, name: str) -> None:
        # propose-time guard mirrors the FSM's authoritative check so the
        # caller gets the error without burning a log entry
        for ns in self.store.namespaces():
            if ns.quota == name:
                raise ValueError(
                    f"quota {name!r} referenced by namespace {ns.name!r}")
        self.apply(MessageType.QUOTA_SPEC_DELETE, {"name": name})

    def quota_specs(self):
        return self.store.quota_specs()

    def quota_spec(self, name: str):
        return self.store.quota_spec(name)

    def quota_usage(self, namespace: str):
        return self.store.quota_usage(namespace)

    def quota_usages(self):
        return self.store.quota_usages()

    # ------------------------------------------------------------- helpers

    def wait_for_idle(self, timeout: float = 10.0) -> bool:
        """Testing/bench helper: wait until no evals are queued or in
        flight."""
        deadline = _time.time() + timeout
        while _time.time() < deadline:
            if (self.broker.ready_count() == 0
                    and self.broker.unacked_count() == 0
                    and self.plan_queue.depth() == 0):
                return True
            _time.sleep(0.01)
        return False
