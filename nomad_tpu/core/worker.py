"""Scheduler worker (reference: nomad/worker.go — run:386,
dequeueEvaluation:437, snapshotMinIndex:537, invokeScheduler:553,
SubmitPlan:593-660).

Each worker loops: dequeue an eval (with lease token), wait for the state
store to catch up to the eval's index, invoke the scheduler via the
factory, then ack/nack.  The worker object is the scheduler's Planner:
plans go to the plan queue and the worker blocks on the applier's result.
"""
from __future__ import annotations

import logging
import threading
from typing import List, Optional

from nomad_tpu.core.plan_queue import LeadershipLostError
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.scheduler import factory
from nomad_tpu.structs import Evaluation, EvalStatus
from nomad_tpu.structs.plan import Plan, PlanResult

log = logging.getLogger(__name__)


class Worker:
    def __init__(self, server, worker_id: int = 0,
                 enabled_schedulers: Optional[List[str]] = None):
        self.server = server
        self.id = worker_id
        self.enabled_schedulers = enabled_schedulers or \
            ["service", "batch", "system", "sysbatch"]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._snapshot = None
        self.stats = {"processed": 0, "failed": 0}

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 5.0) -> None:
        if self._thread:
            self._thread.join(timeout)

    def run(self) -> None:
        while not self._stop.is_set():
            ev, token = self.server.broker.dequeue(
                self.enabled_schedulers, timeout=0.1)
            if ev is None:
                continue
            try:
                self.process_eval(ev, token)
            except (NotLeaderError, LeadershipLostError):
                # leadership moved mid-eval (reference: the worker's RPCs
                # start failing and the eval is nacked for redelivery)
                self.server.broker.nack(ev.id, token)

    # ------------------------------------------------------------- process

    def process_eval(self, ev: Evaluation, token: str) -> None:
        server = self.server
        snap = server.store.snapshot_min_index(
            max(ev.modify_index, ev.snapshot_index))
        if snap is None:
            server.broker.nack(ev.id, token)
            return
        self._snapshot = snap
        self._token = token
        ev = ev.copy()
        try:
            sched = factory.new_scheduler(ev.type, snap, self)
            sched.process(ev)
        except (NotLeaderError, LeadershipLostError):
            raise
        except Exception as e:                      # noqa: BLE001
            log.exception("eval %s failed", ev.id)
            self.stats["failed"] += 1
            ev.status = EvalStatus.FAILED
            ev.status_description = str(e)
            server.update_eval(ev)
            server.broker.nack(ev.id, token)
            return
        ev.status = EvalStatus.COMPLETE
        server.update_eval(ev)
        if server.broker.ack(ev.id, token):
            self.stats["processed"] += 1

    # ------------------------------------------------------------- planner

    def submit_plan(self, plan: Plan) -> PlanResult:
        plan.eval_token = getattr(self, "_token", "")
        pending = self.server.plan_queue.enqueue(plan)
        return pending.future.result(timeout=30.0)

    def create_evals(self, evals: List[Evaluation]) -> None:
        self.server.create_evals(evals)

    def update_eval(self, ev: Evaluation) -> None:
        self.server.update_eval(ev)

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.blocked_evals.block(ev)

    def refresh_snapshot(self, min_index: int = 0):
        snap = self.server.store.snapshot_min_index(min_index)
        self._snapshot = snap
        return snap
