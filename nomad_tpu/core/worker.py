"""Scheduler worker (reference: nomad/worker.go — run:386,
dequeueEvaluation:437, snapshotMinIndex:537, invokeScheduler:553,
SubmitPlan:593-660).

Each worker loops: dequeue an eval (with lease token), wait for the state
store to catch up to the eval's index, invoke the scheduler via the
factory, then ack/nack.  The worker object is the scheduler's Planner:
plans go to the plan queue and the worker blocks on the applier's result.
"""
from __future__ import annotations

import concurrent.futures
import logging
import os
import random
import threading
import time
from collections import deque
from typing import List, Optional

from nomad_tpu import chaos, knobs, tracing
from nomad_tpu import deadline as request_deadline
from nomad_tpu.core.plan_queue import LeadershipLostError
from nomad_tpu.raft import NotLeaderError
from nomad_tpu.raft.transport import Unreachable
from nomad_tpu.rpc.endpoints import RpcError
from nomad_tpu.scheduler import factory
from nomad_tpu.structs import Evaluation, EvalStatus
from nomad_tpu.structs.plan import Plan, PlanResult
from nomad_tpu.telemetry import global_metrics

log = logging.getLogger(__name__)

# transient cluster errors: the eval should be redelivered, not failed.
# A raft-apply commit timeout (futures.TimeoutError) belongs here: the
# write may or may not have landed, which is the same ambiguity as a
# leadership loss, and redelivery resolves both the same way (the worker
# re-snapshots past the eval's index before scheduling again).
TRANSIENT_ERRORS = (NotLeaderError, LeadershipLostError, RpcError,
                    Unreachable, concurrent.futures.TimeoutError,
                    TimeoutError)


class Worker:
    def __init__(self, server, worker_id: int = 0,
                 enabled_schedulers: Optional[List[str]] = None):
        self.server = server
        self.id = worker_id
        self.enabled_schedulers = enabled_schedulers or \
            ["service", "batch", "system", "sysbatch"]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._snapshot = None
        # store index the scheduling snapshot must reach before this
        # worker's current eval may be processed (set at dequeue)
        self._wait_index = 0
        # double-buffered commit pipeline (plan_apply.go:71-178 carried
        # to the worker side): with depth > 0, submit_plan returns at
        # applier-EVALUATE time (the PlanResult is final then; only
        # alloc_index lands later) and the eval's COMPLETE/ack settle is
        # deferred until the raft append + fsync finishes — so wave N+1
        # schedules and dispatches on-device while commit(N) is durably
        # landing.  Depth bounds how many evals may be settle-deferred
        # at once; 0 restores strict blocking submits.
        self.pipeline_depth = max(0, knobs.get_int(
            "NOMAD_TPU_PIPELINE_DEPTH"))
        # (ev, token, [PendingPlan]) awaiting durable commit, oldest first
        self._deferred = deque()
        self._eval_pendings: List = []
        self.stats = {"processed": 0, "failed": 0,
                      "pipelined_evals": 0, "pipeline_discards": 0,
                      # dequeues served off the wave-aligned feeder
                      # buffer (vs direct broker passes): the supply
                      # side of the engine's wave-lane batching
                      "wave_dequeues": 0}

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, name=f"worker-{self.id}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float = 5.0) -> None:
        if self._thread:
            self._thread.join(timeout)

    def run(self) -> None:
        while not self._stop.is_set():
            got = self._dequeue()
            self._drain_deferred()
            if got is None:
                continue
            ev, token = got
            if self._stop.is_set():
                # stop() landed while the dequeue was in flight: hand the
                # lease back so a live worker gets the eval now rather
                # than after the nack timeout
                try:
                    self._nack(ev.id, token)
                except TRANSIENT_ERRORS:
                    pass
                break
            try:
                self.process_eval(ev, token)
            except TRANSIENT_ERRORS:
                # leadership moved mid-eval (reference: the worker's RPCs
                # start failing and the eval is nacked for redelivery);
                # nack best-effort — the lease expires server-side anyway
                try:
                    self._nack(ev.id, token)
                except TRANSIENT_ERRORS:
                    pass
            except Exception:                       # noqa: BLE001
                # never let the worker thread die (reference workers live
                # for the life of the server, worker.go:386) — and hand
                # the lease back so the eval redelivers now, not at the
                # nack timeout
                log.exception("worker %s: unhandled error", self.id)
                try:
                    self._nack(ev.id, token)
                except TRANSIENT_ERRORS:
                    pass
        # settle every still-deferred eval before the thread exits —
        # a clean stop must not leave acked-nowhere leases to time out
        while self._deferred:
            self._settle_eval(*self._deferred.popleft())

    # ------------------------------------------------------ pipelined settle

    def _drain_deferred(self) -> None:
        """Settle deferred evals: everything whose commits already landed
        settles for free; beyond `pipeline_depth` outstanding, block on
        the oldest so the pipeline stays bounded."""
        while self._deferred:
            ev, token, pendings = self._deferred[0]
            if len(self._deferred) <= self.pipeline_depth and \
                    not all(p.future.done() for p in pendings):
                return
            self._deferred.popleft()
            self._settle_eval(ev, token, pendings)

    def _settle_eval(self, ev: Evaluation, token: str,
                     pendings: List) -> None:
        """Deferred tail of process_eval: wait for the durable commits
        backing this eval's plans, then publish COMPLETE and ack.  If a
        commit failed mid-flight, the speculative result is discarded —
        the eval is nacked for redelivery and the re-process snapshots
        past whatever DID commit (`_wait_index`), so a partial landing
        never double-places (same contract as crash-after-commit)."""
        try:
            for p in pendings:
                p.future.result(timeout=600.0)
        except Exception:                           # noqa: BLE001
            # transient or real commit failure: identical discard path
            self.stats["pipeline_discards"] += 1
            try:
                self._nack(ev.id, token)
            except TRANSIENT_ERRORS:
                pass
            return
        if chaos.active is not None and chaos.should("worker.settle_drop"):
            # worker dies between commit and ack: the lease expires and
            # the redelivered eval no-ops via plan dedup
            return
        try:
            self.server.update_eval(ev)
            if self._ack(ev.id, token):
                self.stats["processed"] += 1
                self.stats["pipelined_evals"] += 1
        except TRANSIENT_ERRORS:
            try:
                self._nack(ev.id, token)
            except TRANSIENT_ERRORS:
                pass

    # -- broker ops, overridable for the RPC path (RemoteWorker)

    def _dequeue(self):
        feeder = getattr(self.server, "eval_feeder", None)
        if feeder is not None:
            # wave-aligned path: one pool member drains a whole ready
            # wave in one broker pass; the rest pick from the buffer
            got = feeder.get(self.enabled_schedulers, timeout=0.1)
            if got is None:
                return None
            ev, token = got
            self.stats["wave_dequeues"] += 1
        else:
            ev, token = self.server.broker.dequeue(
                self.enabled_schedulers, timeout=0.1)
            if ev is None:
                return None
        self._wait_index = self.server.store.latest_index
        self._trace_ctx = None
        tracer = tracing.active
        if tracer is not None:
            note = tracer.take_eval_note(ev.id)
            if note is not None:
                self._trace_ctx = note[0]
        return ev, token

    def _ack(self, eval_id: str, token: str) -> bool:
        return self.server.broker.ack(eval_id, token)

    def _nack(self, eval_id: str, token: str) -> bool:
        return self.server.broker.nack(eval_id, token)

    # ------------------------------------------------------------- process

    def process_eval(self, ev: Evaluation, token: str) -> None:
        server = self.server
        # _wait_index covers redelivery: a plan may already have committed
        # for this eval (crash-after-commit nack, lease expiry, failover)
        # at an index past the eval's own, and scheduling from an older
        # snapshot would double-place the job
        snap = server.store.snapshot_min_index(
            max(ev.modify_index, ev.snapshot_index, self._wait_index))
        if snap is None:
            self._nack(ev.id, token)
            return
        self._snapshot = snap
        self._token = token
        self._eval_pendings = []
        ev = ev.copy()
        # sampled eval: the scheduler invocation is a span, and the trace
        # context stays bound for its duration so plan submission (and
        # any follow-up evals it creates) joins the trace
        tracer = tracing.active
        tctx = getattr(self, "_trace_ctx", None)
        tspan = tprev = None
        if tracer is not None and tctx is not None:
            tspan = tracer.start(
                tctx, f"worker.invoke_scheduler.{ev.type}",
                self.server.name)
            tprev = tracing.bind(tracer.child_ctx(tctx, tspan))
        try:
            try:
                sched = factory.new_scheduler(ev.type, snap, self)
                t0 = time.time()
                sched.process(ev)
                global_metrics.measure_since(
                    f"nomad.worker.invoke_scheduler.{ev.type}", t0)
            except TRANSIENT_ERRORS:
                raise
            except Exception as e:                      # noqa: BLE001
                log.exception("eval %s failed", ev.id)
                self.stats["failed"] += 1
                ev.status = EvalStatus.FAILED
                ev.status_description = str(e)
                server.update_eval(ev)  # raises TRANSIENT -> run() nacks
                self._nack(ev.id, token)
                return
        finally:
            if tspan is not None:
                tracer.finish(tspan)
                tracing.bind(tprev)
        ev.status = EvalStatus.COMPLETE
        pendings, self._eval_pendings = self._eval_pendings, []
        if pendings:
            # pipelined submits are still committing: defer the
            # COMPLETE/ack settle and move on to the next eval now
            self._deferred.append((ev, token, pendings))
            self._drain_deferred()
            return
        server.update_eval(ev)
        if self._ack(ev.id, token):
            self.stats["processed"] += 1

    # ------------------------------------------------------------- planner

    def submit_plan(self, plan: Plan) -> PlanResult:
        plan.eval_token = getattr(self, "_token", "")
        t0 = time.time()
        tracer = tracing.active
        tctx = tracing.current() if tracer is not None else None
        tspan = tprev = None
        if tctx is not None:
            tspan = tracer.start(tctx, "plan.submit", self.server.name)
            tprev = tracing.bind(tracer.child_ctx(tctx, tspan))
        try:
            pending = self.server.enqueue_plan(plan)
            if self.pipeline_depth > 0:
                # pipelined: return as soon as the applier has validated
                # the plan and registered its overlay — the PlanResult's
                # content is final at evaluate time (only alloc_index
                # lands post-commit, and the scheduler never reads it).
                # The durable commit settles later in _settle_eval; the
                # applier owns the engine-ticket release either way, so
                # the scheduler must skip its early free.
                res = pending.evaluated.result(timeout=600.0)
                plan.commit_inflight = True
                self._eval_pendings.append(pending)
            else:
                # generous: under full-cluster bursts (the 1M-alloc C2M)
                # the serialized applier legitimately backs up for
                # minutes; an eval failed on a timed-out future gets
                # retried from scratch even though its plan still
                # commits — pure wasted recompute
                res = pending.future.result(timeout=600.0)
        finally:
            if tspan is not None:
                tracer.finish(tspan)
                tracing.bind(tprev)
        global_metrics.measure_since("nomad.plan.submit", t0)
        # per-namespace latency: the fairness gate in the multi-tenant
        # scenarios asserts on victim-tenant p99, not the global mix
        ns = (plan.job.namespace or "default") if plan.job else "default"
        global_metrics.measure_since(f"nomad.plan.submit.ns.{ns}", t0)
        return res

    def create_evals(self, evals: List[Evaluation]) -> None:
        self.server.create_evals(evals)

    def update_eval(self, ev: Evaluation) -> None:
        self.server.update_eval(ev)

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.blocked_evals.block(ev)

    def refresh_snapshot(self, min_index: int = 0):
        snap = self.server.store.snapshot_min_index(min_index)
        self._snapshot = snap
        return snap


class RemoteWorker(Worker):
    """Worker on any cluster member: broker and plan-queue operations RPC
    to the leader (short-circuiting locally when this member IS the
    leader), while scheduling reads come from the local replicated
    snapshot — the reference's every-server worker pool (worker.go:81-85,
    Eval.Dequeue / Plan.Submit RPCs)."""

    # RpcError kinds worth retrying: the request was rejected before it
    # executed (election in progress / forwarded to a dead leader).  Any
    # other kind (stale_eval_token, internal, ...) is a real answer.
    _RETRYABLE_KINDS = frozenset({"no_leader", "not_leader"})

    def _rpc(self, method: str, args: dict, deadline: float = 5.0):
        """rpc_leader with exponential backoff + jitter across leadership
        churn.  Retried requests never double-execute: dequeue/ack/nack
        are lease-guarded and Plan.Submit dedups on plan_id."""
        dl = time.monotonic() + deadline
        # a bound end-to-end request deadline caps the retry budget:
        # churn is only worth riding out while someone still waits
        budget_dl = request_deadline.current()
        if budget_dl is not None:
            dl = min(dl, budget_dl)
        delay = 0.02
        while True:
            if budget_dl is not None and time.monotonic() >= budget_dl:
                request_deadline.expire("worker")
                raise RpcError("deadline_exceeded",
                               f"{method}: retry budget exhausted")
            try:
                return self.server.rpc_leader(method, args)
            except TRANSIENT_ERRORS as e:
                if isinstance(e, RpcError) and \
                        e.kind not in self._RETRYABLE_KINDS:
                    raise
                if self._stop.is_set() or time.monotonic() >= dl:
                    raise
                sleep = min(delay, max(0.0, dl - time.monotonic()))
                self._stop.wait(sleep * (0.5 + random.random() * 0.5))
                delay = min(delay * 2.0, 0.5)

    def _dequeue(self):
        try:
            resp = self._rpc("Eval.Dequeue",
                             {"schedulers": self.enabled_schedulers,
                              "timeout": 0.1})
        except TRANSIENT_ERRORS:
            self._stop.wait(0.05)
            return None
        if resp is None:
            return None
        self._wait_index = resp.get("wait_index", 0)
        self._trace_ctx = resp.get("trace")
        return resp["eval"], resp["token"]

    def _ack(self, eval_id: str, token: str) -> bool:
        return self._rpc("Eval.Ack",
                         {"eval_id": eval_id, "token": token})["ok"]

    def _nack(self, eval_id: str, token: str) -> bool:
        # bounded retry: a prompt nack redelivers in seconds where the
        # lease-expiry fallback costs the full nack_timeout
        delay = 0.02
        for attempt in range(3):
            try:
                return self._rpc("Eval.Nack",
                                 {"eval_id": eval_id, "token": token},
                                 deadline=1.0)["ok"]
            except TRANSIENT_ERRORS:
                if attempt == 2 or self._stop.is_set():
                    break
                self._stop.wait(delay * (0.5 + random.random() * 0.5))
                delay = min(delay * 2.0, 0.25)
        return False   # lease expires server-side; eval redelivers

    def submit_plan(self, plan: Plan) -> PlanResult:
        plan.eval_token = getattr(self, "_token", "")
        t0 = time.time()
        args = {"plan": plan}
        tracer = tracing.active
        tctx = tracing.current() if tracer is not None else None
        tspan = None
        if tctx is not None:
            # the submit span covers RPC + leader-side queue + apply;
            # its child context rides the args so the leader's
            # Plan.Submit handler (endpoints.handle) pops it and binds
            # it for the enqueue → applier → raft chain
            tspan = tracer.start(tctx, "plan.submit", self.server.name)
            args[tracing.TRACE_KEY] = tracer.child_ctx(tctx, tspan)
        try:
            res = self._rpc("Plan.Submit", args)
        finally:
            if tspan is not None:
                tracer.finish(tspan)
        global_metrics.measure_since("nomad.plan.submit", t0)
        ns = (plan.job.namespace or "default") if plan.job else "default"
        global_metrics.measure_since(f"nomad.plan.submit.ns.{ns}", t0)
        return res

    def reblock_eval(self, ev: Evaluation) -> None:
        self._rpc("Eval.Reblock", {"eval": ev})
