"""Server core (reference: nomad/ — the control plane around the scheduler).

Eval broker with ack/nack leases, blocked-eval tracking with
unblock-on-capacity, plan queue + serialized optimistic-concurrency plan
applier (partial commit), scheduler workers, heartbeats, and the leader
control loops.  All host-side; device work happens in nomad_tpu.ops via the
schedulers.
"""

from nomad_tpu.core.broker import EvalBroker
from nomad_tpu.core.blocked import BlockedEvals
from nomad_tpu.core.plan_apply import PlanApplier
from nomad_tpu.core.plan_queue import PlanQueue

__all__ = ["EvalBroker", "BlockedEvals", "PlanApplier", "PlanQueue"]
