"""Node heartbeating (reference: nomad/heartbeat.go — nodeHeartbeater:34,
resetHeartbeatTimer, invalidateHeartbeat:135, disconnectState:177).

Each node has a TTL; a missed TTL transitions the node to `down` — or to
`disconnected` when any alloc on it uses max_client_disconnect — and
triggers evaluations for every affected job.
"""
from __future__ import annotations

import heapq
import threading
import time as _time
from typing import Dict, Optional, Tuple

from nomad_tpu import chaos
from nomad_tpu.structs.node import NodeStatus


class HeartbeatTracker:
    def __init__(self, server, ttl: float = 10.0, tick: float = 0.1):
        self.server = server
        self.ttl = ttl
        self.tick = tick
        self._lock = threading.Lock()
        self._deadlines: Dict[str, float] = {}
        self._heap: list = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._stop = threading.Event()   # fresh per leadership tenure
        self._thread = threading.Thread(target=self._run, name="heartbeat",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(1.0)

    def heartbeat(self, node_id: str) -> float:
        """Reset the node's TTL (Node.UpdateStatus/heartbeat RPC path).
        Returns the TTL so clients know their deadline."""
        if chaos.active is not None and chaos.should("node.churn_kill"):
            # swallow the re-arm: the node misses its TTL and expires
            # through the real _invalidate path (down/disconnected)
            return self.ttl
        deadline = _time.time() + self.ttl
        with self._lock:
            self._deadlines[node_id] = deadline
            heapq.heappush(self._heap, (deadline, node_id))
        return self.ttl

    def untrack(self, node_id: str) -> None:
        with self._lock:
            self._deadlines.pop(node_id, None)

    def _run(self) -> None:
        while not self._stop.is_set():
            now = _time.time()
            expired = []
            with self._lock:
                while self._heap and self._heap[0][0] <= now:
                    deadline, node_id = heapq.heappop(self._heap)
                    # stale entries: node re-heartbeated since
                    if self._deadlines.get(node_id) == deadline:
                        del self._deadlines[node_id]
                        expired.append(node_id)
            for node_id in expired:
                try:
                    self._invalidate(node_id)
                except Exception:           # noqa: BLE001
                    # a failed write (e.g. lost quorum mid-invalidate) must
                    # not kill the heartbeat loop for the whole tenure
                    import logging
                    logging.getLogger(__name__).exception("invalidate")
                    # the node was already popped from _deadlines; without
                    # a retry deadline it would stay tracked-as-alive
                    # forever despite the missed TTL.  Re-arm a short one
                    # (unless the node re-heartbeated meanwhile).
                    retry = _time.time() + min(self.ttl, 1.0)
                    with self._lock:
                        if node_id not in self._deadlines:
                            self._deadlines[node_id] = retry
                            heapq.heappush(self._heap, (retry, node_id))
            self._stop.wait(self.tick)

    def _invalidate(self, node_id: str) -> None:
        """Missed TTL (reference invalidateHeartbeat + disconnectState)."""
        server = self.server
        node = server.store.node_by_id(node_id)
        if node is None or node.status == NodeStatus.DOWN:
            return
        # disconnected iff any alloc on the node tolerates disconnects
        new_status = NodeStatus.DOWN
        for a in server.store.allocs_by_node(node_id):
            if a.terminal_status() or a.job is None:
                continue
            tg = a.job.lookup_task_group(a.task_group)
            if tg is not None and tg.max_client_disconnect_s is not None:
                new_status = NodeStatus.DISCONNECTED
                break
        server.update_node_status(node_id, new_status)
